"""DSA — DeepSeek Sparse Attention (the V3.2/V4 lightning indexer).

The TPU-native analog of the reference's DSA stack (reference:
nemo_automodel/components/models/deepseek_v4/layers.py Indexer /
dsv4_indexer_scores; kernels/sparse_attention.py TileLang sparse MLA).
Design: the mask-based formulation the reference itself uses on its SDPA
fallback path (`_build_indexer_topk_compressed_mask`, layers.py:670) —

1. lightning indexer scores every (query, key) pair through a few tiny
   ReLU heads:  I[t,s] = Σ_h w[t,h] · ReLU(q_idx[t,h,:] · k_idx[s,:])
2. per query, the top-k keys under the causal/segment mask are selected
3. main MLA attention runs with the selection as an additive mask — XLA
   keeps everything static-shape and fuses the mask into the softmax
   (a gather-based Pallas sparse kernel is the later-round optimization;
   this path is the correctness oracle it will be tested against)

The hard top-k passes no gradient, so the indexer learns from a KL term
against the main attention's head-averaged distribution (stop-gradient on
the target), returned as an aux loss the recipe folds into the total.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.ops.attention import NEG_INF


def indexer_scores(
    x: jnp.ndarray,          # (B, S, H) normed layer input, compute dtype
    ip: dict,                # {"wq","wk","wgate"} kernels (+ optional rope)
    n_heads: int,
    head_dim: int,
    positions: jnp.ndarray,  # (B, S)
    inv_freq: jnp.ndarray | None,
) -> jnp.ndarray:
    """Lightning indexer scores (B, S, S) fp32 (queries × keys)."""
    from automodel_tpu.ops.rope import apply_rope

    B, S, H = x.shape
    q = (x @ ip["wq"]["kernel"].astype(x.dtype)).reshape(B, S, n_heads, head_dim)
    k = (x @ ip["wk"]["kernel"].astype(x.dtype)).reshape(B, S, 1, head_dim)
    if inv_freq is not None:
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
    w = x @ ip["wgate"]["kernel"].astype(x.dtype)  # (B, S, n_heads)
    dots = jnp.einsum(
        "bthd,bsd->bhts", q, k[:, :, 0, :], preferred_element_type=jnp.float32
    )  # (B, Hi, S, S)
    dots = jax.nn.relu(dots) * (head_dim ** -0.5)
    return jnp.einsum("bth,bhts->bts", w.astype(jnp.float32), dots)


def indexer_scores_glm(
    x: jnp.ndarray,          # (B, S, H) normed layer input
    q_lat: jnp.ndarray,      # (B, S, r_q) MLA q-lora residual (post q_norm)
    ip: dict,                # {"wq","wk","k_norm","wgate"}
    n_heads: int,
    head_dim: int,
    positions: jnp.ndarray,  # (B, S)
    inv_freq: jnp.ndarray,   # rope freqs for the ROPE SLICE (qk_rope_head_dim)
) -> jnp.ndarray:
    """GLM-5.x IndexShare indexer scores (B, S, S) fp32 (reference:
    glm_moe_dsa/layers.py:215-360 `GlmMoeDsaIndexer.forward`).

    Differences from the DeepSeek lightning indexer (`indexer_scores`):
    queries project from the MLA q-lora residual, keys are LayerNorm'd, the
    rope slice is laid FIRST in the head dim with half-split rotation (our
    apply_rope with a short inv_freq does exactly that), and the per-head
    gate weights carry an extra n_heads**-0.5 factor.
    """
    from automodel_tpu.ops.rope import apply_rope

    B, S, H = x.shape
    q = (q_lat @ ip["wq"]["kernel"].astype(x.dtype)).reshape(B, S, n_heads, head_dim)
    k = x @ ip["wk"]["kernel"].astype(x.dtype)  # (B, S, head_dim)
    # LayerNorm (with bias, eps 1e-6) over the key head dim
    mu = jnp.mean(k.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(k.astype(jnp.float32), axis=-1, keepdims=True)
    k = (k.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + 1e-6)
    k = k * ip["k_norm"]["scale"].astype(jnp.float32) + ip["k_norm"]["bias"].astype(jnp.float32)
    k = k.astype(x.dtype)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k[:, :, None, :], positions, inv_freq)[:, :, 0, :]
    w = (x @ ip["wgate"]["kernel"].astype(x.dtype)).astype(jnp.float32)
    w = w * (n_heads ** -0.5)
    dots = jnp.einsum(
        "bthd,bsd->bhts", q, k, preferred_element_type=jnp.float32
    )
    dots = jax.nn.relu(dots * (head_dim ** -0.5))
    return jnp.einsum("bth,bhts->bts", w, dots)


def topk_select_mask(
    scores: jnp.ndarray,        # (B, S, S) fp32 indexer scores
    base_mask: jnp.ndarray,     # (B?, S, S) bool causal/segment mask
    k: int,
) -> jnp.ndarray:
    """Boolean (B, S, S) selection: per query, EXACTLY the top-k admissible
    keys (lax.top_k tie-breaking — lowest index wins — matching the
    reference's `scores.topk(k).indices` and the chunked sparse path, which
    must agree with this oracle selection-for-selection; a >=-threshold
    formulation over-selects on ties, which the GLM indexer's relu produces
    en masse at exact zero).

    When fewer than k keys are admissible (early queries under causality)
    every admissible key is selected — matching the reference's clamping of
    indexer_topk to the valid prefix."""
    if base_mask.ndim == 2:
        base_mask = jnp.broadcast_to(base_mask[None], scores.shape)
    masked = jnp.where(base_mask, scores, -jnp.inf)
    S = scores.shape[-1]
    k = min(k, S)
    vals, idx = jax.lax.top_k(masked, k)
    sel = jnp.put_along_axis(
        jnp.zeros(masked.shape, bool), idx, jnp.isfinite(vals), axis=-1,
        inplace=False,
    )
    return jnp.logical_and(sel, base_mask)


def indexer_kl_loss(
    scores: jnp.ndarray,      # (B, S, S) fp32 indexer scores
    main_probs: jnp.ndarray,  # (B, S, S) fp32 head-averaged attention probs
    select_mask: jnp.ndarray, # (B, S, S) bool selected set
    token_mask: jnp.ndarray | None = None,  # (B, S) bool; False = pad query
) -> jnp.ndarray:
    """KL(p_main ‖ p_indexer) over the selected set, mean per real query.

    Both distributions renormalize over the selected keys; the main
    attention target is stop-gradiented so only the indexer learns from
    this term (reference: the DSA indexer training objective). Pad queries
    (token_mask False) are excluded — they would otherwise train the
    indexer on garbage distributions."""
    neg = jnp.float32(NEG_INF)
    s = jnp.where(select_mask, scores, neg)
    logq = jax.nn.log_softmax(s, axis=-1)
    p = jnp.where(select_mask, jax.lax.stop_gradient(main_probs), 0.0)
    p = p / jnp.maximum(jnp.sum(p, -1, keepdims=True), 1e-9)
    logp = jnp.log(jnp.maximum(p, 1e-9))
    kl = jnp.sum(p * (logp - logq), axis=-1)  # (B, S)
    if token_mask is None:
        return jnp.mean(kl)
    m = token_mask.astype(jnp.float32)
    return jnp.sum(kl * m) / jnp.maximum(jnp.sum(m), 1.0)
