"""Pallas TPU flash attention — forward + custom-VJP backward.

The TPU-native replacement for the reference's attention kernel stack
(reference: TE `DotProductAttention` injection, nemo_automodel/_transformers/
te_attention.py; FlexAttention block-mask wrapper, components/attention/
flex_attention.py:32). One kernel family covers the mask zoo the reference
spreads across TE/flex/FFPA backends:

- causal (by global token index — valid for packed per-document positions,
  since within a segment document order == global order and cross-segment
  pairs are killed by the segment mask),
- packed-sequence segment ids (the THD/cu_seqlens analog),
- sliding windows (by position, gemma/qwen style),
- attention logit soft-capping (gemma style),
- GQA (kv-head sharing via block index maps, no KV repeat materialized).

Implementation notes:
- Internally (B, H, S, D) layout so blocks satisfy the TPU (8,128) tiling
  rule; per-token int arrays carry an 8-wide trailing/leading broadcast dim
  (compact in HBM, padded only in VMEM).
- Online-softmax forward on a (batch, q_head, q_block, kv_block) grid; the
  kv dimension is innermost so VMEM scratch carries (m, l, acc) across kv
  steps; blocks above the causal diagonal are predicated off with pl.when.
- Backward splits dq (grid over q blocks, scan kv) and dk/dv (grid over kv
  blocks, scan q-heads-in-group × q blocks) — each output is written by
  exactly one grid cell, the standard TPU flash backward decomposition.
- Saves (out, logsumexp) from forward; backward recomputes p block-wise
  (flops-for-memory, same trade as the reference's Triton kernels).
- Runs on CPU via interpret mode for unit-test parity against the XLA
  oracle in ops/attention.py.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)
LANE = 128
SUBLANE = 8


@dataclasses.dataclass(frozen=True)
class BlockSizes:
    block_q: int = 512
    block_kv: int = 512
    block_q_dq: int = 512
    block_kv_dkv: int = 512


def _pick_block(seq: int, want: int) -> int:
    """Largest multiple of LANE that divides seq, capped at `want`."""
    best = 0
    b = LANE
    while b <= min(seq, want):
        if seq % b == 0:
            best = b
        b += LANE
    return best


def _supported(q, k) -> bool:
    B, S, Hq, D = q.shape
    _, T, Hkv, _ = k.shape
    if D % LANE != 0:
        return False
    if _pick_block(S, 512) == 0 or _pick_block(T, 512) == 0:
        return False
    if Hq % Hkv != 0:
        return False
    return True


def _block_mask(iq, ik, qpos_col, kpos_row, qseg_col, kseg_row,
                *, causal, window, block_q, block_kv):
    """(BQ, BK) boolean mask from column/row-shaped aux vectors."""
    mask = jnp.full((block_q, block_kv), True)
    if causal:
        qi = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        ki = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        mask = jnp.logical_and(mask, qi >= ki)
    if window is not None:
        mask = jnp.logical_and(mask, qpos_col - kpos_row < window)
    return jnp.logical_and(mask, qseg_col == kseg_row)


def _run_predicate(iq, ik, *, causal, window, monotonic, block_q, block_kv):
    """Whether this (q_block, kv_block) cell can contain any unmasked pair."""
    run = jnp.bool_(True)
    if causal:
        run = jnp.logical_and(run, (iq + 1) * block_q - 1 >= ik * block_kv)
    if window is not None and monotonic:
        run = jnp.logical_and(run, (ik + 1) * block_kv - 1 >= iq * block_q - window)
    return run


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------
def _fwd_kernel(
    qpos_ref,  # (1, BQ, 8)
    kpos_ref,  # (1, 8, BK)
    qseg_ref,  # (1, BQ, 8)
    kseg_ref,  # (1, 8, BK)
    q_ref,     # (1, 1, BQ, D)
    k_ref,     # (1, 1, BK, D)
    v_ref,
    out_ref,   # (1, 1, BQ, D)
    lse_ref,   # (1, 1, BQ, 8)
    m_scr, l_scr, acc_scr,
    *,
    scale, causal, window, soft_cap, block_q, block_kv, monotonic,
):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = _run_predicate(iq, ik, causal=causal, window=window,
                         monotonic=monotonic, block_q=block_q, block_kv=block_kv)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        mask = _block_mask(
            iq, ik,
            qpos_ref[0, :, :1], kpos_ref[0, :1, :],
            qseg_ref[0, :, :1], kseg_ref[0, :1, :],
            causal=causal, window=window, block_q=block_q, block_kv=block_kv,
        )
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]  # (BQ, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        m = m_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_scr[:] / l_safe
        out = jnp.where(l == 0.0, 0.0, out)
        out_ref[0, 0, :, :] = out.astype(out_ref.dtype)
        lse = jnp.where(l == 0.0, -NEG_INF, m + jnp.log(l_safe))
        lse_ref[0, 0, :, :] = jnp.broadcast_to(lse, lse_ref.shape[2:])


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------
def _recompute_p_ds(q, k, v, do, lse_col, delta_col, mask, *, scale, soft_cap):
    """Shared bwd math: p (softmax probs) and grad wrt the pre-scale scores."""
    s_raw = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if soft_cap is not None:
        t = jnp.tanh(s_raw / soft_cap)
        s = soft_cap * t
    else:
        s = s_raw
    s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse_col)  # (BQ, BK); masked → 0
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta_col)
    if soft_cap is not None:
        ds = ds * (1.0 - t * t)
    ds = jnp.where(mask, ds, 0.0)
    return p, ds * scale


def _dq_kernel(
    qpos_ref, kpos_ref, qseg_ref, kseg_ref,
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref,
    dq_scr,
    *,
    scale, causal, window, soft_cap, block_q, block_kv, monotonic,
):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = _run_predicate(iq, ik, causal=causal, window=window,
                         monotonic=monotonic, block_q=block_q, block_kv=block_kv)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        mask = _block_mask(
            iq, ik,
            qpos_ref[0, :, :1], kpos_ref[0, :1, :],
            qseg_ref[0, :, :1], kseg_ref[0, :1, :],
            causal=causal, window=window, block_q=block_q, block_kv=block_kv,
        )
        _, ds = _recompute_p_ds(
            q, k, v, do, lse_ref[0, 0, :, :1], delta_ref[0, 0, :, :1], mask,
            scale=scale, soft_cap=soft_cap,
        )
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    qpos_ref, kpos_ref, qseg_ref, kseg_ref,
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref,
    dk_scr, dv_scr,
    *,
    scale, causal, window, soft_cap, block_q, block_kv, monotonic,
):
    # grid: (B, Hkv, nk, G, nq) — accumulate over group members and q blocks
    ik, g, iq = pl.program_id(2), pl.program_id(3), pl.program_id(4)
    ng, nq = pl.num_programs(3), pl.num_programs(4)

    @pl.when(jnp.logical_and(g == 0, iq == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _run_predicate(iq, ik, causal=causal, window=window,
                         monotonic=monotonic, block_q=block_q, block_kv=block_kv)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        mask = _block_mask(
            iq, ik,
            qpos_ref[0, :, :1], kpos_ref[0, :1, :],
            qseg_ref[0, :, :1], kseg_ref[0, :1, :],
            causal=causal, window=window, block_q=block_q, block_kv=block_kv,
        )
        p, ds = _recompute_p_ds(
            q, k, v, do, lse_ref[0, 0, :, :1], delta_ref[0, 0, :, :1], mask,
            scale=scale, soft_cap=soft_cap,
        )
        # dv += p^T @ do ; dk += ds^T @ q
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jnp.logical_and(g == ng - 1, iq == nq - 1))
    def _finalize():
        dk_ref[0, 0, :, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side wrappers (public layout: B, S, H, D)
# ---------------------------------------------------------------------------
def _prep_aux(B, S, positions, segment_ids):
    """Build q-side (B,S,8) and kv-side (B,8,S) broadcast aux arrays."""
    monotonic = positions is None
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    else:
        positions = jnp.broadcast_to(positions.astype(jnp.int32), (B, S))
    if segment_ids is None:
        segment_ids = jnp.zeros((B, S), jnp.int32)
    else:
        segment_ids = jnp.broadcast_to(segment_ids.astype(jnp.int32), (B, S))
    q_side = lambda a: jnp.broadcast_to(a[:, :, None], (B, S, SUBLANE))
    kv_side = lambda a: jnp.broadcast_to(a[:, None, :], (B, SUBLANE, S))
    return (q_side(positions), kv_side(positions),
            q_side(segment_ids), kv_side(segment_ids), monotonic)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12))
def _flash(q, k, v, qpos, kpos, qseg, kseg,
           causal, window, soft_cap, scale, block_sizes, monotonic):
    out, _ = _flash_fwd_impl(
        q, k, v, qpos, kpos, qseg, kseg,
        causal, window, soft_cap, scale, block_sizes, monotonic,
    )
    return out


def _flash_fwd_impl(q, k, v, qpos, kpos, qseg, kseg,
                    causal, window, soft_cap, scale, block_sizes, monotonic):
    B, Hq, S, D = q.shape
    _, Hkv, T, _ = k.shape
    G = Hq // Hkv
    BQ = _pick_block(S, block_sizes.block_q)
    BK = _pick_block(T, block_sizes.block_kv)
    nq, nk = S // BQ, T // BK

    kernel = functools.partial(
        _fwd_kernel,
        scale=scale, causal=causal, window=window, soft_cap=soft_cap,
        block_q=BQ, block_kv=BK, monotonic=monotonic,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, Hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, SUBLANE, BK), lambda b, h, iq, ik: (b, 0, ik)),
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, SUBLANE, BK), lambda b, h, iq, ik: (b, 0, ik)),
            pl.BlockSpec((1, 1, BQ, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, h, iq, ik: (b, h // G, ik, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, h, iq, ik: (b, h // G, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, BQ, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, Hq, S, SUBLANE), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BQ, LANE), jnp.float32),
            pltpu.VMEM((BQ, LANE), jnp.float32),
            pltpu.VMEM((BQ, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(qpos, kpos, qseg, kseg, q, k, v)
    return out, lse


def _flash_fwd(q, k, v, qpos, kpos, qseg, kseg,
               causal, window, soft_cap, scale, block_sizes, monotonic):
    out, lse = _flash_fwd_impl(
        q, k, v, qpos, kpos, qseg, kseg,
        causal, window, soft_cap, scale, block_sizes, monotonic,
    )
    return out, (q, k, v, qpos, kpos, qseg, kseg, out, lse)


def _flash_bwd(causal, window, soft_cap, scale, block_sizes, monotonic, res, dout):
    q, k, v, qpos, kpos, qseg, kseg, out, lse = res
    B, Hq, S, D = q.shape
    _, Hkv, T, _ = k.shape
    G = Hq // Hkv
    BQ = _pick_block(S, block_sizes.block_q_dq)
    BK = _pick_block(T, block_sizes.block_kv_dkv)
    nq, nk = S // BQ, T // BK

    # delta = rowsum(dout * out) replicated into the 8-wide aux dim
    delta = jnp.einsum(
        "bhsd,bhsd->bhs", dout.astype(jnp.float32), out.astype(jnp.float32)
    )
    delta = jnp.broadcast_to(delta[..., None], (B, Hq, S, SUBLANE))

    common = dict(
        scale=scale, causal=causal, window=window, soft_cap=soft_cap,
        block_q=BQ, block_kv=BK, monotonic=monotonic,
    )
    aux_specs_q = [
        pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
        pl.BlockSpec((1, SUBLANE, BK), lambda b, h, iq, ik: (b, 0, ik)),
        pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
        pl.BlockSpec((1, SUBLANE, BK), lambda b, h, iq, ik: (b, 0, ik)),
    ]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(B, Hq, nq, nk),
        in_specs=aux_specs_q + [
            pl.BlockSpec((1, 1, BQ, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, h, iq, ik: (b, h // G, ik, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, h, iq, ik: (b, h // G, ik, 0)),
            pl.BlockSpec((1, 1, BQ, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, BQ, D), lambda b, h, iq, ik: (b, h, iq, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((BQ, D), jnp.float32)],
        interpret=_interpret(),
    )(qpos, kpos, qseg, kseg, q, k, v, dout, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=(B, Hkv, nk, G, nq),
        in_specs=[
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, hk, ik, g, iq: (b, iq, 0)),
            pl.BlockSpec((1, SUBLANE, BK), lambda b, hk, ik, g, iq: (b, 0, ik)),
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, hk, ik, g, iq: (b, iq, 0)),
            pl.BlockSpec((1, SUBLANE, BK), lambda b, hk, ik, g, iq: (b, 0, ik)),
            pl.BlockSpec((1, 1, BQ, D), lambda b, hk, ik, g, iq: (b, hk * G + g, iq, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)),
            pl.BlockSpec((1, 1, BQ, D), lambda b, hk, ik, g, iq: (b, hk * G + g, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, hk, ik, g, iq: (b, hk * G + g, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, hk, ik, g, iq: (b, hk * G + g, iq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, BK, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((BK, D), jnp.float32),
            pltpu.VMEM((BK, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(qpos, kpos, qseg, kseg, q, k, v, dout, lse, delta)

    return dq, dk, dv, None, None, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q, k, v,
    *,
    causal: bool = True,
    segment_ids=None,
    positions=None,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
    block_sizes: BlockSizes | None = None,
):
    """Flash attention; shapes q (B,S,Hq,D), k/v (B,T,Hkv,D) → (B,S,Hq,D).

    Raises NotImplementedError for unsupported shapes so the dispatcher in
    ops/attention.py can fall back to the XLA path.
    """
    if not _supported(q, k):
        raise NotImplementedError(
            f"flash_attention: unsupported shapes q={q.shape} k={k.shape} "
            "(need head_dim % 128 == 0 and seq divisible by a 128-multiple block)"
        )
    if sliding_window is not None and not isinstance(sliding_window, int):
        # per-layer traced windows (layer_types scan) not yet supported here
        raise NotImplementedError("flash_attention: traced sliding_window")
    B, S, Hq, D = q.shape
    scale = scale if scale is not None else float(D) ** -0.5
    qpos, kpos, qseg, kseg, monotonic = _prep_aux(B, S, positions, segment_ids)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash(
        qt, kt, vt, qpos, kpos, qseg, kseg,
        causal, sliding_window, logits_soft_cap, float(scale),
        block_sizes or BlockSizes(), monotonic,
    )
    return jnp.swapaxes(out, 1, 2)
