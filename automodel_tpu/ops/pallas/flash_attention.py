"""Pallas flash-attention kernel (TPU).

The analog of the reference's TE `DotProductAttention`/FlexAttention paths
(reference: nemo_automodel/_transformers/te_attention.py,
components/attention/flex_attention.py:32). Implemented in the kernels
milestone; until then the dispatcher in ops/attention.py falls back to the
XLA reference path.
"""

from __future__ import annotations


def flash_attention(q, k, v, *, causal=True, segment_ids=None, positions=None,
                    sliding_window=None, logits_soft_cap=None, scale=None):
    raise NotImplementedError("pallas flash attention lands with the kernels milestone")
