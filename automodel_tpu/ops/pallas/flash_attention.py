"""Pallas TPU flash attention — forward + custom-VJP backward.

The TPU-native replacement for the reference's attention kernel stack
(reference: TE `DotProductAttention` injection, nemo_automodel/_transformers/
te_attention.py; FlexAttention block-mask wrapper, components/attention/
flex_attention.py:32). One kernel family covers the mask zoo the reference
spreads across TE/flex/FFPA backends:

- causal by global token index (the default; valid for packed per-document
  positions, since within a segment document order == global order and
  cross-segment pairs are killed by the segment mask),
- causal by POSITION (q/kv carry independent global positions — the ring
  attention mode, where visiting kv blocks come from other cp ranks),
- packed-sequence segment ids (the THD/cu_seqlens analog),
- sliding windows, static or TRACED (a traced window — e.g. selected per
  layer inside a `lax.scan` — is folded into the per-token `qwin` aux array
  host-side, so the kernel itself never branches on it),
- attention sinks (gpt-oss): the sink joins the softmax denominator but
  contributes no value, so it is exactly a host-side rescale of the no-sink
  kernel output by sigmoid(lse - sink); the VJP stays exact because the
  residuals store the sink-adjusted (out, lse) — see `_flash_bwd`,
- attention logit soft-capping (gemma style),
- GQA (kv-head sharing via block index maps, no KV repeat materialized),
- MLA-shaped heads: v's head_dim may differ from q/k's, and head dims that
  are not lane multiples (64, 96, 192) are zero-padded to the next multiple
  of 128 host-side (differentiable; pad lanes contribute zero logits).

The public entry can also return the per-row logsumexp with a full VJP
(cotangents on lse fold into the kernel's delta term), which is what lets
ring attention merge per-step partials differentiably.

Implementation notes:
- Internally (B, H, S, D) layout so blocks satisfy the TPU (8,128) tiling
  rule; per-token int arrays carry an 8-wide trailing/leading broadcast dim
  (compact in HBM, padded only in VMEM).
- Online-softmax forward on a (batch, q_head, q_block, kv_block) grid; the
  kv dimension is innermost so VMEM scratch carries (m, l, acc) across kv
  steps; blocks above the causal diagonal are predicated off with pl.when.
- Backward splits dq (grid over q blocks, scan kv) and dk/dv (grid over kv
  blocks, scan q-heads-in-group × q blocks) — each output is written by
  exactly one grid cell, the standard TPU flash backward decomposition.
- Saves (out, logsumexp) from forward; backward recomputes p block-wise
  (flops-for-memory, same trade as the reference's Triton kernels).
- Runs on CPU via interpret mode for unit-test parity against the XLA
  oracle in ops/attention.py.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)
# sentinel the kernel writes into lse for fully-masked rows: keeps backward's
# p = exp(s - lse) at exp(-huge) = 0 instead of NaN
EMPTY_LSE = -NEG_INF
LANE = 128
SUBLANE = 8


@dataclasses.dataclass(frozen=True)
class BlockSizes:
    block_q: int = 512
    block_kv: int = 512
    block_q_dq: int = 512
    block_kv_dkv: int = 512


def _pick_block(seq: int, want: int) -> int:
    """Largest multiple of LANE that divides seq, capped at `want`."""
    best = 0
    b = LANE
    while b <= min(seq, want):
        if seq % b == 0:
            best = b
        b += LANE
    return best


def _pad_last(x, multiple: int):
    d = x.shape[-1]
    pad = (-d) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
    return jnp.pad(x, widths)


def _block_mask(iq, ik, qpos_col, qwin_col, kpos_row, qseg_col, kseg_row,
                *, causal_mode, has_window, block_q, block_kv):
    """(BQ, BK) boolean mask from column/row-shaped aux vectors."""
    mask = qseg_col == kseg_row
    if causal_mode == "index":
        qi = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        ki = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        mask = jnp.logical_and(mask, qi >= ki)
    elif causal_mode == "position":
        mask = jnp.logical_and(mask, qpos_col >= kpos_row)
    if has_window:
        # qwin = qpos - window + 1 (host-computed, so `window` may be traced)
        mask = jnp.logical_and(mask, kpos_row >= qwin_col)
        if causal_mode is None:
            # bidirectional local attention: two-sided window. The upper
            # bound qpos + window - 1 == 2*qpos - qwin needs no extra aux.
            mask = jnp.logical_and(mask, kpos_row <= 2 * qpos_col - qwin_col)
    return mask


def _run_predicate(iq, ik, *, causal_mode, skip_window, block_q, block_kv):
    """Whether this (q_block, kv_block) cell can contain any unmasked pair.

    Block skipping needs static info: only index-causal (global order) and a
    static-int window over monotonic positions qualify; everything else runs
    every block and relies on the in-block mask.
    """
    run = jnp.bool_(True)
    if causal_mode == "index":
        run = jnp.logical_and(run, (iq + 1) * block_q - 1 >= ik * block_kv)
    if skip_window is not None:
        # skip_window is only ever set for monotonic positions (qpos == kpos
        # == arange), so the bounds hold for non-causal windows too
        run = jnp.logical_and(
            run, (ik + 1) * block_kv - 1 >= iq * block_q - skip_window
        )
        if causal_mode is None:
            run = jnp.logical_and(
                run, ik * block_kv <= (iq + 1) * block_q - 1 + skip_window
            )
    return run


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------
def _fwd_kernel(
    qpos_ref,  # (1, BQ, 8)
    qwin_ref,  # (1, BQ, 8)
    qseg_ref,  # (1, BQ, 8)
    kpos_ref,  # (1, 8, BK)
    kseg_ref,  # (1, 8, BK)
    q_ref,     # (1, 1, BQ, D)
    k_ref,     # (1, 1, BK, D)
    v_ref,     # (1, 1, BK, Dv)
    out_ref,   # (1, 1, BQ, Dv)
    lse_ref,   # (1, 1, BQ, 8)
    m_scr, l_scr, acc_scr,
    *,
    scale, causal_mode, has_window, skip_window, soft_cap, block_q, block_kv,
):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = _run_predicate(iq, ik, causal_mode=causal_mode, skip_window=skip_window,
                         block_q=block_q, block_kv=block_kv)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        mask = _block_mask(
            iq, ik,
            qpos_ref[0, :, :1], qwin_ref[0, :, :1], kpos_ref[0, :1, :],
            qseg_ref[0, :, :1], kseg_ref[0, :1, :],
            causal_mode=causal_mode, has_window=has_window,
            block_q=block_q, block_kv=block_kv,
        )
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]  # (BQ, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # explicit re-mask: a fully-masked row has m_new == NEG_INF and
        # exp(s - m_new) == 1 for every (masked) entry — zero those out
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        m = m_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_scr[:] / l_safe
        out = jnp.where(l == 0.0, 0.0, out)
        out_ref[0, 0, :, :] = out.astype(out_ref.dtype)
        lse = jnp.where(l == 0.0, EMPTY_LSE, m + jnp.log(l_safe))
        lse_ref[0, 0, :, :] = jnp.broadcast_to(lse, lse_ref.shape[2:])


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------
def _recompute_p_ds(q, k, v, do, lse_col, delta_col, mask, *, scale, soft_cap):
    """Shared bwd math: p (softmax probs) and grad wrt the pre-scale scores."""
    s_raw = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if soft_cap is not None:
        t = jnp.tanh(s_raw / soft_cap)
        s = soft_cap * t
    else:
        s = s_raw
    s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse_col)  # (BQ, BK); masked → 0
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta_col)
    if soft_cap is not None:
        ds = ds * (1.0 - t * t)
    ds = jnp.where(mask, ds, 0.0)
    return p, ds * scale


def _dq_kernel(
    qpos_ref, qwin_ref, qseg_ref, kpos_ref, kseg_ref,
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref,
    dq_scr,
    *,
    scale, causal_mode, has_window, skip_window, soft_cap, block_q, block_kv,
):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = _run_predicate(iq, ik, causal_mode=causal_mode, skip_window=skip_window,
                         block_q=block_q, block_kv=block_kv)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        mask = _block_mask(
            iq, ik,
            qpos_ref[0, :, :1], qwin_ref[0, :, :1], kpos_ref[0, :1, :],
            qseg_ref[0, :, :1], kseg_ref[0, :1, :],
            causal_mode=causal_mode, has_window=has_window,
            block_q=block_q, block_kv=block_kv,
        )
        _, ds = _recompute_p_ds(
            q, k, v, do, lse_ref[0, 0, :, :1], delta_ref[0, 0, :, :1], mask,
            scale=scale, soft_cap=soft_cap,
        )
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    qpos_ref, qwin_ref, qseg_ref, kpos_ref, kseg_ref,
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref,
    dk_scr, dv_scr,
    *,
    scale, causal_mode, has_window, skip_window, soft_cap, block_q, block_kv,
):
    # grid: (B, Hkv, nk, G, nq) — accumulate over group members and q blocks
    ik, g, iq = pl.program_id(2), pl.program_id(3), pl.program_id(4)
    ng, nq = pl.num_programs(3), pl.num_programs(4)

    @pl.when(jnp.logical_and(g == 0, iq == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _run_predicate(iq, ik, causal_mode=causal_mode, skip_window=skip_window,
                         block_q=block_q, block_kv=block_kv)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        mask = _block_mask(
            iq, ik,
            qpos_ref[0, :, :1], qwin_ref[0, :, :1], kpos_ref[0, :1, :],
            qseg_ref[0, :, :1], kseg_ref[0, :1, :],
            causal_mode=causal_mode, has_window=has_window,
            block_q=block_q, block_kv=block_kv,
        )
        p, ds = _recompute_p_ds(
            q, k, v, do, lse_ref[0, 0, :, :1], delta_ref[0, 0, :, :1], mask,
            scale=scale, soft_cap=soft_cap,
        )
        # dv += p^T @ do ; dk += ds^T @ q
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jnp.logical_and(g == ng - 1, iq == nq - 1))
    def _finalize():
        dk_ref[0, 0, :, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side wrappers (public layout: B, S, H, D)
# ---------------------------------------------------------------------------
def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _aux_q(a, B, S):
    return jnp.broadcast_to(a.astype(jnp.int32)[:, :, None], (B, S, SUBLANE))


def _aux_kv(a, B, T):
    return jnp.broadcast_to(a.astype(jnp.int32)[:, None, :], (B, SUBLANE, T))


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13, 14))
def _flash(q, k, v, sinks, qpos, qwin, qseg, kpos, kseg,
           causal_mode, has_window, skip_window, soft_cap, scale, block_sizes):
    out, lse_pub, _ = _flash_fwd_impl(
        q, k, v, sinks, qpos, qwin, qseg, kpos, kseg,
        causal_mode, has_window, skip_window, soft_cap, scale, block_sizes,
    )
    return out, lse_pub


def _flash_fwd_impl(q, k, v, sinks, qpos, qwin, qseg, kpos, kseg,
                    causal_mode, has_window, skip_window, soft_cap, scale,
                    block_sizes):
    B, Hq, S, D = q.shape
    _, Hkv, T, _ = k.shape
    Dv = v.shape[-1]
    G = Hq // Hkv
    BQ = _pick_block(S, block_sizes.block_q)
    BK = _pick_block(T, block_sizes.block_kv)
    nq, nk = S // BQ, T // BK

    kernel = functools.partial(
        _fwd_kernel,
        scale=scale, causal_mode=causal_mode, has_window=has_window,
        skip_window=skip_window, soft_cap=soft_cap, block_q=BQ, block_kv=BK,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, Hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, SUBLANE, BK), lambda b, h, iq, ik: (b, 0, ik)),
            pl.BlockSpec((1, SUBLANE, BK), lambda b, h, iq, ik: (b, 0, ik)),
            pl.BlockSpec((1, 1, BQ, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, h, iq, ik: (b, h // G, ik, 0)),
            pl.BlockSpec((1, 1, BK, Dv), lambda b, h, iq, ik: (b, h // G, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, BQ, Dv), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, S, Dv), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, S, SUBLANE), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BQ, LANE), jnp.float32),
            pltpu.VMEM((BQ, LANE), jnp.float32),
            pltpu.VMEM((BQ, Dv), jnp.float32),
        ],
        interpret=_interpret(),
    )(qpos, qwin, qseg, kpos, kseg, q, k, v)

    lse_row = lse[..., 0]                                # (B, Hq, S)
    empty = lse_row >= 0.5 * EMPTY_LSE
    lse_pub = jnp.where(empty, NEG_INF, lse_row)
    if sinks is not None:
        # sink joins the denominator only: rescale out, lift lse. For a fully
        # masked row all mass goes to the sink → out stays 0, lse becomes sink.
        sink_b = sinks.astype(jnp.float32).reshape(1, Hq, 1)
        lse_tot = jnp.logaddexp(lse_pub, sink_b)
        out = (
            out.astype(jnp.float32) * jnp.exp(lse_pub - lse_tot)[..., None]
        ).astype(out.dtype)
        lse_pub = lse_tot
    # residual for the bwd kernels: fully-masked rows keep the +huge sentinel
    # so p = exp(s - lse) underflows to 0 instead of NaN
    lse_res = jnp.where(empty, EMPTY_LSE, lse_pub)
    return out, lse_pub, lse_res


def _flash_fwd(q, k, v, sinks, qpos, qwin, qseg, kpos, kseg,
               causal_mode, has_window, skip_window, soft_cap, scale,
               block_sizes):
    out, lse_pub, lse_res = _flash_fwd_impl(
        q, k, v, sinks, qpos, qwin, qseg, kpos, kseg,
        causal_mode, has_window, skip_window, soft_cap, scale, block_sizes,
    )
    res = (q, k, v, sinks, qpos, qwin, qseg, kpos, kseg, out, lse_pub, lse_res)
    return (out, lse_pub), res


def _flash_bwd(causal_mode, has_window, skip_window, soft_cap, scale,
               block_sizes, res, cts):
    dout, dlse = cts
    q, k, v, sinks, qpos, qwin, qseg, kpos, kseg, out, lse_pub, lse_res = res
    B, Hq, S, D = q.shape
    _, Hkv, T, _ = k.shape
    Dv = v.shape[-1]
    G = Hq // Hkv
    BQ = _pick_block(S, block_sizes.block_q_dq)
    BK = _pick_block(T, block_sizes.block_kv_dkv)
    nq, nk = S // BQ, T // BK

    # delta = rowsum(dout * out) - dlse: the standard correction term, plus
    # the lse cotangent folded in (d lse / d s_i = p_i, so it rides the same
    # p * (… - delta) expression in the kernels)
    dout = dout.astype(jnp.float32)
    delta = jnp.einsum("bhsd,bhsd->bhs", dout, out.astype(jnp.float32))
    delta = delta - dlse.astype(jnp.float32)
    delta_b = jnp.broadcast_to(delta[..., None], (B, Hq, S, SUBLANE))
    lse_b = jnp.broadcast_to(lse_res[..., None], (B, Hq, S, SUBLANE))
    dout = dout.astype(q.dtype)

    common = dict(
        scale=scale, causal_mode=causal_mode, has_window=has_window,
        skip_window=skip_window, soft_cap=soft_cap, block_q=BQ, block_kv=BK,
    )
    aux_specs_q = [
        pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
        pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
        pl.BlockSpec((1, BQ, SUBLANE), lambda b, h, iq, ik: (b, iq, 0)),
        pl.BlockSpec((1, SUBLANE, BK), lambda b, h, iq, ik: (b, 0, ik)),
        pl.BlockSpec((1, SUBLANE, BK), lambda b, h, iq, ik: (b, 0, ik)),
    ]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(B, Hq, nq, nk),
        in_specs=aux_specs_q + [
            pl.BlockSpec((1, 1, BQ, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, h, iq, ik: (b, h // G, ik, 0)),
            pl.BlockSpec((1, 1, BK, Dv), lambda b, h, iq, ik: (b, h // G, ik, 0)),
            pl.BlockSpec((1, 1, BQ, Dv), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, BQ, D), lambda b, h, iq, ik: (b, h, iq, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((BQ, D), jnp.float32)],
        interpret=_interpret(),
    )(qpos, qwin, qseg, kpos, kseg, q, k, v, dout, lse_b, delta_b)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=(B, Hkv, nk, G, nq),
        in_specs=[
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, hk, ik, g, iq: (b, iq, 0)),
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, hk, ik, g, iq: (b, iq, 0)),
            pl.BlockSpec((1, BQ, SUBLANE), lambda b, hk, ik, g, iq: (b, iq, 0)),
            pl.BlockSpec((1, SUBLANE, BK), lambda b, hk, ik, g, iq: (b, 0, ik)),
            pl.BlockSpec((1, SUBLANE, BK), lambda b, hk, ik, g, iq: (b, 0, ik)),
            pl.BlockSpec((1, 1, BQ, D), lambda b, hk, ik, g, iq: (b, hk * G + g, iq, 0)),
            pl.BlockSpec((1, 1, BK, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)),
            pl.BlockSpec((1, 1, BK, Dv), lambda b, hk, ik, g, iq: (b, hk, ik, 0)),
            pl.BlockSpec((1, 1, BQ, Dv), lambda b, hk, ik, g, iq: (b, hk * G + g, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, hk, ik, g, iq: (b, hk * G + g, iq, 0)),
            pl.BlockSpec((1, 1, BQ, SUBLANE), lambda b, hk, ik, g, iq: (b, hk * G + g, iq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, BK, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)),
            pl.BlockSpec((1, 1, BK, Dv), lambda b, hk, ik, g, iq: (b, hk, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((BK, D), jnp.float32),
            pltpu.VMEM((BK, Dv), jnp.float32),
        ],
        interpret=_interpret(),
    )(qpos, qwin, qseg, kpos, kseg, q, k, v, dout, lse_b, delta_b)

    dsinks = None
    if sinks is not None:
        # d sink = p_sink * (0 - delta_tot + dlse) = -p_sink * delta
        p_sink = jnp.exp(sinks.astype(jnp.float32).reshape(1, Hq, 1) - lse_pub)
        dsinks = -(p_sink * delta).sum(axis=(0, 2)).astype(sinks.dtype)

    return dq, dk, dv, dsinks, None, None, None, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q, k, v,
    *,
    causal: bool = True,
    segment_ids=None,
    positions=None,
    kv_segment_ids=None,
    kv_positions=None,
    sliding_window=None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
    sinks=None,
    block_sizes: BlockSizes | None = None,
    return_lse: bool = False,
):
    """Flash attention; q (B,S,Hq,D), k (B,T,Hkv,D), v (B,T,Hkv,Dv) → (B,S,Hq,Dv).

    `sliding_window` may be a static int or a traced scalar (per-layer window
    selected inside a scan). `kv_positions`/`kv_segment_ids` give the kv side
    independent coordinates (ring attention); providing them switches causal
    masking from global-index to position comparison. `sinks` is a (Hq,)
    vector of learned sink logits (gpt-oss). With `return_lse=True` returns
    (out, lse) where lse is (B, Hq, S) fp32 (NEG_INF for fully-masked rows)
    and is differentiable.

    Raises NotImplementedError for unsupported shapes so the dispatcher in
    ops/attention.py can fall back to the XLA path.
    """
    B, S, Hq, Dq = q.shape
    _, T, Hkv, Dk = k.shape
    Dv = v.shape[-1]
    if Dq != Dk:
        raise NotImplementedError("flash_attention: q/k head_dim mismatch")
    if Hq % Hkv != 0:
        raise NotImplementedError("flash_attention: GQA needs Hq % Hkv == 0")
    if _pick_block(S, 512) == 0 or _pick_block(T, 512) == 0:
        raise NotImplementedError(
            f"flash_attention: seq lens ({S}, {T}) need a 128-multiple block"
        )
    scale = scale if scale is not None else float(Dq) ** -0.5

    asym = kv_positions is not None or kv_segment_ids is not None
    if not causal:
        causal_mode = None
    elif asym:
        causal_mode = "position"
    else:
        causal_mode = "index"

    qp = positions if positions is not None else jnp.arange(S, dtype=jnp.int32)[None, :]
    qp = jnp.broadcast_to(qp.astype(jnp.int32), (B, S))
    if asym:
        kp = kv_positions if kv_positions is not None else qp
        kp = jnp.broadcast_to(kp.astype(jnp.int32), (B, T))
    else:
        kp = qp
    qs = segment_ids if segment_ids is not None else jnp.zeros((B, S), jnp.int32)
    qs = jnp.broadcast_to(qs.astype(jnp.int32), (B, S))
    if asym:
        ks = kv_segment_ids if kv_segment_ids is not None else jnp.zeros((B, T), jnp.int32)
        ks = jnp.broadcast_to(ks.astype(jnp.int32), (B, T))
    else:
        ks = qs

    has_window = sliding_window is not None
    if has_window:
        qwin = qp - (jnp.asarray(sliding_window, jnp.int32) - 1)
        monotonic = positions is None and not asym
        skip_window = (
            sliding_window
            if monotonic and isinstance(sliding_window, int)
            else None
        )
    else:
        qwin = jnp.zeros((B, S), jnp.int32)
        skip_window = None

    # zero-pad narrow head dims to the lane width (differentiable; the pad
    # lanes add zero logits / zero value columns)
    qt = jnp.swapaxes(_pad_last(q, LANE), 1, 2)
    kt = jnp.swapaxes(_pad_last(k, LANE), 1, 2)
    vt = jnp.swapaxes(_pad_last(v, LANE), 1, 2)

    out, lse = _flash(
        qt, kt, vt, sinks,
        _aux_q(qp, B, S), _aux_q(qwin, B, S), _aux_q(qs, B, S),
        _aux_kv(kp, B, T), _aux_kv(ks, B, T),
        causal_mode, has_window, skip_window, logits_soft_cap, float(scale),
        block_sizes or BlockSizes(),
    )
    out = jnp.swapaxes(out, 1, 2)[..., :Dv]
    if return_lse:
        return out, lse
    return out
