"""Pallas TPU kernel for ragged paged attention (serving decode/prefill).

The TPU backend of `ops/paged_attention.py` (arXiv:2604.15464 style): the
grid is (token, page) and the PAGE TABLE drives the kv BlockSpec index map
through scalar prefetch — page j of token t's sequence is DMA'd from
`k_pages[page_tables[t, j]]` directly, so the kernel never materializes the
gathered (T, P, page_size, ...) intermediate the XLA reference builds in
HBM. Pages are streamed innermost with the usual online-softmax (m, l, acc)
VMEM scratch carried across pages (the flash_attention.py recipe), and
pages past a token's position are predicated off with `pl.when` (they still
prefetch — the table's padded entries must point at a valid page index, the
pool's trash page).

Covers the serving engine's hot path: GQA (kv-head sharing via reshape, no
KV repeat) and absorbed-MLA (scores latent + rope parts summed in one
accumulator, output in latent space). Sliding windows and attention sinks
raise NotImplementedError so the dispatcher falls back to the XLA
reference — decode for windowed/sinked models is bandwidth-bound on pages
it must read anyway, so the reference path costs little there.

Head dims are zero-padded to the 128 lane width host-side (pad lanes add
zero logits / zero value columns — exact). Runs on CPU via interpret mode
for unit-test parity against the XLA reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from automodel_tpu.ops.pallas.flash_attention import LANE, NEG_INF, _pad_last


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _gqa_kernel(
    pt_ref,    # (T, P) scalar-prefetch page table
    pos_ref,   # (T,)   scalar-prefetch positions (-1 = pad row)
    q_ref,     # (1, Hq, D)
    k_ref,     # (1, ps, Hkv, D)
    v_ref,     # (1, ps, Hkv, Dv)
    out_ref,   # (1, Hq, Dv)
    m_scr, l_scr, acc_scr,
    *,
    scale, soft_cap, page_size, groups,
):
    t, j = pl.program_id(0), pl.program_id(1)
    np_ = pl.num_programs(1)
    pos = pos_ref[t]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # pages whose first slot is past the token's position hold nothing it
    # may attend to (tables are dense prefixes); pad rows (pos < 0) skip all
    run = jnp.logical_and(pos >= 0, j * page_size <= pos)

    @pl.when(run)
    def _body():
        q = q_ref[0]                     # (Hq, D)
        k = k_ref[0]                     # (ps, Hkv, D)
        v = v_ref[0]                     # (ps, Hkv, Dv)
        Hq, D = q.shape
        ps, Hkv, Dv = v.shape
        qg = q.reshape(Hkv, groups, D)
        # (Hkv, G, ps): contract D, batch over kv heads
        s = jax.lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        kv_idx = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (Hkv, groups, ps), 2
        )
        mask = kv_idx <= pos
        s = jnp.where(mask, s, NEG_INF)
        s = s.reshape(Hq, ps)

        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.where(mask.reshape(Hq, ps), jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape,
        )
        # (Hq, Dv) += (Hkv, G, ps) @ (ps, Hkv, Dv) batched over kv heads
        pv = jax.lax.dot_general(
            p.reshape(Hkv, groups, ps).astype(v.dtype), v,
            (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * alpha + pv.reshape(Hq, Dv)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == np_ - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = jnp.where(l == 0.0, 0.0, acc_scr[:] / l_safe)
        out_ref[0] = out.astype(out_ref.dtype)


def paged_attention_kernel(
    q, k_pages, v_pages, page_tables, positions,
    *,
    scale: float,
    soft_cap: float | None = None,
    window=None,
    sinks=None,
):
    """GQA ragged paged attention; q (T, Hq, D), pages (N, ps, Hkv, D[v]).

    Raises NotImplementedError for features the kernel does not cover so
    `ops/paged_attention.py` can fall back to the XLA reference."""
    if window is not None:
        raise NotImplementedError("paged kernel: sliding windows → XLA path")
    if sinks is not None:
        raise NotImplementedError("paged kernel: attention sinks → XLA path")
    T, Hq, D = q.shape
    N, ps, Hkv, Dv = v_pages.shape
    if Hq % Hkv != 0:
        raise NotImplementedError("paged kernel: GQA needs Hq % Hkv == 0")
    P = page_tables.shape[1]
    G = Hq // Hkv

    qp = _pad_last(q, LANE)
    kp = _pad_last(k_pages, LANE)
    vp = _pad_last(v_pages, LANE)
    Dp, Dvp = qp.shape[-1], vp.shape[-1]

    kernel = functools.partial(
        _gqa_kernel, scale=scale, soft_cap=soft_cap, page_size=ps, groups=G,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T, P),
        in_specs=[
            pl.BlockSpec((1, Hq, Dp), lambda t, j, pt, pos: (t, 0, 0)),
            pl.BlockSpec((1, ps, Hkv, Dp), lambda t, j, pt, pos: (pt[t, j], 0, 0, 0)),
            pl.BlockSpec((1, ps, Hkv, Dvp), lambda t, j, pt, pos: (pt[t, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, Dvp), lambda t, j, pt, pos: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hq, LANE), jnp.float32),
            pltpu.VMEM((Hq, LANE), jnp.float32),
            pltpu.VMEM((Hq, Dvp), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, Hq, Dvp), q.dtype),
        interpret=_interpret(),
    )(page_tables.astype(jnp.int32), positions.astype(jnp.int32), qp, kp, vp)
    return out[..., :Dv]


def _gqa_quant_kernel(
    pt_ref,    # (T, P) scalar-prefetch page table
    pos_ref,   # (T,)   scalar-prefetch positions (-1 = pad row)
    q_ref,     # (1, Hq, D)
    k_ref,     # (1, ps, Hkv, D)  int8
    v_ref,     # (1, ps, Hkv, Dv) int8
    ks_ref,    # (1, ps) f32 per-row K scales of THIS page
    vs_ref,    # (1, ps) f32 per-row V scales
    out_ref,   # (1, Hq, Dv)
    m_scr, l_scr, acc_scr,
    *,
    scale, soft_cap, page_size, groups,
):
    """The int8 variant of `_gqa_kernel`: identical grid/online-softmax
    machinery, but pages arrive quantized and the per-page scale rows ride
    the SAME scalar-prefetch page table (`pt[t, j]` indexes payload and
    scale blocks alike). Dequantization is algebraic per page: the K scale
    multiplies each kv slot's score column, the V scale folds into the
    softmax weights before the value product — the big int8 blocks are
    cast once for the MXU dots, never materialized dequantized in HBM."""
    t, j = pl.program_id(0), pl.program_id(1)
    np_ = pl.num_programs(1)
    pos = pos_ref[t]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = jnp.logical_and(pos >= 0, j * page_size <= pos)

    @pl.when(run)
    def _body():
        q = q_ref[0]                              # (Hq, D)
        k = k_ref[0].astype(jnp.float32)          # (ps, Hkv, D)
        v = v_ref[0].astype(jnp.float32)          # (ps, Hkv, Dv)
        ks = ks_ref[...].reshape(1, 1, page_size)  # per-slot K scales
        vs = vs_ref[...].reshape(1, 1, page_size)
        Hq, D = q.shape
        ps, Hkv, Dv = v.shape
        qg = q.reshape(Hkv, groups, D).astype(jnp.float32)
        # (Hkv, G, ps): contract D, batch over kv heads; the per-row K
        # scale lands on the score column of its kv slot (before any
        # soft-cap nonlinearity)
        s = jax.lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * ks * scale
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        kv_idx = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (Hkv, groups, ps), 2
        )
        mask = kv_idx <= pos
        s = jnp.where(mask, s, NEG_INF)
        s = s.reshape(Hq, ps)

        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.where(mask.reshape(Hq, ps), jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape,
        )
        # V dequant folds into the weights: (p * vs) @ v_int8 == p @ v_fp
        pv = jax.lax.dot_general(
            (p.reshape(Hkv, groups, ps) * vs), v,
            (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * alpha + pv.reshape(Hq, Dv)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == np_ - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = jnp.where(l == 0.0, 0.0, acc_scr[:] / l_safe)
        out_ref[0] = out.astype(out_ref.dtype)


def paged_attention_quant_kernel(
    q, k_pages, v_pages, k_scales, v_scales, page_tables, positions,
    *,
    scale: float,
    soft_cap: float | None = None,
    window=None,
    sinks=None,
):
    """GQA ragged paged attention over int8 pages with (N, ps) per-row
    scales; same contract (and NotImplementedError fallbacks) as
    `paged_attention_kernel`."""
    if window is not None:
        raise NotImplementedError("paged kernel: sliding windows → XLA path")
    if sinks is not None:
        raise NotImplementedError("paged kernel: attention sinks → XLA path")
    T, Hq, D = q.shape
    N, ps, Hkv, Dv = v_pages.shape
    if Hq % Hkv != 0:
        raise NotImplementedError("paged kernel: GQA needs Hq % Hkv == 0")
    P = page_tables.shape[1]
    G = Hq // Hkv

    qp = _pad_last(q, LANE)
    kp = _pad_last(k_pages, LANE)
    vp = _pad_last(v_pages, LANE)
    Dp, Dvp = qp.shape[-1], vp.shape[-1]

    kernel = functools.partial(
        _gqa_quant_kernel,
        scale=scale, soft_cap=soft_cap, page_size=ps, groups=G,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T, P),
        in_specs=[
            pl.BlockSpec((1, Hq, Dp), lambda t, j, pt, pos: (t, 0, 0)),
            pl.BlockSpec((1, ps, Hkv, Dp), lambda t, j, pt, pos: (pt[t, j], 0, 0, 0)),
            pl.BlockSpec((1, ps, Hkv, Dvp), lambda t, j, pt, pos: (pt[t, j], 0, 0, 0)),
            pl.BlockSpec((1, ps), lambda t, j, pt, pos: (pt[t, j], 0)),
            pl.BlockSpec((1, ps), lambda t, j, pt, pos: (pt[t, j], 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, Dvp), lambda t, j, pt, pos: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hq, LANE), jnp.float32),
            pltpu.VMEM((Hq, LANE), jnp.float32),
            pltpu.VMEM((Hq, Dvp), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, Hq, Dvp), q.dtype),
        interpret=_interpret(),
    )(
        page_tables.astype(jnp.int32), positions.astype(jnp.int32),
        qp, kp, vp,
        k_scales.astype(jnp.float32), v_scales.astype(jnp.float32),
    )
    return out[..., :Dv]


def _mla_kernel(
    pt_ref, pos_ref,
    qa_ref,    # (1, n, r)
    qr_ref,    # (1, n, dr)
    c_ref,     # (1, ps, r)
    kr_ref,    # (1, ps, dr)
    out_ref,   # (1, n, r)
    m_scr, l_scr, acc_scr,
    *,
    scale, page_size,
):
    t, j = pl.program_id(0), pl.program_id(1)
    np_ = pl.num_programs(1)
    pos = pos_ref[t]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = jnp.logical_and(pos >= 0, j * page_size <= pos)

    @pl.when(run)
    def _body():
        qa = qa_ref[0]   # (n, r)
        qr = qr_ref[0]   # (n, dr)
        c = c_ref[0]     # (ps, r)
        kr = kr_ref[0]   # (ps, dr)
        n = qa.shape[0]
        ps = c.shape[0]
        # absorbed scores: latent part + rope part share one accumulator
        s = jax.lax.dot_general(
            qa, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s + jax.lax.dot_general(
            qr, kr, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale
        kv_idx = j * page_size + jax.lax.broadcasted_iota(jnp.int32, (n, ps), 1)
        mask = kv_idx <= pos
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape,
        )
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(c.dtype), c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == np_ - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = jnp.where(l == 0.0, 0.0, acc_scr[:] / l_safe)
        out_ref[0] = out.astype(out_ref.dtype)


def paged_mla_attention_kernel(
    q_abs, q_rope, c_pages, kr_pages, page_tables, positions,
    *,
    scale: float,
    window=None,
):
    """Absorbed-MLA ragged paged attention; returns latent outputs (T, n, r)."""
    if window is not None:
        raise NotImplementedError("paged MLA kernel: sliding windows → XLA path")
    T, n, r = q_abs.shape
    N, ps, _ = c_pages.shape
    P = page_tables.shape[1]

    qa = _pad_last(q_abs, LANE)
    qr = _pad_last(q_rope, LANE)
    cp = _pad_last(c_pages, LANE)
    krp = _pad_last(kr_pages, LANE)
    rp, drp = qa.shape[-1], qr.shape[-1]

    kernel = functools.partial(_mla_kernel, scale=scale, page_size=ps)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T, P),
        in_specs=[
            pl.BlockSpec((1, n, rp), lambda t, j, pt, pos: (t, 0, 0)),
            pl.BlockSpec((1, n, drp), lambda t, j, pt, pos: (t, 0, 0)),
            pl.BlockSpec((1, ps, rp), lambda t, j, pt, pos: (pt[t, j], 0, 0)),
            pl.BlockSpec((1, ps, drp), lambda t, j, pt, pos: (pt[t, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n, rp), lambda t, j, pt, pos: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n, LANE), jnp.float32),
            pltpu.VMEM((n, LANE), jnp.float32),
            pltpu.VMEM((n, rp), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, n, rp), q_abs.dtype),
        interpret=_interpret(),
    )(
        page_tables.astype(jnp.int32), positions.astype(jnp.int32),
        qa, qr, cp, krp,
    )
    return out[..., :r]


def _mla_quant_kernel(
    pt_ref, pos_ref,
    qa_ref,    # (1, n, r)
    qr_ref,    # (1, n, dr)
    c_ref,     # (1, ps, r)  int8
    kr_ref,    # (1, ps, dr) int8
    cs_ref,    # (1, ps) f32 per-row latent scales of THIS page
    krs_ref,   # (1, ps) f32 per-row rope scales
    out_ref,   # (1, n, r)
    m_scr, l_scr, acc_scr,
    *,
    scale, page_size,
):
    """int8 variant of `_mla_kernel`: the latent and rope score parts
    carry DIFFERENT per-row scales (two cached quantities, two scale
    arrays), so each is applied to its dot before the parts sum into the
    shared accumulator; the latent scale folds into the softmax weights
    for the value product (values ARE the latent pages)."""
    t, j = pl.program_id(0), pl.program_id(1)
    np_ = pl.num_programs(1)
    pos = pos_ref[t]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = jnp.logical_and(pos >= 0, j * page_size <= pos)

    @pl.when(run)
    def _body():
        qa = qa_ref[0].astype(jnp.float32)    # (n, r)
        qr = qr_ref[0].astype(jnp.float32)    # (n, dr)
        c = c_ref[0].astype(jnp.float32)      # (ps, r)
        kr = kr_ref[0].astype(jnp.float32)    # (ps, dr)
        cs = cs_ref[...].reshape(1, page_size)
        krs = krs_ref[...].reshape(1, page_size)
        n = qa.shape[0]
        ps = c.shape[0]
        s = jax.lax.dot_general(
            qa, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cs
        s = s + jax.lax.dot_general(
            qr, kr, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * krs
        s = s * scale
        kv_idx = j * page_size + jax.lax.broadcasted_iota(jnp.int32, (n, ps), 1)
        mask = kv_idx <= pos
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape,
        )
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p * cs, c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == np_ - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = jnp.where(l == 0.0, 0.0, acc_scr[:] / l_safe)
        out_ref[0] = out.astype(out_ref.dtype)


def paged_mla_attention_quant_kernel(
    q_abs, q_rope, c_pages, kr_pages, c_scales, kr_scales,
    page_tables, positions,
    *,
    scale: float,
    window=None,
):
    """Absorbed-MLA ragged paged attention over int8 latent/rope pages
    with (N, ps) per-row scales; same contract as
    `paged_mla_attention_kernel`."""
    if window is not None:
        raise NotImplementedError("paged MLA kernel: sliding windows → XLA path")
    T, n, r = q_abs.shape
    N, ps, _ = c_pages.shape
    P = page_tables.shape[1]

    qa = _pad_last(q_abs, LANE)
    qr = _pad_last(q_rope, LANE)
    cp = _pad_last(c_pages, LANE)
    krp = _pad_last(kr_pages, LANE)
    rp, drp = qa.shape[-1], qr.shape[-1]

    kernel = functools.partial(_mla_quant_kernel, scale=scale, page_size=ps)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T, P),
        in_specs=[
            pl.BlockSpec((1, n, rp), lambda t, j, pt, pos: (t, 0, 0)),
            pl.BlockSpec((1, n, drp), lambda t, j, pt, pos: (t, 0, 0)),
            pl.BlockSpec((1, ps, rp), lambda t, j, pt, pos: (pt[t, j], 0, 0)),
            pl.BlockSpec((1, ps, drp), lambda t, j, pt, pos: (pt[t, j], 0, 0)),
            pl.BlockSpec((1, ps), lambda t, j, pt, pos: (pt[t, j], 0)),
            pl.BlockSpec((1, ps), lambda t, j, pt, pos: (pt[t, j], 0)),
        ],
        out_specs=pl.BlockSpec((1, n, rp), lambda t, j, pt, pos: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n, LANE), jnp.float32),
            pltpu.VMEM((n, LANE), jnp.float32),
            pltpu.VMEM((n, rp), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, n, rp), q_abs.dtype),
        interpret=_interpret(),
    )(
        page_tables.astype(jnp.int32), positions.astype(jnp.int32),
        qa, qr, cp, krp,
        c_scales.astype(jnp.float32), kr_scales.astype(jnp.float32),
    )
    return out[..., :r]
