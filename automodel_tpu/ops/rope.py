"""Rotary position embeddings.

TPU-native analog of the reference RoPE variants
(reference: nemo_automodel/components/models/llama/rope_utils.py — torch /
fused / quack backends). On TPU a single jnp implementation fuses into the
surrounding matmuls under XLA; no custom kernel is needed for the default
path. Supports llama3-style frequency scaling.
"""

from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RopeScalingConfig:
    """llama3-style NTK/frequency scaling (HF `rope_scaling`)."""

    rope_type: str = "default"  # "default" | "llama3" | "linear"
    factor: float = 1.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192

    @classmethod
    def from_hf(cls, d: dict | None) -> "RopeScalingConfig":
        if not d:
            return cls()
        return cls(
            rope_type=d.get("rope_type", d.get("type", "default")),
            factor=float(d.get("factor", 1.0)),
            low_freq_factor=float(d.get("low_freq_factor", 1.0)),
            high_freq_factor=float(d.get("high_freq_factor", 4.0)),
            original_max_position_embeddings=int(
                d.get("original_max_position_embeddings", 8192)
            ),
        )


def rope_frequencies(
    head_dim: int,
    theta: float = 10000.0,
    scaling: RopeScalingConfig | None = None,
) -> jnp.ndarray:
    """Inverse frequencies, shape (head_dim // 2,), float32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if scaling is None or scaling.rope_type == "default":
        return inv_freq
    if scaling.rope_type == "linear":
        return inv_freq / scaling.factor
    if scaling.rope_type == "llama3":
        low_wavelen = scaling.original_max_position_embeddings / scaling.low_freq_factor
        high_wavelen = scaling.original_max_position_embeddings / scaling.high_freq_factor
        wavelen = 2.0 * math.pi / inv_freq
        # smooth interpolation between scaled and unscaled bands
        smooth = (scaling.original_max_position_embeddings / wavelen - scaling.low_freq_factor) / (
            scaling.high_freq_factor - scaling.low_freq_factor
        )
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = inv_freq / scaling.factor
        blended = (1.0 - smooth) * scaled + smooth * inv_freq
        return jnp.where(
            wavelen < high_wavelen,
            inv_freq,
            jnp.where(wavelen > low_wavelen, scaled, blended),
        )
    raise ValueError(f"Unknown rope_type '{scaling.rope_type}'")


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate (..., seq, heads, head_dim) by per-token positions.

    Uses the HF "half-split" convention: the head_dim is split into two
    halves rotated against each other (matches llama/qwen checkpoints).
    positions: (..., seq) int32.
    """
    orig_dtype = x.dtype
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # (..., S, D/2)
    cos = jnp.cos(angles)[..., :, None, :]  # (..., S, 1, D/2)
    sin = jnp.sin(angles)[..., :, None, :]
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(orig_dtype)
