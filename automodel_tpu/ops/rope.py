"""Rotary position embeddings.

TPU-native analog of the reference RoPE variants
(reference: nemo_automodel/components/models/llama/rope_utils.py — torch /
fused / quack backends). On TPU a single jnp implementation fuses into the
surrounding matmuls under XLA; no custom kernel is needed for the default
path. Supports llama3-style frequency scaling.
"""

from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RopeScalingConfig:
    """Frequency scaling (HF `rope_scaling`): llama3 / linear / yarn."""

    rope_type: str = "default"  # "default" | "llama3" | "linear" | "yarn"
    factor: float = 1.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192
    # yarn (deepseek-style)
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    mscale: float = 1.0
    mscale_all_dim: float = 0.0

    @classmethod
    def from_hf(cls, d: dict | None) -> "RopeScalingConfig":
        if not d:
            return cls()
        return cls(
            rope_type=d.get("rope_type", d.get("type", "default")),
            factor=float(d.get("factor", 1.0)),
            low_freq_factor=float(d.get("low_freq_factor", 1.0)),
            high_freq_factor=float(d.get("high_freq_factor", 4.0)),
            original_max_position_embeddings=int(
                d.get("original_max_position_embeddings", 8192)
            ),
            beta_fast=float(d.get("beta_fast", 32.0)),
            beta_slow=float(d.get("beta_slow", 1.0)),
            mscale=float(d.get("mscale", 1.0)),
            mscale_all_dim=float(d.get("mscale_all_dim", 0.0)),
        )

    def yarn_mscale(self) -> float:
        """Attention-scale correction for yarn (deepseek convention):
        scale *= mscale² with mscale = 0.1·m·ln(factor)+1."""
        if self.rope_type != "yarn" or self.factor <= 1.0:
            return 1.0
        m = self.mscale_all_dim if self.mscale_all_dim else self.mscale
        return float(0.1 * m * math.log(self.factor) + 1.0)


def rope_frequencies(
    head_dim: int,
    theta: float = 10000.0,
    scaling: RopeScalingConfig | None = None,
) -> jnp.ndarray:
    """Inverse frequencies, shape (head_dim // 2,), float32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if scaling is None or scaling.rope_type == "default":
        return inv_freq
    if scaling.rope_type == "linear":
        return inv_freq / scaling.factor
    if scaling.rope_type == "llama3":
        low_wavelen = scaling.original_max_position_embeddings / scaling.low_freq_factor
        high_wavelen = scaling.original_max_position_embeddings / scaling.high_freq_factor
        wavelen = 2.0 * math.pi / inv_freq
        # smooth interpolation between scaled and unscaled bands
        smooth = (scaling.original_max_position_embeddings / wavelen - scaling.low_freq_factor) / (
            scaling.high_freq_factor - scaling.low_freq_factor
        )
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = inv_freq / scaling.factor
        blended = (1.0 - smooth) * scaled + smooth * inv_freq
        return jnp.where(
            wavelen < high_wavelen,
            inv_freq,
            jnp.where(wavelen > low_wavelen, scaled, blended),
        )
    if scaling.rope_type == "yarn":
        # deepseek-yarn: interpolate low-frequency dims, keep high-frequency
        # dims, with a linear ramp between correction dims (beta_fast/slow)
        def correction_dim(num_rot: float) -> float:
            return (
                head_dim
                * math.log(scaling.original_max_position_embeddings / (num_rot * 2 * math.pi))
                / (2 * math.log(theta))
            )

        low = math.floor(correction_dim(scaling.beta_fast))
        high = math.ceil(correction_dim(scaling.beta_slow))
        low = max(low, 0)
        high = min(high, head_dim // 2 - 1)
        ramp = jnp.clip(
            (jnp.arange(head_dim // 2, dtype=jnp.float32) - low) / max(high - low, 0.001),
            0.0,
            1.0,
        )
        keep_mask = 1.0 - ramp  # 1 near low dims (high freq): keep original
        interp = inv_freq / scaling.factor
        return interp * (1.0 - keep_mask) + inv_freq * keep_mask
    raise ValueError(f"Unknown rope_type '{scaling.rope_type}'")


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
    interleaved: bool = False,
) -> jnp.ndarray:
    """Rotate (..., seq, heads, head_dim) by per-token positions.

    Default is the HF "half-split" convention: the head_dim is split into
    two halves rotated against each other (matches llama/qwen checkpoints).
    `interleaved=True` rotates adjacent even/odd pairs instead (GLM-4
    convention, reference: transformers modeling_glm4 rotate_half).

    Partial rotary (GLM/Nemotron `partial_rotary_factor`): when
    2*len(inv_freq) < head_dim only the first 2*len(inv_freq) channels are
    rotated and the tail passes through unchanged.
    positions: (..., seq) int32.

    `inv_freq` with ndim >= 2 is treated as PRECOMPUTED per-token angles
    (..., S, D/2) — the multi-axis rope hook (qwen-vl MRoPE, where each
    channel's angle comes from a different position axis; see
    models/vlm/qwen3_vl.mrope_angles). `positions` is then ignored.
    """
    orig_dtype = x.dtype
    rot = 2 * inv_freq.shape[-1]
    x_pass = None
    if rot < x.shape[-1]:
        x, x_pass = x[..., :rot], x[..., rot:]
    if inv_freq.ndim >= 2:
        angles = inv_freq.astype(jnp.float32)  # (..., S, D/2) precomputed
    else:
        angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # (..., S, D/2)
    cos = jnp.cos(angles)[..., :, None, :]  # (..., S, 1, D/2)
    sin = jnp.sin(angles)[..., :, None, :]
    x = x.astype(jnp.float32)
    if interleaved:
        x1, x2 = x[..., 0::2], x[..., 1::2]
        out = jnp.stack(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        ).reshape(x.shape)
    else:
        x1, x2 = jnp.split(x, 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    out = out.astype(orig_dtype)
    if x_pass is not None:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out
