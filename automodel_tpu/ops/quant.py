"""FP8 / INT8 quantized matmul — the torchao Float8Linear analog.

The reference quantizes linears via torchao `Float8Linear` with dynamic
scaling plus TE FP8 autocast recipes (reference: nemo_automodel/components/
quantization/fp8.py:130 `apply_fp8_to_model`, models/common/utils.py:100-155
TEFp8Config). TPU-native form: a drop-in matmul with per-tensor dynamic
scales, quantize → MXU dot in the low-precision dtype → rescale. Backward
runs in bf16 against the dequantized operands (delayed-scaling-style
training), via custom_vjp. Models opt in with
`TransformerConfig.linear_precision = "fp8" | "int8"`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

FP8_MAX = 448.0   # float8_e4m3fn
INT8_MAX = 127.0


def _quantize(x, qdtype, qmax):
    scale = jnp.max(jnp.abs(x)).astype(jnp.float32) / qmax + 1e-12
    q = (x.astype(jnp.float32) / scale)
    if qdtype == jnp.int8:
        q = jnp.round(q)
    q = jnp.clip(q, -qmax, qmax).astype(qdtype)
    return q, scale


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def quantized_matmul(x, w, precision: str = "fp8"):
    """x (..., K) @ w (K, N) with per-tensor dynamic quantization."""
    return _qmm_fwd(x, w, precision)[0]


def _qmm_fwd(x, w, precision):
    qdtype, qmax = (
        (jnp.int8, INT8_MAX) if precision == "int8" else (jnp.float8_e4m3fn, FP8_MAX)
    )
    qx, sx = _quantize(x, qdtype, qmax)
    qw, sw = _quantize(w, qdtype, qmax)
    out = jnp.einsum(
        "...k,kn->...n", qx, qw, preferred_element_type=jnp.float32
    ) * (sx * sw)
    return out.astype(x.dtype), (x, w)


def _qmm_bwd(precision, res, g):
    # backward in bf16 on the ORIGINAL operands (dynamic-scaling fp8 training
    # quantizes activations/weights forward-only; grads stay high precision)
    x, w = res
    gf = g.astype(jnp.bfloat16)
    dx = jnp.einsum("...n,kn->...k", gf, w.astype(jnp.bfloat16)).astype(x.dtype)
    dw = jnp.einsum(
        "...k,...n->kn",
        x.astype(jnp.bfloat16),
        gf,
        preferred_element_type=jnp.float32,
    ).astype(w.dtype)
    return dx, dw


quantized_matmul.defvjp(_qmm_fwd, _qmm_bwd)


def matmul(x, kernel, precision: str | None = None):
    """Precision-dispatching matmul used by the decoders' linears."""
    if precision in ("fp8", "int8"):
        return quantized_matmul(x, kernel, precision)
    return x @ kernel
