"""FP8 / INT8 quantized matmul + QAT fake-quant — the torchao analog.

The reference quantizes linears via torchao `Float8Linear` with dynamic
scaling plus TE FP8 autocast recipes (reference: nemo_automodel/components/
quantization/fp8.py:130 `apply_fp8_to_model`, models/common/utils.py:100-155
TEFp8Config) and trains quantization-aware via torchao QAT fake-quant with
delayed enabling (reference: quantization/qat.py, recipes/llm/train_ft.py:861
`_maybe_enable_fake_quant`). TPU-native forms:

- `quantized_matmul`: drop-in matmul with PER-CHANNEL dynamic scales
  (rows of x over K, columns of w), quantize → MXU dot in the
  low-precision dtype → rescale. Backward runs in bf16 against the
  original operands (delayed-scaling-style training), via custom_vjp.
  Models opt in with `TransformerConfig.linear_precision = "fp8"|"int8"`.
- `fake_quantize` / `QATConfig.make_param_transform`: straight-through
  quantize-dequantize of weight kernels inside the train step, enabled
  once `step >= start_step` (delayed fake-quant).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

FP8_MAX = 448.0   # float8_e4m3fn
INT8_MAX = 127.0


def _qparams(precision: str):
    if precision == "int8":
        return jnp.int8, INT8_MAX
    if precision == "fp8":
        return jnp.float8_e4m3fn, FP8_MAX
    raise ValueError(f"Unknown quantization precision '{precision}' (int8|fp8)")


def _quantize(x, qdtype, qmax, axis=None):
    """axis=None → per-tensor scale; else per-channel over `axis` reduced.

    Every intermediate runs in f32 and the ±qmax clamp is applied IN f32
    before the low-precision cast: an inf-adjacent input must saturate to
    the grid edge, not ride inf/inf = NaN (scale picks up the inf) or an
    out-of-range f32 through the `astype` — float8_e4m3fn has no inf, so
    an unclamped cast there is free to produce NaN."""
    xf = jnp.clip(
        x.astype(jnp.float32),
        jnp.finfo(jnp.float32).min,
        jnp.finfo(jnp.float32).max,
    )
    scale = (
        jnp.max(jnp.abs(xf), axis=axis, keepdims=axis is not None) / qmax
        + 1e-12
    )
    q = xf / scale
    if qdtype == jnp.int8:
        q = jnp.round(q)
    q = jnp.clip(q, -qmax, qmax).astype(qdtype)
    return q, scale


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def quantized_matmul(x, w, precision: str = "fp8"):
    """x (..., K) @ w (K, N) with per-channel dynamic quantization:
    one scale per x row (over K) and per w output column."""
    return _qmm_fwd(x, w, precision)[0]


def _qmm_fwd(x, w, precision):
    qdtype, qmax = _qparams(precision)
    qx, sx = _quantize(x, qdtype, qmax, axis=-1)   # (..., 1)
    qw, sw = _quantize(w, qdtype, qmax, axis=0)    # (1, N)
    out = jnp.einsum(
        "...k,kn->...n", qx, qw, preferred_element_type=jnp.float32
    ) * (sx * sw[0])
    return out.astype(x.dtype), (x, w)


def _qmm_bwd(precision, res, g):
    # backward in bf16 on the ORIGINAL operands (dynamic-scaling fp8 training
    # quantizes activations/weights forward-only; grads stay high precision)
    x, w = res
    gf = g.astype(jnp.bfloat16)
    dx = jnp.einsum("...n,kn->...k", gf, w.astype(jnp.bfloat16)).astype(x.dtype)
    dw = jnp.einsum(
        "...k,...n->kn",
        x.astype(jnp.bfloat16),
        gf,
        preferred_element_type=jnp.float32,
    ).astype(w.dtype)
    return dx, dw


quantized_matmul.defvjp(_qmm_fwd, _qmm_bwd)


def matmul(x, kernel, precision: str | None = None):
    """Precision-dispatching matmul used by the decoders' linears."""
    if precision in ("fp8", "int8"):
        return quantized_matmul(x, kernel, precision)
    return x @ kernel


# ---------------------------------------------------------------------------
# Paged-KV quantization (serving/kv_pages.py int8 pools)
# ---------------------------------------------------------------------------
def quantize_kv_rows(x):
    """New-token KV cache rows → (int8 rows, per-row f32 scales).

    `x` is (T, ...) — one cache row per leading index (a GQA (Hkv, D) K or
    V row, an MLA (r,) latent or (dr,) rope row); everything behind the
    leading dim shares ONE scale. This is the granularity of the paged
    pool's per-page scale arrays ((L, N+1, ps): one scalar per page slot,
    no head dim — so scales replicate under tp while the int8 payload
    shards its heads exactly like the fp pool). Runs in-jit at scatter
    time inside the serving step."""
    xf = jnp.clip(
        x.astype(jnp.float32),
        jnp.finfo(jnp.float32).min,
        jnp.finfo(jnp.float32).max,
    )
    red = tuple(range(1, x.ndim))
    scale = jnp.max(jnp.abs(xf), axis=red) / INT8_MAX + 1e-12
    sb = scale.reshape(scale.shape + (1,) * (x.ndim - 1))
    q = jnp.clip(jnp.round(xf / sb), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale):
    """Inverse of `quantize_kv_rows` over gathered page views: `scale`'s
    dims align with `q`'s leading dims and broadcast over the rest.
    Returns f32 (the attention score math runs there anyway)."""
    sb = scale.reshape(scale.shape + (1,) * (q.ndim - scale.ndim))
    return q.astype(jnp.float32) * sb


# ---------------------------------------------------------------------------
# QAT (reference: components/quantization/qat.py + train_ft.py:861)
# ---------------------------------------------------------------------------
def fake_quantize(x, precision: str = "int8"):
    """Straight-through quantize-dequantize: forward sees the quantized
    grid, gradients pass through unchanged (STE). Per-channel scales over
    the last (output) dim — reduce over the second-to-last axis so stacked
    (L, in, out) kernels get per-layer-per-column scales."""
    qdtype, qmax = _qparams(precision)
    axis = -2 if x.ndim >= 2 else None
    q, scale = _quantize(x, qdtype, qmax, axis=axis)
    qdq = (q.astype(jnp.float32) * scale).astype(x.dtype)
    return x + jax.lax.stop_gradient(qdq - x)


@dataclasses.dataclass(frozen=True)
class QATConfig:
    """Quantization-aware training: fake-quant the weight kernels inside
    the train step. `start_step` delays enabling (the reference's delayed
    fake-quant: train in high precision first, then adapt to the grid)."""

    enabled: bool = False
    precision: str = "int8"  # int8 | fp8
    start_step: int = 0

    def make_param_transform(self):
        """(params, step) -> params with kernels fake-quantized when
        step >= start_step. Only leaves named 'kernel' (linear weights)
        quantize — embeddings, norms and biases stay high precision."""
        if not self.enabled:
            return None

        def transform(params, step):
            on = step >= self.start_step

            def fq(path, x):
                key = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
                if key != "kernel":
                    return x
                return jnp.where(on, fake_quantize(x, self.precision), x)

            return jax.tree_util.tree_map_with_path(fq, params)

        return transform
