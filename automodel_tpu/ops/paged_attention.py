"""Ragged paged attention over a paged KV pool (serving decode/prefill op).

The attention core of the continuous-batching serving engine
(`automodel_tpu/serving/engine.py`), after arXiv:2604.15464 (Ragged Paged
Attention): query tokens arrive as ONE flat ragged batch — decode tokens
from many requests interleaved with chunked-prefill tokens — and the KV
cache lives in fixed-size pages of a global pool, indexed per token through
a page table. Nothing is padded per request and no dense (B, T) cache is
ever materialized.

Two backends, dispatched like ops/attention.py's flash path:

- XLA reference (this file): gather each token's pages from the pool and
  run masked softmax attention — pure gather/einsum, runs (and is tested)
  under `JAX_PLATFORMS=cpu`, and is the correctness oracle for the kernel.
- Pallas TPU kernel (`ops/pallas/ragged_paged_attention.py`): streams pages
  through VMEM with the page table as a scalar-prefetch BlockSpec index map
  (no gathered (T, P, page, ...) intermediate in HBM); raises
  NotImplementedError for unsupported features (sliding windows, sinks) so
  this dispatcher can fall back to the reference.

Layouts (see serving/kv_pages.py for the pool):

- GQA:  k_pages/v_pages (N, ps, Hkv, D); q (T, Hq, D).
- Quantized pools (serving kv_cache_dtype="int8"): the same page layouts
  hold int8 plus (N, ps) per-row scale arrays riding alongside; the
  reference dequantizes the gathered per-token view, the kernel variant
  dequantizes per page inside the online-softmax loop (the scale rides
  the same scalar-prefetch page table as the payload).
- MLA:  c_pages (N, ps, r) rms-normed kv latents, kr_pages (N, ps, dr)
  rotated shared key-rope head; queries come pre-absorbed — q_abs (T, n, r)
  is q_nope folded through the kv up-projection's key half, q_rope (T, n, dr)
  — and the output is returned in LATENT space (T, n, r): the caller applies
  the value half of the up-projection (exactly `inference/generate.py`'s
  absorbed decode, paged).

Per token t: positions[t] is its sequence position; it attends to pool slots
whose global kv index `page_idx * ps + offset` is <= positions[t] within its
own page table row. Page tables are dense prefixes (pages allocated in
order), so the position bound alone masks both the causal future *and*
unallocated page-table padding (which must still hold a VALID page index —
the pool's trash page — to keep gathers in bounds). positions[t] < 0 marks a
pad row: fully masked, output 0.

Multi-query-per-slot scoring rows: nothing ties a request to one row per
call — chunked prefill feeds whole chunks, and speculative decoding's
draft-then-verify (serving/engine.py) feeds a slot's pending token plus K
provisional drafts at positions p..p+K in the SAME batch. Because the
engine scatters each row's K/V into the pool BEFORE this op gathers (per
layer), a draft row at position p+j attends to the drafts before it
through the ordinary position bound — verifying a whole block costs one
call, the same bandwidth the pages cost anyway. The kernel contract is
unchanged: rows are independent given (page table row, position), so a
verify block is just more ragged rows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.ops.attention import NEG_INF


def _gather_mask(page_tables, positions, page_size, T, P, window=None):
    """(T, P*ps) attend mask from positions (pads → all-False)."""
    kv_idx = jnp.arange(P * page_size, dtype=jnp.int32)
    mask = kv_idx[None, :] <= positions[:, None]  # causal + allocation bound
    if window is not None:
        # window == 0 → global (the layer-scan convention of generate.py)
        dist = positions[:, None] - kv_idx[None, :]
        mask = jnp.logical_and(mask, (window == 0) | (dist < window))
    return mask


def ragged_paged_attention_xla(
    q: jnp.ndarray,            # (T, Hq, D)
    k_pages: jnp.ndarray,      # (N, ps, Hkv, D)
    v_pages: jnp.ndarray,      # (N, ps, Hkv, Dv)
    page_tables: jnp.ndarray,  # (T, P) int32 — per-TOKEN page table row
    positions: jnp.ndarray,    # (T,) int32; -1 = pad row
    *,
    scale: float,
    window=None,               # traced per-layer window; 0/None = global
    soft_cap: float | None = None,
    sinks: jnp.ndarray | None = None,  # (Hq,) learned sink logits
    k_scales: jnp.ndarray | None = None,  # (N, ps) per-row dequant scales
    v_scales: jnp.ndarray | None = None,  # (int8 pages; None = fp pages)
) -> jnp.ndarray:
    """Gather-based reference; returns (T, Hq, Dv) with pad rows zeroed.
    With `k_scales`/`v_scales` the pages are int8: the gather stays on the
    cheap int8 payload (plus the tiny scale rows) and dequantization runs
    on the gathered per-token view in f32 — the CPU-testable oracle for
    the quantized Pallas kernel."""
    T, Hq, D = q.shape
    N, ps, Hkv, _ = k_pages.shape
    P = page_tables.shape[1]
    G = Hq // Hkv

    # gather each token's pages → a contiguous per-token KV view
    keys = k_pages[page_tables].reshape(T, P * ps, Hkv, D)
    values = v_pages[page_tables].reshape(T, P * ps, Hkv, v_pages.shape[-1])
    if k_scales is not None:
        from automodel_tpu.ops.quant import dequantize_kv

        keys = dequantize_kv(
            keys, k_scales[page_tables].reshape(T, P * ps)
        ).astype(q.dtype)
        values = dequantize_kv(
            values, v_scales[page_tables].reshape(T, P * ps)
        ).astype(q.dtype)

    qg = q.reshape(T, Hkv, G, D)
    s = jnp.einsum("tkgd,tckd->tkgc", qg, keys, preferred_element_type=jnp.float32)
    s = s * scale
    if soft_cap is not None:
        s = soft_cap * jnp.tanh(s / soft_cap)
    mask = _gather_mask(page_tables, positions, ps, T, P, window)
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    if sinks is not None:
        sink = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(1, Hkv, G, 1), (T, Hkv, G, 1)
        )
        s = jnp.concatenate([s, sink], axis=-1)
    p = jax.nn.softmax(s, axis=-1)
    if sinks is not None:
        p = p[..., :-1]
    # pad rows (positions < 0): every slot masked → softmax is uniform junk
    # (or all mass on the sink); zero the output explicitly
    p = jnp.where(positions[:, None, None, None] >= 0, p, 0.0)
    o = jnp.einsum("tkgc,tckd->tkgd", p.astype(values.dtype), values)
    return o.reshape(T, Hq, values.shape[-1])


def ragged_paged_mla_attention_xla(
    q_abs: jnp.ndarray,        # (T, n, r) — q_nope absorbed through W_uk
    q_rope: jnp.ndarray,       # (T, n, dr)
    c_pages: jnp.ndarray,      # (N, ps, r) kv latents
    kr_pages: jnp.ndarray,     # (N, ps, dr) shared rotated key-rope head
    page_tables: jnp.ndarray,  # (T, P)
    positions: jnp.ndarray,    # (T,)
    *,
    scale: float,
    window=None,
    c_scales: jnp.ndarray | None = None,   # (N, ps) per-row dequant scales
    kr_scales: jnp.ndarray | None = None,  # (int8 pages; None = fp pages)
) -> jnp.ndarray:
    """Absorbed-MLA reference; returns latent-space outputs (T, n, r)."""
    T, n, r = q_abs.shape
    N, ps, _ = c_pages.shape
    P = page_tables.shape[1]

    c = c_pages[page_tables].reshape(T, P * ps, r)
    kr = kr_pages[page_tables].reshape(T, P * ps, kr_pages.shape[-1])
    if c_scales is not None:
        from automodel_tpu.ops.quant import dequantize_kv

        c = dequantize_kv(
            c, c_scales[page_tables].reshape(T, P * ps)
        ).astype(q_abs.dtype)
        kr = dequantize_kv(
            kr, kr_scales[page_tables].reshape(T, P * ps)
        ).astype(q_abs.dtype)
    s = jnp.einsum("tnr,tcr->tnc", q_abs, c, preferred_element_type=jnp.float32)
    s = s + jnp.einsum("tnd,tcd->tnc", q_rope, kr, preferred_element_type=jnp.float32)
    s = s * scale
    mask = _gather_mask(page_tables, positions, ps, T, P, window)
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(positions[:, None, None] >= 0, p, 0.0)
    return jnp.einsum("tnc,tcr->tnr", p.astype(c.dtype), c)


def _tp_size(mesh_ctx) -> int:
    return 1 if mesh_ctx is None else mesh_ctx.sizes["tp"]


def _annotate_tp(x, mesh_ctx, dim: int):
    """Pin `x`'s axis `dim` to tp — the ONE sharding annotation of the
    reference path (no-op without a mesh: the single-chip program stays
    byte-identical). For GQA `dim` is the head axis (every rank owns
    whole KV heads of every page, so gather + softmax + weighted sum are
    rank-local); for MLA it is the latent-rank axis (heads share one
    latent, so score/value contractions over r reduce cross-rank)."""
    if _tp_size(mesh_ctx) == 1:
        return x
    axes = [None] * x.ndim
    axes[dim] = "tp"
    return jax.lax.with_sharding_constraint(x, mesh_ctx.sharding(*axes))


def _pallas_gqa_shard_map(mesh_ctx):
    """shard_map wrapper for the Pallas GQA kernel under tp>1: each rank
    runs the SAME kernel on its local head slice — q/k/v/out shard the
    head dim, page tables and positions replicate, and the grid/BlockSpec
    machinery (scalar-prefetch page indexing, online softmax) is untouched
    because GQA groups never cross a KV-head boundary."""
    from jax.sharding import PartitionSpec as P

    from automodel_tpu.ops.pallas.ragged_paged_attention import (
        paged_attention_kernel,
    )

    def wrapped(q, k_pages, v_pages, page_tables, positions, *,
                scale, soft_cap, window, sinks):
        tp = mesh_ctx.sizes["tp"]
        if q.shape[1] % tp or k_pages.shape[2] % tp:
            raise NotImplementedError(
                f"heads ({q.shape[1]}/{k_pages.shape[2]}) not divisible by "
                f"tp={tp} — falling back to the XLA reference"
            )
        heads = P(None, "tp", None)
        pages = P(None, None, "tp", None)
        args = (q, k_pages, v_pages, page_tables, positions)
        in_specs = (heads, pages, pages, P(None, None), P(None))
        if sinks is not None:
            args += (sinks,)
            in_specs += (P("tp"),)

        def body(q, k, v, pt, pos, *s):
            return paged_attention_kernel(
                q, k, v, pt, pos, scale=scale, soft_cap=soft_cap,
                window=window, sinks=s[0] if s else None,
            )

        return jax.shard_map(
            body, mesh=mesh_ctx.mesh, in_specs=in_specs, out_specs=heads,
            check_vma=False,
        )(*args)

    return wrapped


def ragged_paged_attention(
    q, k_pages, v_pages, page_tables, positions,
    *,
    scale: float | None = None,
    window=None,
    soft_cap: float | None = None,
    sinks=None,
    impl: str = "auto",
    mesh_ctx=None,
    k_scales=None,
    v_scales=None,
):
    """GQA entry. impl: "xla" | "pallas" | "auto" (pallas on TPU, with a
    shape/feature-based fallback to the reference — the flash dispatch
    pattern of ops/attention.py). With a `mesh_ctx` (tp>1) the reference
    path carries head-sharding annotations and the Pallas kernel runs
    inside a shard_map over the tp axis (rank-local head slices). With
    `k_scales`/`v_scales` ((N, ps) per-row scales) the pages are int8 and
    the quantized kernel/reference dequantizes per page."""
    scale = scale if scale is not None else float(q.shape[-1]) ** -0.5
    quant = k_scales is not None
    resolved = impl
    if impl == "auto":
        resolved = "pallas" if jax.default_backend() == "tpu" else "xla"
    if resolved == "pallas":
        try:
            if _tp_size(mesh_ctx) > 1:
                if quant:
                    # scales replicate while heads shard; the quantized
                    # kernel has no shard_map wrapper yet — the annotated
                    # XLA reference serves the tp>1 quantized path
                    raise NotImplementedError(
                        "tp-sharded quantized paged attention → XLA path"
                    )
                return _pallas_gqa_shard_map(mesh_ctx)(
                    q, k_pages, v_pages, page_tables, positions,
                    scale=scale, soft_cap=soft_cap, window=window,
                    sinks=sinks,
                )
            if quant:
                from automodel_tpu.ops.pallas.ragged_paged_attention import (
                    paged_attention_quant_kernel,
                )

                return paged_attention_quant_kernel(
                    q, k_pages, v_pages, k_scales, v_scales,
                    page_tables, positions,
                    scale=scale, soft_cap=soft_cap, window=window,
                    sinks=sinks,
                )
            from automodel_tpu.ops.pallas.ragged_paged_attention import (
                paged_attention_kernel,
            )

            return paged_attention_kernel(
                q, k_pages, v_pages, page_tables, positions,
                scale=scale, soft_cap=soft_cap, window=window, sinks=sinks,
            )
        except NotImplementedError:
            resolved = "xla"
    if resolved == "xla":
        q = _annotate_tp(q, mesh_ctx, 1)              # head axis
        k_pages = _annotate_tp(k_pages, mesh_ctx, 2)
        v_pages = _annotate_tp(v_pages, mesh_ctx, 2)
        out = ragged_paged_attention_xla(
            q, k_pages, v_pages, page_tables, positions,
            scale=scale, window=window, soft_cap=soft_cap, sinks=sinks,
            k_scales=k_scales, v_scales=v_scales,
        )
        return _annotate_tp(out, mesh_ctx, 1)
    raise ValueError(f"Unknown paged attention impl '{impl}'")


def ragged_paged_mla_attention(
    q_abs, q_rope, c_pages, kr_pages, page_tables, positions,
    *,
    scale: float,
    window=None,
    impl: str = "auto",
    mesh_ctx=None,
    c_scales=None,
    kr_scales=None,
):
    """MLA (absorbed latent-cache) entry; same dispatch contract as the GQA
    one. Returns latent-space outputs (T, n, r). Under tp>1 the latent rank
    r is the sharded dim (q_abs/c_pages/out; the tiny shared rope head
    replicates) — the score contraction reduces over r across ranks, which
    the Pallas kernel's rank-local online softmax cannot express, so the
    sharded MLA path always takes the annotated XLA reference."""
    resolved = impl
    if impl == "auto":
        resolved = "pallas" if jax.default_backend() == "tpu" else "xla"
    quant = c_scales is not None
    if resolved == "pallas":
        try:
            if _tp_size(mesh_ctx) > 1:
                raise NotImplementedError(
                    "latent-sharded MLA paged attention needs the "
                    "cross-rank score reduction — XLA reference only"
                )
            if quant:
                from automodel_tpu.ops.pallas.ragged_paged_attention import (
                    paged_mla_attention_quant_kernel,
                )

                return paged_mla_attention_quant_kernel(
                    q_abs, q_rope, c_pages, kr_pages, c_scales, kr_scales,
                    page_tables, positions,
                    scale=scale, window=window,
                )
            from automodel_tpu.ops.pallas.ragged_paged_attention import (
                paged_mla_attention_kernel,
            )

            return paged_mla_attention_kernel(
                q_abs, q_rope, c_pages, kr_pages, page_tables, positions,
                scale=scale, window=window,
            )
        except NotImplementedError:
            resolved = "xla"
    if resolved == "xla":
        q_abs = _annotate_tp(q_abs, mesh_ctx, 2)      # latent-rank axis
        c_pages = _annotate_tp(c_pages, mesh_ctx, 2)
        out = ragged_paged_mla_attention_xla(
            q_abs, q_rope, c_pages, kr_pages, page_tables, positions,
            scale=scale, window=window,
            c_scales=c_scales, kr_scales=kr_scales,
        )
        return _annotate_tp(out, mesh_ctx, 2)
    raise ValueError(f"Unknown paged attention impl '{impl}'")
