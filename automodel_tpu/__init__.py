"""TPU AutoModel — a TPU-native (JAX/XLA/Pallas/pjit) training framework.

Brand-new implementation of the capabilities of NVIDIA-NeMo/Automodel
(see SURVEY.md): YAML-recipe-driven pretraining / SFT / PEFT / KD for LLMs,
MoE models, VLMs and retrieval models, loading Hugging Face checkpoints into
sharded device arrays. Parallelism is pure configuration over one named
device mesh (`pp / dp_replicate / dp_shard / ep / cp / tp`) via GSPMD
NamedSharding — the TPU-native analog of the reference's DTensor/FSDP2 stack
(reference: nemo_automodel/components/distributed/mesh.py:42).
"""

from automodel_tpu.utils import jax_compat as _jax_compat  # noqa: F401  (installs old-jax shims)

__version__ = "0.1.0"
