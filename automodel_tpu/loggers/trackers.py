"""Experiment-tracker bridges: wandb / mlflow, offline-safe.

The analog of the reference's tracker builders (reference: nemo_automodel/
components/loggers/wandb_utils.py, mlflow_utils.py incl. killed-run
marking, comet_utils.py). Zero-egress environments (and machines without
the client libraries) degrade to a local JSONL mirror with the same API, so
recipes never branch on tracker availability.

YAML:

    wandb:  {project: my-proj, name: run-1, mode: offline}
    mlflow: {tracking_uri: file:./mlruns, experiment: my-exp}
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Optional

import jax

logger = logging.getLogger(__name__)


class _NullTracker:
    """Local JSONL fallback with the tracker interface."""

    def __init__(self, run_dir: str, kind: str):
        self._f = None
        if jax.process_index() == 0:
            os.makedirs(run_dir, exist_ok=True)
            self._f = open(os.path.join(run_dir, f"{kind}_metrics.jsonl"), "a")

    def log(self, metrics: dict, step: int | None = None) -> None:
        if self._f is None:
            return
        rec = {"step": step, "ts": time.time(), **metrics}
        self._f.write(json.dumps(rec, default=float) + "\n")
        self._f.flush()

    def log_config(self, config: dict) -> None:
        self.log({"_config": config})

    def finish(self, status: str = "FINISHED") -> None:
        if self._f is not None:
            self.log({"_status": status})
            self._f.close()
            self._f = None


class WandbTracker:
    def __init__(self, cfg: dict, run_dir: str):
        self._run = None
        self._fallback = None
        if jax.process_index() != 0:
            return
        try:
            import wandb

            self._run = wandb.init(
                project=cfg.get("project", "automodel_tpu"),
                name=cfg.get("name"),
                mode=cfg.get("mode", "offline"),
                dir=run_dir,
                config=cfg.get("config"),
            )
        except Exception as e:  # library missing or no network
            logger.warning("wandb unavailable (%s) — local JSONL mirror", e)
            self._fallback = _NullTracker(run_dir, "wandb")

    def log(self, metrics: dict, step: int | None = None) -> None:
        if self._run is not None:
            self._run.log(metrics, step=step)
        elif self._fallback is not None:
            self._fallback.log(metrics, step)

    def log_config(self, config: dict) -> None:
        if self._run is not None:
            self._run.config.update(config, allow_val_change=True)
        elif self._fallback is not None:
            self._fallback.log_config(config)

    def finish(self, status: str = "FINISHED") -> None:
        if self._run is not None:
            self._run.finish(exit_code=0 if status == "FINISHED" else 1)
            self._run = None
        elif self._fallback is not None:
            self._fallback.finish(status)


class MLflowTracker:
    """Marks the run KILLED on SIGTERM exits (reference: mlflow_utils.py)."""

    def __init__(self, cfg: dict, run_dir: str):
        self._mlflow = None
        self._fallback = None
        if jax.process_index() != 0:
            return
        try:
            import mlflow

            if cfg.get("tracking_uri"):
                mlflow.set_tracking_uri(cfg["tracking_uri"])
            mlflow.set_experiment(cfg.get("experiment", "automodel_tpu"))
            mlflow.start_run(run_name=cfg.get("name"))
            self._mlflow = mlflow
        except Exception as e:
            logger.warning("mlflow unavailable (%s) — local JSONL mirror", e)
            self._fallback = _NullTracker(run_dir, "mlflow")

    def log(self, metrics: dict, step: int | None = None) -> None:
        if self._mlflow is not None:
            clean = {k: float(v) for k, v in metrics.items() if _is_number(v)}
            self._mlflow.log_metrics(clean, step=step)
        elif self._fallback is not None:
            self._fallback.log(metrics, step)

    def log_config(self, config: dict) -> None:
        if self._mlflow is not None:
            self._mlflow.log_params(_flatten(config))
        elif self._fallback is not None:
            self._fallback.log_config(config)

    def finish(self, status: str = "FINISHED") -> None:
        if self._mlflow is not None:
            self._mlflow.end_run(status=status)
            self._mlflow = None
        elif self._fallback is not None:
            self._fallback.finish(status)


def _is_number(v: Any) -> bool:
    try:
        float(v)
        return True
    except (TypeError, ValueError):
        return False


def _flatten(d: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = str(v)
    return out


class CometTracker:
    """Comet experiment bridge (reference: loggers/comet_utils.py) — same
    offline-safe JSONL fallback as the other trackers."""

    def __init__(self, cfg: dict, run_dir: str):
        self._exp = None
        self._fallback = None
        if jax.process_index() != 0:
            return
        try:
            import comet_ml

            self._exp = comet_ml.Experiment(
                project_name=cfg.get("project", "automodel_tpu"),
                workspace=cfg.get("workspace"),
                disabled=bool(cfg.get("disabled", False)),
            )
            if cfg.get("name"):
                self._exp.set_name(cfg["name"])
        except Exception as e:  # library missing or no network
            logger.warning("comet unavailable (%s) — local JSONL mirror", e)
            self._fallback = _NullTracker(run_dir, "comet")

    def log(self, metrics: dict, step: int | None = None) -> None:
        if self._exp is not None:
            self._exp.log_metrics(metrics, step=step)
        elif self._fallback is not None:
            self._fallback.log(metrics, step)

    def log_config(self, config: dict) -> None:
        if self._exp is not None:
            self._exp.log_parameters(config)
        elif self._fallback is not None:
            self._fallback.log_config(config)

    def finish(self, status: str = "FINISHED") -> None:
        if self._exp is not None:
            if status != "FINISHED":
                self._exp.log_other("status", status)
            self._exp.end()
            self._exp = None
        elif self._fallback is not None:
            self._fallback.finish(status)


_TRACKERS = {"wandb": WandbTracker, "mlflow": MLflowTracker, "comet": CometTracker}


def build_trackers(cfg, run_dir: str) -> list:
    """Construct every tracker the YAML asks for."""
    trackers = []
    for key, cls in _TRACKERS.items():
        node = cfg.get(key)
        if node is not None:
            trackers.append(
                cls(node.to_dict() if hasattr(node, "to_dict") else dict(node), run_dir)
            )
    return trackers
