"""JSONL per-step metric logging + rank-filtered stdlib logging.

The analog of the reference's `MetricLogger`/`MetricLoggerDist` and
`setup_logging` (reference: nemo_automodel/components/loggers/
metric_logger.py:88-178, log_utils.py). The JSONL schema mirrors the
reference's CI golden values (tests/ci_tests/golden_values/**/training.jsonl
— per-step loss/grad_norm/lr/tps/mfu records), which is exactly what loss-
curve parity checks consume.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, IO

import jax


class MetricLogger:
    """Append one JSON object per step to a .jsonl file (rank 0 only)."""

    def __init__(self, path: str | None, also_stdout: bool = True):
        self.path = path
        self.also_stdout = also_stdout
        self._f: IO | None = None
        self._counters: dict[str, float] = {}
        if path and jax.process_index() == 0:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._f = open(path, "a")

    def set_counter(self, name: str, value: float) -> None:
        """Pin a counter to an externally-owned monotonic total; current
        values ride every subsequent `log` record. The resilience layer
        mirrors its retry/rollback/wasted-step totals here, so goodput is
        reconstructable from the JSONL."""
        self._counters[name] = value

    def log(self, record: dict) -> None:
        rec = {k: _to_scalar(v) for k, v in record.items()}
        for k, v in self._counters.items():
            rec.setdefault(k, v)
        rec.setdefault("ts", time.time())
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        if self.also_stdout and jax.process_index() == 0:
            step = rec.get("step", "?")
            body = " ".join(
                f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in rec.items()
                if k not in ("ts", "step")
            )
            logging.getLogger("metrics").info("step %s | %s", step, body)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def _to_scalar(v: Any):
    if hasattr(v, "item"):
        try:
            return v.item()
        except Exception:
            return str(v)
    return v


class RankFilter(logging.Filter):
    """Only rank 0 emits (reference: loggers/log_utils.py RankFilter)."""

    def filter(self, record: logging.LogRecord) -> bool:
        return jax.process_index() == 0


def setup_logging(level: int = logging.INFO) -> None:
    handler = logging.StreamHandler(sys.stdout)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s [%(name)s] %(message)s")
    )
    handler.addFilter(RankFilter())
    root = logging.getLogger()
    root.handlers = [handler]
    root.setLevel(level)
