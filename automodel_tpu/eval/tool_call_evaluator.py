"""Tool-call accuracy evaluation for agent SFT.

The analog of the reference evaluator (reference: nemo_automodel/
components/eval/tool_call_evaluator.py + parser): extract JSON tool calls
from generated text, compare against gold calls by function name and
arguments (exact and fuzzy-normalized), and report call/name/arg accuracy.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any


_CALL_RE = re.compile(
    r"<tool_call>\s*(\{.*?\})\s*</tool_call>|```json\s*(\{.*?\})\s*```",
    re.DOTALL,
)


def parse_tool_calls(text: str) -> list[dict]:
    """Extract tool-call dicts from generated text.

    Accepts `<tool_call>{...}</tool_call>` blocks, ```json fences, or the
    whole string being a JSON object/array of {name, arguments}.
    """
    calls: list[dict] = []
    for m in _CALL_RE.finditer(text):
        blob = m.group(1) or m.group(2)
        try:
            calls.append(json.loads(blob))
        except json.JSONDecodeError:
            continue
    if not calls:
        try:
            data = json.loads(text.strip())
            if isinstance(data, dict):
                calls = [data]
            elif isinstance(data, list):
                calls = [c for c in data if isinstance(c, dict)]
        except json.JSONDecodeError:
            pass
    return [c for c in map(normalize_call, calls) if c is not None]


def normalize_call(c: dict) -> dict | None:
    """Canonicalize one call dict (shared by predictions AND gold refs):
    resolve name aliases and JSON-decode string-typed arguments."""
    name = c.get("name") or c.get("function", {}).get("name")
    args = c.get("arguments", c.get("parameters", {}))
    if isinstance(args, str):
        try:
            args = json.loads(args)
        except json.JSONDecodeError:
            args = {"_raw": args}
    if not name:
        return None
    return {"name": name, "arguments": args or {}}


def _norm(v: Any) -> Any:
    if isinstance(v, str):
        s = v.strip().lower()
        try:
            return float(s)
        except ValueError:
            return s
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, dict):
        return {k: _norm(x) for k, x in sorted(v.items())}
    if isinstance(v, list):
        return [_norm(x) for x in v]
    return v


@dataclasses.dataclass
class ToolCallMetrics:
    num_examples: int = 0
    name_matches: int = 0
    exact_matches: int = 0
    fuzzy_matches: int = 0

    def as_dict(self) -> dict:
        n = max(self.num_examples, 1)
        return {
            "num_examples": self.num_examples,
            "name_accuracy": self.name_matches / n,
            "exact_accuracy": self.exact_matches / n,
            "fuzzy_accuracy": self.fuzzy_matches / n,
        }


def evaluate_tool_calls(predictions: list[str], references: list[list[dict]]) -> dict:
    """Per-example: all gold calls must be matched (order-insensitive)."""
    if len(predictions) != len(references):
        raise ValueError(
            f"{len(predictions)} predictions vs {len(references)} references"
        )
    m = ToolCallMetrics()
    for pred_text, gold in zip(predictions, references):
        m.num_examples += 1
        pred = parse_tool_calls(pred_text)
        gold = [c for c in map(normalize_call, gold) if c is not None]
        if sorted(c["name"] for c in pred) == sorted(c["name"] for c in gold):
            m.name_matches += 1
        else:
            continue
        def key_exact(c):
            return (c["name"], json.dumps(c["arguments"], sort_keys=True))
        def key_fuzzy(c):
            return (c["name"], json.dumps(_norm(c["arguments"]), sort_keys=True))
        if sorted(map(key_exact, pred)) == sorted(map(key_exact, gold)):
            m.exact_matches += 1
            m.fuzzy_matches += 1
        elif sorted(map(key_fuzzy, pred)) == sorted(map(key_fuzzy, gold)):
            m.fuzzy_matches += 1
    return m.as_dict()
