"""Validation-time sampling eval: generate continuations, score them.

The analog of the reference's DP-sharded sampling eval (reference:
nemo_automodel/components/eval/ — generation metrics computed per DP rank
over that rank's shard, then reduced). Here each process evaluates the
batches its dataloader shard yields (the loader is already DP-rank
sharded); metrics reduce across processes with a host all-gather when
multi-host.

Metrics:
- gen_token_accuracy: greedy continuation tokens matching the reference
  continuation, over supervised positions.
- gen_prefix_len: mean exact-match prefix length (the acceptance-length
  analog for plain generation).
- tool-call precision/recall/F1 when a tokenizer is given and references
  carry `<tool_call>` blocks (eval/tool_call_evaluator).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

IGNORE_INDEX = -100


def run_sampling_eval(
    params,
    model_cfg,
    batches,                  # iterable of {"input_ids", "labels", ...} (np)
    *,
    prompt_len: int = 16,
    max_new_tokens: int = 32,
    max_batches: int = 4,
    eos_token_id: int | None = None,
    tokenizer=None,
    seed: int = 0,
) -> dict:
    """Greedy-generate from each batch's prompt prefix and score against the
    corpus continuation. Returns a flat dict of scalar metrics."""
    from automodel_tpu.inference.generate import GenerateConfig, generate

    gen = GenerateConfig(max_new_tokens=max_new_tokens, eos_token_id=eos_token_id)
    tok_hits = tok_total = 0.0
    prefix_sum = prefix_n = 0.0
    preds_text: list[str] = []
    refs_text: list[str] = []
    for bi, mb in enumerate(batches):
        if bi >= max_batches:
            break
        ids = jnp.asarray(np.asarray(mb["input_ids"]))
        if ids.shape[1] <= prompt_len:
            continue
        prompts = ids[:, :prompt_len]
        out = generate(params, model_cfg, prompts, jax.random.key(seed + bi), gen)
        n_ref = min(max_new_tokens, ids.shape[1] - prompt_len)
        cont = np.asarray(out[:, prompt_len : prompt_len + n_ref])
        ref = np.asarray(ids[:, prompt_len : prompt_len + n_ref])
        # labels are pre-shifted (labels[t] supervises ids[t+1]): the token
        # at absolute position p carries supervision flag labels[p-1]
        labels = np.asarray(mb["labels"])[:, prompt_len - 1 : prompt_len - 1 + n_ref]
        valid = labels != IGNORE_INDEX
        hit = (cont == ref) & valid
        tok_hits += float(hit.sum())
        tok_total += float(valid.sum())
        # exact-match prefix length per sample (over valid positions)
        miss = (~hit) & valid
        first_miss = np.where(
            miss.any(axis=1), miss.argmax(axis=1), valid.sum(axis=1)
        )
        prefix_sum += float(first_miss.sum())
        prefix_n += float(len(first_miss))
        if tokenizer is not None:
            for row_pred, row_ref, row_valid in zip(cont, ref, valid):
                preds_text.append(tokenizer.decode([int(t) for t, v in zip(row_pred, row_valid) if v]))
                refs_text.append(tokenizer.decode([int(t) for t, v in zip(row_ref, row_valid) if v]))

    totals = np.asarray([tok_hits, tok_total, prefix_sum, prefix_n])
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        totals = np.asarray(
            multihost_utils.process_allgather(jnp.asarray(totals))
        ).sum(axis=0)
    tok_hits, tok_total, prefix_sum, prefix_n = [float(x) for x in totals]
    metrics = {
        "gen_token_accuracy": tok_hits / max(tok_total, 1.0),
        "gen_prefix_len": prefix_sum / max(prefix_n, 1.0),
        "gen_samples": prefix_n,
    }
    if tokenizer is not None and refs_text:
        from automodel_tpu.eval.tool_call_evaluator import (
            evaluate_tool_calls,
            parse_tool_calls,
        )

        ref_calls = [parse_tool_calls(t) for t in refs_text]
        if any(ref_calls):
            tc = evaluate_tool_calls(preds_text, ref_calls)
            metrics.update({f"tool_{k}": v for k, v in tc.items()})
    return metrics
