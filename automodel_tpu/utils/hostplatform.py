"""Force the host (CPU) JAX platform with a virtual device count.

Single home for the recipe used by tests/conftest.py, bench.py and
__graft_entry__.py: this container's sitecustomize registers an `axon` TPU
platform with priority over env vars, and if that tunnel is down, any
backend touch hangs indefinitely. Must be called BEFORE the first JAX
backend initialization (importing jax is fine — backends are lazy).
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_devices(n_devices: int = 1) -> None:
    """Point JAX at an n-device virtual CPU platform, replacing any stale
    device count already present in XLA_FLAGS."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"{_COUNT_FLAG}={n_devices}"
    if _COUNT_FLAG in flags:
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags

    # The env var alone loses to the sitecustomize platform registration;
    # the config knob must be set too.
    import jax

    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            raise RuntimeError(
                "force_cpu_devices() called after a JAX backend was already "
                "initialized — the CPU platform / device count cannot take "
                "effect. Call it before any jax.devices()/computation."
            )
    except (ImportError, AttributeError):
        pass  # private API moved; skip the guard rather than lie

    jax.config.update("jax_platforms", "cpu")
