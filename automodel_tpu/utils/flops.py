"""MFU / FLOPs accounting.

The analog of the reference `AutoMFU` + flops_utils (reference:
nemo_automodel/_transformers/mfu.py:110, components/utils/flops_utils.py):
per-architecture FLOPs formulas live on the model configs
(`flops_per_token`); this module adds the device peak-FLOPs table and the
MFU/TPS computation used by recipes and bench.py.
"""

from __future__ import annotations

import dataclasses

import jax

#: bf16 peak TFLOP/s per chip (dense). Sources: public TPU/GPU spec sheets.
PEAK_TFLOPS = {
    "tpu v4": 275.0,
    "tpu v5 lite": 197.0,   # v5e
    "tpu v5e": 197.0,
    "tpu v5p": 459.0,
    "tpu v5": 459.0,
    "tpu v6 lite": 918.0,   # trillium
    "tpu v6e": 918.0,
    "h100": 989.0,
    "a100": 312.0,
    "cpu": 1.0,
}


def device_peak_tflops(device=None) -> float:
    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for name, peak in PEAK_TFLOPS.items():
        if name in kind:
            return peak
    return 100.0  # unknown accelerator — report *something* deterministic


@dataclasses.dataclass
class MFUCalculator:
    """tokens/sec + MFU from a model config's flops_per_token."""

    flops_per_token: float
    num_devices: int = 1
    peak_tflops_per_device: float | None = None

    def __post_init__(self):
        if self.peak_tflops_per_device is None:
            self.peak_tflops_per_device = device_peak_tflops()

    def metrics(self, num_tokens: int, seconds: float) -> dict:
        tps = num_tokens / seconds
        achieved = tps * self.flops_per_token
        peak = self.peak_tflops_per_device * 1e12 * self.num_devices
        return {
            "tps": tps,
            "tps_per_device": tps / self.num_devices,
            "tflops_per_device": achieved / self.num_devices / 1e12,
            "mfu_pct": 100.0 * achieved / peak,
        }
