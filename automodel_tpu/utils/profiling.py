"""Profiling hooks — the autonvtx analog.

The reference wraps modules in NVTX range push/pop hooks
(reference: nemo_automodel/autonvtx/__init__.py:33-97, enabled by
`nvtx: true`). The TPU equivalents: `jax.profiler` traces (viewable in
TensorBoard/XProf/Perfetto) and `jax.named_scope` annotations — plus jit
already names computations after the jitted function, so a trace of the
train step decomposes per-op without per-module hooks.

Recipe usage (`profiling:` YAML section):

    profiling: {trace_dir: runs/trace, start_step: 5, num_steps: 3}
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import jax

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ProfilingConfig:
    trace_dir: Optional[str] = None
    start_step: int = 5     # skip compile + warmup steps
    num_steps: int = 3

    def build(self) -> "Profiler":
        return Profiler(self)


class Profiler:
    """Step-windowed trace capture; call `step(n)` once per train step."""

    def __init__(self, config: ProfilingConfig):
        self.config = config
        self._active = False
        self.done = False

    def step(self, step_num: int) -> None:
        c = self.config
        if c.trace_dir is None or self.done:
            return
        if not self._active and step_num >= c.start_step:
            jax.profiler.start_trace(c.trace_dir)
            self._active = True
            logger.info("profiler trace started (step %d) → %s", step_num, c.trace_dir)
        elif self._active and step_num >= c.start_step + c.num_steps:
            jax.profiler.stop_trace()
            self._active = False
            self.done = True
            logger.info("profiler trace written to %s", c.trace_dir)

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self.done = True


annotate = jax.named_scope  # the NVTX-range analog for model code
