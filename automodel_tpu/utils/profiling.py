"""Deprecated shim — profiling moved to `automodel_tpu.observability.profiler`.

Kept so existing imports (`from automodel_tpu.utils.profiling import
ProfilingConfig`) and recipe YAML (`profiling:` section) keep working.
New code should import from `automodel_tpu.observability` directly.
"""

from __future__ import annotations

import warnings

from automodel_tpu.observability.profiler import (  # noqa: F401
    Profiler,
    ProfilingConfig,
    ServeProfiler,
    annotate,
    serve_step_cost,
    step_efficiency,
)

warnings.warn(
    "automodel_tpu.utils.profiling moved to "
    "automodel_tpu.observability.profiler; this shim will be removed",
    DeprecationWarning,
    stacklevel=2,
)

__all__ = [
    "Profiler",
    "ProfilingConfig",
    "ServeProfiler",
    "annotate",
    "serve_step_cost",
    "step_efficiency",
]
