"""Compatibility layer for older jax releases (>= 0.4.37).

The codebase targets the modern jax API surface — `jax.shard_map` with
`check_vma`, `jax.sharding.use_mesh`, `lax.ragged_all_to_all`. Hosts that
ship an older jaxlib (e.g. the CPU-only CI container on jax 0.4.37) still
have the same functionality under the pre-stabilization names:

- `jax.shard_map(..., check_vma=)`  → `jax.experimental.shard_map.shard_map
  (..., check_rep=)` — identical semantics; `check_vma` renamed from
  `check_rep` when shard_map graduated out of experimental.
- `jax.sharding.use_mesh(mesh)`     → the `Mesh` object itself, which has
  been a context manager since 0.4.x.
- `lax.ragged_all_to_all`           → no pre-stabilization spelling exists;
  install a stub that raises with guidance (every CPU code path already
  selects the dense `all_to_all` layout via `ragged=False`, so the stub
  only fires if a TPU-only path is forced on an old host).

`install()` is idempotent and a no-op on modern jax; it runs once at
`automodel_tpu` import time so every entry point (tests, recipes, bench,
__graft_entry__) sees one consistent surface.
"""

from __future__ import annotations

import jax
from jax import lax


def _shard_map_compat(f=None, /, *, mesh=None, in_specs=None, out_specs=None,
                      check_vma=True, **kwargs):
    from jax.experimental.shard_map import shard_map as _sm

    kwargs.pop("axis_names", None)  # new-API-only knob; default = all axes
    if f is None:  # decorator form: jax.shard_map(mesh=..., ...)(f)
        return lambda fn: _shard_map_compat(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kwargs,
        )
    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, **kwargs,
    )


def _axis_size_compat(axis_name):
    """`lax.axis_size` predecessor: read the bound axis env (concrete int,
    usable in shapes — `lax.psum(1, name)` would be traced)."""
    from jax._src.core import get_axis_env

    if isinstance(axis_name, (tuple, list)):
        import math

        return math.prod(_axis_size_compat(a) for a in axis_name)
    return get_axis_env().axis_size(axis_name)


def _ragged_all_to_all_missing(*args, **kwargs):
    raise NotImplementedError(
        "lax.ragged_all_to_all is unavailable on this jax "
        f"({jax.__version__}); the dropless EP dispatch must run with "
        "ragged=False (dense bucket all_to_all) on this host — see "
        "moe/experts.py:_dropless_ep_local"
    )


def install() -> None:
    """Idempotently bridge the old jax API surface to the modern names."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_compat
    if not hasattr(jax.sharding, "use_mesh"):
        # Mesh is itself a context manager (sets the ambient resource env);
        # use_mesh only adds sharding-in-types plumbing we don't rely on.
        jax.sharding.use_mesh = lambda mesh: mesh
    if not hasattr(lax, "axis_size"):
        lax.axis_size = _axis_size_compat
    if not hasattr(lax, "ragged_all_to_all"):
        lax.ragged_all_to_all = _ragged_all_to_all_missing


install()
