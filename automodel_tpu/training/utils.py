"""Training utilities: EMA, NEFTune noise, Megatron-style timers.

Analogs of the reference training utils (reference: nemo_automodel/
components/training/ema.py:40,97 EMA managers; neftune.py noisy
embeddings; timers.py Megatron-style timer hierarchy).
"""

from __future__ import annotations

import dataclasses
import time
from collections import defaultdict
from typing import Any

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# EMA — exponential moving average of params (reference: training/ema.py)
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class EMAConfig:
    decay: float = 0.999
    update_every: int = 1


def init_ema(params: Any) -> Any:
    return jax.tree.map(lambda p: p, params)


def update_ema(ema: Any, params: Any, decay: float) -> Any:
    """ema ← decay·ema + (1-decay)·params (jit-friendly, sharding-preserving)."""
    return jax.tree.map(lambda e, p: decay * e + (1.0 - decay) * p, ema, params)


# ---------------------------------------------------------------------------
# NEFTune — uniform noise on embeddings during SFT (reference: neftune.py)
# ---------------------------------------------------------------------------
def neftune_noise(embeddings: jnp.ndarray, rng: jax.Array, alpha: float) -> jnp.ndarray:
    """Add U(-mag, mag) with mag = alpha / sqrt(seq_len * dim) per NEFTune."""
    B, S, D = embeddings.shape
    mag = alpha / jnp.sqrt(jnp.float32(S * D))
    noise = jax.random.uniform(rng, embeddings.shape, jnp.float32, -1.0, 1.0) * mag
    return embeddings + noise.astype(embeddings.dtype)


# ---------------------------------------------------------------------------
# GC cadence (reference: training/garbage_collection.py:22) — automatic
# gen-2 collections mid-step cause host-side jitter that shows up as device
# bubbles; freeze the warm state and collect on a fixed step cadence instead.
# ---------------------------------------------------------------------------
import gc


class GCController:
    def __init__(self, every_steps: int = 100, enabled: bool = True):
        self.every_steps = every_steps
        self.enabled = enabled
        if enabled:
            gc.collect()
            gc.freeze()
            gc.disable()

    def step(self, step_num: int) -> None:
        if self.enabled and self.every_steps > 0 and step_num % self.every_steps == 0:
            gc.collect()

    def close(self) -> None:
        if self.enabled:
            gc.enable()
            gc.unfreeze()
            self.enabled = False


# ---------------------------------------------------------------------------
# Timers (reference: training/timers.py)
# ---------------------------------------------------------------------------
class Timers:
    """Named wall-clock timers with simple start/stop/log semantics."""

    def __init__(self):
        self._starts: dict[str, float] = {}
        self._totals: dict[str, float] = defaultdict(float)
        self._counts: dict[str, int] = defaultdict(int)

    def start(self, name: str) -> None:
        self._starts[name] = time.perf_counter()

    def stop(self, name: str) -> float:
        dt = time.perf_counter() - self._starts.pop(name)
        self._totals[name] += dt
        self._counts[name] += 1
        return dt

    def __call__(self, name: str):
        """Context-manager form: `with timers("fwd"): ...`"""
        timers = self

        class _Ctx:
            def __enter__(self):
                timers.start(name)

            def __exit__(self, *exc):
                timers.stop(name)

        return _Ctx()

    def summary(self) -> dict:
        return {
            name: {
                "total_s": self._totals[name],
                "count": self._counts[name],
                "mean_ms": 1e3 * self._totals[name] / max(self._counts[name], 1),
            }
            for name in self._totals
        }
