"""Checkpointable, rank-aware RNG.

The analog of the reference `StatefulRNG` / `ScopedRNG`
(reference: nemo_automodel/components/training/rng.py:85,117). JAX keys are
functional, so "stateful" here means a counter-based key stream that
serializes into the recipe checkpoint and replays identically on resume.
"""

from __future__ import annotations

import jax


class StatefulRNG:
    def __init__(self, seed: int = 0, ranked: bool = True):
        self.seed = int(seed)
        self.ranked = bool(ranked)
        self.counter = 0
        base = jax.random.key(self.seed)
        if ranked:
            base = jax.random.fold_in(base, jax.process_index())
        self._base = base

    def next_key(self) -> jax.Array:
        self.counter += 1
        return jax.random.fold_in(self._base, self.counter)

    def state_dict(self) -> dict:
        return {"seed": self.seed, "ranked": self.ranked, "counter": self.counter}

    def load_state_dict(self, state: dict) -> None:
        assert int(state["seed"]) == self.seed, "resume with a different seed"
        self.counter = int(state["counter"])
