"""The optimizer step: grad accumulation, global-token normalization,
grad clipping, parameter update — one jitted pure function.

The analog of the reference's hot loop
(reference: nemo_automodel/recipes/llm/train_ft.py:1085
`_run_train_optim_step` + :938 `_forward_backward_step` and
components/training/utils.py:379 `scale_grads_and_clip_grad_norm`).
Differences by design:

- Microbatching is a `lax.scan` INSIDE one jit, not a Python loop of
  backward calls — XLA overlaps the FSDP all-gathers with compute the way
  the reference's `defer_fsdp_grad_sync` does imperatively.
- Loss normalization: per-microbatch losses are summed, gradients are summed,
  and both divide by the GLOBAL number of label tokens (train_ft.py:1093's
  dp all-reduce is implicit: under GSPMD a `jnp.sum` over a dp/cp-sharded
  array is already global).
- Grad norm is computed over the full (sharded) pytree — DTensor/EP/PP
  special-casing (grad_utils.py:112) is unnecessary because GSPMD owns the
  layout.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class TrainState(NamedTuple):
    step: jnp.ndarray  # () int32
    params: Any        # fp32 master weights (sharded per param rules)
    opt_state: Any


def init_train_state(params, tx: optax.GradientTransformation) -> TrainState:
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))


@dataclasses.dataclass
class TrainStepConfig:
    max_grad_norm: Optional[float] = 1.0
    # skip the optimizer update when gradients are non-finite (loss spike /
    # overflow robustness; the reference guards via assert_finite CI checks)
    skip_nonfinite_updates: bool = False


def make_train_step(
    loss_fn: Callable,  # (params, batch_slice, rng) -> (loss_sum, aux)
    tx: optax.GradientTransformation,
    lr_schedule: Callable | None = None,
    config: TrainStepConfig | None = None,
    param_transform: Callable | None = None,  # (params, step) -> params (QAT)
    grad_fn: Callable | None = None,  # (params, mb, rng, *extra) -> (grads, loss_sum, aux)
) -> Callable:
    """Build `train_step(state, batch, rng) -> (state, metrics)`.

    `batch` leaves are (accum_steps, microbatch, ...); accumulation runs as a
    scan over dim 0. Loss functions return a SUM loss plus `aux` — either the
    valid-token count directly, or a dict containing "num_label_tokens" and
    any extra per-step arrays (e.g. MoE tokens_per_expert), which are summed
    across microbatches and surfaced in metrics. Normalization by total
    tokens happens here, once.

    `grad_fn` replaces value_and_grad(loss_fn) for programs that compute
    gradients explicitly (the 1F1B pipeline interleaves its own forward and
    backward — decoder.make_pp_1f1b_loss_and_grad); everything downstream
    (accumulation, normalization, clipping, update) is identical.

    `param_transform` (QAT fake-quant) composes with BOTH gradient paths:
    inside the differentiated function for the autodiff path, and — for an
    explicit `grad_fn` — by vjp of the transform around the pipeline's
    grads (d(master) = dtransform^T · d(quantized)), exactly the LoRA
    merge-vjp composition of the PEFT×PP path. The straight-through
    estimator means the transform's vjp is (masked) identity, so the
    pipeline never knows it ran on fake-quantized weights.
    """
    config = config or TrainStepConfig()

    def grad_one(params, step, mb, rng, *extra):
        if grad_fn is not None:
            if param_transform is None:
                grads, ce, aux = grad_fn(params, mb, rng, *extra)
            else:
                qp, q_vjp = jax.vjp(
                    lambda p: param_transform(p, step), params
                )
                grads, ce, aux = grad_fn(qp, mb, rng, *extra)
                grads = jax.tree.map(
                    lambda g, p: g.astype(p.dtype), grads, qp
                )
                (grads,) = q_vjp(grads)
            if not isinstance(aux, dict):
                aux = {"num_label_tokens": aux}
            return grads, ce, aux

        # QAT fake-quant runs INSIDE the differentiated function so the
        # straight-through estimator routes gradients to the master weights
        def fwd(p):
            if param_transform is not None:
                p = param_transform(p, step)
            return loss_fn(p, mb, rng, *extra)

        (ce, aux), grads = jax.value_and_grad(fwd, has_aux=True)(params)
        if not isinstance(aux, dict):
            aux = {"num_label_tokens": aux}
        return grads, ce, aux

    def train_step(state: TrainState, batch, rng, *extra):
        accum = jax.tree.leaves(batch)[0].shape[0]

        def micro(carry, xs):
            idx, mb = xs
            g_acc, ce_acc, aux_acc = carry
            g, ce, aux = grad_one(
                state.params, state.step, mb, jax.random.fold_in(rng, idx), *extra
            )
            return (
                jax.tree.map(jnp.add, g_acc, g),
                ce_acc + ce,
                jax.tree.map(jnp.add, aux_acc, aux),
            ), None

        zero_grads = jax.tree.map(jnp.zeros_like, state.params)
        # shape-only probe for the aux accumulator structure (no compute)
        _, _, aux_shapes = jax.eval_shape(
            grad_one, state.params, state.step,
            jax.tree.map(lambda x: x[0], batch), rng, *extra,
        )
        aux0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), aux_shapes)
        (grads, ce_sum, aux_sum), _ = jax.lax.scan(
            micro,
            (zero_grads, jnp.float32(0.0), aux0),
            (jnp.arange(accum), batch),
        )
        n_tokens = aux_sum["num_label_tokens"]

        # normalize by the global number of label tokens
        denom = jnp.maximum(n_tokens, 1.0)
        grads = jax.tree.map(lambda g: (g / denom).astype(jnp.float32), grads)

        grad_norm = optax.global_norm(grads)
        if config.max_grad_norm is not None:
            scale = jnp.minimum(1.0, config.max_grad_norm / (grad_norm + 1e-6))
            grads = jax.tree.map(lambda g: g * scale, grads)

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        ok = jnp.logical_and(jnp.isfinite(grad_norm), jnp.isfinite(ce_sum))
        if config.skip_nonfinite_updates:
            params = jax.tree.map(
                lambda new, old: jnp.where(ok, new, old), params, state.params
            )
            opt_state = jax.tree.map(
                lambda new, old: jnp.where(ok, new, old) if hasattr(new, "shape") else new,
                opt_state, state.opt_state,
            )
        new_state = TrainState(step=state.step + 1, params=params, opt_state=opt_state)

        metrics = {
            "loss": ce_sum / denom,
            "grad_norm": grad_norm,
            **aux_sum,
        }
        if config.skip_nonfinite_updates:
            metrics["skipped_nonfinite"] = 1.0 - ok.astype(jnp.float32)
        if lr_schedule is not None:
            metrics["lr"] = lr_schedule(state.step)
        return new_state, metrics

    return train_step


def jit_train_step(train_step: Callable) -> Callable:
    """Jit with state donation; shardings propagate from the input arrays."""
    return jax.jit(train_step, donate_argnums=0)
