from automodel_tpu.training.train_step import (
    TrainState,
    TrainStepConfig,
    init_train_state,
    jit_train_step,
    make_train_step,
)

__all__ = [
    "TrainState",
    "TrainStepConfig",
    "init_train_state",
    "jit_train_step",
    "make_train_step",
]
