"""Step scheduler: grad-accum batching, epochs, checkpoint/val cadence.

The analog of the reference `StepScheduler`
(reference: nemo_automodel/components/training/step_scheduler.py:56,349):
iterates the dataloader in groups of `grad_acc_steps` microbatches, tracks
epoch/step, decides checkpoint/validation cadence, carries a SIGTERM flag
for checkpoint-and-exit, and is checkpointable (state_dict/load_state_dict).
"""

from __future__ import annotations

import dataclasses
import signal
import time
from typing import Any, Iterator, Optional


@dataclasses.dataclass
class StepSchedulerConfig:
    grad_acc_steps: int = 1
    ckpt_every_steps: int = 1000
    val_every_steps: Optional[int] = None
    num_epochs: int = 1
    max_steps: Optional[int] = None

    def build(self, dataloader) -> "StepScheduler":
        return StepScheduler(self, dataloader)


class StepScheduler:
    def __init__(self, config: StepSchedulerConfig, dataloader):
        self.config = config
        self.dataloader = dataloader
        self.step = 0
        self.epoch = 0
        self.sigterm_received = False
        self.sigterm_time: Optional[float] = None  # time.monotonic() at signal

    # -- iteration ---------------------------------------------------------
    def __iter__(self) -> Iterator[list]:
        """Yields lists of `grad_acc_steps` microbatches; increments step."""
        for epoch in range(self.epoch, self.config.num_epochs):
            self.epoch = epoch
            if hasattr(self.dataloader, "set_epoch"):
                self.dataloader.set_epoch(epoch)
            group: list = []
            for batch in self.dataloader:
                group.append(batch)
                if len(group) == self.config.grad_acc_steps:
                    self.step += 1
                    yield group
                    group = []
                    if self.done or self.sigterm_received:
                        return
            # drop ragged tail (matches reference semantics)

    @property
    def done(self) -> bool:
        return self.config.max_steps is not None and self.step >= self.config.max_steps

    # -- cadence -------------------------------------------------------------
    @property
    def is_ckpt_step(self) -> bool:
        return self.step > 0 and self.step % self.config.ckpt_every_steps == 0

    @property
    def is_val_step(self) -> bool:
        return (
            self.config.val_every_steps is not None
            and self.step > 0
            and self.step % self.config.val_every_steps == 0
        )

    # -- SIGTERM → checkpoint-and-exit (reference: signal_handler.py:94) ----
    def install_sigterm_handler(self) -> None:
        def handler(signum, frame):
            self.sigterm_received = True
            # stamp the ARRIVAL: the emergency-checkpoint grace deadline
            # counts from when the orchestrator sent the signal (k8s/SLURM
            # semantics), not from when the current step finished
            if self.sigterm_time is None:
                self.sigterm_time = time.monotonic()

        signal.signal(signal.SIGTERM, handler)

    def grace_remaining(self, grace_s: float) -> float:
        """Seconds left of a `grace_s` window that opened at the SIGTERM."""
        if self.sigterm_time is None:
            return grace_s
        return max(0.0, grace_s - (time.monotonic() - self.sigterm_time))

    # -- checkpointable state ------------------------------------------------
    def state_dict(self) -> dict:
        return {"step": self.step, "epoch": self.epoch}

    def load_state_dict(self, state: dict) -> None:
        self.step = int(state["step"])
        self.epoch = int(state["epoch"])
