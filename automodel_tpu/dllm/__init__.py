from automodel_tpu.dllm.mdlm import (  # noqa: F401
    corrupt_blockwise,
    corrupt_uniform,
    mdlm_loss_from_hidden,
)
