"""Block-diffusion training mask (the diffusion_gemma / BD3LM geometry).

The analog of the reference's highest-correctness-risk dLLM piece
(reference: nemo_automodel/components/models/diffusion_gemma/
attention_mask.py `build_block_diffusion_training_mask`): the model runs a
shared stack twice — a causal "encoder" pass over the CLEAN sequence and a
bidirectional "canvas" pass over the NOISED response — and each canvas
layer attends over `[encoder_KV ; canvas_KV]`. For training, all response
blocks are supervised jointly, and the mask splits column-wise:

* encoder columns → M_OBC (offset-block-causal): a canvas query in block i
  sees a clean response column only if that column's block is STRICTLY
  before i; prompt columns are always visible.
* canvas columns → M_BD (block-diagonal): bidirectional within the query's
  own block only.

THE leakage invariant: M_OBC uses strict `block_q > block_kv`. With `>=`
the canvas sees the clean answer for exactly the tokens it is being
trained to denoise and the loss collapses (reference docstring; pinned by
tests/unit/test_block_diffusion.py).

The sliding variant anchors the encoder window to the BLOCK boundary (the
inference-time cache end `prefix + i·block_size`), not the query position —
a per-query band would starve late-in-block queries of previous-block
context the inference geometry provides (train/inference parity).
"""

from __future__ import annotations

import jax.numpy as jnp


def block_ids(num_positions: int, block_size: int) -> jnp.ndarray:
    return jnp.arange(num_positions) // block_size


def build_block_diffusion_training_mask(
    prefix_lengths,               # int | (B,) int array — prompt lengths
    response_length: int,
    enc_len: int,
    block_size: int,
    *,
    sliding_window: int | None = None,
    batch_size: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (mask_full, mask_sliding): bool keep-masks of shape
    (B, response_length, enc_len + response_length); True = attend.
    mask_sliding additionally applies the block-anchored encoder window for
    sliding-attention layers (equal to mask_full when sliding_window is
    None)."""
    if isinstance(prefix_lengths, int):
        if batch_size is None:
            raise ValueError("batch_size required when prefix_lengths is an int")
        prefix = jnp.full((batch_size,), prefix_lengths, jnp.int32)
    else:
        prefix = jnp.asarray(prefix_lengths, jnp.int32)
        if prefix.ndim != 1:
            raise ValueError(f"prefix_lengths must be 1-D, got {prefix.shape}")
        batch_size = prefix.shape[0]

    canvas_len = response_length
    q_block = block_ids(canvas_len, block_size)              # (Lq,)

    # -- encoder columns: M_OBC --------------------------------------------
    enc_pos = jnp.arange(enc_len)
    enc_rel = enc_pos[None, :] - prefix[:, None]             # (B, enc_len)
    enc_block = jnp.where(enc_rel >= 0, enc_rel // block_size, -1)
    enc_is_valid = enc_rel < response_length                 # pad tail never attends
    # strict >: the leakage invariant
    m_obc = (q_block[None, :, None] > enc_block[:, None, :]) & enc_is_valid[:, None, :]

    # -- canvas columns: M_BD ----------------------------------------------
    kv_block = block_ids(canvas_len, block_size)
    m_bd = jnp.broadcast_to(
        q_block[:, None] == kv_block[None, :], (batch_size, canvas_len, canvas_len)
    )

    keep = jnp.concatenate([m_obc, m_bd], axis=2)            # (B, Lq, key_len)

    if sliding_window is None:
        return keep, keep

    # block-anchored encoder window: keep the last `sliding_window` cache
    # columns ending at the block's inference-time cache boundary
    block_start = q_block * block_size                       # (Lq,)
    valid_cache = prefix[:, None] + block_start[None, :]     # (B, Lq)
    enc_within = enc_pos[None, None, :] >= (
        valid_cache[:, :, None] - sliding_window + 1
    )                                                        # (B, Lq, enc_len)
    # canvas columns are never windowed (M_BD already confines them); only
    # the encoder half needs the AND
    return keep, jnp.concatenate([m_obc & enc_within, m_bd], axis=2)
