"""Masked-diffusion LM (MDLM / LLaDA-style) training primitives.

The analog of the reference dLLM stack (reference: nemo_automodel/recipes/
dllm/train_ft.py `DiffusionLMSFTRecipe`, strategy.py `MDLMStrategy`,
components/datasets/dllm/corruption.py:73 `corrupt_uniform`,
components/loss/dllm_loss.py:105 `MDLMCrossEntropyLoss`), TPU-native:

- Corruption runs INSIDE the jitted train step from the step's folded PRNG
  key, so the noise realization is a pure function of (step, microbatch) —
  the resume-determinism the reference retrofits with hand-seeded torch
  Generators (train_ft.py:223 comment) falls out of the design.
- The loss rides the chunked fused lm-head CE (no (B·S, V) logits) with the
  absorbing-kernel ELBO weight 1/p as a per-token weight.
- The model is the standard dense decoder with `causal=False` —
  bidirectional attention is a config flag, not a separate model family.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.loss.linear_ce import fused_linear_cross_entropy


def corrupt_uniform(
    rng: jax.Array,
    input_ids: jnp.ndarray,   # (B, L)
    loss_mask: jnp.ndarray,   # (B, L) bool — supervised positions
    mask_token_id: int,
    eps: float = 1e-3,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """LLaDA/MDLM absorbing corruption (reference: corruption.py:73).

    Per sequence, t ~ U[0,1]; p = (1-eps)·t + eps; each supervised token is
    independently replaced by [MASK] with probability p. Returns
    (noisy_ids, noise_mask, p_mask).
    """
    B, L = input_ids.shape
    kt, km = jax.random.split(rng)
    t = jax.random.uniform(kt, (B,))
    p_mask = jnp.broadcast_to(((1.0 - eps) * t + eps)[:, None], (B, L))
    noise = jax.random.uniform(km, (B, L)) < p_mask
    noise_mask = noise & loss_mask.astype(bool)
    noisy = jnp.where(noise_mask, mask_token_id, input_ids)
    return noisy, noise_mask, p_mask.astype(jnp.float32)


def corrupt_blockwise(
    rng: jax.Array,
    input_ids: jnp.ndarray,
    loss_mask: jnp.ndarray,
    mask_token_id: int,
    block_size: int,
    eps: float = 1e-3,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Blockwise variant: an independent t (hence p) per length-`block_size`
    block, so one sequence mixes clean and heavily-masked spans (reference:
    corruption.py `corrupt_blockwise`)."""
    B, L = input_ids.shape
    nb = (L + block_size - 1) // block_size
    kt, km = jax.random.split(rng)
    t = jax.random.uniform(kt, (B, nb))
    p_blocks = (1.0 - eps) * t + eps
    p_mask = jnp.repeat(p_blocks, block_size, axis=1)[:, :L]
    noise = jax.random.uniform(km, (B, L)) < p_mask
    noise_mask = noise & loss_mask.astype(bool)
    noisy = jnp.where(noise_mask, mask_token_id, input_ids)
    return noisy, noise_mask, p_mask.astype(jnp.float32)


def mdlm_loss_from_hidden(
    hidden: jnp.ndarray,          # (B, L, H) — model output on NOISY ids
    lm_head_kernel: jnp.ndarray,  # (H, V)
    clean_ids: jnp.ndarray,       # (B, L) uncorrupted targets
    noise_mask: jnp.ndarray,      # (B, L) bool
    p_mask: jnp.ndarray,          # (B, L)
    loss_mask: jnp.ndarray,       # (B, L) bool
    *,
    chunk_size: int = 1024,
    logits_soft_cap: float | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """MDLM ELBO (reference: dllm_loss.py:105): CE at masked∩supervised
    positions weighted 1/p, normalized by the TOTAL supervised (maskable)
    count. Returns (weighted_sum, num_supervised) for the standard
    sum/÷tokens train-step contract."""
    eff = noise_mask & loss_mask.astype(bool)
    labels = jnp.where(eff, clean_ids, -100)
    weights = 1.0 / jnp.maximum(p_mask, 1e-8)
    ce_sum, _ = fused_linear_cross_entropy(
        hidden, lm_head_kernel, labels,
        chunk_size=chunk_size, logits_soft_cap=logits_soft_cap,
        token_weights=weights,
    )
    n_supervised = jnp.sum(loss_mask.astype(jnp.float32))
    return ce_sum, n_supervised
