"""MDLM iterative-unmasking generation (LLaDA-style).

The inference counterpart of the masked-diffusion trainer (reference:
recipes/dllm/ — the reference trains dLLMs and defers serving to external
engines; this minimal sampler makes the trained checkpoint usable
standalone): start from an all-[MASK] canvas after the prompt, and over
`steps` rounds fill in the highest-confidence predictions, re-denoising the
rest — low-confidence counts stay masked for later rounds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def generate_mdlm(
    forward_logits,            # (ids (B,L)) -> logits (B,L,V)
    prompt_ids: jnp.ndarray,   # (B, P)
    gen_len: int,
    mask_token_id: int,
    *,
    steps: int = 8,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
) -> jnp.ndarray:
    """Returns (B, P + gen_len) ids with the canvas filled in."""
    B, P = prompt_ids.shape
    canvas = jnp.concatenate(
        [prompt_ids, jnp.full((B, gen_len), mask_token_id, prompt_ids.dtype)], axis=1
    )
    per_round = max(1, gen_len // steps)
    rng = rng if rng is not None else jax.random.key(0)

    for s in range(steps):
        logits = forward_logits(canvas)
        # the mask token is never a legal output — keep it out of the argmax
        # and the sampler so every committed slot is a real token
        logits = logits.at[..., mask_token_id].set(-jnp.inf)
        if temperature > 0.0:
            rng, k = jax.random.split(rng)
            pred = jax.random.categorical(k, logits / temperature, axis=-1)
        else:
            pred = jnp.argmax(logits, axis=-1)
        pred = pred.astype(canvas.dtype)
        # confidence of the token actually committed, not the argmax
        logp = jax.nn.log_softmax(logits, axis=-1)
        conf = jnp.take_along_axis(logp, pred[..., None], axis=-1)[..., 0]

        masked = canvas == mask_token_id
        # unmask the per_round most confident masked slots (all, final round)
        conf_m = jnp.where(masked, conf, -jnp.inf)
        n_left = steps - s
        k_now = gen_len if n_left == 1 else per_round
        thresh = jax.lax.top_k(conf_m, min(k_now, conf_m.shape[1]))[0][:, -1:]
        take = masked & (conf_m >= thresh)
        canvas = jnp.where(take, pred, canvas)
    return canvas
