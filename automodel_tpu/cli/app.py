"""CLI entry: ``automodel_tpu <cfg.yaml> [--key.path=value ...]``.

The analog of the reference CLI (reference: nemo_automodel/cli/app.py:95
`main`, cli/utils.py resolve_recipe_name). The recipe class resolves from,
in priority order: the ``recipe._target_`` field, a bare ``recipe:`` name
from RECIPE_ALIASES, or the default next-token-prediction trainer.

The launcher story differs from torchrun by design: a TPU pod runs ONE
process per host, each executing this same command; multi-host rendezvous
is `jax.distributed.initialize` inside the recipe (distributed/init_utils),
driven by env (GKE/XPK set it up). There is no process-spawning launcher to
re-exec through.
"""

from __future__ import annotations

import sys

from automodel_tpu.config import ConfigNode, parse_args_and_load_config
from automodel_tpu.config.loader import _resolve_target

RECIPE_ALIASES = {
    "llm_train_ft": "automodel_tpu.recipes.llm.train_ft.TrainFinetuneRecipeForNextTokenPrediction",
    "llm_finetune": "automodel_tpu.recipes.llm.train_ft.TrainFinetuneRecipeForNextTokenPrediction",
    "llm_pretrain": "automodel_tpu.recipes.llm.train_ft.TrainFinetuneRecipeForNextTokenPrediction",
    "llm_benchmark": "automodel_tpu.recipes.llm.benchmark.BenchmarkRecipe",
    "llm_kd": "automodel_tpu.recipes.llm.kd.KDRecipeForNextTokenPrediction",
    "vlm_finetune": "automodel_tpu.recipes.vlm.finetune.FinetuneRecipeForVLM",
    "llm_seq_cls": "automodel_tpu.recipes.llm.train_seq_cls.TrainSeqClsRecipe",
    "retrieval_bi_encoder": "automodel_tpu.recipes.retrieval.train_bi_encoder.TrainBiEncoderRecipe",
}


def resolve_recipe_class(cfg: ConfigNode):
    node = cfg.get("recipe")
    if node is None:
        path = RECIPE_ALIASES["llm_train_ft"]
    elif isinstance(node, str):
        path = RECIPE_ALIASES.get(node, node)
    elif "_target_" in node:
        path = node.get("_target_")
    else:
        path = RECIPE_ALIASES["llm_train_ft"]
    return _resolve_target(path)


def main(argv=None) -> None:
    cfg = parse_args_and_load_config(argv)
    recipe_cls = resolve_recipe_class(cfg)
    recipe = recipe_cls(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()


if __name__ == "__main__":
    main()
