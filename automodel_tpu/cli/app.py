"""CLI entry: ``automodel_tpu <cfg.yaml> [--key.path=value ...]``.

The analog of the reference CLI (reference: nemo_automodel/cli/app.py:95
`main`, cli/utils.py resolve_recipe_name). The recipe class resolves from,
in priority order: the ``recipe._target_`` field, a bare ``recipe:`` name
from RECIPE_ALIASES, or the default next-token-prediction trainer.

The launcher story differs from torchrun by design: a TPU pod runs ONE
process per host, each executing this same command; multi-host rendezvous
is `jax.distributed.initialize` inside the recipe (distributed/init_utils),
driven by env (GKE/XPK set it up). There is no process-spawning launcher to
re-exec through.
"""

from __future__ import annotations

import sys

from automodel_tpu.config import ConfigNode, parse_args_and_load_config
from automodel_tpu.config.loader import _resolve_target

RECIPE_ALIASES = {
    "llm_train_ft": "automodel_tpu.recipes.llm.train_ft.TrainFinetuneRecipeForNextTokenPrediction",
    "llm_finetune": "automodel_tpu.recipes.llm.train_ft.TrainFinetuneRecipeForNextTokenPrediction",
    "llm_pretrain": "automodel_tpu.recipes.llm.train_ft.TrainFinetuneRecipeForNextTokenPrediction",
    "llm_benchmark": "automodel_tpu.recipes.llm.benchmark.BenchmarkRecipe",
    "llm_kd": "automodel_tpu.recipes.llm.kd.KDRecipeForNextTokenPrediction",
    "llm_train_eagle3": "automodel_tpu.recipes.llm.train_eagle3.TrainEagle3Recipe",
    "llm_train_eagle1": "automodel_tpu.recipes.llm.train_eagle1.TrainEagle1Recipe",
    "llm_train_eagle2": "automodel_tpu.recipes.llm.train_eagle1.TrainEagle2Recipe",
    "llm_train_dflash": "automodel_tpu.recipes.llm.train_dflash.TrainDFlashRecipe",
    "llm_serve": "automodel_tpu.recipes.llm.serve.ServeRecipe",
    "llm_spec_bench": "automodel_tpu.recipes.llm.spec_bench.SpecAcceptanceBenchRecipe",
    "llm_dflash_decode_eval": "automodel_tpu.recipes.llm.spec_bench.DFlashDecodeEvalRecipe",
    "dllm_train_ft": "automodel_tpu.recipes.dllm.train_ft.DiffusionLMSFTRecipe",
    "diffusion_train": "automodel_tpu.recipes.diffusion.train.TrainDiffusionRecipe",
    "bagel_finetune": "automodel_tpu.recipes.multimodal.bagel.BagelRecipe",
    "multimodal_pretrain": "automodel_tpu.recipes.multimodal.pretrain.PretrainRecipeForMultimodal",
    "vlm_finetune": "automodel_tpu.recipes.vlm.finetune.FinetuneRecipeForVLM",
    "vlm_kd": "automodel_tpu.recipes.vlm.kd.KDRecipeForVLM",
    "vlm_generate": "automodel_tpu.recipes.vlm.generate.GenerateRecipeForVLM",
    "multimodal_finetune": "automodel_tpu.recipes.multimodal.finetune.FinetuneRecipeForOmni",
    "llm_seq_cls": "automodel_tpu.recipes.llm.train_seq_cls.TrainSeqClsRecipe",
    "retrieval_bi_encoder": "automodel_tpu.recipes.retrieval.train_bi_encoder.TrainBiEncoderRecipe",
    "retrieval_cross_encoder": "automodel_tpu.recipes.retrieval.train_cross_encoder.TrainCrossEncoderRecipe",
    "retrieval_distill_bi_encoder": "automodel_tpu.recipes.retrieval.distill_bi_encoder.DistillBiEncoderRecipe",
    "retrieval_mine_hard_negatives": "automodel_tpu.recipes.retrieval.mine_hard_negatives.MineHardNegativesRecipe",
}


def resolve_recipe_class(cfg: ConfigNode):
    node = cfg.get("recipe")
    if node is None:
        path = RECIPE_ALIASES["llm_train_ft"]
    elif isinstance(node, str):
        path = RECIPE_ALIASES.get(node, node)
    elif "_target_" in node:
        path = node.get("_target_")
    else:
        path = RECIPE_ALIASES["llm_train_ft"]
    return _resolve_target(path)


def print_capabilities() -> None:
    """`python -m automodel_tpu --capabilities` — the analog of the
    reference's capability query (reference: cli/query_capabilities.py).

    Runs on the host CPU platform: a metadata query must answer even when
    the accelerator tunnel is down (touching a dead backend hangs)."""
    import json

    from automodel_tpu.utils.hostplatform import force_cpu_devices

    try:
        force_cpu_devices(1)
    except RuntimeError:
        pass  # a backend is already live in this process — query that one

    import jax

    from automodel_tpu import __version__
    from automodel_tpu.models.registry import MODEL_ARCH_MAPPING

    caps = {
        "version": __version__,
        "backend": "cpu (forced for query)",
        "devices": len(jax.devices()),
        "architectures": sorted(MODEL_ARCH_MAPPING),
        "recipes": sorted(RECIPE_ALIASES),
        "parallelism": [
            "dp_replicate", "dp_shard(fsdp)", "tp",
            "cp(ring load-balanced | blockdiag per-document)",
            "ep(dropless ragged-a2a)", "pp(gpipe|1f1b|interleaved|zb)",
        ],
        "features": [
            "lora_peft", "knowledge_distillation", "mtp", "fp8_int8_matmul",
            "dropless_moe", "attention_sinks", "kv_cache_generation",
            "mla_latent_cache_decode", "vlm_generation", "chunked_sparse_dsa",
            "speculative_eagle1_eagle3", "speculative_dflash_jetspec",
            "dflash_decode_eval", "acceptance_length_bench",
            "sampling_eval", "agent_tool_call_sft", "neat_packing",
            "orbax_checkpointing", "hf_safetensors_io", "golden_value_ci",
            "profiler_traces", "wandb_mlflow_trackers",
            "bagel_unified_multimodal", "flow_matching_adapters",
        ],
    }
    print(json.dumps(caps, indent=2))


def main(argv=None) -> None:
    import sys as _sys

    args = list(_sys.argv[1:] if argv is None else argv)
    if args and args[0] in ("--capabilities", "capabilities"):
        print_capabilities()
        return
    if args and args[0] == "launch":
        # `automodel_tpu launch <cfg.yaml> [--launcher.k=v] [--any.other=v]`
        # — generate (and optionally submit) a SLURM/GKE multi-host job
        # spec. Non-launcher overrides are forwarded into the job's train
        # command so the cluster run matches what was asked for.
        from automodel_tpu.launcher import launch_main

        largs = args[1:]
        cfg = parse_args_and_load_config(largs)
        import shlex

        train_overrides = " ".join(
            shlex.quote(a) for a in largs[1:]
            if not a.startswith("--launcher.") and not a.startswith("--platform.")
        )
        launch_main(largs[0], cfg.get("launcher"), train_overrides=train_overrides)
        return
    cfg = parse_args_and_load_config(args)
    # `platform: {force_cpu_devices: N}` — run the recipe on an N-device
    # virtual CPU mesh (dev boxes / CI without accelerators). Must happen
    # before the recipe's first JAX backend touch.
    n_cpu = cfg.get("platform.force_cpu_devices", None)
    if n_cpu:
        from automodel_tpu.utils.hostplatform import force_cpu_devices

        force_cpu_devices(int(n_cpu))
    recipe_cls = resolve_recipe_class(cfg)
    recipe = recipe_cls(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()


if __name__ == "__main__":
    main()
