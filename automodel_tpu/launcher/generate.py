"""Executable multi-host launchers: SLURM sbatch + GKE JobSet generators.

The analog of the reference launcher stack (reference: slurm.sub,
nemo_automodel/components/launcher/interactive.py:70 torchrun re-exec,
launcher/nemo_run + launcher/skypilot submission), TPU-native:

- There is no process-spawning launcher to re-exec through: a TPU pod runs
  ONE process per host, every host executes the same
  `python -m automodel_tpu <cfg>` command, and `jax.distributed.initialize`
  performs the rendezvous from environment variables.
- SLURM: `srun --ntasks-per-node=1` with the coordinator at node 0
  (JAX_COORDINATOR_ADDRESS from `scontrol show hostnames`), SIGUSR1
  forwarded for checkpoint-then-exit (the recipe's SIGTERM path).
- GKE: a JobSet-style manifest with `google.com/tpu` resources and TPU
  topology selectors; the TPU webhook injects the rendezvous env
  (TPU_WORKER_HOSTNAMES et al., which distributed/init_utils autodetects).

`automodel_tpu launch <cfg.yaml>` writes the manifest; `--launcher.submit=true`
also invokes sbatch/kubectl when present.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import subprocess
from typing import Optional


@dataclasses.dataclass
class LauncherConfig:
    backend: str = "slurm"             # "slurm" | "gke"
    nodes: int = 1
    job_name: str = "automodel-tpu"
    output_dir: str = "launch_jobs"
    submit: bool = False
    extra_args: str = ""               # appended to the training command
    # slurm
    account: Optional[str] = None
    partition: Optional[str] = None
    time_limit: str = "01:00:00"
    container_image: Optional[str] = None
    # gke
    namespace: str = "default"
    tpu_type: str = "tpu-v5-lite-podslice"   # node selector accelerator
    tpu_topology: str = "2x4"
    tpu_chips_per_host: int = 4
    image: str = "python:3.12"
    workdir: str = "/workspace"
    # bounded pod-recreation budget: TPU spot/preemptible nodes get
    # reclaimed routinely, and `backoffLimit: 0` turned every preemption
    # into a dead job even though the recipe auto-resumes from its
    # emergency checkpoint; a small bounded budget restarts those while a
    # crash-looping job still fails fast
    backoff_limit: int = 3

    def __post_init__(self):
        if self.backend not in ("slurm", "gke"):
            raise ValueError(f"launcher.backend must be slurm|gke, got {self.backend}")
        if self.nodes < 1:
            raise ValueError(f"launcher.nodes must be >= 1, got {self.nodes}")
        if self.backoff_limit < 0:
            raise ValueError(
                f"launcher.backoff_limit must be >= 0, got {self.backoff_limit}"
            )


def _train_command(config_path: str, extra: str) -> str:
    cmd = f"python -m automodel_tpu {shlex.quote(config_path)}"
    return f"{cmd} {extra}".strip()


def render_slurm_script(cfg: LauncherConfig, config_path: str) -> str:
    """One srun task per node; node 0 is the JAX coordinator."""
    directives = [
        f"#SBATCH -J {cfg.job_name}",
        f"#SBATCH -N {cfg.nodes}",
        "#SBATCH --ntasks-per-node=1",
        f"#SBATCH -t {cfg.time_limit}",
        f"#SBATCH --output={cfg.output_dir}/%x_%j.out",
        f"#SBATCH --error={cfg.output_dir}/%x_%j.err",
        "#SBATCH --signal=B:USR1@300",  # checkpoint-then-exit grace window
    ]
    if cfg.account:
        directives.append(f"#SBATCH -A {cfg.account}")
    if cfg.partition:
        directives.append(f"#SBATCH -p {cfg.partition}")

    srun = "srun --ntasks-per-node=1 --kill-on-bad-exit=1"
    if cfg.container_image:
        srun += f" --container-image={cfg.container_image}"

    return "\n".join([
        "#!/bin/bash",
        *directives,
        "",
        "# JAX multi-host rendezvous: coordinator = first allocated node.",
        "# Per-task rank comes from SLURM_PROCID, which the recipe's",
        "# distributed/init_utils reads directly — no wrapper shell needed.",
        'HOSTS=$(scontrol show hostnames "$SLURM_JOB_NODELIST")',
        "export JAX_COORDINATOR_ADDRESS=$(echo \"$HOSTS\" | head -n1):8476",
        "export JAX_NUM_PROCESSES=$SLURM_JOB_NUM_NODES",
        "",
        "# forward SIGUSR1 so the recipe checkpoints before the wall clock",
        "trap 'kill -TERM $SRUN_PID 2>/dev/null' USR1",
        "",
        f"{srun} {_train_command(config_path, cfg.extra_args)} &",
        "SRUN_PID=$!",
        "# first wait returns when USR1 interrupts it; wait again so the",
        "# batch script stays alive while the recipe checkpoints and exits",
        "wait $SRUN_PID",
        "wait $SRUN_PID",
        "",
    ])


def render_gke_jobset(cfg: LauncherConfig, config_path: str) -> str:
    """JobSet-style manifest (XPK pattern): completions==parallelism==hosts,
    Indexed completion (required for multi-host TPU webhook identity), TPU
    topology via node selectors; the webhook injects the rendezvous env
    that distributed/init_utils autodetects. Built as a dict and dumped —
    command strings are YAML-escaped by construction."""
    import yaml

    cmd = _train_command(config_path, cfg.extra_args)
    doc = {
        "apiVersion": "jobset.x-k8s.io/v1alpha2",
        "kind": "JobSet",
        "metadata": {"name": cfg.job_name, "namespace": cfg.namespace},
        "spec": {"replicatedJobs": [{
            "name": "workers",
            "replicas": 1,
            "template": {"spec": {
                "parallelism": cfg.nodes,
                "completions": cfg.nodes,
                "completionMode": "Indexed",
                "backoffLimit": cfg.backoff_limit,
                "template": {"spec": {
                    "restartPolicy": "Never",
                    "nodeSelector": {
                        "cloud.google.com/gke-tpu-accelerator": cfg.tpu_type,
                        "cloud.google.com/gke-tpu-topology": cfg.tpu_topology,
                    },
                    "containers": [{
                        "name": "automodel",
                        "image": cfg.image,
                        "workingDir": cfg.workdir,
                        "command": ["bash", "-c"],
                        "args": [cmd],
                        "resources": {
                            "requests": {"google.com/tpu": cfg.tpu_chips_per_host},
                            "limits": {"google.com/tpu": cfg.tpu_chips_per_host},
                        },
                    }],
                }},
            }},
        }]},
    }
    return yaml.safe_dump(doc, sort_keys=False)


def launch_main(
    config_path: str,
    launcher_node,
    submit_override: bool | None = None,
    train_overrides: str = "",
) -> str:
    """Generate (and optionally submit) the job spec. Returns the spec path.
    `train_overrides` (CLI dotted overrides) join the rendered command."""
    def coerce(field, v):
        t = type(field.default)
        if field.default is None or v is None:
            return v
        if t is bool:  # env interpolation yields strings; bool("false") lies
            if isinstance(v, str):
                return v.strip().lower() in ("1", "true", "yes", "on")
            return bool(v)
        return t(v)

    kwargs = {}
    if launcher_node is not None:
        for f in dataclasses.fields(LauncherConfig):
            if f.name in launcher_node:
                kwargs[f.name] = coerce(f, launcher_node.get(f.name))
    cfg = LauncherConfig(**kwargs)
    if submit_override is not None:
        cfg.submit = submit_override
    if train_overrides:
        cfg.extra_args = f"{cfg.extra_args} {train_overrides}".strip()

    os.makedirs(cfg.output_dir, exist_ok=True)
    if cfg.backend == "slurm":
        spec = render_slurm_script(cfg, config_path)
        path = os.path.join(cfg.output_dir, f"{cfg.job_name}.sub")
        submit_cmd = ["sbatch", path]
    else:
        spec = render_gke_jobset(cfg, config_path)
        path = os.path.join(cfg.output_dir, f"{cfg.job_name}.yaml")
        submit_cmd = ["kubectl", "apply", "-f", path]

    with open(path, "w") as f:
        f.write(spec)
    print(f"wrote {cfg.backend} job spec: {path}")

    if cfg.submit:
        try:
            out = subprocess.run(submit_cmd, capture_output=True, text=True, timeout=60)
            print(out.stdout.strip() or out.stderr.strip())
            if out.returncode != 0:
                raise RuntimeError(f"submission failed: {out.stderr.strip()[:500]}")
        except FileNotFoundError:
            raise RuntimeError(
                f"`{submit_cmd[0]}` not found on this host — spec written to "
                f"{path}; submit it from a cluster login node"
            ) from None
    return path
