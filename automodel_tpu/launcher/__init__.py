from automodel_tpu.launcher.generate import (  # noqa: F401
    LauncherConfig,
    render_gke_jobset,
    render_slurm_script,
    launch_main,
)
