"""LoRA — low-rank adapters as a functional parameter transform.

The analog of the reference PEFT stack (reference: nemo_automodel/
components/_peft/lora.py:44 `PeftConfig`, :88 `LinearLoRA`,
module_matcher.py pattern DSL). TPU-native design: instead of wrapping
nn.Modules, LoRA is a PYTREE TRANSFORM —

    effective_params = merge_lora(base_params, lora_params, cfg)

run inside the jitted loss so XLA fuses the (alpha/r)·A@B update into the
parameter cast; gradients flow only into the (tiny) lora tree, the base
tree is frozen by construction (it is not part of the optimizer state at
all — stronger than requires_grad=False). Works unchanged for any model
because matching is by parameter path, mirroring the reference's
module-matcher wildcards.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Sequence

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    """(reference: _peft/lora.py:44 PeftConfig, quantization/qlora.py,
    DoRA per arXiv:2402.09353)."""

    r: int = 16
    alpha: float = 32.0
    target_modules: tuple = ("q_proj", "k_proj", "v_proj", "o_proj")
    # regex alternative to target_modules (module-matcher DSL analog)
    match_pattern: str | None = None
    dtype: Any = jnp.float32
    # DoRA: decompose W' = m · (W + ΔW)/‖W + ΔW‖_col with trainable
    # per-output magnitudes m (init ‖W‖_col)
    dora: bool = False
    # QLoRA: store the frozen base weights int8 (absmax per output channel)
    # and dequantize inside the jitted merge — base memory ÷4 vs fp32
    quantize_base: str | None = None  # None | "int8"

    @property
    def scale(self) -> float:
        return self.alpha / self.r

    def __post_init__(self):
        if self.quantize_base not in (None, "int8"):
            raise ValueError(
                f"quantize_base must be None or 'int8', got {self.quantize_base}"
            )


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", k)) for k in path)


def _matches(cfg: LoRAConfig, path_s: str, leaf) -> bool:
    if getattr(leaf, "ndim", 0) < 2:
        return False
    if not path_s.endswith("kernel"):
        return False
    if cfg.match_pattern is not None:
        return re.search(cfg.match_pattern, path_s) is not None
    return any(t in path_s.split("/") for t in cfg.target_modules)


def init_lora(base_params: Any, cfg: LoRAConfig, rng: jax.Array) -> dict:
    """Build the adapter tree: for each matched kernel (..., in, out) create
    a: (..., in, r) gaussian and b: (..., r, out) zeros (so the merged model
    starts exactly at the base model)."""
    flat = jax.tree_util.tree_flatten_with_path(base_params)[0]
    lora: dict = {}
    for i, (path, leaf) in enumerate(flat):
        ps = _path_str(path)
        if not _matches(cfg, ps, leaf):
            continue
        *lead, fan_in, fan_out = leaf.shape
        ka = jax.random.fold_in(rng, i)
        a = (fan_in ** -0.5) * jax.random.normal(
            ka, (*lead, fan_in, cfg.r), cfg.dtype
        )
        b = jnp.zeros((*lead, cfg.r, fan_out), cfg.dtype)
        lora[ps] = {"a": a, "b": b}
        if cfg.dora:
            # trainable magnitude = the base kernel's per-output column norm
            lora[ps]["m"] = jnp.linalg.norm(
                leaf.astype(jnp.float32), axis=-2
            ).astype(cfg.dtype)
    if not lora:
        raise ValueError(
            f"LoRA matched no parameters (targets={cfg.target_modules}, "
            f"pattern={cfg.match_pattern})"
        )
    return lora


def lora_param_shardings(lora: dict, base_shardings: Any, mesh_ctx) -> dict:
    """Adapters shard like their base kernel on the non-rank dims; the rank
    dim is replicated (r is tiny)."""
    flat = {
        _path_str(p): s
        for p, s in jax.tree_util.tree_flatten_with_path(
            base_shardings, is_leaf=lambda x: hasattr(x, "spec")
        )[0]
    }
    out: dict = {}
    for ps, ab in lora.items():
        base = flat[ps].spec
        lead = list(base[:-2]) if len(base) >= 2 else []
        in_ax = base[-2] if len(base) >= 2 else None
        out_ax = base[-1] if len(base) >= 1 else None
        from jax.sharding import NamedSharding, PartitionSpec

        out[ps] = {
            "a": NamedSharding(mesh_ctx.mesh, PartitionSpec(*lead, in_ax, None)),
            "b": NamedSharding(mesh_ctx.mesh, PartitionSpec(*lead, None, out_ax)),
        }
        if "m" in ab:  # DoRA magnitude: (*lead, out)
            out[ps]["m"] = NamedSharding(
                mesh_ctx.mesh, PartitionSpec(*lead, out_ax)
            )
    return out


def quantize_base(base_params: Any, cfg: LoRAConfig) -> Any:
    """QLoRA base storage: every ndim≥2 kernel becomes {"q8", "sc"} — int8
    absmax-quantized per output channel (reference: quantization/qlora.py;
    nf4 replaced by the TPU-friendly int8 layout ops/quant.py uses)."""
    if cfg.quantize_base is None:
        return base_params

    def walk(path, leaf):
        if getattr(leaf, "ndim", 0) < 2 or not _path_str(path).endswith("kernel"):
            return leaf
        absmax = jnp.max(jnp.abs(leaf.astype(jnp.float32)), axis=-2, keepdims=True)
        sc = jnp.maximum(absmax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(leaf / sc), -127, 127).astype(jnp.int8)
        return {"q8": q, "sc": sc.astype(jnp.float32)}

    return jax.tree_util.tree_map_with_path(walk, base_params)


def _is_q8(x) -> bool:
    return isinstance(x, dict) and set(x) == {"q8", "sc"}


def merge_lora(base_params: Any, lora: dict, cfg: LoRAConfig) -> Any:
    """base + scale·A@B for every adapted kernel (einsum keeps stacked
    leading layer dims intact). Runs under jit — fused with the bf16 cast.
    int8-quantized base leaves dequantize in the same fusion; DoRA
    renormalizes columns and applies the trainable magnitude."""
    scale = cfg.scale

    def walk(path, leaf):
        ps = _path_str(path)
        if _is_q8(leaf):
            leaf = (leaf["q8"].astype(jnp.float32) * leaf["sc"]).astype(cfg.dtype)
        if ps not in lora:
            return leaf
        a, b = lora[ps]["a"], lora[ps]["b"]
        delta = jnp.einsum("...ir,...ro->...io", a, b) * scale
        merged = leaf + delta.astype(leaf.dtype)
        if cfg.dora:
            norm = jnp.linalg.norm(merged.astype(jnp.float32), axis=-2, keepdims=True)
            merged = (
                lora[ps]["m"][..., None, :] * merged / jnp.maximum(norm, 1e-8)
            ).astype(leaf.dtype)
        return merged

    return jax.tree_util.tree_map_with_path(
        walk, base_params, is_leaf=_is_q8
    )


def merged_state_dict(base_params: Any, lora: dict, cfg: LoRAConfig) -> Any:
    """Materialized merged weights (for consolidated HF export)."""
    return jax.device_get(merge_lora(base_params, lora, cfg))
