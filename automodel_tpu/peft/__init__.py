from automodel_tpu.peft.lora import (
    LoRAConfig,
    init_lora,
    lora_param_shardings,
    merge_lora,
    merged_state_dict,
)

__all__ = [
    "LoRAConfig",
    "init_lora",
    "lora_param_shardings",
    "merge_lora",
    "merged_state_dict",
]
