"""Multi-host bootstrap — the analog of `initialize_distributed`.

The reference initializes NCCL process groups from torchrun env vars
(reference: nemo_automodel/components/distributed/init_utils.py:1-176).
On TPU there are no process groups to manage: `jax.distributed.initialize`
joins the pod's coordination service (one process per host) and every XLA
collective rides ICI/DCN after that. Single-process runs skip it entirely.
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger(__name__)

_INITIALIZED = False


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host JAX runtime if the environment asks for it.

    Env detection mirrors the reference's rank/world discovery: we honor
    JAX's own vars plus the common launcher ones. No-op when single-host.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes or _int_env("JAX_NUM_PROCESSES")
    if process_id is None:
        process_id = _int_env("JAX_PROCESS_ID")
    # SLURM fallback ONLY for processes actually launched by srun (PROCID is
    # set per task); a bare python in an salloc shell must stay single-process
    if process_id is None and _int_env("SLURM_PROCID") is not None:
        process_id = _int_env("SLURM_PROCID")
        num_processes = num_processes or _int_env("SLURM_NTASKS")

    # single-slice multi-host pods advertise their peers via
    # TPU_WORKER_HOSTNAMES; >1 entry → argless autodetect rendezvous
    tpu_hosts = [
        h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    ]
    multihost_hinted = (
        coordinator_address is not None
        or (num_processes is not None and num_processes > 1)
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        or len(tpu_hosts) > 1
    )
    if multihost_hinted:
        if coordinator_address is None and num_processes is None:
            jax.distributed.initialize()  # TPU runtime autodetection
        else:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        logger.info(
            "jax.distributed initialized: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
    _INITIALIZED = True


def _int_env(name: str) -> int | None:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def get_world_size_safe() -> int:
    return jax.process_count()


def get_rank_safe() -> int:
    return jax.process_index()


def is_main_process() -> bool:
    return jax.process_index() == 0
