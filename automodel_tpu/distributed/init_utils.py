"""Multi-host bootstrap — the analog of `initialize_distributed`.

The reference initializes NCCL process groups from torchrun env vars
(reference: nemo_automodel/components/distributed/init_utils.py:1-176).
On TPU there are no process groups to manage: `jax.distributed.initialize`
joins the pod's coordination service (one process per host) and every XLA
collective rides ICI/DCN after that. Single-process runs skip it entirely.
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger(__name__)

_INITIALIZED = False


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host JAX runtime if the environment asks for it.

    Env detection mirrors the reference's rank/world discovery: we honor
    JAX's own vars plus the common launcher ones. No-op when single-host.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes or _int_env("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("JAX_PROCESS_ID")

    tpu_autodetect = os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    if coordinator_address or tpu_autodetect:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        logger.info(
            "jax.distributed initialized: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
    _INITIALIZED = True


def _int_env(name: str) -> int | None:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def get_world_size_safe() -> int:
    return jax.process_count()


def get_rank_safe() -> int:
    return jax.process_index()


def is_main_process() -> bool:
    return jax.process_index() == 0
