"""Device mesh context — the single source of truth for parallel topology.

TPU-native re-design of the reference mesh stack
(reference: nemo_automodel/components/distributed/mesh.py:42 `MeshAxisName`,
:66 `ParallelismSizes`, :82 `MeshContext`, mesh_utils.py:276
`_create_fsdp2_device_mesh`, :374 `_create_moe_mesh`). Where the reference
builds a torch DeviceMesh plus a separate 2-D MoE mesh, here there is ONE
`jax.sharding.Mesh` whose axes carry the reference's canonical vocabulary:

    (pp, dp_replicate, dp_shard, ep, cp, tp)     # outermost → innermost

- `pp`           pipeline stages (microbatched stage loop, see parallel/pp.py)
- `dp_replicate` HSDP replication groups (outermost → rides DCN multi-host)
- `dp_shard`     FSDP parameter/optimizer sharding (the fully_shard analog)
- `ep`           expert parallelism; also shards the batch outside MoE blocks
- `cp`           context/sequence parallelism (ring attention over ICI)
- `tp`           tensor parallelism (innermost → fastest ICI hops)

Flattened aliases mirror mesh_utils.py:311-325: `dp = (dp_replicate,
dp_shard)`, `dp_shard_cp = (dp_shard, cp)`, `dp_cp`, and the batch axis for
token sharding `batch = (dp_replicate, dp_shard, ep)` (the analog of the
reference carving the MoE mesh out of the same ranks, mesh_utils.py:374-415).
In GSPMD a flattened alias is just a tuple inside a PartitionSpec — no
separate mesh object is needed.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


class MeshAxisName:
    """Canonical axis names (reference: distributed/mesh.py:42-59)."""

    PP = "pp"
    DP_REPLICATE = "dp_replicate"
    DP_SHARD = "dp_shard"
    EP = "ep"
    CP = "cp"
    TP = "tp"

    ALL = (PP, DP_REPLICATE, DP_SHARD, EP, CP, TP)

    # Flattened aliases (reference: mesh_utils.py:311-325). Resolved inside
    # PartitionSpecs — order matters (outer axis first = major order).
    ALIASES = {
        "dp": (DP_REPLICATE, DP_SHARD),
        "dp_shard_cp": (DP_SHARD, CP),
        "dp_cp": (DP_REPLICATE, DP_SHARD, CP),
        "dp_shard_cp_ep": (DP_SHARD, CP, EP),
        "batch": (DP_REPLICATE, DP_SHARD, EP),
        "batch_cp": (DP_REPLICATE, DP_SHARD, EP, CP),
        "ep_shard": (DP_REPLICATE, DP_SHARD),  # FSDP axis for expert params
    }


@dataclasses.dataclass
class MeshConfig:
    """Parallelism sizes; -1 on dp_shard means "infer from device count".

    The analog of the reference's `ParallelismSizes` + `DistributedSetup`
    (distributed/mesh.py:66, distributed/config.py:96).
    """

    pp: int = 1
    dp_replicate: int = 1
    dp_shard: int = -1
    ep: int = 1
    cp: int = 1
    tp: int = 1

    def build(self, devices: Sequence[Any] | None = None) -> "MeshContext":
        devices = list(devices if devices is not None else jax.devices())
        n = len(devices)
        fixed = self.pp * self.dp_replicate * self.ep * self.cp * self.tp
        dp_shard = self.dp_shard
        if dp_shard == -1:
            if n % fixed != 0:
                raise ValueError(
                    f"{n} devices not divisible by pp*dp_replicate*ep*cp*tp={fixed}"
                )
            dp_shard = n // fixed
        if fixed * dp_shard != n:
            raise ValueError(
                f"Mesh sizes pp={self.pp} dp_replicate={self.dp_replicate} "
                f"dp_shard={dp_shard} ep={self.ep} cp={self.cp} tp={self.tp} "
                f"multiply to {fixed * dp_shard}, but there are {n} devices"
            )
        shape = (self.pp, self.dp_replicate, dp_shard, self.ep, self.cp, self.tp)
        dev_array = np.asarray(devices).reshape(shape)
        mesh = Mesh(dev_array, MeshAxisName.ALL)
        return MeshContext(mesh=mesh, config=dataclasses.replace(self, dp_shard=dp_shard))

    @classmethod
    def from_config(cls, node: Any) -> "MeshConfig":
        """Build from a ConfigNode/dict `distributed:` section."""
        kwargs = {}
        for f in dataclasses.fields(cls):
            if node is not None and f.name in node:
                kwargs[f.name] = int(node[f.name] if not hasattr(node, "get") else node.get(f.name))
        return cls(**kwargs)


@dataclasses.dataclass
class MeshContext:
    """A built mesh plus spec/sharding helpers (reference: mesh.py:82)."""

    mesh: Mesh
    config: MeshConfig

    # -- sizes ---------------------------------------------------------------
    def axis_size(self, name: str) -> int:
        if name in MeshAxisName.ALIASES:
            return int(math.prod(self.mesh.shape[a] for a in MeshAxisName.ALIASES[name]))
        return int(self.mesh.shape[name])

    @property
    def sizes(self) -> dict:
        return {a: int(self.mesh.shape[a]) for a in MeshAxisName.ALL}

    @property
    def num_devices(self) -> int:
        return self.mesh.size

    @property
    def dp_size(self) -> int:
        return self.axis_size("dp")

    @property
    def batch_size_divisor(self) -> int:
        """Global batch must divide by this (all token-sharding axes)."""
        return self.axis_size("batch")

    # -- specs ---------------------------------------------------------------
    def resolve_axes(self, axes) -> tuple:
        """Expand aliases; axes may be a str, tuple of str, or None."""
        if axes is None:
            return ()
        if isinstance(axes, str):
            axes = (axes,)
        out: list[str] = []
        for a in axes:
            if a in MeshAxisName.ALIASES:
                out.extend(MeshAxisName.ALIASES[a])
            else:
                if a not in MeshAxisName.ALL:
                    raise ValueError(f"Unknown mesh axis '{a}'")
                out.append(a)
        return tuple(out)

    def spec(self, *dim_axes) -> PartitionSpec:
        """PartitionSpec from per-dimension axis names (aliases resolved).

        `None` means replicated on that dim. Axes whose mesh size is 1 are
        kept (harmless) so specs are topology-independent.
        """
        parts = []
        for axes in dim_axes:
            resolved = self.resolve_axes(axes)
            if not resolved:
                parts.append(None)
            elif len(resolved) == 1:
                parts.append(resolved[0])
            else:
                parts.append(tuple(resolved))
        return PartitionSpec(*parts)

    def sharding(self, *dim_axes) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec(*dim_axes))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def __enter__(self):
        self._ctx = jax.sharding.use_mesh(self.mesh)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)
