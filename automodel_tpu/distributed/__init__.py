from automodel_tpu.distributed.mesh import MeshAxisName, MeshConfig, MeshContext
from automodel_tpu.distributed.init_utils import (
    get_rank_safe,
    get_world_size_safe,
    initialize_distributed,
    is_main_process,
)

__all__ = [
    "MeshAxisName",
    "MeshConfig",
    "MeshContext",
    "initialize_distributed",
    "get_rank_safe",
    "get_world_size_safe",
    "is_main_process",
]
