from automodel_tpu.config.loader import (
    ALLOWED_IMPORT_PREFIXES,
    ConfigError,
    ConfigNode,
    instantiate,
    load_yaml,
)
from automodel_tpu.config.arg_parser import (
    apply_overrides,
    parse_args_and_load_config,
    parse_override,
)

__all__ = [
    "ALLOWED_IMPORT_PREFIXES",
    "ConfigError",
    "ConfigNode",
    "instantiate",
    "load_yaml",
    "apply_overrides",
    "parse_args_and_load_config",
    "parse_override",
]
