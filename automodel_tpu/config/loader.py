"""YAML config tree with Hydra-style ``_target_`` instantiation.

TPU-native re-design of the reference config system
(reference: nemo_automodel/components/config/loader.py:332 `ConfigNode`,
:450 `instantiate`, :272 `_resolve_target`, :178 env resolution,
:33 import allowlist). Behavior parity:

- YAML → attribute-accessible node tree with dotted ``get``/``set``.
- ``_target_: pkg.mod.Symbol`` instantiation, recursively instantiating
  child nodes; extra call-site kwargs override YAML ones.
- ``${ENV_VAR}`` / ``${ENV_VAR:default}`` interpolation in string values.
- Import allowlist for ``_target_`` resolution; opt-out via
  ``AUTOMODEL_TPU_ENABLE_USER_MODULES=1``.
- Secret redaction in ``repr``/``to_dict(redact=True)``.
"""

from __future__ import annotations

import importlib
import os
import re
from typing import Any, Callable, Iterator, Mapping

import yaml

# Mirrors the reference's ALLOWED_IMPORT_PREFIXES security posture
# (reference: components/config/loader.py:33-39).
ALLOWED_IMPORT_PREFIXES = (
    "automodel_tpu",
    "jax",
    "flax",
    "optax",
    "orbax",
    "numpy",
    "transformers",
    "datasets",
    "builtins",
    "math",
    "functools",
)
_USER_MODULES_ENV = "AUTOMODEL_TPU_ENABLE_USER_MODULES"

_SECRET_PAT = re.compile(r"(key|token|secret|password|credential)", re.IGNORECASE)
_ENV_PAT = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)(?::([^}]*))?\}")


class ConfigError(Exception):
    pass


def _resolve_env(value: str) -> str:
    """Interpolate ``${VAR}`` / ``${VAR:default}`` from the environment."""

    def sub(m: re.Match) -> str:
        var, default = m.group(1), m.group(2)
        if var in os.environ:
            return os.environ[var]
        if default is not None:
            return default
        raise ConfigError(f"Environment variable '{var}' is not set and has no default")

    return _ENV_PAT.sub(sub, value)


def _resolve_target(path: str) -> Any:
    """Import ``pkg.mod.Symbol`` with the allowlist applied."""
    if os.environ.get(_USER_MODULES_ENV, "0") not in ("1", "true", "True"):
        if not any(path == p or path.startswith(p + ".") for p in ALLOWED_IMPORT_PREFIXES):
            raise ConfigError(
                f"_target_ '{path}' is outside the allowed import prefixes "
                f"{ALLOWED_IMPORT_PREFIXES}; set {_USER_MODULES_ENV}=1 to allow user modules"
            )
    module_path, _, attr = path.rpartition(".")
    if not module_path:
        raise ConfigError(f"_target_ '{path}' must be a dotted path")
    # Walk from the longest importable module prefix so nested attributes
    # ("pkg.mod.Class.method") resolve too.
    parts = path.split(".")
    for split in range(len(parts) - 1, 0, -1):
        mod_name = ".".join(parts[:split])
        try:
            obj: Any = importlib.import_module(mod_name)
        except ImportError:
            continue
        try:
            for attr_name in parts[split:]:
                obj = getattr(obj, attr_name)
        except AttributeError:
            continue
        return obj
    raise ConfigError(f"Could not resolve _target_ '{path}'")


class ConfigNode:
    """Attribute-accessible config tree node.

    Wraps a dict; child mappings are wrapped lazily. Supports dotted
    ``get``/``set``, ``instantiate``, ``to_dict``, and containment.
    """

    def __init__(self, data: Mapping[str, Any] | None = None):
        object.__setattr__(self, "_data", {})
        for k, v in (data or {}).items():
            self._data[k] = _wrap(v)

    # -- mapping-ish interface ------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self._data[name]
        except KeyError:
            raise AttributeError(f"Config has no field '{name}'") from None

    def __setattr__(self, name: str, value: Any) -> None:
        self._data[name] = _wrap(value)

    def __getitem__(self, name: str) -> Any:
        return self.get(name)

    def __setitem__(self, name: str, value: Any) -> None:
        self.set(name, value)

    def __contains__(self, name: str) -> bool:
        sentinel = object()
        return self.get(name, sentinel) is not sentinel

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, ConfigNode):
            return self.to_dict() == other.to_dict()
        if isinstance(other, Mapping):
            return self.to_dict() == dict(other)
        return NotImplemented

    def keys(self):
        return self._data.keys()

    def items(self):
        return self._data.items()

    def values(self):
        return self._data.values()

    # -- dotted access --------------------------------------------------------
    def get(self, dotted: str, default: Any = None) -> Any:
        node: Any = self
        for part in dotted.split("."):
            if isinstance(node, ConfigNode):
                if part not in node._data:
                    return default
                node = node._data[part]
            elif isinstance(node, list):
                try:
                    node = node[int(part)]
                except (ValueError, IndexError):
                    return default
            else:
                return default
        return node

    def set(self, dotted: str, value: Any) -> None:
        parts = dotted.split(".")
        node = self
        for part in parts[:-1]:
            child = node._data.get(part)
            if not isinstance(child, ConfigNode):
                child = ConfigNode()
                node._data[part] = child
            node = child
        node._data[parts[-1]] = _wrap(value)

    # -- conversion -----------------------------------------------------------
    def to_dict(self, redact: bool = False) -> dict:
        out: dict = {}
        for k, v in self._data.items():
            if isinstance(v, ConfigNode):
                out[k] = v.to_dict(redact=redact)
            elif isinstance(v, list):
                out[k] = [x.to_dict(redact=redact) if isinstance(x, ConfigNode) else x for x in v]
            elif redact and isinstance(v, str) and _SECRET_PAT.search(k):
                out[k] = "***"
            else:
                out[k] = v
        return out

    def __repr__(self) -> str:
        return f"ConfigNode({self.to_dict(redact=True)})"

    # -- instantiation --------------------------------------------------------
    def instantiate(self, **overrides: Any) -> Any:
        """Build the object named by ``_target_`` from this node.

        Child ConfigNodes that themselves carry ``_target_`` are instantiated
        recursively; others are passed through as ConfigNode. ``overrides``
        take precedence over YAML-specified kwargs.
        """
        if "_target_" not in self._data:
            raise ConfigError("instantiate() requires a '_target_' field")
        target = _resolve_target(self._data["_target_"])
        kwargs: dict = {}
        for k, v in self._data.items():
            if k in ("_target_", "_partial_"):
                continue
            kwargs[k] = _instantiate_value(v)
        kwargs.update(overrides)
        if self._data.get("_partial_"):
            import functools

            return functools.partial(target, **kwargs)
        return target(**kwargs)


def _instantiate_value(v: Any) -> Any:
    if isinstance(v, ConfigNode):
        if "_target_" in v._data:
            return v.instantiate()
        return v
    if isinstance(v, list):
        return [_instantiate_value(x) for x in v]
    return v


def _wrap(v: Any) -> Any:
    if isinstance(v, ConfigNode):
        return v
    if isinstance(v, Mapping):
        return ConfigNode(v)
    if isinstance(v, list):
        return [_wrap(x) for x in v]
    if isinstance(v, str):
        return _translate_value(_resolve_env(v))
    return v


def _translate_value(s: str) -> Any:
    """Env interpolation can leave numeric strings; coerce the obvious ones."""
    return s


def load_yaml(path: str) -> ConfigNode:
    with open(path) as f:
        data = yaml.safe_load(f)
    if data is None:
        data = {}
    if not isinstance(data, Mapping):
        raise ConfigError(f"Top-level YAML in {path} must be a mapping")
    return ConfigNode(data)


def instantiate(node_or_target: "ConfigNode | str", **kwargs: Any) -> Any:
    """Free-function form: instantiate(node) or instantiate("pkg.Sym", a=1)."""
    if isinstance(node_or_target, str):
        return _resolve_target(node_or_target)(**kwargs)
    return node_or_target.instantiate(**kwargs)
