"""CLI argument parsing: ``automodel_tpu <cfg.yaml> [--a.b.c=v ...]``.

Re-design of the reference's dotted CLI overrides
(reference: nemo_automodel/components/config/_arg_parser.py:79
`parse_args_and_load_config`). Values are YAML-parsed so ``--lr=3e-4``
arrives as a float and ``--flags='[1,2]'`` as a list.
"""

from __future__ import annotations

import sys
from typing import Any, Sequence

import yaml

from automodel_tpu.config.loader import ConfigNode, load_yaml


def parse_override(arg: str) -> tuple[str, Any]:
    """Parse ``--a.b.c=value`` (or ``a.b.c=value``) into (dotted_key, value)."""
    arg = arg.lstrip("-")
    if "=" not in arg:
        raise ValueError(f"Override '{arg}' must be of the form key.path=value")
    key, _, raw = arg.partition("=")
    # YAML 1.1 misses "3e-4"-style floats; coerce numerics explicitly first.
    try:
        value: Any = int(raw)
    except ValueError:
        try:
            value = float(raw)
        except ValueError:
            try:
                value = yaml.safe_load(raw)
            except yaml.YAMLError:
                value = raw
    return key, value


def apply_overrides(cfg: ConfigNode, overrides: Sequence[str]) -> ConfigNode:
    for arg in overrides:
        key, value = parse_override(arg)
        cfg.set(key, value)
    return cfg


def parse_args_and_load_config(argv: Sequence[str] | None = None) -> ConfigNode:
    """Load the YAML named by argv[0] and apply the dotted overrides after it."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        raise SystemExit("usage: automodel_tpu <config.yaml> [--key.path=value ...]")
    cfg_path, overrides = argv[0], argv[1:]
    cfg = load_yaml(cfg_path)
    apply_overrides(cfg, overrides)
    return cfg
