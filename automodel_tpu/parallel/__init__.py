from automodel_tpu.parallel.sharding import (
    AxisRules,
    DEFAULT_RULES,
    logical_to_shardings,
    with_logical_constraint,
)

__all__ = [
    "AxisRules",
    "DEFAULT_RULES",
    "logical_to_shardings",
    "with_logical_constraint",
]
