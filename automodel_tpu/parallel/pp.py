"""Pipeline parallelism: GPipe-schedule microbatch streaming over the `pp`
mesh axis.

The analog of the reference's `AutoPipeline` on torch.distributed.pipelining
(reference: nemo_automodel/components/distributed/pipelining/
autopipeline.py:49, functional.py:98 layer-FQN splitting, :777 schedule
builder). TPU-native design — there is no runtime pipelining framework to
call; the schedule is compiled:

- Layer weights stay STACKED (L, ...) and shard dim 0 over `pp` (the
  logical `layers` axis maps to the pp mesh axis), so "splitting the model
  into stages" is a sharding annotation, not a graph surgery.
- The whole pipeline is one `shard_map`: each stage scans its local layer
  stack; activations hop stage→stage with `lax.ppermute` (ICI neighbor
  traffic, the p2p `send/recv` analog); a `lax.scan` over
  (num_microbatches + num_stages - 1) ticks realizes the GPipe schedule.
- Backward is the transposed program — autodiff of ppermute/scan gives the
  reverse schedule for free, with weight-grad psums over the data axes
  inserted by shard_map's transpose.
- Embedding / final-norm / loss run OUTSIDE the shard_map under plain GSPMD
  (they are dp/cp-sharded elementwise-ish work).

Round-1 scope: pure pp × dp (tp=1, cp=1 inside the pipeline); interleaved /
1F1B schedules and tp-in-pipeline come later. The bubble fraction is the
GPipe (P-1)/(M+P-1).
"""

from __future__ import annotations

import logging
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from automodel_tpu.distributed.mesh import MeshContext

logger = logging.getLogger(__name__)


def _check_microbatch_split(B: int, M: int, mesh_ctx, batch_axes) -> None:
    """The microbatch dim splits the GLOBAL batch, and each microbatch is
    still sharded over the data axes — so B must divide by M·dp_total.
    Validate eagerly with an actionable message (the raw shard_map
    divisibility error names in_specs, not the config knobs)."""
    if B % M != 0:
        raise ValueError(f"batch {B} must divide into {M} pipeline microbatches")
    dp_total = 1
    for ax in batch_axes:
        dp_total *= mesh_ctx.sizes.get(ax, 1)
    if (B // M) % dp_total != 0:
        raise ValueError(
            f"per-microbatch batch {B}//{M}={B // M} must be divisible by the "
            f"data-parallel extent {dp_total} ({'×'.join(batch_axes)}); raise "
            "dataloader.microbatch_size or lower pipeline_microbatches"
        )


def pipeline_bubble_fraction(num_microbatches: int, num_stages: int) -> float:
    """Idle fraction of the schedule span — (P-1)/(M+P-1) for both GPipe
    and non-interleaved 1F1B (1F1B buys memory, not bubble)."""
    return (num_stages - 1) / (num_microbatches + num_stages - 1)


def pipeline_layers(
    h: jnp.ndarray,            # (B, S, H) embedded activations (global)
    positions: jnp.ndarray,    # (B, S) int32
    segment_ids: jnp.ndarray,  # (B, S) int32
    stacked_params: Any,       # layer stack, leaves (L, ...), L % pp == 0
    layer_fn: Callable,        # (h, layer_params, positions, segment_ids) -> h
    mesh_ctx: MeshContext,
    num_microbatches: int,
    batch_axes: tuple = ("dp_replicate", "dp_shard", "ep"),
    remat_policy: str | None = "full",
    param_logical_specs: Any = None,
    layer_aux: bool = False,
    extras_specs: Any = None,
    token_mask: jnp.ndarray | None = None,
):
    """Run the stacked layers as a pp-staged pipeline; returns (B, S, H).

    positions/segment_ids travel with their microbatch through the ring so
    every stage masks with the right coordinates.

    Composition: the seq dim stays sharded on `cp` (layer_fn must run the
    in-shard ring attention — decoder `manual=True` mode); head/mlp param
    dims stay sharded on `tp` when `param_logical_specs` names them
    (layer_fn psums the partial o/down projections over tp).

    `layer_aux=True` switches the layer contract to
    `layer_fn(h, lp, pos, seg) -> (h, aux_scalar, extras_pytree)` — the MoE
    mode: per-layer load-balance losses accumulate across (stage,
    microbatch) into one global scalar — the MEAN over (data-shard,
    microbatch) token chunks, summed over layers (psum over pp + token
    axes, then / n_chunks). The switch loss is a product of per-token
    means, so the global-gate value is not recoverable from chunk scalars;
    the chunk-mean is the standard per-microbatch estimator (equal to the
    global value under uniform routing stats) — and the
    per-layer `extras` leaves (e.g. tokens_per_expert (E,)) stack over the
    layer dim and come back (L, ...) with `extras_specs` out-specs (use
    P("pp", ...) for the stacked layer dim). Returns (out, aux, extras).

    `token_mask` ((B, S) bool, False = pad/ignored; layer_aux mode only)
    extends the contract to `layer_fn(h, lp, pos, seg, mask)` so routing /
    aux stats exclude masked tokens, matching the GSPMD scan path. The mask
    does NOT ride the ppermute ring: every pp rank holds all microbatches'
    token arrays (same in_spec as positions), so stage p just indexes its
    current microbatch `t - p` directly.
    """
    pp = mesh_ctx.sizes["pp"]
    B, S, H = h.shape
    M = num_microbatches
    _check_microbatch_split(B, M, mesh_ctx, batch_axes)
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    assert L % pp == 0, f"{L} layers not divisible by pp={pp}"
    logger.info(
        "pipeline(gpipe): pp=%d M=%d bubble=%.3f",
        pp, M, pipeline_bubble_fraction(M, pp),
    )

    h_mb = h.reshape(M, B // M, S, H)
    pos_mb = positions.reshape(M, B // M, S)
    seg_mb = segment_ids.reshape(M, B // M, S)
    has_mask = layer_aux and token_mask is not None
    n_chunks = M * math.prod(
        mesh_ctx.sizes[a] for a in tuple(batch_axes) + ("cp",)
    )

    def run(h_mb, pos_mb, seg_mb, params_local, *maybe_mask):
        # inside shard_map: h_mb (M, B_loc, S, H); params leaves (L/pp, ...)
        p_idx = lax.axis_index("pp")
        n_stage = lax.axis_size("pp")
        T = M + n_stage - 1
        mask_mb = maybe_mask[0] if has_mask else None

        def apply_stage(x, pos, seg, tm=None):
            from automodel_tpu.models.common.layers import maybe_remat

            if layer_aux:
                def body(c, lp):
                    y, a, e = (
                        layer_fn(c, lp, pos, seg, tm)
                        if has_mask else layer_fn(c, lp, pos, seg)
                    )
                    return y, (a, e)

                y, (auxs, extras) = lax.scan(
                    maybe_remat(body, remat_policy), x, params_local
                )
                return y, jnp.sum(auxs).astype(jnp.float32), extras

            def body(c, lp):
                return layer_fn(c, lp, pos, seg), None

            y, _ = lax.scan(maybe_remat(body, remat_policy), x, params_local)
            return y, jnp.float32(0.0), ()

        if layer_aux:
            ex_shapes = jax.eval_shape(
                lambda p: apply_stage(
                    h_mb[0], pos_mb[0], seg_mb[0],
                    mask_mb[0] if has_mask else None,
                )[2],
                params_local,
            )
            ex0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), ex_shapes)
        else:
            ex0 = ()

        def tick(carry, t):
            (act, pos, seg), outputs, aux_acc, ex_acc = carry
            m = jnp.clip(t, 0, M - 1)
            is_first = p_idx == 0
            x = jnp.where(is_first, h_mb[m], act)
            pos = jnp.where(is_first, pos_mb[m], pos)
            seg = jnp.where(is_first, seg_mb[m], seg)
            # stage p works on microbatch t - p; its token mask is read from
            # the (rank-complete) mask_mb rather than streamed with the act
            tm = (
                mask_mb[jnp.clip(t - p_idx, 0, M - 1)] if has_mask else None
            )
            y, aux, ex = apply_stage(x, pos, seg, tm)
            # stage p holds real data for microbatch t - p on ticks
            # p <= t < p + M; off-window ticks recompute clipped garbage that
            # must not leak into the aux/stat accumulators
            valid = jnp.logical_and(t >= p_idx, t - p_idx < M)
            # aux_acc is carried as shape (1,), not a scalar: jax 0.4.37's
            # shard_map linearization mis-promotes scalar scan residuals
            # (broadcast-in-dim shape mismatch under grad); any rank>=1
            # carry avoids the bug
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            ex_acc = jax.tree.map(
                lambda a, e: a + jnp.where(valid, e, jnp.zeros_like(e)),
                ex_acc, ex,
            )
            out_idx = t - (n_stage - 1)
            write = jnp.logical_and(out_idx >= 0, p_idx == n_stage - 1)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(out_idx, 0, M - 1), 0
                ),
                lambda o: o,
                outputs,
            )
            perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
            stream = lax.ppermute((y, pos, seg), "pp", perm)
            return (stream, outputs, aux_acc, ex_acc), None

        init_stream = (jnp.zeros_like(h_mb[0]), pos_mb[0], seg_mb[0])
        (_, outputs, aux_acc, ex_acc), _ = lax.scan(
            tick,
            (init_stream, jnp.zeros_like(h_mb), jnp.zeros((1,), jnp.float32),
             ex0),
            jnp.arange(T),
        )
        # Only the last stage's buffer is real; every pp rank needs it because
        # the head (final norm + lm-head/loss) runs under GSPMD outside this
        # shard_map with pp unmapped. masked-psum IS the broadcast: an
        # all-reduce of one activation buffer moves the same bytes as any
        # one-to-all broadcast over the ring, and XLA lowers it to one
        # collective — the zeros are the selection mask, not wasted traffic.
        outputs = lax.psum(
            jnp.where(p_idx == n_stage - 1, outputs, jnp.zeros_like(outputs)), "pp"
        )
        data_axes = tuple(batch_axes) + ("cp",)
        # each stage's aux covers its own layers → sum over pp; each token
        # shard routes its own tokens → mean over the (data shard,
        # microbatch) chunks (replicated over tp already — tp ranks see
        # identical tokens)
        aux_acc = lax.psum(aux_acc[0], data_axes + ("pp",)) / n_chunks
        ex_acc = jax.tree.map(lambda e: lax.psum(e, data_axes), ex_acc)
        return outputs, aux_acc, ex_acc

    act_spec = P(None, batch_axes, "cp", None)  # (M, B, S_cp, H)
    tok_spec = P(None, batch_axes, "cp")
    mask_ops = (token_mask.reshape(M, B // M, S),) if has_mask else ()
    out, aux, extras = jax.shard_map(
        run,
        mesh=mesh_ctx.mesh,
        in_specs=(
            act_spec, tok_spec, tok_spec,
            _param_specs_pp(stacked_params, param_logical_specs),
        ) + ((tok_spec,) if has_mask else ()),
        out_specs=(act_spec, P(), extras_specs if layer_aux else ()),
        check_vma=False,
    )(h_mb, pos_mb, seg_mb, stacked_params, *mask_ops)
    out = out.reshape(B, S, H)
    if layer_aux:
        return out, aux, extras
    return out


# ---------------------------------------------------------------------------
# interleaved (virtual-stage) 1F1B schedule tables
# ---------------------------------------------------------------------------
def interleaved_1f1b_tables(num_microbatches: int, num_devices: int, virtual: int):
    """Greedy simulation of interleaved 1F1B over S = P·V virtual stages,
    stage s living on device s % P (the Megatron cyclic mapping; reference:
    distributed/pipelining/functional.py:182 virtual stages + :777
    ScheduleInterleaved1F1B).

    Returns (fwd_tab, bwd_tab): int32 arrays (T, P) encoding the action per
    half-tick as `v * M + m` (virtual-stage-major) or -1 for idle. One fwd
    and one bwd slot per device per tick; every dependency is satisfied with
    ≥ 1 tick of latency so the +1/-1 ppermute streams deliver in time.

    Policy: depth-first over microbatch GROUPS of size P per virtual stage
    (Megatron's ordering), bwd-first once a stage's backward is ready —
    giving the interleaved bubble ≈ (P-1)/(V·M) instead of (P-1)/(M+P-1).
    """
    M, P, V = num_microbatches, num_devices, virtual
    S = P * V
    not_done = 10 ** 9
    fwd_done = [[not_done] * M for _ in range(S)]
    bwd_done = [[not_done] * M for _ in range(S)]
    fwd_next = [0] * S
    bwd_next = [0] * S

    def stage_key(s: int, m: int, fwd: bool) -> tuple:
        # depth-first group ordering: finish group g of vstage v before
        # starting group g of vstage v+1's successors; backward prefers the
        # LAST vstage first (it becomes ready first)
        g = m // P
        v = s // P
        return (g, v if fwd else (V - 1 - v), m % P)

    fwd_rows, bwd_rows = [], []
    t = 0
    while any(bwd_next[s] < M for s in range(S)) and t < 8 * V * (M + P):
        frow, brow = [-1] * P, [-1] * P
        for p in range(P):
            # candidate forward actions on this device, best schedule-key first
            f_cands = []
            b_cands = []
            for v in range(V):
                s = v * P + p
                f = fwd_next[s]
                if f < M and (s == 0 or fwd_done[s - 1][f] < t):
                    # in-flight bound per stage chain: keep ≤ S - s microbatches
                    # between this stage's fwd and its bwd (generalizes the
                    # non-interleaved P - p bound; also keys the stash mod)
                    if (f - bwd_next[s]) < (S - s):
                        f_cands.append((stage_key(s, f, True), s, f))
                b = bwd_next[s]
                if b < M and fwd_done[s][b] < t and (
                    s == S - 1 or bwd_done[s + 1][b] < t
                ):
                    b_cands.append((stage_key(s, b, False), s, b))
            if b_cands:
                _, s, b = min(b_cands)
                brow[p] = (s // P) * M + b
                bwd_done[s][b] = t
                bwd_next[s] += 1
            if f_cands:
                # bwd-first steady state: allow the fwd too (separate slot)
                _, s, f = min(f_cands)
                frow[p] = (s // P) * M + f
                fwd_done[s][f] = t
                fwd_next[s] += 1
        fwd_rows.append(frow)
        bwd_rows.append(brow)
        t += 1
    assert all(bwd_next[s] == M and fwd_next[s] == M for s in range(S)), (
        f"interleaved schedule incomplete for M={M} P={P} V={V}: "
        f"fwd={fwd_next} bwd={bwd_next}"
    )
    import numpy as np

    return np.asarray(fwd_rows, np.int32), np.asarray(bwd_rows, np.int32)


# ---------------------------------------------------------------------------
# 1F1B schedule (memory-capped training pipeline)
# ---------------------------------------------------------------------------
def one_f_one_b_tables(num_microbatches: int, num_stages: int):
    """Static per-half-tick action tables for non-interleaved 1F1B.

    The schedule builder analog (reference: distributed/pipelining/
    functional.py:777): greedy simulation of Megatron's policy — stage p
    warms up with (P-1-p) forwards, then alternates 1 fwd / 1 bwd, then
    drains. Returns (fwd_mb, bwd_mb): int arrays (T, P) holding the
    microbatch id acted on, or -1 for an idle slot. At most one action per
    (tick, stage); dependencies are satisfied with ≥1-tick latency, so
    ppermute streams inserted between ticks carry the data in time.
    """
    M, P = num_microbatches, num_stages
    not_done = 10 ** 9
    fwd_done = [[not_done] * M for _ in range(P)]  # completion half-tick
    bwd_done = [[not_done] * M for _ in range(P)]
    next_f = [0] * P
    next_b = [0] * P
    warmup_left = [P - 1 - p for p in range(P)]
    fwd_rows, bwd_rows = [], []
    t = 0
    while any(next_b[p] < M for p in range(P)) and t < 4 * (M + P):
        frow, brow = [-1] * P, [-1] * P
        for p in range(P):
            f, b = next_f[p], next_b[p]
            # 1F1B memory bound: at most P-p microbatches in flight at stage
            # p (warmup depth + the steady-state one) — also what keeps the
            # mod-P stash indexing collision-free
            f_ready = (
                f < M
                and (p == 0 or fwd_done[p - 1][f] < t)
                and (f - b) < (P - p)
            )
            b_ready = (
                b < M
                and fwd_done[p][b] < t
                and (p == P - 1 or bwd_done[p + 1][b] < t)
            )
            # policy: forwards during warmup, then bwd-first (1F1B steady)
            if warmup_left[p] > 0 and f_ready:
                frow[p] = f
                fwd_done[p][f] = t
                next_f[p] += 1
                warmup_left[p] -= 1
            elif b_ready:
                brow[p] = b
                bwd_done[p][b] = t
                next_b[p] += 1
            elif f_ready:
                frow[p] = f
                fwd_done[p][f] = t
                next_f[p] += 1
        fwd_rows.append(frow)
        bwd_rows.append(brow)
        t += 1
    assert all(next_b[p] == M and next_f[p] == M for p in range(P)), (
        f"1F1B schedule did not complete for M={M} P={P}: "
        f"fwd={next_f} bwd={next_b} — silent gradient loss prevented"
    )
    import numpy as np

    return np.asarray(fwd_rows, np.int32), np.asarray(bwd_rows, np.int32)


def pipeline_train_1f1b(
    h: jnp.ndarray,            # (B, S, H) embedded activations (global)
    positions: jnp.ndarray,    # (B, S)
    segment_ids: jnp.ndarray,  # (B, S)
    labels: jnp.ndarray,       # (B, S) int32 (-100 = ignored)
    stacked_params: Any,       # leaves (L, ...), L % pp == 0
    layer_fn: Callable,        # (h, layer_params, positions, segment_ids) -> h
    head_params: Any,
    head_loss_fn: Callable,    # (h_mb, head_params, labels_mb) -> scalar SUM loss
    mesh_ctx: MeshContext,
    num_microbatches: int,
    batch_axes: tuple = ("dp_replicate", "dp_shard", "ep"),
    param_logical_specs: Any = None,
    aux_scale: jnp.ndarray | None = None,
    extras_specs: Any = None,
) -> tuple:
    """1F1B training pipeline: returns (loss_sum, d_h, layer_grads, head_grads).

    Unlike `pipeline_layers` (GPipe + autodiff, which stashes all M
    microbatch boundary activations), this runs an explicit fwd/bwd
    interleave with per-stage `jax.vjp`: at most `pp` microbatch inputs are
    stashed per stage — the 1F1B memory bound — at the same bubble fraction
    (P-1)/(M+P-1). The head (final-norm + lm-head + loss) runs fused into
    the last stage's backward, so logits are never stored.

    Grads come back already reduced: layer_grads sharded (pp on dim 0),
    head_grads and d_h replicated. Compose with `jax.vjp` of the embedding
    outside. Loss/grad parity vs end-to-end autodiff: tests/unit/test_pp.py.

    `aux_scale` (a traced scalar, e.g. the global label-token count) enables
    the MoE layer contract `layer_fn -> (h, aux, extras)`: every stage's
    backward adds `aux_scale · aux` into the differentiated scalar, so the
    expert-dispatch A2A and its gradients stay confined to that stage's step
    while load-balance gradients flow. The per-layer `extras` pytree (e.g.
    tokens_per_expert (E,)) accumulates over microbatches, stacks over the
    stage's layers, and is returned as a fifth output with `extras_specs`
    out-specs (P("pp", ...) on the stacked layer dim). The returned loss is
    then ce_sum + aux_scale·Σaux — the `combine_losses` contract.
    """
    pp = mesh_ctx.sizes["pp"]
    B, S, H = h.shape
    M = num_microbatches
    has_aux = aux_scale is not None
    _check_microbatch_split(B, M, mesh_ctx, batch_axes)
    fwd_tab, bwd_tab = one_f_one_b_tables(M, pp)
    T = fwd_tab.shape[0]
    logger.info(
        "pipeline(1f1b): pp=%d M=%d ticks=%d bubble=%.3f",
        pp, M, T, pipeline_bubble_fraction(M, pp),
    )

    h_mb = h.reshape(M, B // M, S, H)
    pos_mb = positions.reshape(M, B // M, S)
    seg_mb = segment_ids.reshape(M, B // M, S)
    lab_mb = labels.reshape(M, B // M, S)
    scale_in = jnp.asarray(aux_scale if has_aux else 0.0, jnp.float32)

    def run(h_mb, pos_mb, seg_mb, lab_mb, params_local, head_local, scale):
        p_idx = lax.axis_index("pp")
        n_stage = lax.axis_size("pp")
        is_last = p_idx == n_stage - 1
        ftab = jnp.asarray(fwd_tab)
        btab = jnp.asarray(bwd_tab)

        def stage(x, params, pos, seg):
            if has_aux:
                def body(c, lp):
                    y, a, e = layer_fn(c, lp, pos, seg)
                    return y, (a, e)

                y, (auxs, extras) = lax.scan(body, x, params)
                return y, jnp.sum(auxs).astype(jnp.float32), extras

            def body(c, lp):
                return layer_fn(c, lp, pos, seg), None

            y, _ = lax.scan(body, x, params)
            return y, jnp.float32(0.0), ()

        def full_bwd(x, params, head, pos, seg, lab, dy):
            """Backward of one microbatch at this stage: last stage fuses the
            head+loss (ignoring dy), others pull the streamed cotangent. The
            has_aux report carries (loss_contribution, per-layer extras).

            stage() is hoisted OUT of the is_last cond: its collectives (cp
            ring hops, tp psums, ep A2As) must execute rank-uniformly — pp
            ranks take different branches, and branch-divergent collectives
            deadlock the CPU runtime's global rendezvous (reproduced:
            pp×cp 1F1B dryrun hang). The cond keeps only local head/vdot
            math, so the head matmul still runs on the last stage alone."""

            def fwd(xx, pp_, hh_):
                y, aux, ex = stage(xx, pp_, pos, seg)
                sa = aux * scale
                # cond operands stay explicit arrays — 0.4.37 shard_map
                # linearization mishandles captured/scalar cond residuals
                s = lax.cond(
                    is_last,
                    lambda yy, hh: head_loss_fn(yy, hh, lab).astype(jnp.float32),
                    lambda yy, hh: jnp.vdot(
                        yy.astype(jnp.float32), dy.astype(jnp.float32)
                    ),
                    y, hh_,
                ) + sa
                return s, (jnp.where(is_last, s, sa), ex)

            out, vjp, (rep, extras) = jax.vjp(fwd, x, params, head, has_aux=True)
            dx, dparams, dhead = vjp(jnp.ones((), out.dtype))
            return rep, dx, dparams, dhead, extras

        zeros_g = jax.tree.map(jnp.zeros_like, params_local)
        zeros_h = jax.tree.map(jnp.zeros_like, head_local)
        stash0 = jnp.zeros((n_stage,) + h_mb.shape[1:], h_mb.dtype)
        ex0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(
                lambda p: stage(h_mb[0], p, pos_mb[0], seg_mb[0])[2],
                params_local,
            ),
        )

        def tick(carry, t):
            (fstream, bstream, fstash, bstash, stash,
             gacc, hacc, dh_acc, loss_acc, ex_acc) = carry
            mf = jnp.take(ftab[t], p_idx)
            mb = jnp.take(btab[t], p_idx)

            # ---- bank arrivals (streams hold the NEIGHBOR's t-1 output;
            # consumption may be ticks later, so stash by microbatch id) ----
            prev_t = jnp.maximum(t - 1, 0)
            from_prev = jnp.take(ftab[prev_t], (p_idx - 1) % n_stage)
            f_arrived = jnp.logical_and(
                jnp.logical_and(t > 0, p_idx > 0), from_prev >= 0
            )
            fstash = jnp.where(
                f_arrived,
                lax.dynamic_update_index_in_dim(
                    fstash, fstream, jnp.clip(from_prev, 0, M - 1) % n_stage, 0
                ),
                fstash,
            )
            from_next = jnp.take(btab[prev_t], (p_idx + 1) % n_stage)
            b_arrived = jnp.logical_and(
                jnp.logical_and(t > 0, p_idx < n_stage - 1), from_next >= 0
            )
            bstash = jnp.where(
                b_arrived,
                lax.dynamic_update_index_in_dim(
                    bstash, bstream, jnp.clip(from_next, 0, M - 1) % n_stage, 0
                ),
                bstash,
            )

            # ---- forward slot ----
            mf_c = jnp.clip(mf, 0, M - 1)
            x_in = jnp.where(p_idx == 0, h_mb[mf_c], fstash[mf_c % n_stage])
            stash = jnp.where(
                mf >= 0,
                lax.dynamic_update_index_in_dim(stash, x_in, mf_c % n_stage, 0),
                stash,
            )
            y, _, _ = stage(x_in, params_local, pos_mb[mf_c], seg_mb[mf_c])
            fout = jnp.where(mf >= 0, y, jnp.zeros_like(y))

            # ---- backward slot ----
            mb_c = jnp.clip(mb, 0, M - 1)
            x_b = stash[mb_c % n_stage]
            loss_i, dx, dparams, dhead, ex = full_bwd(
                x_b, params_local, head_local,
                pos_mb[mb_c], seg_mb[mb_c], lab_mb[mb_c], bstash[mb_c % n_stage],
            )
            do_b = mb >= 0
            gacc = jax.tree.map(
                lambda a, g: a + jnp.where(do_b, g, jnp.zeros_like(g)), gacc, dparams
            )
            hacc = jax.tree.map(
                lambda a, g: a + jnp.where(do_b, g, jnp.zeros_like(g)), hacc, dhead
            )
            ex_acc = jax.tree.map(
                lambda a, e: a + jnp.where(do_b, e, jnp.zeros_like(e)), ex_acc, ex
            )
            dh_acc = jnp.where(
                jnp.logical_and(do_b, p_idx == 0),
                lax.dynamic_update_index_in_dim(dh_acc, dx, mb_c, 0),
                dh_acc,
            )
            loss_acc = loss_acc + jnp.where(do_b, loss_i, 0.0)

            fwd_perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
            bwd_perm = [((i + 1) % n_stage, i) for i in range(n_stage)]
            fstream = lax.ppermute(fout, "pp", fwd_perm)
            bout = jnp.where(do_b, dx, jnp.zeros_like(dx))
            bstream = lax.ppermute(bout, "pp", bwd_perm)
            return (
                fstream, bstream, fstash, bstash, stash,
                gacc, hacc, dh_acc, loss_acc, ex_acc,
            ), None

        carry0 = (
            jnp.zeros_like(h_mb[0]),
            jnp.zeros_like(h_mb[0]),
            stash0,
            stash0,
            stash0,
            zeros_g,
            zeros_h,
            jnp.zeros_like(h_mb),
            jnp.zeros((), jnp.float32),
            ex0,
        )
        (_, _, _, _, _, gacc, hacc, dh_acc, loss_acc, ex_acc), _ = lax.scan(
            tick, carry0, jnp.arange(T)
        )
        # Manual-collective grad reduction (the transpose of shard_map would
        # have inserted these in the autodiff path): param grads are partial
        # per data shard → psum over batch+cp; NOT over tp (activations are
        # tp-replicated so per-rank grads are already correct for each
        # rank's param slice) and NOT over axes a leaf is sharded on (an
        # ep-sharded expert slice already holds its complete grad — every
        # token routed to it arrived through the A2A). Layer grads stay on
        # their own pp stage; head grads / loss / d_h are made consistent
        # across pp.
        data_axes = tuple(batch_axes) + ("cp",)
        gacc = jax.tree.map(
            lambda g, s: lax.psum(g, _grad_reduce_axes(s, data_axes)),
            gacc, pspecs,
        )
        hacc = jax.tree.map(lambda g: lax.psum(g, data_axes + ("pp",)), hacc)
        dh_acc = lax.psum(dh_acc, "pp")
        loss_acc = lax.psum(loss_acc, data_axes + ("pp",))
        ex_acc = jax.tree.map(lambda e: lax.psum(e, data_axes), ex_acc)
        return loss_acc, dh_acc, gacc, hacc, ex_acc

    act_spec = P(None, batch_axes, "cp", None)
    tok_spec = P(None, batch_axes, "cp")
    pspecs = _param_specs_pp(stacked_params, param_logical_specs)
    hspec = jax.tree.map(lambda x: P(*([None] * x.ndim)), head_params)
    loss, dh, gl, gh, ex = jax.shard_map(
        run,
        mesh=mesh_ctx.mesh,
        in_specs=(act_spec, tok_spec, tok_spec, tok_spec, pspecs, hspec, P()),
        out_specs=(P(), act_spec, pspecs, hspec,
                   extras_specs if has_aux else ()),
        check_vma=False,
    )(h_mb, pos_mb, seg_mb, lab_mb, stacked_params, head_params, scale_in)
    if has_aux:
        return loss, dh.reshape(B, S, H), gl, gh, ex
    return loss, dh.reshape(B, S, H), gl, gh


# ---------------------------------------------------------------------------
# zero-bubble (ZB-H1) schedule: backward split into B (input-grad) and W
# (weight-grad) passes; W fills the drain bubbles
# ---------------------------------------------------------------------------
def zero_bubble_tables(num_microbatches: int, num_stages: int):
    """Static per-tick action tables for the ZB-H1 zero-bubble schedule
    (Qi et al. 2023; the reference exposes it as the `zbv` option of
    `build_pipeline_schedule`, distributed/pipelining/functional.py:777).

    The backward splits into B (activation/input gradient — on the critical
    path, streamed upstream immediately) and W (weight gradient — no
    dataflow successors, so it can fill what would otherwise be drain
    bubbles). Greedy per-device policy: warmup forwards like 1F1B, then
    B > F > W priority; W(m) only after the same stage's B(m). Returns
    (fwd, bwd, wgt) int arrays (T, P): microbatch id or -1. Stash capacity
    is bounded by the (·) < P constraints below, which keep the mod-P stash
    slots (inputs held F→W, cotangents held B→W) collision-free.
    """
    M, P = num_microbatches, num_stages
    not_done = 10 ** 9
    fwd_done = [[not_done] * M for _ in range(P)]
    bwd_done = [[not_done] * M for _ in range(P)]
    next_f, next_b, next_w = [0] * P, [0] * P, [0] * P
    warmup_left = [P - 1 - p for p in range(P)]
    fwd_rows, bwd_rows, wgt_rows = [], [], []
    t = 0
    while any(next_w[p] < M for p in range(P)) and t < 6 * (M + P):
        frow, brow, wrow = [-1] * P, [-1] * P, [-1] * P
        for p in range(P):
            f, b, w = next_f[p], next_b[p], next_w[p]
            f_ready = (
                f < M
                and (p == 0 or fwd_done[p - 1][f] < t)
                and (f - b) < (P - p)   # 1F1B in-flight bound
                and (f - w) < P         # input stash held until W
            )
            b_ready = (
                b < M
                and fwd_done[p][b] < t
                and (p == P - 1 or bwd_done[p + 1][b] < t)
                and (b - w) < P         # cotangent stash held until W
            )
            w_ready = w < M and bwd_done[p][w] < t
            if warmup_left[p] > 0 and f_ready:
                frow[p] = f
                fwd_done[p][f] = t
                next_f[p] += 1
                warmup_left[p] -= 1
            elif b_ready:
                brow[p] = b
                bwd_done[p][b] = t
                next_b[p] += 1
            elif f_ready:
                frow[p] = f
                fwd_done[p][f] = t
                next_f[p] += 1
            elif w_ready:
                wrow[p] = w
                next_w[p] += 1
        fwd_rows.append(frow)
        bwd_rows.append(brow)
        wgt_rows.append(wrow)
        t += 1
    assert all(next_w[p] == M and next_b[p] == M for p in range(P)), (
        f"zero-bubble schedule did not complete for M={M} P={P}: "
        f"f={next_f} b={next_b} w={next_w} — silent gradient loss prevented"
    )
    import numpy as np

    return (
        np.asarray(fwd_rows, np.int32),
        np.asarray(bwd_rows, np.int32),
        np.asarray(wgt_rows, np.int32),
    )


def pipeline_train_zb(
    h: jnp.ndarray,
    positions: jnp.ndarray,
    segment_ids: jnp.ndarray,
    labels: jnp.ndarray,
    stacked_params: Any,
    layer_fn: Callable,
    head_params: Any,
    head_loss_fn: Callable,
    mesh_ctx: MeshContext,
    num_microbatches: int,
    batch_axes: tuple = ("dp_replicate", "dp_shard", "ep"),
    param_logical_specs: Any = None,
    aux_scale: jnp.ndarray | None = None,
    extras_specs: Any = None,
) -> tuple:
    """Zero-bubble (ZB-H1) training pipeline — pipeline_train_1f1b's
    interface with the backward split into B and W passes, including the
    MoE layer-aux contract (`aux_scale`/`extras_specs`, see 1F1B): aux
    gradients split naturally — B's x-only vjp carries the aux input-grad,
    W's param-only vjp the aux weight-grad; extras are reported by B.

    B computes only the input gradient (XLA dead-code-eliminates the
    weight-grad matmuls from the x-only vjp) and streams it upstream at
    1F1B latency; W re-linearizes against the stashed microbatch input and
    stashed cotangent to produce the weight gradients in the schedule's
    idle slots. Memory matches 1F1B's O(P) activation stash plus an O(P)
    cotangent stash (the ZB-H1 point: no extra in-flight microbatches).

    HONEST SCOPE: this executor runs all three lanes (F, B, W) where-masked
    every tick inside one lax.scan, so each tick costs a constant
    F + split-backward regardless of the schedule's idle pattern — exactly
    like pipeline_train_1f1b ("1F1B buys memory, not bubble" above). The
    zb value here is schedule parity with the reference's zbv option
    (pipelining/functional.py:777) and the B/W machinery a future
    branch-per-tick executor needs for the actual bubble win; wall-clock
    today tracks the table span at the same per-tick cost.
    """
    pp = mesh_ctx.sizes["pp"]
    B, S, H = h.shape
    M = num_microbatches
    has_aux = aux_scale is not None
    _check_microbatch_split(B, M, mesh_ctx, batch_axes)
    fwd_tab, bwd_tab, wgt_tab = zero_bubble_tables(M, pp)
    T = fwd_tab.shape[0]
    logger.info(
        "pipeline(zb): pp=%d M=%d ticks=%d (1f1b bubble %.3f; W fills drain)",
        pp, M, T, pipeline_bubble_fraction(M, pp),
    )

    h_mb = h.reshape(M, B // M, S, H)
    pos_mb = positions.reshape(M, B // M, S)
    seg_mb = segment_ids.reshape(M, B // M, S)
    lab_mb = labels.reshape(M, B // M, S)
    scale_in = jnp.asarray(aux_scale if has_aux else 0.0, jnp.float32)

    def run(h_mb, pos_mb, seg_mb, lab_mb, params_local, head_local, scale):
        p_idx = lax.axis_index("pp")
        n_stage = lax.axis_size("pp")
        is_last = p_idx == n_stage - 1
        ftab = jnp.asarray(fwd_tab)
        btab = jnp.asarray(bwd_tab)
        wtab = jnp.asarray(wgt_tab)

        def stage(x, params, pos, seg):
            if has_aux:
                def body(c, lp):
                    y, a, e = layer_fn(c, lp, pos, seg)
                    return y, (a, e)

                y, (auxs, extras) = lax.scan(body, x, params)
                return y, jnp.sum(auxs).astype(jnp.float32), extras

            def body(c, lp):
                return layer_fn(c, lp, pos, seg), None

            y, _ = lax.scan(body, x, params)
            return y, jnp.float32(0.0), ()

        def b_pass(x, pos, seg, lab, dy):
            """Input-grad-only backward (weight grads are W's job). stage()
            runs OUTSIDE the is_last cond — collectives must be rank-uniform
            (see pipeline_train_1f1b.full_bwd)."""

            def fwd(xx):
                y, aux, ex = stage(xx, params_local, pos, seg)
                sa = aux * scale
                s = lax.cond(
                    is_last,
                    lambda yy: head_loss_fn(yy, head_local, lab).astype(
                        jnp.float32
                    ),
                    lambda yy: jnp.vdot(
                        yy.astype(jnp.float32), dy.astype(jnp.float32)
                    ),
                    y,
                ) + sa
                return s, (jnp.where(is_last, s, sa), ex)

            out, vjp, (rep, ex) = jax.vjp(fwd, x, has_aux=True)
            (dx,) = vjp(jnp.ones((), out.dtype))
            return rep, dx, ex

        def w_pass(x, pos, seg, lab, dy):
            """Weight-grad-only backward against the stashed input/cotangent.
            Same hoisted-stage structure as b_pass."""

            def fwd(pp_, hh_):
                y, aux, _ = stage(x, pp_, pos, seg)
                sa = aux * scale
                return lax.cond(
                    is_last,
                    lambda yy, hh: head_loss_fn(yy, hh, lab).astype(jnp.float32),
                    lambda yy, hh: jnp.vdot(
                        yy.astype(jnp.float32), dy.astype(jnp.float32)
                    ),
                    y, hh_,
                ) + sa

            _, vjp = jax.vjp(fwd, params_local, head_local)
            return vjp(jnp.ones((), jnp.float32))

        zeros_g = jax.tree.map(jnp.zeros_like, params_local)
        zeros_h = jax.tree.map(jnp.zeros_like, head_local)
        stash0 = jnp.zeros((n_stage,) + h_mb.shape[1:], h_mb.dtype)
        ex0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(
                lambda p: stage(h_mb[0], p, pos_mb[0], seg_mb[0])[2],
                params_local,
            ),
        )

        def tick(carry, t):
            (fstream, bstream, fstash, bstash, stash,
             gacc, hacc, dh_acc, loss_acc, ex_acc) = carry
            mf = jnp.take(ftab[t], p_idx)
            mb = jnp.take(btab[t], p_idx)
            mw = jnp.take(wtab[t], p_idx)

            prev_t = jnp.maximum(t - 1, 0)
            from_prev = jnp.take(ftab[prev_t], (p_idx - 1) % n_stage)
            f_arrived = jnp.logical_and(
                jnp.logical_and(t > 0, p_idx > 0), from_prev >= 0
            )
            fstash = jnp.where(
                f_arrived,
                lax.dynamic_update_index_in_dim(
                    fstash, fstream, jnp.clip(from_prev, 0, M - 1) % n_stage, 0
                ),
                fstash,
            )
            from_next = jnp.take(btab[prev_t], (p_idx + 1) % n_stage)
            b_arrived = jnp.logical_and(
                jnp.logical_and(t > 0, p_idx < n_stage - 1), from_next >= 0
            )
            bstash = jnp.where(
                b_arrived,
                lax.dynamic_update_index_in_dim(
                    bstash, bstream, jnp.clip(from_next, 0, M - 1) % n_stage, 0
                ),
                bstash,
            )

            # ---- forward slot ----
            mf_c = jnp.clip(mf, 0, M - 1)
            x_in = jnp.where(p_idx == 0, h_mb[mf_c], fstash[mf_c % n_stage])
            stash = jnp.where(
                mf >= 0,
                lax.dynamic_update_index_in_dim(stash, x_in, mf_c % n_stage, 0),
                stash,
            )
            y, _, _ = stage(x_in, params_local, pos_mb[mf_c], seg_mb[mf_c])
            fout = jnp.where(mf >= 0, y, jnp.zeros_like(y))

            # ---- B slot: input grad only ----
            mb_c = jnp.clip(mb, 0, M - 1)
            loss_i, dx, ex = b_pass(
                stash[mb_c % n_stage], pos_mb[mb_c], seg_mb[mb_c],
                lab_mb[mb_c], bstash[mb_c % n_stage],
            )
            do_b = mb >= 0
            dh_acc = jnp.where(
                jnp.logical_and(do_b, p_idx == 0),
                lax.dynamic_update_index_in_dim(dh_acc, dx, mb_c, 0),
                dh_acc,
            )
            loss_acc = loss_acc + jnp.where(do_b, loss_i, 0.0)
            ex_acc = jax.tree.map(
                lambda a, e: a + jnp.where(do_b, e, jnp.zeros_like(e)), ex_acc, ex
            )

            # ---- W slot: weight grads against stashed input + cotangent ----
            mw_c = jnp.clip(mw, 0, M - 1)
            dparams, dhead = w_pass(
                stash[mw_c % n_stage], pos_mb[mw_c], seg_mb[mw_c],
                lab_mb[mw_c], bstash[mw_c % n_stage],
            )
            do_w = mw >= 0
            gacc = jax.tree.map(
                lambda a, g: a + jnp.where(do_w, g, jnp.zeros_like(g)), gacc, dparams
            )
            hacc = jax.tree.map(
                lambda a, g: a + jnp.where(do_w, g, jnp.zeros_like(g)), hacc, dhead
            )

            fwd_perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
            bwd_perm = [((i + 1) % n_stage, i) for i in range(n_stage)]
            fstream = lax.ppermute(fout, "pp", fwd_perm)
            bout = jnp.where(do_b, dx, jnp.zeros_like(dx))
            bstream = lax.ppermute(bout, "pp", bwd_perm)
            return (
                fstream, bstream, fstash, bstash, stash,
                gacc, hacc, dh_acc, loss_acc, ex_acc,
            ), None

        carry0 = (
            jnp.zeros_like(h_mb[0]),
            jnp.zeros_like(h_mb[0]),
            stash0,
            stash0,
            stash0,
            zeros_g,
            zeros_h,
            jnp.zeros_like(h_mb),
            jnp.zeros((), jnp.float32),
            ex0,
        )
        (_, _, _, _, _, gacc, hacc, dh_acc, loss_acc, ex_acc), _ = lax.scan(
            tick, carry0, jnp.arange(T)
        )
        data_axes = tuple(batch_axes) + ("cp",)
        gacc = jax.tree.map(
            lambda g, s: lax.psum(g, _grad_reduce_axes(s, data_axes)),
            gacc, pspecs,
        )
        hacc = jax.tree.map(lambda g: lax.psum(g, data_axes + ("pp",)), hacc)
        dh_acc = lax.psum(dh_acc, "pp")
        loss_acc = lax.psum(loss_acc, data_axes + ("pp",))
        ex_acc = jax.tree.map(lambda e: lax.psum(e, data_axes), ex_acc)
        return loss_acc, dh_acc, gacc, hacc, ex_acc

    act_spec = P(None, batch_axes, "cp", None)
    tok_spec = P(None, batch_axes, "cp")
    pspecs = _param_specs_pp(stacked_params, param_logical_specs)
    hspec = jax.tree.map(lambda x: P(*([None] * x.ndim)), head_params)
    loss, dh, gl, gh, ex = jax.shard_map(
        run,
        mesh=mesh_ctx.mesh,
        in_specs=(act_spec, tok_spec, tok_spec, tok_spec, pspecs, hspec, P()),
        out_specs=(P(), act_spec, pspecs, hspec,
                   extras_specs if has_aux else ()),
        check_vma=False,
    )(h_mb, pos_mb, seg_mb, lab_mb, stacked_params, head_params, scale_in)
    if has_aux:
        return loss, dh.reshape(B, S, H), gl, gh, ex
    return loss, dh.reshape(B, S, H), gl, gh


def interleave_layer_order(num_layers: int, num_devices: int, virtual: int):
    """Row permutation putting stage s = ℓ // chunk on device s % P under
    contiguous pp sharding of dim 0: device p's rows become its V stage
    chunks in v order. Returns (perm, inv_perm) index arrays."""
    import numpy as np

    S = num_devices * virtual
    assert num_layers % S == 0, (num_layers, S)
    chunk = num_layers // S
    order = []
    for p in range(num_devices):
        for v in range(virtual):
            s = v * num_devices + p
            order.extend(range(s * chunk, (s + 1) * chunk))
    perm = np.asarray(order, np.int64)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(num_layers)
    return perm, inv


def pipeline_train_interleaved(
    h: jnp.ndarray,            # (B, S, H) embedded activations (global)
    positions: jnp.ndarray,
    segment_ids: jnp.ndarray,
    labels: jnp.ndarray,
    stacked_params: Any,       # leaves (L, ...), L % (pp·virtual) == 0
    layer_fn: Callable,
    head_params: Any,
    head_loss_fn: Callable,
    mesh_ctx: MeshContext,
    num_microbatches: int,
    virtual: int,
    batch_axes: tuple = ("dp_replicate", "dp_shard", "ep"),
    param_logical_specs: Any = None,
    aux_scale: jnp.ndarray | None = None,
    extras_specs: Any = None,
) -> tuple:
    """Interleaved (virtual-stage) 1F1B: S = pp·virtual stages mapped
    cyclically onto the pp ring (stage s on device s % pp) — the Megatron
    interleaved schedule (reference: pipelining/functional.py:777
    ScheduleInterleaved1F1B). Same contract as `pipeline_train_1f1b`; the
    bubble shrinks ≈ V× because each pipeline hop carries 1/V of the layer
    work. Layer stacks are row-permuted so contiguous pp sharding gives each
    device its V stage chunks (`interleave_layer_order`); returned layer
    grads are un-permuted back to natural order.

    KNOWN COST: the permute/unpermute pair reshards the layer stack across
    pp every step (two all-to-alls). Storing params in permuted order for
    the whole run (one-time setup permutation) removes it; so would folding
    the non-interleaved 1F1B into this implementation as the V=1 case —
    both are staged follow-ups.
    """
    pp = mesh_ctx.sizes["pp"]
    B, Sq, H = h.shape
    M = num_microbatches
    V = virtual
    Svirt = pp * V
    has_aux = aux_scale is not None
    _check_microbatch_split(B, M, mesh_ctx, batch_axes)
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    assert L % Svirt == 0, f"{L} layers not divisible by pp*virtual={Svirt}"
    chunk = L // Svirt
    fwd_tab, bwd_tab = interleaved_1f1b_tables(M, pp, V)
    T = fwd_tab.shape[0]
    logger.info(
        "pipeline(interleaved-1f1b): pp=%d V=%d M=%d ticks=%d",
        pp, V, M, T,
    )

    perm, inv = interleave_layer_order(L, pp, V)
    params_perm = jax.tree.map(lambda x: x[perm], stacked_params)

    h_mb = h.reshape(M, B // M, Sq, H)
    pos_mb = positions.reshape(M, B // M, Sq)
    seg_mb = segment_ids.reshape(M, B // M, Sq)
    lab_mb = labels.reshape(M, B // M, Sq)
    K = min(Svirt, M)  # stash depth: in-flight per stage ≤ Svirt, consecutive
    scale_in = jnp.asarray(aux_scale if has_aux else 0.0, jnp.float32)

    def run(h_mb, pos_mb, seg_mb, lab_mb, params_local, head_local, scale):
        p_idx = lax.axis_index("pp")
        P = lax.axis_size("pp")
        ftab = jnp.asarray(fwd_tab)
        btab = jnp.asarray(bwd_tab)

        def chunk_params(v):
            return jax.tree.map(
                lambda x: lax.dynamic_slice_in_dim(x, v * chunk, chunk, 0),
                params_local,
            )

        def chunk_scan(x, cparams, pos, seg):
            """One virtual stage's layer scan → (y, aux_sum, extras)."""
            if has_aux:
                def body(c, lp):
                    y, a, e = layer_fn(c, lp, pos, seg)
                    return y, (a, e)

                y, (auxs, extras) = lax.scan(body, x, cparams)
                return y, jnp.sum(auxs).astype(jnp.float32), extras

            def body(c, lp):
                return layer_fn(c, lp, pos, seg), None

            y, _ = lax.scan(body, x, cparams)
            return y, jnp.float32(0.0), ()

        def stage(x, v, pos, seg):
            return chunk_scan(x, chunk_params(v), pos, seg)[0]

        def full_bwd(x, v, head, pos, seg, lab, dy, is_last):
            # chunk_scan OUTSIDE the is_last cond — collectives must be
            # rank-uniform (see pipeline_train_1f1b.full_bwd)
            def fwd(xx, pp_, hh_):
                y, aux, ex = chunk_scan(xx, pp_, pos, seg)
                sa = aux * scale
                s = lax.cond(
                    is_last,
                    lambda yy, hh: head_loss_fn(yy, hh, lab).astype(jnp.float32),
                    lambda yy, hh: jnp.vdot(
                        yy.astype(jnp.float32), dy.astype(jnp.float32)
                    ),
                    y, hh_,
                ) + sa
                return s, (jnp.where(is_last, s, sa), ex)

            out, vjp, (rep, ex) = jax.vjp(
                fwd, x, chunk_params(v), head, has_aux=True
            )
            dx, dparams, dhead = vjp(jnp.ones((), out.dtype))
            return rep, dx, dparams, dhead, ex

        zeros_g = jax.tree.map(jnp.zeros_like, params_local)
        zeros_h = jax.tree.map(jnp.zeros_like, head_local)
        stash0 = jnp.zeros((V, K) + h_mb.shape[1:], h_mb.dtype)
        # extras accumulate per LOCAL layer row (V·chunk rows, permuted
        # order — un-permuted with the grads outside)
        ex0 = jax.tree.map(
            lambda s: jnp.zeros((V * chunk,) + s.shape[1:], s.dtype),
            jax.eval_shape(
                lambda p: chunk_scan(h_mb[0], p, pos_mb[0], seg_mb[0])[2],
                chunk_params(0),
            ),
        )

        def decode(a):
            return a // M, a % M  # (vstage, microbatch); a < 0 → idle

        def tick(carry, t):
            (fstream, bstream, fstash, bstash, stash,
             gacc, hacc, dh_acc, loss_acc, ex_acc) = carry
            fa = jnp.take(ftab[t], p_idx)
            ba = jnp.take(btab[t], p_idx)

            # ---- bank arrivals (sent at t-1 by ring neighbors) ----
            prev_t = jnp.maximum(t - 1, 0)
            fa_prev = jnp.take(ftab[prev_t], (p_idx - 1) % P)
            v_prev, m_prev = decode(jnp.maximum(fa_prev, 0))
            v_recv = v_prev + jnp.where(p_idx == 0, 1, 0)
            f_ok = jnp.logical_and(t > 0, fa_prev >= 0)
            # stage Svirt-1's fwd output has no consumer; stage index of the
            # sender is v_prev*P + (p_idx-1)%P — drop when it was the last
            s_prev = v_prev * P + (p_idx - 1) % P
            f_ok = jnp.logical_and(f_ok, s_prev < Svirt - 1)
            f_ok = jnp.logical_and(f_ok, v_recv < V)
            fstash = jnp.where(
                f_ok,
                lax.dynamic_update_index_in_dim(
                    fstash,
                    lax.dynamic_update_index_in_dim(
                        jnp.take(fstash, jnp.clip(v_recv, 0, V - 1), axis=0),
                        fstream, m_prev % K, 0,
                    ),
                    jnp.clip(v_recv, 0, V - 1), 0,
                ),
                fstash,
            )
            ba_prev = jnp.take(btab[prev_t], (p_idx + 1) % P)
            vb_prev, mb_prev = decode(jnp.maximum(ba_prev, 0))
            vb_recv = vb_prev - jnp.where(p_idx == P - 1, 1, 0)
            s_bprev = vb_prev * P + (p_idx + 1) % P
            b_ok = jnp.logical_and(t > 0, ba_prev >= 0)
            b_ok = jnp.logical_and(b_ok, s_bprev > 0)
            b_ok = jnp.logical_and(b_ok, vb_recv >= 0)
            bstash = jnp.where(
                b_ok,
                lax.dynamic_update_index_in_dim(
                    bstash,
                    lax.dynamic_update_index_in_dim(
                        jnp.take(bstash, jnp.clip(vb_recv, 0, V - 1), axis=0),
                        bstream, mb_prev % K, 0,
                    ),
                    jnp.clip(vb_recv, 0, V - 1), 0,
                ),
                bstash,
            )

            # ---- forward slot ----
            vf, mf = decode(jnp.maximum(fa, 0))
            first_stage = jnp.logical_and(vf == 0, p_idx == 0)
            x_in = jnp.where(
                first_stage, h_mb[mf],
                jnp.take(fstash, vf, axis=0)[mf % K],
            )
            stash = jnp.where(
                fa >= 0,
                lax.dynamic_update_index_in_dim(
                    stash,
                    lax.dynamic_update_index_in_dim(
                        jnp.take(stash, vf, axis=0), x_in, mf % K, 0
                    ),
                    vf, 0,
                ),
                stash,
            )
            y = stage(x_in, vf, pos_mb[mf], seg_mb[mf])
            fout = jnp.where(fa >= 0, y, jnp.zeros_like(y))

            # ---- backward slot ----
            vb, mb = decode(jnp.maximum(ba, 0))
            x_b = jnp.take(stash, vb, axis=0)[mb % K]
            is_last = jnp.logical_and(vb == V - 1, p_idx == P - 1)
            loss_i, dx, dparams, dhead, ex = full_bwd(
                x_b, vb, head_local, pos_mb[mb], seg_mb[mb], lab_mb[mb],
                jnp.take(bstash, vb, axis=0)[mb % K], is_last,
            )
            do_b = ba >= 0
            gacc = jax.tree.map(
                lambda a, g: jnp.where(
                    do_b,
                    lax.dynamic_update_slice_in_dim(
                        a,
                        lax.dynamic_slice_in_dim(a, vb * chunk, chunk, 0) + g,
                        vb * chunk, 0,
                    ),
                    a,
                ),
                gacc, dparams,
            )
            ex_acc = jax.tree.map(
                lambda a, e: jnp.where(
                    do_b,
                    lax.dynamic_update_slice_in_dim(
                        a,
                        lax.dynamic_slice_in_dim(a, vb * chunk, chunk, 0) + e,
                        vb * chunk, 0,
                    ),
                    a,
                ),
                ex_acc, ex,
            )
            hacc = jax.tree.map(
                lambda a, g: a + jnp.where(do_b, g, jnp.zeros_like(g)), hacc, dhead
            )
            dh_acc = jnp.where(
                jnp.logical_and(do_b, jnp.logical_and(vb == 0, p_idx == 0)),
                lax.dynamic_update_index_in_dim(dh_acc, dx, mb, 0),
                dh_acc,
            )
            loss_acc = loss_acc + jnp.where(do_b, loss_i, 0.0)

            fwd_perm = [(i, (i + 1) % P) for i in range(P)]
            bwd_perm = [((i + 1) % P, i) for i in range(P)]
            fstream = lax.ppermute(fout, "pp", fwd_perm)
            bout = jnp.where(do_b, dx, jnp.zeros_like(dx))
            bstream = lax.ppermute(bout, "pp", bwd_perm)
            return (
                fstream, bstream, fstash, bstash, stash,
                gacc, hacc, dh_acc, loss_acc, ex_acc,
            ), None

        carry0 = (
            jnp.zeros_like(h_mb[0]),
            jnp.zeros_like(h_mb[0]),
            stash0, stash0, stash0,
            zeros_g, zeros_h,
            jnp.zeros_like(h_mb),
            jnp.zeros((), jnp.float32),
            ex0,
        )
        (_, _, _, _, _, gacc, hacc, dh_acc, loss_acc, ex_acc), _ = lax.scan(
            tick, carry0, jnp.arange(T)
        )
        data_axes = tuple(batch_axes) + ("cp",)
        gacc = jax.tree.map(
            lambda g, s: lax.psum(g, _grad_reduce_axes(s, data_axes)),
            gacc, pspecs,
        )
        hacc = jax.tree.map(lambda g: lax.psum(g, data_axes + ("pp",)), hacc)
        dh_acc = lax.psum(dh_acc, "pp")
        loss_acc = lax.psum(loss_acc, data_axes + ("pp",))
        ex_acc = jax.tree.map(lambda e: lax.psum(e, data_axes), ex_acc)
        return loss_acc, dh_acc, gacc, hacc, ex_acc

    act_spec = P(None, batch_axes, "cp", None)
    tok_spec = P(None, batch_axes, "cp")
    pspecs = _param_specs_pp(params_perm, param_logical_specs)
    hspec = jax.tree.map(lambda x: P(*([None] * x.ndim)), head_params)
    loss, dh, gl, gh, ex = jax.shard_map(
        run,
        mesh=mesh_ctx.mesh,
        in_specs=(act_spec, tok_spec, tok_spec, tok_spec, pspecs, hspec, P()),
        out_specs=(P(), act_spec, pspecs, hspec,
                   extras_specs if has_aux else ()),
        check_vma=False,
    )(h_mb, pos_mb, seg_mb, lab_mb, params_perm, head_params, scale_in)
    gl = jax.tree.map(lambda x: x[inv], gl)  # back to natural layer order
    if has_aux:
        # extras rows follow the permuted layer order like the grads
        ex = jax.tree.map(lambda x: x[inv], ex)
        return loss, dh.reshape(B, Sq, H), gl, gh, ex
    return loss, dh.reshape(B, Sq, H), gl, gh


#: logical param axes that stay sharded inside the pipeline shard_map;
#: everything else (fsdp/embed dims) is gathered at the boundary — the
#: per-step FSDP-unshard analog. `expert` stays on ep so each pipeline
#: stage's MoE dispatch exchanges tokens over its own ragged A2A step.
_PP_MANUAL_AXES = {
    "layers": "pp", "heads": "tp", "kv_heads": "tp", "mlp": "tp",
    "expert": "ep",
}


def _grad_reduce_axes(spec, data_axes: tuple) -> tuple:
    """Data axes to psum a param grad over inside the pipeline shard_map:
    every data axis the leaf is NOT sharded on. An ep-sharded expert slice
    already holds its complete grad — every token routed to its experts
    arrived through the A2A — so psum over ep would mix grads of DIFFERENT
    experts living at the same buffer offset on different ranks."""
    named = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            named.update(entry)
        else:
            named.add(entry)
    return tuple(a for a in data_axes if a not in named)


def _param_specs_pp(stacked_params, logical=None):
    """Stacked-leaf in_specs: dim 0 on pp; tp dims kept when `logical`
    (a pytree of logical axis-name tuples, decoder param_specs style)."""
    if logical is None:
        return jax.tree.map(
            lambda x: P(*(["pp"] + [None] * (x.ndim - 1))), stacked_params
        )

    def one(spec):
        return P(*(_PP_MANUAL_AXES.get(ax) for ax in spec))

    return jax.tree.map(
        one, logical,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(a, (str, type(None))) for a in x),
    )
