"""Pipeline parallelism: GPipe-schedule microbatch streaming over the `pp`
mesh axis.

The analog of the reference's `AutoPipeline` on torch.distributed.pipelining
(reference: nemo_automodel/components/distributed/pipelining/
autopipeline.py:49, functional.py:98 layer-FQN splitting, :777 schedule
builder). TPU-native design — there is no runtime pipelining framework to
call; the schedule is compiled:

- Layer weights stay STACKED (L, ...) and shard dim 0 over `pp` (the
  logical `layers` axis maps to the pp mesh axis), so "splitting the model
  into stages" is a sharding annotation, not a graph surgery.
- The whole pipeline is one `shard_map`: each stage scans its local layer
  stack; activations hop stage→stage with `lax.ppermute` (ICI neighbor
  traffic, the p2p `send/recv` analog); a `lax.scan` over
  (num_microbatches + num_stages - 1) ticks realizes the GPipe schedule.
- Backward is the transposed program — autodiff of ppermute/scan gives the
  reverse schedule for free, with weight-grad psums over the data axes
  inserted by shard_map's transpose.
- Embedding / final-norm / loss run OUTSIDE the shard_map under plain GSPMD
  (they are dp/cp-sharded elementwise-ish work).

Round-1 scope: pure pp × dp (tp=1, cp=1 inside the pipeline); interleaved /
1F1B schedules and tp-in-pipeline come later. The bubble fraction is the
GPipe (P-1)/(M+P-1).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from automodel_tpu.distributed.mesh import MeshContext


def pipeline_layers(
    h: jnp.ndarray,            # (B, S, H) embedded activations (global)
    positions: jnp.ndarray,    # (B, S) int32
    segment_ids: jnp.ndarray,  # (B, S) int32
    stacked_params: Any,       # layer stack, leaves (L, ...), L % pp == 0
    layer_fn: Callable,        # (h, layer_params, positions, segment_ids) -> h
    mesh_ctx: MeshContext,
    num_microbatches: int,
    batch_axes: tuple = ("dp_replicate", "dp_shard", "ep"),
    remat_policy: str | None = "full",
) -> jnp.ndarray:
    """Run the stacked layers as a pp-staged pipeline; returns (B, S, H).

    positions/segment_ids travel with their microbatch through the ring so
    every stage masks with the right coordinates.
    """
    pp = mesh_ctx.sizes["pp"]
    if mesh_ctx.sizes["tp"] != 1 or mesh_ctx.sizes["cp"] != 1:
        raise NotImplementedError(
            "pipeline parallelism currently composes with dp/ep only "
            f"(got tp={mesh_ctx.sizes['tp']} cp={mesh_ctx.sizes['cp']})"
        )
    B, S, H = h.shape
    M = num_microbatches
    assert B % M == 0, f"batch {B} must divide into {M} microbatches"
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    assert L % pp == 0, f"{L} layers not divisible by pp={pp}"

    h_mb = h.reshape(M, B // M, S, H)
    pos_mb = positions.reshape(M, B // M, S)
    seg_mb = segment_ids.reshape(M, B // M, S)

    def run(h_mb, pos_mb, seg_mb, params_local):
        # inside shard_map: h_mb (M, B_loc, S, H); params leaves (L/pp, ...)
        p_idx = lax.axis_index("pp")
        n_stage = lax.axis_size("pp")
        T = M + n_stage - 1

        def apply_stage(x, pos, seg):
            from automodel_tpu.models.common.layers import maybe_remat

            def body(c, lp):
                return layer_fn(c, lp, pos, seg), None

            y, _ = lax.scan(maybe_remat(body, remat_policy), x, params_local)
            return y

        def tick(carry, t):
            (act, pos, seg), outputs = carry
            m = jnp.clip(t, 0, M - 1)
            is_first = p_idx == 0
            x = jnp.where(is_first, h_mb[m], act)
            pos = jnp.where(is_first, pos_mb[m], pos)
            seg = jnp.where(is_first, seg_mb[m], seg)
            y = apply_stage(x, pos, seg)
            out_idx = t - (n_stage - 1)
            write = jnp.logical_and(out_idx >= 0, p_idx == n_stage - 1)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(out_idx, 0, M - 1), 0
                ),
                lambda o: o,
                outputs,
            )
            perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
            stream = lax.ppermute((y, pos, seg), "pp", perm)
            return (stream, outputs), None

        init_stream = (jnp.zeros_like(h_mb[0]), pos_mb[0], seg_mb[0])
        (_, outputs), _ = lax.scan(
            tick, (init_stream, jnp.zeros_like(h_mb)), jnp.arange(T)
        )
        # only the last stage's buffer is real; make it consistent everywhere
        outputs = lax.psum(
            jnp.where(p_idx == n_stage - 1, outputs, jnp.zeros_like(outputs)), "pp"
        )
        return outputs

    act_spec = P(None, batch_axes, None, None)  # (M, B, S, H)
    tok_spec = P(None, batch_axes, None)
    out = jax.shard_map(
        run,
        mesh=mesh_ctx.mesh,
        in_specs=(act_spec, tok_spec, tok_spec, _param_specs_pp(stacked_params)),
        out_specs=act_spec,
        check_vma=False,
    )(h_mb, pos_mb, seg_mb, stacked_params)
    return out.reshape(B, S, H)


def _param_specs_pp(stacked_params):
    """Every stacked leaf: dim 0 on pp, everything else replicated in-map."""
    def one(x):
        return P(*(["pp"] + [None] * (x.ndim - 1)))

    return jax.tree.map(one, stacked_params)
