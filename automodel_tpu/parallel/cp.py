"""Context parallelism: ring attention + load-balanced sequence sharding.

The analog of the reference CP stack (reference: nemo_automodel/components/
distributed/context_parallel/sharder.py:15-49 `ContextParallelSharder`
closed-verb contract, :116 round-robin head/tail load balancing; TE ring
attention wiring moe/parallelizer.py:749-800). TPU-native design:

- The sequence dim of activations is sharded on the `cp` mesh axis (GSPMD).
- Attention runs inside a `shard_map` over the mesh: each cp rank holds its
  local q and rotates k/v blocks around the ring with `lax.ppermute`
  (ICI-neighbor traffic, the XLA analog of TE's p2p ring), merging partial
  results with a running online softmax — differentiable end-to-end, so the
  backward pass is the reverse ring for free.
- Causality is evaluated by POSITION, so any sequence layout works. The
  load-balanced layout is the reference's head/tail round-robin: the global
  sequence is permuted so cp rank r owns chunks (r, 2*cp-1-r), equalizing
  causal work across ranks; positions ride the permutation.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from automodel_tpu.distributed.mesh import MeshContext
from automodel_tpu.ops.attention import NEG_INF


# ---------------------------------------------------------------------------
# load-balanced layout (reference: sharder.py:116-143)
# ---------------------------------------------------------------------------
def load_balanced_permutation(seq_len: int, cp_size: int) -> np.ndarray:
    """perm[i] = global index of the token placed at layout slot i.

    Rank r's contiguous slice [r*S/cp, (r+1)*S/cp) holds global chunks
    (r, 2*cp-1-r), so every rank sees an equal mix of early (cheap) and late
    (expensive) causal positions.
    """
    assert seq_len % (2 * cp_size) == 0, (seq_len, cp_size)
    chunk = seq_len // (2 * cp_size)
    order = []
    for r in range(cp_size):
        order.append(np.arange(r * chunk, (r + 1) * chunk))
        hi = 2 * cp_size - 1 - r
        order.append(np.arange(hi * chunk, (hi + 1) * chunk))
    return np.concatenate(order)


@dataclasses.dataclass
class ContextParallelSharder:
    """Permutes packed batches into the load-balanced CP layout.

    Closed-verb contract mirroring the reference (sharder.py:15-49):
    `shard_batch` reorders the sequence dim and attaches positions;
    `local_token_global_indices` exposes the layout coordinate.
    """

    cp_size: int
    load_balanced: bool = True
    seq_keys: tuple = ("input_ids", "labels", "positions", "segment_ids", "loss_mask")

    def permutation(self, seq_len: int) -> np.ndarray:
        if self.cp_size == 1 or not self.load_balanced:
            return np.arange(seq_len)
        return load_balanced_permutation(seq_len, self.cp_size)

    def shard_batch(self, batch: dict) -> dict:
        seq_len = batch["input_ids"].shape[-1]
        perm = self.permutation(seq_len)
        if "positions" not in batch:
            batch = {**batch, "positions": np.broadcast_to(
                np.arange(seq_len, dtype=np.int32), batch["input_ids"].shape
            )}
        out = {}
        for k, v in batch.items():
            if k in self.seq_keys and getattr(v, "ndim", 0) >= 2 and v.shape[-1] == seq_len:
                out[k] = np.asarray(v)[..., perm]
            else:
                out[k] = v
        return out

    def local_token_global_indices(self, seq_len: int, rank: int) -> np.ndarray:
        perm = self.permutation(seq_len)
        local = seq_len // self.cp_size
        return perm[rank * local : (rank + 1) * local]


# ---------------------------------------------------------------------------
# per-document (blockdiag) CP layout: whole documents per rank → NO exchange
# ---------------------------------------------------------------------------
def document_pack_permutation(segment_row: np.ndarray, cp_size: int) -> np.ndarray:
    """perm[i] = source index of the token placed at layout slot i, packing
    WHOLE documents onto cp ranks (first-fit decreasing by length).

    The TPU-native answer to the reference's blockdiag_cp exchange
    (reference: distributed/blockdiag_cp/exchange.py — differentiable
    all-gather / left-halo / a2av collectives restricted to same-document
    blocks): with packed attention already block-diagonal per document,
    placing each document entirely on one rank makes every key a query
    needs LOCAL — the per-document exchange collapses to none at all.
    Raises when a document exceeds the per-rank capacity S/cp (those need
    the ring layout, which handles any span)."""
    S = segment_row.shape[0]
    assert S % cp_size == 0, (S, cp_size)
    cap = S // cp_size
    # contiguous document spans (packing emits docs back-to-back);
    # vectorized — this runs per row per batch in the host data path
    cuts = (np.flatnonzero(np.diff(segment_row)) + 1).tolist()
    bounds = [0] + cuts + [S]
    docs = [(bounds[j], bounds[j + 1]) for j in range(len(bounds) - 1)]
    # capacity-aligned packing (datasets/packing.py align=S/cp): no doc
    # crosses a rank boundary already → identity layout, nothing to move
    if all(lo // cap == (hi - 1) // cap for lo, hi in docs):
        return np.arange(S)
    too_big = [d for d in docs if d[1] - d[0] > cap]
    if too_big:
        raise ValueError(
            f"blockdiag CP: document of {too_big[0][1] - too_big[0][0]} tokens "
            f"exceeds the per-rank capacity {cap} (= seq {S} / cp {cp_size}); "
            "use distributed.cp_layout: balanced (the ring handles documents "
            "of any span)"
        )
    loads = [0] * cp_size
    assign: list[list[tuple]] = [[] for _ in range(cp_size)]
    for d in sorted(docs, key=lambda d: d[0] - d[1]):  # longest first
        r = min(
            (r for r in range(cp_size) if loads[r] + (d[1] - d[0]) <= cap),
            key=lambda r: loads[r],
            default=None,
        )
        if r is None:
            raise ValueError(
                f"blockdiag CP: documents do not fit cp={cp_size} ranks of "
                f"capacity {cap} (first-fit-decreasing overflow); repack with "
                "a multiple-of-capacity target or use cp_layout: balanced"
            )
        assign[r].append(d)
        loads[r] += d[1] - d[0]
    perm = np.empty(S, np.int64)
    i = 0
    for r in range(cp_size):
        for lo, hi in sorted(assign[r]):  # preserve order within the rank
            perm[i : i + hi - lo] = np.arange(lo, hi)
            i += hi - lo
    assert i == S  # capacities sum to S, so every token lands exactly once
    return perm


@dataclasses.dataclass
class BlockDiagContextParallelSharder:
    """Per-document CP sharder: permutes each packed row so whole documents
    land on single cp ranks (document_pack_permutation above); positions /
    labels / segment ids ride the same per-row permutation. Attention then
    runs LOCAL per shard (`cp_blockdiag` on the model config) — zero ring
    steps. Requires packed batches (segment_ids) whose documents fit S/cp."""

    cp_size: int
    seq_keys: tuple = ("input_ids", "labels", "positions", "segment_ids", "loss_mask")

    def shard_batch(self, batch: dict) -> dict:
        if "segment_ids" not in batch:
            raise ValueError(
                "blockdiag CP needs packed batches with segment_ids; use a "
                "packing dataset or distributed.cp_layout: balanced"
            )
        seg = np.asarray(batch["segment_ids"])
        seq_len = seg.shape[-1]
        flat = seg.reshape(-1, seq_len)
        perms = np.stack([
            document_pack_permutation(row, self.cp_size) for row in flat
        ]).reshape(seg.shape)
        if "positions" not in batch:
            batch = {**batch, "positions": np.broadcast_to(
                np.arange(seq_len, dtype=np.int32), batch["input_ids"].shape
            )}
        out = {}
        for k, v in batch.items():
            if k in self.seq_keys and getattr(v, "ndim", 0) >= 2 and v.shape[-1] == seq_len:
                out[k] = np.take_along_axis(np.asarray(v), perms, axis=-1)
            else:
                out[k] = v
        return out


def _cp_shard_map_attention(inner_fn, mesh_ctx, q, k, v, positions,
                            segment_ids, sinks):
    """Shared shard_map wrapper for the CP attention variants: batch on the
    data axes, sequence on cp, heads on tp; sinks (per-q-head) ride the tp
    axis. `inner_fn(q, k, v, positions, segment_ids, sinks=None)` runs
    per-shard."""
    batch = ("dp_replicate", "dp_shard", "ep")
    qkv_spec = P(batch, "cp", "tp", None)
    tok_spec = P(batch, "cp")
    in_specs = [qkv_spec, qkv_spec, qkv_spec, tok_spec, tok_spec]
    args = [q, k, v, positions, segment_ids]
    if sinks is not None:
        in_specs.append(P("tp"))
        args.append(sinks)
    return jax.shard_map(
        inner_fn,
        mesh=mesh_ctx.mesh,
        in_specs=tuple(in_specs),
        out_specs=qkv_spec,
        check_vma=False,
    )(*args)


def local_cp_attention(
    q, k, v,
    positions, segment_ids,
    mesh_ctx: MeshContext,
    *,
    causal: bool = True,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
    sinks=None,
    attn_impl: str = "auto",
):
    """Blockdiag-CP attention: every document is rank-local (the sharder's
    contract), so attention is one LOCAL flash per cp shard — no ppermute
    ring, no exchange. segment/position masking inside the shard keeps
    cross-document isolation identical to the ring's."""
    from automodel_tpu.ops.attention import dot_product_attention

    if segment_ids is None:
        # zero-segment defaulting (the ring's behavior) would silently cut
        # a genuinely rank-spanning sequence at shard boundaries here —
        # the local path is only valid under the per-document contract
        raise ValueError(
            "blockdiag CP local attention requires packed segment_ids "
            "(every document rank-local); got none — use the ring layout "
            "for unpacked sequences"
        )

    def fn(q, k, v, positions, segment_ids, sinks=None):
        return dot_product_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            positions=positions, sliding_window=sliding_window,
            logits_soft_cap=logits_soft_cap, scale=scale,
            sinks=sinks, impl=attn_impl,
        )

    return _cp_shard_map_attention(
        fn, mesh_ctx, q, k, v, positions, segment_ids, sinks
    )


# ---------------------------------------------------------------------------
# ring attention (inside shard_map)
# ---------------------------------------------------------------------------
def _partial_attention_xla(q, k, v, qpos, kpos, qseg, kseg, *, scale, soft_cap, window, causal):
    """One XLA ring step: normalized partial out + lse of local q vs a
    visiting kv block.

    Returns (o (B,S,Hq,Dv) normalized fp32, lse (B,Hq,S) fp32; NEG_INF for
    rows with no unmasked kv). Shapes: q (B,S,Hq,D); k,v (B,T,Hkv,D).
    """
    B, S, Hq, D = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    qg = q.reshape(B, S, Hkv, G, D)
    s = jnp.einsum("bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32) * scale
    if soft_cap is not None:
        s = soft_cap * jnp.tanh(s / soft_cap)
    mask = jnp.ones((B, S, T), bool)
    if causal:
        mask = jnp.logical_and(mask, qpos[:, :, None] >= kpos[:, None, :])
    if window is not None:
        mask = jnp.logical_and(mask, qpos[:, :, None] - kpos[:, None, :] < window)
        if not causal:
            # bidirectional local attention: two-sided window (matches the
            # flash kernel and ops/attention.py oracle)
            mask = jnp.logical_and(mask, kpos[:, None, :] - qpos[:, :, None] < window)
    mask = jnp.logical_and(mask, qseg[:, :, None] == kseg[:, None, :])
    s = jnp.where(mask[:, None, None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # (B,Hkv,G,S)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask[:, None, None, :, :], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bkgst,btkd->bskgd", p.astype(v.dtype), v).astype(jnp.float32)
    o = o.reshape(B, S, Hq, v.shape[-1])
    l_safe = jnp.where(l == 0.0, 1.0, l)
    lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe)).reshape(B, Hq, S)
    o = o / jnp.moveaxis(l_safe.reshape(B, Hq, S), 1, 2)[..., None]
    return o, lse


def _flash_ring_ok(q, k) -> bool:
    from automodel_tpu.ops.pallas.flash_attention import _pick_block

    S, T = q.shape[1], k.shape[1]
    return (
        _pick_block(S, 512) > 0
        and _pick_block(T, 512) > 0
        and q.shape[2] % k.shape[2] == 0
        and q.shape[-1] == k.shape[-1]
    )


def ring_attention(
    q, k, v,
    positions, segment_ids,
    *,
    axis_name: str = "cp",
    causal: bool = True,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
    sinks=None,
    attn_impl: str = "auto",
):
    """Ring attention over `axis_name`; call INSIDE shard_map.

    All inputs are local shards: q/k/v (B, S_loc, H, D); positions and
    segment_ids (B, S_loc) in GLOBAL coordinates (survive any layout).

    Each step computes local-q × visiting-kv attention — through the Pallas
    flash kernel in position-causal mode when shapes allow (reference: TE ring
    wiring, moe/parallelizer.py:749-800), else the XLA oracle — and merges
    (out, lse) partials with a running logsumexp. The merge is plain JAX, so
    the whole ring differentiates through the flash kernel's lse-aware VJP.
    gpt-oss sinks join once at the end: out *= sigmoid(lse_final - sink).
    """
    B, S, Hq, D = q.shape
    Dv = v.shape[-1]
    scale = scale if scale is not None else D ** -0.5
    cp = lax.axis_size(axis_name)

    if segment_ids is None:
        segment_ids = jnp.zeros((B, S), jnp.int32)

    use_flash = attn_impl in ("auto", "flash") and _flash_ring_ok(q, k)
    if use_flash:
        from automodel_tpu.ops.pallas.flash_attention import flash_attention

        def partial_step(k_blk, v_blk, kpos, kseg):
            o, lse = flash_attention(
                q, k_blk, v_blk,
                causal=causal,
                positions=positions, segment_ids=segment_ids,
                kv_positions=kpos, kv_segment_ids=kseg,
                sliding_window=sliding_window,
                logits_soft_cap=logits_soft_cap,
                scale=scale, return_lse=True,
            )
            return o.astype(jnp.float32), lse
    else:
        def partial_step(k_blk, v_blk, kpos, kseg):
            return _partial_attention_xla(
                q, k_blk, v_blk, positions, kpos, segment_ids, kseg,
                scale=scale, soft_cap=logits_soft_cap,
                window=sliding_window, causal=causal,
            )

    def step(carry, _):
        o_acc, lse_acc, kv = carry
        k_blk, v_blk, kpos, kseg = kv
        o_i, lse_i = partial_step(k_blk, v_blk, kpos, kseg)
        lse_new = jnp.logaddexp(lse_acc, lse_i)
        w_old = jnp.exp(lse_acc - lse_new)       # (B,Hq,S)
        w_new = jnp.exp(lse_i - lse_new)
        to_bshd = lambda x: jnp.moveaxis(x, 1, 2)[..., None]
        o_acc = o_acc * to_bshd(w_old) + o_i * to_bshd(w_new)
        kv = lax.ppermute(
            kv, axis_name, [(i, (i + 1) % cp) for i in range(cp)]
        )
        return (o_acc, lse_new, kv), None

    o0 = jnp.zeros((B, S, Hq, Dv), jnp.float32)
    lse0 = jnp.full((B, Hq, S), NEG_INF, jnp.float32)
    kv0 = (k, v, positions, segment_ids)
    (o_f, lse_f, _), _ = lax.scan(step, (o0, lse0, kv0), None, length=cp)

    if sinks is not None:
        # the sink joins the global softmax denominator exactly once
        sig = jax.nn.sigmoid(lse_f - sinks.astype(jnp.float32).reshape(1, Hq, 1))
        o_f = o_f * jnp.moveaxis(sig, 1, 2)[..., None]
    return o_f.astype(q.dtype)


def ring_dot_product_attention(
    q, k, v,
    positions, segment_ids,
    mesh_ctx: MeshContext,
    *,
    causal: bool = True,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
    sinks=None,
    attn_impl: str = "auto",
):
    """shard_map wrapper: GSPMD everywhere else, explicit ring on `cp`."""
    if segment_ids is None:
        segment_ids = jnp.zeros(positions.shape, jnp.int32)

    def fn(q, k, v, positions, segment_ids, sinks=None):
        return ring_attention(
            q, k, v, positions, segment_ids,
            axis_name="cp", causal=causal,
            sliding_window=sliding_window,
            logits_soft_cap=logits_soft_cap,
            scale=scale, sinks=sinks, attn_impl=attn_impl,
        )

    return _cp_shard_map_attention(
        fn, mesh_ctx, q, k, v, positions, segment_ids, sinks
    )
