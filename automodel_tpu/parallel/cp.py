"""Context parallelism: ring attention + load-balanced sequence sharding.

The analog of the reference CP stack (reference: nemo_automodel/components/
distributed/context_parallel/sharder.py:15-49 `ContextParallelSharder`
closed-verb contract, :116 round-robin head/tail load balancing; TE ring
attention wiring moe/parallelizer.py:749-800). TPU-native design:

- The sequence dim of activations is sharded on the `cp` mesh axis (GSPMD).
- Attention runs inside a `shard_map` over the mesh: each cp rank holds its
  local q and rotates k/v blocks around the ring with `lax.ppermute`
  (ICI-neighbor traffic, the XLA analog of TE's p2p ring), merging partial
  results with a running online softmax — differentiable end-to-end, so the
  backward pass is the reverse ring for free.
- Causality is evaluated by POSITION, so any sequence layout works. The
  load-balanced layout is the reference's head/tail round-robin: the global
  sequence is permuted so cp rank r owns chunks (r, 2*cp-1-r), equalizing
  causal work across ranks; positions ride the permutation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from automodel_tpu.distributed.mesh import MeshContext
from automodel_tpu.ops.attention import NEG_INF


# ---------------------------------------------------------------------------
# load-balanced layout (reference: sharder.py:116-143)
# ---------------------------------------------------------------------------
def load_balanced_permutation(seq_len: int, cp_size: int) -> np.ndarray:
    """perm[i] = global index of the token placed at layout slot i.

    Rank r's contiguous slice [r*S/cp, (r+1)*S/cp) holds global chunks
    (r, 2*cp-1-r), so every rank sees an equal mix of early (cheap) and late
    (expensive) causal positions.
    """
    assert seq_len % (2 * cp_size) == 0, (seq_len, cp_size)
    chunk = seq_len // (2 * cp_size)
    order = []
    for r in range(cp_size):
        order.append(np.arange(r * chunk, (r + 1) * chunk))
        hi = 2 * cp_size - 1 - r
        order.append(np.arange(hi * chunk, (hi + 1) * chunk))
    return np.concatenate(order)


@dataclasses.dataclass
class ContextParallelSharder:
    """Permutes packed batches into the load-balanced CP layout.

    Closed-verb contract mirroring the reference (sharder.py:15-49):
    `shard_batch` reorders the sequence dim and attaches positions;
    `local_token_global_indices` exposes the layout coordinate.
    """

    cp_size: int
    load_balanced: bool = True
    seq_keys: tuple = ("input_ids", "labels", "positions", "segment_ids", "loss_mask")

    def permutation(self, seq_len: int) -> np.ndarray:
        if self.cp_size == 1 or not self.load_balanced:
            return np.arange(seq_len)
        return load_balanced_permutation(seq_len, self.cp_size)

    def shard_batch(self, batch: dict) -> dict:
        seq_len = batch["input_ids"].shape[-1]
        perm = self.permutation(seq_len)
        if "positions" not in batch:
            batch = {**batch, "positions": np.broadcast_to(
                np.arange(seq_len, dtype=np.int32), batch["input_ids"].shape
            )}
        out = {}
        for k, v in batch.items():
            if k in self.seq_keys and getattr(v, "ndim", 0) >= 2 and v.shape[-1] == seq_len:
                out[k] = np.asarray(v)[..., perm]
            else:
                out[k] = v
        return out

    def local_token_global_indices(self, seq_len: int, rank: int) -> np.ndarray:
        perm = self.permutation(seq_len)
        local = seq_len // self.cp_size
        return perm[rank * local : (rank + 1) * local]


# ---------------------------------------------------------------------------
# ring attention (inside shard_map)
# ---------------------------------------------------------------------------
def _partial_attention(q, k, v, qpos, kpos, qseg, kseg, *, scale, soft_cap, window, causal):
    """One ring step: masked scores of local q vs a visiting kv block.

    Returns (m (B,Hq,S,1), l (B,Hq,S,1), o (B,S,Hq,D) un-normalized).
    Shapes: q (B,S,Hq,D); k,v (B,T,Hkv,D).
    """
    B, S, Hq, D = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    qg = q.reshape(B, S, Hkv, G, D)
    s = jnp.einsum("bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32) * scale
    if soft_cap is not None:
        s = soft_cap * jnp.tanh(s / soft_cap)
    mask = jnp.ones((B, S, T), bool)
    if causal:
        mask = jnp.logical_and(mask, qpos[:, :, None] >= kpos[:, None, :])
    if window is not None:
        mask = jnp.logical_and(mask, qpos[:, :, None] - kpos[:, None, :] < window)
    mask = jnp.logical_and(mask, qseg[:, :, None] == kseg[:, None, :])
    s = jnp.where(mask[:, None, None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # (B,Hkv,G,S)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask[:, None, None, :, :], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bkgst,btkd->bskgd", p.astype(v.dtype), v)
    return m, l, o.reshape(B, S, Hq, v.shape[-1])


def ring_attention(
    q, k, v,
    positions, segment_ids,
    *,
    axis_name: str = "cp",
    causal: bool = True,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
):
    """Ring attention over `axis_name`; call INSIDE shard_map.

    All inputs are local shards: q/k/v (B, S_loc, H, D); positions and
    segment_ids (B, S_loc) in GLOBAL coordinates (survive any layout).
    """
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    scale = scale if scale is not None else D ** -0.5
    cp = lax.axis_size(axis_name)

    if segment_ids is None:
        segment_ids = jnp.zeros((B, S), jnp.int32)

    def step(carry, _):
        m_acc, l_acc, o_acc, kv = carry
        k_blk, v_blk, kpos, kseg = kv
        m_i, l_i, o_i = _partial_attention(
            q, k_blk, v_blk, positions, kpos, segment_ids, kseg,
            scale=scale, soft_cap=logits_soft_cap, window=sliding_window, causal=causal,
        )
        m_new = jnp.maximum(m_acc, m_i)
        a_old = jnp.exp(m_acc - m_new)
        a_new = jnp.exp(m_i - m_new)
        l_acc = l_acc * a_old + l_i * a_new
        # scale factors broadcast (B,Hkv,G,S) → (B,S,Hq,1)
        def to_bshd(x):
            return jnp.moveaxis(x, -1, 1).reshape(B, S, Hq)[..., None]
        o_acc = o_acc * to_bshd(a_old) + o_i * to_bshd(a_new)
        kv = lax.ppermute(
            kv, axis_name, [(i, (i + 1) % cp) for i in range(cp)]
        )
        return (m_new, l_acc, o_acc, kv), None

    m0 = jnp.full((B, Hkv, G, S), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, S), jnp.float32)
    o0 = jnp.zeros((B, S, Hq, v.shape[-1]), jnp.float32)
    kv0 = (k, v, positions, segment_ids)
    (m_f, l_f, o_f, _), _ = lax.scan(step, (m0, l0, o0, kv0), None, length=cp)

    l_bshd = jnp.moveaxis(l_f, -1, 1).reshape(B, S, Hq)[..., None]
    l_safe = jnp.where(l_bshd == 0.0, 1.0, l_bshd)
    out = jnp.where(l_bshd == 0.0, 0.0, o_f / l_safe)
    return out.astype(q.dtype)


def ring_dot_product_attention(
    q, k, v,
    positions, segment_ids,
    mesh_ctx: MeshContext,
    *,
    causal: bool = True,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
):
    """shard_map wrapper: GSPMD everywhere else, explicit ring on `cp`."""
    batch = ("dp_replicate", "dp_shard", "ep")
    qkv_spec = P(batch, "cp", "tp", None)
    tok_spec = P(batch, "cp")

    if segment_ids is None:
        segment_ids = jnp.zeros(positions.shape, jnp.int32)

    fn = functools.partial(
        ring_attention,
        axis_name="cp",
        causal=causal,
        sliding_window=sliding_window,
        logits_soft_cap=logits_soft_cap,
        scale=scale,
    )
    return jax.shard_map(
        fn,
        mesh=mesh_ctx.mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, tok_spec, tok_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v, positions, segment_ids)
