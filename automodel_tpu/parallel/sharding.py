"""Logical-axis sharding rules — the TP-plan / FSDP2 analog.

The reference expresses parallelism as per-module DTensor plans
(reference: nemo_automodel/components/distributed/optimized_tp_plans.py,
parallelizer.py:2188 `fsdp2_strategy_parallelize`, :1058
`apply_fsdp2_sharding_recursively`). The TPU-native equivalent: every
parameter and activation carries a tuple of LOGICAL axis names, and a rule
table maps logical axes → mesh axes. One table change re-lays-out the whole
model — "parallelism is configuration" with zero model-code changes.

FSDP2's `fully_shard` ≙ mapping the designated fsdp logical axes onto
`dp_shard`; TP plans ≙ mapping `heads`/`mlp`/`vocab` onto `tp`; expert
parallelism ≙ mapping `expert` onto `ep`. XLA's GSPMD inserts the
all-gathers/reduce-scatters that FSDP2 performs imperatively.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from automodel_tpu.distributed.mesh import MeshAxisName, MeshContext

logger = logging.getLogger(__name__)

# A logical spec is a tuple of logical axis names (or None), one per dim.
LogicalSpec = tuple

#: Default rule table. First match wins per logical axis. Mesh axis entries
#: may be a single axis, a tuple, an alias from MeshAxisName.ALIASES, or None.
DEFAULT_RULES: tuple[tuple[str, Any], ...] = (
    # activations
    ("act_batch", "batch"),          # (dp_replicate, dp_shard, ep)
    ("act_seq", "cp"),               # context parallel shards the seq dim
    ("act_embed", None),
    ("act_heads", "tp"),             # attention activations shard on heads
    ("act_kv_heads", "tp"),
    ("act_mlp", "tp"),
    ("act_vocab", "tp"),
    ("act_expert", "ep"),
    # parameters — 2-D sharding: fsdp axis x tp axis
    ("vocab", "tp"),
    ("embed", "dp_shard"),           # the FSDP ("fully_shard") dim
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("head_dim", None),
    ("mlp", "tp"),
    ("expert", "ep"),
    ("expert_embed", "dp_shard"),    # FSDP dim inside expert weights
    ("expert_mlp", "tp"),
    # stacked-layer leading dim: sharded over pp = pipeline stage splitting
    # (a sharding annotation, not graph surgery — see parallel/pp.py)
    ("layers", "pp"),
    ("norm", None),
    # serving paged-KV pool (serving/kv_pages.py): pages replicate over the
    # data tier (page IDs are GLOBAL — the host allocator/scheduler/prefix
    # cache never know the mesh exists), while the per-page head dim shards
    # over tp: GQA pools partition KV heads, absorbed-MLA pools partition
    # the kv latent rank (heads share ONE latent, so the latent — the big
    # cached quantity — is the dim that halves HBM per chip)
    ("pages", None),
    ("mla_latent", "tp"),
)


@dataclasses.dataclass
class AxisRules:
    """Ordered (logical_axis → mesh axes) table with override support."""

    rules: tuple[tuple[str, Any], ...] = DEFAULT_RULES

    def with_overrides(self, *overrides: tuple[str, Any]) -> "AxisRules":
        return AxisRules(rules=tuple(overrides) + self.rules)

    def lookup(self, logical: str) -> Any:
        for name, mesh_axes in self.rules:
            if name == logical:
                return mesh_axes
        raise KeyError(f"No sharding rule for logical axis '{logical}'")

    def spec(self, logical_axes: Sequence[Any], mesh_ctx: MeshContext) -> PartitionSpec:
        """Logical spec → PartitionSpec, resolving aliases via the mesh.

        A mesh axis may be claimed by at most one dim of a given array;
        duplicates (e.g. `embed` and `mlp` both on `tp`) keep the first.
        """
        used: set[str] = set()
        parts: list = []
        for ax in logical_axes:
            if ax is None:
                parts.append(None)
                continue
            mesh_axes = mesh_ctx.resolve_axes(self.lookup(ax))
            mesh_axes = tuple(a for a in mesh_axes if a not in used)
            used.update(mesh_axes)
            if not mesh_axes:
                parts.append(None)
            elif len(mesh_axes) == 1:
                parts.append(mesh_axes[0])
            else:
                parts.append(tuple(mesh_axes))
        return PartitionSpec(*parts)


def logical_to_shardings(
    logical_specs: Any,
    mesh_ctx: MeshContext,
    rules: AxisRules | None = None,
    shapes: Any = None,
) -> Any:
    """Map a pytree of logical specs to NamedShardings.

    When `shapes` (matching pytree of array shapes) is given, dims whose size
    is not divisible by their assigned mesh-axes product fall back to
    replicated on that dim with a warning — the analog of the reference's
    head-count divisibility validation (parallelizer.py:1486).
    """
    rules = rules or AxisRules()
    mesh = mesh_ctx.mesh

    def one(spec, shape=None):
        pspec = rules.spec(spec, mesh_ctx)
        if shape is not None:
            pspec = _validate_divisibility(pspec, shape, mesh)
        return NamedSharding(mesh, pspec)

    if shapes is None:
        return jax.tree.map(one, logical_specs, is_leaf=_is_logical_spec)
    return jax.tree.map(one, logical_specs, shapes, is_leaf=_is_logical_spec)


def _validate_divisibility(pspec: PartitionSpec, shape, mesh: Mesh) -> PartitionSpec:
    parts = list(pspec)
    parts += [None] * (len(shape) - len(parts))
    out = []
    for dim, axes in zip(shape, parts):
        if axes is None:
            out.append(None)
            continue
        axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
        prod = math.prod(mesh.shape[a] for a in axes_t)
        if dim % prod != 0:
            logger.warning(
                "dim of size %d not divisible by mesh axes %s (=%d); replicating",
                dim, axes_t, prod,
            )
            out.append(None)
        else:
            out.append(axes)
    return PartitionSpec(*out)


def _is_logical_spec(x: Any) -> bool:
    return isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x)


def with_logical_constraint(x, logical_axes: Sequence[Any], mesh_ctx: MeshContext, rules: AxisRules | None = None):
    """`jax.lax.with_sharding_constraint` via logical axis names.

    The activation-sharding analog of DTensor's redistribute: used inside
    model code to pin intermediate layouts (e.g. after attention, re-shard
    tokens back to (batch, cp, None)).
    """
    rules = rules or AxisRules()
    spec = rules.spec(logical_axes, mesh_ctx)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh_ctx.mesh, spec))
