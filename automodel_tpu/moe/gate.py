"""MoE router / gate.

The analog of the reference `Gate`
(reference: nemo_automodel/components/moe/layers.py:212-610): softmax or
sigmoid scoring, DeepSeek group-limited top-k, aux loss (`_compute_aux_loss`
layers.py:548), aux-free bias balancing (`update_bias` layers.py:463), and
the deterministic `FakeBalancedGate` (layers.py:126) used by the benchmark
recipes so routing cost is measured without load-imbalance noise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig


def init_gate(cfg: MoEConfig, hidden_size: int, rng: jax.Array) -> dict:
    std = hidden_size ** -0.5
    params = {
        "weight": std * jax.random.truncated_normal(
            rng, -3.0, 3.0, (hidden_size, cfg.n_routed_experts)
        )
    }
    if cfg.router_bias:
        params["bias"] = jnp.zeros((cfg.n_routed_experts,))
    if cfg.gate_bias_update_speed > 0:
        # selection-only bias (not part of the autodiff graph semantics)
        params["e_score_bias"] = jnp.zeros((cfg.n_routed_experts,))
    return params


def gate_param_specs(cfg: MoEConfig) -> dict:
    specs = {"weight": ("embed", None)}
    if cfg.router_bias:
        specs["bias"] = (None,)
    if cfg.gate_bias_update_speed > 0:
        specs["e_score_bias"] = (None,)
    return specs


def _group_limited_mask(scores: jnp.ndarray, cfg: MoEConfig) -> jnp.ndarray:
    """DeepSeek group-limited routing: keep only experts inside the
    topk_groups best groups (group score = sum of its top-2 experts)."""
    T = scores.shape[0]
    E, G = cfg.n_routed_experts, cfg.n_groups
    grouped = scores.reshape(T, G, E // G)
    top2 = jax.lax.top_k(grouped, min(2, E // G))[0].sum(-1)  # (T, G)
    _, top_groups = jax.lax.top_k(top2, cfg.topk_groups)       # (T, topk_groups)
    group_mask = jnp.zeros((T, G), scores.dtype).at[
        jnp.arange(T)[:, None], top_groups
    ].set(1.0)
    return jnp.repeat(group_mask, E // G, axis=-1)  # (T, E)


def gate_forward(
    params: dict,
    cfg: MoEConfig,
    x: jnp.ndarray,  # (T, H)
    token_mask: jnp.ndarray | None = None,  # (T,) bool; False = pad/ignored
    forced_indices: jnp.ndarray | None = None,  # (T,K) — routing replay (R3)
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, dict]:
    """Route tokens. Returns (weights (T,K), indices (T,K), aux_loss, stats).

    `forced_indices` replays a previously captured top-k selection
    (reference: components/moe/router_replay.py — rollout/training routing
    mismatch in RL): only the DISCRETE selection is replayed; scores and
    weights are recomputed from the live router, so router gradients flow.
    Entries == E (the invalid slot from a masked capture) stay invalid.

    aux_loss is the switch-style load-balancing loss
    E * sum_e(fraction_tokens_e * mean_prob_e), matching the reference's
    `_compute_aux_loss` (layers.py:548); it is 0 when aux_loss_coeff == 0.
    NOTE: aux_loss is O(1) per layer — when combining with a sum-CE loss that
    is later divided by the global token count, multiply by that count first
    (see loss/utils.py `combine_losses`, the MoEAuxLossAutoScaler analog).

    Masked tokens (padding / ignored labels) are routed to the invalid
    expert index E, so they consume no capacity and are excluded from the
    aux-loss statistics (the reference threads token_mask the same way).
    """
    T, H = x.shape
    E, K = cfg.n_routed_experts, cfg.experts_per_token

    if cfg.fake_balanced_gate:
        # deterministic round-robin: token t → experts (tK, tK+1, …) mod E.
        # Same input ⇒ same routing, so remat recompute is consistent
        # (reference: models/common/utils.py:185-191).
        base = (jnp.arange(T)[:, None] * K + jnp.arange(K)[None, :]) % E
        weights = jnp.full((T, K), 1.0 / K, jnp.float32)
        stats = {
            "tokens_per_expert": jax.nn.one_hot(base, E, dtype=jnp.float32).sum((0, 1)),
            "mean_prob": jnp.full((E,), 1.0 / E, jnp.float32),
        }
        return weights, base.astype(jnp.int32), jnp.float32(0.0), stats

    logits = x.astype(jnp.float32) @ params["weight"].astype(jnp.float32)  # (T, E)
    if "bias" in params:
        logits = logits + params["bias"].astype(jnp.float32)
    if cfg.score_func == "softmax":
        scores = jax.nn.softmax(logits, axis=-1)
    elif cfg.score_func == "sigmoid":
        scores = jax.nn.sigmoid(logits)
    else:
        raise ValueError(f"Unknown score_func '{cfg.score_func}'")

    select_scores = scores
    if "e_score_bias" in params:
        select_scores = scores + jax.lax.stop_gradient(params["e_score_bias"])
    if cfg.n_groups > 1:
        gmask = _group_limited_mask(select_scores, cfg)
        select_scores = jnp.where(gmask > 0, select_scores, -jnp.inf)

    if forced_indices is not None:
        indices = jnp.clip(forced_indices.astype(jnp.int32), 0, E - 1)
        replay_invalid = forced_indices >= E
    else:
        _, indices = jax.lax.top_k(select_scores, K)      # (T, K)
        replay_invalid = None
    weights = jnp.take_along_axis(scores, indices, axis=-1)  # weight by raw score
    if replay_invalid is not None:
        weights = jnp.where(replay_invalid, 0.0, weights)
        indices = jnp.where(replay_invalid, E, indices)  # keep the invalid slot
    if cfg.norm_topk_prob:
        weights = weights / jnp.maximum(weights.sum(-1, keepdims=True), 1e-20)
    weights = weights * cfg.route_scale

    if token_mask is not None:
        tm = token_mask.astype(jnp.float32)
        indices = jnp.where(token_mask[:, None], indices, E)  # E = invalid slot
        weights = weights * tm[:, None]
        n_valid = jnp.maximum(tm.sum(), 1.0)
    else:
        tm = None
        n_valid = jnp.float32(T)

    # load-balance statistics (also feeds moe/metrics.py); one_hot of the
    # invalid index E is all-zero, so masked tokens drop out everywhere.
    one_hot = jax.nn.one_hot(indices, E, dtype=jnp.float32)  # (T, K, E)
    tokens_per_expert = one_hot.sum((0, 1))                  # (E,)
    fraction = tokens_per_expert / (n_valid * K)
    if tm is None:
        mean_prob = scores.mean(0)
    else:
        mean_prob = (scores * tm[:, None]).sum(0) / n_valid
    aux_loss = jnp.float32(cfg.aux_loss_coeff) * E * jnp.sum(fraction * mean_prob)
    stats = {"tokens_per_expert": tokens_per_expert, "mean_prob": mean_prob}
    return weights.astype(jnp.float32), indices.astype(jnp.int32), aux_loss, stats


def update_gate_bias(params: dict, cfg: MoEConfig, tokens_per_expert: jnp.ndarray) -> dict:
    """DeepSeek aux-free balancing (reference: layers.py:463 `update_bias`):
    raise the selection bias of under-loaded experts, lower over-loaded."""
    if "e_score_bias" not in params:
        return params
    err = tokens_per_expert.mean() - tokens_per_expert  # >0 → under-loaded
    new_bias = params["e_score_bias"] + cfg.gate_bias_update_speed * jnp.sign(err)
    return {**params, "e_score_bias": new_bias}
