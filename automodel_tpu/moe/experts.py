"""Expert compute + token dispatch.

The TPU-native replacement for the reference's experts/dispatcher stack
(reference: nemo_automodel/components/moe/experts.py:202 `GroupedExperts`,
:651 `GroupedExpertsDeepEP`; megatron/token_dispatcher.py:504
`MoEFlexTokenDispatcher`; megatron/fused_a2a.py DeepEP NVSHMEM all-to-all).

Design: capacity-based einsum dispatch — the GSPMD-native MoE pattern.
Routing produces a (tokens, experts, capacity) dispatch tensor; two einsums
move tokens to expert-major layout and back. When the expert dim is sharded
on the `ep` mesh axis and tokens on `batch`, XLA lowers the einsums to
exactly the all-to-all pair DeepEP implements by hand, riding ICI. Static
shapes (capacity padding) keep everything jit-compatible; overflow tokens
are dropped (capacity_factor controls headroom), matching Megatron-style
capacity dispatch semantics.

A sort-based dropless path (ragged grouped GEMM ≙ megablox gmm) is the
planned second dispatcher; this module keeps the dispatcher abstraction so
both share the gate and expert weights.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig

_EXPERT_ACT = {
    "silu": jax.nn.silu,
    "geglu": jax.nn.gelu,
    "quick_geglu": lambda x: x * jax.nn.sigmoid(1.702 * x),
    "relu2": lambda x: jnp.square(jax.nn.relu(x)),
}


def gated_combine(g, u, kind: str, limit: float = 7.0):
    """gate/up → MLP inner. "swigluoai" is gpt-oss's clamped variant:
    min(g,limit)·sigmoid(1.702·g)·(clip(u,±limit)+1); others are act(g)·u."""
    if kind == "swigluoai":
        g = jnp.minimum(g, limit)
        u = jnp.clip(u, -limit, limit)
        return g * jax.nn.sigmoid(1.702 * g) * (u + 1.0)
    return _EXPERT_ACT[kind](g) * u


def init_experts(cfg: MoEConfig, hidden_size: int, rng: jax.Array) -> dict:
    E, H, I = cfg.n_routed_experts, hidden_size, cfg.moe_intermediate_size
    k1, k2, k3 = jax.random.split(rng, 3)
    std_in, std_out = H ** -0.5, I ** -0.5
    params = {
        "up_proj": {"kernel": std_in * jax.random.truncated_normal(k2, -3, 3, (E, H, I))},
        "down_proj": {"kernel": std_out * jax.random.truncated_normal(k3, -3, 3, (E, I, H))},
    }
    if cfg.gated_experts:
        params["gate_proj"] = {
            "kernel": std_in * jax.random.truncated_normal(k1, -3, 3, (E, H, I))
        }
    if cfg.expert_bias:
        params["up_proj"]["bias"] = jnp.zeros((E, I))
        params["down_proj"]["bias"] = jnp.zeros((E, H))
        if cfg.gated_experts:
            params["gate_proj"]["bias"] = jnp.zeros((E, I))
    return params


def expert_param_specs(cfg: MoEConfig) -> dict:
    specs = {
        "up_proj": {"kernel": ("expert", "expert_embed", "expert_mlp")},
        "down_proj": {"kernel": ("expert", "expert_mlp", "expert_embed")},
    }
    if cfg.gated_experts:
        specs["gate_proj"] = {"kernel": ("expert", "expert_embed", "expert_mlp")}
    if cfg.expert_bias:
        specs["up_proj"]["bias"] = ("expert", "expert_mlp")
        specs["down_proj"]["bias"] = ("expert", "expert_embed")
        if cfg.gated_experts:
            specs["gate_proj"]["bias"] = ("expert", "expert_mlp")
    return specs


def compute_capacity(cfg: MoEConfig, num_tokens: int) -> int:
    per_expert = num_tokens * cfg.experts_per_token / cfg.n_routed_experts
    cap = int(per_expert * cfg.capacity_factor)
    return max(8, ((cap + 7) // 8) * 8)  # sublane-align


def dispatch_tensors(
    cfg: MoEConfig,
    indices: jnp.ndarray,  # (T, K) int32
    weights: jnp.ndarray,  # (T, K) f32
    capacity: int,
    dtype=jnp.float32,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Build the dispatch one-hot (T,E,C) and combine weights (T,E).

    Position of token t within expert e's buffer = number of earlier
    (token, slot) pairs routed to e — a cumsum over the flattened (T*K)
    routing order, matching Megatron's capacity dispatcher semantics.

    Memory note (the reference's DeepEP path never materializes per-slot
    buffers; this is the GSPMD formulation's cost): ONE (T,E,C) tensor in
    the COMPUTE dtype. The per-token combine weights factor as a (T,E)
    matrix — `experts_forward` fuses it into the combine einsum instead of
    materializing a second (T,E,C). For DSv3-scale expert counts prefer
    `dispatcher: dropless` (sort + ragged_dot, EP-capable), which has no
    (T,E,C) at all.
    """
    T, K = indices.shape
    E = cfg.n_routed_experts
    flat = indices.reshape(T * K)
    onehot = jax.nn.one_hot(flat, E, dtype=jnp.int32)          # (T*K, E)
    pos = jnp.cumsum(onehot, axis=0) - onehot                   # (T*K, E)
    pos_in_expert = jnp.sum(pos * onehot, axis=-1).reshape(T, K)
    keep = (pos_in_expert < capacity).astype(dtype)             # (T, K)

    # Accumulate per top-k slot so peak memory stays at one (T, E, C) tensor
    # (a (T*K, E, C) intermediate would be K× larger).
    dispatch = jnp.zeros((T, E, capacity), dtype)
    combine_w = jnp.zeros((T, E), jnp.float32)
    idx_tk = indices.reshape(T, K)
    for k in range(K):
        eh = jax.nn.one_hot(idx_tk[:, k], E, dtype=dtype)                # (T, E)
        ch = jax.nn.one_hot(pos_in_expert[:, k], capacity, dtype=dtype)
        kept_e = eh * keep[:, k : k + 1]
        dispatch = dispatch + kept_e[:, :, None] * ch[:, None, :]
        combine_w = combine_w + kept_e.astype(jnp.float32) * weights[:, k][:, None]
    return dispatch, combine_w


def experts_forward_dropless(
    params: dict,
    cfg: MoEConfig,
    x: jnp.ndarray,        # (T, H)
    weights: jnp.ndarray,  # (T, K)
    indices: jnp.ndarray,  # (T, K)
) -> jnp.ndarray:
    """Dropless sort-based dispatch + ragged grouped GEMM.

    The megablox/`GroupedExpertsDeepEP` analog (reference: experts.py:651):
    (token, slot) pairs are sorted by expert id, the three expert matmuls run
    as `lax.ragged_dot` over the per-expert group sizes (no capacity padding,
    no dropped tokens), and outputs scatter-add back into token order. Static
    shapes throughout (TK rows total), so jit-compatible.

    Scope: replicated or dp-sharded experts (ep=1) — ragged group sizes
    don't currently split across an `ep` axis under GSPMD; EP meshes use the
    capacity dispatcher.
    """
    T, H = x.shape
    K = cfg.experts_per_token
    E = cfg.n_routed_experts
    dtype = x.dtype

    flat_expert = indices.reshape(T * K)
    # stable sort groups rows by expert while keeping token order within
    sort_idx = jnp.argsort(flat_expert, stable=True)
    token_of = sort_idx // K
    expert_of = jnp.take(flat_expert, sort_idx)
    xs = jnp.take(x, token_of, axis=0)  # (TK, H)
    group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)

    # masked tokens carry the sentinel index E (see gate_forward) — clip once
    # for the bias gathers; their rows are zero-weighted in the combine anyway
    safe_expert = jnp.clip(expert_of, 0, E - 1)
    u = jax.lax.ragged_dot(xs, params["up_proj"]["kernel"].astype(dtype), group_sizes)
    if "bias" in params["up_proj"]:
        u = u + jnp.take(params["up_proj"]["bias"].astype(dtype), safe_expert, axis=0)
    if cfg.gated_experts:
        g = jax.lax.ragged_dot(xs, params["gate_proj"]["kernel"].astype(dtype), group_sizes)
        if "bias" in params["gate_proj"]:
            g = g + jnp.take(params["gate_proj"]["bias"].astype(dtype), safe_expert, axis=0)
        h_in = gated_combine(g, u, cfg.expert_activation, cfg.swiglu_limit)
    else:
        h_in = _EXPERT_ACT[cfg.expert_activation](u)
    y = jax.lax.ragged_dot(h_in, params["down_proj"]["kernel"].astype(dtype), group_sizes)
    if "bias" in params["down_proj"]:
        y = y + jnp.take(params["down_proj"]["bias"].astype(dtype), safe_expert, axis=0)

    w_sorted = jnp.take(weights.reshape(T * K), sort_idx, axis=0).astype(dtype)
    contrib = y * w_sorted[:, None]
    return jnp.zeros((T, H), dtype).at[token_of].add(contrib)


def _raw_ragged_a2a(x, out, in_off, send_sz, out_off, recv_sz, axis_name):
    """Seam over `lax.ragged_all_to_all` — tests monkeypatch this with a
    collective emulator because XLA:CPU has no ragged-all-to-all thunk."""
    from jax import lax

    return lax.ragged_all_to_all(
        x, out, in_off, send_sz, out_off, recv_sz, axis_name=axis_name
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _ragged_exchange(x, in_off, send_sz, out_off, recv_sz, recv_off,
                     back_out_off, out_rows, axis_name):
    """Differentiable ragged all-to-all (TPU): sends x's contiguous
    per-peer row chunks, returns an (out_rows, …) buffer with untouched rows
    zero. The VJP runs the REVERSE ragged exchange of the cotangents — the
    combine direction's metadata is exactly the dispatch direction's swapped.
    """
    out = jnp.zeros((out_rows,) + x.shape[1:], x.dtype)
    return _raw_ragged_a2a(x, out, in_off, send_sz, out_off, recv_sz, axis_name)


def _ragged_exchange_fwd(x, in_off, send_sz, out_off, recv_sz, recv_off,
                         back_out_off, out_rows, axis_name):
    out = _ragged_exchange(
        x, in_off, send_sz, out_off, recv_sz, recv_off, back_out_off,
        out_rows, axis_name,
    )
    return out, (x.shape[0], in_off, send_sz, out_off, recv_sz, recv_off,
                 back_out_off)


def _ragged_exchange_bwd(out_rows, axis_name, res, dout):
    n_in, in_off, send_sz, out_off, recv_sz, recv_off, back_out_off = res
    dx = jnp.zeros((n_in,) + dout.shape[1:], dout.dtype)
    dx = _raw_ragged_a2a(
        dout, dx, recv_off, recv_sz, back_out_off, send_sz, axis_name
    )
    return dx, None, None, None, None, None, None


_ragged_exchange.defvjp(_ragged_exchange_fwd, _ragged_exchange_bwd)


def _dropless_ep_local(params, cfg, x, weights, indices, *, axis_name, bucket,
                       ragged=False):
    """Per-shard body of the EP dropless dispatch; call INSIDE shard_map.

    The DeepEP-semantics analog (reference: moe/megatron/fused_a2a.py:139
    `fused_dispatch`, :238 `fused_combine`; token_dispatcher.py:504): tokens
    travel to the EP rank that owns their expert and come back, with NO
    capacity drops. Two exchange layouts:

    - ragged=True (TPU): `lax.ragged_all_to_all` ships exactly the routed
      rows — wire traffic proportional to actual tokens, DeepEP's defining
      property. Offsets ride a tiny (P,P) counts all_gather. The receive
      buffer stays worst-case sized (P·bucket — every token in the step
      could route here), but bytes on ICI are the ragged sizes.
    - ragged=False (CPU fallback / dryrun): a static (ep, bucket, H)
      all_to_all padded to the dropless worst case (XLA:CPU has no
      ragged-all-to-all).

    Layout invariant: rows sorted by global expert id are grouped by owner
    rank (experts are contiguous per rank), so one stable sort serves both
    the send bucketing and, on the receiver, the ragged_dot grouping.
    """
    from jax import lax

    T, H = x.shape
    K = cfg.experts_per_token
    E = cfg.n_routed_experts
    P = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    E_loc = E // P
    TK = T * K
    dtype = x.dtype

    flat_expert = indices.reshape(TK)                       # sentinel E = masked
    sort_idx = jnp.argsort(flat_expert, stable=True)
    expert_sorted = jnp.take(flat_expert, sort_idx)
    token_of = sort_idx // K
    xs = jnp.take(x, token_of, axis=0)                      # (TK, H) sorted rows

    counts_e = jnp.bincount(flat_expert, length=E).astype(jnp.int32)
    counts_peer = counts_e.reshape(P, E_loc).sum(-1)        # rows per dest rank
    offsets_peer = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts_peer)[:-1]]
    )

    if ragged:
        # C[j, i] = rows rank j sends to rank i (tiny (P,P) metadata gather)
        C = lax.all_gather(counts_peer, axis_name)          # (P, P)
        recv_sz = C[:, r]                                   # from each sender
        recv_off = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(recv_sz)[:-1]]
        )
        # where MY chunk lands on receiver i: after all senders j < r
        out_off = (jnp.cumsum(C, axis=0) - C)[r]            # (P,)
        # where my RETURN chunk lands on source i: at i's offsets_peer[r]
        OP = lax.all_gather(offsets_peer, axis_name)        # (P, P)
        back_out_off = OP[:, r]
        R = P * bucket

        recv_x = _ragged_exchange(
            xs, offsets_peer, counts_peer, out_off, recv_sz, recv_off,
            back_out_off, R, axis_name,
        )
        eid_out = jnp.full((R,), E, jnp.int32)
        recv_eid = _raw_ragged_a2a(
            expert_sorted.astype(jnp.int32), eid_out, offsets_peer,
            counts_peer, out_off, recv_sz, axis_name,
        )
        le = recv_eid - r * E_loc                           # local expert id
        recv_valid = (le >= 0) & (le < E_loc)
        valid_send = expert_sorted < E
    else:
        dest = jnp.minimum(expert_sorted // E_loc, P)       # sentinel → P (drop)
        slot = jnp.arange(TK, dtype=jnp.int32) - jnp.take(
            offsets_peer, jnp.minimum(dest, P - 1)
        )
        valid_send = (dest < P) & (slot < bucket)
        flat_pos = jnp.where(valid_send, dest * bucket + slot, P * bucket)

        send_x = jnp.zeros((P * bucket, H), dtype).at[flat_pos].set(xs, mode="drop")
        send_eid = jnp.full((P * bucket,), E, jnp.int32).at[flat_pos].set(
            expert_sorted, mode="drop"
        )

        recv_x = lax.all_to_all(send_x.reshape(P, bucket, H), axis_name, 0, 0)
        recv_eid = lax.all_to_all(send_eid.reshape(P, bucket), axis_name, 0, 0)
        recv_x = recv_x.reshape(P * bucket, H)
        le = recv_eid.reshape(P * bucket) - r * E_loc       # local expert id
        recv_valid = (le >= 0) & (le < E_loc)

    # regroup received rows by local expert (invalid rows sort last);
    # group sizes come from the received expert ids — no extra collective
    key = jnp.where(recv_valid, le, E_loc)
    sort2 = jnp.argsort(key, stable=True)
    xs2 = jnp.take(recv_x, sort2, axis=0)
    group_sizes = jnp.bincount(key, length=E_loc + 1)[:E_loc].astype(jnp.int32)
    safe_le = jnp.clip(jnp.take(key, sort2), 0, E_loc - 1)

    u = lax.ragged_dot(xs2, params["up_proj"]["kernel"].astype(dtype), group_sizes)
    if "bias" in params["up_proj"]:
        u = u + jnp.take(params["up_proj"]["bias"].astype(dtype), safe_le, axis=0)
    if cfg.gated_experts:
        g = lax.ragged_dot(xs2, params["gate_proj"]["kernel"].astype(dtype), group_sizes)
        if "bias" in params["gate_proj"]:
            g = g + jnp.take(params["gate_proj"]["bias"].astype(dtype), safe_le, axis=0)
        h_in = gated_combine(g, u, cfg.expert_activation, cfg.swiglu_limit)
    else:
        h_in = _EXPERT_ACT[cfg.expert_activation](u)
    y2 = lax.ragged_dot(h_in, params["down_proj"]["kernel"].astype(dtype), group_sizes)
    if "bias" in params["down_proj"]:
        y2 = y2 + jnp.take(params["down_proj"]["bias"].astype(dtype), safe_le, axis=0)
    y2 = jnp.where(jnp.take(recv_valid, sort2)[:, None], y2, 0.0)

    # undo the regroup sort, return rows to their source rank
    y_recv = jnp.zeros_like(y2).at[sort2].set(y2)
    if ragged:
        # combine = dispatch with the metadata roles swapped; rows land back
        # at their original sorted offsets, unsent rows stay zero
        ys = _ragged_exchange(
            y_recv, recv_off, recv_sz, back_out_off, counts_peer,
            offsets_peer, out_off, TK, axis_name,
        )
    else:
        y_back = lax.all_to_all(y_recv.reshape(P, bucket, H), axis_name, 0, 0)
        y_back = y_back.reshape(P * bucket, H)
        ys = jnp.take(y_back, jnp.minimum(flat_pos, P * bucket - 1), axis=0)
    ys = jnp.where(valid_send[:, None], ys, 0.0)
    w_sorted = jnp.take(weights.reshape(TK), sort_idx).astype(dtype)
    return jnp.zeros((T, H), dtype).at[token_of].add(ys * w_sorted[:, None])


def shared_expert_forward(
    params: dict,
    cfg: MoEConfig,
    flat: jnp.ndarray,  # (T, H)
    *,
    tp_axis: str | None = None,
) -> jnp.ndarray:
    """Dense shared-expert branch added to the routed output (DeepSeek /
    Qwen-MoE style). One implementation for both execution modes: under
    GSPMD (moe/layer.py) leave `tp_axis=None`; inside the pipeline
    shard_map (moe_lm `_pp_moe_layer_setup`) pass the mesh axis so the
    mlp-dim-sharded down-proj partials are psummed manually."""
    dtype = flat.dtype
    u = flat @ params["up_proj"]["kernel"].astype(dtype)
    if cfg.shared_expert_is_gated:
        g = flat @ params["gate_proj"]["kernel"].astype(dtype)
        inner = gated_combine(g, u, cfg.shared_expert_activation, cfg.swiglu_limit)
    else:
        inner = _EXPERT_ACT[cfg.shared_expert_activation](u)
    out = inner @ params["down_proj"]["kernel"].astype(dtype)
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    if cfg.shared_expert_gated:
        out = out * jax.nn.sigmoid(flat @ params["gate"]["kernel"].astype(dtype))
    return out


def dropless_ep_shardmap_body(
    params: dict,
    cfg: MoEConfig,
    x: jnp.ndarray,        # (T_loc, H) — this shard's tokens
    weights: jnp.ndarray,  # (T_loc, K)
    indices: jnp.ndarray,  # (T_loc, K)
    *,
    axis_name: str = "ep",
    ragged: bool | None = None,
) -> jnp.ndarray:
    """Dropless EP dispatch for callers ALREADY inside a shard_map over a
    mesh containing `axis_name` — the pipeline-stage entry point: the pp
    schedules (parallel/pp.py) run each stage's layer scan inside one
    full-mesh shard_map, so the expert A2A must be issued as a manual
    collective confined to that stage's step (it overlaps with other
    stages' compute instead of fencing the whole program).

    `params` holds the LOCAL expert slice (E/ep experts, dim 0); token rows
    are this shard's. bucket = the dropless worst case for the local rows
    (every (token, slot) pair could target one peer).
    """
    if ragged is None:
        ragged = jax.default_backend() == "tpu"
    bucket = max(8, x.shape[0] * cfg.experts_per_token)
    return _dropless_ep_local(
        params, cfg, x, weights, indices,
        axis_name=axis_name, bucket=bucket, ragged=ragged,
    )


def experts_forward_dropless_ep(
    params: dict,
    cfg: MoEConfig,
    x: jnp.ndarray,        # (T, H) flat tokens, sharded (dp, ep, cp)
    weights: jnp.ndarray,  # (T, K)
    indices: jnp.ndarray,  # (T, K)
    mesh_ctx,
    ragged: bool | None = None,  # None = auto (TPU yes, CPU dense fallback)
) -> jnp.ndarray:
    """Dropless dispatch ACROSS an ep>1 mesh axis (DeepEP semantics).

    shard_map wrapper around `_dropless_ep_local`: tokens stay sharded on
    (dp, ep, cp); expert weights enter sharded on `ep` only (fsdp/tp dims
    are gathered at the boundary, the FSDP-unshard analog).
    """
    import functools

    from jax.sharding import PartitionSpec as P

    ep = mesh_ctx.sizes["ep"]
    E = cfg.n_routed_experts
    if E % ep != 0:
        raise ValueError(f"n_routed_experts={E} not divisible by ep={ep}")

    tok = P(("dp_replicate", "dp_shard", "ep", "cp"), None)
    tok_k = tok
    eparams = {
        proj: params[proj]
        for proj in ("gate_proj", "up_proj", "down_proj")
        if proj in params
    }
    espec = {
        proj: {k: P("ep", *([None] * (v.ndim - 1))) for k, v in eparams[proj].items()}
        for proj in eparams
    }

    # dropless worst case: every local (token, slot) row targets one rank
    t_total = x.shape[0]
    t_loc = t_total // (mesh_ctx.axis_size("batch") * mesh_ctx.sizes["cp"])
    bucket = max(8, t_loc * cfg.experts_per_token)

    # ragged A2A ships only the routed rows (DeepEP's bandwidth property);
    # XLA:CPU has no ragged-all-to-all, so the virtual-device mesh (tests,
    # driver dryrun) uses the dense worst-case bucket layout instead
    if ragged is None:
        ragged = jax.default_backend() == "tpu"
    fn = functools.partial(
        _dropless_ep_local, axis_name="ep", bucket=bucket, cfg=cfg,
        ragged=ragged,
    )
    return jax.shard_map(
        lambda p, xx, ww, ii: fn(p, x=xx, weights=ww, indices=ii),
        mesh=mesh_ctx.mesh,
        in_specs=(espec, tok, tok_k, tok_k),
        out_specs=tok,
        check_vma=False,
    )(eparams, x, weights, indices)


def experts_forward(
    params: dict,
    cfg: MoEConfig,
    x: jnp.ndarray,        # (T, H)
    dispatch: jnp.ndarray, # (T, E, C) one-hot
    combine_w: jnp.ndarray,  # (T, E) routing weights
    constrain=None,
) -> jnp.ndarray:
    """Dispatch → batched expert MLP → weighted combine. Returns (T, H)."""
    c = constrain or (lambda a, axes: a)
    dtype = x.dtype
    # tokens → expert-major: XLA inserts the A2A here when ep-sharded
    xe = jnp.einsum("tec,th->ech", dispatch.astype(dtype), x)
    xe = c(xe, ("act_expert", None, "act_embed"))
    u = jnp.einsum("ech,ehi->eci", xe, params["up_proj"]["kernel"].astype(dtype))
    if "bias" in params["up_proj"]:
        u = u + params["up_proj"]["bias"].astype(dtype)[:, None, :]
    if cfg.gated_experts:
        g = jnp.einsum("ech,ehi->eci", xe, params["gate_proj"]["kernel"].astype(dtype))
        if "bias" in params["gate_proj"]:
            g = g + params["gate_proj"]["bias"].astype(dtype)[:, None, :]
        h_in = gated_combine(g, u, cfg.expert_activation, cfg.swiglu_limit)
    else:
        h_in = _EXPERT_ACT[cfg.expert_activation](u)
    y = jnp.einsum("eci,eih->ech", h_in, params["down_proj"]["kernel"].astype(dtype))
    if "bias" in params["down_proj"]:
        y = y + params["down_proj"]["bias"].astype(dtype)[:, None, :]
    y = c(y, ("act_expert", None, "act_embed"))
    # expert-major → tokens (the A2A back); the per-token routing weight
    # factors as (T,E) and fuses into the einsum — no second (T,E,C)
    return jnp.einsum(
        "tec,te,ech->th", dispatch.astype(dtype), combine_w.astype(dtype), y
    )
