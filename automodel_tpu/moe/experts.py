"""Expert compute + token dispatch.

The TPU-native replacement for the reference's experts/dispatcher stack
(reference: nemo_automodel/components/moe/experts.py:202 `GroupedExperts`,
:651 `GroupedExpertsDeepEP`; megatron/token_dispatcher.py:504
`MoEFlexTokenDispatcher`; megatron/fused_a2a.py DeepEP NVSHMEM all-to-all).

Design: capacity-based einsum dispatch — the GSPMD-native MoE pattern.
Routing produces a (tokens, experts, capacity) dispatch tensor; two einsums
move tokens to expert-major layout and back. When the expert dim is sharded
on the `ep` mesh axis and tokens on `batch`, XLA lowers the einsums to
exactly the all-to-all pair DeepEP implements by hand, riding ICI. Static
shapes (capacity padding) keep everything jit-compatible; overflow tokens
are dropped (capacity_factor controls headroom), matching Megatron-style
capacity dispatch semantics.

A sort-based dropless path (ragged grouped GEMM ≙ megablox gmm) is the
planned second dispatcher; this module keeps the dispatcher abstraction so
both share the gate and expert weights.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig

_EXPERT_ACT = {
    "silu": jax.nn.silu,
    "geglu": jax.nn.gelu,
    "quick_geglu": lambda x: x * jax.nn.sigmoid(1.702 * x),
    "relu2": lambda x: jnp.square(jax.nn.relu(x)),
}


def gated_combine(g, u, kind: str, limit: float = 7.0):
    """gate/up → MLP inner. "swigluoai" is gpt-oss's clamped variant:
    min(g,limit)·sigmoid(1.702·g)·(clip(u,±limit)+1); others are act(g)·u."""
    if kind == "swigluoai":
        g = jnp.minimum(g, limit)
        u = jnp.clip(u, -limit, limit)
        return g * jax.nn.sigmoid(1.702 * g) * (u + 1.0)
    return _EXPERT_ACT[kind](g) * u


def init_experts(cfg: MoEConfig, hidden_size: int, rng: jax.Array) -> dict:
    E, H, I = cfg.n_routed_experts, hidden_size, cfg.moe_intermediate_size
    k1, k2, k3 = jax.random.split(rng, 3)
    std_in, std_out = H ** -0.5, I ** -0.5
    params = {
        "gate_proj": {"kernel": std_in * jax.random.truncated_normal(k1, -3, 3, (E, H, I))},
        "up_proj": {"kernel": std_in * jax.random.truncated_normal(k2, -3, 3, (E, H, I))},
        "down_proj": {"kernel": std_out * jax.random.truncated_normal(k3, -3, 3, (E, I, H))},
    }
    if cfg.expert_bias:
        params["gate_proj"]["bias"] = jnp.zeros((E, I))
        params["up_proj"]["bias"] = jnp.zeros((E, I))
        params["down_proj"]["bias"] = jnp.zeros((E, H))
    return params


def expert_param_specs(cfg: MoEConfig) -> dict:
    specs = {
        "gate_proj": {"kernel": ("expert", "expert_embed", "expert_mlp")},
        "up_proj": {"kernel": ("expert", "expert_embed", "expert_mlp")},
        "down_proj": {"kernel": ("expert", "expert_mlp", "expert_embed")},
    }
    if cfg.expert_bias:
        specs["gate_proj"]["bias"] = ("expert", "expert_mlp")
        specs["up_proj"]["bias"] = ("expert", "expert_mlp")
        specs["down_proj"]["bias"] = ("expert", "expert_embed")
    return specs


def compute_capacity(cfg: MoEConfig, num_tokens: int) -> int:
    per_expert = num_tokens * cfg.experts_per_token / cfg.n_routed_experts
    cap = int(per_expert * cfg.capacity_factor)
    return max(8, ((cap + 7) // 8) * 8)  # sublane-align


def dispatch_tensors(
    cfg: MoEConfig,
    indices: jnp.ndarray,  # (T, K) int32
    weights: jnp.ndarray,  # (T, K) f32
    capacity: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Build dispatch (T,E,C) bool-ish and combine (T,E,C) f32 tensors.

    Position of token t within expert e's buffer = number of earlier
    (token, slot) pairs routed to e — a cumsum over the flattened (T*K)
    routing order, matching Megatron's capacity dispatcher semantics.
    """
    T, K = indices.shape
    E = cfg.n_routed_experts
    flat = indices.reshape(T * K)
    onehot = jax.nn.one_hot(flat, E, dtype=jnp.int32)          # (T*K, E)
    pos = jnp.cumsum(onehot, axis=0) - onehot                   # (T*K, E)
    pos_in_expert = jnp.sum(pos * onehot, axis=-1).reshape(T, K)
    keep = (pos_in_expert < capacity).astype(jnp.float32)       # (T, K)

    # Accumulate per top-k slot so peak memory stays at one (T, E, C) tensor
    # (a (T*K, E, C) intermediate would be K× larger).
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    idx_tk = indices.reshape(T, K)
    for k in range(K):
        eh = jax.nn.one_hot(idx_tk[:, k], E, dtype=jnp.float32)          # (T, E)
        ch = jax.nn.one_hot(pos_in_expert[:, k], capacity, dtype=jnp.float32)
        contrib = (eh * keep[:, k : k + 1])[:, :, None] * ch[:, None, :]  # (T, E, C)
        dispatch = dispatch + contrib
        combine = combine + contrib * weights[:, k][:, None, None]
    return dispatch, combine


def experts_forward_dropless(
    params: dict,
    cfg: MoEConfig,
    x: jnp.ndarray,        # (T, H)
    weights: jnp.ndarray,  # (T, K)
    indices: jnp.ndarray,  # (T, K)
) -> jnp.ndarray:
    """Dropless sort-based dispatch + ragged grouped GEMM.

    The megablox/`GroupedExpertsDeepEP` analog (reference: experts.py:651):
    (token, slot) pairs are sorted by expert id, the three expert matmuls run
    as `lax.ragged_dot` over the per-expert group sizes (no capacity padding,
    no dropped tokens), and outputs scatter-add back into token order. Static
    shapes throughout (TK rows total), so jit-compatible.

    Scope: replicated or dp-sharded experts (ep=1) — ragged group sizes
    don't currently split across an `ep` axis under GSPMD; EP meshes use the
    capacity dispatcher.
    """
    T, H = x.shape
    K = cfg.experts_per_token
    E = cfg.n_routed_experts
    dtype = x.dtype

    flat_expert = indices.reshape(T * K)
    # stable sort groups rows by expert while keeping token order within
    sort_idx = jnp.argsort(flat_expert, stable=True)
    token_of = sort_idx // K
    expert_of = jnp.take(flat_expert, sort_idx)
    xs = jnp.take(x, token_of, axis=0)  # (TK, H)
    group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)

    # masked tokens carry the sentinel index E (see gate_forward) — clip once
    # for the bias gathers; their rows are zero-weighted in the combine anyway
    safe_expert = jnp.clip(expert_of, 0, E - 1)
    g = jax.lax.ragged_dot(xs, params["gate_proj"]["kernel"].astype(dtype), group_sizes)
    u = jax.lax.ragged_dot(xs, params["up_proj"]["kernel"].astype(dtype), group_sizes)
    if "bias" in params["gate_proj"]:
        g = g + jnp.take(params["gate_proj"]["bias"].astype(dtype), safe_expert, axis=0)
        u = u + jnp.take(params["up_proj"]["bias"].astype(dtype), safe_expert, axis=0)
    h_in = gated_combine(g, u, cfg.expert_activation, cfg.swiglu_limit)
    y = jax.lax.ragged_dot(h_in, params["down_proj"]["kernel"].astype(dtype), group_sizes)
    if "bias" in params["down_proj"]:
        y = y + jnp.take(params["down_proj"]["bias"].astype(dtype), safe_expert, axis=0)

    w_sorted = jnp.take(weights.reshape(T * K), sort_idx, axis=0).astype(dtype)
    contrib = y * w_sorted[:, None]
    return jnp.zeros((T, H), dtype).at[token_of].add(contrib)


def experts_forward(
    params: dict,
    cfg: MoEConfig,
    x: jnp.ndarray,        # (T, H)
    dispatch: jnp.ndarray, # (T, E, C)
    combine: jnp.ndarray,  # (T, E, C)
    constrain=None,
) -> jnp.ndarray:
    """Dispatch → batched expert MLP → weighted combine. Returns (T, H)."""
    c = constrain or (lambda a, axes: a)
    dtype = x.dtype
    # tokens → expert-major: XLA inserts the A2A here when ep-sharded
    xe = jnp.einsum("tec,th->ech", dispatch.astype(dtype), x)
    xe = c(xe, ("act_expert", None, "act_embed"))
    g = jnp.einsum("ech,ehi->eci", xe, params["gate_proj"]["kernel"].astype(dtype))
    u = jnp.einsum("ech,ehi->eci", xe, params["up_proj"]["kernel"].astype(dtype))
    if "bias" in params["gate_proj"]:
        g = g + params["gate_proj"]["bias"].astype(dtype)[:, None, :]
        u = u + params["up_proj"]["bias"].astype(dtype)[:, None, :]
    h_in = gated_combine(g, u, cfg.expert_activation, cfg.swiglu_limit)
    y = jnp.einsum("eci,eih->ech", h_in, params["down_proj"]["kernel"].astype(dtype))
    if "bias" in params["down_proj"]:
        y = y + params["down_proj"]["bias"].astype(dtype)[:, None, :]
    y = c(y, ("act_expert", None, "act_embed"))
    # expert-major → tokens (the A2A back), weighted by routing probs
    return jnp.einsum("tec,ech->th", combine.astype(dtype), y)
