from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.gate import gate_forward, init_gate, update_gate_bias
from automodel_tpu.moe.layer import init_moe, moe_forward, moe_param_specs

__all__ = [
    "MoEConfig",
    "gate_forward",
    "init_gate",
    "update_gate_bias",
    "init_moe",
    "moe_forward",
    "moe_param_specs",
]
