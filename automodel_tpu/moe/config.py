"""MoE configuration.

The analog of the reference `MoEConfig`
(reference: nemo_automodel/components/moe/config.py:26-93): routed/shared
expert counts, top-k, grouped routing, score function, aux-loss coeff,
DeepSeek-style gate-bias update, expert activation. TPU-specific addition:
`capacity_factor` — the einsum-dispatch path pads each expert to a fixed
capacity so shapes stay static under jit (the XLA-native replacement for
DeepEP's dynamic all-to-all; dropped tokens ≙ capacity overflow).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_routed_experts: int = 8
    n_shared_experts: int = 0
    experts_per_token: int = 2  # top-k
    n_groups: int = 1           # deepseek group-limited routing
    topk_groups: int = 1
    score_func: str = "softmax"  # "softmax" | "sigmoid"
    norm_topk_prob: bool = True
    route_scale: float = 1.0
    aux_loss_coeff: float = 0.0
    gate_bias_update_speed: float = 0.0  # deepseek aux-free balancing
    # silu | geglu | quick_geglu | swigluoai are GATED (3-matrix) MLPs;
    # relu2 is NON-gated (up/down only, inner = relu(u)²) — matching the
    # reference's is_gated_activation split (moe/layers.py:46-82)
    expert_activation: str = "silu"
    expert_bias: bool = False         # gpt-oss experts carry projection biases
    swiglu_limit: float = 7.0         # swigluoai clamp (HF swiglu_limit)
    router_bias: bool = False         # gpt-oss router linear has a bias
    moe_intermediate_size: int = 512
    shared_expert_intermediate_size: Optional[int] = None
    shared_expert_gated: bool = False  # qwen3-next: sigmoid(gate(x))·shared(x)
    shared_expert_activation: str = "silu"  # nemotron: relu2 (non-gated)
    capacity_factor: float = 1.25    # static-shape dispatch headroom
    # "dropless" (default): sort + ragged grouped GEMM, ragged_all_to_all
    # under EP — exact (HF never drops tokens) and avoids the (T,E,C)
    # dispatch tensor that dominates memory at DSv3 scale (E=256).
    # "capacity": einsum dispatch with padded capacity (kept for perf
    # comparison and as the GSPMD-A2A fallback).
    dispatcher: str = "dropless"
    router_dtype: str = "float32"
    fake_balanced_gate: bool = False  # perf benchmarking (reference layers.py:126)

    def __post_init__(self):
        if self.dispatcher not in ("capacity", "dropless"):
            raise ValueError(
                f"Unknown MoE dispatcher '{self.dispatcher}' "
                "(expected 'capacity' or 'dropless')"
            )
        known_acts = ("silu", "geglu", "quick_geglu", "relu2", "swigluoai")
        for field in ("expert_activation", "shared_expert_activation"):
            if getattr(self, field) not in known_acts:
                raise ValueError(
                    f"Unknown {field} '{getattr(self, field)}' (expected one of {known_acts})"
                )

    @property
    def gated_experts(self) -> bool:
        return self.expert_activation != "relu2"

    @property
    def shared_expert_is_gated(self) -> bool:
        return self.shared_expert_activation != "relu2"

    @property
    def shared_intermediate(self) -> int:
        if self.shared_expert_intermediate_size is not None:
            return self.shared_expert_intermediate_size
        return self.moe_intermediate_size * self.n_shared_experts
