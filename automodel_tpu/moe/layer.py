"""The MoE block: gate + routed experts + shared experts.

The analog of the reference `MoE` module (reference: nemo_automodel/
components/moe/layers.py:611-793): routed expert output plus an
always-active shared-expert MLP, aux loss surfaced to the training loss.

Aux-loss contract (the `MoEAuxLossAutoScaler` analog, reference:
moe/megatron/moe_utils.py:569): each layer's aux loss is O(1). Training
losses in this framework are SUM cross-entropy later divided by the global
label-token count, so the aux term must be multiplied by that count before
joining the sum — use loss/utils.py `combine_losses`, which preserves the
reference's effective aux_loss_coeff at any scale.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.experts import (
    compute_capacity,
    dispatch_tensors,
    expert_param_specs,
    experts_forward,
    experts_forward_dropless,
    experts_forward_dropless_ep,
    init_experts,
)
from automodel_tpu.moe.gate import gate_forward, gate_param_specs, init_gate


def init_moe(cfg: MoEConfig, hidden_size: int, rng: jax.Array) -> dict:
    kg, ke, ks = jax.random.split(rng, 3)
    params = {
        "gate": init_gate(cfg, hidden_size, kg),
        "experts": init_experts(cfg, hidden_size, ke),
    }
    if cfg.n_shared_experts > 0:
        Hs = cfg.shared_intermediate
        std_in, std_out = hidden_size ** -0.5, Hs ** -0.5
        k1, k2, k3 = jax.random.split(ks, 3)
        params["shared"] = {
            "up_proj": {"kernel": std_in * jax.random.truncated_normal(k2, -3, 3, (hidden_size, Hs))},
            "down_proj": {"kernel": std_out * jax.random.truncated_normal(k3, -3, 3, (Hs, hidden_size))},
        }
        if cfg.shared_expert_is_gated:
            params["shared"]["gate_proj"] = {
                "kernel": std_in * jax.random.truncated_normal(k1, -3, 3, (hidden_size, Hs))
            }
        if cfg.shared_expert_gated:
            params["shared"]["gate"] = {
                "kernel": std_in * jax.random.truncated_normal(
                    jax.random.fold_in(ks, 9), -3, 3, (hidden_size, 1)
                )
            }
    return params


def moe_param_specs(cfg: MoEConfig) -> dict:
    specs = {
        "gate": gate_param_specs(cfg),
        "experts": expert_param_specs(cfg),
    }
    if cfg.n_shared_experts > 0:
        specs["shared"] = {
            "up_proj": {"kernel": ("embed", "mlp")},
            "down_proj": {"kernel": ("mlp", "embed")},
        }
        if cfg.shared_expert_is_gated:
            specs["shared"]["gate_proj"] = {"kernel": ("embed", "mlp")}
        if cfg.shared_expert_gated:
            specs["shared"]["gate"] = {"kernel": ("embed", None)}
    return specs


def moe_forward(
    params: dict,
    cfg: MoEConfig,
    x: jnp.ndarray,  # (B, S, H)
    constrain=None,
    token_mask: jnp.ndarray | None = None,  # (B, S) bool
    mesh_ctx=None,
    forced_indices: jnp.ndarray | None = None,  # (B*S, K) routing replay
) -> tuple[jnp.ndarray, jnp.ndarray, dict]:
    """Returns (out (B,S,H), aux_loss scalar, stats). stats["indices"] is
    the (T,K) selection — capture it for routing replay (R3)."""
    B, S, H = x.shape
    flat = x.reshape(B * S, H)
    flat_mask = token_mask.reshape(B * S) if token_mask is not None else None
    weights, indices, aux_loss, stats = gate_forward(
        params["gate"], cfg, flat, flat_mask, forced_indices
    )
    stats = {**stats, "indices": indices}
    if cfg.dispatcher == "dropless":
        if mesh_ctx is not None and mesh_ctx.sizes["ep"] > 1:
            routed = experts_forward_dropless_ep(
                params["experts"], cfg, flat, weights, indices, mesh_ctx
            )
        else:
            routed = experts_forward_dropless(params["experts"], cfg, flat, weights, indices)
    else:
        capacity = compute_capacity(cfg, B * S)
        dispatch, combine = dispatch_tensors(cfg, indices, weights, capacity)
        routed = experts_forward(params["experts"], cfg, flat, dispatch, combine, constrain)
    out = routed
    if cfg.n_shared_experts > 0:
        from automodel_tpu.moe.experts import shared_expert_forward

        out = out + shared_expert_forward(params["shared"], cfg, flat)
    return out.reshape(B, S, H).astype(x.dtype), aux_loss, stats
