from automodel_tpu.cli.app import main

main()
