"""AutoDiffusionPipeline: diffusers-layout pipeline save/load + sampling.

The analog of the reference's `NeMoAutoDiffusionPipeline`
(reference: nemo_automodel/_diffusers/auto_diffusion_pipeline.py, 973 LoC
— loads an HF Diffusers pipeline directory with per-component sharding).
TPU-native form: the pipeline directory follows the diffusers layout —

    model_index.json                      # component → [module, class]
    transformer/config.json + model.safetensors   (DiT denoiser)
    vae/config.json + model.safetensors           (optional AutoencoderKL-lite)
    scheduler/scheduler_config.json               (flow-matching params)

Components load into sharded jnp params (NamedShardings from the mesh
context when given); sampling runs the rectified-flow Euler integrator
with classifier-free guidance and decodes through the VAE when present.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.diffusion.flow_matching import euler_sample
from automodel_tpu.models.diffusion import dit, vae

_INDEX = "model_index.json"


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Flow-matching sampler parameters (the scheduler component)."""

    shift: float = 3.0
    num_train_timesteps: int = 1000

    def to_hf(self) -> dict:
        return {
            "_class_name": "FlowMatchEulerDiscreteScheduler",
            "shift": self.shift,
            "num_train_timesteps": self.num_train_timesteps,
        }

    @classmethod
    def from_hf(cls, d: dict) -> "SchedulerConfig":
        return cls(
            shift=float(d.get("shift", 3.0)),
            num_train_timesteps=int(d.get("num_train_timesteps", 1000)),
        )


def _dit_config_to_hf(cfg: dit.DiTConfig) -> dict:
    return {
        "_class_name": "DiTConfig",
        "input_size": cfg.input_size,
        "patch_size": cfg.patch_size,
        "in_channels": cfg.in_channels,
        "hidden_size": cfg.hidden_size,
        "num_layers": cfg.num_layers,
        "num_heads": cfg.num_heads,
        "mlp_ratio": cfg.mlp_ratio,
        "num_classes": cfg.num_classes,
        "cross_attention_dim": cfg.cross_attention_dim,
    }


def _dit_config_from_hf(d: dict, **overrides) -> dit.DiTConfig:
    kw = {
        k: d[k]
        for k in (
            "input_size", "patch_size", "in_channels", "hidden_size",
            "num_layers", "num_heads", "mlp_ratio", "num_classes",
            "cross_attention_dim",
        )
        if k in d
    }
    kw.update(overrides)
    return dit.DiTConfig(**kw)


def _flatten(tree, prefix=""):
    for k, v in tree.items():
        name = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
        if isinstance(v, dict):
            yield from _flatten(v, name)
        else:
            yield name, np.asarray(v)


def _unflatten(flat: dict) -> dict:
    out: dict = {}
    for name, v in flat.items():
        node = out
        parts = name.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _save_component(dirpath: str, config: dict, params=None, config_name="config.json"):
    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, config_name), "w") as f:
        json.dump(config, f, indent=2)
    if params is not None:
        from safetensors.numpy import save_file

        save_file(dict(_flatten(params)), os.path.join(dirpath, "model.safetensors"))


def _load_tensors(dirpath: str) -> dict:
    from safetensors.numpy import load_file

    return _unflatten(load_file(os.path.join(dirpath, "model.safetensors")))


@dataclasses.dataclass
class AutoDiffusionPipeline:
    """Transformer (DiT) + optional VAE + flow-matching scheduler."""

    transformer_cfg: dit.DiTConfig
    transformer_params: Any
    scheduler: SchedulerConfig = dataclasses.field(default_factory=SchedulerConfig)
    vae_cfg: Optional[vae.VAEConfig] = None
    vae_params: Any = None

    def __post_init__(self) -> None:
        if (self.vae_params is None) != (self.vae_cfg is None):
            raise ValueError(
                "vae_cfg and vae_params must be provided together (got "
                f"vae_cfg={'set' if self.vae_cfg is not None else 'None'}, "
                f"vae_params={'set' if self.vae_params is not None else 'None'})"
            )

    # -- persistence --------------------------------------------------------
    def save_pretrained(self, out_dir: str) -> None:
        os.makedirs(out_dir, exist_ok=True)
        # component entries record REAL importable symbols (the functional
        # modules' config dataclasses), keeping the diffusers convention of
        # [module, class] resolvable
        index = {
            "_class_name": "AutoDiffusionPipeline",
            "transformer": ["automodel_tpu.models.diffusion.dit", "DiTConfig"],
            "scheduler": ["automodel_tpu.diffusion.pipeline", "SchedulerConfig"],
        }
        if self.vae_params is not None:
            index["vae"] = ["automodel_tpu.models.diffusion.vae", "VAEConfig"]
        with open(os.path.join(out_dir, _INDEX), "w") as f:
            json.dump(index, f, indent=2)
        _save_component(
            os.path.join(out_dir, "transformer"),
            _dit_config_to_hf(self.transformer_cfg),
            self.transformer_params,
        )
        _save_component(
            os.path.join(out_dir, "scheduler"), self.scheduler.to_hf(),
            config_name="scheduler_config.json",
        )
        if self.vae_params is not None:
            _save_component(
                os.path.join(out_dir, "vae"), self.vae_cfg.to_hf(), self.vae_params
            )

    @classmethod
    def from_pretrained(
        cls, ckpt_dir: str, mesh_ctx=None, dtype=None
    ) -> "AutoDiffusionPipeline":
        with open(os.path.join(ckpt_dir, _INDEX)) as f:
            index = json.load(f)
        with open(os.path.join(ckpt_dir, "transformer", "config.json")) as f:
            tcfg_d = json.load(f)
        overrides = {"dtype": dtype} if dtype is not None else {}
        tcfg = _dit_config_from_hf(tcfg_d, **overrides)
        tparams = _load_tensors(os.path.join(ckpt_dir, "transformer"))
        if mesh_ctx is not None:
            from automodel_tpu.parallel import logical_to_shardings

            sh = logical_to_shardings(
                dit.param_specs(tcfg), mesh_ctx,
                shapes=jax.tree.map(lambda p: p.shape, tparams),
            )
            tparams = jax.device_put(tparams, sh)
        else:
            tparams = jax.tree.map(jnp.asarray, tparams)

        sched_path = os.path.join(ckpt_dir, "scheduler", "scheduler_config.json")
        sched = SchedulerConfig()
        if os.path.exists(sched_path):
            with open(sched_path) as f:
                sched = SchedulerConfig.from_hf(json.load(f))

        vcfg, vparams = None, None
        if "vae" in index and os.path.isdir(os.path.join(ckpt_dir, "vae")):
            with open(os.path.join(ckpt_dir, "vae", "config.json")) as f:
                vcfg = vae.VAEConfig.from_hf(json.load(f))
            vparams = jax.tree.map(
                jnp.asarray, _load_tensors(os.path.join(ckpt_dir, "vae"))
            )
        return cls(
            transformer_cfg=tcfg, transformer_params=tparams,
            scheduler=sched, vae_cfg=vcfg, vae_params=vparams,
        )

    # -- sampling -----------------------------------------------------------
    def __call__(
        self,
        rng: jax.Array,
        batch_size: int = 1,
        *,
        class_labels: jnp.ndarray | None = None,
        text_embeddings: jnp.ndarray | None = None,  # (B, L, Dtext) SimpleAdapter
        guidance_scale: float = 1.0,
        num_inference_steps: int = 16,
        decode: bool = True,
    ) -> jnp.ndarray:
        """Sample latents (and decode to images when a VAE is attached).

        Classifier-free guidance doubles the denoiser batch: conditional
        and null-class velocities combine as v = v_u + g·(v_c - v_u)."""
        cfg = self.transformer_cfg
        shape = (batch_size, cfg.input_size, cfg.input_size, cfg.in_channels)
        use_cfg = (
            guidance_scale != 1.0 and class_labels is not None and cfg.num_classes > 0
        )

        text_kw = {}
        if cfg.cross_attention_dim > 0:
            if text_embeddings is None:
                raise ValueError(
                    "this pipeline's transformer is text-conditioned "
                    "(cross_attention_dim > 0); pass text_embeddings"
                )
            text_kw["encoder_hidden_states"] = text_embeddings

        def velocity(x, sigma):
            if not use_cfg:
                return dit.forward(
                    self.transformer_params, cfg, x.astype(cfg.dtype), sigma,
                    class_labels=class_labels, **text_kw,
                ).astype(jnp.float32)
            null = jnp.full_like(class_labels, cfg.num_classes)
            tk = (
                {"encoder_hidden_states": jnp.concatenate(
                    [text_kw["encoder_hidden_states"]] * 2
                )}
                if text_kw else {}
            )
            v2 = dit.forward(
                self.transformer_params, cfg,
                jnp.concatenate([x, x]).astype(cfg.dtype),
                jnp.concatenate([sigma, sigma]),
                class_labels=jnp.concatenate([class_labels, null]), **tk,
            ).astype(jnp.float32)
            v_c, v_u = jnp.split(v2, 2)
            return v_u + guidance_scale * (v_c - v_u)

        latents = euler_sample(
            velocity, rng, shape,
            steps=num_inference_steps, shift=self.scheduler.shift,
        )
        if decode and self.vae_params is not None:
            return vae.decode(self.vae_params, self.vae_cfg, latents)
        return latents
