from automodel_tpu.diffusion.flow_matching import (  # noqa: F401
    euler_sample,
    flow_matching_loss,
    interpolate,
    sample_sigmas,
    time_shift,
)
from automodel_tpu.diffusion.pipeline import (  # noqa: F401
    AutoDiffusionPipeline,
    SchedulerConfig,
)
