"""Rectified-flow / flow-matching training primitives.

The analog of the reference flow-matching stack (reference:
nemo_automodel/components/flow_matching/pipeline.py `FlowMatchingPipeline`
— interpolation, σ sampling, loss weighting; time_shift_utils.py), as pure
functions:

    x_σ    = (1−σ)·x0 + σ·x1          (x1 ~ N(0, I))
    target = x1 − x0                   (the constant velocity field)
    loss   = w(σ) · ‖v_θ(x_σ, σ, c) − target‖²

σ is sampled uniform or logit-normal and optionally time-shifted
(σ → s·σ / (1 + (s−1)·σ), the resolution-aware shift of SD3/Pika-style
training). An Euler integrator turns the trained velocity field into a
sampler.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_sigmas(
    rng: jax.Array,
    batch: int,
    *,
    scheme: str = "logit_normal",
    logit_mean: float = 0.0,
    logit_std: float = 1.0,
    sigma_min: float = 0.0,
    sigma_max: float = 1.0,
) -> jnp.ndarray:
    """(B,) noise levels in [sigma_min, sigma_max]
    (reference: time_shift_utils.py:65 `compute_density_for_timestep_sampling`)."""
    if scheme == "uniform":
        s = jax.random.uniform(rng, (batch,))
    elif scheme == "logit_normal":
        u = logit_mean + logit_std * jax.random.normal(rng, (batch,))
        s = jax.nn.sigmoid(u)
    else:
        raise ValueError(f"unknown sigma sampling scheme '{scheme}'")
    return sigma_min + (sigma_max - sigma_min) * s


def time_shift(sigma: jnp.ndarray, shift: float = 3.0) -> jnp.ndarray:
    """σ → s·σ/(1+(s−1)·σ) — pushes sampling toward high noise
    (reference: time_shift_utils.py:23, constant mode)."""
    return shift * sigma / (1.0 + (shift - 1.0) * sigma)


def interpolate(x0: jnp.ndarray, x1: jnp.ndarray, sigma: jnp.ndarray) -> jnp.ndarray:
    """(1−σ)·x0 + σ·x1 with σ broadcast over trailing dims
    (reference: pipeline.py:61 `forward`)."""
    s = sigma.reshape(sigma.shape + (1,) * (x0.ndim - sigma.ndim))
    return (1.0 - s) * x0 + s * x1


def loss_weight(sigma: jnp.ndarray, scheme: str = "linear", shift: float = 3.0) -> jnp.ndarray:
    """Per-sample loss weight (reference: time_shift_utils.py:102)."""
    if scheme == "none":
        return jnp.ones_like(sigma)
    if scheme == "linear":
        return 1.0 + (shift - 1.0) * sigma  # emphasize high-noise steps
    raise ValueError(f"unknown loss weighting scheme '{scheme}'")


def flow_matching_loss(
    velocity_pred: jnp.ndarray,  # model output v_θ(x_σ)
    x0: jnp.ndarray,
    x1: jnp.ndarray,
    sigma: jnp.ndarray,          # (B,)
    *,
    weighting: str = "linear",
    shift: float = 3.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Weighted MSE to the velocity target. Returns (sum, count) for the
    standard sum/÷count train-step contract (count = batch size so the
    logged loss is per-sample)."""
    target = (x1 - x0).astype(jnp.float32)
    err = jnp.mean(
        jnp.square(velocity_pred.astype(jnp.float32) - target),
        axis=tuple(range(1, x0.ndim)),
    )                                                   # (B,)
    w = loss_weight(sigma, weighting, shift)
    return jnp.sum(w * err), jnp.float32(x0.shape[0])


def euler_sample(
    velocity_fn,                 # (x, sigma (B,)) -> v
    rng: jax.Array,
    shape: tuple,
    *,
    steps: int = 16,
    shift: float = 3.0,
) -> jnp.ndarray:
    """Integrate dx/dσ = v from σ=1 (noise) to σ=0 (data) on the shifted
    grid — the rectified-flow Euler sampler. `rng` seeds the initial noise."""
    x = jax.random.normal(rng, shape)
    grid = time_shift(jnp.linspace(1.0, 0.0, steps + 1), shift)
    for i in range(steps):
        s_now, s_next = grid[i], grid[i + 1]
        sig = jnp.full((shape[0],), s_now)
        v = velocity_fn(x, sig)
        x = x + (s_next - s_now) * v
    return x
