"""Flow-matching model adapters: decouple the training pipeline from
model-specific conditioning.

The analog of the reference's adapter layer (reference: nemo_automodel/
components/flow_matching/adapters/base.py `ModelAdapter` +
`FlowMatchingContext`, simple.py `SimpleAdapter` — the Wan-style
hidden_states/timestep/encoder_hidden_states interface; flux.py/
qwen_image.py follow the same contract with richer inputs). An adapter
turns a `FlowMatchingContext` into model inputs and runs the forward; the
diffusion recipe stays model-agnostic.

Adapters here:
- "class": the class-conditional DiT path (labels + CFG label dropout).
- "simple": Wan-layout text conditioning — `encoder_hidden_states` from
  the batch's `text_embeddings`, with CFG dropout zeroing the embeddings
  (base.py cfg_dropout_prob semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class FlowMatchingContext:
    """What the pipeline hands every adapter (reference: base.py:30)."""

    noisy_latents: jnp.ndarray   # (B, H, W, C) x_sigma
    latents: jnp.ndarray         # (B, H, W, C) clean
    sigma: jnp.ndarray           # (B,)
    batch: Dict[str, Any]
    rng: jax.Array               # CFG dropout randomness
    cfg_dropout_prob: float = 0.0


class ClassConditionalAdapter:
    """The DiT class-label path (CFG drops to the null class)."""

    name = "class"

    def prepare_inputs(self, cfg, context: FlowMatchingContext) -> dict:
        labels = context.batch.get("class_labels")
        if labels is not None and cfg.num_classes > 0 and context.cfg_dropout_prob > 0:
            drop = jax.random.uniform(context.rng, (labels.shape[0],)) < context.cfg_dropout_prob
            labels = jnp.where(drop, cfg.num_classes, labels)
        return {
            "latents": context.noisy_latents,
            "sigma": context.sigma,
            "class_labels": labels,
        }

    def forward(self, module, params, cfg, inputs, mesh_ctx=None):
        return module.forward(params, cfg, mesh_ctx=mesh_ctx, **inputs)


class SimpleAdapter:
    """Wan-style text conditioning (reference: adapters/simple.py): the
    batch carries precomputed `text_embeddings` (B, L, Dtext); CFG dropout
    zeroes whole samples' embeddings (the null condition)."""

    name = "simple"

    def prepare_inputs(self, cfg, context: FlowMatchingContext) -> dict:
        text = context.batch.get("text_embeddings")
        if text is None:
            raise ValueError(
                "SimpleAdapter needs batch['text_embeddings'] "
                "(B, L, cross_attention_dim)"
            )
        if context.cfg_dropout_prob > 0:
            drop = (
                jax.random.uniform(context.rng, (text.shape[0],))
                < context.cfg_dropout_prob
            )
            text = jnp.where(drop[:, None, None], 0.0, text)
        return {
            "latents": context.noisy_latents,
            "sigma": context.sigma,
            "encoder_hidden_states": text,
        }

    def forward(self, module, params, cfg, inputs, mesh_ctx=None):
        return module.forward(params, cfg, mesh_ctx=mesh_ctx, **inputs)


ADAPTERS = {
    "class": ClassConditionalAdapter,
    "simple": SimpleAdapter,
}


def get_flow_adapter(name: str):
    try:
        return ADAPTERS[name]()
    except KeyError:
        raise ValueError(
            f"unknown flow-matching adapter '{name}' (known: {sorted(ADAPTERS)})"
        ) from None
