"""Qwen3-Next: hybrid gated-delta-net (linear attention) + gated full
attention, with an optional MoE MLP.

TPU-native re-design of the reference family (reference: nemo_automodel/
components/models/qwen3_next/layers.py `Qwen3NextFp32GatedDeltaNet`,
`Qwen3NextAttention`; model.py `Qwen3NextModel`; HF transformers
modeling_qwen3_next.py is the numerical oracle):

- The gated delta rule runs as a `lax.scan` over the sequence carrying the
  (B, Hv, dk, dv) fp32 state: S ← S·exp(g) ; Δ = β·(v − Sᵀk) ; S ← S + kΔᵀ;
  o = Sᵀq. Exact recurrence of HF's `torch_recurrent_gated_delta_rule`.
  (A chunked parallel form is the planned perf upgrade; the scan is the
  correctness baseline and already O(T) with static shapes.)
- The depthwise causal conv over the flattened q|k|v channels is one
  grouped `lax.conv_general_dilated` with left padding — no conv-state
  cache object.
- Full-attention layers reuse the shared attention ops with two additions:
  the doubled q projection whose second half sigmoid-gates the attention
  output, and partial RoPE (rotary over the first quarter of head_dim).
- Norms are zero-centered ((1+w)·x̂, like gemma); the GDN output norm is
  the gated RMSNorm w·x̂·silu(z) per value head.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.layer import init_moe, moe_forward, moe_param_specs
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope, rope_frequencies


@dataclasses.dataclass
class Qwen3NextConfig:
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    layer_types: tuple  # per layer: "linear_attention" | "full_attention"
    # gated delta net
    linear_num_value_heads: int
    linear_num_key_heads: int
    linear_key_head_dim: int
    linear_value_head_dim: int
    linear_conv_kernel_dim: int = 4
    # moe (None → dense MLP)
    moe: Optional[MoEConfig] = None
    partial_rotary_factor: float = 0.25
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    logits_soft_cap: Optional[float] = None
    dtype: jnp.dtype = jnp.float32
    remat_policy: Optional[str] = "full"
    scan_unroll: int = 1
    # gated-delta-net impl: "scan" (sequential oracle), "chunked" (WY block
    # form), or "auto" (chunked once S outgrows one chunk)
    gdn_impl: str = "auto"
    gdn_chunk: int = 64
    mtp_num_layers: int = 0  # chassis compatibility

    def __post_init__(self):
        assert len(self.layer_types) == self.num_layers
        assert self.linear_num_value_heads % self.linear_num_key_heads == 0

    @property
    def gdn_key_dim(self) -> int:
        return self.linear_key_head_dim * self.linear_num_key_heads

    @property
    def gdn_value_dim(self) -> int:
        return self.linear_value_head_dim * self.linear_num_value_heads

    @property
    def rotary_dim(self) -> int:
        return int(self.head_dim * self.partial_rotary_factor)

    def flops_per_token(self, seq_len: int) -> float:
        H, I = self.hidden_size, self.intermediate_size
        n_full = sum(1 for t in self.layer_types if t == "full_attention")
        n_lin = self.num_layers - n_full
        attn_p = H * (2 * self.num_heads + 2 * self.num_kv_heads) * self.head_dim + self.num_heads * self.head_dim * H
        gdn_p = H * (2 * self.gdn_key_dim + 2 * self.gdn_value_dim + 2 * self.linear_num_value_heads) + self.gdn_value_dim * H
        if self.moe is not None:
            mlp_p = 3 * H * self.moe.moe_intermediate_size * self.moe.experts_per_token
            if self.moe.n_shared_experts:
                mlp_p += 3 * H * self.moe.shared_intermediate
        else:
            mlp_p = 3 * H * I
        n_params = self.vocab_size * H * (1 if self.tie_word_embeddings else 2) + n_full * attn_p + n_lin * gdn_p + self.num_layers * mlp_p
        return 6.0 * n_params + 6 * n_full * self.num_heads * self.head_dim * seq_len


def from_hf_config(
    hf: dict, dtype=jnp.float32, remat_policy="full", **overrides
) -> Qwen3NextConfig:
    """Build from an HF Qwen3NextConfig dict. Unknown recipe overrides
    (attn_impl etc. meant for the generic decoder) are ignored."""
    overrides = {
        k: v for k, v in overrides.items()
        if k in {f.name for f in dataclasses.fields(Qwen3NextConfig)}
    }
    L = int(hf["num_hidden_layers"])
    layer_types = hf.get("layer_types")
    if layer_types is None:
        interval = int(hf.get("full_attention_interval", 4))
        layer_types = [
            "full_attention" if (i + 1) % interval == 0 else "linear_attention"
            for i in range(L)
        ]
    moe = None
    if int(hf.get("num_experts", 0) or 0) > 0:
        sparse_step = int(hf.get("decoder_sparse_step", 1) or 1)
        mlp_only = list(hf.get("mlp_only_layers") or [])
        if sparse_step != 1 or mlp_only:
            raise NotImplementedError(
                f"qwen3-next with decoder_sparse_step={sparse_step} / "
                f"mlp_only_layers={mlp_only}: per-layer dense/MoE mixing is "
                "not implemented — every layer would be built MoE, a "
                "different architecture than HF"
            )
        moe = MoEConfig(
            n_routed_experts=int(hf["num_experts"]),
            experts_per_token=int(hf["num_experts_per_tok"]),
            moe_intermediate_size=int(hf["moe_intermediate_size"]),
            n_shared_experts=1 if int(hf.get("shared_expert_intermediate_size", 0)) else 0,
            shared_expert_intermediate_size=int(hf.get("shared_expert_intermediate_size", 0)),
            score_func="softmax",
            norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
            aux_loss_coeff=float(hf.get("router_aux_loss_coef", 0.0) or 0.0),
            shared_expert_gated=True,
            dispatcher="dropless",  # HF never drops tokens; match it
        )
    return Qwen3NextConfig(
        vocab_size=int(hf["vocab_size"]),
        hidden_size=int(hf["hidden_size"]),
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=L,
        num_heads=int(hf["num_attention_heads"]),
        num_kv_heads=int(hf["num_key_value_heads"]),
        head_dim=int(hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]),
        layer_types=tuple(layer_types),
        linear_num_value_heads=int(hf["linear_num_value_heads"]),
        linear_num_key_heads=int(hf["linear_num_key_heads"]),
        linear_key_head_dim=int(hf["linear_key_head_dim"]),
        linear_value_head_dim=int(hf["linear_value_head_dim"]),
        linear_conv_kernel_dim=int(hf.get("linear_conv_kernel_dim", 4)),
        moe=moe,
        partial_rotary_factor=float(hf.get("partial_rotary_factor", 0.25)),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
        dtype=dtype,
        remat_policy=remat_policy,
        **overrides,
    )


# ---------------------------------------------------------------------------
# init / specs — layers are stacked per type (two scans, interleaved order
# preserved via the layer_types tuple)
# ---------------------------------------------------------------------------
def _init_gdn(cfg: Qwen3NextConfig, rng, n) -> dict:
    H = cfg.hidden_size
    Kd, Vd = cfg.gdn_key_dim, cfg.gdn_value_dim
    Hv = cfg.linear_num_value_heads
    conv_dim = 2 * Kd + Vd
    ks = jax.random.split(rng, 4)

    def stack(k, shape):
        return jnp.stack([dense_init(kk, shape) for kk in jax.random.split(k, n)])

    return {
        "in_proj_qkvz": {"kernel": stack(ks[0], (H, 2 * Kd + 2 * Vd))},
        "in_proj_ba": {"kernel": stack(ks[1], (H, 2 * Hv))},
        "conv": {"kernel": 0.2 * jax.random.normal(ks[2], (n, cfg.linear_conv_kernel_dim, conv_dim))},
        "dt_bias": jnp.ones((n, Hv)),
        "A_log": jnp.log(jax.random.uniform(ks[3], (n, Hv), minval=1e-3, maxval=16.0)),
        "norm": {"scale": jnp.ones((n, cfg.linear_value_head_dim))},
        "out_proj": {"kernel": stack(jax.random.fold_in(ks[2], 1), (Vd, H))},
    }


def _gdn_specs(cfg) -> dict:
    return {
        "in_proj_qkvz": {"kernel": ("layers", "embed", "heads")},
        "in_proj_ba": {"kernel": ("layers", "embed", "heads")},
        "conv": {"kernel": ("layers", None, "heads")},
        "dt_bias": ("layers", "heads"),
        "A_log": ("layers", "heads"),
        "norm": {"scale": ("layers", "norm")},
        "out_proj": {"kernel": ("layers", "heads", "embed")},
    }


def _init_attn(cfg: Qwen3NextConfig, rng, n) -> dict:
    H, D = cfg.hidden_size, cfg.head_dim
    ks = jax.random.split(rng, 4)

    def stack(k, shape):
        return jnp.stack([dense_init(kk, shape) for kk in jax.random.split(k, n)])

    return {
        "q_proj": {"kernel": stack(ks[0], (H, cfg.num_heads * D * 2))},
        "k_proj": {"kernel": stack(ks[1], (H, cfg.num_kv_heads * D))},
        "v_proj": {"kernel": stack(ks[2], (H, cfg.num_kv_heads * D))},
        "o_proj": {"kernel": stack(ks[3], (cfg.num_heads * D, H))},
        "q_norm": {"scale": jnp.zeros((n, D))},
        "k_norm": {"scale": jnp.zeros((n, D))},
    }


def _attn_specs(cfg) -> dict:
    return {
        "q_proj": {"kernel": ("layers", "embed", "heads")},
        "k_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "v_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "o_proj": {"kernel": ("layers", "heads", "embed")},
        "q_norm": {"scale": ("layers", "norm")},
        "k_norm": {"scale": ("layers", "norm")},
    }


def _init_mlp(cfg: Qwen3NextConfig, rng, n) -> dict:
    if cfg.moe is not None:
        return {
            "moe": jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[init_moe(cfg.moe, cfg.hidden_size, jax.random.fold_in(rng, i)) for i in range(n)],
            )
        }
    H, I = cfg.hidden_size, cfg.intermediate_size
    ks = jax.random.split(rng, 3)

    def stack(k, shape):
        return jnp.stack([dense_init(kk, shape) for kk in jax.random.split(k, n)])

    return {
        "gate_proj": {"kernel": stack(ks[0], (H, I))},
        "up_proj": {"kernel": stack(ks[1], (H, I))},
        "down_proj": {"kernel": stack(ks[2], (I, H))},
    }


def _mlp_specs(cfg) -> dict:
    if cfg.moe is not None:
        inner = moe_param_specs(cfg.moe)
        return {"moe": jax.tree.map(
            lambda s: ("layers",) + s,
            inner,
            is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x),
        )}
    return {
        "gate_proj": {"kernel": ("layers", "embed", "mlp")},
        "up_proj": {"kernel": ("layers", "embed", "mlp")},
        "down_proj": {"kernel": ("layers", "mlp", "embed")},
    }


def init(cfg: Qwen3NextConfig, rng: jax.Array) -> dict:
    n_lin = sum(1 for t in cfg.layer_types if t == "linear_attention")
    n_full = cfg.num_layers - n_lin
    ks = jax.random.split(rng, 6)
    # all-linear / all-full stacks keep a 1-layer dummy so the pytree
    # structure (and its specs/shardings) is config-independent
    params = {
        "embed": {"embedding": 0.02 * jax.random.normal(ks[0], (cfg.vocab_size, cfg.hidden_size))},
        "gdn_layers": _init_gdn(cfg, ks[1], max(n_lin, 1)),
        "attn_layers": _init_attn(cfg, ks[2], max(n_full, 1)),
        "mlp_layers": _init_mlp(cfg, ks[3], cfg.num_layers),
        "input_norms": {"scale": jnp.zeros((cfg.num_layers, cfg.hidden_size))},
        "post_norms": {"scale": jnp.zeros((cfg.num_layers, cfg.hidden_size))},
        "final_norm": {"scale": jnp.zeros((cfg.hidden_size,))},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense_init(ks[4], (cfg.hidden_size, cfg.vocab_size))}
    return params


def param_specs(cfg: Qwen3NextConfig) -> dict:
    specs = {
        "embed": {"embedding": ("vocab", "embed")},
        "gdn_layers": _gdn_specs(cfg),
        "attn_layers": _attn_specs(cfg),
        "mlp_layers": _mlp_specs(cfg),
        "input_norms": {"scale": ("layers", "norm")},
        "post_norms": {"scale": ("layers", "norm")},
        "final_norm": {"scale": ("norm",)},
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"kernel": ("embed", "vocab")}
    return specs


# ---------------------------------------------------------------------------
# gated delta net forward
# ---------------------------------------------------------------------------
def _l2norm(x, eps=1e-6):
    return x * jax.lax.rsqrt(jnp.sum(jnp.square(x), -1, keepdims=True) + eps)


def gated_delta_rule(q, k, v, g, beta):
    """Sequential gated delta rule (HF `torch_recurrent_gated_delta_rule`
    oracle semantics; q,k already L2-normed and q scaled).

    q,k (B,S,Hv,dk); v (B,S,Hv,dv); g,beta (B,S,Hv). Returns (B,S,Hv,dv).
    """
    B, S, Hv, dk = q.shape
    dv = v.shape[-1]

    def step(S_state, xs):
        q_t, k_t, v_t, g_t, b_t = xs  # (B,Hv,dk),(B,Hv,dk),(B,Hv,dv),(B,Hv),(B,Hv)
        S_state = S_state * jnp.exp(g_t)[..., None, None]
        kv_mem = jnp.einsum("bhkv,bhk->bhv", S_state, k_t)
        delta = (v_t - kv_mem) * b_t[..., None]
        S_state = S_state + k_t[..., :, None] * delta[..., None, :]
        o_t = jnp.einsum("bhkv,bhk->bhv", S_state, q_t)
        return S_state, o_t

    xs = jax.tree.map(lambda x: jnp.moveaxis(x, 1, 0), (q, k, v, g, beta))
    S0 = jnp.zeros((B, Hv, dk, dv), jnp.float32)
    _, outs = jax.lax.scan(step, S0, xs)
    return jnp.moveaxis(outs, 0, 1)  # (B,S,Hv,dv)


def gated_delta_rule_chunked(q, k, v, g, beta, chunk: int = 64):
    """Chunked (block-parallel) gated delta rule — same contract as
    `gated_delta_rule` (q pre-scaled, q/k pre-l2normed).

    Algorithm oracle: HF transformers `torch_chunk_gated_delta_rule`
    (modeling_qwen3_next.py) — the WY/UT-transform chunk decomposition of
    the delta rule. TPU-native differences: the in-chunk unit-lower-
    triangular inverse is a batched `solve_triangular` (one MXU-friendly
    solve instead of HF's per-row Python loop), and the inter-chunk
    recurrence is a `lax.scan` over S/chunk steps carrying the (dk, dv)
    state.
    """
    B, S, Hv, dk = q.shape
    dv = v.shape[-1]
    pad = (-S) % chunk
    if pad:
        p2 = lambda a: jnp.pad(a, [(0, 0), (0, pad)] + [(0, 0)] * (a.ndim - 2))
        q, k, v, g, beta = p2(q), p2(k), p2(v), p2(g), p2(beta)
    T = S + pad
    Nc, Q = T // chunk, chunk

    def ch(a):  # (B,T,H,...) → (B,H,Nc,Q,...)
        a = jnp.swapaxes(a, 1, 2)
        return a.reshape((B, Hv, Nc, Q) + a.shape[3:])

    qc, kc, vc = ch(q), ch(k), ch(v.astype(jnp.float32))
    gc, bc = ch(g.astype(jnp.float32)), ch(beta.astype(jnp.float32))
    v_beta = vc * bc[..., None]
    k_beta = kc * bc[..., None]
    gcum = jnp.cumsum(gc, axis=-1)                     # (B,H,Nc,Q)

    ii = jnp.arange(Q)
    tril = ii[:, None] >= ii[None, :]
    tril_s = ii[:, None] > ii[None, :]
    # mask BEFORE exp: upper-triangle diffs are sums of |g| over the interval
    # and can exceed the fp32 exp range (~88.7) → inf, whose where-VJP would
    # send 0·inf = NaN into the A_log/dt_bias gradients
    dmask = jnp.exp(
        jnp.where(tril, gcum[..., :, None] - gcum[..., None, :], -jnp.inf)
    )                                                   # (B,H,Nc,Q,Q)
    A = jnp.where(
        tril_s, jnp.einsum("bhcik,bhcjk->bhcij", k_beta, kc) * dmask, 0.0
    )
    M = jnp.eye(Q, dtype=A.dtype) + A                  # unit lower triangular
    u = jax.scipy.linalg.solve_triangular(M, v_beta, lower=True)
    w = jax.scipy.linalg.solve_triangular(
        M, k_beta * jnp.exp(gcum)[..., None], lower=True
    )

    def step(S_state, xs):  # S_state (B,H,dk,dv)
        q_i, k_i, u_i, w_i, gc_i, dm_i = xs
        v_prime = jnp.einsum("bhqk,bhkv->bhqv", w_i, S_state)
        v_new = u_i - v_prime
        attn_local = jnp.einsum("bhik,bhjk->bhij", q_i, k_i) * dm_i
        out_i = (
            jnp.einsum("bhqk,bhkv->bhqv", q_i * jnp.exp(gc_i)[..., None], S_state)
            + jnp.einsum("bhij,bhjv->bhiv", attn_local, v_new)
        )
        g_last = gc_i[..., -1:]
        S_state = S_state * jnp.exp(g_last)[..., None] + jnp.einsum(
            "bhqk,bhqv->bhkv", k_i * jnp.exp(g_last - gc_i)[..., None], v_new
        )
        return S_state, out_i

    xs = jax.tree.map(
        lambda a: jnp.moveaxis(a, 2, 0), (qc, kc, u, w, gcum, dmask)
    )
    S0 = jnp.zeros((B, Hv, dk, dv), jnp.float32)
    _, outs = jax.lax.scan(step, S0, xs)                # (Nc,B,H,Q,dv)
    out = jnp.moveaxis(outs, 0, 2).reshape(B, Hv, T, dv)[:, :, :S]
    return jnp.swapaxes(out, 1, 2)                     # (B,S,Hv,dv)


def _gdn_block(x, lp, cfg: Qwen3NextConfig):
    """x (B,S,H) normed input → GDN output (B,S,H)."""
    B, S, H = x.shape
    Hk, Hv = cfg.linear_num_key_heads, cfg.linear_num_value_heads
    dk, dv = cfg.linear_key_head_dim, cfg.linear_value_head_dim
    gv = Hv // Hk
    Kd, Vd = cfg.gdn_key_dim, cfg.gdn_value_dim
    dtype = x.dtype

    qkvz = x @ lp["in_proj_qkvz"]["kernel"].astype(dtype)   # (B,S,2Kd+2Vd)
    ba = x @ lp["in_proj_ba"]["kernel"].astype(dtype)       # (B,S,2Hv)

    # HF interleaved-per-key-head layout (fix_query_key_value_ordering)
    qkvz = qkvz.reshape(B, S, Hk, 2 * dk + 2 * gv * dv)
    q = qkvz[..., :dk]
    k = qkvz[..., dk : 2 * dk]
    v = qkvz[..., 2 * dk : 2 * dk + gv * dv].reshape(B, S, Hv, dv)
    z = qkvz[..., 2 * dk + gv * dv :].reshape(B, S, Hv, dv)
    ba = ba.reshape(B, S, Hk, 2 * gv)
    b = ba[..., :gv].reshape(B, S, Hv)
    a = ba[..., gv:].reshape(B, S, Hv)

    # depthwise causal conv over flattened q|k|v channels, then silu
    mixed = jnp.concatenate(
        [q.reshape(B, S, Kd), k.reshape(B, S, Kd), v.reshape(B, S, Vd)], axis=-1
    )
    K_ = cfg.linear_conv_kernel_dim
    conv_w = lp["conv"]["kernel"].astype(dtype)             # (K, C)
    mixed = jax.lax.conv_general_dilated(
        mixed,
        conv_w[:, None, :],                                 # (K, 1, C) = WIO
        window_strides=(1,),
        padding=[(K_ - 1, 0)],
        dimension_numbers=("NWC", "WIO", "NWC"),
        feature_group_count=mixed.shape[-1],
    )
    mixed = jax.nn.silu(mixed)
    q = mixed[..., :Kd].reshape(B, S, Hk, dk)
    k = mixed[..., Kd : 2 * Kd].reshape(B, S, Hk, dk)
    v = mixed[..., 2 * Kd :].reshape(B, S, Hv, dv)

    q = _l2norm(q.astype(jnp.float32)) * dk ** -0.5
    k = _l2norm(k.astype(jnp.float32))
    q = jnp.repeat(q, gv, axis=2)
    k = jnp.repeat(k, gv, axis=2)

    beta = jax.nn.sigmoid(b.astype(jnp.float32))
    # decay (fp32: A_log/dt_bias stay full precision, reference layers.py:79)
    g = -jnp.exp(lp["A_log"].astype(jnp.float32)) * jax.nn.softplus(
        a.astype(jnp.float32) + lp["dt_bias"].astype(jnp.float32)
    )

    use_chunked = cfg.gdn_impl == "chunked" or (
        cfg.gdn_impl == "auto" and S > cfg.gdn_chunk
    )
    if use_chunked:
        core = gated_delta_rule_chunked(
            q, k, v.astype(jnp.float32), g, beta, chunk=cfg.gdn_chunk
        )
    else:
        core = gated_delta_rule(q, k, v.astype(jnp.float32), g, beta)

    # gated RMSNorm per value head: w·x̂·silu(z) (NOT zero-centered)
    core = rms_norm(core, lp["norm"]["scale"], cfg.rms_norm_eps)
    core = core * jax.nn.silu(z.astype(jnp.float32))
    core = core.reshape(B, S, Vd).astype(dtype)
    return core @ lp["out_proj"]["kernel"].astype(dtype)


def _attn_block(x, lp, cfg: Qwen3NextConfig, positions, segment_ids, inv_freq, mesh_ctx):
    from automodel_tpu.ops.attention import dot_product_attention

    B, S, H = x.shape
    D = cfg.head_dim
    dtype = x.dtype
    q2 = (x @ lp["q_proj"]["kernel"].astype(dtype)).reshape(B, S, cfg.num_heads, 2 * D)
    q, gate = q2[..., :D], q2[..., D:]
    k = (x @ lp["k_proj"]["kernel"].astype(dtype)).reshape(B, S, cfg.num_kv_heads, D)
    v = (x @ lp["v_proj"]["kernel"].astype(dtype)).reshape(B, S, cfg.num_kv_heads, D)
    q = rms_norm(q, lp["q_norm"]["scale"], cfg.rms_norm_eps, zero_centered=True)
    k = rms_norm(k, lp["k_norm"]["scale"], cfg.rms_norm_eps, zero_centered=True)
    # apply_rope rotates only the first 2*len(inv_freq)=rotary_dim channels
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    attn = dot_product_attention(
        q, k, v, causal=True, segment_ids=segment_ids, positions=positions,
        impl="xla",
    )
    attn = attn * jax.nn.sigmoid(gate.astype(attn.dtype))
    return attn.reshape(B, S, cfg.num_heads * D) @ lp["o_proj"]["kernel"].astype(dtype)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def forward(
    params: dict,
    cfg: Qwen3NextConfig,
    input_ids: jnp.ndarray,
    *,
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    return_stats: bool = False,
    token_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Returns logits (or hidden). With MoE, returns (out, aux_loss[, stats])."""
    from automodel_tpu.models.common.layers import cast_params, maybe_remat

    # A_log/dt_bias must stay fp32 under bf16 compute — the exp(A_log) decay
    # compounds through the recurrence (reference: Qwen3NextFp32GatedDeltaNet,
    # layers.py:79). Restore them after the blanket cast.
    fp32_gdn = {k: params["gdn_layers"][k] for k in ("A_log", "dt_bias")}
    params = cast_params(params, cfg.dtype)
    params["gdn_layers"] = {**params["gdn_layers"], **fp32_gdn}
    B, S = input_ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    inv_freq = rope_frequencies(cfg.rotary_dim, cfg.rope_theta)

    h = jnp.take(params["embed"]["embedding"], input_ids, axis=0).astype(cfg.dtype)

    lin_idx = 0
    full_idx = 0
    aux_total = jnp.float32(0.0)
    stats_list = []
    # interleaved hybrid stack: a Python loop over layers (layer types are
    # static); remat per layer
    for i, lt in enumerate(cfg.layer_types):
        ln_in = params["input_norms"]["scale"][i]
        ln_post = params["post_norms"]["scale"][i]

        def one_layer(h, _ps=params, _i=i, _lt=lt, _li=lin_idx, _fi=full_idx,
                      _ln_in=ln_in, _ln_post=ln_post):
            x = rms_norm(h, _ln_in, cfg.rms_norm_eps, zero_centered=True)
            if _lt == "linear_attention":
                lp = jax.tree.map(lambda p: p[_li], _ps["gdn_layers"])
                h = h + _gdn_block(x, lp, cfg)
            else:
                lp = jax.tree.map(lambda p: p[_fi], _ps["attn_layers"])
                h = h + _attn_block(x, lp, cfg, positions, segment_ids, inv_freq, mesh_ctx)
            x2 = rms_norm(h, _ln_post, cfg.rms_norm_eps, zero_centered=True)
            if cfg.moe is not None:
                mp = jax.tree.map(lambda p: p[_i], _ps["mlp_layers"]["moe"])
                out, aux, st = moe_forward(
                    mp, cfg.moe, x2, token_mask=token_mask, mesh_ctx=mesh_ctx
                )
                return h + out, aux, st
            mp = jax.tree.map(lambda p: p[_i], _ps["mlp_layers"])
            mlp = jax.nn.silu(x2 @ mp["gate_proj"]["kernel"]) * (x2 @ mp["up_proj"]["kernel"])
            return h + mlp @ mp["down_proj"]["kernel"], None, None

        h, aux, st = maybe_remat(lambda hh: one_layer(hh), cfg.remat_policy)(h)
        if aux is not None:
            aux_total = aux_total + aux
            stats_list.append(st["tokens_per_expert"])
        if lt == "linear_attention":
            lin_idx += 1
        else:
            full_idx += 1

    h = rms_norm(h, params["final_norm"]["scale"], cfg.rms_norm_eps, zero_centered=True)
    if return_hidden:
        out = h
    else:
        kernel = (
            params["embed"]["embedding"].T
            if cfg.tie_word_embeddings
            else params["lm_head"]["kernel"]
        )
        out = jnp.einsum("bsh,hv->bsv", h, kernel.astype(h.dtype), preferred_element_type=jnp.float32)
    if cfg.moe is not None:
        if return_stats:
            return out, aux_total, {"tokens_per_expert": jnp.stack(stats_list)}
        return out, aux_total
    return out


# ---------------------------------------------------------------------------
# HF state-dict adapter (reference: qwen3_next/state_dict_adapter.py —
# re-derived from the HF module layout, not translated)
# ---------------------------------------------------------------------------
class Qwen3NextAdapter:
    """from_hf for Qwen3NextForCausalLM safetensors checkpoints."""

    def __init__(self, cfg: Qwen3NextConfig):
        self.cfg = cfg

    def from_hf(self, read, shardings=None) -> dict:
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get, _set

        cfg = self.cfg
        params: dict = {}

        def put(tree, path, value):
            # stream each tensor straight into its sharded layout — never
            # hold the whole checkpoint unsharded (DenseDecoderAdapter idiom)
            sh = _get(shardings, path) if shardings is not None else None
            _set(tree, path, jax.device_put(value, sh) if sh is not None else jnp.asarray(value))
        put(params, ("embed", "embedding"), read("model.embed_tokens.weight"))
        put(params, ("final_norm", "scale"), read("model.norm.weight"))
        if not cfg.tie_word_embeddings:
            put(params, ("lm_head", "kernel"), np.ascontiguousarray(read("lm_head.weight").T))

        L = cfg.num_layers
        in_norms = np.stack([read(f"model.layers.{i}.input_layernorm.weight") for i in range(L)])
        post_norms = np.stack([read(f"model.layers.{i}.post_attention_layernorm.weight") for i in range(L)])
        put(params, ("input_norms", "scale"), in_norms)
        put(params, ("post_norms", "scale"), post_norms)

        lin_ids = [i for i, t in enumerate(cfg.layer_types) if t == "linear_attention"]
        full_ids = [i for i, t in enumerate(cfg.layer_types) if t == "full_attention"]

        def stackT(fmt, ids):
            return np.stack([np.ascontiguousarray(read(fmt.format(i)).T) for i in ids])

        def stack_(fmt, ids):
            return np.stack([read(fmt.format(i)) for i in ids])

        g = "model.layers.{}.linear_attn."
        if lin_ids:
            put(params, ("gdn_layers", "in_proj_qkvz", "kernel"), stackT(g + "in_proj_qkvz.weight", lin_ids))
            put(params, ("gdn_layers", "in_proj_ba", "kernel"), stackT(g + "in_proj_ba.weight", lin_ids))
            # HF conv1d.weight is (C, 1, K) depthwise → ours (K, C)
            conv = np.stack([
                np.ascontiguousarray(read((g + "conv1d.weight").format(i))[:, 0, :].T)
                for i in lin_ids
            ])
            put(params, ("gdn_layers", "conv", "kernel"), conv)
            put(params, ("gdn_layers", "dt_bias"), stack_(g + "dt_bias", lin_ids))
            put(params, ("gdn_layers", "A_log"), stack_(g + "A_log", lin_ids))
            put(params, ("gdn_layers", "norm", "scale"), stack_(g + "norm.weight", lin_ids))
            put(params, ("gdn_layers", "out_proj", "kernel"), stackT(g + "out_proj.weight", lin_ids))
        else:  # keep pytree structure (dummy 1-layer stack)
            params["gdn_layers"] = init(cfg, jax.random.key(0))["gdn_layers"]

        a = "model.layers.{}.self_attn."
        if full_ids:
            put(params, ("attn_layers", "q_proj", "kernel"), stackT(a + "q_proj.weight", full_ids))
            put(params, ("attn_layers", "k_proj", "kernel"), stackT(a + "k_proj.weight", full_ids))
            put(params, ("attn_layers", "v_proj", "kernel"), stackT(a + "v_proj.weight", full_ids))
            put(params, ("attn_layers", "o_proj", "kernel"), stackT(a + "o_proj.weight", full_ids))
            put(params, ("attn_layers", "q_norm", "scale"), stack_(a + "q_norm.weight", full_ids))
            put(params, ("attn_layers", "k_norm", "scale"), stack_(a + "k_norm.weight", full_ids))
        else:  # keep the pytree structure (init pads one dummy stack)
            dummy = init(cfg, jax.random.key(0))["attn_layers"]
            params["attn_layers"] = dummy

        m = "model.layers.{}.mlp."
        if cfg.moe is not None:
            E = cfg.moe.n_routed_experts
            moe_tree: dict = {}
            put(moe_tree, ("gate", "weight"), stackT(m + "gate.weight", range(L)))
            for proj in ("gate_proj", "up_proj", "down_proj"):
                w = np.stack([
                    np.stack([
                        np.ascontiguousarray(
                            read(f"model.layers.{i}.mlp.experts.{e}.{proj}.weight").T
                        )
                        for e in range(E)
                    ])
                    for i in range(L)
                ])
                put(moe_tree, ("experts", proj, "kernel"), w)
            if cfg.moe.n_shared_experts:
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    put(
                        moe_tree, ("shared", proj, "kernel"),
                        stackT(m + f"shared_expert.{proj}.weight", range(L)),
                    )
                if cfg.moe.shared_expert_gated:
                    put(
                        moe_tree, ("shared", "gate", "kernel"),
                        stackT(m + "shared_expert_gate.weight", range(L)),
                    )
            params["mlp_layers"] = {"moe": moe_tree}
        else:
            for proj in ("gate_proj", "up_proj", "down_proj"):
                put(
                    params, ("mlp_layers", proj, "kernel"),
                    stackT(m + f"{proj}.weight", range(L)),
                )

        return params

    def to_hf(self, params):
        """Yield (hf_name, tensor) — the exact inverse of from_hf, so a
        trained model round-trips back into Qwen3NextForCausalLM layout."""
        import numpy as np

        cfg = self.cfg

        def _t(x):
            return np.ascontiguousarray(np.asarray(x).T)

        yield "model.embed_tokens.weight", np.asarray(params["embed"]["embedding"])
        yield "model.norm.weight", np.asarray(params["final_norm"]["scale"])
        if not cfg.tie_word_embeddings:
            yield "lm_head.weight", _t(params["lm_head"]["kernel"])

        L = cfg.num_layers
        for i in range(L):
            yield (
                f"model.layers.{i}.input_layernorm.weight",
                np.asarray(params["input_norms"]["scale"][i]),
            )
            yield (
                f"model.layers.{i}.post_attention_layernorm.weight",
                np.asarray(params["post_norms"]["scale"][i]),
            )

        lin_ids = [i for i, t in enumerate(cfg.layer_types) if t == "linear_attention"]
        full_ids = [i for i, t in enumerate(cfg.layer_types) if t == "full_attention"]

        gdn = params["gdn_layers"]
        for j, i in enumerate(lin_ids):
            g = f"model.layers.{i}.linear_attn."
            yield g + "in_proj_qkvz.weight", _t(gdn["in_proj_qkvz"]["kernel"][j])
            yield g + "in_proj_ba.weight", _t(gdn["in_proj_ba"]["kernel"][j])
            # ours (K, C) depthwise → HF conv1d.weight (C, 1, K)
            yield g + "conv1d.weight", np.ascontiguousarray(
                np.asarray(gdn["conv"]["kernel"][j]).T[:, None, :]
            )
            yield g + "dt_bias", np.asarray(gdn["dt_bias"][j])
            yield g + "A_log", np.asarray(gdn["A_log"][j])
            yield g + "norm.weight", np.asarray(gdn["norm"]["scale"][j])
            yield g + "out_proj.weight", _t(gdn["out_proj"]["kernel"][j])

        attn = params["attn_layers"]
        for j, i in enumerate(full_ids):
            a = f"model.layers.{i}.self_attn."
            for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
                yield a + f"{proj}.weight", _t(attn[proj]["kernel"][j])
            yield a + "q_norm.weight", np.asarray(attn["q_norm"]["scale"][j])
            yield a + "k_norm.weight", np.asarray(attn["k_norm"]["scale"][j])

        mlp = params["mlp_layers"]
        if cfg.moe is not None:
            moe = mlp["moe"]
            E = cfg.moe.n_routed_experts
            for i in range(L):
                m = f"model.layers.{i}.mlp."
                yield m + "gate.weight", _t(moe["gate"]["weight"][i])
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    w = np.asarray(moe["experts"][proj]["kernel"][i])
                    for e in range(E):
                        yield (
                            f"model.layers.{i}.mlp.experts.{e}.{proj}.weight",
                            np.ascontiguousarray(w[e].T),
                        )
                if cfg.moe.n_shared_experts:
                    for proj in ("gate_proj", "up_proj", "down_proj"):
                        yield (
                            m + f"shared_expert.{proj}.weight",
                            _t(moe["shared"][proj]["kernel"][i]),
                        )
                    if cfg.moe.shared_expert_gated:
                        yield (
                            m + "shared_expert_gate.weight",
                            _t(moe["shared"]["gate"]["kernel"][i]),
                        )
        else:
            for i in range(L):
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    yield (
                        f"model.layers.{i}.mlp.{proj}.weight",
                        _t(mlp[proj]["kernel"][i]),
                    )


def _register_adapter():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["qwen3_next"] = Qwen3NextAdapter


_register_adapter()
