"""Qwen3.5 / Qwen3.5-MoE — the Qwen3-Next hybrid engine with the Qwen3.5
checkpoint layout.

The reference rebuilds both on the Qwen3-Next Block (reference:
nemo_automodel/components/models/qwen3_5/model.py:321 `Qwen3_5DenseBlock`,
qwen3_5_moe/model.py:98 `Qwen3_5MoeBlock`); the architecture differences are
checkpoint-layout only:

- The gated-delta-net projections are SEPARATE linears (`in_proj_qkv` flat
  [q|k|v], `in_proj_z`, `in_proj_b`, `in_proj_a`) instead of Qwen3-Next's
  fused per-key-head-interleaved `in_proj_qkvz`/`in_proj_ba`
  (qwen3_5_moe/cp_linear_attn.py:545-565 vs qwen3_next
  `fix_query_key_value_ordering`).
- MoE expert weights are STACKED (`experts.gate_up_proj` (E, 2I, H),
  `experts.down_proj` (E, H, I)) instead of per-expert
  (qwen3_5_moe/state_dict_adapter.py:19-25).
- VL checkpoints prefix text weights `model.language_model.`.

So: forward/init/param_specs come verbatim from models/hybrid/qwen3_next;
this module contributes config adapters and a state-dict adapter that
synthesizes the Qwen3-Next layout from the Qwen3.5 one (and the exact
inverse for export). MTP sublayers (`mtp.*` keys) are a training-time
auxiliary in the reference and are skipped at load here.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from automodel_tpu.models.hybrid import qwen3_next as qn

# module protocol re-exports: the engine is qwen3-next
init = qn.init
forward = qn.forward
param_specs = qn.param_specs
Qwen3_5Config = qn.Qwen3NextConfig


def _text_config(hf: dict) -> dict:
    """Unwrap `text_config` (VL composite configs) when present."""
    sub = hf.get("text_config")
    if isinstance(sub, dict):
        merged = dict(sub)
        merged.setdefault("tie_word_embeddings", hf.get("tie_word_embeddings", False))
        return merged
    return hf


def qwen3_5_config(hf: dict, **overrides) -> qn.Qwen3NextConfig:
    """Qwen3_5ForCausalLM (dense hybrid)."""
    return qn.from_hf_config(_text_config(hf), **overrides)


def qwen3_5_moe_config(hf: dict, **overrides) -> qn.Qwen3NextConfig:
    """Qwen3_5MoeForConditionalGeneration (text decoder; the vision tower is
    served by the VLM tier)."""
    return qn.from_hf_config(_text_config(hf), **overrides)


class Qwen3_5Adapter(qn.Qwen3NextAdapter):
    """Qwen3.5 checkpoint layout ↔ the qwen3-next params pytree.

    Wraps the parent's from_hf/to_hf with a key-translation layer: prefix
    stripping, GDN projection fuse/split, and expert restacking.

    `vl_prefix`: VL composite checkpoints (ForConditionalGeneration) nest the
    text weights under `model.language_model.`; the dense ForCausalLM does
    not. Import probes the actual layout; export follows this flag.
    """

    def __init__(self, cfg, vl_prefix: bool = True):
        super().__init__(cfg)
        self.vl_prefix = vl_prefix

    # -- GDN projection fuse/split ------------------------------------------
    def _dims(self):
        c = self.cfg
        Hk, dk = c.linear_num_key_heads, c.linear_key_head_dim
        Hv, dv = c.linear_num_value_heads, c.linear_value_head_dim
        return Hk, dk, Hv, dv, Hv // Hk, c.gdn_key_dim, c.gdn_value_dim

    def _fuse_qkvz(self, qkv_w, z_w):
        """HF (2Kd+Vd, H) + (Vd, H) → fused interleaved (2Kd+2Vd, H)."""
        Hk, dk, Hv, dv, gv, Kd, Vd = self._dims()
        H = qkv_w.shape[1]
        qkvT = np.ascontiguousarray(qkv_w.T)  # (H, 2Kd+Vd) flat [q|k|v]
        q = qkvT[:, :Kd].reshape(H, Hk, dk)
        k = qkvT[:, Kd : 2 * Kd].reshape(H, Hk, dk)
        v = qkvT[:, 2 * Kd :].reshape(H, Hk, gv * dv)
        z = np.ascontiguousarray(z_w.T).reshape(H, Hk, gv * dv)
        fusedT = np.concatenate([q, k, v, z], axis=-1).reshape(H, 2 * Kd + 2 * Vd)
        return np.ascontiguousarray(fusedT.T)

    def _split_qkvz(self, fused_w):
        """Inverse of _fuse_qkvz: fused (2Kd+2Vd, H) → (qkv (2Kd+Vd,H), z (Vd,H))."""
        Hk, dk, Hv, dv, gv, Kd, Vd = self._dims()
        H = fused_w.shape[1]
        fT = np.ascontiguousarray(fused_w.T).reshape(H, Hk, 2 * dk + 2 * gv * dv)
        q = fT[..., :dk].reshape(H, Kd)
        k = fT[..., dk : 2 * dk].reshape(H, Kd)
        v = fT[..., 2 * dk : 2 * dk + gv * dv].reshape(H, Vd)
        z = fT[..., 2 * dk + gv * dv :].reshape(H, Vd)
        qkvT = np.concatenate([q, k, v], axis=-1)
        return np.ascontiguousarray(qkvT.T), np.ascontiguousarray(z.T)

    def _fuse_ba(self, b_w, a_w):
        """HF (Hv, H) + (Hv, H) → fused interleaved (2Hv, H)."""
        Hk, dk, Hv, dv, gv, Kd, Vd = self._dims()
        H = b_w.shape[1]
        b = np.ascontiguousarray(b_w.T).reshape(H, Hk, gv)
        a = np.ascontiguousarray(a_w.T).reshape(H, Hk, gv)
        fusedT = np.concatenate([b, a], axis=-1).reshape(H, 2 * Hv)
        return np.ascontiguousarray(fusedT.T)

    def _split_ba(self, fused_w):
        Hk, dk, Hv, dv, gv, Kd, Vd = self._dims()
        H = fused_w.shape[1]
        fT = np.ascontiguousarray(fused_w.T).reshape(H, Hk, 2 * gv)
        b = fT[..., :gv].reshape(H, Hv)
        a = fT[..., gv:].reshape(H, Hv)
        return np.ascontiguousarray(b.T), np.ascontiguousarray(a.T)

    # -- import --------------------------------------------------------------
    def from_hf(self, read, shardings=None) -> dict:
        from automodel_tpu.checkpoint.hf_adapter import memo1_reader, reader_has_key

        read = memo1_reader(read)  # per-expert slicing re-reads stacked tensors
        probe = lambda key: reader_has_key(read, key)  # noqa: E731

        prefix = ""
        if probe("model.language_model.embed_tokens.weight"):
            prefix = "language_model."

        def vread(name):
            """Serve qwen3-next-layout names from the qwen3.5 checkpoint."""
            if name == "lm_head.weight":
                for cand in ("lm_head.weight", "model.lm_head.weight"):
                    if probe(cand):
                        return read(cand)
                raise KeyError(name)
            assert name.startswith("model."), name
            rest = name[len("model."):]
            if ".linear_attn.in_proj_qkvz." in rest:
                base = rest.replace("in_proj_qkvz.weight", "")
                return self._fuse_qkvz(
                    read(f"model.{prefix}{base}in_proj_qkv.weight"),
                    read(f"model.{prefix}{base}in_proj_z.weight"),
                )
            if ".linear_attn.in_proj_ba." in rest:
                base = rest.replace("in_proj_ba.weight", "")
                return self._fuse_ba(
                    read(f"model.{prefix}{base}in_proj_b.weight"),
                    read(f"model.{prefix}{base}in_proj_a.weight"),
                )
            if ".mlp.experts." in rest:
                # "layers.{i}.mlp.experts.{e}.{proj}.weight" ← stacked tensors
                head, _, tail = rest.partition(".mlp.experts.")
                e_str, proj, _w = tail.split(".")
                e = int(e_str)
                I = self.cfg.moe.moe_intermediate_size
                if proj == "down_proj":
                    # stacked (E, H, I); per-expert HF linear is (H, I)
                    return read(f"model.{prefix}{head}.mlp.experts.down_proj")[e]
                gu = read(f"model.{prefix}{head}.mlp.experts.gate_up_proj")[e]  # (2I, H)
                return gu[:I] if proj == "gate_proj" else gu[I:]
            return read(f"model.{prefix}{rest}")

        return super().from_hf(vread, shardings=shardings)

    # -- export --------------------------------------------------------------
    def to_hf(self, params):
        prefix = "language_model." if self.vl_prefix else ""
        I = self.cfg.moe.moe_intermediate_size if self.cfg.moe is not None else 0
        E = self.cfg.moe.n_routed_experts if self.cfg.moe is not None else 0
        # buffer per-expert slices back into the stacked tensors
        gu_buf: dict[str, dict[str, np.ndarray]] = {}
        down_buf: dict[str, dict[str, np.ndarray]] = {}
        for name, tensor in super().to_hf(params):
            if name == "lm_head.weight":
                yield name, tensor
                continue
            rest = name[len("model."):]
            if ".linear_attn.in_proj_qkvz." in rest:
                base = rest.replace("in_proj_qkvz.weight", "")
                qkv, z = self._split_qkvz(tensor)
                yield f"model.{prefix}{base}in_proj_qkv.weight", qkv
                yield f"model.{prefix}{base}in_proj_z.weight", z
                continue
            if ".linear_attn.in_proj_ba." in rest:
                base = rest.replace("in_proj_ba.weight", "")
                b, a = self._split_ba(tensor)
                yield f"model.{prefix}{base}in_proj_b.weight", b
                yield f"model.{prefix}{base}in_proj_a.weight", a
                continue
            if ".mlp.experts." in rest:
                head, _, tail = rest.partition(".mlp.experts.")
                e_str, proj, _w = tail.split(".")
                e = int(e_str)
                if proj == "down_proj":
                    buf = down_buf.setdefault(head, {})
                else:
                    buf = gu_buf.setdefault(head + "|" + proj, {})
                buf[e] = tensor
                full = f"model.{prefix}{head}.mlp.experts."
                if proj == "down_proj" and len(buf) == E:
                    yield full + "down_proj", np.stack([buf[i] for i in range(E)])
                elif proj != "down_proj":
                    gk, uk = head + "|gate_proj", head + "|up_proj"
                    if len(gu_buf.get(gk, {})) == E and len(gu_buf.get(uk, {})) == E:
                        yield full + "gate_up_proj", np.stack(
                            [
                                np.concatenate([gu_buf[gk][i], gu_buf[uk][i]], axis=0)
                                for i in range(E)
                            ]
                        )
                continue
            yield f"model.{prefix}{rest}", tensor


def _register_adapter():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["qwen3_5"] = Qwen3_5Adapter


_register_adapter()
