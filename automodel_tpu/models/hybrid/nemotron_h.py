"""NemotronH / Nemotron-V3: the hybrid Mamba2 + attention + MLP (+ MoE)
family.

TPU-native re-design of the reference family (reference: nemo_automodel/
components/models/nemotron_v3/layers.py `NemotronV3Block` — block pattern
'M' mamba / '*' attention / '-' mlp / 'E' moe; model.py `NemotronV3Model`;
HF transformers NemotronHForCausalLM is the layout oracle for dense
checkpoints). Architecture facts this file encodes:

- every layer is ONE pre-norm mixer block: h += mixer(rmsnorm(h))
  (no attention+MLP pair — the pattern interleaves the sublayer kinds)
- the mamba mixer is exactly the Mamba2 SSD mixer (shared implementation,
  models/hybrid/mamba2.py `_mixer` — lax.scan recurrence, fp32 state)
- attention is plain GQA with NO positional embedding (positions come from
  the mamba recurrences; reference layers.py `NemotronV3Attention` "no
  RoPE")
- dense MLP blocks are non-gated relu² (reference moe/layers.py MLP with
  activation="relu2")
- the MoE variant routes with the DeepSeek-style sigmoid grouped gate,
  1 non-gated relu² shared expert, no aux loss, routed scaling
  (reference model.py:92-113 moe_defaults)

Like qwen3_next, layer params are stacked PER TYPE (mamba/attn/mlp/moe
stacks) with the interleaving preserved by the static pattern tuple, so
each stack shards uniformly over the mesh and remat applies per layer.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.models.hybrid.mamba2 import Mamba2Config, _mixer
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.layer import init_moe, moe_forward, moe_param_specs
from automodel_tpu.ops.norms import rms_norm


@dataclasses.dataclass
class NemotronHConfig:
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    block_pattern: tuple  # per layer: "mamba" | "attention" | "mlp" | "moe"
    # attention
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    attention_bias: bool = False
    # mamba (names mirror Mamba2Config)
    mamba_num_heads: int = 8
    mamba_head_dim: int = 64
    ssm_state_size: int = 128
    n_groups: int = 8
    conv_kernel: int = 4
    use_conv_bias: bool = True
    use_mamba_bias: bool = False
    time_step_limit: tuple = (0.0, float("inf"))
    # mlp / moe
    mlp_bias: bool = False
    moe: Optional[MoEConfig] = None
    residual_in_fp32: bool = True
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    logits_soft_cap: Optional[float] = None
    dtype: jnp.dtype = jnp.float32
    remat_policy: Optional[str] = "full"
    scan_unroll: int = 1
    mtp_num_layers: int = 0  # chassis compatibility

    def __post_init__(self):
        assert len(self.block_pattern) == self.num_layers
        bad = set(self.block_pattern) - {"mamba", "attention", "mlp", "moe"}
        assert not bad, f"unknown block types {bad}"

    @property
    def mamba_cfg(self) -> Mamba2Config:
        """Internal Mamba2Config view so the SSD mixer is shared verbatim."""
        return Mamba2Config(
            vocab_size=1,  # unused by the mixer
            hidden_size=self.hidden_size,
            num_layers=1,
            state_size=self.ssm_state_size,
            num_heads=self.mamba_num_heads,
            head_dim=self.mamba_head_dim,
            n_groups=self.n_groups,
            conv_kernel=self.conv_kernel,
            use_conv_bias=self.use_conv_bias,
            use_bias=self.use_mamba_bias,
            time_step_limit=self.time_step_limit,
            rms_norm_eps=self.rms_norm_eps,
            dtype=self.dtype,
        )

    def _counts(self):
        p = self.block_pattern
        return (
            sum(1 for t in p if t == "mamba"),
            sum(1 for t in p if t == "attention"),
            sum(1 for t in p if t == "mlp"),
            sum(1 for t in p if t == "moe"),
        )

    def flops_per_token(self, seq_len: int) -> float:
        H = self.hidden_size
        n_m, n_a, n_d, n_e = self._counts()
        I_m = self.mamba_num_heads * self.mamba_head_dim
        conv_dim = I_m + 2 * self.n_groups * self.ssm_state_size
        mamba_p = H * (2 * I_m + 2 * self.n_groups * self.ssm_state_size + self.mamba_num_heads) + I_m * H + 2 * I_m * self.ssm_state_size
        attn_p = H * (self.num_heads + 2 * self.num_kv_heads) * self.head_dim + self.num_heads * self.head_dim * H
        mlp_p = 2 * H * self.intermediate_size
        moe_p = 0.0
        if self.moe is not None:
            moe_p = 2 * H * self.moe.moe_intermediate_size * self.moe.experts_per_token
            if self.moe.n_shared_experts:
                moe_p += 2 * H * self.moe.shared_intermediate
        n_params = (
            self.vocab_size * H * (1 if self.tie_word_embeddings else 2)
            + n_m * mamba_p + n_a * attn_p + n_d * mlp_p + n_e * moe_p
        )
        return 6.0 * n_params + 6 * n_a * self.num_heads * self.head_dim * seq_len


_PATTERN_CHARS = {"M": "mamba", "*": "attention", "-": "mlp", "E": "moe"}


def from_hf_config(hf: dict, dtype=jnp.float32, remat_policy="full", **overrides) -> NemotronHConfig:
    """Build from an HF NemotronHConfig dict. Accepts both the
    `hybrid_override_pattern` string ("M-M*-…") and an explicit
    `layers_block_type` list (reference layers.py:666)."""
    overrides = {
        k: v for k, v in overrides.items()
        if k in {f.name for f in dataclasses.fields(NemotronHConfig)}
    }
    L = int(hf["num_hidden_layers"])
    pattern = hf.get("layers_block_type")
    if pattern is None:
        s = hf.get("hybrid_override_pattern")
        if s is None:
            raise ValueError(
                "NemotronH config needs hybrid_override_pattern or layers_block_type"
            )
        unknown = set(s) - set(_PATTERN_CHARS)
        if unknown:
            raise ValueError(
                f"hybrid_override_pattern has unknown block chars {sorted(unknown)}; "
                f"known: {sorted(_PATTERN_CHARS)} (M=mamba, *=attention, -=mlp, E=moe)"
            )
        pattern = [_PATTERN_CHARS[c] for c in s]
    pattern = [
        {"M": "mamba", "*": "attention", "-": "mlp"}.get(t, t) for t in pattern
    ]
    moe = None
    if int(hf.get("n_routed_experts", 0) or 0) > 0:
        moe = MoEConfig(
            n_routed_experts=int(hf["n_routed_experts"]),
            experts_per_token=int(hf.get("num_experts_per_tok", 8)),
            n_groups=int(hf.get("n_group", 1) or 1),
            topk_groups=int(hf.get("topk_group", 1) or 1),
            score_func="sigmoid",
            route_scale=float(hf.get("routed_scaling_factor", 1.0) or 1.0),
            norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
            aux_loss_coeff=0.0,
            moe_intermediate_size=int(hf["moe_intermediate_size"]),
            n_shared_experts=1,
            shared_expert_intermediate_size=int(
                hf.get("moe_shared_expert_intermediate_size")
                or hf["moe_intermediate_size"]
            ),
            expert_activation="relu2",
            shared_expert_activation="relu2",
            expert_bias=bool(hf.get("mlp_bias", False)),
            dispatcher="dropless",
        )
    tsl = hf.get("time_step_limit") or (0.0, float("inf"))
    return NemotronHConfig(
        vocab_size=int(hf["vocab_size"]),
        hidden_size=int(hf["hidden_size"]),
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=L,
        block_pattern=tuple(pattern),
        num_heads=int(hf["num_attention_heads"]),
        num_kv_heads=int(hf.get("num_key_value_heads", hf["num_attention_heads"])),
        head_dim=int(
            hf.get("attention_head_dim")
            or hf.get("head_dim")
            or hf["hidden_size"] // hf["num_attention_heads"]
        ),
        attention_bias=bool(hf.get("attention_bias", False)),
        mamba_num_heads=int(hf.get("mamba_num_heads", 8)),
        mamba_head_dim=int(hf.get("mamba_head_dim", 64)),
        ssm_state_size=int(hf.get("ssm_state_size", 128)),
        n_groups=int(hf.get("n_groups", 8)),
        conv_kernel=int(hf.get("conv_kernel", 4)),
        use_conv_bias=bool(hf.get("use_conv_bias", True)),
        use_mamba_bias=bool(hf.get("use_bias", False)),
        time_step_limit=tuple(tsl),
        mlp_bias=bool(hf.get("mlp_bias", False)),
        moe=moe,
        residual_in_fp32=bool(hf.get("residual_in_fp32", True)),
        rms_norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
        dtype=dtype,
        remat_policy=remat_policy,
        **overrides,
    )


# ---------------------------------------------------------------------------
# init / specs
# ---------------------------------------------------------------------------
def _stack(k, shape, n):
    return jnp.stack([dense_init(kk, shape) for kk in jax.random.split(k, n)])


def _init_mamba_stack(cfg: NemotronHConfig, rng, n) -> dict:
    m = cfg.mamba_cfg
    H, I, Hd = cfg.hidden_size, m.intermediate_size, m.num_heads
    ks = jax.random.split(rng, 3)
    proj_out = 2 * I + 2 * m.n_groups * m.state_size + Hd
    layers = {
        "in_proj": {"kernel": _stack(ks[0], (H, proj_out), n)},
        "conv": {"kernel": 0.2 * jax.random.normal(ks[1], (n, m.conv_kernel, m.conv_dim))},
        "dt_bias": jnp.zeros((n, Hd)),
        "A_log": jnp.log(jnp.broadcast_to(jnp.arange(1, Hd + 1, dtype=jnp.float32), (n, Hd))),
        "D": jnp.ones((n, Hd)),
        "gated_norm": {"scale": jnp.ones((n, I))},
        "out_proj": {"kernel": _stack(ks[2], (I, H), n)},
    }
    if m.use_conv_bias:
        layers["conv"]["bias"] = jnp.zeros((n, m.conv_dim))
    if m.use_bias:
        layers["in_proj"]["bias"] = jnp.zeros((n, proj_out))
        layers["out_proj"]["bias"] = jnp.zeros((n, H))
    return layers


def _mamba_specs(cfg: NemotronHConfig) -> dict:
    m = cfg.mamba_cfg
    specs = {
        "in_proj": {"kernel": ("layers", "embed", "heads")},
        "conv": {"kernel": ("layers", None, "heads")},
        "dt_bias": ("layers", "heads"),
        "A_log": ("layers", "heads"),
        "D": ("layers", "heads"),
        "gated_norm": {"scale": ("layers", "norm")},
        "out_proj": {"kernel": ("layers", "heads", "embed")},
    }
    if m.use_conv_bias:
        specs["conv"]["bias"] = ("layers", "heads")
    if m.use_bias:
        specs["in_proj"]["bias"] = ("layers", "heads")
        specs["out_proj"]["bias"] = ("layers", "norm")
    return specs


def _init_attn_stack(cfg: NemotronHConfig, rng, n) -> dict:
    H, D = cfg.hidden_size, cfg.head_dim
    ks = jax.random.split(rng, 4)
    layers = {
        "q_proj": {"kernel": _stack(ks[0], (H, cfg.num_heads * D), n)},
        "k_proj": {"kernel": _stack(ks[1], (H, cfg.num_kv_heads * D), n)},
        "v_proj": {"kernel": _stack(ks[2], (H, cfg.num_kv_heads * D), n)},
        "o_proj": {"kernel": _stack(ks[3], (cfg.num_heads * D, H), n)},
    }
    if cfg.attention_bias:
        layers["q_proj"]["bias"] = jnp.zeros((n, cfg.num_heads * D))
        layers["k_proj"]["bias"] = jnp.zeros((n, cfg.num_kv_heads * D))
        layers["v_proj"]["bias"] = jnp.zeros((n, cfg.num_kv_heads * D))
        layers["o_proj"]["bias"] = jnp.zeros((n, H))
    return layers


def _attn_specs(cfg: NemotronHConfig) -> dict:
    specs = {
        "q_proj": {"kernel": ("layers", "embed", "heads")},
        "k_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "v_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "o_proj": {"kernel": ("layers", "heads", "embed")},
    }
    if cfg.attention_bias:
        specs["q_proj"]["bias"] = ("layers", "heads")
        specs["k_proj"]["bias"] = ("layers", "kv_heads")
        specs["v_proj"]["bias"] = ("layers", "kv_heads")
        specs["o_proj"]["bias"] = ("layers", "norm")
    return specs


def _init_mlp_stack(cfg: NemotronHConfig, rng, n) -> dict:
    H, I = cfg.hidden_size, cfg.intermediate_size
    ks = jax.random.split(rng, 2)
    layers = {
        "up_proj": {"kernel": _stack(ks[0], (H, I), n)},
        "down_proj": {"kernel": _stack(ks[1], (I, H), n)},
    }
    if cfg.mlp_bias:
        layers["up_proj"]["bias"] = jnp.zeros((n, I))
        layers["down_proj"]["bias"] = jnp.zeros((n, H))
    return layers


def _mlp_specs(cfg: NemotronHConfig) -> dict:
    specs = {
        "up_proj": {"kernel": ("layers", "embed", "mlp")},
        "down_proj": {"kernel": ("layers", "mlp", "embed")},
    }
    if cfg.mlp_bias:
        specs["up_proj"]["bias"] = ("layers", "mlp")
        specs["down_proj"]["bias"] = ("layers", "norm")
    return specs


def init(cfg: NemotronHConfig, rng: jax.Array) -> dict:
    n_m, n_a, n_d, n_e = cfg._counts()
    ks = jax.random.split(rng, 7)
    # each per-type stack keeps a 1-layer dummy when absent so the pytree
    # structure (and its shardings) is pattern-independent
    params = {
        "embed": {"embedding": 0.02 * jax.random.normal(ks[0], (cfg.vocab_size, cfg.hidden_size))},
        "mamba_layers": _init_mamba_stack(cfg, ks[1], max(n_m, 1)),
        "attn_layers": _init_attn_stack(cfg, ks[2], max(n_a, 1)),
        "mlp_layers": _init_mlp_stack(cfg, ks[3], max(n_d, 1)),
        "norms": {"scale": jnp.ones((cfg.num_layers, cfg.hidden_size))},
        "final_norm": {"scale": jnp.ones((cfg.hidden_size,))},
    }
    if n_e or cfg.moe is not None:
        moe_cfg = cfg.moe or MoEConfig()
        params["moe_layers"] = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[
                init_moe(moe_cfg, cfg.hidden_size, jax.random.fold_in(ks[4], i))
                for i in range(max(n_e, 1))
            ],
        )
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense_init(ks[5], (cfg.hidden_size, cfg.vocab_size))}
    return params


def param_specs(cfg: NemotronHConfig) -> dict:
    specs = {
        "embed": {"embedding": ("vocab", "embed")},
        "mamba_layers": _mamba_specs(cfg),
        "attn_layers": _attn_specs(cfg),
        "mlp_layers": _mlp_specs(cfg),
        "norms": {"scale": ("layers", "norm")},
        "final_norm": {"scale": ("norm",)},
    }
    if cfg.moe is not None:
        inner = moe_param_specs(cfg.moe)
        specs["moe_layers"] = jax.tree.map(
            lambda s: ("layers",) + s,
            inner,
            is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x),
        )
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"kernel": ("embed", "vocab")}
    return specs


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _attn_block(x, lp, cfg: NemotronHConfig, positions, segment_ids):
    from automodel_tpu.ops.attention import dot_product_attention

    B, S, H = x.shape
    D = cfg.head_dim
    dtype = x.dtype

    def proj(name, nh):
        y = x @ lp[name]["kernel"].astype(dtype)
        if "bias" in lp[name]:
            y = y + lp[name]["bias"].astype(dtype)
        return y.reshape(B, S, nh, D)

    q = proj("q_proj", cfg.num_heads)
    k = proj("k_proj", cfg.num_kv_heads)
    v = proj("v_proj", cfg.num_kv_heads)
    # no RoPE: position information flows from the mamba recurrences
    attn = dot_product_attention(
        q, k, v, causal=True, segment_ids=segment_ids, positions=positions,
    )
    out = attn.reshape(B, S, cfg.num_heads * D) @ lp["o_proj"]["kernel"].astype(dtype)
    if "bias" in lp["o_proj"]:
        out = out + lp["o_proj"]["bias"].astype(dtype)
    return out


def _mlp_block(x, lp, cfg: NemotronHConfig):
    dtype = x.dtype
    u = x @ lp["up_proj"]["kernel"].astype(dtype)
    if "bias" in lp["up_proj"]:
        u = u + lp["up_proj"]["bias"].astype(dtype)
    y = jnp.square(jax.nn.relu(u)) @ lp["down_proj"]["kernel"].astype(dtype)
    if "bias" in lp["down_proj"]:
        y = y + lp["down_proj"]["bias"].astype(dtype)
    return y


def forward(
    params: dict,
    cfg: NemotronHConfig,
    input_ids: jnp.ndarray,
    *,
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    return_stats: bool = False,
    token_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Returns logits (or hidden). With MoE, returns (out, aux_loss[, stats])."""
    from automodel_tpu.models.common.layers import cast_params, maybe_remat

    fp32_m = {k: params["mamba_layers"][k] for k in ("A_log", "dt_bias", "D")}
    params = cast_params(params, cfg.dtype)
    params["mamba_layers"] = {**params["mamba_layers"], **fp32_m}
    mcfg = cfg.mamba_cfg

    B, S = input_ids.shape
    res_dtype = jnp.float32 if cfg.residual_in_fp32 else cfg.dtype
    h = jnp.take(params["embed"]["embedding"], input_ids, axis=0).astype(res_dtype)

    idx = {"mamba": 0, "attention": 0, "mlp": 0, "moe": 0}
    aux_total = jnp.float32(0.0)
    stats_list = []
    for i, bt in enumerate(cfg.block_pattern):
        ln = params["norms"]["scale"][i]

        def one_layer(hh, _ps=params, _i=i, _bt=bt, _ti=idx[bt], _ln=ln):
            x = rms_norm(hh, _ln, cfg.rms_norm_eps).astype(cfg.dtype)
            if _bt == "mamba":
                lp = jax.tree.map(lambda p: p[_ti], _ps["mamba_layers"])
                return hh + _mixer(x, lp, mcfg, segment_ids).astype(res_dtype), None, None
            if _bt == "attention":
                lp = jax.tree.map(lambda p: p[_ti], _ps["attn_layers"])
                return hh + _attn_block(x, lp, cfg, positions, segment_ids).astype(res_dtype), None, None
            if _bt == "mlp":
                lp = jax.tree.map(lambda p: p[_ti], _ps["mlp_layers"])
                return hh + _mlp_block(x, lp, cfg).astype(res_dtype), None, None
            mp = jax.tree.map(lambda p: p[_ti], _ps["moe_layers"])
            out, aux, st = moe_forward(
                mp, cfg.moe, x, token_mask=token_mask, mesh_ctx=mesh_ctx
            )
            return hh + out.astype(res_dtype), aux, st

        h, aux, st = maybe_remat(lambda hh: one_layer(hh), cfg.remat_policy)(h)
        if aux is not None:
            aux_total = aux_total + aux
            stats_list.append(st["tokens_per_expert"])
        idx[bt] += 1

    h = rms_norm(h, params["final_norm"]["scale"], cfg.rms_norm_eps).astype(cfg.dtype)
    if return_hidden:
        out = h
    else:
        kernel = (
            params["embed"]["embedding"].T
            if cfg.tie_word_embeddings
            else params["lm_head"]["kernel"]
        )
        out = jnp.einsum(
            "bsh,hv->bsv", h, kernel.astype(h.dtype), preferred_element_type=jnp.float32
        )
    if cfg.moe is not None:
        if return_stats:
            return out, aux_total, {"tokens_per_expert": jnp.stack(stats_list)}
        return out, aux_total
    return out


# ---------------------------------------------------------------------------
# HF state-dict adapter (NemotronHForCausalLM backbone.* layout, with the
# same mixer key shapes as Mamba2; attention/mlp/moe mixers keyed per type)
# ---------------------------------------------------------------------------
class NemotronHAdapter:
    def __init__(self, cfg: NemotronHConfig):
        self.cfg = cfg

    def from_hf(self, read, shardings=None) -> dict:
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get, _set

        cfg = self.cfg
        params: dict = {}

        def put(path, value):
            sh = _get(shardings, path) if shardings is not None else None
            _set(params, path, jax.device_put(value, sh) if sh is not None else jnp.asarray(value))

        put(("embed", "embedding"), read("backbone.embeddings.weight"))
        put(("final_norm", "scale"), read("backbone.norm_f.weight"))
        if not cfg.tie_word_embeddings:
            put(("lm_head", "kernel"), np.ascontiguousarray(read("lm_head.weight").T))

        L = cfg.num_layers
        b = "backbone.layers.{}."
        put(("norms", "scale"), np.stack([read((b + "norm.weight").format(i)) for i in range(L)]))

        ids = {
            t: [i for i, bt in enumerate(cfg.block_pattern) if bt == t]
            for t in ("mamba", "attention", "mlp", "moe")
        }

        def stackT(fmt, idxs):
            return np.stack([np.ascontiguousarray(read(fmt.format(i)).T) for i in idxs])

        def stack_(fmt, idxs):
            return np.stack([read(fmt.format(i)) for i in idxs])

        m = b + "mixer."
        if ids["mamba"]:
            put(("mamba_layers", "in_proj", "kernel"), stackT(m + "in_proj.weight", ids["mamba"]))
            put(("mamba_layers", "conv", "kernel"), np.stack([
                np.ascontiguousarray(read((m + "conv1d.weight").format(i))[:, 0, :].T)
                for i in ids["mamba"]
            ]))
            if cfg.use_conv_bias:
                put(("mamba_layers", "conv", "bias"), stack_(m + "conv1d.bias", ids["mamba"]))
            if cfg.use_mamba_bias:
                put(("mamba_layers", "in_proj", "bias"), stack_(m + "in_proj.bias", ids["mamba"]))
                put(("mamba_layers", "out_proj", "bias"), stack_(m + "out_proj.bias", ids["mamba"]))
            put(("mamba_layers", "dt_bias"), stack_(m + "dt_bias", ids["mamba"]))
            put(("mamba_layers", "A_log"), stack_(m + "A_log", ids["mamba"]))
            put(("mamba_layers", "D"), stack_(m + "D", ids["mamba"]))
            put(("mamba_layers", "gated_norm", "scale"), stack_(m + "norm.weight", ids["mamba"]))
            put(("mamba_layers", "out_proj", "kernel"), stackT(m + "out_proj.weight", ids["mamba"]))
        else:
            params["mamba_layers"] = init(cfg, jax.random.key(0))["mamba_layers"]

        if ids["attention"]:
            for p in ("q_proj", "k_proj", "v_proj", "o_proj"):
                put(("attn_layers", p, "kernel"), stackT(m + p + ".weight", ids["attention"]))
                if cfg.attention_bias:
                    put(("attn_layers", p, "bias"), stack_(m + p + ".bias", ids["attention"]))
        else:
            params["attn_layers"] = init(cfg, jax.random.key(0))["attn_layers"]

        if ids["mlp"]:
            for p in ("up_proj", "down_proj"):
                put(("mlp_layers", p, "kernel"), stackT(m + p + ".weight", ids["mlp"]))
                if cfg.mlp_bias:
                    put(("mlp_layers", p, "bias"), stack_(m + p + ".bias", ids["mlp"]))
        else:
            params["mlp_layers"] = init(cfg, jax.random.key(0))["mlp_layers"]

        if cfg.moe is not None and ids["moe"]:
            E = cfg.moe.n_routed_experts
            put(("moe_layers", "gate", "weight"), stackT(m + "gate.weight", ids["moe"]))
            for proj in ("up_proj", "down_proj"):
                w = np.stack([
                    np.stack([
                        np.ascontiguousarray(
                            read(f"backbone.layers.{i}.mixer.experts.{e}.{proj}.weight").T
                        )
                        for e in range(E)
                    ])
                    for i in ids["moe"]
                ])
                put(("moe_layers", "experts", proj, "kernel"), w)
            for proj in ("up_proj", "down_proj"):
                put(
                    ("moe_layers", "shared", proj, "kernel"),
                    stackT(m + f"shared_experts.{proj}.weight", ids["moe"]),
                )
        elif cfg.moe is not None:
            params["moe_layers"] = init(cfg, jax.random.key(0))["moe_layers"]

        return params

    def to_hf(self, params):
        """Yield (hf_name, tensor) — inverse of from_hf for the dense blocks
        (MoE export mirrors from_hf's key layout)."""
        import numpy as np

        cfg = self.cfg

        def g(*path):
            node = params
            for p in path:
                node = node[p]
            return np.asarray(jax.device_get(node))

        yield "backbone.embeddings.weight", g("embed", "embedding")
        yield "backbone.norm_f.weight", g("final_norm", "scale")
        if not cfg.tie_word_embeddings:
            yield "lm_head.weight", np.ascontiguousarray(g("lm_head", "kernel").T)
        b = "backbone.layers.{}."
        idx = {"mamba": 0, "attention": 0, "mlp": 0, "moe": 0}
        for i, bt in enumerate(cfg.block_pattern):
            yield (b + "norm.weight").format(i), g("norms", "scale")[i]
            m = (b + "mixer.").format(i)
            t = idx[bt]
            if bt == "mamba":
                yield m + "in_proj.weight", np.ascontiguousarray(g("mamba_layers", "in_proj", "kernel")[t].T)
                yield m + "conv1d.weight", np.ascontiguousarray(g("mamba_layers", "conv", "kernel")[t].T)[:, None, :]
                if cfg.use_conv_bias:
                    yield m + "conv1d.bias", g("mamba_layers", "conv", "bias")[t]
                if cfg.use_mamba_bias:
                    yield m + "in_proj.bias", g("mamba_layers", "in_proj", "bias")[t]
                    yield m + "out_proj.bias", g("mamba_layers", "out_proj", "bias")[t]
                yield m + "dt_bias", g("mamba_layers", "dt_bias")[t]
                yield m + "A_log", g("mamba_layers", "A_log")[t]
                yield m + "D", g("mamba_layers", "D")[t]
                yield m + "norm.weight", g("mamba_layers", "gated_norm", "scale")[t]
                yield m + "out_proj.weight", np.ascontiguousarray(g("mamba_layers", "out_proj", "kernel")[t].T)
            elif bt == "attention":
                for p in ("q_proj", "k_proj", "v_proj", "o_proj"):
                    yield m + p + ".weight", np.ascontiguousarray(g("attn_layers", p, "kernel")[t].T)
                    if cfg.attention_bias:
                        yield m + p + ".bias", g("attn_layers", p, "bias")[t]
            elif bt == "mlp":
                for p in ("up_proj", "down_proj"):
                    yield m + p + ".weight", np.ascontiguousarray(g("mlp_layers", p, "kernel")[t].T)
                    if cfg.mlp_bias:
                        yield m + p + ".bias", g("mlp_layers", p, "bias")[t]
            else:
                yield m + "gate.weight", np.ascontiguousarray(g("moe_layers", "gate", "weight")[t].T)
                E = cfg.moe.n_routed_experts
                for e in range(E):
                    for p in ("up_proj", "down_proj"):
                        yield (
                            m + f"experts.{e}.{p}.weight",
                            np.ascontiguousarray(g("moe_layers", "experts", p, "kernel")[t][e].T),
                        )
                for p in ("up_proj", "down_proj"):
                    yield m + f"shared_experts.{p}.weight", np.ascontiguousarray(
                        g("moe_layers", "shared", p, "kernel")[t].T
                    )
            idx[bt] += 1


def _register():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["nemotron_h"] = NemotronHAdapter


_register()
