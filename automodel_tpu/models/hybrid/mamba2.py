"""Mamba2 (SSD) selective-state-space family.

The hybrid-Mamba building block of the reference's nemotron families
(reference: nemo_automodel/components/models/nemotron_v3/layers.py mamba
mixers; HF transformers Mamba2ForCausalLM is the numerical oracle).
TPU-native: the mixer's selective scan runs as a `lax.scan` over the
sequence carrying the (B, H, P, N) fp32 state

    S_t = exp(Δ_t·A_h)·S_{t-1} + Δ_t · x_t ⊗ B_t
    y_t = S_t C_t + D_h · x_t

with the depthwise causal conv over the fused x|B|C channels and the
gated RMSNorm (y·silu(z), then normalize) before out_proj. (The chunked
SSD block form is the planned perf upgrade; the scan is the correctness
baseline with static shapes.)
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.ops.norms import rms_norm


@dataclasses.dataclass
class Mamba2Config:
    vocab_size: int
    hidden_size: int
    num_layers: int
    state_size: int = 128
    num_heads: int = 8
    head_dim: int = 64
    n_groups: int = 1
    conv_kernel: int = 4
    expand: int = 2
    use_conv_bias: bool = True
    use_bias: bool = False
    residual_in_fp32: bool = True
    time_step_limit: tuple = (0.0, float("inf"))
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = True
    logits_soft_cap: Optional[float] = None
    dtype: jnp.dtype = jnp.float32
    remat_policy: Optional[str] = "full"
    scan_unroll: int = 1
    mtp_num_layers: int = 0  # chassis compatibility
    # SSD recurrence impl: "scan" (sequential oracle), "chunked" (block
    # matmul form), or "auto" (chunked once S outgrows one chunk)
    ssd_impl: str = "auto"
    ssd_chunk: int = 128

    @property
    def intermediate_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def conv_dim(self) -> int:
        return self.intermediate_size + 2 * self.n_groups * self.state_size

    def flops_per_token(self, seq_len: int) -> float:
        H, I = self.hidden_size, self.intermediate_size
        per_layer = (
            H * (2 * I + 2 * self.n_groups * self.state_size + self.num_heads)
            + I * H
            + 2 * I * self.state_size  # state update + readout
        )
        n = self.vocab_size * H * (1 if self.tie_word_embeddings else 2)
        return 6.0 * (n + self.num_layers * per_layer)


def from_hf_config(hf: dict, dtype=jnp.float32, remat_policy="full", **overrides) -> Mamba2Config:
    overrides = {
        k: v for k, v in overrides.items()
        if k in {f.name for f in dataclasses.fields(Mamba2Config)}
    }
    tsl = hf.get("time_step_limit") or (0.0, float("inf"))
    return Mamba2Config(
        vocab_size=int(hf["vocab_size"]),
        hidden_size=int(hf["hidden_size"]),
        num_layers=int(hf["num_hidden_layers"]),
        state_size=int(hf.get("state_size", 128)),
        num_heads=int(hf.get("num_heads", 8)),
        head_dim=int(hf.get("head_dim", 64)),
        n_groups=int(hf.get("n_groups", 1)),
        conv_kernel=int(hf.get("conv_kernel", 4)),
        expand=int(hf.get("expand", 2)),
        use_conv_bias=bool(hf.get("use_conv_bias", True)),
        use_bias=bool(hf.get("use_bias", False)),
        residual_in_fp32=bool(hf.get("residual_in_fp32", True)),
        time_step_limit=tuple(tsl),
        rms_norm_eps=float(hf.get("layer_norm_epsilon", hf.get("rms_norm_eps", 1e-5))),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", True)),
        dtype=dtype,
        remat_policy=remat_policy,
        **overrides,
    )


# ---------------------------------------------------------------------------
# init / specs
# ---------------------------------------------------------------------------
def init(cfg: Mamba2Config, rng: jax.Array) -> dict:
    H, I, Hd = cfg.hidden_size, cfg.intermediate_size, cfg.num_heads
    L = cfg.num_layers
    ks = jax.random.split(rng, 4)

    def stack(k, shape):
        return jnp.stack([dense_init(kk, shape) for kk in jax.random.split(k, L)])

    proj_out = 2 * I + 2 * cfg.n_groups * cfg.state_size + Hd
    layers = {
        "norm": {"scale": jnp.ones((L, H))},
        "in_proj": {"kernel": stack(ks[0], (H, proj_out))},
        "conv": {"kernel": 0.2 * jax.random.normal(ks[1], (L, cfg.conv_kernel, cfg.conv_dim))},
        "dt_bias": jnp.zeros((L, Hd)),
        "A_log": jnp.log(jnp.broadcast_to(jnp.arange(1, Hd + 1, dtype=jnp.float32), (L, Hd))),
        "D": jnp.ones((L, Hd)),
        "gated_norm": {"scale": jnp.ones((L, I))},
        "out_proj": {"kernel": stack(ks[2], (I, H))},
    }
    if cfg.use_conv_bias:
        layers["conv"]["bias"] = jnp.zeros((L, cfg.conv_dim))
    if cfg.use_bias:
        layers["in_proj"]["bias"] = jnp.zeros((L, proj_out))
        layers["out_proj"]["bias"] = jnp.zeros((L, H))
    params = {
        "embed": {"embedding": 0.02 * jax.random.normal(ks[3], (cfg.vocab_size, H))},
        "layers": layers,
        "final_norm": {"scale": jnp.ones((H,))},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense_init(jax.random.fold_in(rng, 9), (H, cfg.vocab_size))}
    return params


def param_specs(cfg: Mamba2Config) -> dict:
    layers = {
        "norm": {"scale": ("layers", "norm")},
        "in_proj": {"kernel": ("layers", "embed", "heads")},
        "conv": {"kernel": ("layers", None, "heads")},
        "dt_bias": ("layers", "heads"),
        "A_log": ("layers", "heads"),
        "D": ("layers", "heads"),
        "gated_norm": {"scale": ("layers", "norm")},
        "out_proj": {"kernel": ("layers", "heads", "embed")},
    }
    if cfg.use_conv_bias:
        layers["conv"]["bias"] = ("layers", "heads")
    if cfg.use_bias:
        layers["in_proj"]["bias"] = ("layers", "heads")
        layers["out_proj"]["bias"] = ("layers", "norm")
    specs = {
        "embed": {"embedding": ("vocab", "embed")},
        "layers": layers,
        "final_norm": {"scale": ("norm",)},
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"kernel": ("embed", "vocab")}
    return specs


# ---------------------------------------------------------------------------
# mixer
# ---------------------------------------------------------------------------
def selective_scan(x, dt, A, B, C, D, reset=None):
    """Sequential SSD recurrence (HF `torch_forward` oracle semantics).

    x (Bz,S,H,P); dt (Bz,S,H) post-softplus; A (H,) negative; B,C
    (Bz,S,H,N) group-expanded; reset (Bz,S) bool zeroes the carried state
    at packed-document heads. Returns (Bz,S,H,P) fp32.
    """
    Bz, S, Hd, P = x.shape
    if reset is None:
        reset = jnp.zeros((Bz, S), bool)

    def step(state, xs):  # state (Bz,H,P,N)
        x_t, dt_t, b_t, c_t, r_t = xs
        state = jnp.where(r_t[:, None, None, None], 0.0, state)
        da = jnp.exp(dt_t * A)[..., None, None]            # (Bz,H,1,1)
        dbx = (dt_t[..., None] * x_t)[..., :, None] * b_t[..., None, :]
        state = state * da + dbx
        y = jnp.einsum("bhpn,bhn->bhp", state, c_t)
        return state, y

    xs = jax.tree.map(lambda v: jnp.moveaxis(v, 1, 0), (x, dt, B, C, reset))
    s0 = jnp.zeros((Bz, Hd, P, C.shape[-1]), jnp.float32)
    _, ys = jax.lax.scan(step, s0, xs)
    y = jnp.moveaxis(ys, 0, 1)                             # (Bz,S,H,P)
    return y + x * D[None, None, :, None]


def selective_scan_chunked(x, dt, A, B, C, D, reset=None, chunk: int = 128):
    """Chunked (block-parallel) SSD — same semantics as `selective_scan`.

    The Mamba2 SSD block decomposition (reference: nemotron_v3/layers.py
    mamba mixers; the HF `torch_forward` sequential scan is the oracle):
    within each chunk of Q tokens the recurrence is a (Q×Q) decay-masked
    matmul (MXU work), chunk-boundary states are B-weighted sums, and only
    the O(S/Q) inter-chunk recurrence remains sequential. Packed-document
    resets fold into the per-token log-decay as a -inf-like additive term, so
    exp(cum_t - cum_s) underflows to exactly 0 across any document boundary.

    x (Bz,S,H,P) fp32; dt (Bz,S,H) post-softplus; A (H,) negative; B,C
    (Bz,S,H,N); reset (Bz,S) bool. Returns (Bz,S,H,P) fp32.
    """
    Bz, S, Hd, P = x.shape
    N = B.shape[-1]
    pad = (-S) % chunk
    if pad:
        zpad = lambda a: jnp.pad(a, [(0, 0), (0, pad)] + [(0, 0)] * (a.ndim - 2))
        x, B, C = zpad(x), zpad(B), zpad(C)
        dt = jnp.pad(dt, ((0, 0), (0, pad), (0, 0)))
        reset = jnp.pad(
            reset if reset is not None else jnp.zeros((Bz, S), bool),
            ((0, 0), (0, pad)),
        )
    T = S + pad
    Nc, Q = T // chunk, chunk

    loga = dt * A  # (Bz,T,H)
    if reset is not None:
        # a reset zeroes the carry INTO that position: decay → exp(-300) = 0
        loga = loga + jnp.where(reset[..., None], -300.0, 0.0)

    ch = lambda a: a.reshape((Bz, Nc, Q) + a.shape[2:])
    xc, dtc, Bc, Cc, lac = ch(x), ch(dt), ch(B), ch(C), ch(loga)
    cum = jnp.cumsum(lac, axis=2)                      # inclusive (Bz,Nc,Q,H)

    # intra-chunk: y_t += sum_{s<=t} (C_t·B_s) exp(cum_t - cum_s) dt_s x_s
    CB = jnp.einsum("bcqhn,bcshn->bchqs", Cc, Bc)
    diff = cum[:, :, :, None, :] - cum[:, :, None, :, :]            # (b,c,q,s,h)
    tri = jnp.tril(jnp.ones((Q, Q), bool))
    # mask BEFORE exp: upper-triangle diffs are positive (and huge across a
    # reset, where they reach +300·k and overflow to inf); exp-of-masked
    # would be fwd-fine but its where-VJP emits 0·inf = NaN into d(cumsum)
    decay = jnp.exp(jnp.where(tri[None, None, :, :, None], diff, -jnp.inf))
    M = CB * jnp.moveaxis(decay, -1, 2)
    M = M * jnp.moveaxis(dtc, -1, 2)[:, :, :, None, :]   # × dt_s
    y_intra = jnp.einsum("bchqs,bcshp->bcqhp", M, xc)

    # chunk-end states: S_c = sum_s exp(cum_end - cum_s) dt_s x_s ⊗ B_s
    w_state = jnp.exp(cum[:, :, -1:, :] - cum) * dtc   # (Bz,Nc,Q,H)
    states = jnp.einsum("bcsh,bcshn,bcshp->bchpn", w_state, Bc, xc)

    # inter-chunk recurrence over Nc chunk states (the only sequential part)
    T_c = jnp.exp(cum[:, :, -1, :])                    # (Bz,Nc,H) total decay

    def step(carry, xs):  # carry (Bz,H,P,N) = state at chunk start
        s_c, t_c = xs
        out = carry
        carry = carry * t_c[..., None, None] + s_c
        return carry, out

    xs = (jnp.moveaxis(states, 1, 0), jnp.moveaxis(T_c, 1, 0))
    s0 = jnp.zeros((Bz, Hd, P, N), jnp.float32)
    _, starts = jax.lax.scan(step, s0, xs)             # (Nc,Bz,H,P,N)
    starts = jnp.moveaxis(starts, 0, 1)                # (Bz,Nc,H,P,N)

    # inter-chunk: y_t += C_t · (exp(cum_t) · S_chunk_start)
    y_inter = jnp.einsum(
        "bcqhn,bchpn,bcqh->bcqhp", Cc, starts, jnp.exp(cum)
    )
    y = (y_intra + y_inter).reshape(Bz, T, Hd, P)[:, :S]
    return y + x[:, :S] * D[None, None, :, None]


def _mixer(h, lp, cfg: Mamba2Config, segment_ids=None):
    Bz, S, H = h.shape
    I, N, G, Hd = cfg.intermediate_size, cfg.state_size, cfg.n_groups, cfg.num_heads
    dtype = h.dtype

    proj = h @ lp["in_proj"]["kernel"].astype(dtype)
    if "bias" in lp["in_proj"]:
        proj = proj + lp["in_proj"]["bias"].astype(dtype)
    gate = proj[..., :I]
    xbc = proj[..., I : I + cfg.conv_dim]
    dt = proj[..., I + cfg.conv_dim :]                     # (Bz,S,Hd)

    conv_w = lp["conv"]["kernel"].astype(dtype)            # (K, C)
    if segment_ids is None:
        xbc = jax.lax.conv_general_dilated(
            xbc, conv_w[:, None, :], (1,), [(cfg.conv_kernel - 1, 0)],
            dimension_numbers=("NWC", "WIO", "NWC"),
            feature_group_count=cfg.conv_dim,
        )
    else:
        # packed docs: the conv window must not reach into the previous
        # document — unrolled K-tap form with a per-tap same-segment mask
        # (the seq_idx-aware causal_conv1d of the reference)
        K = cfg.conv_kernel
        acc = xbc * conv_w[K - 1][None, None, :]
        for j in range(1, K):
            shifted = jnp.pad(xbc, ((0, 0), (j, 0), (0, 0)))[:, :S]
            seg_j = jnp.pad(segment_ids, ((0, 0), (j, 0)))[:, :S]
            same = (seg_j == segment_ids)[..., None].astype(dtype)
            acc = acc + shifted * same * conv_w[K - 1 - j][None, None, :]
        xbc = acc
    if "bias" in lp["conv"]:
        xbc = xbc + lp["conv"]["bias"].astype(dtype)
    xbc = jax.nn.silu(xbc)

    x = xbc[..., :I].reshape(Bz, S, Hd, cfg.head_dim).astype(jnp.float32)
    B = xbc[..., I : I + G * N].reshape(Bz, S, G, N).astype(jnp.float32)
    C = xbc[..., I + G * N :].reshape(Bz, S, G, N).astype(jnp.float32)
    B = jnp.repeat(B, Hd // G, axis=2)
    C = jnp.repeat(C, Hd // G, axis=2)

    dt = jax.nn.softplus(dt.astype(jnp.float32) + lp["dt_bias"].astype(jnp.float32))
    dt = jnp.clip(dt, cfg.time_step_limit[0], cfg.time_step_limit[1])
    A = -jnp.exp(lp["A_log"].astype(jnp.float32))          # (Hd,)

    reset = None
    if segment_ids is not None:
        prev = jnp.pad(segment_ids, ((0, 0), (1, 0)), constant_values=-1)[:, :S]
        reset = segment_ids != prev
    use_chunked = cfg.ssd_impl == "chunked" or (
        cfg.ssd_impl == "auto" and S > cfg.ssd_chunk
    )
    if use_chunked:
        y = selective_scan_chunked(
            x, dt, A, B, C, lp["D"].astype(jnp.float32), reset,
            chunk=cfg.ssd_chunk,
        )
    else:
        y = selective_scan(x, dt, A, B, C, lp["D"].astype(jnp.float32), reset)
    y = y.reshape(Bz, S, I)
    # HF MambaRMSNormGated: gate first, then normalize
    y = y * jax.nn.silu(gate.astype(jnp.float32))
    y = rms_norm(y, lp["gated_norm"]["scale"], cfg.rms_norm_eps)
    out = y.astype(dtype) @ lp["out_proj"]["kernel"].astype(dtype)
    if "bias" in lp["out_proj"]:
        out = out + lp["out_proj"]["bias"].astype(dtype)
    return out


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def forward(
    params: dict,
    cfg: Mamba2Config,
    input_ids: jnp.ndarray,
    *,
    positions=None,
    segment_ids=None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
) -> jnp.ndarray:
    from automodel_tpu.models.common.layers import cast_params, maybe_remat

    fp32 = {k: params["layers"][k] for k in ("A_log", "dt_bias", "D")}
    params = cast_params(params, cfg.dtype)
    params["layers"] = {**params["layers"], **fp32}

    res_dtype = jnp.float32 if cfg.residual_in_fp32 else cfg.dtype
    h = jnp.take(params["embed"]["embedding"], input_ids, axis=0).astype(res_dtype)

    def body(c, lp):
        x = rms_norm(c, lp["norm"]["scale"], cfg.rms_norm_eps).astype(cfg.dtype)
        return c + _mixer(x, lp, cfg, segment_ids).astype(res_dtype), None

    h, _ = jax.lax.scan(
        maybe_remat(body, cfg.remat_policy), h, params["layers"],
        unroll=cfg.scan_unroll,
    )
    h = rms_norm(h, params["final_norm"]["scale"], cfg.rms_norm_eps).astype(cfg.dtype)
    if return_hidden:
        return h
    kernel = (
        params["embed"]["embedding"].T
        if cfg.tie_word_embeddings
        else params["lm_head"]["kernel"]
    )
    return jnp.einsum(
        "bsh,hv->bsv", h, kernel.astype(h.dtype), preferred_element_type=jnp.float32
    )


# ---------------------------------------------------------------------------
# HF adapter (Mamba2ForCausalLM: backbone.* key layout)
# ---------------------------------------------------------------------------
class Mamba2Adapter:
    def __init__(self, cfg: Mamba2Config):
        self.cfg = cfg

    def from_hf(self, read, shardings=None) -> dict:
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get, _set

        cfg = self.cfg
        params: dict = {}

        def put(path, value):
            sh = _get(shardings, path) if shardings is not None else None
            _set(params, path, jax.device_put(value, sh) if sh is not None else jnp.asarray(value))

        put(("embed", "embedding"), read("backbone.embeddings.weight"))
        put(("final_norm", "scale"), read("backbone.norm_f.weight"))
        if not cfg.tie_word_embeddings:
            put(("lm_head", "kernel"), np.ascontiguousarray(read("lm_head.weight").T))

        L = cfg.num_layers
        b = "backbone.layers.{}."

        def stackT(fmt):
            return np.stack([np.ascontiguousarray(read(fmt.format(i)).T) for i in range(L)])

        def stack_(fmt):
            return np.stack([read(fmt.format(i)) for i in range(L)])

        put(("layers", "norm", "scale"), stack_(b + "norm.weight"))
        put(("layers", "in_proj", "kernel"), stackT(b + "mixer.in_proj.weight"))
        put(("layers", "conv", "kernel"), np.stack([
            np.ascontiguousarray(read((b + "mixer.conv1d.weight").format(i))[:, 0, :].T)
            for i in range(L)
        ]))
        if cfg.use_conv_bias:
            put(("layers", "conv", "bias"), stack_(b + "mixer.conv1d.bias"))
        if cfg.use_bias:
            put(("layers", "in_proj", "bias"), stack_(b + "mixer.in_proj.bias"))
            put(("layers", "out_proj", "bias"), stack_(b + "mixer.out_proj.bias"))
        put(("layers", "dt_bias"), stack_(b + "mixer.dt_bias"))
        put(("layers", "A_log"), stack_(b + "mixer.A_log"))
        put(("layers", "D"), stack_(b + "mixer.D"))
        put(("layers", "gated_norm", "scale"), stack_(b + "mixer.norm.weight"))
        put(("layers", "out_proj", "kernel"), stackT(b + "mixer.out_proj.weight"))
        return params

    def to_hf(self, params):
        """Yield (hf_name, tensor) — the inverse of from_hf (unstack layers,
        transpose kernels, re-insert the conv depthwise axis)."""
        import numpy as np

        cfg = self.cfg

        def g(*path):
            node = params
            for p in path:
                node = node[p]
            return np.asarray(jax.device_get(node))

        yield "backbone.embeddings.weight", g("embed", "embedding")
        yield "backbone.norm_f.weight", g("final_norm", "scale")
        if not cfg.tie_word_embeddings:
            yield "lm_head.weight", np.ascontiguousarray(g("lm_head", "kernel").T)
        b = "backbone.layers.{}."
        for i in range(cfg.num_layers):
            yield (b + "norm.weight").format(i), g("layers", "norm", "scale")[i]
            yield (b + "mixer.in_proj.weight").format(i), np.ascontiguousarray(
                g("layers", "in_proj", "kernel")[i].T
            )
            yield (b + "mixer.conv1d.weight").format(i), np.ascontiguousarray(
                g("layers", "conv", "kernel")[i].T
            )[:, None, :]
            if cfg.use_conv_bias:
                yield (b + "mixer.conv1d.bias").format(i), g("layers", "conv", "bias")[i]
            if cfg.use_bias:
                yield (b + "mixer.in_proj.bias").format(i), g("layers", "in_proj", "bias")[i]
                yield (b + "mixer.out_proj.bias").format(i), g("layers", "out_proj", "bias")[i]
            yield (b + "mixer.dt_bias").format(i), g("layers", "dt_bias")[i]
            yield (b + "mixer.A_log").format(i), g("layers", "A_log")[i]
            yield (b + "mixer.D").format(i), g("layers", "D")[i]
            yield (b + "mixer.norm.weight").format(i), g("layers", "gated_norm", "scale")[i]
            yield (b + "mixer.out_proj.weight").format(i), np.ascontiguousarray(
                g("layers", "out_proj", "kernel")[i].T
            )


def _register():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["mamba2"] = Mamba2Adapter


_register()
