"""BAGEL: unified multimodal understanding + generation (MoT decoder).

The analog of the reference's bagel family (reference: nemo_automodel/
components/models/bagel/, 4227 LoC — model.py `BagelForUnifiedMultimodal`,
modeling_qwen2_packed.py `Qwen2MoTDecoderLayer`, attention_masks.py
`create_sparse_mask`, embeddings.py, connector.py). One model both
UNDERSTANDS images (SigLIP tower → connector → text stream, CE loss) and
GENERATES them (VAE latents → flow-matching velocity head, MSE loss), with
a Mixture-of-Transformers text backbone: every projection/norm has an
understanding expert and a `*_moe_gen` GENERATION sibling, routed by token
type, sharing one attention pattern.

TPU-native design decisions:

- BATCHED (B, S) layout with a per-token `token_type` array (0=text, 1=vit,
  2=vae) instead of the reference's flat packed sequence + scatter indexes.
  The reference's index_put routing becomes compute-both + `where` select:
  for a 2-expert MoT that costs 2× the linear FLOPs but keeps every shape
  static under jit (attention, which both experts share, dominates at
  scale). The packed-attention mask predicates (attention_masks.py:69-83)
  translate to array form: causal by row OR same bidirectional region;
  keys in a NOISE region visible only to that region; same sample.
- The generation path is flow matching exactly per the reference
  (model.py:494-530): t ~ sigmoid(raw), shifted t' = s·t/(1+(s-1)t),
  x_t = (1-t')·clean + t'·noise, velocity target = noise - clean, and
  `llm2vae` zero-initialized so stage 2 starts with zero MSE signal.
- Grid position embeddings are the reference's FROZEN 2D sin/cos tables
  (embeddings.py:76 `BagelGridPositionEmbedding`): stored as buffers in the
  param tree, excluded from `trainable`, regenerated at init.
- The VAE stays outside this module (reference model.py docstring): the
  recipe feeds already-encoded latents.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init, embed_init
from automodel_tpu.models.vision import vit
from automodel_tpu.ops.attention import NEG_INF
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope, rope_frequencies

TEXT, VIT, VAE = 0, 1, 2  # token_type values


@dataclasses.dataclass(frozen=True)
class BagelConfig:
    # text backbone (qwen2-shaped: qkv bias, o no-bias, optional qk norm)
    vocab_size: int = 152064
    hidden_size: int = 3584
    intermediate_size: int = 18944
    num_layers: int = 28
    num_heads: int = 28
    num_kv_heads: int = 4
    head_dim: Optional[int] = None
    rope_theta: float = 1000000.0
    rms_norm_eps: float = 1e-6
    qk_norm: bool = True
    visual_gen: bool = True        # MoT + flow-matching head (stage 2)
    freeze_und: bool = False       # stage-2 option: train gen experts only
    # understanding side
    vision: vit.VisionConfig = dataclasses.field(default_factory=vit.VisionConfig)
    connector_act: str = "gelu_tanh"
    vit_max_num_patch_per_side: int = 70
    # generation side
    latent_patch_size: int = 2
    max_latent_size: int = 32
    timestep_shift: float = 1.0
    z_channels: int = 16
    timestep_embed_size: int = 256
    # execution
    dtype: Any = jnp.bfloat16
    remat_policy: str = "full"
    # attention runs through ops.attention.xla_attention with the explicit
    # mixed-modal keep mask (no flash path for this mask shape yet)
    mtp_num_layers: int = 0  # chassis compatibility

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def patch_latent_dim(self) -> int:
        return self.latent_patch_size ** 2 * self.z_channels

    def flops_per_token(self, seq_len: int) -> float:
        D = self.resolved_head_dim
        H = self.hidden_size
        attn = H * D * (self.num_heads + 2 * self.num_kv_heads) + self.num_heads * D * H
        mlp = 3 * H * self.intermediate_size
        experts = 2 if self.visual_gen else 1
        n = (
            self.vocab_size * H * 2
            + self.num_layers * (attn + mlp) * experts
            + self.vision.param_count()
        )
        return 6.0 * n + 6 * self.num_layers * self.num_heads * D * seq_len


def bagel_config(hf: Mapping[str, Any], **overrides) -> BagelConfig:
    """HF BagelConfig layout (reference: bagel/configuration.py): nested
    llm_config/text_config (qwen2) + vision_config (siglip) + vit_*/latent
    scalars + vae_config {z_channels, downsample}."""
    t = dict(hf.get("llm_config") or hf.get("text_config") or {})
    v = dict(hf.get("vision_config") or {})
    vae = dict(hf.get("vae_config") or {})
    heads = int(t.get("num_attention_heads", 28))
    vision_kw = dict(remat_policy=overrides.get("remat_policy", "full"))
    vision = vit.VisionConfig.from_hf(v, **vision_kw)
    kw = dict(
        vocab_size=int(t.get("vocab_size", 152064)),
        hidden_size=int(t.get("hidden_size", 3584)),
        intermediate_size=int(t.get("intermediate_size", 18944)),
        num_layers=int(t.get("num_hidden_layers", 28)),
        num_heads=heads,
        num_kv_heads=int(t.get("num_key_value_heads", heads)),
        head_dim=t.get("head_dim"),
        rope_theta=float(t.get("rope_theta", 1000000.0)),
        rms_norm_eps=float(t.get("rms_norm_eps", 1e-6)),
        qk_norm=bool(t.get("qk_norm", True)),
        visual_gen=bool(hf.get("visual_gen", True)),
        freeze_und=bool(t.get("freeze_und", False)),
        vision=vision,
        vit_max_num_patch_per_side=int(hf.get("vit_max_num_patch_per_side", 70)),
        latent_patch_size=int(hf.get("latent_patch_size", 2)),
        max_latent_size=int(hf.get("max_latent_size", 32)),
        timestep_shift=float(hf.get("timestep_shift", 1.0)),
        z_channels=int(vae.get("z_channels", 16)),
    )
    kw.update({
        k: v for k, v in overrides.items()
        if k in ("dtype", "remat_policy")
    })
    return BagelConfig(**kw)


# ---------------------------------------------------------------------------
# frozen 2D sin/cos grid table (reference: embeddings.py:46-76)
# ---------------------------------------------------------------------------
def sincos_grid_table(embed_dim: int, grid_size: int) -> jnp.ndarray:
    """(grid_size², embed_dim); x features then y, sin block then cos."""
    half = embed_dim // 2
    pair = half // 2
    freqs = 10000.0 ** (-jnp.arange(pair, dtype=jnp.float32) / pair)
    ys, xs = jnp.meshgrid(
        jnp.arange(grid_size, dtype=jnp.float32),
        jnp.arange(grid_size, dtype=jnp.float32),
        indexing="ij",
    )

    def enc(p):
        ph = p.reshape(-1, 1) * freqs[None, :]
        return jnp.concatenate([jnp.sin(ph), jnp.cos(ph)], axis=-1)

    return jnp.concatenate([enc(xs), enc(ys)], axis=1).astype(jnp.float32)


def timestep_features(t: jnp.ndarray, width: int) -> jnp.ndarray:
    """(N, width) cos|sin features (reference: embeddings.py:96)."""
    half = width // 2
    freqs = jnp.exp(
        -math.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half
    )
    ph = t[:, None].astype(jnp.float32) * freqs[None, :]
    return jnp.concatenate([jnp.cos(ph), jnp.sin(ph)], axis=-1)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------
def _lin(k, din, dout, bias=True):
    p = {"kernel": dense_init(k, (din, dout))}
    if bias:
        p["bias"] = jnp.zeros((dout,))
    return p


def init(cfg: BagelConfig, rng: jax.Array) -> dict:
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    D = cfg.resolved_head_dim
    ks = jax.random.split(rng, 16)

    def stack(k, shape, bias_width=None):
        kk = jax.random.split(k, L)
        p = {"kernel": jnp.stack([dense_init(x, shape) for x in kk])}
        if bias_width is not None:
            p["bias"] = jnp.zeros((L, bias_width))
        return p

    def layer_group(base_key):
        kq, kk_, kv, ko, kg, ku, kd = jax.random.split(base_key, 7)
        g = {
            "input_norm": {"scale": jnp.ones((L, H))},
            "q_proj": stack(kq, (H, cfg.num_heads * D), cfg.num_heads * D),
            "k_proj": stack(kk_, (H, cfg.num_kv_heads * D), cfg.num_kv_heads * D),
            "v_proj": stack(kv, (H, cfg.num_kv_heads * D), cfg.num_kv_heads * D),
            "o_proj": stack(ko, (cfg.num_heads * D, H)),
            "post_attn_norm": {"scale": jnp.ones((L, H))},
            "gate_proj": stack(kg, (H, I)),
            "up_proj": stack(ku, (H, I)),
            "down_proj": stack(kd, (I, H)),
        }
        if cfg.qk_norm:
            g["q_norm"] = {"scale": jnp.ones((L, D))}
            g["k_norm"] = {"scale": jnp.ones((L, D))}
        return g

    lm: dict = {
        "embed": {"embedding": embed_init(ks[0], (cfg.vocab_size, H))},
        "layers": {"und": layer_group(ks[1])},
        "final_norm": {"und": {"scale": jnp.ones((H,))}},
        "lm_head": {"kernel": dense_init(ks[2], (H, cfg.vocab_size))},
    }
    if cfg.visual_gen:
        lm["layers"]["gen"] = layer_group(ks[3])
        lm["final_norm"]["gen"] = {"scale": jnp.ones((H,))}

    params: dict = {
        "language_model": lm,
        "vit_model": vit.init(cfg.vision, ks[4]),
        "connector": {
            "fc1": _lin(ks[5], cfg.vision.hidden_size, H),
            "fc2": _lin(ks[6], H, H),
        },
        # NOTE: the frozen sin/cos grid tables (vit_pos_embed /
        # latent_pos_embed) are NOT parameters — the reference keeps them
        # requires_grad=False (embeddings.py:72); here they are deterministic
        # jit-time constants recomputed in forward, so they can neither
        # receive gradients nor weight-decay drift. The HF adapter still
        # round-trips the checkpoint keys.
    }
    if cfg.visual_gen:
        params["time_embedder"] = {
            "fc1": _lin(ks[7], cfg.timestep_embed_size, H),
            "fc2": _lin(ks[8], H, H),
        }
        params["vae2llm"] = _lin(ks[9], cfg.patch_latent_dim, H)
        # zero-init: stage 2 starts with the MSE head contributing nothing
        # (reference: model.py:210-213)
        params["llm2vae"] = {
            "kernel": jnp.zeros((H, cfg.patch_latent_dim)),
            "bias": jnp.zeros((cfg.patch_latent_dim,)),
        }
    return params


def param_specs(cfg: BagelConfig) -> dict:
    H = cfg.hidden_size

    def lin_spec(din_ax, dout_ax, bias=True):
        p = {"kernel": (din_ax, dout_ax)}
        if bias:
            p["bias"] = ("norm",)
        return p

    def layer_group():
        g = {
            "input_norm": {"scale": ("layers", "norm")},
            "q_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "k_proj": {"kernel": ("layers", "embed", "kv_heads"), "bias": ("layers", "kv_heads")},
            "v_proj": {"kernel": ("layers", "embed", "kv_heads"), "bias": ("layers", "kv_heads")},
            "o_proj": {"kernel": ("layers", "heads", "embed")},
            "post_attn_norm": {"scale": ("layers", "norm")},
            "gate_proj": {"kernel": ("layers", "embed", "mlp")},
            "up_proj": {"kernel": ("layers", "embed", "mlp")},
            "down_proj": {"kernel": ("layers", "mlp", "embed")},
        }
        if cfg.qk_norm:
            g["q_norm"] = {"scale": ("layers", "norm")}
            g["k_norm"] = {"scale": ("layers", "norm")}
        return g

    lm = {
        "embed": {"embedding": ("vocab", "embed")},
        "layers": {"und": layer_group()},
        "final_norm": {"und": {"scale": ("norm",)}},
        "lm_head": {"kernel": ("embed", "vocab")},
    }
    if cfg.visual_gen:
        lm["layers"]["gen"] = layer_group()
        lm["final_norm"]["gen"] = {"scale": ("norm",)}
    specs = {
        "language_model": lm,
        "vit_model": vit.param_specs(cfg.vision),
        "connector": {
            "fc1": lin_spec("embed", "mlp"),
            "fc2": lin_spec("mlp", "embed"),
        },
    }
    if cfg.visual_gen:
        specs["time_embedder"] = {
            "fc1": lin_spec(None, "embed"),
            "fc2": lin_spec("embed", "embed"),
        }
        specs["vae2llm"] = lin_spec(None, "embed")
        specs["llm2vae"] = lin_spec("embed", None)
    return specs


# ---------------------------------------------------------------------------
# packed multimodal mask (reference: attention_masks.py:60-83, array form)
# ---------------------------------------------------------------------------
def bagel_attention_mask(token_type, segment_ids):
    """(B, S, S) bool: same sample ∧ (row-causal ∨ same bidirectional
    region) ∧ (key not in a noise region ∨ same noise region). Regions are
    per (sample, modality): all vit tokens of a sample form one full
    region, all vae tokens one noise region (one image + one latent per
    sample — the batched layout's contract)."""
    B, S = token_type.shape
    seg = segment_ids if segment_ids is not None else jnp.zeros((B, S), jnp.int32)
    full_id = jnp.where(token_type > 0, seg * 2 + (token_type - 1), -1)
    noise_id = jnp.where(token_type == VAE, seg, -1)
    rows = jnp.arange(S)
    causal = rows[:, None] >= rows[None, :]
    same_region = (full_id[:, :, None] == full_id[:, None, :]) & (
        full_id[:, :, None] >= 0
    )
    keep = causal[None] | same_region
    key_noise = noise_id[:, None, :] >= 0
    keep &= (~key_noise) | (noise_id[:, :, None] == noise_id[:, None, :])
    keep &= seg[:, :, None] == seg[:, None, :]
    return keep


# ---------------------------------------------------------------------------
# MoT forward
# ---------------------------------------------------------------------------
def _mot_linear(x, und, gen, gen_mask):
    """where(gen, x@gen, x@und) — both experts on all tokens (static
    shapes; the reference scatters instead, modeling_qwen2_packed.py:648)."""
    yu = x @ und["kernel"].astype(x.dtype)
    if "bias" in und:
        yu = yu + und["bias"].astype(x.dtype)
    if gen is None:
        return yu
    yg = x @ gen["kernel"].astype(x.dtype)
    if "bias" in gen:
        yg = yg + gen["bias"].astype(x.dtype)
    return jnp.where(gen_mask[..., None], yg, yu)


def _mot_norm(x, und_scale, gen_scale, gen_mask, eps):
    yu = rms_norm(x, und_scale, eps)
    if gen_scale is None:
        return yu
    yg = rms_norm(x, gen_scale, eps)
    return jnp.where(gen_mask[..., None], yg, yu)


def forward(
    params: dict,
    cfg: BagelConfig,
    input_ids: jnp.ndarray,        # (B, S) text ids (anything at non-text slots)
    token_type: jnp.ndarray,       # (B, S) 0=text 1=vit 2=vae
    *,
    pixel_values: jnp.ndarray | None = None,   # (B, H, W, 3) und image
    latents: jnp.ndarray | None = None,        # (B, C, Hl, Wl) VAE latents
    timesteps: jnp.ndarray | None = None,      # (B,) raw (pre-sigmoid) t
    rng: jax.Array | None = None,              # flow-matching noise
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    **_ignored,
):
    """Returns (out, gen_out) — `out` is logits or the und-normed hidden;
    `gen_out` is None in understanding-only mode, else a dict with the
    flow-matching pieces (velocity_pred, target, t_shifted) at every
    position (mask by token_type == VAE ∧ t > 0 in the loss; reference:
    model.py:556-581)."""
    from automodel_tpu.models.common.layers import cast_params

    params = cast_params(params, cfg.dtype)
    B, S = input_ids.shape
    H = cfg.hidden_size
    D = cfg.resolved_head_dim
    eps = cfg.rms_norm_eps
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    if segment_ids is None:
        segment_ids = jnp.zeros((B, S), jnp.int32)

    lm = params["language_model"]
    h = jnp.take(lm["embed"]["embedding"], input_ids, axis=0).astype(cfg.dtype)

    # --- understanding branch: tower → connector → +grid pos → scatter ----
    if pixel_values is not None:
        feats = vit.forward(params["vit_model"], cfg.vision, pixel_values)
        c = params["connector"]
        x = feats.astype(cfg.dtype) @ c["fc1"]["kernel"].astype(cfg.dtype) + c["fc1"]["bias"].astype(cfg.dtype)
        x = jax.nn.gelu(x, approximate=True)
        x = x @ c["fc2"]["kernel"].astype(cfg.dtype) + c["fc2"]["bias"].astype(cfg.dtype)
        side = cfg.vision.image_size // cfg.vision.patch_size
        gy, gx = jnp.meshgrid(jnp.arange(side), jnp.arange(side), indexing="ij")
        grid_pos = (gy * cfg.vit_max_num_patch_per_side + gx).reshape(-1)
        # frozen sin/cos grid table: a jit-time constant, not a param
        table = sincos_grid_table(H, cfg.vit_max_num_patch_per_side)
        x = x + jnp.take(table, grid_pos, axis=0).astype(cfg.dtype)[None]
        from automodel_tpu.models.vlm.llava import merge_image_embeddings

        h = merge_image_embeddings(h, x, token_type == VIT)

    # --- generation branch: latents → x_t tokens → scatter ----------------
    gen_ctx = None
    if cfg.visual_gen and latents is not None:
        assert timesteps is not None and rng is not None, (
            "visual_gen forward needs timesteps and rng for flow matching"
        )
        p = cfg.latent_patch_size
        C = cfg.z_channels
        _, _, Hl, Wl = latents.shape
        hh, ww = Hl // p, Wl // p
        lat = latents[:, :, : hh * p, : ww * p].reshape(B, C, hh, p, ww, p)
        clean = jnp.einsum("bchpwq->bhwpqc", lat).reshape(B, hh * ww, p * p * C)
        noise = jax.random.normal(rng, clean.shape, clean.dtype)
        t = jax.nn.sigmoid(timesteps.astype(jnp.float32))
        s = cfg.timestep_shift
        t = s * t / (1 + (s - 1) * t)                       # (B,)
        x_t = (1 - t[:, None, None]) * clean + t[:, None, None] * noise
        te = params["time_embedder"]
        tf = timestep_features(t, cfg.timestep_embed_size)
        temb = tf @ te["fc1"]["kernel"] + te["fc1"]["bias"]
        temb = jax.nn.silu(temb) @ te["fc2"]["kernel"] + te["fc2"]["bias"]
        gy, gx = jnp.meshgrid(jnp.arange(hh), jnp.arange(ww), indexing="ij")
        lat_pos = (gy * cfg.max_latent_size + gx).reshape(-1)
        lpe = jnp.take(
            sincos_grid_table(H, cfg.max_latent_size), lat_pos, axis=0
        )
        v2l = params["vae2llm"]
        tok = (
            x_t.astype(cfg.dtype) @ v2l["kernel"].astype(cfg.dtype)
            + v2l["bias"].astype(cfg.dtype)
            + temb[:, None, :].astype(cfg.dtype)
            + lpe[None].astype(cfg.dtype)
        )
        from automodel_tpu.models.vlm.llava import merge_image_embeddings

        h = merge_image_embeddings(h, tok, token_type == VAE)
        gen_ctx = (clean, noise, t)

    # --- MoT decoder -------------------------------------------------------
    gen_mask = token_type == VAE

    def _freeze(x):
        """freeze_und (stage-2 option): detach und-token activations so the
        understanding experts receive no gradients — applied at every layer
        input AND to the post-projection q/k/v und slices, matching the
        reference's per-slice detaches (modeling_qwen2_packed.py:662-706)."""
        if not cfg.freeze_und:
            return x
        gm = gen_mask.reshape(gen_mask.shape + (1,) * (x.ndim - 2))
        return jnp.where(gm, x, jax.lax.stop_gradient(x))

    h = _freeze(h)
    keep = bagel_attention_mask(token_type, segment_ids)
    inv_freq = rope_frequencies(D, cfg.rope_theta)
    und_l = lm["layers"]["und"]
    gen_l = lm["layers"].get("gen")
    Hq, Hkv = cfg.num_heads, cfg.num_kv_heads
    G = Hq // Hkv
    remat = cfg.remat_policy not in (None, "none")

    def one_layer(h, i):
        h = _freeze(h)
        lu = jax.tree.map(lambda x: x[i], und_l)
        lg = jax.tree.map(lambda x: x[i], gen_l) if gen_l is not None else None

        def g(name):
            return None if lg is None else lg[name]

        x = _mot_norm(
            h, lu["input_norm"]["scale"],
            None if lg is None else lg["input_norm"]["scale"], gen_mask, eps,
        )
        q = _mot_linear(x, lu["q_proj"], g("q_proj"), gen_mask)
        k = _mot_linear(x, lu["k_proj"], g("k_proj"), gen_mask)
        v = _mot_linear(x, lu["v_proj"], g("v_proj"), gen_mask)
        q = q.reshape(B, S, Hq, D)
        k = k.reshape(B, S, Hkv, D)
        v = v.reshape(B, S, Hkv, D)
        if cfg.qk_norm:
            q = _mot_norm(q, lu["q_norm"]["scale"],
                          None if lg is None else lg["q_norm"]["scale"],
                          gen_mask[..., None], eps)
            k = _mot_norm(k, lu["k_norm"]["scale"],
                          None if lg is None else lg["k_norm"]["scale"],
                          gen_mask[..., None], eps)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        q, k, v = _freeze(q), _freeze(k), _freeze(v)
        from automodel_tpu.ops.attention import xla_attention

        attn = xla_attention(q, k, v, mask=keep).reshape(B, S, Hq * D)
        h = h + _mot_linear(attn, lu["o_proj"], g("o_proj"), gen_mask)

        x = _mot_norm(
            h, lu["post_attn_norm"]["scale"],
            None if lg is None else lg["post_attn_norm"]["scale"], gen_mask, eps,
        )
        gate = jax.nn.silu(_mot_linear(x, lu["gate_proj"], g("gate_proj"), gen_mask))
        up = _mot_linear(x, lu["up_proj"], g("up_proj"), gen_mask)
        h = h + _mot_linear(gate * up, lu["down_proj"], g("down_proj"), gen_mask)
        return h

    step = jax.checkpoint(one_layer) if remat else one_layer
    for i in range(cfg.num_layers):
        h = step(h, i)

    fn = lm["final_norm"]
    h = _mot_norm(
        h, fn["und"]["scale"],
        fn["gen"]["scale"] if "gen" in fn else None, gen_mask, eps,
    )

    gen_out = None
    if gen_ctx is not None:
        clean, noise, t = gen_ctx
        l2v = params["llm2vae"]
        pred_full = h @ l2v["kernel"].astype(h.dtype) + l2v["bias"].astype(h.dtype)
        # gather the vae slots back into latent-grid order (inverse of the
        # merge scatter): slot j of the latent grid sits at the j-th VAE
        # position of the row
        order = jnp.cumsum(gen_mask.astype(jnp.int32), axis=1) - 1
        N = clean.shape[1]
        idx = jnp.where(gen_mask, order, N)  # invalid → dropped bucket
        pred = jnp.zeros((B, N + 1, cfg.patch_latent_dim), pred_full.dtype)
        pred = pred.at[jnp.arange(B)[:, None], idx].set(pred_full)
        pred = pred[:, :N]
        gen_out = {
            "velocity_pred": pred.astype(jnp.float32),
            "target": (noise - clean).astype(jnp.float32),
            "t": t,
        }

    if return_hidden:
        return h, gen_out
    logits = jnp.einsum(
        "bsh,hv->bsv", h, lm["lm_head"]["kernel"].astype(h.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits, gen_out


def bagel_losses(
    logits_or_hidden,
    gen_out,
    labels: jnp.ndarray,         # (B, S) -100 at unsupervised
    token_type: jnp.ndarray,
    timesteps: jnp.ndarray | None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(ce_sum, n_ce_tokens, mse_mean) — CE over supervised text positions,
    MSE over generation latents at t>0 (reference: model.py:556-581; the
    -inf sentinel timesteps sigmoid to 0 and drop out)."""
    from automodel_tpu.loss import cross_entropy_sum

    del timesteps  # the shifted t rides gen_out; kept for API clarity
    ce, n = cross_entropy_sum(logits_or_hidden, labels)
    mse = jnp.float32(0.0)
    if gen_out is not None:
        d = (gen_out["velocity_pred"] - gen_out["target"]) ** 2
        w = (gen_out["t"] > 0).astype(jnp.float32)[:, None]     # (B, 1)
        mse = jnp.sum(d.mean(-1) * w) / jnp.maximum(w.sum() * d.shape[1], 1.0)
    return ce, n, mse


# ---------------------------------------------------------------------------
# HF state-dict adapter (reference: bagel/state_dict_adapter.py —
# ema.safetensors layout: language_model.model.* with *_moe_gen siblings,
# vit_model.vision_model.*, connector.*, top-level pos tables + gen linears)
# ---------------------------------------------------------------------------
class BagelAdapter:
    def __init__(self, cfg: BagelConfig):
        self.cfg = cfg

    _LAYER = [
        ("input_layernorm{g}.weight", ("input_norm", "scale"), False),
        ("self_attn.q_proj{g}.weight", ("q_proj", "kernel"), True),
        ("self_attn.q_proj{g}.bias", ("q_proj", "bias"), False),
        ("self_attn.k_proj{g}.weight", ("k_proj", "kernel"), True),
        ("self_attn.k_proj{g}.bias", ("k_proj", "bias"), False),
        ("self_attn.v_proj{g}.weight", ("v_proj", "kernel"), True),
        ("self_attn.v_proj{g}.bias", ("v_proj", "bias"), False),
        ("self_attn.o_proj{g}.weight", ("o_proj", "kernel"), True),
        ("post_attention_layernorm{g}.weight", ("post_attn_norm", "scale"), False),
    ]
    _QKN = [
        ("self_attn.q_norm{g}.weight", ("q_norm", "scale"), False),
        ("self_attn.k_norm{g}.weight", ("k_norm", "scale"), False),
    ]

    def _mlp_name(self, expert: str, proj: str) -> str:
        return (
            f"mlp.{proj}.weight" if expert == "und" else f"mlp_moe_gen.{proj}.weight"
        )

    def _layer_entries(self, expert: str):
        g = "" if expert == "und" else "_moe_gen"
        rows = [(suf.format(g=g), path, tr) for suf, path, tr in self._LAYER]
        if self.cfg.qk_norm:
            rows += [(suf.format(g=g), path, tr) for suf, path, tr in self._QKN]
        return rows

    def _experts(self):
        return ("und", "gen") if self.cfg.visual_gen else ("und",)

    _GEN_TOP = [
        ("time_embedder.mlp.0.weight", ("time_embedder", "fc1", "kernel"), True),
        ("time_embedder.mlp.0.bias", ("time_embedder", "fc1", "bias"), False),
        ("time_embedder.mlp.2.weight", ("time_embedder", "fc2", "kernel"), True),
        ("time_embedder.mlp.2.bias", ("time_embedder", "fc2", "bias"), False),
        ("vae2llm.weight", ("vae2llm", "kernel"), True),
        ("vae2llm.bias", ("vae2llm", "bias"), False),
        ("llm2vae.weight", ("llm2vae", "kernel"), True),
        ("llm2vae.bias", ("llm2vae", "bias"), False),
    ]
    _CONN = [
        ("connector.fc1.weight", ("connector", "fc1", "kernel"), True),
        ("connector.fc1.bias", ("connector", "fc1", "bias"), False),
        ("connector.fc2.weight", ("connector", "fc2", "kernel"), True),
        ("connector.fc2.bias", ("connector", "fc2", "bias"), False),
    ]

    def from_hf(self, read, shardings=None) -> dict:
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import LlavaAdapter, _get, _set

        cfg = self.cfg
        params: dict = {}

        def put(path, value):
            sh = _get(shardings, path) if shardings is not None else None
            _set(
                params, path,
                jax.device_put(value, sh) if sh is not None else jnp.asarray(value),
            )

        def one(name, tr):
            x = np.asarray(read(name))
            return np.ascontiguousarray(x.T) if tr else x

        lmp = "language_model."
        put(("language_model", "embed", "embedding"), one(lmp + "model.embed_tokens.weight", False))
        put(("language_model", "final_norm", "und", "scale"), one(lmp + "model.norm.weight", False))
        put(("language_model", "lm_head", "kernel"), one(lmp + "lm_head.weight", True))
        if cfg.visual_gen:
            put(("language_model", "final_norm", "gen", "scale"),
                one(lmp + "model.norm_moe_gen.weight", False))
        for expert in self._experts():
            for suf, path, tr in self._layer_entries(expert):
                put(("language_model", "layers", expert) + path, np.stack([
                    one(f"{lmp}model.layers.{i}.{suf}", tr)
                    for i in range(cfg.num_layers)
                ]))
            for proj in ("gate_proj", "up_proj", "down_proj"):
                put(("language_model", "layers", expert, proj, "kernel"), np.stack([
                    one(f"{lmp}model.layers.{i}.{self._mlp_name(expert, proj)}", True)
                    for i in range(cfg.num_layers)
                ]))
        for suf, path, tr in self._CONN:
            put(path, one(suf, tr))
        # SigLIP tower: reuse the shared ViT mapping under vit_model.
        vt = LlavaAdapter(cfg)._vit_from_hf(read, "vit_model")
        sub = _get(shardings, ("vit_model",)) if shardings is not None else None
        params["vit_model"] = (
            jax.tree.map(jax.device_put, vt, sub) if sub is not None
            else jax.tree.map(jnp.asarray, vt)
        )
        if cfg.visual_gen:
            for suf, path, tr in self._GEN_TOP:
                put(path, one(suf, tr))
        return params

    def to_hf(self, params):
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import LlavaAdapter, _get

        cfg = self.cfg

        def _t(x):
            return np.ascontiguousarray(np.asarray(x).T)

        lm = params["language_model"]
        yield "language_model.model.embed_tokens.weight", np.asarray(lm["embed"]["embedding"])
        yield "language_model.model.norm.weight", np.asarray(lm["final_norm"]["und"]["scale"])
        yield "language_model.lm_head.weight", _t(lm["lm_head"]["kernel"])
        if cfg.visual_gen:
            yield "language_model.model.norm_moe_gen.weight", np.asarray(
                lm["final_norm"]["gen"]["scale"]
            )
        for expert in self._experts():
            grp = lm["layers"][expert]
            for i in range(cfg.num_layers):
                for suf, path, tr in self._layer_entries(expert):
                    x = np.asarray(_get(grp, path)[i])
                    yield f"language_model.model.layers.{i}.{suf}", (_t(x) if tr else x)
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    yield (
                        f"language_model.model.layers.{i}.{self._mlp_name(expert, proj)}",
                        _t(grp[proj]["kernel"][i]),
                    )
        for suf, path, tr in self._CONN:
            x = np.asarray(_get(params, path))
            yield suf, (_t(x) if tr else x)
        # the frozen tables are computed constants, not params — emit the
        # checkpoint keys the reference layout expects
        yield "vit_pos_embed.pos_embed", np.asarray(
            sincos_grid_table(cfg.hidden_size, cfg.vit_max_num_patch_per_side)
        )
        yield from LlavaAdapter(cfg)._vit_to_hf(params["vit_model"], "vit_model")
        if cfg.visual_gen:
            for suf, path, tr in self._GEN_TOP:
                x = np.asarray(_get(params, path))
                yield suf, (_t(x) if tr else x)
            yield "latent_pos_embed.pos_embed", np.asarray(
                sincos_grid_table(cfg.hidden_size, cfg.max_latent_size)
            )


def _register_adapter():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["bagel"] = BagelAdapter


_register_adapter()
