from automodel_tpu.models.omni import model

__all__ = ["model"]
