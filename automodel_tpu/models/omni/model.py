"""Omni (text · image · audio) model: towers + projectors + decoder LM.

The analog of the reference's omni families
(reference: nemo_automodel/components/models/nemotron_omni/model.py:240
`NemotronOmniForConditionalGeneration` — vision encoder + Parakeet sound
encoder + two RMSNorm→Linear→ReLU²→Linear projectors + LLM backbone;
qwen2_5_omni is the same shape around a qwen2 decoder). TPU-native form:
the existing ViT tower and the audio encoder feed modality projectors
whose outputs scatter into the token stream at the image/audio
placeholder ids (the llava merge, reused for both modalities), then the
generic dense decoder runs on the merged embeddings.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import jax.numpy as jnp

from automodel_tpu.models.audio import encoder as audio_encoder
from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.models.llm import decoder as text_decoder
from automodel_tpu.models.llm.families import llama_config, qwen2_config
from automodel_tpu.models.vision import vit
from automodel_tpu.models.vlm.llava import merge_image_embeddings
from automodel_tpu.ops.norms import rms_norm


@dataclasses.dataclass(frozen=True)
class OmniConfig:
    vision: vit.VisionConfig = dataclasses.field(default_factory=vit.VisionConfig)
    audio: audio_encoder.AudioConfig = dataclasses.field(
        default_factory=audio_encoder.AudioConfig
    )
    text: Any = dataclasses.field(default_factory=text_decoder.TransformerConfig)
    image_token_id: int = 32000
    audio_token_id: int = 32001
    projector_hidden_size: int = 0  # 0 → 4 * text hidden

    @property
    def dtype(self):
        return self.text.dtype

    @property
    def proj_hidden(self) -> int:
        return self.projector_hidden_size or 4 * self.text.hidden_size

    def flops_per_token(self, seq_len: int) -> float:
        """Text FLOPs/token + amortized tower costs (one image + one audio
        clip per sample)."""
        Ht = self.text.hidden_size
        vision = 6.0 * self.vision.param_count() * self.vision.num_positions
        audio = 6.0 * self.audio.param_count() * self.audio.max_frames
        proj = 6.0 * self.proj_hidden * (
            self.vision.hidden_size + self.audio.hidden_size + 2 * Ht
        ) * seq_len * 0.1
        return self.text.flops_per_token(seq_len) + (vision + audio + proj) / seq_len


_TEXT_ADAPTERS = {"llama": llama_config, "qwen2": qwen2_config}


def omni_config(hf: Mapping[str, Any], **overrides) -> OmniConfig:
    """HF-style omni config: {text_config|llm_config, vision_config,
    audio_config|sound_config, image_token_id, audio_token_id}."""
    text_section = hf.get("text_config") or hf.get("llm_config")
    if text_section is None:
        raise ValueError(
            "omni config requires a 'text_config' (or 'llm_config') section"
        )
    text_hf = dict(text_section)
    arch = (text_hf.get("architectures") or ["LlamaForCausalLM"])[0]
    name = "qwen2" if "Qwen2" in arch else "llama"
    text_overrides = {
        k: overrides[k] for k in ("dtype", "remat_policy", "attn_impl") if k in overrides
    }
    text = _TEXT_ADAPTERS[name](text_hf, **text_overrides)
    common = dict(dtype=text.dtype, remat_policy=text_overrides.get("remat_policy", "full"))
    vision = vit.VisionConfig.from_hf(dict(hf["vision_config"]), **common)
    audio_section = hf.get("audio_config") or hf.get("sound_config")
    if audio_section is None:
        raise ValueError(
            "omni config requires an 'audio_config' (or 'sound_config') section"
        )
    audio = audio_encoder.AudioConfig.from_hf(dict(audio_section), **common)
    return OmniConfig(
        vision=vision,
        audio=audio,
        text=text,
        image_token_id=int(hf.get("image_token_id", hf.get("img_context_token_id", 32000))),
        audio_token_id=int(hf.get("audio_token_id", hf.get("sound_context_token_id", 32001))),
        projector_hidden_size=int(hf.get("projector_hidden_size", 0)),
    )


def _init_projector(rng, d_in: int, d_mid: int, d_out: int) -> dict:
    """RMSNorm → Linear → ReLU² → Linear (reference: nemotron_omni
    SoundProjection / VisionProjector, model.py:91,125)."""
    k1, k2 = jax.random.split(rng)
    return {
        "norm": {"scale": jnp.ones((d_in,))},
        "linear1": {"kernel": dense_init(k1, (d_in, d_mid))},
        "linear2": {"kernel": dense_init(k2, (d_mid, d_out))},
    }


def _projector_specs() -> dict:
    return {
        "norm": {"scale": ("norm",)},
        "linear1": {"kernel": ("embed", "mlp")},
        "linear2": {"kernel": ("mlp", "embed")},
    }


def _project(p: dict, x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    x = rms_norm(x, p["norm"]["scale"], eps)
    x = x @ p["linear1"]["kernel"].astype(x.dtype)
    x = jnp.square(jax.nn.relu(x))
    return x @ p["linear2"]["kernel"].astype(x.dtype)


def init(cfg: OmniConfig, rng: jax.Array) -> dict:
    kv, ka, kt, kp1, kp2 = jax.random.split(rng, 5)
    Ht = cfg.text.hidden_size
    return {
        "vision_tower": vit.init(cfg.vision, kv),
        "audio_tower": audio_encoder.init(cfg.audio, ka),
        "vision_projection": _init_projector(
            kp1, cfg.vision.hidden_size, cfg.proj_hidden, Ht
        ),
        "sound_projection": _init_projector(
            kp2, cfg.audio.hidden_size, cfg.proj_hidden, Ht
        ),
        "language_model": text_decoder.init(cfg.text, kt),
    }


def param_specs(cfg: OmniConfig) -> dict:
    return {
        "vision_tower": vit.param_specs(cfg.vision),
        "audio_tower": audio_encoder.param_specs(cfg.audio),
        "vision_projection": _projector_specs(),
        "sound_projection": _projector_specs(),
        "language_model": text_decoder.param_specs(cfg.text),
    }


def forward(
    params: dict,
    cfg: OmniConfig,
    input_ids: jnp.ndarray,             # (B, S)
    pixel_values: jnp.ndarray | None = None,   # (B, H, W, C)
    audio_features: jnp.ndarray | None = None,  # (B, T, mel)
    *,
    audio_mask: jnp.ndarray | None = None,      # (B, T) bool
    positions=None,
    segment_ids=None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
):
    """Merge image + audio embeddings into the token stream and run the
    decoder. Placeholder layout is the caller's contract: the k-th image
    patch fills the k-th image_token_id position, likewise audio frames
    at audio_token_id positions (reference: nemotron_omni forward step 3
    'Replace image token embeddings with vision embeddings')."""
    lm = params["language_model"]
    merged = jnp.take(lm["embed"]["embedding"], input_ids, axis=0).astype(cfg.dtype)

    if pixel_values is not None:
        feats = vit.forward(params["vision_tower"], cfg.vision, pixel_values)
        if cfg.vision.use_cls_token:
            feats = feats[:, 1:]
        img = _project(params["vision_projection"], feats.astype(cfg.dtype))
        merged = merge_image_embeddings(merged, img, input_ids == cfg.image_token_id)

    if audio_features is not None:
        frames, frame_mask = audio_encoder.forward(
            params["audio_tower"], cfg.audio, audio_features, audio_mask
        )
        snd = _project(params["sound_projection"], frames.astype(cfg.dtype))
        # zero padding-derived frames so trailing audio placeholders carry
        # no garbage when a clip is shorter than its placeholder span
        snd = snd * frame_mask[..., None].astype(snd.dtype)
        merged = merge_image_embeddings(merged, snd, input_ids == cfg.audio_token_id)

    return text_decoder.forward(
        lm, cfg.text, input_ids,
        positions=positions, segment_ids=segment_ids,
        mesh_ctx=mesh_ctx, rules=rules,
        return_hidden=return_hidden, inputs_embeds=merged,
    )
