"""Tokenizer wrapper over HF AutoTokenizer.

The analog of `NeMoAutoTokenizer` (reference: nemo_automodel/
_transformers/auto_tokenizer.py + components/tokenization/): passthrough
construction with the quality-of-life defaults the recipes rely on —
pad-token defaulting to EOS, optional chat-template application, and a
plain-callable interface the datasets use.
"""

from __future__ import annotations

import logging
from typing import Any

logger = logging.getLogger(__name__)


def build_tokenizer(
    pretrained_path: str,
    *,
    default_pad_to_eos: bool = True,
    trust_remote_code: bool = False,
    **kwargs: Any,
):
    """Load an HF tokenizer from a local path/hub name with pad defaulting."""
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(
        pretrained_path, trust_remote_code=trust_remote_code, **kwargs
    )
    if tok.pad_token_id is None and default_pad_to_eos and tok.eos_token_id is not None:
        tok.pad_token = tok.eos_token
        logger.info("tokenizer pad_token defaulted to eos (%s)", tok.eos_token)
    return tok


def apply_chat_template(tokenizer, messages: list, add_generation_prompt: bool = False) -> str:
    """Render a chat conversation via the tokenizer's template (or a plain
    role-prefixed fallback when none is defined)."""
    if getattr(tokenizer, "chat_template", None):
        return tokenizer.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=add_generation_prompt
        )
    text = "".join(f"<|{m['role']}|>\n{m['content']}\n" for m in messages)
    if add_generation_prompt:
        text += "<|assistant|>\n"
    return text
