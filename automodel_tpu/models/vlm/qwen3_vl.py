"""Qwen3-VL-MoE: ViT tower with deepstack → merger → Qwen3-MoE text with
interleaved MRoPE.

The analog of the reference's qwen3_vl_moe (reference: nemo_automodel/
components/models/qwen3_vl_moe/model.py, 707 LoC — the reference reuses the
HF vision tower and rebuilds the text decoder on its Qwen3-MoE block; here
both sides are native):

- Vision: conv patch embed over (temporal_patch × P × P) voxels (images
  duplicate the frame across the temporal patch — folded into the channel
  dim here, exactly equivalent and checkpoint-invertible), learned
  interpolatable pos-embed, pre-LN blocks with qkv bias and 2D rotary (half
  h / half w over the head dim, half-split rotation — the qwen2-vl vision
  convention), merger (LN → spatial 2×2 merge → fc1 → gelu → fc2), plus one
  extra merger per DEEPSTACK tap layer: intermediate tower features are
  merged and added to the LLM's hidden states after its first K layers
  (reference model.py:419 `_deepstack_process`; moe decoder
  `deepstack_embeds` hook).
- Text: the shared MoE decoder with a qwen3-moe config; MRoPE 3-axis
  (t/h/w) positions built per sample (verified against the in-env
  transformers qwen2_5_vl `get_rope_index`: image block positions are
  (0, row, col) + image-start offset; following text resumes at max+1),
  folded into per-token rope angles via `mrope_angles` (sectioned or
  interleaved channel layout) and threaded through `rope_angles`.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.models.moe_lm import decoder as moe_decoder
from automodel_tpu.models.moe_lm.families import qwen3_moe_config
from automodel_tpu.models.vlm.kimi_vl import _layer_norm, _ln_init
from automodel_tpu.models.vlm.llava import merge_image_embeddings
from automodel_tpu.ops.rope import apply_rope


@dataclasses.dataclass(frozen=True)
class Qwen3VLVisionConfig:
    patch_size: int = 16
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    num_heads: int = 16
    num_layers: int = 24
    hidden_size: int = 1152
    intermediate_size: int = 4096
    out_hidden_size: int = 2048          # text hidden
    num_position_embeddings: int = 2304  # (48×48 grid)
    deepstack_visual_indexes: tuple = (5, 11, 17)
    rope_theta: float = 10000.0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def pos_grid(self) -> int:
        return int(self.num_position_embeddings ** 0.5)


@dataclasses.dataclass(frozen=True)
class Qwen3VLMoEConfig:
    vision: Qwen3VLVisionConfig = dataclasses.field(default_factory=Qwen3VLVisionConfig)
    text: Any = None  # MoETransformerConfig (qwen3-moe body)
    image_token_id: int = 151655
    mrope_section: tuple = (24, 20, 20)
    mrope_interleaved: bool = True

    @property
    def dtype(self):
        return self.text.dtype

    @property
    def moe(self):
        return self.text.moe

    @property
    def mtp_num_layers(self) -> int:
        return 0

    def flops_per_token(self, seq_len: int) -> float:
        v = self.vision
        vis = v.num_layers * (4 * v.hidden_size**2 + 2 * v.hidden_size * v.intermediate_size)
        return self.text.flops_per_token(seq_len) + 6.0 * vis / max(seq_len, 1)


def qwen3_vl_moe_config(hf: Mapping[str, Any], **overrides) -> Qwen3VLMoEConfig:
    v = dict(hf.get("vision_config") or {})
    text_hf = dict(hf["text_config"])
    text_overrides = {
        k: overrides[k]
        for k in ("dtype", "remat_policy", "attn_impl", "linear_precision")
        if k in overrides
    }
    text = qwen3_moe_config(text_hf, **text_overrides)
    rs = text_hf.get("rope_scaling") or {}
    section = tuple(rs.get("mrope_section", (24, 20, 20)))
    vision = Qwen3VLVisionConfig(
        patch_size=int(v.get("patch_size", 16)),
        temporal_patch_size=int(v.get("temporal_patch_size", 2)),
        spatial_merge_size=int(v.get("spatial_merge_size", 2)),
        num_heads=int(v.get("num_heads", v.get("num_attention_heads", 16))),
        num_layers=int(v.get("depth", v.get("num_hidden_layers", 24))),
        hidden_size=int(v.get("hidden_size", 1152)),
        intermediate_size=int(v.get("intermediate_size", 4096)),
        out_hidden_size=int(v.get("out_hidden_size", text.hidden_size)),
        num_position_embeddings=int(v.get("num_position_embeddings", 2304)),
        deepstack_visual_indexes=tuple(v.get("deepstack_visual_indexes", (5, 11, 17))),
    )
    return Qwen3VLMoEConfig(
        vision=vision,
        text=text,
        image_token_id=int(hf.get("image_token_id", 151655)),
        mrope_section=section,
        mrope_interleaved=bool(rs.get("mrope_interleaved", True)),
    )


# ---------------------------------------------------------------------------
# vision tower
# ---------------------------------------------------------------------------
def _merger_init(k, Dv, merged, out):
    k1, k2 = jax.random.split(k)
    return {
        "norm": _ln_init(Dv),
        "linear_fc1": {"kernel": dense_init(k1, (merged, merged)), "bias": jnp.zeros((merged,))},
        "linear_fc2": {"kernel": dense_init(k2, (merged, out)), "bias": jnp.zeros((out,))},
    }


def init_vision(cfg: Qwen3VLVisionConfig, rng: jax.Array) -> dict:
    D, I, P = cfg.hidden_size, cfg.intermediate_size, cfg.patch_size
    Cin = 3 * cfg.temporal_patch_size
    L = cfg.num_layers
    m = cfg.spatial_merge_size
    merged = D * m * m
    ks = jax.random.split(rng, 9)

    def stack(k, shape):
        return jnp.stack([dense_init(kk, shape) for kk in jax.random.split(k, L)])

    return {
        "patch_embed": {
            "proj": {
                "kernel": 0.02 * jax.random.normal(ks[0], (P, P, Cin, D)),
                "bias": jnp.zeros((D,)),
            },
        },
        "pos_embed": {"weight": 0.02 * jax.random.normal(ks[1], (cfg.pos_grid, cfg.pos_grid, D))},
        "blocks": {
            "norm1": {"scale": jnp.ones((L, D)), "bias": jnp.zeros((L, D))},
            "norm2": {"scale": jnp.ones((L, D)), "bias": jnp.zeros((L, D))},
            "qkv": {"kernel": stack(ks[2], (D, 3 * D)), "bias": jnp.zeros((L, 3 * D))},
            "proj": {"kernel": stack(ks[3], (D, D)), "bias": jnp.zeros((L, D))},
            "fc1": {"kernel": stack(ks[4], (D, I)), "bias": jnp.zeros((L, I))},
            "fc2": {"kernel": stack(ks[5], (I, D)), "bias": jnp.zeros((L, D))},
        },
        "merger": _merger_init(ks[6], D, merged, cfg.out_hidden_size),
        "deepstack_mergers": jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[
                _merger_init(k, D, merged, cfg.out_hidden_size)
                for k in jax.random.split(ks[7], len(cfg.deepstack_visual_indexes))
            ],
        ),
    }


def vision_param_specs(cfg: Qwen3VLVisionConfig) -> dict:
    merger = {
        "norm": {"scale": ("norm",), "bias": ("norm",)},
        "linear_fc1": {"kernel": ("embed", "mlp"), "bias": ("norm",)},
        "linear_fc2": {"kernel": ("mlp", "embed"), "bias": ("norm",)},
    }
    return {
        "patch_embed": {
            "proj": {"kernel": (None, None, None, "embed"), "bias": ("norm",)},
        },
        "pos_embed": {"weight": (None, None, "embed")},
        "blocks": {
            "norm1": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "norm2": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "qkv": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "proj": {"kernel": ("layers", "heads", "embed"), "bias": ("layers", "norm")},
            "fc1": {"kernel": ("layers", "embed", "mlp"), "bias": ("layers", "mlp")},
            "fc2": {"kernel": ("layers", "mlp", "embed"), "bias": ("layers", "norm")},
        },
        "merger": merger,
        "deepstack_mergers": jax.tree.map(
            lambda s: ("layers",) + s, merger, is_leaf=lambda x: isinstance(x, tuple)
        ),
    }


def _vision_rope_angles(cfg: Qwen3VLVisionConfig, gh: int, gw: int) -> jnp.ndarray:
    """(gh*gw, head_dim/2) — first half of pairs from the row index, second
    half from the column index (qwen2-vl vision rotary convention)."""
    d4 = cfg.head_dim // 4
    freqs = 1.0 / (cfg.rope_theta ** (jnp.arange(d4) * 2.0 / (cfg.head_dim // 2)))
    ys, xs = jnp.meshgrid(jnp.arange(gh), jnp.arange(gw), indexing="ij")
    h_ang = ys.reshape(-1, 1) * freqs[None, :]
    w_ang = xs.reshape(-1, 1) * freqs[None, :]
    return jnp.concatenate([h_ang, w_ang], axis=-1)  # (N, d/2)


def _apply_merger(x, mp, gh, gw, m, dtype):
    """x (B, gh*gw, D) → (B, (gh/m)*(gw/m), out)."""
    B, N, D = x.shape
    x = _layer_norm(x, mp["norm"])
    x = x.reshape(B, gh // m, m, gw // m, m, D)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(B, (gh // m) * (gw // m), m * m * D)
    x = jax.nn.gelu(
        x @ mp["linear_fc1"]["kernel"].astype(dtype) + mp["linear_fc1"]["bias"].astype(dtype),
        approximate=True,
    )
    return x @ mp["linear_fc2"]["kernel"].astype(dtype) + mp["linear_fc2"]["bias"].astype(dtype)


def vision_forward(params: dict, cfg: Qwen3VLVisionConfig, pixel_values: jnp.ndarray):
    """pixel_values (B, H, W, 3) → (main (B, Nm, out), deepstack (K, B, Nm, out))."""
    B, Himg, Wimg, _ = pixel_values.shape
    P, m = cfg.patch_size, cfg.spatial_merge_size
    gh, gw = Himg // P, Wimg // P
    D = cfg.hidden_size
    dtype = params["blocks"]["qkv"]["kernel"].dtype

    # images repeat the frame across the temporal patch (HF duplicates
    # frames before Conv3d; folded into channels here — same arithmetic)
    pix = jnp.concatenate([pixel_values] * cfg.temporal_patch_size, axis=-1)
    x = jax.lax.conv_general_dilated(
        pix.astype(dtype), params["patch_embed"]["proj"]["kernel"].astype(dtype),
        window_strides=(P, P), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ) + params["patch_embed"]["proj"]["bias"].astype(dtype)
    x = x.reshape(B, gh * gw, D)

    pe = params["pos_embed"]["weight"]
    if pe.shape[:2] != (gh, gw):
        pe = jax.image.resize(pe, (gh, gw, D), method="bicubic")
    x = x + pe.reshape(1, gh * gw, D).astype(dtype)

    angles = _vision_rope_angles(cfg, gh, gw)
    Hn, hd = cfg.num_heads, cfg.head_dim
    taps = {}

    def block(x, lp):
        y = _layer_norm(x, lp["norm1"])
        qkv = (y @ lp["qkv"]["kernel"] + lp["qkv"]["bias"]).reshape(B, gh * gw, 3, Hn, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = apply_rope(q, None, angles[None])
        k = apply_rope(k, None, angles[None])
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        p = jax.nn.softmax(s * (hd ** -0.5), axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, gh * gw, D)
        x = x + attn @ lp["proj"]["kernel"] + lp["proj"]["bias"]
        y = _layer_norm(x, lp["norm2"])
        h = jax.nn.gelu(y @ lp["fc1"]["kernel"] + lp["fc1"]["bias"], approximate=True)
        return x + h @ lp["fc2"]["kernel"] + lp["fc2"]["bias"]

    # python loop: deepstack taps are layer-heterogeneous
    for i in range(cfg.num_layers):
        lp = jax.tree.map(lambda p: p[i], params["blocks"])
        x = block(x, lp)
        if i in cfg.deepstack_visual_indexes:
            taps[i] = x

    main = _apply_merger(x, params["merger"], gh, gw, m, dtype)
    ds = []
    for j, i in enumerate(cfg.deepstack_visual_indexes):
        mp = jax.tree.map(lambda p: p[j], params["deepstack_mergers"])
        ds.append(_apply_merger(taps[i], mp, gh, gw, m, dtype))
    return main, jnp.stack(ds)


# ---------------------------------------------------------------------------
# MRoPE
# ---------------------------------------------------------------------------
def mrope_axis_map(section: tuple, interleaved: bool, n_freq: int) -> jnp.ndarray:
    """(n_freq,) int in {0,1,2}: which position axis drives each rope freq.

    sectioned: first section[0] freqs → t, then h, then w (qwen2-vl).
    interleaved: round-robin t,h,w while quotas remain (qwen3-vl)."""
    assert sum(section) == n_freq, (section, n_freq)
    if not interleaved:
        out = []
        for ax, n in enumerate(section):
            out += [ax] * n
        return jnp.asarray(out, jnp.int32)
    left = list(section)
    out = []
    ax = 0
    while len(out) < n_freq:
        if left[ax] > 0:
            out.append(ax)
            left[ax] -= 1
        ax = (ax + 1) % 3
    return jnp.asarray(out, jnp.int32)


def mrope_angles(pos3: jnp.ndarray, inv_freq: jnp.ndarray, axis_map: jnp.ndarray) -> jnp.ndarray:
    """pos3 (3, B, S) × inv_freq (D/2,) → per-token angles (B, S, D/2)."""
    sel = jnp.take(pos3, axis_map, axis=0)          # (D/2, B, S)
    return jnp.transpose(sel, (1, 2, 0)).astype(jnp.float32) * inv_freq[None, None, :]


def get_mrope_positions(input_ids, image_mask, gh_m: int, gw_m: int) -> jnp.ndarray:
    """(3, B, S) t/h/w positions — one contiguous image block per sample
    (semantics verified against transformers qwen2_5_vl `get_rope_index`:
    image positions are (0, row, col) + image-start; following text resumes
    at max+1)."""
    B, S = input_ids.shape
    ar = jnp.arange(S, dtype=jnp.int32)[None, :]
    n_img = jnp.sum(image_mask.astype(jnp.int32), axis=1, keepdims=True)  # (B,1)
    img_start = jnp.where(
        n_img > 0, jnp.argmax(image_mask, axis=1).astype(jnp.int32)[:, None], S
    )
    after = (ar >= img_start + n_img).astype(jnp.int32)
    delta = (max(gh_m, gw_m) - n_img).astype(jnp.int32)
    text_pos = ar + after * delta
    idx_in_img = jnp.cumsum(image_mask.astype(jnp.int32), axis=1) - 1
    row = idx_in_img // gw_m + img_start
    col = idx_in_img % gw_m + img_start
    t = jnp.where(image_mask, img_start, text_pos)
    h = jnp.where(image_mask, row, text_pos)
    w = jnp.where(image_mask, col, text_pos)
    return jnp.stack([t, h, w])


# ---------------------------------------------------------------------------
# full model
# ---------------------------------------------------------------------------
def init(cfg: Qwen3VLMoEConfig, rng: jax.Array) -> dict:
    kv, kt = jax.random.split(rng)
    return {
        "visual": init_vision(cfg.vision, kv),
        "language_model": moe_decoder.init(cfg.text, kt),
    }


def param_specs(cfg: Qwen3VLMoEConfig) -> dict:
    return {
        "visual": vision_param_specs(cfg.vision),
        "language_model": moe_decoder.param_specs(cfg.text),
    }


def _prepare_mm(params, cfg: Qwen3VLMoEConfig, input_ids, pixel_values, constrain):
    """Shared multimodal prep for forward + generation: merged prompt
    embeddings, pre-scattered deepstack residuals, MRoPE angles, pos3."""
    v = cfg.vision
    P, m = v.patch_size, v.spatial_merge_size
    gh_m = pixel_values.shape[1] // P // m
    gw_m = pixel_values.shape[2] // P // m
    image_embeds, ds_embeds = vision_forward(params["visual"], v, pixel_values)

    lm = params["language_model"]
    # FSDP-unshard the table's embed dim before the gather (see moe decoder)
    tbl = constrain(lm["embed"]["embedding"], ("vocab", None))
    token_embeds = jnp.take(tbl, input_ids, axis=0).astype(cfg.dtype)
    image_mask = input_ids == cfg.image_token_id
    merged = merge_image_embeddings(token_embeds, image_embeds, image_mask)

    # deepstack taps, pre-scattered over the sequence (zeros off-image)
    zeros = jnp.zeros_like(token_embeds)
    ds_full = jnp.stack([
        merge_image_embeddings(zeros, ds_embeds[k], image_mask)
        for k in range(ds_embeds.shape[0])
    ])

    pos3 = get_mrope_positions(input_ids, image_mask, gh_m, gw_m)
    from automodel_tpu.ops.rope import rope_frequencies

    inv_freq = rope_frequencies(
        cfg.text.rope_dim, cfg.text.rope_theta, cfg.text.rope_scaling
    )
    axis_map = mrope_axis_map(cfg.mrope_section, cfg.mrope_interleaved, inv_freq.shape[-1])
    angles = mrope_angles(pos3, inv_freq, axis_map)
    return merged, ds_full, angles, pos3


@partial(jax.jit, static_argnames=("cfg",))
def _prepare_generation_jit(params, cfg, input_ids, pixel_values):
    merged, ds_full, angles, pos3 = _prepare_mm(
        params, cfg, input_ids, pixel_values, lambda a, ax: a
    )
    return merged, ds_full, angles, jnp.max(pos3, axis=(0, 2)).astype(jnp.int32) + 1


def prepare_generation(params, cfg: Qwen3VLMoEConfig, input_ids, pixel_values):
    """Build the KV-cache generate inputs (inference.generate kwargs):
    merged prompt embeds + prefill MRoPE angles + the rope position of the
    first decoded token (text resumes at max(pos3)+1) + deepstack residuals
    for the prefill layers. Jitted — the ViT's per-layer python loop would
    otherwise dispatch op-by-op on every generation batch."""
    merged, ds_full, angles, pos0 = _prepare_generation_jit(
        params, cfg, input_ids, pixel_values
    )
    return {
        "prompt_embeds": merged,
        "rope_angles": angles,
        "decode_rope_pos0": pos0,
        "deepstack_embeds": ds_full,
    }


def forward(
    params: dict,
    cfg: Qwen3VLMoEConfig,
    input_ids: jnp.ndarray,
    pixel_values: jnp.ndarray,
    *,
    positions=None,
    segment_ids=None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    token_mask=None,
    return_stats: bool = False,
):
    """Returns (out, aux_loss[, stats]) — the MoE module protocol."""
    from automodel_tpu.models.llm.decoder import _make_constrain

    constrain = _make_constrain(mesh_ctx, rules)
    merged, ds_full, angles, _pos3 = _prepare_mm(
        params, cfg, input_ids, pixel_values, constrain
    )
    lm = params["language_model"]

    return moe_decoder.forward(
        lm, cfg.text, input_ids,
        positions=positions, segment_ids=segment_ids,
        mesh_ctx=mesh_ctx, rules=rules,
        return_hidden=return_hidden, inputs_embeds=merged,
        token_mask=token_mask, return_stats=return_stats,
        rope_angles=angles, deepstack_embeds=ds_full,
    )


# ---------------------------------------------------------------------------
# HF state-dict adapter
# ---------------------------------------------------------------------------
class Qwen3VLMoEAdapter:
    """HF layout: `model.visual.*`, `model.language_model.*` (qwen3-moe
    naming with STACKED kernel-oriented expert tensors — reference:
    qwen3_vl_moe/state_dict_adapter.py: gate_up_proj (E, dim, 2·I) [gate;up],
    down_proj (E, I, dim)), top-level `lm_head.weight`."""

    def __init__(self, cfg: Qwen3VLMoEConfig):
        self.cfg = cfg

    def _lm(self):
        from automodel_tpu.checkpoint.hf_adapter import MoEDecoderAdapter

        return MoEDecoderAdapter(self.cfg.text)

    _VIS_TOP = [
        ("pos_embed.weight", ("pos_embed", "weight"), "pos"),
        ("patch_embed.proj.bias", ("patch_embed", "proj", "bias"), None),
    ]
    _BLK = [
        ("norm1.weight", ("norm1", "scale"), False),
        ("norm1.bias", ("norm1", "bias"), False),
        ("norm2.weight", ("norm2", "scale"), False),
        ("norm2.bias", ("norm2", "bias"), False),
        ("attn.qkv.weight", ("qkv", "kernel"), True),
        ("attn.qkv.bias", ("qkv", "bias"), False),
        ("attn.proj.weight", ("proj", "kernel"), True),
        ("attn.proj.bias", ("proj", "bias"), False),
        ("mlp.linear_fc1.weight", ("fc1", "kernel"), True),
        ("mlp.linear_fc1.bias", ("fc1", "bias"), False),
        ("mlp.linear_fc2.weight", ("fc2", "kernel"), True),
        ("mlp.linear_fc2.bias", ("fc2", "bias"), False),
    ]
    _MERGER = [
        ("norm.weight", ("norm", "scale"), False),
        ("norm.bias", ("norm", "bias"), False),
        ("linear_fc1.weight", ("linear_fc1", "kernel"), True),
        ("linear_fc1.bias", ("linear_fc1", "bias"), False),
        ("linear_fc2.weight", ("linear_fc2", "kernel"), True),
        ("linear_fc2.bias", ("linear_fc2", "bias"), False),
    ]

    def from_hf(self, read, shardings=None) -> dict:
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get, _set, memo1_reader

        read = memo1_reader(read)  # per-expert slicing re-reads stacked tensors
        v = self.cfg.vision
        params: dict = {}

        def put(path, value):
            sh = _get(shardings, path) if shardings is not None else None
            _set(params, path, jax.device_put(value, sh) if sh is not None else jnp.asarray(value))

        def one(name, transpose):
            x = np.asarray(read(name))
            return np.ascontiguousarray(x.T) if transpose else x

        g = v.pos_grid
        pe = np.asarray(read("model.visual.pos_embed.weight"))  # (N, D)
        put(("visual", "pos_embed", "weight"), pe.reshape(g, g, -1))
        # Conv3d (D, 3, tp, P, P) → channel-folded HWIO (P, P, 3*tp, D):
        # frame-duplication makes tp a pure channel axis (tp-major like the
        # jnp.concatenate([pix]*tp) in vision_forward: channel c = t*3 + rgb)
        w = np.asarray(read("model.visual.patch_embed.proj.weight"))
        D_, C3, TP, P_, _ = w.shape
        w = np.transpose(w, (3, 4, 2, 1, 0)).reshape(P_, P_, TP * C3, D_)
        put(("visual", "patch_embed", "proj", "kernel"), np.ascontiguousarray(w))
        put(("visual", "patch_embed", "proj", "bias"),
            np.asarray(read("model.visual.patch_embed.proj.bias")))
        for suf, path, tr in self._BLK:
            put(
                ("visual", "blocks") + path,
                np.stack([
                    one(f"model.visual.blocks.{i}.{suf}", tr)
                    for i in range(v.num_layers)
                ]),
            )
        for suf, path, tr in self._MERGER:
            put(("visual", "merger") + path, one("model.visual.merger." + suf, tr))
        for suf, path, tr in self._MERGER:
            put(
                ("visual", "deepstack_mergers") + path,
                np.stack([
                    one(f"model.visual.deepstack_merger_list.{j}.{suf}", tr)
                    for j in range(len(v.deepstack_visual_indexes))
                ]),
            )

        I = self.cfg.text.moe.moe_intermediate_size

        def lm_read(name):
            if name == "lm_head.weight":
                return read("lm_head.weight")
            assert name.startswith("model."), name
            rest = name[len("model."):]
            if ".mlp.experts." in rest:
                head, _, tail = rest.partition(".mlp.experts.")
                e_str, proj, _w = tail.split(".")
                e = int(e_str)
                if proj == "down_proj":
                    # stacked (E, I, dim) kernel-oriented; per-expert HF
                    # linear expected by MoEDecoderAdapter is (dim, I) → T
                    return np.asarray(
                        read(f"model.language_model.{head}.mlp.experts.down_proj")
                    )[e].T
                gu = np.asarray(
                    read(f"model.language_model.{head}.mlp.experts.gate_up_proj")
                )[e]  # (dim, 2I) [gate; up]
                half = gu[:, :I] if proj == "gate_proj" else gu[:, I:]
                return np.ascontiguousarray(half.T)  # HF linear (I, dim)
            return read("model.language_model." + rest)

        lm_sh = _get(shardings, ("language_model",)) if shardings is not None else None
        params["language_model"] = self._lm().from_hf(lm_read, shardings=lm_sh)
        return params

    def to_hf(self, params):
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get

        v = self.cfg.vision
        E = self.cfg.text.moe.n_routed_experts

        def _t(x):
            return np.ascontiguousarray(np.asarray(x).T)

        vis = params["visual"]
        g = v.pos_grid
        yield "model.visual.pos_embed.weight", np.asarray(
            vis["pos_embed"]["weight"]
        ).reshape(g * g, -1)
        k = np.asarray(vis["patch_embed"]["proj"]["kernel"])  # (P,P,3*tp,D)
        P_, _, Ctp, D_ = k.shape
        k = k.reshape(P_, P_, Ctp // 3, 3, D_)
        yield "model.visual.patch_embed.proj.weight", np.ascontiguousarray(
            np.transpose(k, (4, 3, 2, 0, 1))
        )
        yield "model.visual.patch_embed.proj.bias", np.asarray(
            vis["patch_embed"]["proj"]["bias"]
        )
        for i in range(v.num_layers):
            for suf, path, tr in self._BLK:
                x = np.asarray(_get(vis["blocks"], path)[i])
                yield f"model.visual.blocks.{i}.{suf}", (_t(x) if tr else x)
        for suf, path, tr in self._MERGER:
            x = np.asarray(_get(vis["merger"], path))
            yield "model.visual.merger." + suf, (_t(x) if tr else x)
        for j in range(len(v.deepstack_visual_indexes)):
            for suf, path, tr in self._MERGER:
                x = np.asarray(_get(vis["deepstack_mergers"], path)[j])
                yield f"model.visual.deepstack_merger_list.{j}.{suf}", (_t(x) if tr else x)

        gu_buf: dict = {}
        down_buf: dict = {}
        for name, tensor in self._lm().to_hf(params["language_model"]):
            if name == "lm_head.weight":
                yield name, tensor
                continue
            rest = name[len("model."):]
            if ".mlp.experts." in rest:
                head, _, tail = rest.partition(".mlp.experts.")
                e_str, proj, _w = tail.split(".")
                e = int(e_str)
                full = f"model.language_model.{head}.mlp.experts."
                if proj == "down_proj":
                    buf = down_buf.setdefault(head, {})
                    buf[e] = tensor  # HF per-expert (dim, I) → stacked (E, I, dim)
                    if len(buf) == E:
                        yield full + "down_proj", np.stack(
                            [np.ascontiguousarray(buf[i].T) for i in range(E)]
                        )
                        del down_buf[head]  # bound host memory to one layer
                else:
                    buf = gu_buf.setdefault(head + "|" + proj, {})
                    buf[e] = tensor  # HF per-expert (I, dim)
                    gk, uk = head + "|gate_proj", head + "|up_proj"
                    if len(gu_buf.get(gk, {})) == E and len(gu_buf.get(uk, {})) == E:
                        yield full + "gate_up_proj", np.stack(
                            [
                                np.ascontiguousarray(
                                    np.concatenate(
                                        [gu_buf[gk][i].T, gu_buf[uk][i].T], axis=1
                                    )
                                )
                                for i in range(E)
                            ]
                        )
                        del gu_buf[gk], gu_buf[uk]
                continue
            yield "model.language_model." + rest, tensor


def _register_adapter():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["qwen3_vl_moe"] = Qwen3VLMoEAdapter


_register_adapter()
