"""Kimi-VL: MoonViT vision tower → 2×2 merge + MLP projector → DeepSeek-V3
MoE text model.

The analog of the reference's kimivl (reference: nemo_automodel/components/
models/kimivl/model.py, 908 LoC): MoonViT is a bias-ful ViT with a learnable
interpolatable 2D position embedding, interleaved 2D rope over (x, y) patch
coordinates (model.py:195 `Rope2DPosEmb`, :138 `_apply_rope_vision`),
LayerNorm/GELU-tanh blocks, and a 2×2 patch merger feeding a
pre-LN → linear → gelu → linear projector into the DeepSeek-V3 hidden space
(model.py:387). The text model is our MoE decoder with the deepseek config
(the reference wires HF DeepseekV3 modeling; kimi_k2 checkpoints share the
layout).

TPU design: one fixed patch grid per batch (static shapes under jit; the
reference's per-image variable grids are a host-side collation concern —
the collator resizes to the configured grid). Attention inside the tower is
bidirectional full attention over the image's patches.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.models.moe_lm import decoder as moe_decoder
from automodel_tpu.models.moe_lm.families import deepseek_v3_moe_config
from automodel_tpu.models.vlm.llava import merge_image_embeddings


@dataclasses.dataclass(frozen=True)
class MoonViTConfig:
    patch_size: int = 14
    pos_emb_height: int = 64
    pos_emb_width: int = 64
    num_heads: int = 16
    num_layers: int = 27
    hidden_size: int = 1152
    intermediate_size: int = 4304
    merge_kernel: tuple = (2, 2)
    rope_theta: float = 10000.0
    # Kimi-K2.5 (MoonViT3d): divided space/time position embeddings — the
    # temporal part is a FIXED 1D sincos table (reference: kimi_k25_vl/
    # model.py:190 get_1d_sincos_pos_embed). Image inputs sit at t=0, whose
    # sincos vector is a deterministic constant added to every patch.
    temporal_pos_emb: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


@dataclasses.dataclass(frozen=True)
class KimiVLConfig:
    vision: MoonViTConfig = dataclasses.field(default_factory=MoonViTConfig)
    text: Any = None  # MoETransformerConfig (deepseek-v3 body)
    image_token_id: int = 163605

    @property
    def dtype(self):
        return self.text.dtype

    @property
    def moe(self):
        return self.text.moe

    @property
    def mtp_num_layers(self) -> int:
        return getattr(self.text, "mtp_num_layers", 0)

    def flops_per_token(self, seq_len: int) -> float:
        v = self.vision
        vis_params = v.num_layers * (4 * v.hidden_size**2 + 2 * v.hidden_size * v.intermediate_size)
        return self.text.flops_per_token(seq_len) + 6.0 * vis_params / max(seq_len, 1)


def kimi_vl_config(hf: Mapping[str, Any], **overrides) -> KimiVLConfig:
    """HF KimiVLConfig: {vision_config (moonvit), text_config (deepseek_v3),
    media_placeholder_token_id}."""
    v = dict(hf.get("vision_config") or {})
    text_overrides = {
        k: overrides[k]
        for k in ("dtype", "remat_policy", "attn_impl", "linear_precision")
        if k in overrides
    }
    text = deepseek_v3_moe_config(dict(hf["text_config"]), **text_overrides)
    mk = v.get("merge_kernel_size", (2, 2))
    vision = MoonViTConfig(
        patch_size=int(v.get("patch_size", 14)),
        pos_emb_height=int(v.get("init_pos_emb_height", 64)),
        pos_emb_width=int(v.get("init_pos_emb_width", 64)),
        num_heads=int(v.get("num_attention_heads", 16)),
        num_layers=int(v.get("num_hidden_layers", 27)),
        hidden_size=int(v.get("hidden_size", 1152)),
        intermediate_size=int(v.get("intermediate_size", 4304)),
        merge_kernel=tuple(mk),
    )
    return KimiVLConfig(
        vision=vision,
        text=text,
        image_token_id=int(
            hf.get("media_placeholder_token_id", hf.get("image_token_id", 163605))
        ),
    )


# ---------------------------------------------------------------------------
# MoonViT tower
# ---------------------------------------------------------------------------
def _ln_init(dim):
    return {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}


def init_vision(cfg: MoonViTConfig, rng: jax.Array) -> dict:
    D, I, P = cfg.hidden_size, cfg.intermediate_size, cfg.patch_size
    L = cfg.num_layers
    ks = jax.random.split(rng, 8)

    def stack(k, shape):
        return jnp.stack([dense_init(kk, shape) for kk in jax.random.split(k, L)])

    return {
        "patch_embed": {
            # conv kernel stored (P, P, C, D) — HWIO
            "proj": {
                "kernel": 0.02 * jax.random.normal(ks[0], (P, P, 3, D)),
                "bias": jnp.zeros((D,)),
            },
            "pos_emb": {
                "weight": jax.random.normal(
                    ks[1], (cfg.pos_emb_height, cfg.pos_emb_width, D)
                )
            },
        },
        "blocks": {
            "norm0": {"scale": jnp.ones((L, D)), "bias": jnp.zeros((L, D))},
            "norm1": {"scale": jnp.ones((L, D)), "bias": jnp.zeros((L, D))},
            "wqkv": {"kernel": stack(ks[2], (D, 3 * D)), "bias": jnp.zeros((L, 3 * D))},
            "wo": {"kernel": stack(ks[3], (D, D)), "bias": jnp.zeros((L, D))},
            "fc0": {"kernel": stack(ks[4], (D, I)), "bias": jnp.zeros((L, I))},
            "fc1": {"kernel": stack(ks[5], (I, D)), "bias": jnp.zeros((L, D))},
        },
        "final_norm": _ln_init(D),
    }


def vision_param_specs(cfg: MoonViTConfig) -> dict:
    return {
        "patch_embed": {
            "proj": {"kernel": (None, None, None, "embed"), "bias": ("norm",)},
            "pos_emb": {"weight": (None, None, "embed")},
        },
        "blocks": {
            "norm0": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "norm1": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "wqkv": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "wo": {"kernel": ("layers", "heads", "embed"), "bias": ("layers", "norm")},
            "fc0": {"kernel": ("layers", "embed", "mlp"), "bias": ("layers", "mlp")},
            "fc1": {"kernel": ("layers", "mlp", "embed"), "bias": ("layers", "norm")},
        },
        "final_norm": {"scale": ("norm",), "bias": ("norm",)},
    }


def _layer_norm(x, p, eps=1e-5):
    mu = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
    y = (x.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)).astype(x.dtype)


def _rope2d_angles(cfg: MoonViTConfig, gh: int, gw: int) -> jnp.ndarray:
    """(gh*gw, head_dim/2) rotation angles, pairs alternating (x, y)
    (reference Rope2DPosEmb: freqs over dim/4, x/y interleaved per pair)."""
    d = cfg.head_dim
    n4 = d // 4
    freqs = 1.0 / (cfg.rope_theta ** (jnp.arange(0, d, 4)[:n4] / d))
    ys, xs = jnp.meshgrid(jnp.arange(gh), jnp.arange(gw), indexing="ij")
    x_ang = xs.reshape(-1, 1) * freqs[None, :]  # (N, d/4)
    y_ang = ys.reshape(-1, 1) * freqs[None, :]
    return jnp.stack([x_ang, y_ang], axis=-1).reshape(gh * gw, d // 2)


def _apply_rope2d(x, angles):
    """x (B, N, Hn, D); angles (N, D/2): rotate adjacent channel pairs."""
    B, N, Hn, D = x.shape
    xf = x.astype(jnp.float32).reshape(B, N, Hn, D // 2, 2)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    a, b = xf[..., 0], xf[..., 1]
    out = jnp.stack([a * cos - b * sin, a * sin + b * cos], axis=-1)
    return out.reshape(B, N, Hn, D).astype(x.dtype)


def vision_forward(params: dict, cfg: MoonViTConfig, pixel_values: jnp.ndarray) -> jnp.ndarray:
    """pixel_values (B, H, W, 3) → merged patch features
    (B, (gh/kh)*(gw/kw), kh*kw, D)."""
    B, Himg, Wimg, C = pixel_values.shape
    P = cfg.patch_size
    gh, gw = Himg // P, Wimg // P
    D = cfg.hidden_size
    dtype = params["blocks"]["wqkv"]["kernel"].dtype

    x = jax.lax.conv_general_dilated(
        pixel_values.astype(dtype),
        params["patch_embed"]["proj"]["kernel"].astype(dtype),
        window_strides=(P, P), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ) + params["patch_embed"]["proj"]["bias"].astype(dtype)
    x = x.reshape(B, gh * gw, D)

    if cfg.temporal_pos_emb:
        # t=0 row of the fixed temporal sincos table: sin(0)=0 | cos(0)=1
        D_ = cfg.hidden_size
        half = D_ // 2
        t0 = jnp.concatenate([jnp.zeros((half,)), jnp.ones((D_ - half,))])
        x = x + t0.astype(x.dtype)
    pe = params["patch_embed"]["pos_emb"]["weight"]
    if pe.shape[:2] != (gh, gw):
        pe = jax.image.resize(pe, (gh, gw, D), method="bicubic")
    x = x + pe.reshape(1, gh * gw, D).astype(dtype)

    angles = _rope2d_angles(cfg, gh, gw)
    Hn, hd = cfg.num_heads, cfg.head_dim

    def block(x, lp):
        y = _layer_norm(x, lp["norm0"])
        qkv = y @ lp["wqkv"]["kernel"] + lp["wqkv"]["bias"]
        qkv = qkv.reshape(B, gh * gw, 3, Hn, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = _apply_rope2d(q, angles)
        k = _apply_rope2d(k, angles)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        p = jax.nn.softmax(s * (hd ** -0.5), axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, gh * gw, D)
        x = x + attn @ lp["wo"]["kernel"] + lp["wo"]["bias"]
        y = _layer_norm(x, lp["norm1"])
        m = jax.nn.gelu(y @ lp["fc0"]["kernel"] + lp["fc0"]["bias"], approximate=True)
        x = x + m @ lp["fc1"]["kernel"] + lp["fc1"]["bias"]
        return x, None

    x, _ = jax.lax.scan(block, x, params["blocks"])
    x = _layer_norm(x, params["final_norm"])

    kh, kw = cfg.merge_kernel
    x = x.reshape(B, gh // kh, kh, gw // kw, kw, D)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(B, (gh // kh) * (gw // kw), kh * kw, D)


# ---------------------------------------------------------------------------
# full model
# ---------------------------------------------------------------------------
def init(cfg: KimiVLConfig, rng: jax.Array) -> dict:
    kv, kt, kp = jax.random.split(rng, 3)
    D = cfg.vision.hidden_size
    kh, kw = cfg.vision.merge_kernel
    merged = D * kh * kw
    Ht = cfg.text.hidden_size
    k1, k2 = jax.random.split(kp)
    return {
        "vision_tower": init_vision(cfg.vision, kv),
        "projector": {
            "pre_norm": _ln_init(D),
            "linear_1": {"kernel": dense_init(k1, (merged, merged)), "bias": jnp.zeros((merged,))},
            "linear_2": {"kernel": dense_init(k2, (merged, Ht)), "bias": jnp.zeros((Ht,))},
        },
        "language_model": moe_decoder.init(cfg.text, kt),
    }


def param_specs(cfg: KimiVLConfig) -> dict:
    return {
        "vision_tower": vision_param_specs(cfg.vision),
        "projector": {
            "pre_norm": {"scale": ("norm",), "bias": ("norm",)},
            "linear_1": {"kernel": ("embed", "mlp"), "bias": ("norm",)},
            "linear_2": {"kernel": ("mlp", "embed"), "bias": ("norm",)},
        },
        "language_model": moe_decoder.param_specs(cfg.text),
    }


def encode_images(params: dict, cfg: KimiVLConfig, pixel_values: jnp.ndarray) -> jnp.ndarray:
    """MoonViT tower + merge + projector → image embeddings (B, Nm, H_text).
    Shared by forward and vlm_generate."""
    feats = vision_forward(params["vision_tower"], cfg.vision, pixel_values)
    pj = params["projector"]
    dtype = cfg.dtype
    x = _layer_norm(feats.astype(dtype), pj["pre_norm"])  # LN over D per patch
    B, Nm, K4, D = x.shape
    x = x.reshape(B, Nm, K4 * D)
    x = jax.nn.gelu(
        x @ pj["linear_1"]["kernel"].astype(dtype) + pj["linear_1"]["bias"].astype(dtype),
        approximate=True,
    )
    return x @ pj["linear_2"]["kernel"].astype(dtype) + pj["linear_2"]["bias"].astype(dtype)


def forward(
    params: dict,
    cfg: KimiVLConfig,
    input_ids: jnp.ndarray,      # (B, S)
    pixel_values: jnp.ndarray,   # (B, H, W, 3)
    *,
    positions=None,
    segment_ids=None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    token_mask=None,
    return_stats: bool = False,
):
    """Returns (out, aux_loss[, stats]) — the MoE module protocol (the VLM
    recipe folds aux into the loss)."""
    image_embeds = encode_images(params, cfg, pixel_values)

    from automodel_tpu.models.llm.decoder import _make_constrain

    lm = params["language_model"]
    # FSDP-unshard the table's embed dim before the gather (see moe decoder)
    constrain = _make_constrain(mesh_ctx, rules)
    tbl = constrain(lm["embed"]["embedding"], ("vocab", None))
    token_embeds = jnp.take(tbl, input_ids, axis=0).astype(cfg.dtype)
    merged = merge_image_embeddings(
        token_embeds, image_embeds, input_ids == cfg.image_token_id
    )
    return moe_decoder.forward(
        lm, cfg.text, input_ids,
        positions=positions, segment_ids=segment_ids,
        mesh_ctx=mesh_ctx, rules=rules,
        return_hidden=return_hidden, inputs_embeds=merged,
        token_mask=token_mask, return_stats=return_stats,
    )


def kimi_k25_vl_config(hf, **overrides) -> KimiVLConfig:
    """KimiK25VLForConditionalGeneration (reference: models/kimi_k25_vl/,
    1593 LoC — MoonViT3d + DeepseekV3 text): the kimi_vl geometry plus the
    divided space/time position embedding. Image inputs sit at t=0 of the
    FIXED temporal sincos table (a deterministic constant; video temporal
    attention is image-only-skipped, the reference's stance for several VL
    onboardings)."""
    cfg = kimi_vl_config(hf, **overrides)
    import dataclasses as _dc

    return _dc.replace(
        cfg, vision=_dc.replace(cfg.vision, temporal_pos_emb=True)
    )


# ---------------------------------------------------------------------------
# HF state-dict adapter
# ---------------------------------------------------------------------------
class KimiVLAdapter:
    """HF Kimi-VL layout: `vision_tower.*` / `multi_modal_projector.*` /
    `language_model.model.*` + `language_model.lm_head.*` (deepseek naming
    inside — delegated to MoEDecoderAdapter with a key-prefix shim)."""

    def __init__(self, cfg: KimiVLConfig, style: str = "kimi"):
        self.cfg = cfg
        # "k25": Kimi-K2.5 checkpoint names the projector mm_projector with
        # Sequential indices (reference: kimi_k25_vl/state_dict_adapter.py:
        # 208-211 linear_1→proj.0, linear_2→proj.2)
        self.style = style

    def _proj_name(self, suf: str) -> str:
        if self.style == "k25":
            suf = suf.replace("linear_1.", "proj.0.").replace("linear_2.", "proj.2.")
            return "mm_projector." + suf
        return "multi_modal_projector." + suf

    def _lm(self):
        from automodel_tpu.checkpoint.hf_adapter import MoEDecoderAdapter

        return MoEDecoderAdapter(self.cfg.text, style="deepseek")

    _VIS = [
        # (hf suffix, path, transpose)
        ("patch_embed.pos_emb.weight", ("patch_embed", "pos_emb", "weight"), False),
        ("encoder.final_layernorm.weight", ("final_norm", "scale"), False),
        ("encoder.final_layernorm.bias", ("final_norm", "bias"), False),
    ]
    _BLK = [
        ("norm0.weight", ("norm0", "scale"), False),
        ("norm0.bias", ("norm0", "bias"), False),
        ("norm1.weight", ("norm1", "scale"), False),
        ("norm1.bias", ("norm1", "bias"), False),
        ("wqkv.weight", ("wqkv", "kernel"), True),
        ("wqkv.bias", ("wqkv", "bias"), False),
        ("wo.weight", ("wo", "kernel"), True),
        ("wo.bias", ("wo", "bias"), False),
        ("mlp.fc0.weight", ("fc0", "kernel"), True),
        ("mlp.fc0.bias", ("fc0", "bias"), False),
        ("mlp.fc1.weight", ("fc1", "kernel"), True),
        ("mlp.fc1.bias", ("fc1", "bias"), False),
    ]
    _PROJ = [
        ("pre_norm.weight", ("pre_norm", "scale"), False),
        ("pre_norm.bias", ("pre_norm", "bias"), False),
        ("linear_1.weight", ("linear_1", "kernel"), True),
        ("linear_1.bias", ("linear_1", "bias"), False),
        ("linear_2.weight", ("linear_2", "kernel"), True),
        ("linear_2.bias", ("linear_2", "bias"), False),
    ]

    def from_hf(self, read, shardings=None) -> dict:
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get, _set

        params: dict = {}

        def put(path, value):
            sh = _get(shardings, path) if shardings is not None else None
            _set(params, path, jax.device_put(value, sh) if sh is not None else jnp.asarray(value))

        def one(name, transpose):
            x = read(name)
            return np.ascontiguousarray(np.asarray(x).T) if transpose else np.asarray(x)

        for suf, path, tr in self._VIS:
            put(("vision_tower",) + path, one("vision_tower." + suf, tr))
        # conv2d: HF OIHW (D, 3, P, P) → HWIO (P, P, 3, D)
        w = np.asarray(read("vision_tower.patch_embed.proj.weight"))
        put(("vision_tower", "patch_embed", "proj", "kernel"),
            np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0))))
        put(("vision_tower", "patch_embed", "proj", "bias"),
            np.asarray(read("vision_tower.patch_embed.proj.bias")))
        L = self.cfg.vision.num_layers
        for suf, path, tr in self._BLK:
            put(
                ("vision_tower", "blocks") + path,
                np.stack([
                    one(f"vision_tower.encoder.blocks.{i}.{suf}", tr)
                    for i in range(L)
                ]),
            )
        for suf, path, tr in self._PROJ:
            put(("projector",) + path, one(self._proj_name(suf), tr))

        def lm_read(name):
            if name == "lm_head.weight":
                return read("language_model.lm_head.weight")
            assert name.startswith("model."), name
            return read("language_model." + name)

        lm_sh = _get(shardings, ("language_model",)) if shardings is not None else None
        params["language_model"] = self._lm().from_hf(lm_read, shardings=lm_sh)
        return params

    def to_hf(self, params):
        import numpy as np

        def _t(x):
            return np.ascontiguousarray(np.asarray(x).T)

        vis = params["vision_tower"]
        from automodel_tpu.checkpoint.hf_adapter import _get

        for suf, path, tr in self._VIS:
            x = np.asarray(_get(vis, path))
            yield "vision_tower." + suf, (_t(x) if tr else x)
        k = np.asarray(vis["patch_embed"]["proj"]["kernel"])  # (P,P,3,D)
        yield "vision_tower.patch_embed.proj.weight", np.ascontiguousarray(
            np.transpose(k, (3, 2, 0, 1))
        )
        yield "vision_tower.patch_embed.proj.bias", np.asarray(
            vis["patch_embed"]["proj"]["bias"]
        )
        L = self.cfg.vision.num_layers
        for i in range(L):
            for suf, path, tr in self._BLK:
                x = np.asarray(_get(vis["blocks"], path)[i])
                yield f"vision_tower.encoder.blocks.{i}.{suf}", (_t(x) if tr else x)
        for suf, path, tr in self._PROJ:
            x = np.asarray(_get(params["projector"], path))
            yield self._proj_name(suf), (_t(x) if tr else x)
        for name, tensor in self._lm().to_hf(params["language_model"]):
            if name == "lm_head.weight":
                yield "language_model.lm_head.weight", tensor
            else:
                yield "language_model." + name, tensor


def _register_adapter():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["kimi_vl"] = KimiVLAdapter


_register_adapter()
