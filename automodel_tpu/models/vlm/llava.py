"""LLaVA-style vision-language model: ViT tower → MLP projector → decoder.

The analog of the reference's VLM families (reference: nemo_automodel/
components/models/llava_onevision/ — 909 LoC; _transformers
NeMoAutoModelForImageTextToText). Image patch features are projected into
the text embedding space and scattered into the token stream at the image
placeholder positions (the HF llava merge), then the standard dense decoder
runs on the merged embeddings.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.models.llm import decoder as text_decoder
from automodel_tpu.models.llm.families import llama_config, qwen2_config
from automodel_tpu.models.vision import vit


@dataclasses.dataclass(frozen=True)
class LlavaConfig:
    vision: vit.VisionConfig = dataclasses.field(default_factory=vit.VisionConfig)
    text: Any = dataclasses.field(default_factory=text_decoder.TransformerConfig)
    image_token_id: int = 32000
    projector_layers: int = 2

    @property
    def dtype(self):
        return self.text.dtype

    def flops_per_token(self, seq_len: int) -> float:
        """Text FLOPs/token + the tower+projector cost of one image per
        sample amortized over the sequence."""
        Hv, Ht = self.vision.hidden_size, self.text.hidden_size
        vision_per_image = 6.0 * self.vision.param_count() * self.vision.num_positions
        projector_per_image = 6.0 * (Hv * Ht + Ht * Ht) * self.vision.num_patches
        return (
            self.text.flops_per_token(seq_len)
            + (vision_per_image + projector_per_image) / seq_len
        )


_TEXT_ADAPTERS = {
    "llama": llama_config,
    "qwen2": qwen2_config,
}


def llava_config(hf: Mapping[str, Any], **overrides) -> LlavaConfig:
    """HF llava-style config: {vision_config, text_config, image_token_index}."""
    text_hf = dict(hf["text_config"])
    arch = (text_hf.get("architectures") or ["LlamaForCausalLM"])[0]
    name = "qwen2" if "Qwen2" in arch else "llama"
    text_overrides = {
        k: overrides[k] for k in ("dtype", "remat_policy", "attn_impl") if k in overrides
    }
    text = _TEXT_ADAPTERS[name](text_hf, **text_overrides)
    vision_hf = dict(hf["vision_config"])
    vision_kw = dict(
        dtype=text.dtype,
        remat_policy=text_overrides.get("remat_policy", "full"),
    )
    if vision_hf.get("model_type", "") == "clip_vision_model":
        # CLIP towers: class token, pre-LN, quick_gelu, and llava selects
        # the penultimate layer's patch features by default
        vision_kw.update(
            use_cls_token=True,
            use_pre_layernorm=True,
            activation="quick_gelu",
            feature_layer=int(hf.get("vision_feature_layer", -2)),
        )
    vision = vit.VisionConfig.from_hf(vision_hf, **vision_kw)
    return LlavaConfig(
        vision=vision,
        text=text,
        image_token_id=int(hf.get("image_token_index", hf.get("image_token_id", 32000))),
    )


def init(cfg: LlavaConfig, rng: jax.Array) -> dict:
    kv, kt, kp = jax.random.split(rng, 3)
    Hv, Ht = cfg.vision.hidden_size, cfg.text.hidden_size
    k1, k2 = jax.random.split(kp)
    return {
        "vision_tower": vit.init(cfg.vision, kv),
        "projector": {
            "fc1": {"kernel": dense_init(k1, (Hv, Ht)), "bias": jnp.zeros((Ht,))},
            "fc2": {"kernel": dense_init(k2, (Ht, Ht)), "bias": jnp.zeros((Ht,))},
        },
        "language_model": text_decoder.init(cfg.text, kt),
    }


def param_specs(cfg: LlavaConfig) -> dict:
    return {
        "vision_tower": vit.param_specs(cfg.vision),
        "projector": {
            "fc1": {"kernel": ("embed", "mlp"), "bias": ("norm",)},
            "fc2": {"kernel": ("mlp", "embed"), "bias": ("norm",)},
        },
        "language_model": text_decoder.param_specs(cfg.text),
    }


def merge_image_embeddings(
    token_embeds: jnp.ndarray,   # (B, S, H)
    image_embeds: jnp.ndarray,   # (B, N, H)
    image_mask: jnp.ndarray,     # (B, S) bool — True at placeholder tokens
) -> jnp.ndarray:
    """Scatter the j-th image patch into the j-th placeholder position
    (the HF llava merge, jit-friendly via cumsum indexing)."""
    order = jnp.cumsum(image_mask.astype(jnp.int32), axis=1) - 1  # (B, S)
    order = jnp.clip(order, 0, image_embeds.shape[1] - 1)
    gathered = jnp.take_along_axis(image_embeds, order[..., None], axis=1)
    return jnp.where(image_mask[..., None], gathered.astype(token_embeds.dtype), token_embeds)


def encode_images(params: dict, cfg: LlavaConfig, pixel_values: jnp.ndarray) -> jnp.ndarray:
    """Vision tower + projector → per-image patch embeddings in the text
    embedding space (B, N, H_text). Shared by forward and vlm_generate."""
    feats = vit.forward(params["vision_tower"], cfg.vision, pixel_values)
    if cfg.vision.use_cls_token:
        feats = feats[:, 1:]  # llava "default" select: drop the CLS feature
    pj = params["projector"]
    x = jax.nn.gelu(
        feats.astype(cfg.dtype) @ pj["fc1"]["kernel"].astype(cfg.dtype)
        + pj["fc1"]["bias"].astype(cfg.dtype),
        approximate=True,
    )
    return x @ pj["fc2"]["kernel"].astype(cfg.dtype) + pj["fc2"]["bias"].astype(cfg.dtype)


def forward(
    params: dict,
    cfg: LlavaConfig,
    input_ids: jnp.ndarray,      # (B, S)
    pixel_values: jnp.ndarray,   # (B, H, W, C)
    *,
    positions=None,
    segment_ids=None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
):
    image_embeds = encode_images(params, cfg, pixel_values)

    lm = params["language_model"]
    token_embeds = jnp.take(lm["embed"]["embedding"], input_ids, axis=0).astype(cfg.dtype)
    merged = merge_image_embeddings(
        token_embeds, image_embeds, input_ids == cfg.image_token_id
    )
    return text_decoder.forward(
        lm, cfg.text, input_ids,
        positions=positions, segment_ids=segment_ids,
        mesh_ctx=mesh_ctx, rules=rules,
        return_hidden=return_hidden, inputs_embeds=merged,
    )
