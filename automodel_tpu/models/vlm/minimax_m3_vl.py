"""MiniMax M3 VL: CLIP-style tower with 3D rope → projector → patch merger
→ MiniMax M3 (mixed sparse/dense MoE) text backbone.

The analog of the reference's minimax_m3_vl (reference: nemo_automodel/
components/models/minimax_m3_vl/, 2980 LoC — vision_encoder.py tower,
model.py `MiniMaxM3SparseForConditionalGeneration`). TPU design notes:

- Vision (vision_encoder.py:126 `MiniMaxM3VisionTransformer`): conv patch
  embed over (temporal_patch × P × P) voxels with frames duplicated across
  the temporal patch (folded into the channel dim, checkpoint-invertible —
  the qwen3_vl idiom), `pre_layrnorm` (checkpoint typo preserved), then
  bidirectional pre-LN CLIP blocks with separate biased q/k/v/out
  projections and axis-split 3D NEOX rope: axis_dim = 2·((2·(hd//2)//3)//2)
  channels per t/h/w axis, angles concatenated then half-split rotated over
  the first 3·axis_dim channels, tail passes through. Tokens are arranged
  in SPATIAL-MERGE-BLOCK order (each m×m block contiguous) so the rope
  positions (vision_encoder.py:149 `_rope_position_freqs`) and the merger's
  consecutive-m² reshape both hold. Images ⇒ t = 0.
- Projector then merger (vision_encoder.py:215,228): 2-layer GELU projector
  (vision → projector_hidden → text), then the patch merger folds m²
  consecutive projected tokens → projector_hidden → text.
- Text: the het_moe engine with `minimax_m3_text_config` — per-layer
  dense/MoE (moe_layer_freq), block-sparse DSA layers, gemma norms,
  swigluoai. Features are spliced at image_token_index positions; plain
  integer positions (no MRoPE), so `encode_images` + the generic VLM
  generate path compose. Each batch row is one image: batching gives the
  block-diagonal no-cross-image attention the reference builds masks for.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.models.moe_lm import het_moe
from automodel_tpu.models.moe_lm.het_families import minimax_m3_text_config
from automodel_tpu.models.vlm.kimi_vl import _layer_norm, _ln_init
from automodel_tpu.models.vlm.llava import merge_image_embeddings
from automodel_tpu.ops.rope import apply_rope


@dataclasses.dataclass(frozen=True)
class MiniMaxM3VisionConfig:
    hidden_size: int = 1280
    num_heads: int = 16
    num_layers: int = 32
    intermediate_size: int = 5120
    patch_size: int = 14
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def axis_dim(self) -> int:
        """Per-axis rope channels (reference: vision_encoder.py:143)."""
        rope_dims = 2 * (self.head_dim // 2)
        return int(2 * ((rope_dims // 3) // 2))


@dataclasses.dataclass(frozen=True)
class MiniMaxM3VLConfig:
    vision: MiniMaxM3VisionConfig = dataclasses.field(
        default_factory=MiniMaxM3VisionConfig
    )
    text: Any = None  # HetMoEConfig (minimax_m3 body)
    image_token_id: int = 200025
    projector_hidden_size: int = 6144
    projector_bias: bool = True
    patch_merge_bias: bool = True

    @property
    def dtype(self):
        return self.text.dtype

    @property
    def moe(self):
        return self.text.moe

    @property
    def mtp_num_layers(self) -> int:
        return 0

    def flops_per_token(self, seq_len: int) -> float:
        v = self.vision
        vis = v.num_layers * (
            4 * v.hidden_size ** 2 + 2 * v.hidden_size * v.intermediate_size
        )
        return self.text.flops_per_token(seq_len) + 6.0 * vis / max(seq_len, 1)


def minimax_m3_vl_config(hf: Mapping[str, Any], **overrides) -> MiniMaxM3VLConfig:
    v = dict(hf.get("vision_config") or {})
    comp = dict(v.get("img_token_compression_config") or {})
    text_hf = dict(hf["text_config"])
    text_overrides = {
        k: overrides[k]
        for k in ("dtype", "remat_policy", "attn_impl", "linear_precision")
        if k in overrides
    }
    text = minimax_m3_text_config(text_hf, **text_overrides)
    vision = MiniMaxM3VisionConfig(
        hidden_size=int(v.get("hidden_size", 1280)),
        num_heads=int(v.get("num_attention_heads", 16)),
        num_layers=int(v.get("num_hidden_layers", 32)),
        intermediate_size=int(v.get("intermediate_size", 5120)),
        patch_size=int(v.get("patch_size", 14)),
        temporal_patch_size=int(comp.get("temporal_patch_size", 2)),
        spatial_merge_size=int(comp.get("spatial_merge_size", 2)),
        rope_theta=float(v.get("rope_theta", 10000.0)),
        layer_norm_eps=float(v.get("layer_norm_eps", 1e-5)),
    )
    return MiniMaxM3VLConfig(
        vision=vision,
        text=text,
        image_token_id=int(hf.get("image_token_index", 200025)),
        projector_hidden_size=int(hf.get("projector_hidden_size", text.hidden_size)),
        projector_bias=bool(hf.get("multimodal_projector_bias", True)),
        patch_merge_bias=bool(hf.get("patch_merge_bias", True)),
    )


# ---------------------------------------------------------------------------
# vision tower
# ---------------------------------------------------------------------------
def _proj2_init(k, din, dhid, dout, bias: bool):
    k1, k2 = jax.random.split(k)
    p = {
        "linear_1": {"kernel": dense_init(k1, (din, dhid))},
        "linear_2": {"kernel": dense_init(k2, (dhid, dout))},
    }
    if bias:
        p["linear_1"]["bias"] = jnp.zeros((dhid,))
        p["linear_2"]["bias"] = jnp.zeros((dout,))
    return p


def init_vision(cfg: MiniMaxM3VLConfig, rng: jax.Array) -> dict:
    v = cfg.vision
    D, I, P = v.hidden_size, v.intermediate_size, v.patch_size
    L = v.num_layers
    Cin = 3 * v.temporal_patch_size
    m = v.spatial_merge_size
    T = cfg.text.hidden_size
    ks = jax.random.split(rng, 8)

    def stack(k, shape):
        return jnp.stack([dense_init(kk, shape) for kk in jax.random.split(k, L)])

    return {
        "patch_embed": {
            "kernel": 0.02 * jax.random.normal(ks[0], (P, P, Cin, D)),
        },
        "pre_layrnorm": _ln_init(D),
        "blocks": {
            "layer_norm1": {"scale": jnp.ones((L, D)), "bias": jnp.zeros((L, D))},
            "layer_norm2": {"scale": jnp.ones((L, D)), "bias": jnp.zeros((L, D))},
            "q_proj": {"kernel": stack(ks[1], (D, D)), "bias": jnp.zeros((L, D))},
            "k_proj": {"kernel": stack(ks[2], (D, D)), "bias": jnp.zeros((L, D))},
            "v_proj": {"kernel": stack(ks[3], (D, D)), "bias": jnp.zeros((L, D))},
            "out_proj": {"kernel": stack(ks[4], (D, D)), "bias": jnp.zeros((L, D))},
            "fc1": {"kernel": stack(ks[5], (D, I)), "bias": jnp.zeros((L, I))},
            "fc2": {"kernel": stack(ks[6], (I, D)), "bias": jnp.zeros((L, D))},
        },
        "projector": _proj2_init(
            jax.random.fold_in(ks[7], 0), D, cfg.projector_hidden_size, T,
            cfg.projector_bias,
        ),
        "patch_merger": _proj2_init(
            jax.random.fold_in(ks[7], 1), T * m * m, cfg.projector_hidden_size, T,
            cfg.patch_merge_bias,
        ),
    }


def vision_param_specs(cfg: MiniMaxM3VLConfig) -> dict:
    def proj2(bias):
        p = {
            "linear_1": {"kernel": ("embed", "mlp")},
            "linear_2": {"kernel": ("mlp", "embed")},
        }
        if bias:
            p["linear_1"]["bias"] = ("norm",)
            p["linear_2"]["bias"] = ("norm",)
        return p

    return {
        "patch_embed": {"kernel": (None, None, None, "embed")},
        "pre_layrnorm": {"scale": ("norm",), "bias": ("norm",)},
        "blocks": {
            "layer_norm1": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "layer_norm2": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "q_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "k_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "v_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "out_proj": {"kernel": ("layers", "heads", "embed"), "bias": ("layers", "norm")},
            "fc1": {"kernel": ("layers", "embed", "mlp"), "bias": ("layers", "mlp")},
            "fc2": {"kernel": ("layers", "mlp", "embed"), "bias": ("layers", "norm")},
        },
        "projector": proj2(cfg.projector_bias),
        "patch_merger": proj2(cfg.patch_merge_bias),
    }


def _vision_angles(v: MiniMaxM3VisionConfig, gh: int, gw: int) -> jnp.ndarray:
    """(gh·gw, 3·axis_dim/2) t/h/w angles in merge-block token order
    (reference: vision_encoder.py:149 `_rope_position_freqs`; images t=0)."""
    m = v.spatial_merge_size
    ad = v.axis_dim
    inv_freq = 1.0 / (v.rope_theta ** (jnp.arange(0, ad, 2, dtype=jnp.float32) / ad))
    ys, xs = jnp.meshgrid(jnp.arange(gh), jnp.arange(gw), indexing="ij")

    def merge_order(p):
        p = p.reshape(gh // m, m, gw // m, m)
        return jnp.transpose(p, (0, 2, 1, 3)).reshape(-1)

    hpos, wpos = merge_order(ys), merge_order(xs)
    h_ang = hpos[:, None].astype(jnp.float32) * inv_freq[None, :]
    w_ang = wpos[:, None].astype(jnp.float32) * inv_freq[None, :]
    t_ang = jnp.zeros_like(h_ang)
    return jnp.concatenate([t_ang, h_ang, w_ang], axis=-1)


def encode_images(params: dict, cfg: MiniMaxM3VLConfig, pixel_values: jnp.ndarray):
    """pixel_values (B, H, W, 3) → (B, (gh/m)·(gw/m), text_hidden)."""
    v = cfg.vision
    B, Himg, Wimg, _ = pixel_values.shape
    P, m = v.patch_size, v.spatial_merge_size
    gh, gw = Himg // P, Wimg // P
    D = v.hidden_size
    vp = params["visual"]
    dtype = vp["blocks"]["q_proj"]["kernel"].dtype

    pix = jnp.concatenate([pixel_values] * v.temporal_patch_size, axis=-1)
    x = jax.lax.conv_general_dilated(
        pix.astype(dtype), vp["patch_embed"]["kernel"].astype(dtype),
        window_strides=(P, P), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    # merge-block token order: each m×m spatial block contiguous
    x = x.reshape(B, gh // m, m, gw // m, m, D)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(B, gh * gw, D)
    x = _layer_norm(x, vp["pre_layrnorm"])

    angles = _vision_angles(v, gh, gw)[None]  # (1, N, 3·ad/2)
    Hn, hd = v.num_heads, v.head_dim

    def block(x, lp):
        y = _layer_norm(x, lp["layer_norm1"])
        q = (y @ lp["q_proj"]["kernel"] + lp["q_proj"]["bias"]).reshape(B, -1, Hn, hd)
        k = (y @ lp["k_proj"]["kernel"] + lp["k_proj"]["bias"]).reshape(B, -1, Hn, hd)
        vv = (y @ lp["v_proj"]["kernel"] + lp["v_proj"]["bias"]).reshape(B, -1, Hn, hd)
        q = apply_rope(q, None, angles)
        k = apply_rope(k, None, angles)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        p = jax.nn.softmax(s * (hd ** -0.5), axis=-1).astype(vv.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p, vv).reshape(B, -1, D)
        x = x + attn @ lp["out_proj"]["kernel"] + lp["out_proj"]["bias"]
        y = _layer_norm(x, lp["layer_norm2"])
        h = jax.nn.gelu(y @ lp["fc1"]["kernel"] + lp["fc1"]["bias"], approximate=False)
        return x + h @ lp["fc2"]["kernel"] + lp["fc2"]["bias"]

    def one(carry, lp):
        return block(carry, lp), None

    x, _ = jax.lax.scan(one, x, vp["blocks"])

    def proj2(x, pp):
        b1 = pp["linear_1"].get("bias")
        b2 = pp["linear_2"].get("bias")
        h = x @ pp["linear_1"]["kernel"].astype(x.dtype)
        if b1 is not None:
            h = h + b1.astype(x.dtype)
        h = jax.nn.gelu(h, approximate=False)
        h = h @ pp["linear_2"]["kernel"].astype(x.dtype)
        if b2 is not None:
            h = h + b2.astype(x.dtype)
        return h

    x = proj2(x, vp["projector"])                       # (B, N, text)
    T = x.shape[-1]
    x = x.reshape(B, (gh // m) * (gw // m), m * m * T)  # m² consecutive → 1
    return proj2(x, vp["patch_merger"])


# ---------------------------------------------------------------------------
# full model
# ---------------------------------------------------------------------------
def init(cfg: MiniMaxM3VLConfig, rng: jax.Array) -> dict:
    kv, kt = jax.random.split(rng)
    return {
        "visual": init_vision(cfg, kv),
        "language_model": het_moe.init(cfg.text, kt),
    }


def param_specs(cfg: MiniMaxM3VLConfig) -> dict:
    return {
        "visual": vision_param_specs(cfg),
        "language_model": het_moe.param_specs(cfg.text),
    }


def forward(
    params: dict,
    cfg: MiniMaxM3VLConfig,
    input_ids: jnp.ndarray,
    pixel_values: jnp.ndarray,
    *,
    positions=None,
    segment_ids=None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    token_mask=None,
    return_stats: bool = False,
):
    """Returns (out, aux_loss[, stats]) — the MoE module protocol."""
    image_embeds = encode_images(params, cfg, pixel_values)
    lm = params["language_model"]
    token_embeds = jnp.take(
        lm["embed"]["embedding"], input_ids, axis=0
    ).astype(cfg.dtype)
    merged = merge_image_embeddings(
        token_embeds, image_embeds, input_ids == cfg.image_token_id
    )
    return het_moe.forward(
        lm, cfg.text, input_ids,
        positions=positions, segment_ids=segment_ids,
        mesh_ctx=mesh_ctx, rules=rules,
        return_hidden=return_hidden, inputs_embeds=merged,
        token_mask=token_mask, return_stats=return_stats,
    )


def apply_gate_bias_update(params: dict, cfg: MiniMaxM3VLConfig, tokens_per_expert):
    lm = het_moe.apply_gate_bias_update(
        params["language_model"], cfg.text, tokens_per_expert
    )
    return {**params, "language_model": lm}


# ---------------------------------------------------------------------------
# HF state-dict adapter
# ---------------------------------------------------------------------------
class MiniMaxM3VLAdapter:
    """HF layout (reference: minimax_m3_vl/state_dict_adapter.py:318): text
    under `language_model.model.*` / `language_model.lm_head.weight`, tower
    under `vision_tower.vision_model.*`, projector / patch merger TOP-LEVEL
    (`multi_modal_projector.*`, `patch_merge_mlp.*`). Text tensors delegate
    to the het_moe adapter (style minimax_m3)."""

    _LN = [("weight", "scale"), ("bias", "bias")]
    _BLK = [
        ("layer_norm1.weight", ("layer_norm1", "scale"), False),
        ("layer_norm1.bias", ("layer_norm1", "bias"), False),
        ("layer_norm2.weight", ("layer_norm2", "scale"), False),
        ("layer_norm2.bias", ("layer_norm2", "bias"), False),
        ("self_attn.q_proj.weight", ("q_proj", "kernel"), True),
        ("self_attn.q_proj.bias", ("q_proj", "bias"), False),
        ("self_attn.k_proj.weight", ("k_proj", "kernel"), True),
        ("self_attn.k_proj.bias", ("k_proj", "bias"), False),
        ("self_attn.v_proj.weight", ("v_proj", "kernel"), True),
        ("self_attn.v_proj.bias", ("v_proj", "bias"), False),
        ("self_attn.out_proj.weight", ("out_proj", "kernel"), True),
        ("self_attn.out_proj.bias", ("out_proj", "bias"), False),
        ("mlp.fc1.weight", ("fc1", "kernel"), True),
        ("mlp.fc1.bias", ("fc1", "bias"), False),
        ("mlp.fc2.weight", ("fc2", "kernel"), True),
        ("mlp.fc2.bias", ("fc2", "bias"), False),
    ]

    def __init__(self, cfg: MiniMaxM3VLConfig):
        self.cfg = cfg

    def _lm(self):
        from automodel_tpu.models.moe_lm.het_families import HetMoEAdapter

        return HetMoEAdapter(self.cfg.text, style="minimax_m3")

    def _proj2_entries(self, node: str, bias: bool):
        e = [
            (f"{node}.linear_1.weight", ("linear_1", "kernel"), True),
            (f"{node}.linear_2.weight", ("linear_2", "kernel"), True),
        ]
        if bias:
            e += [
                (f"{node}.linear_1.bias", ("linear_1", "bias"), False),
                (f"{node}.linear_2.bias", ("linear_2", "bias"), False),
            ]
        return e

    def from_hf(self, read, shardings=None) -> dict:
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get, _set, memo1_reader

        read = memo1_reader(read)
        v = self.cfg.vision
        params: dict = {}

        def put(path, value):
            sh = _get(shardings, path) if shardings is not None else None
            _set(
                params, path,
                jax.device_put(value, sh) if sh is not None else jnp.asarray(value),
            )

        def one(name, tr):
            x = np.asarray(read(name))
            return np.ascontiguousarray(x.T) if tr else x

        # Conv3d (D, 3, tp, P, P) → channel-folded HWIO (P, P, tp*3, D)
        w = np.asarray(read("vision_tower.vision_model.embeddings.patch_embedding.weight"))
        D_, C3, TP, P_, _ = w.shape
        w = np.transpose(w, (3, 4, 2, 1, 0)).reshape(P_, P_, TP * C3, D_)
        put(("visual", "patch_embed", "kernel"), np.ascontiguousarray(w))
        for hf_s, nat in self._LN:
            put(
                ("visual", "pre_layrnorm", nat),
                one(f"vision_tower.vision_model.pre_layrnorm.{hf_s}", False),
            )
        for suf, path, tr in self._BLK:
            put(
                ("visual", "blocks") + path,
                np.stack([
                    one(f"vision_tower.vision_model.encoder.layers.{i}.{suf}", tr)
                    for i in range(v.num_layers)
                ]),
            )
        for node, key, bias in (
            ("multi_modal_projector", "projector", self.cfg.projector_bias),
            ("patch_merge_mlp", "patch_merger", self.cfg.patch_merge_bias),
        ):
            for suf, path, tr in self._proj2_entries(node, bias):
                put(("visual", key) + path, one(suf, tr))

        def lm_read(name):
            return read("language_model." + name)

        lm_sh = _get(shardings, ("language_model",)) if shardings is not None else None
        params["language_model"] = self._lm().from_hf(lm_read, shardings=lm_sh)
        return params

    def to_hf(self, params):
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get

        v = self.cfg.vision

        def _t(x):
            return np.ascontiguousarray(np.asarray(x).T)

        vis = params["visual"]
        k = np.asarray(vis["patch_embed"]["kernel"])  # (P,P,tp*3,D)
        P_, _, Ctp, D_ = k.shape
        k = k.reshape(P_, P_, Ctp // 3, 3, D_)
        yield (
            "vision_tower.vision_model.embeddings.patch_embedding.weight",
            np.ascontiguousarray(np.transpose(k, (4, 3, 2, 0, 1))),
        )
        for hf_s, nat in self._LN:
            yield (
                f"vision_tower.vision_model.pre_layrnorm.{hf_s}",
                np.asarray(vis["pre_layrnorm"][nat]),
            )
        for i in range(v.num_layers):
            for suf, path, tr in self._BLK:
                x = np.asarray(_get(vis["blocks"], path)[i])
                yield (
                    f"vision_tower.vision_model.encoder.layers.{i}.{suf}",
                    (_t(x) if tr else x),
                )
        for node, key, bias in (
            ("multi_modal_projector", "projector", self.cfg.projector_bias),
            ("patch_merge_mlp", "patch_merger", self.cfg.patch_merge_bias),
        ):
            for suf, path, tr in self._proj2_entries(node, bias):
                x = np.asarray(_get(vis[key], path))
                yield suf, (_t(x) if tr else x)

        for name, tensor in self._lm().to_hf(params["language_model"]):
            yield "language_model." + name, tensor


def _register_adapter():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["minimax_m3_vl"] = MiniMaxM3VLAdapter


_register_adapter()
