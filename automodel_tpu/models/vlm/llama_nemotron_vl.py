"""Llama-Nemotron VL: SigLIP tower → pixel-shuffle ↓ → LN/MLP projector →
BIDIRECTIONAL llama encoder, pooled for retrieval/reranking embeddings.

The analog of the reference's llama_nemotron_vl (reference: nemo_automodel/
components/models/llama_nemotron_vl/model.py, 717 LoC — registered under
the retrieval tag: _transformers/registry.py:126). This is an EMBEDDING
model, not a generator: a SigLIP vision encoder's patch features are
space-to-depth downsampled (`pixel_shuffle`, model.py:627, InternVL
convention, downsample_ratio=0.5 ⇒ 4× fewer tokens at 4× channels),
projected by `mlp1` (LayerNorm → Linear → GELU → Linear, model.py:458),
spliced into the token stream at `img_context_token_id` positions, and run
through a non-causal llama (`LlamaBidirectionalModel`, model.py:260); the
last hidden state is masked-pooled (avg/last/cls, model.py:190 `pool`).

TPU mapping: the tower is the shared models/vision/vit.py encoder (SigLIP
flavor: no CLS, no pre-LN, tanh-gelu), the text side the generic dense
decoder with `causal=False` (the llama_bidirectional config), and pooling
mirrors loss/infonce.mean_pool so the retrieval recipes can drive it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.models.llm import decoder as text_decoder
from automodel_tpu.models.llm.families import llama_bidirectional_config
from automodel_tpu.models.vision import vit
from automodel_tpu.models.vlm.llava import merge_image_embeddings
from automodel_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class LlamaNemotronVLConfig:
    vision: vit.VisionConfig = dataclasses.field(default_factory=vit.VisionConfig)
    text: Any = None  # TransformerConfig (causal=False)
    img_context_token_id: int = 128258
    downsample_ratio: float = 0.5
    pooling: str = "avg"  # avg | last | cls

    @property
    def dtype(self):
        return self.text.dtype

    @property
    def mtp_num_layers(self) -> int:
        return 0

    @property
    def num_image_token(self) -> int:
        """Merged tokens one image occupies (model.py:432)."""
        side = self.vision.image_size // self.vision.patch_size
        return int(side ** 2 * self.downsample_ratio ** 2)

    def flops_per_token(self, seq_len: int) -> float:
        vis = 6.0 * self.vision.param_count() * self.vision.num_positions
        return self.text.flops_per_token(seq_len) + vis / max(seq_len, 1)


def llama_nemotron_vl_config(hf: Mapping[str, Any], **overrides) -> LlamaNemotronVLConfig:
    llm_hf = dict(hf["llm_config"])
    text_overrides = {
        k: overrides[k]
        for k in ("dtype", "remat_policy", "attn_impl", "linear_precision")
        if k in overrides
    }
    text = llama_bidirectional_config(llm_hf, **text_overrides)
    v = dict(hf["vision_config"])
    vision = vit.VisionConfig.from_hf(
        v,
        dtype=text.dtype,
        remat_policy=text_overrides.get("remat_policy", "full"),
        feature_layer=int(hf.get("select_layer", -1)),
    )
    return LlamaNemotronVLConfig(
        vision=vision,
        text=text,
        img_context_token_id=int(hf.get("img_context_token_id", 128258)),
        downsample_ratio=float(hf.get("downsample_ratio", 0.5)),
        pooling=str(hf.get("pooling", llm_hf.get("pooling", "avg"))),
    )


def init(cfg: LlamaNemotronVLConfig, rng: jax.Array) -> dict:
    kv, kt, kp = jax.random.split(rng, 3)
    Hv = cfg.vision.hidden_size
    Ht = cfg.text.hidden_size
    r = int(1 / cfg.downsample_ratio)
    k1, k2 = jax.random.split(kp)
    return {
        "vision_tower": vit.init(cfg.vision, kv),
        "mlp1": {
            "norm": {"scale": jnp.ones((Hv * r * r,)), "bias": jnp.zeros((Hv * r * r,))},
            "fc1": {"kernel": dense_init(k1, (Hv * r * r, Ht)), "bias": jnp.zeros((Ht,))},
            "fc2": {"kernel": dense_init(k2, (Ht, Ht)), "bias": jnp.zeros((Ht,))},
        },
        "language_model": text_decoder.init(cfg.text, kt),
    }


def param_specs(cfg: LlamaNemotronVLConfig) -> dict:
    return {
        "vision_tower": vit.param_specs(cfg.vision),
        "mlp1": {
            "norm": {"scale": ("norm",), "bias": ("norm",)},
            "fc1": {"kernel": ("embed", "mlp"), "bias": ("norm",)},
            "fc2": {"kernel": ("mlp", "embed"), "bias": ("norm",)},
        },
        "language_model": text_decoder.param_specs(cfg.text),
    }


def pixel_shuffle(x: jnp.ndarray, scale: float) -> jnp.ndarray:
    """(N, h, w, C) → (N, h·s, w·s, C/s²) — the exact InternVL shuffle
    (reference: model.py:627; view/permute sequence reproduced so channel
    order matches the checkpoint's mlp1 weights)."""
    n, h, w, c = x.shape
    x = x.reshape(n, h, int(w * scale), int(c / scale))
    x = jnp.transpose(x, (0, 2, 1, 3))
    x = x.reshape(n, int(h * scale), int(w * scale), int(c / (scale * scale)))
    return jnp.transpose(x, (0, 2, 1, 3))


def encode_images(params: dict, cfg: LlamaNemotronVLConfig, pixel_values: jnp.ndarray):
    """(B, H, W, 3) → (B, num_image_token, text_hidden) — extract_feature
    (model.py:643): tower → pixel-shuffle ↓ → mlp1."""
    feats = vit.forward(params["vision_tower"], cfg.vision, pixel_values)
    B, N, C = feats.shape
    side = int(N ** 0.5)
    x = pixel_shuffle(feats.reshape(B, side, side, C), cfg.downsample_ratio)
    x = x.reshape(B, -1, x.shape[-1])
    mp = params["mlp1"]
    dt = cfg.dtype
    x = layer_norm(x, mp["norm"]["scale"], mp["norm"]["bias"])
    x = x.astype(dt) @ mp["fc1"]["kernel"].astype(dt) + mp["fc1"]["bias"].astype(dt)
    x = jax.nn.gelu(x, approximate=False)
    return x @ mp["fc2"]["kernel"].astype(dt) + mp["fc2"]["bias"].astype(dt)


def forward(
    params: dict,
    cfg: LlamaNemotronVLConfig,
    input_ids: jnp.ndarray,
    pixel_values: jnp.ndarray,
    *,
    positions=None,
    segment_ids=None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = True,
):
    """Non-causal encode of the merged image+text sequence. The natural
    output is the hidden state (return_hidden=True default) — this family
    is an embedding model; `embed` below applies the retrieval pooling."""
    image_embeds = encode_images(params, cfg, pixel_values)
    lm = params["language_model"]
    token_embeds = jnp.take(lm["embed"]["embedding"], input_ids, axis=0).astype(cfg.dtype)
    merged = merge_image_embeddings(
        token_embeds, image_embeds, input_ids == cfg.img_context_token_id
    )
    return text_decoder.forward(
        lm, cfg.text, input_ids,
        positions=positions, segment_ids=segment_ids,
        mesh_ctx=mesh_ctx, rules=rules,
        return_hidden=return_hidden, inputs_embeds=merged,
    )


def embed(
    params: dict,
    cfg: LlamaNemotronVLConfig,
    input_ids: jnp.ndarray,
    pixel_values: jnp.ndarray,
    attention_mask: jnp.ndarray,  # (B, S) 1 = real token
    pooling: str | None = None,
) -> jnp.ndarray:
    """(B, text_hidden) pooled embeddings (model.py:190 `pool`)."""
    hidden = forward(params, cfg, input_ids, pixel_values, return_hidden=True)
    mask = attention_mask.astype(hidden.dtype)
    pool = pooling or cfg.pooling
    if pool == "avg":
        return (hidden * mask[..., None]).sum(1) / jnp.maximum(
            mask.sum(1)[..., None], 1.0
        )
    if pool == "cls":
        return hidden[:, 0]
    if pool == "last":
        last = jnp.maximum(mask.sum(1).astype(jnp.int32) - 1, 0)
        return jnp.take_along_axis(hidden, last[:, None, None], axis=1)[:, 0]
    raise ValueError(f"pooling '{pool}' not supported (avg | cls | last)")


# ---------------------------------------------------------------------------
# HF state-dict adapter
# ---------------------------------------------------------------------------
class LlamaNemotronVLAdapter:
    """HF layout (reference: model.py module tree): tower under
    `vision_model.vision_model.*` (SiglipVisionModel nests a vision_model),
    projector `mlp1.{0,1,3}.*` (Sequential LN/Linear/GELU/Linear), text as a
    BARE LlamaModel under `language_model.*` (no `model.` level, no
    lm_head — it is an encoder)."""

    def __init__(self, cfg: LlamaNemotronVLConfig):
        self.cfg = cfg

    def _vit(self):
        from automodel_tpu.checkpoint.hf_adapter import LlavaAdapter

        return LlavaAdapter(self.cfg)

    def _lm(self):
        from automodel_tpu.checkpoint.hf_adapter import DenseDecoderAdapter

        return DenseDecoderAdapter(self.cfg.text)

    _MLP1 = [
        ("mlp1.0.weight", ("norm", "scale"), False),
        ("mlp1.0.bias", ("norm", "bias"), False),
        ("mlp1.1.weight", ("fc1", "kernel"), True),
        ("mlp1.1.bias", ("fc1", "bias"), False),
        ("mlp1.3.weight", ("fc2", "kernel"), True),
        ("mlp1.3.bias", ("fc2", "bias"), False),
    ]

    def from_hf(self, read, shardings=None) -> dict:
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get, _set

        def place(subtree, sub_shardings):
            if sub_shardings is None:
                return jax.tree.map(jnp.asarray, subtree)
            return jax.tree.map(jax.device_put, subtree, sub_shardings)

        params: dict = {
            "vision_tower": place(
                self._vit()._vit_from_hf(read, "vision_model"),
                _get(shardings, ("vision_tower",)) if shardings is not None else None,
            )
        }
        mlp1: dict = {}
        for name, path, tr in self._MLP1:
            x = np.asarray(read(name))
            _set(mlp1, path, np.ascontiguousarray(x.T) if tr else x)
        params["mlp1"] = place(
            mlp1, _get(shardings, ("mlp1",)) if shardings is not None else None
        )

        def lm_read(name):
            # DenseDecoderAdapter asks for model.*-prefixed names and
            # lm_head.weight; the checkpoint stores a bare LlamaModel.
            if name.startswith("model."):
                raise KeyError(name)  # → adapter's bare-model fallback
            if name == "lm_head.weight":
                raise KeyError(name)  # encoder: no head; leaf omitted
            return read("language_model." + name)

        lm_sh = _get(shardings, ("language_model",)) if shardings is not None else None
        params["language_model"] = self._lm().from_hf(lm_read, shardings=lm_sh)
        return params

    def to_hf(self, params):
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get

        yield from self._vit()._vit_to_hf(params["vision_tower"], "vision_model")
        for name, path, tr in self._MLP1:
            x = np.asarray(_get(params["mlp1"], path))
            yield name, (np.ascontiguousarray(x.T) if tr else x)
        for name, tensor in self._lm().to_hf(params["language_model"]):
            if name == "lm_head.weight":
                continue  # encoder checkpoints carry no head
            assert name.startswith("model."), name
            yield "language_model." + name[len("model."):], tensor


def _register_adapter():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["llama_nemotron_vl"] = LlamaNemotronVLAdapter


_register_adapter()
