from automodel_tpu.models.audio import encoder

__all__ = ["encoder"]
