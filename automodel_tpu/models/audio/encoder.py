"""Audio (speech) encoder for omni models: mel features → frame embeddings.

The analog of the reference's sound encoders inside its omni families
(reference: nemo_automodel/components/models/nemotron_omni/model.py —
Parakeet conformer via trust_remote_code; qwen2_5_omni's audio tower).
TPU-native form: strided-conv time subsampling (×4) + a pre-LN
bidirectional transformer over frames with sinusoidal positions
(whisper-style) — conv front-ends and self-attention both map straight
onto the MXU; the conformer's depthwise-conv blocks add little on TPU and
are omitted by design. Functional pytree + stacked-layer scan like the
vision tower (models/vision/vit.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init, maybe_remat
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class AudioConfig:
    num_mel_bins: int = 80
    hidden_size: int = 256
    intermediate_size: int = 1024
    num_layers: int = 4
    num_heads: int = 4
    conv_kernel: int = 3
    # two stride-2 convs → frames/4; each output frame covers 4 mel frames
    subsample_stride: int = 2
    max_frames: int = 1500  # post-subsample positions (whisper: 1500)
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat_policy: str = "full"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def subsample_factor(self) -> int:
        return self.subsample_stride ** 2

    def out_frames(self, mel_frames: int) -> int:
        s = self.subsample_stride
        return ((mel_frames + s - 1) // s + s - 1) // s

    def param_count(self) -> int:
        H, I, L, K = self.hidden_size, self.intermediate_size, self.num_layers, self.conv_kernel
        return (
            K * self.num_mel_bins * H + K * H * H
            + L * (4 * H * H + 2 * H * I)
        )

    @classmethod
    def from_hf(cls, hf: dict, **overrides) -> "AudioConfig":
        kw = dict(
            num_mel_bins=int(hf.get("num_mel_bins", 80)),
            hidden_size=int(hf.get("hidden_size", hf.get("d_model", 256))),
            intermediate_size=int(
                hf.get("intermediate_size", hf.get("encoder_ffn_dim", 1024))
            ),
            num_layers=int(hf.get("num_hidden_layers", hf.get("encoder_layers", 4))),
            num_heads=int(
                hf.get("num_attention_heads", hf.get("encoder_attention_heads", 4))
            ),
        )
        kw.update(overrides)
        return cls(**kw)


def init(cfg: AudioConfig, rng: jax.Array) -> dict:
    H, I, L, K = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers, cfg.conv_kernel
    ks = jax.random.split(rng, 10)

    def stack(key, shape):
        keys = jax.random.split(key, L)
        return jnp.stack([dense_init(k, shape) for k in keys])

    return {
        # conv kernels in (K, in, out) — lax.conv 'NWC'/'WIO' layout
        "conv1": {
            "kernel": dense_init(ks[0], (K * cfg.num_mel_bins, H)).reshape(K, cfg.num_mel_bins, H),
            "bias": jnp.zeros((H,)),
        },
        "conv2": {
            "kernel": dense_init(ks[1], (K * H, H)).reshape(K, H, H),
            "bias": jnp.zeros((H,)),
        },
        "layers": {
            "ln1": {"scale": jnp.ones((L, H)), "bias": jnp.zeros((L, H))},
            "q_proj": {"kernel": stack(ks[2], (H, H)), "bias": jnp.zeros((L, H))},
            "k_proj": {"kernel": stack(ks[3], (H, H)), "bias": jnp.zeros((L, H))},
            "v_proj": {"kernel": stack(ks[4], (H, H)), "bias": jnp.zeros((L, H))},
            "o_proj": {"kernel": stack(ks[5], (H, H)), "bias": jnp.zeros((L, H))},
            "ln2": {"scale": jnp.ones((L, H)), "bias": jnp.zeros((L, H))},
            "fc1": {"kernel": stack(ks[6], (H, I)), "bias": jnp.zeros((L, I))},
            "fc2": {"kernel": stack(ks[7], (I, H)), "bias": jnp.zeros((L, H))},
        },
        "final_ln": {"scale": jnp.ones((H,)), "bias": jnp.zeros((H,))},
    }


def param_specs(cfg: AudioConfig) -> dict:
    return {
        "conv1": {"kernel": (None, None, "embed"), "bias": ("norm",)},
        "conv2": {"kernel": (None, "embed", "embed"), "bias": ("norm",)},
        "layers": {
            "ln1": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "q_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "k_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "v_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "o_proj": {"kernel": ("layers", "heads", "embed"), "bias": ("layers", "norm")},
            "ln2": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "fc1": {"kernel": ("layers", "embed", "mlp"), "bias": ("layers", "mlp")},
            "fc2": {"kernel": ("layers", "mlp", "embed"), "bias": ("layers", "norm")},
        },
        "final_ln": {"scale": ("norm",), "bias": ("norm",)},
    }


def sinusoidal_positions(n: int, dim: int) -> jnp.ndarray:
    """Whisper-style fixed sinusoidal embeddings (n, dim), float32."""
    half = dim // 2
    freq = jnp.exp(-jnp.log(10000.0) * jnp.arange(half) / max(half - 1, 1))
    angles = jnp.arange(n)[:, None] * freq[None, :]
    return jnp.concatenate([jnp.sin(angles), jnp.cos(angles)], axis=-1)


def _conv1d(x, kernel, bias, stride):
    """(B, T, Cin) ⊛ (K, Cin, Cout) strided, SAME padding."""
    y = jax.lax.conv_general_dilated(
        x, kernel.astype(x.dtype), window_strides=(stride,), padding="SAME",
        dimension_numbers=("NWC", "WIO", "NWC"),
    )
    return y + bias.astype(x.dtype)


def forward(
    params: dict,
    cfg: AudioConfig,
    mel: jnp.ndarray,  # (B, T, num_mel_bins) float
    frame_mask: jnp.ndarray | None = None,  # (B, T) bool — True = real audio
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """mel → (frame embeddings (B, T', H), valid mask (B, T'))."""
    from automodel_tpu.models.common.layers import cast_params

    params = cast_params(params, cfg.dtype)
    s = cfg.subsample_stride

    def subsample_mask(m, stride):
        pad = (-m.shape[1]) % stride
        m = jnp.pad(m, ((0, 0), (0, pad)))
        # a subsampled frame is valid if ANY source frame under it is
        return m.reshape(m.shape[0], -1, stride).any(-1)

    x = mel.astype(cfg.dtype)
    mask = frame_mask
    if mask is not None:
        # zero padded frames before each conv so the SAME-padded strided
        # kernels read deterministic zeros at the valid/padded boundary
        x = x * mask[..., None].astype(cfg.dtype)
    x = jax.nn.gelu(_conv1d(x, params["conv1"]["kernel"], params["conv1"]["bias"], s))
    if mask is not None:
        mask = subsample_mask(mask, s)[:, : x.shape[1]]
        x = x * mask[..., None].astype(cfg.dtype)
    x = jax.nn.gelu(_conv1d(x, params["conv2"]["kernel"], params["conv2"]["bias"], s))
    B, T, H = x.shape
    if mask is None:
        out_mask = jnp.ones((B, T), bool)
    else:
        out_mask = subsample_mask(mask, s)[:, :T]
        x = x * out_mask[..., None].astype(cfg.dtype)
    x = x + sinusoidal_positions(T, H).astype(cfg.dtype)

    nh, hd, eps = cfg.num_heads, cfg.head_dim, cfg.layer_norm_eps

    def layer(x, lp):
        y = layer_norm(x, lp["ln1"]["scale"], lp["ln1"]["bias"], eps)
        q = (y @ lp["q_proj"]["kernel"] + lp["q_proj"]["bias"]).reshape(B, T, nh, hd)
        k = (y @ lp["k_proj"]["kernel"] + lp["k_proj"]["bias"]).reshape(B, T, nh, hd)
        v = (y @ lp["v_proj"]["kernel"] + lp["v_proj"]["bias"]).reshape(B, T, nh, hd)
        # padded frames sit in segment 0, real audio in segment 1 — the
        # segment mask keeps real frames from attending to padding
        seg = out_mask.astype(jnp.int32)
        a = dot_product_attention(
            q, k, v, causal=False, impl="xla", segment_ids=seg
        )
        x = x + a.reshape(B, T, H) @ lp["o_proj"]["kernel"] + lp["o_proj"]["bias"]
        y = layer_norm(x, lp["ln2"]["scale"], lp["ln2"]["bias"], eps)
        y = jax.nn.gelu(y @ lp["fc1"]["kernel"] + lp["fc1"]["bias"], approximate=True)
        return x + y @ lp["fc2"]["kernel"] + lp["fc2"]["bias"]

    fn = maybe_remat(lambda c, lp: (layer(c, lp), None), cfg.remat_policy)
    x, _ = jax.lax.scan(fn, x, params["layers"])
    x = layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"], eps)
    return x, out_mask
