"""Shared model-building blocks: params-as-pytrees, stacked-layer scan, remat.

Design (TPU-first, not a port): a model is

- an `init(rng, cfg) -> params` building a nested dict of jnp arrays whose
  per-layer weights are STACKED along a leading `layers` dim,
- a pure `forward(params, cfg, batch) -> output`, scanning over the stacked
  layer weights with `jax.lax.scan` + `jax.checkpoint` (one compiled layer
  body regardless of depth — fast XLA compiles and natural rematerialization),
- a `param_specs(cfg)` pytree of LOGICAL axis names consumed by
  parallel/sharding.py.

This replaces the reference's nn.Module trees + per-module FSDP wrapping +
activation-checkpoint wrapping (reference: components/distributed/
parallelizer.py:1058, activation_checkpointing.py) with compiler-native
equivalents.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

# -- remat policies (the analog of full/selective activation checkpointing,
#    reference: distributed/activation_checkpointing.py) ---------------------
REMAT_POLICIES: dict[str, Any] = {
    "none": None,  # save everything (no remat)
    "full": jax.checkpoint_policies.nothing_saveable,
    "dots": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "checkpoint_dots": jax.checkpoint_policies.checkpoint_dots,
}


def maybe_remat(fn: Callable, policy_name: str | None) -> Callable:
    if policy_name is None or policy_name == "none":
        return fn
    policy = REMAT_POLICIES[policy_name]
    return jax.checkpoint(fn, policy=policy, prevent_cse=False)


def scan_layers(
    layer_fn: Callable,
    carry,
    stacked_params,
    *,
    remat_policy: str | None = "full",
    unroll: int = 1,
):
    """Scan `layer_fn(carry, layer_params) -> carry` over stacked weights."""
    fn = maybe_remat(lambda c, p: (layer_fn(c, p), None), remat_policy)
    carry, _ = jax.lax.scan(fn, carry, stacked_params, unroll=unroll)
    return carry


def window_plan(windows: tuple) -> "tuple[str, Any]":
    """Plan static-window execution of a per-layer window tuple.

    Per-layer sliding windows must stay STATIC Python ints so the Pallas
    flash kernel can specialize (a traced window forces the XLA fallback and
    its S×S logits). Returns one of:
      ("uniform", w)                 — all layers share one window
      ("periodic", p, pattern)       — pattern of period p repeats (gemma2)
      ("segments", [(start, end, w)])— few contiguous runs (qwen2 SWA split)
    """
    L = len(windows)
    if all(w == windows[0] for w in windows):
        return ("uniform", windows[0])
    for p in (2, 3, 4):
        if L % p == 0 and windows == windows[:p] * (L // p):
            return ("periodic", p, windows[:p])
    segs = []
    start = 0
    for i in range(1, L + 1):
        if i == L or windows[i] != windows[start]:
            segs.append((start, i, windows[start]))
            start = i
    return ("segments", segs)


def scan_layers_windowed(
    layer_fn: Callable,  # (carry, layer_params, window) -> carry
    carry,
    stacked_params,
    windows: tuple,      # per-layer static window (int | None), len == L
    *,
    remat_policy: str | None = "full",
    unroll: int = 1,
):
    """Scan over stacked layers whose sliding windows differ per layer,
    keeping every window a static Python value (see window_plan)."""
    plan = window_plan(windows)
    if plan[0] == "uniform":
        w = plan[1]
        fn = maybe_remat(lambda c, p: (layer_fn(c, p, w), None), remat_policy)
        carry, _ = jax.lax.scan(fn, carry, stacked_params, unroll=unroll)
        return carry
    if plan[0] == "periodic":
        p, pattern = plan[1], plan[2]

        def superlayer(c, lp):
            for j, w in enumerate(pattern):
                c = layer_fn(c, jax.tree.map(lambda x: x[j], lp), w)
            return c, None

        grouped = jax.tree.map(
            lambda x: x.reshape((x.shape[0] // p, p) + x.shape[1:]), stacked_params
        )
        fn = maybe_remat(superlayer, remat_policy)
        carry, _ = jax.lax.scan(fn, carry, grouped, unroll=unroll)
        return carry
    # contiguous segments: one scan per run
    for start, end, w in plan[1]:
        seg = jax.tree.map(lambda x: x[start:end], stacked_params)
        fn = maybe_remat(lambda c, p, w=w: (layer_fn(c, p, w), None), remat_policy)
        carry, _ = jax.lax.scan(fn, carry, seg, unroll=unroll)
    return carry


# -- initializers ------------------------------------------------------------
def dense_init(rng, shape, dtype=jnp.float32, scale: float | None = None):
    """Truncated-normal fan-in init (matches the reference models' defaults)."""
    fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
    std = scale if scale is not None else fan_in ** -0.5
    return (std * jax.random.truncated_normal(rng, -3.0, 3.0, shape)).astype(dtype)


def embed_init(rng, shape, dtype=jnp.float32, std: float = 0.02):
    return (std * jax.random.truncated_normal(rng, -3.0, 3.0, shape)).astype(dtype)


def split_rngs(rng, names):
    keys = jax.random.split(rng, len(names))
    return dict(zip(names, keys))


def cast_params(params, dtype):
    """Compute-dtype cast (mixed precision: fp32 master, bf16 compute)."""
    return jax.tree.map(
        lambda p: p.astype(dtype) if jnp.issubdtype(p.dtype, jnp.floating) else p,
        params,
    )


def count_params(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
