"""Convolutional image VAE (AutoencoderKL-style) for latent diffusion.

The analog of the diffusers `AutoencoderKL` the reference loads through
`NeMoAutoDiffusionPipeline` (reference: nemo_automodel/_diffusers/
auto_diffusion_pipeline.py — vae component of the loaded pipeline).
TPU-native form: plain lax convs in NHWC, group-norm + silu res blocks,
stride-2 downsampling / nearest-neighbor upsampling, a diagonal-Gaussian
latent with the diffusers `scaling_factor` convention. Functional pytree
like every other model here.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 32
    channel_mults: tuple = (1, 2)   # one stride-2 downsample between levels
    num_res_blocks: int = 1
    groups: int = 8
    scaling_factor: float = 0.18215  # diffusers AutoencoderKL convention
    dtype: Any = jnp.float32
    remat_policy: str = "none"

    @property
    def downsample_factor(self) -> int:
        return 2 ** (len(self.channel_mults) - 1)

    @classmethod
    def from_hf(cls, hf: dict, **overrides) -> "VAEConfig":
        kw = dict(
            in_channels=int(hf.get("in_channels", 3)),
            latent_channels=int(hf.get("latent_channels", 4)),
            scaling_factor=float(hf.get("scaling_factor", 0.18215)),
        )
        if hf.get("block_out_channels"):
            boc = [int(c) for c in hf["block_out_channels"]]
            kw["base_channels"] = boc[0]
            kw["channel_mults"] = tuple(c // boc[0] for c in boc)
        if hf.get("layers_per_block"):
            kw["num_res_blocks"] = int(hf["layers_per_block"])
        kw.update(overrides)
        return cls(**kw)

    def to_hf(self) -> dict:
        return {
            "_class_name": "VAEConfig",
            "in_channels": self.in_channels,
            "latent_channels": self.latent_channels,
            "scaling_factor": self.scaling_factor,
            "block_out_channels": [self.base_channels * m for m in self.channel_mults],
            "layers_per_block": self.num_res_blocks,
        }


def _conv_init(rng, k, cin, cout):
    return dense_init(rng, (k * k * cin, cout)).reshape(k, k, cin, cout)


def _init_res_block(rng, cin, cout):
    k1, k2, k3 = jax.random.split(rng, 3)
    p = {
        "norm1": {"scale": jnp.ones((cin,)), "bias": jnp.zeros((cin,))},
        "conv1": {"kernel": _conv_init(k1, 3, cin, cout), "bias": jnp.zeros((cout,))},
        "norm2": {"scale": jnp.ones((cout,)), "bias": jnp.zeros((cout,))},
        "conv2": {"kernel": _conv_init(k2, 3, cout, cout), "bias": jnp.zeros((cout,))},
    }
    if cin != cout:
        p["skip"] = {"kernel": _conv_init(k3, 1, cin, cout), "bias": jnp.zeros((cout,))}
    return p


def init(cfg: VAEConfig, rng: jax.Array) -> dict:
    chans = [cfg.base_channels * m for m in cfg.channel_mults]
    ks = iter(jax.random.split(rng, 64))
    enc: dict = {
        "conv_in": {
            "kernel": _conv_init(next(ks), 3, cfg.in_channels, chans[0]),
            "bias": jnp.zeros((chans[0],)),
        }
    }
    c = chans[0]
    for li, ch in enumerate(chans):
        for bi in range(cfg.num_res_blocks):
            enc[f"res_{li}_{bi}"] = _init_res_block(next(ks), c, ch)
            c = ch
        if li + 1 < len(chans):
            enc[f"down_{li}"] = {
                "kernel": _conv_init(next(ks), 3, c, c), "bias": jnp.zeros((c,))
            }
    enc["norm_out"] = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
    enc["conv_out"] = {
        "kernel": _conv_init(next(ks), 3, c, 2 * cfg.latent_channels),
        "bias": jnp.zeros((2 * cfg.latent_channels,)),
    }

    dec: dict = {
        "conv_in": {
            "kernel": _conv_init(next(ks), 3, cfg.latent_channels, c),
            "bias": jnp.zeros((c,)),
        }
    }
    for li, ch in enumerate(reversed(chans)):
        for bi in range(cfg.num_res_blocks):
            dec[f"res_{li}_{bi}"] = _init_res_block(next(ks), c, ch)
            c = ch
        if li + 1 < len(chans):
            dec[f"up_{li}"] = {
                "kernel": _conv_init(next(ks), 3, c, c), "bias": jnp.zeros((c,))
            }
    dec["norm_out"] = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
    dec["conv_out"] = {
        "kernel": _conv_init(next(ks), 3, c, cfg.in_channels),
        "bias": jnp.zeros((cfg.in_channels,)),
    }
    return {"encoder": enc, "decoder": dec}


def param_specs(cfg: VAEConfig) -> dict:
    """Conv towers are tiny relative to the denoiser: replicate."""
    params = jax.eval_shape(lambda: init(cfg, jax.random.key(0)))
    return jax.tree.map(lambda _: (None,), params)


def _group_norm(x, scale, bias, groups, eps=1e-6):
    B, H, W, C = x.shape
    g = x.reshape(B, H, W, groups, C // groups).astype(jnp.float32)
    mean = g.mean((1, 2, 4), keepdims=True)
    var = g.var((1, 2, 4), keepdims=True)
    g = (g - mean) * jax.lax.rsqrt(var + eps)
    return (g.reshape(B, H, W, C) * scale + bias).astype(x.dtype)


def _conv(x, p, stride=1):
    y = jax.lax.conv_general_dilated(
        x, p["kernel"].astype(x.dtype), window_strides=(stride, stride),
        padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + p["bias"].astype(x.dtype)


def _res_block(x, p, groups):
    h = _group_norm(x, p["norm1"]["scale"], p["norm1"]["bias"], groups)
    h = _conv(jax.nn.silu(h), p["conv1"])
    h = _group_norm(h, p["norm2"]["scale"], p["norm2"]["bias"], groups)
    h = _conv(jax.nn.silu(h), p["conv2"])
    skip = _conv(x, p["skip"]) if "skip" in p else x
    return skip + h


def encode(params: dict, cfg: VAEConfig, images: jnp.ndarray, rng=None):
    """images (B, H, W, C) → latents (B, H/f, W/f, latent_channels),
    scaled by scaling_factor. `rng` samples the posterior; None → mean."""
    enc = params["encoder"]
    chans = [cfg.base_channels * m for m in cfg.channel_mults]
    x = _conv(images.astype(cfg.dtype), enc["conv_in"])
    for li in range(len(chans)):
        for bi in range(cfg.num_res_blocks):
            x = _res_block(x, enc[f"res_{li}_{bi}"], cfg.groups)
        if li + 1 < len(chans):
            x = _conv(x, enc[f"down_{li}"], stride=2)
    x = _group_norm(x, enc["norm_out"]["scale"], enc["norm_out"]["bias"], cfg.groups)
    x = _conv(jax.nn.silu(x), enc["conv_out"])
    mean, logvar = jnp.split(x, 2, axis=-1)
    if rng is not None:
        logvar = jnp.clip(logvar, -30.0, 20.0)
        mean = mean + jnp.exp(0.5 * logvar) * jax.random.normal(
            rng, mean.shape, mean.dtype
        )
    return mean * cfg.scaling_factor


def decode(params: dict, cfg: VAEConfig, latents: jnp.ndarray) -> jnp.ndarray:
    """latents (scaled) → images (B, H, W, C)."""
    dec = params["decoder"]
    chans = [cfg.base_channels * m for m in cfg.channel_mults]
    x = _conv((latents / cfg.scaling_factor).astype(cfg.dtype), dec["conv_in"])
    for li in range(len(chans)):
        for bi in range(cfg.num_res_blocks):
            x = _res_block(x, dec[f"res_{li}_{bi}"], cfg.groups)
        if li + 1 < len(chans):
            B, H, W, C = x.shape
            x = jax.image.resize(x, (B, 2 * H, 2 * W, C), "nearest")
            x = _conv(x, dec[f"up_{li}"])
    x = _group_norm(x, dec["norm_out"]["scale"], dec["norm_out"]["bias"], cfg.groups)
    return _conv(jax.nn.silu(x), dec["conv_out"])
