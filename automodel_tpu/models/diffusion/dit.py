"""DiT — diffusion transformer with adaLN-zero conditioning.

The denoiser backbone for the diffusion recipe (the role the reference
fills with diffusers transformers behind its flow-matching adapters,
reference: components/flow_matching/adapters/, _diffusers/
auto_diffusion_pipeline.py). TPU-native, same params-pytree + stacked-
layer-scan shape as every model here:

- patchify latents → tokens; learned pos embedding
- conditioning vector c = MLP(sinusoidal(σ·1000)) [+ class embedding]
- per block, adaLN-zero: (shift, scale, gate)×2 from c, gates zero-init so
  every block starts as identity and the model output starts at zero
- final adaLN + linear → unpatchify to the velocity field
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init, maybe_remat
from automodel_tpu.ops.attention import dot_product_attention


@dataclasses.dataclass
class DiTConfig:
    input_size: int = 32          # latent H=W
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 384
    num_layers: int = 6
    num_heads: int = 6
    mlp_ratio: float = 4.0
    num_classes: int = 0          # 0 = unconditional
    # Wan-style text conditioning (reference: flow_matching/adapters/
    # simple.py — hidden_states/timestep/encoder_hidden_states interface):
    # per-block cross-attention over (B, L, cross_attention_dim) text
    # embeddings; 0 = off. The cross-attn out kernel is zero-init so
    # conditioning starts neutral.
    cross_attention_dim: int = 0
    dtype: jnp.dtype = jnp.float32
    remat_policy: Optional[str] = "full"
    scan_unroll: int = 1

    @property
    def num_patches(self) -> int:
        return (self.input_size // self.patch_size) ** 2

    @property
    def mlp_dim(self) -> int:
        return int(self.hidden_size * self.mlp_ratio)

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.in_channels

    def flops_per_token(self, seq_len: int) -> float:
        """seq_len = tokens per sample, i.e. (input_size/patch_size)²."""
        H = self.hidden_size
        per_layer = 4 * H * H + 2 * H * self.mlp_dim + 6 * H * H  # attn+mlp+mod
        # bidirectional attention scores + AV: 2 matmuls × 2S·H flops/token
        attn = 4 * self.num_layers * seq_len * H
        return 6.0 * self.num_layers * per_layer + 3.0 * attn


def init(cfg: DiTConfig, rng: jax.Array) -> dict:
    H, L, M = cfg.hidden_size, cfg.num_layers, cfg.mlp_dim
    ks = jax.random.split(rng, 10)

    def stack(k, shape):
        return jnp.stack([dense_init(kk, shape) for kk in jax.random.split(k, L)])

    params = {
        "patch_embed": {
            "kernel": dense_init(ks[0], (cfg.patch_dim, H)),
            "bias": jnp.zeros((H,)),
        },
        "pos_embed": 0.02 * jax.random.normal(ks[1], (cfg.num_patches, H)),
        "time_mlp": {
            "w1": {"kernel": dense_init(ks[2], (256, H)), "bias": jnp.zeros((H,))},
            "w2": {"kernel": dense_init(ks[3], (H, H)), "bias": jnp.zeros((H,))},
        },
        "layers": {
            "qkv": {"kernel": stack(ks[4], (H, 3 * H))},
            "attn_out": {"kernel": stack(ks[5], (H, H))},
            "mlp_in": {"kernel": stack(ks[6], (H, M))},
            "mlp_out": {"kernel": stack(ks[7], (M, H))},
            # adaLN-zero modulation: 6H (shift/scale/gate ×2), zero-init
            "mod": {
                "kernel": jnp.zeros((L, H, 6 * H)),
                "bias": jnp.zeros((L, 6 * H)),
            },
        },
        "final": {
            "mod": {"kernel": jnp.zeros((H, 2 * H)), "bias": jnp.zeros((2 * H,))},
            "out": {"kernel": jnp.zeros((H, cfg.patch_dim)), "bias": jnp.zeros((cfg.patch_dim,))},
        },
    }
    if cfg.num_classes > 0:
        params["class_embed"] = {
            "embedding": 0.02 * jax.random.normal(ks[8], (cfg.num_classes + 1, H))
        }  # +1 = the CFG null class
    if cfg.cross_attention_dim > 0:
        kq, kkv = jax.random.split(ks[9])
        params["layers"]["xq"] = {"kernel": stack(kq, (H, H))}
        params["layers"]["xkv"] = {"kernel": stack(kkv, (cfg.cross_attention_dim, 2 * H))}
        params["layers"]["xout"] = {"kernel": jnp.zeros((L, H, H))}
    return params


def param_specs(cfg: DiTConfig) -> dict:
    specs = {
        "patch_embed": {"kernel": ("embed", None), "bias": (None,)},
        "pos_embed": (None, "embed"),
        "time_mlp": {
            "w1": {"kernel": (None, "embed"), "bias": (None,)},
            "w2": {"kernel": ("embed", None), "bias": (None,)},
        },
        "layers": {
            "qkv": {"kernel": ("layers", "embed", "heads")},
            "attn_out": {"kernel": ("layers", "heads", "embed")},
            "mlp_in": {"kernel": ("layers", "embed", "mlp")},
            "mlp_out": {"kernel": ("layers", "mlp", "embed")},
            "mod": {"kernel": ("layers", "embed", None), "bias": ("layers", None)},
        },
        "final": {
            "mod": {"kernel": ("embed", None), "bias": (None,)},
            "out": {"kernel": ("embed", None), "bias": (None,)},
        },
    }
    if cfg.num_classes > 0:
        specs["class_embed"] = {"embedding": (None, "embed")}
    if cfg.cross_attention_dim > 0:
        specs["layers"]["xq"] = {"kernel": ("layers", "embed", "heads")}
        specs["layers"]["xkv"] = {"kernel": ("layers", None, "heads")}
        specs["layers"]["xout"] = {"kernel": ("layers", "heads", "embed")}
    return specs


def _timestep_embedding(sigma: jnp.ndarray, dim: int = 256) -> jnp.ndarray:
    """Sinusoidal embedding of σ·1000 (DiT convention)."""
    t = sigma.astype(jnp.float32) * 1000.0
    half = dim // 2
    freqs = jnp.exp(-jnp.log(10000.0) * jnp.arange(half) / half)
    ang = t[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


def _ln(x, eps=1e-6):
    """Parameter-free LayerNorm (adaLN supplies the affine)."""
    m = jnp.mean(x, -1, keepdims=True)
    v = jnp.var(x, -1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps)


def _patchify(x: jnp.ndarray, p: int) -> jnp.ndarray:
    B, Hh, Ww, C = x.shape
    x = x.reshape(B, Hh // p, p, Ww // p, p, C)
    return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(B, (Hh // p) * (Ww // p), p * p * C)


def _unpatchify(x: jnp.ndarray, p: int, hw: int, c: int) -> jnp.ndarray:
    B, N, _ = x.shape
    g = hw // p
    x = x.reshape(B, g, g, p, p, c)
    return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(B, hw, hw, c)


def forward(
    params: dict,
    cfg: DiTConfig,
    latents: jnp.ndarray,         # (B, H, W, C) noisy input x_σ
    sigma: jnp.ndarray,           # (B,)
    class_labels: jnp.ndarray | None = None,  # (B,) int; num_classes = null
    encoder_hidden_states: jnp.ndarray | None = None,  # (B, L, Dtext)
    mesh_ctx=None,
) -> jnp.ndarray:
    """Predict the velocity field, same shape as `latents`."""
    from automodel_tpu.models.common.layers import cast_params

    params = cast_params(params, cfg.dtype)
    B = latents.shape[0]
    Hn = cfg.num_heads
    D = cfg.hidden_size // Hn

    x = _patchify(latents.astype(cfg.dtype), cfg.patch_size)
    x = x @ params["patch_embed"]["kernel"] + params["patch_embed"]["bias"]
    x = x + params["pos_embed"][None]

    t = _timestep_embedding(sigma)
    tm = params["time_mlp"]
    c = jax.nn.silu(t.astype(cfg.dtype) @ tm["w1"]["kernel"] + tm["w1"]["bias"])
    c = c @ tm["w2"]["kernel"] + tm["w2"]["bias"]
    if cfg.num_classes > 0:
        labels = (
            class_labels
            if class_labels is not None
            else jnp.full((B,), cfg.num_classes, jnp.int32)
        )
        c = c + jnp.take(params["class_embed"]["embedding"], labels, axis=0)
    c = jax.nn.silu(c)

    if cfg.cross_attention_dim > 0:
        if encoder_hidden_states is None:
            raise ValueError(
                "cross_attention_dim > 0 requires encoder_hidden_states "
                "(the SimpleAdapter text-conditioning contract)"
            )
        text = encoder_hidden_states.astype(cfg.dtype)
    else:
        text = None

    def block(h, lp):
        mod = c @ lp["mod"]["kernel"] + lp["mod"]["bias"]          # (B, 6H)
        s1, sc1, g1, s2, sc2, g2 = jnp.split(mod[:, None, :], 6, axis=-1)
        a_in = _ln(h) * (1 + sc1) + s1
        qkv = (a_in @ lp["qkv"]["kernel"]).reshape(B, -1, 3, Hn, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = dot_product_attention(q, k, v, causal=False, impl="xla")
        h = h + g1 * (attn.reshape(B, -1, Hn * D) @ lp["attn_out"]["kernel"])
        if text is not None:
            xq = (_ln(h) @ lp["xq"]["kernel"]).reshape(B, -1, Hn, D)
            xkv = (text @ lp["xkv"]["kernel"]).reshape(B, -1, 2, Hn, D)
            xa = dot_product_attention(
                xq, xkv[:, :, 0], xkv[:, :, 1], causal=False, impl="xla"
            )
            h = h + xa.reshape(B, -1, Hn * D) @ lp["xout"]["kernel"]
        m_in = _ln(h) * (1 + sc2) + s2
        mlp = jax.nn.gelu(m_in @ lp["mlp_in"]["kernel"], approximate=True)
        h = h + g2 * (mlp @ lp["mlp_out"]["kernel"])
        return h, None

    x, _ = jax.lax.scan(
        maybe_remat(block, cfg.remat_policy), x, params["layers"],
        unroll=cfg.scan_unroll,
    )

    fm = params["final"]
    mod = c @ fm["mod"]["kernel"] + fm["mod"]["bias"]
    s, sc = jnp.split(mod[:, None, :], 2, axis=-1)
    x = _ln(x) * (1 + sc) + s
    x = x @ fm["out"]["kernel"] + fm["out"]["bias"]
    return _unpatchify(
        x.astype(jnp.float32), cfg.patch_size, cfg.input_size, cfg.in_channels
    )
