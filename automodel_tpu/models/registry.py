"""Architecture registry: HF `architectures[0]` → TPU-native implementation.

The analog of the reference's `MODEL_ARCH_MAPPING` + `_ModelRegistry.get`
(reference: nemo_automodel/_transformers/registry.py:30-490). Each entry
yields a `ModelSpec` bundling config-adapter, init/forward/param_specs, and
the HF state-dict adapter used for zero-conversion checkpoint I/O.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

from automodel_tpu.models.hybrid import mamba2 as mamba2_module
from automodel_tpu.models.hybrid import nemotron_h as nemotron_h_module
from automodel_tpu.models.hybrid import qwen3_5 as qwen3_5_module
from automodel_tpu.models.hybrid import qwen3_next as qwen3_next_module
from automodel_tpu.models.llm import decoder, families
from automodel_tpu.models.moe_lm import decoder as moe_decoder
from automodel_tpu.models.moe_lm import families as moe_families
from automodel_tpu.models.moe_lm import gemma4 as gemma4_module
from automodel_tpu.models.moe_lm import het_families
from automodel_tpu.models.moe_lm import het_moe as het_moe_module
from automodel_tpu.models.omni import bagel as bagel_module
from automodel_tpu.models.omni import model as omni_module
from automodel_tpu.models.vlm import kimi_vl as kimi_vl_module
from automodel_tpu.models.vlm import llama_nemotron_vl as llama_nemotron_vl_module
from automodel_tpu.models.vlm import minimax_m3_vl as minimax_m3_vl_module
from automodel_tpu.models.vlm import llava as llava_module
from automodel_tpu.models.vlm import qwen3_vl as qwen3_vl_module


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Everything the framework needs to drive one architecture."""

    name: str
    config_from_hf: Callable[..., Any]
    module: Any  # provides init / forward / param_specs / (unembed)
    adapter_name: str = "dense_decoder"  # state-dict adapter key
    adapter_kwargs: dict = dataclasses.field(default_factory=dict)


MODEL_ARCH_MAPPING: dict[str, ModelSpec] = {
    "LlamaForCausalLM": ModelSpec("llama", families.llama_config, decoder),
    "MistralForCausalLM": ModelSpec("mistral", families.mistral_config, decoder),
    "Ministral3ForCausalLM": ModelSpec(
        "ministral3", families.ministral3_config, decoder
    ),
    # Ministral bidirectional retrieval encoder (reference: models/
    # ministral_bidirectional, 188 LoC)
    "Ministral3BidirectionalModel": ModelSpec(
        "ministral_bidirectional", families.ministral_bidirectional_config, decoder
    ),
    "Qwen2ForCausalLM": ModelSpec("qwen2", families.qwen2_config, decoder),
    "Qwen3ForCausalLM": ModelSpec("qwen3", families.qwen3_config, decoder),
    "Gemma2ForCausalLM": ModelSpec("gemma2", families.gemma2_config, decoder),
    "Gemma3ForCausalLM": ModelSpec("gemma3", families.gemma3_config, decoder),
    "Glm4ForCausalLM": ModelSpec(
        "glm4", families.glm4_config, decoder, adapter_kwargs={"style": "glm4"}
    ),
    "Ernie4_5ForCausalLM": ModelSpec("ernie4_5", families.ernie4_5_config, decoder),
    "HunYuanDenseV1ForCausalLM": ModelSpec(
        "hunyuan_dense", families.hunyuan_dense_config, decoder,
        adapter_kwargs={"style": "hunyuan"},
    ),
    "Qwen3MoeForCausalLM": ModelSpec(
        "qwen3_moe", moe_families.qwen3_moe_config, moe_decoder, adapter_name="moe_decoder"
    ),
    "MixtralForCausalLM": ModelSpec(
        "mixtral", moe_families.mixtral_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "mixtral"},
    ),
    "DeepseekV3ForCausalLM": ModelSpec(
        "deepseek_v3", moe_families.deepseek_v3_moe_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "deepseek"},
    ),
    "DeepseekV4ForCausalLM": ModelSpec(
        "deepseek_v4", moe_families.deepseek_v4_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "deepseek"},
    ),
    "GptOssForCausalLM": ModelSpec(
        "gpt_oss", moe_families.gpt_oss_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "gpt_oss"},
    ),
    "Glm4MoeForCausalLM": ModelSpec(
        "glm4_moe", moe_families.glm4_moe_config, moe_decoder,
        adapter_name="moe_decoder",
    ),
    # GLM4-MoE-Lite: the GLM4 MoE body on MLA attention (reference:
    # models/glm4_moe_lite/, 387 LoC — reuses deepseek MLA + glm4 adapter)
    "Glm4MoeLiteForCausalLM": ModelSpec(
        "glm4_moe_lite", moe_families.deepseek_v3_moe_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "deepseek"},
    ),
    # Hy-MT2 translation MoE (reference: models/hy_mt2/, 964 LoC)
    "HyMT2ForCausalLM": ModelSpec(
        "hy_mt2", moe_families.hy_mt2_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "hy_mt2"},
    ),
    # Mistral4: DSv3 MLA+MoE body + llama4 position-dependent q-rope
    # scaling (reference: models/mistral4/, 1483 LoC)
    "Mistral4ForCausalLM": ModelSpec(
        "mistral4", moe_families.mistral4_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "deepseek"},
    ),
    # Gemma4-MoE (VL composite; text decoder — reference: models/gemma4_moe,
    # parallel dense+MoE FFN, KV sharing, Gemma4Gate router)
    "Gemma4ForConditionalGeneration": ModelSpec(
        "gemma4_moe", gemma4_module.gemma4_moe_config, gemma4_module,
        adapter_name="gemma4_moe",
    ),
    # Step-3.5 / MiMo-V2-Flash: heterogeneous sliding/global attention
    # geometries over per-layer dense/MoE MLPs (reference: models/step3p5,
    # models/mimo_v2_flash) — the het_moe engine
    "Step3p5ForCausalLM": ModelSpec(
        "step3p5", het_families.step3p5_config, het_moe_module,
        adapter_name="het_moe", adapter_kwargs={"style": "step3p5"},
    ),
    "MiMoV2FlashForCausalLM": ModelSpec(
        "mimo_v2_flash", het_families.mimo_v2_flash_config, het_moe_module,
        adapter_name="het_moe", adapter_kwargs={"style": "mimo"},
    ),
    # Ling 2.0 (reference: models/ling_v2): deepseek-style routed MoE on
    # qk-normed partial-rope GQA; fused query_key_value checkpoint layout
    "BailingMoeV2ForCausalLM": ModelSpec(
        "ling_v2", moe_families.bailing_moe_v2_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "bailing"},
    ),
    # GLM-5.x: MLA+MoE body + GLM indexer with IndexShare (reference:
    # models/glm_moe_dsa — deepseek-style checkpoint naming for MLA/MoE)
    "GlmMoeDsaForCausalLM": ModelSpec(
        "glm_moe_dsa", moe_families.glm_moe_dsa_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "deepseek"},
    ),
    "Ernie4_5_MoeForCausalLM": ModelSpec(
        "ernie4_5_moe", moe_families.ernie4_5_moe_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "ernie"},
    ),
    "HunYuanMoEV1ForCausalLM": ModelSpec(
        "hunyuan_moe", moe_families.hunyuan_moe_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "hunyuan"},
    ),
    "MiniMaxM2ForCausalLM": ModelSpec(
        "minimax_m2", moe_families.minimax_m2_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "minimax"},
    ),
    # MiniMax M3: mixed sparse/dense MoE with block-level DSA (lightning
    # indexer top-k key blocks), gemma norms, swigluoai MLPs (reference:
    # models/minimax_m3_vl/, 2980 LoC — text backbone on the het engine)
    "MiniMaxM3SparseForCausalLM": ModelSpec(
        "minimax_m3", het_families.minimax_m3_text_config, het_moe_module,
        adapter_name="het_moe", adapter_kwargs={"style": "minimax_m3"},
    ),
    # kimi_k2 is checkpoint-compatible with DeepSeek-V3 (reference:
    # components/models/kimi_k2/__init__.py — a 34-LoC alias of deepseek_v3)
    "KimiK2ForCausalLM": ModelSpec(
        "kimi_k2", moe_families.deepseek_v3_moe_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "deepseek"},
    ),
    # DeepSeek-V3.2 = the V3 body + DSA sparse attention (reference:
    # components/models/deepseek_v32 — carries index_topk in its config)
    "DeepseekV32ForCausalLM": ModelSpec(
        "deepseek_v32", moe_families.deepseek_v4_config, moe_decoder,
        adapter_name="moe_decoder", adapter_kwargs={"style": "deepseek"},
    ),
    "BaichuanForCausalLM": ModelSpec(
        "baichuan", families.baichuan_config, decoder,
        adapter_kwargs={"style": "baichuan"},
    ),
    "LlamaBidirectionalModel": ModelSpec(
        "llama_bidirectional", families.llama_bidirectional_config, decoder
    ),
    "LlamaBidirectionalForSequenceClassification": ModelSpec(
        "llama_bidirectional", families.llama_bidirectional_config, decoder
    ),
    "Mamba2ForCausalLM": ModelSpec(
        "mamba2", mamba2_module.from_hf_config, mamba2_module, adapter_name="mamba2"
    ),
    "NemotronHForCausalLM": ModelSpec(
        "nemotron_h", nemotron_h_module.from_hf_config, nemotron_h_module,
        adapter_name="nemotron_h",
    ),
    "NemotronHForCausalLMV3": ModelSpec(
        "nemotron_h", nemotron_h_module.from_hf_config, nemotron_h_module,
        adapter_name="nemotron_h",
    ),
    "Qwen3NextForCausalLM": ModelSpec(
        "qwen3_next", qwen3_next_module.from_hf_config, qwen3_next_module,
        adapter_name="qwen3_next",
    ),
    # Qwen3.5 dense / MoE (VL text decoder) — the qwen3-next engine with the
    # Qwen3.5 checkpoint layout (reference: models/qwen3_5{,_moe}/model.py
    # rebuild both on the Qwen3-Next Block)
    "Qwen3_5ForCausalLM": ModelSpec(
        "qwen3_5", qwen3_5_module.qwen3_5_config, qwen3_5_module,
        adapter_name="qwen3_5", adapter_kwargs={"vl_prefix": False},
    ),
    "Qwen3_5MoeForConditionalGeneration": ModelSpec(
        "qwen3_5_moe", qwen3_5_module.qwen3_5_moe_config, qwen3_5_module,
        adapter_name="qwen3_5",
    ),
    # omni (text·image·audio; reference: components/models/nemotron_omni,
    # qwen2_5_omni) — towers + projectors around a dense decoder backbone
    "OmniForConditionalGeneration": ModelSpec(
        "omni", omni_module.omni_config, omni_module, adapter_name="omni"
    ),
    # BAGEL: unified multimodal understanding + generation — MoT decoder
    # with und/gen expert siblings, SigLIP tower, flow-matching latent head
    # (reference: components/models/bagel/, 4227 LoC)
    "BagelForUnifiedMultimodal": ModelSpec(
        "bagel", bagel_module.bagel_config, bagel_module, adapter_name="bagel"
    ),
    "BagelForConditionalGeneration": ModelSpec(
        "bagel", bagel_module.bagel_config, bagel_module, adapter_name="bagel"
    ),
    # Kimi-VL: MoonViT tower + 2×2-merge projector + DeepSeek-V3 MoE text
    # (reference: models/kimivl, 908 LoC)
    "KimiVLForConditionalGeneration": ModelSpec(
        "kimi_vl", kimi_vl_module.kimi_vl_config, kimi_vl_module,
        adapter_name="kimi_vl",
    ),
    # Kimi-K2.5 VL: MoonViT3d (divided space/time pos emb; image t=0) +
    # DeepseekV3 text (reference: models/kimi_k25_vl/, 1593 LoC)
    "KimiK25VLForConditionalGeneration": ModelSpec(
        "kimi_k25_vl", kimi_vl_module.kimi_k25_vl_config, kimi_vl_module,
        adapter_name="kimi_vl", adapter_kwargs={"style": "k25"},
    ),
    "KimiK25ForConditionalGeneration": ModelSpec(
        "kimi_k25_vl", kimi_vl_module.kimi_k25_vl_config, kimi_vl_module,
        adapter_name="kimi_vl", adapter_kwargs={"style": "k25"},
    ),
    # MiniMax M3 VL: CLIP-style 3D-rope tower + projector/patch-merger +
    # the M3 sparse/dense MoE text backbone (reference: models/minimax_m3_vl)
    "MiniMaxM3SparseForConditionalGeneration": ModelSpec(
        "minimax_m3_vl", minimax_m3_vl_module.minimax_m3_vl_config,
        minimax_m3_vl_module, adapter_name="minimax_m3_vl",
    ),
    # Qwen3-VL-MoE: deepstack ViT + interleaved-MRoPE qwen3-moe text
    # (reference: models/qwen3_vl_moe, 707 LoC)
    "Qwen3VLMoeForConditionalGeneration": ModelSpec(
        "qwen3_vl_moe", qwen3_vl_module.qwen3_vl_moe_config, qwen3_vl_module,
        adapter_name="qwen3_vl_moe",
    ),
    # Llama-Nemotron VL: SigLIP tower + pixel-shuffle + mlp1 projector +
    # bidirectional llama — a retrieval/reranking EMBEDDING model
    # (reference: models/llama_nemotron_vl/, registered under the retrieval
    # tag in _transformers/registry.py:126)
    "LlamaNemotronVLModel": ModelSpec(
        "llama_nemotron_vl", llama_nemotron_vl_module.llama_nemotron_vl_config,
        llama_nemotron_vl_module, adapter_name="llama_nemotron_vl",
    ),
    "LlavaForConditionalGeneration": ModelSpec(
        "llava", llava_module.llava_config, llava_module, adapter_name="llava"
    ),
    "LlavaOnevisionForConditionalGeneration": ModelSpec(
        "llava_onevision", llava_module.llava_config, llava_module, adapter_name="llava"
    ),
}


def register_model(arch: str, spec: ModelSpec) -> None:
    MODEL_ARCH_MAPPING[arch] = spec


def get_model_spec(arch_or_hf_config: "str | Mapping") -> ModelSpec:
    if isinstance(arch_or_hf_config, str):
        arch = arch_or_hf_config
    else:
        archs = arch_or_hf_config.get("architectures") or []
        arch = archs[0] if archs else ""
    try:
        return MODEL_ARCH_MAPPING[arch]
    except KeyError:
        raise KeyError(
            f"Architecture '{arch}' is not registered; known: "
            f"{sorted(MODEL_ARCH_MAPPING)}"
        ) from None
