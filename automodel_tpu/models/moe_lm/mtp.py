"""Multi-Token Prediction (MTP) head — DeepSeek-V3's auxiliary depth-1
future-token predictor.

The analog of the reference's MTP module + loss (reference:
nemo_automodel/components/models/common/ MTP module, deepseek_v3/model.py
MTP wiring, loss/mtp.py `calculate_mtp_loss`). Structure (depth 1):

    h_mtp = Block( W_proj · concat( RMSNorm_h(h_main), RMSNorm_e(embed(t+1)) ) )

sharing the main embedding and unembedding; its logits predict t+2. The
loss is the same chunked fused linear CE, scaled by `mtp_loss_coeff` and
joined to the main objective by the recipe.

Deviation from DSv3: the MTP block uses a dense MLP (the reference's MTP
block is a full MoE decoder block); MTP weights are training-only state and
are not mapped by the HF adapter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.loss.linear_ce import fused_linear_cross_entropy
from automodel_tpu.loss.masked_ce import IGNORE_INDEX
from automodel_tpu.models.common.layers import dense_init
from automodel_tpu.models.llm.decoder import (
    attention_block,
    attention_layer_specs,
    init_attention_layers,
    mlp_block,
)
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import rope_frequencies


def init_mtp(cfg, rng: jax.Array) -> dict:
    """One MTP block; layer params keep the stacked (L=1, ...) convention."""
    H = cfg.hidden_size
    k1, k2 = jax.random.split(rng)
    block = init_attention_layers(cfg, k1, 1)
    block.update(
        {
            "gate_proj": {"kernel": dense_init(jax.random.fold_in(k2, 0), (1, H, cfg.intermediate_size))},
            "up_proj": {"kernel": dense_init(jax.random.fold_in(k2, 1), (1, H, cfg.intermediate_size))},
            "down_proj": {"kernel": dense_init(jax.random.fold_in(k2, 2), (1, cfg.intermediate_size, H))},
        }
    )
    return {
        "hnorm": {"scale": jnp.ones((H,))},
        "enorm": {"scale": jnp.ones((H,))},
        "eh_proj": {"kernel": dense_init(jax.random.fold_in(k2, 3), (2 * H, H))},
        "block": block,
        "final_norm": {"scale": jnp.ones((H,))},
    }


def mtp_param_specs(cfg) -> dict:
    return {
        "hnorm": {"scale": ("norm",)},
        "enorm": {"scale": ("norm",)},
        "eh_proj": {"kernel": (None, "embed")},
        "block": {
            **attention_layer_specs(cfg),
            "gate_proj": {"kernel": ("layers", "embed", "mlp")},
            "up_proj": {"kernel": ("layers", "embed", "mlp")},
            "down_proj": {"kernel": ("layers", "mlp", "embed")},
        },
        "final_norm": {"scale": ("norm",)},
    }


def mtp_hidden(
    params: dict,       # full model params (embed + mtp subtree)
    cfg,
    h_main: jnp.ndarray,    # (B, S, H) final hidden states of the main model
    input_ids: jnp.ndarray, # (B, S)
    positions: jnp.ndarray,
    segment_ids,
    constrain,
) -> jnp.ndarray:
    """Hidden states whose logits predict token t+2 at position t."""
    mtp = params["mtp"]
    if positions is None:
        B, S = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    # embedding of the NEXT token (t+1), shifted left; last slot repeats
    next_ids = jnp.concatenate([input_ids[:, 1:], input_ids[:, -1:]], axis=1)
    emb = jnp.take(params["embed"]["embedding"], next_ids, axis=0).astype(cfg.dtype)
    x = jnp.concatenate(
        [
            rms_norm(h_main, mtp["hnorm"]["scale"], cfg.rms_norm_eps),
            rms_norm(emb, mtp["enorm"]["scale"], cfg.rms_norm_eps),
        ],
        axis=-1,
    )
    h = x @ mtp["eh_proj"]["kernel"].astype(cfg.dtype)

    inv_freq = rope_frequencies(cfg.rope_dim, cfg.rope_theta, cfg.rope_scaling)
    lp = jax.tree.map(lambda a: a[0], mtp["block"])  # unstack the L=1 dim
    h = attention_block(h, lp, cfg, positions, segment_ids, inv_freq, constrain, cfg.sliding_window)
    h = mlp_block(h, lp, cfg, constrain)
    return rms_norm(h, mtp["final_norm"]["scale"], cfg.rms_norm_eps)


def mtp_loss(
    hidden_mtp: jnp.ndarray,    # (B, S, H)
    lm_kernel: jnp.ndarray,     # (H, V)
    labels: jnp.ndarray,        # (B, S) — next-token labels (t+1 at slot t)
    *,
    chunk_size: int = 1024,
    segment_ids: jnp.ndarray | None = None,  # (B, S) — packed documents
    logits_soft_cap: float | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """CE against labels shifted one more step (t+2 at slot t).

    On packed sequences, positions where the NEXT token belongs to a
    different document are masked — MTP must never supervise across
    document boundaries (matches datasets/packing.py's invariant).
    """
    mtp_labels = jnp.concatenate(
        [labels[:, 1:], jnp.full_like(labels[:, -1:], IGNORE_INDEX)], axis=1
    )
    if segment_ids is not None:
        same_doc = jnp.concatenate(
            [
                segment_ids[:, 1:] == segment_ids[:, :-1],
                jnp.zeros_like(segment_ids[:, -1:], dtype=bool),
            ],
            axis=1,
        )
        mtp_labels = jnp.where(same_doc, mtp_labels, IGNORE_INDEX)
    return fused_linear_cross_entropy(
        hidden_mtp, lm_kernel, mtp_labels, chunk_size=chunk_size,
        logits_soft_cap=logits_soft_cap,
    )
