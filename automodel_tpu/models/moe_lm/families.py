"""MoE model-family adapters: HF config dict → MoETransformerConfig.

The analog of the reference's MoE families (reference: nemo_automodel/
components/models/{qwen3_moe,deepseek_v3,glm4_moe}/model.py + registry).
"""

from __future__ import annotations

from typing import Any, Mapping

from automodel_tpu.models.llm.families import _base_kwargs
from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig
from automodel_tpu.moe.config import MoEConfig


def qwen3_moe_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """Qwen3MoeForCausalLM (reference: models/qwen3_moe, 838 LoC)."""
    kw = _base_kwargs(hf)
    kw["qk_norm"] = True
    moe = MoEConfig(
        n_routed_experts=int(hf["num_experts"]),
        experts_per_token=int(hf["num_experts_per_tok"]),
        moe_intermediate_size=int(hf["moe_intermediate_size"]),
        norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
        score_func="softmax",
        aux_loss_coeff=float(hf.get("router_aux_loss_coef", 0.0)),
    )
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=0, **kw)


def mixtral_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """MixtralForCausalLM — softmax top-k with renormalization (equivalent to
    softmax over the selected logits)."""
    kw = _base_kwargs(hf)
    moe = MoEConfig(
        n_routed_experts=int(hf["num_local_experts"]),
        experts_per_token=int(hf["num_experts_per_tok"]),
        moe_intermediate_size=int(hf["intermediate_size"]),
        norm_topk_prob=True,
        score_func="softmax",
        aux_loss_coeff=float(hf.get("router_aux_loss_coef", 0.02)),
    )
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=0, **kw)


def deepseek_v3_moe_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """DeepSeek-V3-style MoE body: sigmoid scores, group-limited routing,
    shared experts, aux-free gate-bias balancing, first-k-dense layers.
    Uses MLA attention when the HF config carries kv_lora_rank.
    """
    kw = _base_kwargs(hf)
    moe = MoEConfig(
        n_routed_experts=int(hf["n_routed_experts"]),
        n_shared_experts=int(hf.get("n_shared_experts", 0)),
        experts_per_token=int(hf["num_experts_per_tok"]),
        n_groups=int(hf.get("n_group", 1)),
        topk_groups=int(hf.get("topk_group", 1)),
        moe_intermediate_size=int(hf["moe_intermediate_size"]),
        score_func="sigmoid" if hf.get("scoring_func", "sigmoid") == "sigmoid" else "softmax",
        norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
        route_scale=float(hf.get("routed_scaling_factor", 1.0)),
        aux_loss_coeff=float(hf.get("aux_loss_alpha", 0.0)),
        gate_bias_update_speed=float(hf.get("bias_update_speed", 0.001)),
    )
    first_k = int(hf.get("first_k_dense_replace", 0))
    if hf.get("num_nextn_predict_layers"):
        kw["mtp_num_layers"] = min(int(hf["num_nextn_predict_layers"]), 1)
    if hf.get("kv_lora_rank"):
        kw["attention_type"] = "mla"
        kw["mla_q_lora_rank"] = int(hf["q_lora_rank"]) if hf.get("q_lora_rank") else None
        kw["mla_kv_lora_rank"] = int(hf["kv_lora_rank"])
        kw["mla_qk_nope_head_dim"] = int(hf.get("qk_nope_head_dim", 128))
        kw["mla_qk_rope_head_dim"] = int(hf.get("qk_rope_head_dim", 64))
        kw["mla_v_head_dim"] = int(hf.get("v_head_dim", 128))
        kw["head_dim"] = None
        rs = kw["rope_scaling"]
        if rs.rope_type == "yarn":
            qk = kw["mla_qk_nope_head_dim"] + kw["mla_qk_rope_head_dim"]
            kw["attn_scale"] = qk ** -0.5 * rs.yarn_mscale() ** 2
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=first_k, **kw)


def deepseek_v4_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """DeepseekV4ForCausalLM: the V3 MoE+MLA body plus DSA — the lightning
    indexer's top-k sparse attention (reference: components/models/
    deepseek_v4/layers.py Indexer, kernels/sparse_attention.py; index_topk /
    index_n_heads / index_head_dim are the HF config fields).

    Uncompressed indexer (compress_ratio=0 path); the pooled-KV compressor
    is a later-round addition. Indexer weights initialize fresh when absent
    from the checkpoint.
    """
    dsa = {}
    if hf.get("index_topk"):
        dsa = dict(
            dsa_index_topk=int(hf["index_topk"]),
            dsa_index_n_heads=int(hf.get("index_n_heads", 4)),
            dsa_index_head_dim=int(hf.get("index_head_dim", 64)),
        )
    return deepseek_v3_moe_config(hf, **dsa, **overrides)


def bailing_moe_v2_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """BailingMoeV2ForCausalLM (Ling 2.0 mini/flash/1T; reference:
    models/ling_v2, 1007 LoC): GQA with per-head qk-norm and partial rotary,
    first-k-dense prefix, DeepSeek-style sigmoid grouped routing with the
    aux-free expert bias, one shared expert. Checkpoints store fused
    query_key_value / attention.dense / word_embeddings names — the
    adapter's "bailing" style."""
    if hf.get("use_qkv_bias"):
        raise NotImplementedError("bailing fused qkv bias")
    kw = _base_kwargs(hf)
    kw["qk_norm"] = bool(hf.get("use_qk_norm", True))
    kw["partial_rotary_factor"] = float(hf.get("partial_rotary_factor", 1.0))
    enable_bias = bool(hf.get("moe_router_enable_expert_bias", True))
    moe = MoEConfig(
        n_routed_experts=int(hf["num_experts"]),
        n_shared_experts=int(hf.get("num_shared_experts", 1)),
        experts_per_token=int(hf["num_experts_per_tok"]),
        n_groups=int(hf.get("n_group", 1)),
        topk_groups=int(hf.get("topk_group", 1)),
        moe_intermediate_size=int(hf["moe_intermediate_size"]),
        score_func=(
            "sigmoid" if hf.get("score_function", "sigmoid") == "sigmoid" else "softmax"
        ),
        norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
        route_scale=float(hf.get("routed_scaling_factor", 1.0)),
        aux_loss_coeff=float(hf.get("router_aux_loss_coef", 0.0) or 0.0),
        gate_bias_update_speed=(
            float(hf.get("bias_update_speed", 0.001)) if enable_bias else 0.0
        ),
    )
    first_k = int(hf.get("first_k_dense_replace", 1))
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=first_k, **kw)


def glm_moe_dsa_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """GlmMoeDsaForCausalLM (GLM-5.x; reference: models/glm_moe_dsa, 3028
    LoC): the DeepSeek-style MLA+MoE body (sigmoid grouped router with
    correction bias, shared experts, first-k-dense) plus the GLM lightning
    indexer — queries from the q-lora residual, LayerNorm'd keys, rope-first
    slice — with IndexShare ("shared" layers reuse the previous full layer's
    top-k selection, config `indexer_types`)."""
    dsa = {}
    if hf.get("index_topk"):
        dsa = dict(
            dsa_index_topk=int(hf["index_topk"]),
            dsa_index_n_heads=int(hf.get("index_n_heads", 4)),
            dsa_index_head_dim=int(hf.get("index_head_dim", 64)),
            dsa_indexer_style="glm",
        )
        if hf.get("indexer_types"):
            dsa["dsa_indexer_types"] = tuple(hf["indexer_types"])
    return deepseek_v3_moe_config(hf, **dsa, **overrides)


def glm4_moe_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """Glm4MoeForCausalLM (GLM-4.5/4.6; reference: models/glm4_moe, 658 LoC):
    DeepSeek-style sigmoid grouped router with e_score correction bias +
    shared experts + first-k-dense, on GQA attention with partial
    half-split rotary and optional qk-norm."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    kw["partial_rotary_factor"] = float(hf.get("partial_rotary_factor", 0.5))
    kw["qk_norm"] = bool(hf.get("use_qk_norm", False))
    moe = MoEConfig(
        n_routed_experts=int(hf["n_routed_experts"]),
        n_shared_experts=int(hf.get("n_shared_experts", 0)),
        experts_per_token=int(hf["num_experts_per_tok"]),
        n_groups=int(hf.get("n_group", 1)),
        topk_groups=int(hf.get("topk_group", 1)),
        moe_intermediate_size=int(hf["moe_intermediate_size"]),
        score_func="sigmoid",
        norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
        route_scale=float(hf.get("routed_scaling_factor", 1.0)),
        aux_loss_coeff=float(hf.get("aux_loss_alpha", 0.0)),
        gate_bias_update_speed=float(hf.get("bias_update_speed", 0.001)),
    )
    first_k = int(hf.get("first_k_dense_replace", 0))
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=first_k, **kw)


def ernie4_5_moe_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """Ernie4_5_MoeForCausalLM (reference: models/ernie4_5, 897 LoC):
    softmax scoring with the aux-free `moe_statics` correction bias applied
    to the probabilities for SELECTION only, renormalized top-k weights,
    one fused shared-experts MLP, dense layers before
    `moe_layer_start_index`."""
    interval = int(hf.get("moe_layer_interval", 1))
    if interval != 1:
        raise NotImplementedError("ernie moe_layer_interval != 1")
    n_layers = int(hf["num_hidden_layers"])
    end = int(hf.get("moe_layer_end_index", n_layers - 1))
    if end not in (-1, n_layers - 1):
        raise NotImplementedError("ernie moe_layer_end_index < num_layers-1")
    kw = _base_kwargs(hf)
    kw["rope_interleaved"] = True  # glm-style interleaved rotary
    kw["attention_bias"] = bool(hf.get("use_bias", False))
    kw["tie_word_embeddings"] = bool(hf.get("tie_word_embeddings", True))
    n_shared = int(hf.get("moe_num_shared_experts", 0))
    moe = MoEConfig(
        n_routed_experts=int(hf["moe_num_experts"]),
        n_shared_experts=n_shared,
        experts_per_token=int(hf["moe_k"]),
        moe_intermediate_size=int(hf["moe_intermediate_size"]),
        shared_expert_intermediate_size=(
            int(hf["moe_intermediate_size"]) * n_shared if n_shared else None
        ),
        score_func="softmax",
        norm_topk_prob=True,
        aux_loss_coeff=float(hf.get("router_aux_loss_coef", 0.0)),
        gate_bias_update_speed=float(hf.get("bias_update_speed", 0.001)),
    )
    first_k = int(hf.get("moe_layer_start_index", 0))
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=first_k, **kw)


def minimax_m2_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """MiniMaxM2ForCausalLM (reference: models/minimax_m2, 748 LoC): GQA
    with RMSNorm over the FLATTENED q/k projections, partial rotary via
    `rotary_dim`, and a no-shared-experts MoE with a forced e-score
    correction bias (reference model.py:134 force_e_score_correction_bias)."""
    kw = _base_kwargs(hf)
    head_dim = kw["head_dim"] or kw["hidden_size"] // kw["num_heads"]
    if hf.get("rotary_dim"):
        kw["partial_rotary_factor"] = float(hf["rotary_dim"]) / head_dim
    kw["qk_norm_flat"] = bool(hf.get("use_qk_norm", True))
    score = str(hf.get("scoring_func", "sigmoid")).lower()
    moe = MoEConfig(
        n_routed_experts=int(hf["num_local_experts"]),
        experts_per_token=int(hf["num_experts_per_tok"]),
        moe_intermediate_size=int(hf["intermediate_size"]),
        score_func="softmax" if score == "softmax" else "sigmoid",
        norm_topk_prob=True,
        aux_loss_coeff=float(hf.get("router_aux_loss_coef", 0.0)),
        gate_bias_update_speed=float(hf.get("bias_update_speed", 0.001)),
    )
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=0, **kw)


def hunyuan_moe_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """HunYuanMoEV1ForCausalLM (reference: models/hy_v3, 838 LoC): softmax
    top-k renormalized router (no bias/groups), an always-on shared MLP at
    the dense intermediate size, post-rope qk-norm attention."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    kw["qk_norm"] = True
    kw["qk_norm_after_rope"] = True
    n_experts = hf["num_experts"]
    topk = hf.get("moe_topk", 1)
    if not isinstance(n_experts, int) or not isinstance(topk, int):
        raise NotImplementedError("hunyuan per-layer expert-count lists")
    # Released HunYuan-A13B checkpoints carry moe_intermediate_size /
    # num_shared_expert; fall back to the dense intermediate size (what the
    # installed transformers modeling always uses) only when absent.
    moe_inter = hf.get("moe_intermediate_size")
    if moe_inter is None:
        moe_inter = hf["intermediate_size"]
    n_shared = hf.get("num_shared_expert")
    if n_shared is None:
        n_shared = 1
    # released A13B checkpoints carry these as uniform per-layer lists
    if isinstance(moe_inter, (list, tuple)) and len(set(moe_inter)) == 1:
        moe_inter = moe_inter[0]
    if isinstance(n_shared, (list, tuple)) and len(set(n_shared)) == 1:
        n_shared = n_shared[0]
    if not isinstance(moe_inter, int) or not isinstance(n_shared, int):
        raise NotImplementedError("hunyuan per-layer moe size/shared lists")
    moe = MoEConfig(
        n_routed_experts=int(n_experts),
        n_shared_experts=int(n_shared),
        experts_per_token=int(topk),
        moe_intermediate_size=int(moe_inter),
        # shared width n_shared·moe_inter comes from shared_intermediate's default
        score_func="softmax",
        norm_topk_prob=True,
        aux_loss_coeff=float(hf.get("router_aux_loss_coef", 0.0)),
    )
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=0, **kw)


def gpt_oss_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """GptOssForCausalLM: alternating sliding/full attention with learnable
    sinks, biased router, fused-gate_up experts with biases and the clamped
    swiglu-oai activation (reference: models/gpt_oss, 1082 LoC)."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = bool(hf.get("attention_bias", True))
    kw["o_proj_bias"] = bool(hf.get("attention_bias", True))
    kw["attention_sinks"] = True
    if hf.get("sliding_window"):
        kw["sliding_window"] = int(hf["sliding_window"])
        if hf.get("layer_types"):
            kw["layer_types"] = tuple(
                "sliding" if t == "sliding_attention" else "global"
                for t in hf["layer_types"]
            )
        else:
            kw["layer_types"] = tuple(
                "sliding" if i % 2 == 0 else "global" for i in range(kw["num_layers"])
            )
    moe = MoEConfig(
        n_routed_experts=int(hf["num_local_experts"]),
        experts_per_token=int(hf.get("num_experts_per_tok", 4)),
        moe_intermediate_size=int(hf["intermediate_size"]),
        norm_topk_prob=True,   # softmax-over-top-k == normalized softmax top-k
        score_func="softmax",
        router_bias=True,
        expert_bias=True,
        expert_activation="swigluoai",
        swiglu_limit=float(hf.get("swiglu_limit", 7.0)),
        aux_loss_coeff=float(hf.get("router_aux_loss_coef", 0.0)),
    )
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=0, **kw)


def hy_mt2_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """HyMT2ForCausalLM (reference: models/hy_mt2/, 964 LoC — Tencent
    Hy-MT2-30B-A3B translation MoE): GQA with per-head pre-rope qk-norm,
    dense layer 0 + MoE (128 routed top-8 + 1 shared), router sigmoid via
    moe_router_use_sigmoid, optional expert selection bias."""
    kw = _base_kwargs(hf)
    kw["qk_norm"] = bool(hf.get("qk_norm", True))
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    moe_inter = int(hf.get("expert_hidden_dim") or hf["moe_intermediate_size"])
    n_shared = int(hf.get("num_shared_experts", 0) or 0)
    moe = MoEConfig(
        n_routed_experts=int(hf["num_experts"]),
        n_shared_experts=n_shared,
        experts_per_token=int(hf["num_experts_per_tok"]),
        moe_intermediate_size=moe_inter,
        shared_expert_intermediate_size=(
            int(hf.get("shared_expert_intermediate_size") or moe_inter * n_shared)
            if n_shared else None
        ),
        score_func="sigmoid" if hf.get("moe_router_use_sigmoid", True) else "softmax",
        norm_topk_prob=bool(hf.get("route_norm", True)),
        route_scale=float(hf.get("router_scaling_factor", 1.0) or 1.0),
        gate_bias_update_speed=(
            0.001 if bool(hf.get("moe_router_enable_expert_bias", False)) else 0.0
        ),
    )
    first_k = int(hf.get("first_k_dense_replace", 1))
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)
    return MoETransformerConfig(moe=moe_overrides or moe, first_k_dense=first_k, **kw)


def mistral4_config(hf: Mapping[str, Any], **overrides) -> MoETransformerConfig:
    """Mistral4ForCausalLM (reference: models/mistral4/, 1483 LoC): the
    DeepSeek-V3 MLA+MoE body with llama4-style position-dependent q-rope
    scaling (model.py:52 `_get_llama_4_attn_scale` via
    rope_parameters.llama_4_scaling_beta)."""
    cfg = deepseek_v3_moe_config(hf, **overrides)
    rp = hf.get("rope_parameters") or hf.get("rope_scaling") or {}
    beta = rp.get("llama_4_scaling_beta")
    if beta:
        import dataclasses as _dc

        cfg = _dc.replace(
            cfg,
            mla_qpe_scaling_beta=float(beta),
            mla_qpe_scaling_orig_max=int(
                rp.get("original_max_position_embeddings", 8192)
            ),
        )
    return cfg
