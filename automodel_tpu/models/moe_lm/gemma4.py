"""Gemma4-MoE (E2B/E4B/26B-A4B family) — parallel dense+MoE FFN decoder.

The analog of the reference's gemma4_moe (reference: nemo_automodel/
components/models/gemma4_moe/model.py, 3377 LoC). Architecture, per layer
(model.py:355-440 `Gemma4MoEDecoderLayer.forward`):

    x  = residual + post_attn_norm(attn(input_norm(x)))
    d  = post_ffn_norm_1(dense_mlp(pre_ffn_norm(x)))
    m  = post_ffn_norm_2(moe(pre_ffn_norm_2(x), gate_input = RAW x))
    x  = (residual' + post_ffn_norm(d + m)) * layer_scalar

- The router (model.py:200 `Gemma4Gate`) scores a no-scale RMSNorm of the
  RAW residual, scaled by hidden**-0.5 and a learned per-channel scale, in
  fp32: softmax → top-k → renormalize. No aux loss, no groups.
- Attention is gemma3-style: per-head-dim zero-centered qk-norm,
  query_pre_attn_scalar scaling, alternating sliding/global layers with a
  separate local rope theta, zero-centered norms, scaled embeddings.
- KV sharing (model.py:103 `_Gemma4KVShareHolder`): the trailing
  `num_kv_shared_layers` layers compute no K/V; each reads the most recent
  SAME-TYPE (sliding/global) full layer's K/V. Shared layers' k/v kernels
  are zero-filled placeholders in the pytree (absent from HF checkpoints)
  so the stacked layout stays uniform.

TPU design: stacked params + a python loop over layers (the KV-share read
pattern is layer-heterogeneous; same idiom as models/hybrid/qwen3_next).
Experts run through the shared MoE machinery (moe/experts.py dropless or
EP-distributed paths) with the Gemma4 gate computed locally.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init, embed_init
from automodel_tpu.models.llm.decoder import _make_constrain, _stack
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.experts import (
    expert_param_specs,
    experts_forward_dropless,
    experts_forward_dropless_ep,
    init_experts,
)
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import RopeScalingConfig, apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class Gemma4MoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 4096      # dense-branch MLP
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: int = 4
    head_dim: int = 256
    layer_types: tuple = ()            # "sliding" | "global" per layer
    sliding_window: Optional[int] = 512
    rope_theta: float = 1_000_000.0
    rope_local_theta: float = 10_000.0
    rope_scaling: RopeScalingConfig = dataclasses.field(default_factory=RopeScalingConfig)
    attn_scale: Optional[float] = None  # query_pre_attn_scalar ** -0.5
    num_kv_shared_layers: int = 0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = True
    moe: MoEConfig = dataclasses.field(default_factory=MoEConfig)
    causal: bool = True
    logits_soft_cap: Optional[float] = None
    dtype: Any = jnp.bfloat16
    remat_policy: str = "full"
    attn_impl: str = "auto"
    scan_unroll: int = 1
    mtp_num_layers: int = 0  # chassis compatibility

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim

    @property
    def embed_scale(self) -> float:
        return float(self.hidden_size) ** 0.5

    def flops_per_token(self, seq_len: int) -> float:
        H, D = self.hidden_size, self.head_dim
        attn_p = H * D * (2 * self.num_heads + 2 * self.num_kv_heads)
        mlp_p = 3 * H * self.intermediate_size
        moe_p = 3 * H * self.moe.moe_intermediate_size * self.moe.experts_per_token
        n = self.vocab_size * H + self.num_layers * (attn_p + mlp_p + moe_p)
        return 6.0 * n + 6.0 * self.num_layers * self.num_heads * D * seq_len


def init(cfg: Gemma4MoEConfig, rng: jax.Array) -> dict:
    H, I, D = cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    L = cfg.num_layers
    ks = jax.random.split(rng, 12)
    layers = {
        "input_norm": {"scale": jnp.zeros((L, H))},
        "post_attn_norm": {"scale": jnp.zeros((L, H))},
        "q_proj": {"kernel": _stack(dense_init, ks[0], (H, cfg.num_heads * D), L)},
        "k_proj": {"kernel": _stack(dense_init, ks[1], (H, cfg.num_kv_heads * D), L)},
        "v_proj": {"kernel": _stack(dense_init, ks[2], (H, cfg.num_kv_heads * D), L)},
        "o_proj": {"kernel": _stack(dense_init, ks[3], (cfg.num_heads * D, H), L)},
        "q_norm": {"scale": jnp.zeros((L, D))},
        "k_norm": {"scale": jnp.zeros((L, D))},
        "pre_ffn_norm": {"scale": jnp.zeros((L, H))},
        "post_ffn_norm_1": {"scale": jnp.zeros((L, H))},
        "pre_ffn_norm_2": {"scale": jnp.zeros((L, H))},
        "post_ffn_norm_2": {"scale": jnp.zeros((L, H))},
        "post_ffn_norm": {"scale": jnp.zeros((L, H))},
        "layer_scalar": jnp.ones((L, 1)),
        "gate_proj": {"kernel": _stack(dense_init, ks[4], (H, I), L)},
        "up_proj": {"kernel": _stack(dense_init, ks[5], (H, I), L)},
        "down_proj": {"kernel": _stack(dense_init, ks[6], (I, H), L)},
        "router": {
            "proj": {"kernel": _stack(dense_init, ks[7], (H, cfg.moe.n_routed_experts), L)},
            "scale": jnp.ones((L, H)),
        },
        "experts": jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[
                init_experts(cfg.moe, H, k)
                for k in jax.random.split(ks[8], L)
            ],
        ),
    }
    params = {
        "embed": {"embedding": embed_init(ks[9], (cfg.vocab_size, H))},
        "final_norm": {"scale": jnp.zeros((H,))},
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense_init(ks[10], (H, cfg.vocab_size))}
    return params


def param_specs(cfg: Gemma4MoEConfig) -> dict:
    layers = {
        "input_norm": {"scale": ("layers", "norm")},
        "post_attn_norm": {"scale": ("layers", "norm")},
        "q_proj": {"kernel": ("layers", "embed", "heads")},
        "k_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "v_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "o_proj": {"kernel": ("layers", "heads", "embed")},
        "q_norm": {"scale": ("layers", "norm")},
        "k_norm": {"scale": ("layers", "norm")},
        "pre_ffn_norm": {"scale": ("layers", "norm")},
        "post_ffn_norm_1": {"scale": ("layers", "norm")},
        "pre_ffn_norm_2": {"scale": ("layers", "norm")},
        "post_ffn_norm_2": {"scale": ("layers", "norm")},
        "post_ffn_norm": {"scale": ("layers", "norm")},
        "layer_scalar": ("layers", None),
        "gate_proj": {"kernel": ("layers", "embed", "mlp")},
        "up_proj": {"kernel": ("layers", "embed", "mlp")},
        "down_proj": {"kernel": ("layers", "mlp", "embed")},
        "router": {
            "proj": {"kernel": ("layers", "embed", None)},
            "scale": ("layers", "norm"),
        },
        "experts": jax.tree.map(
            lambda s: ("layers",) + s,
            expert_param_specs(cfg.moe),
            is_leaf=lambda x: isinstance(x, tuple),
        ),
    }
    specs = {
        "embed": {"embedding": ("vocab", "embed")},
        "final_norm": {"scale": ("norm",)},
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"kernel": ("embed", "vocab")}
    return specs


def gemma4_gate(x_raw, lp, cfg: Gemma4MoEConfig):
    """Router on the RAW residual: no-scale RMSNorm · H**-0.5 · scale →
    fp32 linear → softmax → top-k → renormalize. Returns (weights (T,K),
    indices (T,K))."""
    x = x_raw.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + cfg.rms_norm_eps)
    x = x * (float(cfg.hidden_size) ** -0.5)
    x = x * lp["router"]["scale"].astype(jnp.float32)
    logits = x @ lp["router"]["proj"]["kernel"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    weights, indices = jax.lax.top_k(probs, cfg.moe.experts_per_token)
    weights = weights / jnp.maximum(jnp.sum(weights, -1, keepdims=True), 1e-20)
    return weights, indices


def forward(
    params: dict,
    cfg: Gemma4MoEConfig,
    input_ids: jnp.ndarray,
    *,
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    token_mask: jnp.ndarray | None = None,
    return_stats: bool = False,
    **_ignored,
) -> tuple:
    """Returns (logits-or-hidden, aux_loss[, stats]) — the moe_lm protocol
    (aux is always 0.0: the Gemma4 router carries no aux loss)."""
    from automodel_tpu.models.common.layers import cast_params, maybe_remat

    params = cast_params(params, cfg.dtype)
    B, S = input_ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    constrain = _make_constrain(mesh_ctx, rules)

    tbl = constrain(params["embed"]["embedding"], ("vocab", None))
    h = jnp.take(tbl, input_ids, axis=0).astype(cfg.dtype)
    h = h * jnp.asarray(cfg.embed_scale, cfg.dtype)
    h = constrain(h, ("act_batch", "act_seq", "act_embed"))

    inv_freq_g = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    inv_freq_l = rope_frequencies(cfg.head_dim, cfg.rope_local_theta, None)
    D = cfg.head_dim
    scale = cfg.attn_scale if cfg.attn_scale is not None else D ** -0.5
    eps = cfg.rms_norm_eps
    layer_types = cfg.layer_types or tuple(
        "sliding" if (i + 1) % 6 else "global" for i in range(cfg.num_layers)
    )
    first_shared = cfg.num_layers - cfg.num_kv_shared_layers
    ep = mesh_ctx is not None and mesh_ctx.sizes["ep"] > 1

    stats_rows = []
    last_kv: dict = {"sliding": None, "global": None}

    def one_layer(h, lp, lt, kv_in):
        """Returns (h_out, (k, v), tokens_per_expert)."""
        inv_freq = inv_freq_l if lt == "sliding" else inv_freq_g
        window = cfg.sliding_window if lt == "sliding" else None
        resid = h
        x = rms_norm(h, lp["input_norm"]["scale"], eps, zero_centered=True)
        q = (x @ lp["q_proj"]["kernel"]).reshape(B, S, cfg.num_heads, D)
        q = rms_norm(q, lp["q_norm"]["scale"], eps, zero_centered=True)
        q = apply_rope(q, positions, inv_freq)
        if kv_in is None:
            k = (x @ lp["k_proj"]["kernel"]).reshape(B, S, cfg.num_kv_heads, D)
            k = rms_norm(k, lp["k_norm"]["scale"], eps, zero_centered=True)
            k = apply_rope(k, positions, inv_freq)
            v = (x @ lp["v_proj"]["kernel"]).reshape(B, S, cfg.num_kv_heads, D)
        else:
            k, v = kv_in
        q = constrain(q, ("act_batch", "act_seq", "act_heads", None))
        attn = dot_product_attention(
            q, k, v, causal=cfg.causal, segment_ids=segment_ids,
            positions=positions, sliding_window=window, scale=scale,
            impl=cfg.attn_impl,
        ).reshape(B, S, cfg.num_heads * D)
        attn_out = attn @ lp["o_proj"]["kernel"]
        attn_out = rms_norm(attn_out, lp["post_attn_norm"]["scale"], eps, zero_centered=True)
        h = resid + attn_out
        h = constrain(h, ("act_batch", "act_seq", "act_embed"))

        resid = h
        xd = rms_norm(h, lp["pre_ffn_norm"]["scale"], eps, zero_centered=True)
        d = jax.nn.gelu(xd @ lp["gate_proj"]["kernel"], approximate=True) * (
            xd @ lp["up_proj"]["kernel"]
        )
        d = d @ lp["down_proj"]["kernel"]
        d = rms_norm(d, lp["post_ffn_norm_1"]["scale"], eps, zero_centered=True)

        xm = rms_norm(h, lp["pre_ffn_norm_2"]["scale"], eps, zero_centered=True)
        flat = xm.reshape(B * S, cfg.hidden_size)
        weights, indices = gemma4_gate(h.reshape(B * S, cfg.hidden_size), lp, cfg)
        weights = weights.astype(flat.dtype)
        if ep:
            routed = experts_forward_dropless_ep(
                lp["experts"], cfg.moe, flat, weights, indices, mesh_ctx
            )
        else:
            routed = experts_forward_dropless(
                lp["experts"], cfg.moe, flat, weights, indices
            )
        m = routed.reshape(B, S, cfg.hidden_size)
        m = rms_norm(m, lp["post_ffn_norm_2"]["scale"], eps, zero_centered=True)

        out = rms_norm(d + m, lp["post_ffn_norm"]["scale"], eps, zero_centered=True)
        h = (resid + out) * lp["layer_scalar"][0]
        h = constrain(h, ("act_batch", "act_seq", "act_embed"))

        tpe = jnp.sum(
            jax.nn.one_hot(indices, cfg.moe.n_routed_experts, dtype=jnp.float32),
            axis=(0, 1),
        )
        return h, (k, v), tpe

    remat = cfg.remat_policy not in (None, "none")
    for i, lt in enumerate(layer_types):
        lp = jax.tree.map(lambda p: p[i], params["layers"])
        kv_in = last_kv[lt] if i >= first_shared else None

        def body(h, lp=lp, lt=lt, kv_in=kv_in):
            return one_layer(h, lp, lt, kv_in)

        h, kv, tpe = (jax.checkpoint(body) if remat else body)(h)
        if i < first_shared:
            last_kv[lt] = kv
        stats_rows.append(tpe)

    h = rms_norm(h, params["final_norm"]["scale"], eps, zero_centered=True)
    if return_hidden:
        out = h
    else:
        from automodel_tpu.models.llm.decoder import unembed

        out = unembed(params, cfg, h)
    aux = jnp.float32(0.0)
    if return_stats:
        return out, aux, {"tokens_per_expert": jnp.stack(stats_rows)}
    return out, aux


def gemma4_moe_config(hf: dict, **overrides) -> Gemma4MoEConfig:
    """Gemma4ForConditionalGeneration → text-decoder config. VL composite
    configs nest under text_config (vision tower: VLM tier)."""
    text = hf.get("text_config") or hf
    lt = text.get("layer_types")
    if lt is not None:
        layer_types = tuple(
            "sliding" if t == "sliding_attention" else "global" for t in lt
        )
    else:
        pattern = int(text.get("sliding_window_pattern", 6) or 6)
        layer_types = tuple(
            "global" if (i + 1) % pattern == 0 else "sliding"
            for i in range(int(text["num_hidden_layers"]))
        )
    moe_inter = text.get("moe_intermediate_size") or text.get("expert_intermediate_size")
    moe = MoEConfig(
        n_routed_experts=int(text["num_experts"]),
        experts_per_token=int(text["top_k_experts"]),
        moe_intermediate_size=int(moe_inter),
        score_func="softmax",
        norm_topk_prob=True,
        expert_activation="geglu",
        aux_loss_coeff=0.0,
        dispatcher="dropless",
    )
    heads = int(text["num_attention_heads"])
    qpas = text.get("query_pre_attn_scalar")
    kw = dict(
        vocab_size=int(text["vocab_size"]),
        hidden_size=int(text["hidden_size"]),
        intermediate_size=int(text["intermediate_size"]),
        num_layers=int(text["num_hidden_layers"]),
        num_heads=heads,
        num_kv_heads=int(text.get("num_key_value_heads", heads)),
        head_dim=int(text.get("head_dim", 256)),
        layer_types=layer_types,
        sliding_window=int(text.get("sliding_window", 512) or 512),
        rope_theta=float(text.get("rope_theta", 1_000_000.0)),
        rope_local_theta=float(text.get("rope_local_base_freq", 10_000.0)),
        rope_scaling=RopeScalingConfig.from_hf(text.get("rope_scaling")),
        attn_scale=(float(qpas) ** -0.5) if qpas else None,
        num_kv_shared_layers=int(text.get("num_kv_shared_layers", 0) or 0),
        rms_norm_eps=float(text.get("rms_norm_eps", 1e-6)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", True)),
        moe=moe,
    )
    moe_overrides = overrides.pop("moe", None)
    for k in list(overrides):
        if k not in {f.name for f in dataclasses.fields(Gemma4MoEConfig)}:
            overrides.pop(k)
    kw.update(overrides)
    if moe_overrides is not None:
        kw["moe"] = moe_overrides
    return Gemma4MoEConfig(**kw)


# ---------------------------------------------------------------------------
# HF state-dict adapter (reference: gemma4_moe/state_dict_adapter.py —
# stacked moe.gate_up_proj/down_proj/per_expert_scale, router.* keys)
# ---------------------------------------------------------------------------
class Gemma4MoEAdapter:
    """Gemma4ForConditionalGeneration text weights ↔ our params pytree.

    HF stores experts stacked: `moe.gate_up_proj` (E, 2I, H) [gate; up],
    `moe.down_proj` (E, H, I) and a `moe.per_expert_scale` (E) absorbed into
    down_proj at load (exported back as ones — reference adapter does the
    same). KV-shared trailing layers carry no k/v/k_norm keys: zero-filled
    placeholders at load, omitted at save.
    """

    def __init__(self, cfg: Gemma4MoEConfig):
        self.cfg = cfg

    _NORMS = {
        "input_layernorm": ("input_norm",),
        "post_attention_layernorm": ("post_attn_norm",),
        "pre_feedforward_layernorm": ("pre_ffn_norm",),
        "post_feedforward_layernorm_1": ("post_ffn_norm_1",),
        "pre_feedforward_layernorm_2": ("pre_ffn_norm_2",),
        "post_feedforward_layernorm_2": ("post_ffn_norm_2",),
        "post_feedforward_layernorm": ("post_ffn_norm",),
    }

    def _kv_absent(self, i: int) -> bool:
        return i >= self.cfg.num_layers - self.cfg.num_kv_shared_layers

    def from_hf(self, read, shardings=None) -> dict:
        import numpy as np

        from automodel_tpu.checkpoint.hf_adapter import _get, _set

        cfg = self.cfg
        L = cfg.num_layers
        I = cfg.moe.moe_intermediate_size

        from automodel_tpu.checkpoint.hf_adapter import reader_has_key

        prefix = "model.language_model." if reader_has_key(
            read, "model.language_model.embed_tokens.weight"
        ) else "model."

        def probe(k):
            return reader_has_key(read, k)

        params: dict = {}

        def put(path, value):
            sh = _get(shardings, path) if shardings is not None else None
            _set(params, path, jax.device_put(value, sh) if sh is not None else jnp.asarray(value))

        put(("embed", "embedding"), read(prefix + "embed_tokens.weight"))
        put(("final_norm", "scale"), read(prefix + "norm.weight"))
        if not cfg.tie_word_embeddings and probe("lm_head.weight"):
            put(("lm_head", "kernel"), np.ascontiguousarray(read("lm_head.weight").T))

        def lay(i, suffix):
            return read(f"{prefix}layers.{i}.{suffix}")

        def stackT(suffix):
            return np.stack(
                [np.ascontiguousarray(lay(i, suffix).T) for i in range(L)]
            )

        def stack_(suffix):
            return np.stack([lay(i, suffix) for i in range(L)])

        for hf_name, path in self._NORMS.items():
            put(("layers",) + path + ("scale",), stack_(hf_name + ".weight"))
        put(("layers", "q_norm", "scale"), stack_("self_attn.q_norm.weight"))
        put(("layers", "q_proj", "kernel"), stackT("self_attn.q_proj.weight"))
        put(("layers", "o_proj", "kernel"), stackT("self_attn.o_proj.weight"))

        def kv_stack(suffix, transpose):
            from automodel_tpu.checkpoint.hf_adapter import _stack_layers_zero_fill

            def one_kv(name, tr, _tr2):
                if tr:
                    return np.ascontiguousarray(np.asarray(read(name)).T)
                return np.asarray(read(name))

            # kv-absent layers raise KeyError from read; zero-filled there
            def guarded(name, tr, _tr2):
                i = int(name.split("layers.")[1].split(".")[0])
                if self._kv_absent(i):
                    raise KeyError(name)
                return one_kv(name, tr, _tr2)

            names = [f"{prefix}layers.{i}.{suffix}" for i in range(L)]
            return _stack_layers_zero_fill(
                guarded, names, transpose, None,
                absent_ok=self._kv_absent,
            )

        put(("layers", "k_proj", "kernel"), kv_stack("self_attn.k_proj.weight", True))
        put(("layers", "v_proj", "kernel"), kv_stack("self_attn.v_proj.weight", True))
        put(("layers", "k_norm", "scale"), kv_stack("self_attn.k_norm.weight", False))

        scalars = []
        for i in range(L):
            try:
                scalars.append(np.asarray(lay(i, "layer_scalar")).reshape(1))
            except KeyError:
                scalars.append(np.ones((1,), np.float32))
        put(("layers", "layer_scalar"), np.stack(scalars))

        for proj in ("gate_proj", "up_proj", "down_proj"):
            put(("layers", proj, "kernel"), stackT(f"mlp.{proj}.weight"))

        put(("layers", "router", "proj", "kernel"), stackT("router.proj.weight"))
        put(("layers", "router", "scale"), stack_("router.scale"))

        gates, ups, downs = [], [], []
        for i in range(L):
            gu = np.asarray(lay(i, "moe.gate_up_proj"))        # (E, 2I, H)
            dn = np.asarray(lay(i, "moe.down_proj"))           # (E, H, I)
            try:
                pes = np.asarray(lay(i, "moe.per_expert_scale"))
            except KeyError:
                pes = np.ones((gu.shape[0],), gu.dtype)
            guT = np.swapaxes(gu, -1, -2)                      # (E, H, 2I)
            gates.append(guT[..., :I])
            ups.append(guT[..., I:])
            downs.append(np.swapaxes(dn, -1, -2) * pes[:, None, None])
        put(("layers", "experts", "gate_proj", "kernel"), np.stack(gates))
        put(("layers", "experts", "up_proj", "kernel"), np.stack(ups))
        put(("layers", "experts", "down_proj", "kernel"), np.stack(downs))
        return params

    def to_hf(self, params):
        import numpy as np

        cfg = self.cfg
        L = cfg.num_layers
        prefix = "model.language_model."

        def _t(x):
            return np.ascontiguousarray(np.asarray(x).T)

        yield prefix + "embed_tokens.weight", np.asarray(params["embed"]["embedding"])
        yield prefix + "norm.weight", np.asarray(params["final_norm"]["scale"])
        if not cfg.tie_word_embeddings and "lm_head" in params:
            yield "lm_head.weight", _t(params["lm_head"]["kernel"])
        lay = params["layers"]
        for i in range(L):
            base = f"{prefix}layers.{i}."
            for hf_name, path in self._NORMS.items():
                node = lay
                for p in path:
                    node = node[p]
                yield base + hf_name + ".weight", np.asarray(node["scale"][i])
            yield base + "self_attn.q_norm.weight", np.asarray(lay["q_norm"]["scale"][i])
            yield base + "self_attn.q_proj.weight", _t(lay["q_proj"]["kernel"][i])
            yield base + "self_attn.o_proj.weight", _t(lay["o_proj"]["kernel"][i])
            if not self._kv_absent(i):
                yield base + "self_attn.k_proj.weight", _t(lay["k_proj"]["kernel"][i])
                yield base + "self_attn.v_proj.weight", _t(lay["v_proj"]["kernel"][i])
                yield base + "self_attn.k_norm.weight", np.asarray(lay["k_norm"]["scale"][i])
            yield base + "layer_scalar", np.asarray(lay["layer_scalar"][i]).reshape(1)
            for proj in ("gate_proj", "up_proj", "down_proj"):
                yield base + f"mlp.{proj}.weight", _t(lay[proj]["kernel"][i])
            yield base + "router.proj.weight", _t(lay["router"]["proj"]["kernel"][i])
            yield base + "router.scale", np.asarray(lay["router"]["scale"][i])
            g = np.asarray(lay["experts"]["gate_proj"]["kernel"][i])  # (E, H, I)
            u = np.asarray(lay["experts"]["up_proj"]["kernel"][i])
            d = np.asarray(lay["experts"]["down_proj"]["kernel"][i])  # (E, I, H)
            gu = np.swapaxes(np.concatenate([g, u], axis=-1), -1, -2)  # (E, 2I, H)
            yield base + "moe.gate_up_proj", np.ascontiguousarray(gu)
            yield base + "moe.down_proj", np.ascontiguousarray(np.swapaxes(d, -1, -2))
            yield base + "moe.per_expert_scale", np.ones((g.shape[0],), g.dtype)


def _register_adapter():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["gemma4_moe"] = Gemma4MoEAdapter


_register_adapter()
