"""Step-3.5 and MiMo-V2-Flash family adapters for the heterogeneous MoE
decoder (models/moe_lm/het_moe.py).

References: nemo_automodel/components/models/step3p5/ (model.py:235 MoE
mapping, layers.py:183 attention, state_dict_adapter.py stacked-expert
layout) and mimo_v2_flash/ (config.py hybrid_layer_pattern semantics,
model.py:269 per-type sink biases, standard per-expert checkpoint layout).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.models.moe_lm.het_moe import AttnGeom, HetMoEConfig
from automodel_tpu.moe.config import MoEConfig


# ---------------------------------------------------------------------------
# configs
# ---------------------------------------------------------------------------
def step3p5_config(hf: Mapping[str, Any], **overrides) -> HetMoEConfig:
    """Step3p5ForCausalLM: sliding layers re-head via attention_other_setting,
    per-layer rope theta / partial rotary / NoPE, head-wise sigmoid gate,
    moe_layers_enum MoE placement with a separate shared expert."""
    L = int(hf["num_hidden_layers"])
    heads = int(hf["num_attention_heads"])
    kv = int(hf.get("num_attention_groups", heads))
    other = dict(hf.get("attention_other_setting") or {})
    head_dim = int(hf.get("head_dim", hf["hidden_size"] // heads))
    lt_raw = list(hf.get("layer_types") or ["full_attention"] * L)
    layer_types = tuple(
        "sliding" if t == "sliding_attention" else "global" for t in lt_raw
    )
    enum = hf.get("moe_layers_enum")
    if enum is None:
        moe_set = set(range(1, L))
    elif isinstance(enum, str):
        moe_set = {int(i) for i in enum.strip().split(",")}
    elif isinstance(enum, int):
        moe_set = {enum}
    else:
        moe_set = {int(i) for i in enum}
    thetas = hf.get("rope_theta", 10000.0)
    thetas = tuple(thetas) if isinstance(thetas, (list, tuple)) else (float(thetas),) * L
    prf = hf.get("partial_rotary_factors")
    prf = tuple(prf) if prf else (1.0,) * L
    use_rope = hf.get("use_rope_layers")
    use_rope = tuple(bool(b) for b in use_rope) if use_rope else (True,) * L
    use_bias = bool(hf.get("use_moe_router_bias", False))
    act = str(hf.get("moe_router_activation", "softmax"))
    share_dim = hf.get("share_expert_dims") or hf.get("share_expert_dim") or 0
    if isinstance(share_dim, (list, tuple)):
        if len(set(share_dim)) != 1:
            raise NotImplementedError("step3p5 per-layer share_expert_dims")
        share_dim = share_dim[0]
    limits = hf.get("swiglu_limits_shared") or hf.get("swiglu_limits")
    limit = None
    if limits:
        nz = {float(x) for x in limits if x}
        if len(nz) > 1:
            raise NotImplementedError("step3p5 per-layer swiglu limits")
        limit = nz.pop() if nz else None
    moe = MoEConfig(
        n_routed_experts=int(hf["moe_num_experts"]),
        experts_per_token=int(hf.get("moe_top_k", 2)),
        moe_intermediate_size=int(hf.get("moe_intermediate_size", hf["intermediate_size"])),
        score_func="sigmoid" if act == "sigmoid" else "softmax",
        norm_topk_prob=True,
        route_scale=float(hf.get("moe_router_scaling_factor", 1.0)),
        gate_bias_update_speed=0.001 if use_bias else 0.0,
    )
    kw = dict(
        vocab_size=int(hf["vocab_size"]),
        hidden_size=int(hf["hidden_size"]),
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=L,
        layer_types=layer_types,
        global_attn=AttnGeom(num_heads=heads, num_kv_heads=kv, head_dim=head_dim),
        sliding_attn=AttnGeom(
            num_heads=int(other.get("num_attention_heads", heads)),
            num_kv_heads=int(other.get("num_attention_groups", kv)),
            head_dim=head_dim,
            sliding_window=int(hf.get("sliding_window") or 0) or None,
        ),
        qk_norm=True,
        head_gate=bool(hf.get("use_head_wise_attn_gate", False)),
        attention_bias=bool(hf.get("attention_bias", False)),
        rope_thetas=thetas,
        partial_rotary=prf,
        use_rope=use_rope,
        mlp_kinds=tuple("moe" if i in moe_set else "dense" for i in range(L)),
        moe=moe,
        share_expert_dim=int(share_dim),
        swiglu_limit=limit,
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)  # unknown keys raise loudly in HetMoEConfig
    if moe_overrides is not None:
        kw["moe"] = moe_overrides
    return HetMoEConfig(**kw)


def mimo_v2_flash_config(hf: Mapping[str, Any], **overrides) -> HetMoEConfig:
    """MiMoV2FlashForCausalLM: hybrid_layer_pattern (1 = sliding) with
    swa_* head settings, per-type attention-sink biases, DeepSeek-style
    sigmoid routing on every moe_layer_freq layer."""
    L = int(hf["num_hidden_layers"])
    heads = int(hf["num_attention_heads"])
    kv = int(hf.get("num_key_value_heads", heads))
    pattern = hf.get("hybrid_layer_pattern")
    if pattern is None:
        block = hf.get("hybrid_block_size")
        if block:
            pattern = [0 if ((i + 1) % int(block) == 0) else 1 for i in range(L)]
        else:
            pattern = [0 if (i % 6 == 0 or i == L - 1) else 1 for i in range(L)]
    layer_types = tuple("sliding" if p == 1 else "global" for p in pattern)
    freq = hf.get("moe_layer_freq")
    if freq is None:
        freq = [1] * L
    head_dim = int(hf.get("head_dim", hf["hidden_size"] // heads))
    v_dim = int(hf.get("v_head_dim", head_dim) or head_dim)
    prf = float(hf.get("partial_rotary_factor", 1.0))
    moe = MoEConfig(
        n_routed_experts=int(hf["n_routed_experts"]),
        n_shared_experts=int(hf.get("n_shared_experts") or 0),
        experts_per_token=int(hf.get("num_experts_per_tok", 8)),
        n_groups=int(hf.get("n_group", 1)),
        topk_groups=int(hf.get("topk_group", 1)),
        moe_intermediate_size=int(hf["moe_intermediate_size"]),
        score_func="sigmoid" if hf.get("scoring_func", "sigmoid") == "sigmoid" else "softmax",
        norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
        route_scale=float(hf.get("routed_scaling_factor", 1.0) or 1.0),
        gate_bias_update_speed=float(hf.get("bias_update_speed", 0.001)),
    )
    thetas = tuple(
        float(hf.get("swa_rope_theta", 10000.0)) if lt == "sliding"
        else float(hf.get("rope_theta", 5_000_000.0))
        for lt in layer_types
    )
    kw = dict(
        vocab_size=int(hf["vocab_size"]),
        hidden_size=int(hf["hidden_size"]),
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=L,
        layer_types=layer_types,
        global_attn=AttnGeom(
            num_heads=heads, num_kv_heads=kv, head_dim=head_dim,
            v_head_dim=v_dim,
            sinks=bool(hf.get("add_full_attention_sink_bias", False)),
        ),
        sliding_attn=AttnGeom(
            num_heads=int(hf.get("swa_num_attention_heads", heads)),
            num_kv_heads=int(hf.get("swa_num_key_value_heads", kv)),
            head_dim=int(hf.get("swa_head_dim", head_dim) or head_dim),
            v_head_dim=int(hf.get("swa_v_head_dim", v_dim) or v_dim),
            sliding_window=int(hf.get("sliding_window") or 128),
            sinks=bool(hf.get("add_swa_attention_sink_bias", True)),
        ),
        qk_norm=False,
        attention_bias=bool(hf.get("attention_bias", False)),
        rope_thetas=thetas,
        partial_rotary=(prf,) * L,
        use_rope=(True,) * L,
        mlp_kinds=tuple("moe" if f else "dense" for f in freq),
        moe=moe,
        share_expert_dim=0,  # shared experts live inside the MoE block
        rms_norm_eps=float(hf.get("layernorm_epsilon", hf.get("rms_norm_eps", 1e-5))),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)  # unknown keys raise loudly in HetMoEConfig
    if moe_overrides is not None:
        kw["moe"] = moe_overrides
    return HetMoEConfig(**kw)


def minimax_m3_text_config(hf: Mapping[str, Any], **overrides) -> HetMoEConfig:
    """MiniMaxM3SparseForCausalLM (reference: models/minimax_m3_vl/config.py
    MiniMaxM3VLTextConfig + layers.py): single attention geometry with
    per-head GEMMA qk-norm, partial rope (rotary_dim of head_dim), per-layer
    dense-vs-MoE from moe_layer_freq (0 = dense), SwiGLU-OAI dense/shared
    MLPs, sigmoid routing with correction bias + routed scaling, and
    block-level DSA sparse attention on layers selected by
    sparse_attention_config.sparse_attention_freq.

    num_mtp_modules is accepted and DROPPED (the reference VL adapter's
    stage-1 behavior, state_dict_adapter.py:30; MTP for M3 is future work —
    training uses the main CE path only)."""
    L = int(hf["num_hidden_layers"])
    heads = int(hf["num_attention_heads"])
    kv = int(hf.get("num_key_value_heads", heads))
    head_dim = int(hf.get("head_dim", hf["hidden_size"] // heads))
    rotary_dim = int(hf.get("rotary_dim") or round(
        head_dim * float(hf.get("partial_rotary_factor", 1.0))
    ))
    freq = hf.get("moe_layer_freq")
    mlp_kinds = tuple(
        "dense" if (freq is not None and not freq[i]) else "moe" for i in range(L)
    )
    sp_cfg = dict(hf.get("sparse_attention_config") or {})
    if sp_cfg and sp_cfg.get("use_sparse_attention", True):
        sp_freq = sp_cfg.get("sparse_attention_freq")
        sparse = tuple(
            bool(sp_freq[i]) if sp_freq is not None else True for i in range(L)
        )
    else:
        sparse = ()
    n_shared = int(hf.get("n_shared_experts") or 0)
    moe = MoEConfig(
        n_routed_experts=int(hf.get("num_local_experts", hf.get("num_experts", 8))),
        n_shared_experts=0,  # shared expert is the swigluoai share_expert_dim path
        experts_per_token=int(hf.get("num_experts_per_tok", 4)),
        moe_intermediate_size=int(hf["intermediate_size"]),
        score_func=(
            "softmax" if str(hf.get("scoring_func", "sigmoid")).lower() == "softmax"
            else "sigmoid"
        ),
        norm_topk_prob=True,
        route_scale=float(hf.get("routed_scaling_factor", 1.0) or 1.0),
        gate_bias_update_speed=0.001 if bool(hf.get("use_routing_bias", True)) else 0.0,
        expert_activation="swigluoai",
        swiglu_limit=float(hf.get("swiglu_limit", 7.0)),
    )
    kw = dict(
        vocab_size=int(hf["vocab_size"]),
        hidden_size=int(hf["hidden_size"]),
        intermediate_size=int(hf.get("dense_intermediate_size", hf["intermediate_size"])),
        num_layers=L,
        layer_types=("global",) * L,
        global_attn=AttnGeom(num_heads=heads, num_kv_heads=kv, head_dim=head_dim),
        sliding_attn=AttnGeom(num_heads=heads, num_kv_heads=kv, head_dim=head_dim),
        qk_norm=bool(hf.get("use_qk_norm", True)),
        rope_thetas=(float(hf.get("rope_theta", 5_000_000.0)),) * L,
        partial_rotary=(rotary_dim / head_dim,) * L,
        use_rope=(True,) * L,
        mlp_kinds=mlp_kinds,
        moe=moe,
        share_expert_dim=int(hf.get("shared_intermediate_size", hf["intermediate_size"])) * n_shared,
        swiglu_limit=float(hf.get("swiglu_limit", 7.0)),
        dense_activation="swigluoai",
        zero_centered_norm=bool(hf.get("use_gemma_norm", True)),
        sparse_attn=sparse,
        sparse_index_heads=int(sp_cfg.get("sparse_num_index_heads", 1) or 1),
        sparse_index_dim=int(sp_cfg.get("sparse_index_dim", 64) or 64),
        sparse_block_size=int(sp_cfg.get("sparse_block_size", 32) or 32),
        sparse_topk_blocks=int(sp_cfg.get("sparse_topk_blocks", 8) or 8),
        sparse_init_blocks=int(sp_cfg.get("sparse_init_block", 0) or 0),
        sparse_local_blocks=int(sp_cfg.get("sparse_local_block", 1) or 1),
        sparse_score_type=str(sp_cfg.get("sparse_score_type", "max")),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )
    if bool(hf.get("attention_output_gate", False)):
        raise NotImplementedError(
            "minimax_m3 attention_output_gate (the reference rejects it too: "
            "minimax_m3_vl/layers.py:411)"
        )
    moe_overrides = overrides.pop("moe", None)
    kw.update(overrides)  # unknown keys raise loudly in HetMoEConfig
    if moe_overrides is not None:
        kw["moe"] = moe_overrides
    return HetMoEConfig(**kw)


# ---------------------------------------------------------------------------
# state-dict adapter (shared; per-family naming via `style`)
# ---------------------------------------------------------------------------
class HetMoEAdapter:
    """HF ↔ het_moe params.

    style="step3p5": self_attn.{q,k}_norm + g_proj; STACKED expert tensors
    moe.{gate,up,down}_proj.weight (E, I, H)/(E, H, I), router moe.gate.weight
    (E, H) + moe.router_bias, shared expert under share_expert.*.
    style="mimo": standard per-expert mlp.experts.{e}.{proj}.weight, router
    mlp.gate.weight + mlp.gate.e_score_correction_bias, per-layer
    self_attn.attention_sink_bias, shared under mlp.shared_experts.*.
    style="minimax_m3": per-expert block_sparse_moe.experts.{e}.w1/w3/w2
    (gate/up/down), router block_sparse_moe.gate.weight +
    block_sparse_moe.e_score_correction_bias, shared experts under
    block_sparse_moe.shared_experts.* (→ the share_expert_dim shared_mlp),
    indexer self_attn.index_{q,k}_{proj,norm} on sparse layers (reference:
    minimax_m3_vl/state_dict_adapter.py key maps).
    """

    _M3_PROJ = {"gate_proj": "w1", "up_proj": "w3", "down_proj": "w2"}

    def __init__(self, cfg: HetMoEConfig, style: str = "step3p5"):
        self.cfg = cfg
        self.style = style

    # per-layer bookkeeping -------------------------------------------------
    def _index_maps(self):
        from automodel_tpu.models.moe_lm.het_moe import layer_rows

        return layer_rows(self.cfg)

    _IDX_ENTRIES = [
        ("self_attn.index_q_proj.weight", ("index_q_proj", "kernel"), True),
        ("self_attn.index_k_proj.weight", ("index_k_proj", "kernel"), True),
        ("self_attn.index_q_norm.weight", ("index_q_norm", "scale"), False),
        ("self_attn.index_k_norm.weight", ("index_k_norm", "scale"), False),
    ]

    def _attn_entries(self, g: AttnGeom):
        e = [
            ("self_attn.q_proj.weight", ("q_proj", "kernel"), True),
            ("self_attn.k_proj.weight", ("k_proj", "kernel"), True),
            ("self_attn.v_proj.weight", ("v_proj", "kernel"), True),
            ("self_attn.o_proj.weight", ("o_proj", "kernel"), True),
        ]
        if self.cfg.qk_norm:
            e += [
                ("self_attn.q_norm.weight", ("q_norm", "scale"), False),
                ("self_attn.k_norm.weight", ("k_norm", "scale"), False),
            ]
        if self.cfg.head_gate:
            e.append(("self_attn.g_proj.weight", ("g_proj", "kernel"), True))
        if g.sinks:
            e.append(("self_attn.attention_sink_bias", ("sinks",), False))
        return e

    def to_hf(self, params):
        cfg = self.cfg
        E = cfg.moe.n_routed_experts

        def _t(x):
            return np.ascontiguousarray(np.asarray(x).T)

        yield "model.embed_tokens.weight", np.asarray(params["embed"]["embedding"])
        yield "model.norm.weight", np.asarray(params["final_norm"]["scale"])
        if not cfg.tie_word_embeddings:
            yield "lm_head.weight", _t(params["lm_head"]["kernel"])
        for li, lt, a_key, ai, is_moe, mi, is_sparse, spi in self._index_maps():
            base = f"model.layers.{li}."
            yield base + "input_layernorm.weight", np.asarray(
                params["input_norms"]["scale"][li]
            )
            yield base + "post_attention_layernorm.weight", np.asarray(
                params["post_norms"]["scale"][li]
            )
            ap = params[a_key]
            for suf, path, tr in self._attn_entries(cfg.geom(lt)):
                node = ap
                for pseg in path:
                    node = node[pseg]
                x = np.asarray(node[ai])
                yield base + suf, (_t(x) if tr else x)
            if is_sparse:
                for suf, path, tr in self._IDX_ENTRIES:
                    node = params["indexer"]
                    for pseg in path:
                        node = node[pseg]
                    x = np.asarray(node[spi])
                    yield base + suf, (_t(x) if tr else x)
            if not is_moe:
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    yield base + f"mlp.{proj}.weight", _t(
                        params["dense_mlp"][proj]["kernel"][mi]
                    )
                continue
            moe = params["moe"]
            if self.style == "minimax_m3":
                yield base + "block_sparse_moe.gate.weight", _t(
                    np.asarray(moe["gate"]["weight"][mi])
                )
                if "e_score_bias" in moe["gate"]:
                    yield base + "block_sparse_moe.e_score_correction_bias", (
                        np.asarray(moe["gate"]["e_score_bias"][mi])
                    )
                for e in range(E):
                    for proj, w in self._M3_PROJ.items():
                        yield base + f"block_sparse_moe.experts.{e}.{w}.weight", _t(
                            np.asarray(moe["experts"][proj]["kernel"][mi, e])
                        )
                if cfg.share_expert_dim:
                    for proj in ("gate_proj", "up_proj", "down_proj"):
                        yield base + f"block_sparse_moe.shared_experts.{proj}.weight", _t(
                            params["shared_mlp"][proj]["kernel"][mi]
                        )
            elif self.style == "step3p5":
                yield base + "moe.gate.weight", _t(np.asarray(moe["gate"]["weight"][mi]))
                if "e_score_bias" in moe["gate"]:
                    yield base + "moe.router_bias", np.asarray(
                        moe["gate"]["e_score_bias"][mi]
                    )
                # stacked (E, I, H)/(E, H, I): ours are (E, H, I)/(E, I, H)
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    w = np.asarray(moe["experts"][proj]["kernel"][mi])
                    yield base + f"moe.{proj}.weight", np.ascontiguousarray(
                        np.swapaxes(w, -1, -2)
                    )
                if cfg.share_expert_dim:
                    for proj in ("gate_proj", "up_proj", "down_proj"):
                        yield base + f"share_expert.{proj}.weight", _t(
                            params["shared_mlp"][proj]["kernel"][mi]
                        )
            else:  # mimo
                yield base + "mlp.gate.weight", _t(np.asarray(moe["gate"]["weight"][mi]))
                if "e_score_bias" in moe["gate"]:
                    yield base + "mlp.gate.e_score_correction_bias", np.asarray(
                        moe["gate"]["e_score_bias"][mi]
                    )
                for e in range(E):
                    for proj in ("gate_proj", "up_proj", "down_proj"):
                        yield base + f"mlp.experts.{e}.{proj}.weight", _t(
                            np.asarray(moe["experts"][proj]["kernel"][mi, e])
                        )
                if cfg.moe.n_shared_experts:
                    for proj in ("gate_proj", "up_proj", "down_proj"):
                        yield base + f"mlp.shared_experts.{proj}.weight", _t(
                            np.asarray(moe["shared"][proj]["kernel"][mi])
                        )

    def from_hf(self, read, shardings=None) -> dict:
        from automodel_tpu.checkpoint.hf_adapter import _get, _set, memo1_reader

        read = memo1_reader(read)
        cfg = self.cfg
        E = cfg.moe.n_routed_experts
        rows = self._index_maps()
        params: dict = {}

        def put(path, value):
            sh = _get(shardings, path) if shardings is not None else None
            _set(params, path, jax.device_put(value, sh) if sh is not None else jnp.asarray(value))

        def one(name, tr):
            x = np.asarray(read(name))
            return np.ascontiguousarray(x.T) if tr else x

        put(("embed", "embedding"), one("model.embed_tokens.weight", False))
        put(("final_norm", "scale"), one("model.norm.weight", False))
        if not cfg.tie_word_embeddings:
            put(("lm_head", "kernel"), one("lm_head.weight", True))
        put(("input_norms", "scale"), np.stack([
            one(f"model.layers.{li}.input_layernorm.weight", False)
            for li in range(cfg.num_layers)
        ]))
        put(("post_norms", "scale"), np.stack([
            one(f"model.layers.{li}.post_attention_layernorm.weight", False)
            for li in range(cfg.num_layers)
        ]))
        for a_key, lt_name in (("g_attn", "global"), ("s_attn", "sliding")):
            lis = [r for r in rows if r[1] == lt_name]
            if not lis:
                # dummy stack kept for pytree uniformity — placed onto its
                # declared shardings so jitted in_shardings stay consistent
                from automodel_tpu.models.moe_lm.het_moe import _init_attn_group

                dummy = _init_attn_group(cfg, cfg.geom(lt_name), jax.random.key(0), 1)
                sub = _get(shardings, (a_key,)) if shardings is not None else None
                if sub is not None:
                    params[a_key] = jax.tree.map(jax.device_put, dummy, sub)
                else:
                    params[a_key] = jax.tree.map(jnp.asarray, dummy)
                continue
            for suf, path, tr in self._attn_entries(cfg.geom(lt_name)):
                put(
                    (a_key,) + path,
                    np.stack([
                        one(f"model.layers.{li}.{suf}", tr)
                        for (li, *_rest) in lis
                    ]),
                )
        sparse_rows = [r for r in rows if r[6]]
        if sparse_rows:
            for suf, path, tr in self._IDX_ENTRIES:
                put(("indexer",) + path, np.stack([
                    one(f"model.layers.{li}.{suf}", tr)
                    for (li, *_r) in sparse_rows
                ]))
        dense_rows = [r for r in rows if not r[4]]
        if dense_rows:
            for proj in ("gate_proj", "up_proj", "down_proj"):
                put(("dense_mlp", proj, "kernel"), np.stack([
                    one(f"model.layers.{li}.mlp.{proj}.weight", True)
                    for (li, *_r) in dense_rows
                ]))
        moe_rows = [r for r in rows if r[4]]
        if moe_rows:
            if self.style == "minimax_m3":
                put(("moe", "gate", "weight"), np.stack([
                    one(f"model.layers.{li}.block_sparse_moe.gate.weight", True)
                    for (li, *_r) in moe_rows
                ]))
                if cfg.moe.gate_bias_update_speed > 0:
                    put(("moe", "gate", "e_score_bias"), np.stack([
                        one(
                            f"model.layers.{li}.block_sparse_moe."
                            "e_score_correction_bias",
                            False,
                        )
                        for (li, *_r) in moe_rows
                    ]))
                for proj, w in self._M3_PROJ.items():
                    put(("moe", "experts", proj, "kernel"), np.stack([
                        np.stack([
                            one(
                                f"model.layers.{li}.block_sparse_moe."
                                f"experts.{e}.{w}.weight",
                                True,
                            )
                            for e in range(E)
                        ])
                        for (li, *_r) in moe_rows
                    ]))
                if cfg.share_expert_dim:
                    for proj in ("gate_proj", "up_proj", "down_proj"):
                        put(("shared_mlp", proj, "kernel"), np.stack([
                            one(
                                f"model.layers.{li}.block_sparse_moe."
                                f"shared_experts.{proj}.weight",
                                True,
                            )
                            for (li, *_r) in moe_rows
                        ]))
            elif self.style == "step3p5":
                put(("moe", "gate", "weight"), np.stack([
                    one(f"model.layers.{li}.moe.gate.weight", True)
                    for (li, *_r) in moe_rows
                ]))
                if cfg.moe.gate_bias_update_speed > 0:
                    put(("moe", "gate", "e_score_bias"), np.stack([
                        one(f"model.layers.{li}.moe.router_bias", False)
                        for (li, *_r) in moe_rows
                    ]))
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    put(("moe", "experts", proj, "kernel"), np.stack([
                        np.ascontiguousarray(np.swapaxes(
                            np.asarray(read(f"model.layers.{li}.moe.{proj}.weight")),
                            -1, -2,
                        ))
                        for (li, *_r) in moe_rows
                    ]))
                if cfg.share_expert_dim:
                    for proj in ("gate_proj", "up_proj", "down_proj"):
                        put(("shared_mlp", proj, "kernel"), np.stack([
                            one(f"model.layers.{li}.share_expert.{proj}.weight", True)
                            for (li, *_r) in moe_rows
                        ]))
            else:  # mimo
                put(("moe", "gate", "weight"), np.stack([
                    one(f"model.layers.{li}.mlp.gate.weight", True)
                    for (li, *_r) in moe_rows
                ]))
                if cfg.moe.gate_bias_update_speed > 0:
                    put(("moe", "gate", "e_score_bias"), np.stack([
                        one(f"model.layers.{li}.mlp.gate.e_score_correction_bias", False)
                        for (li, *_r) in moe_rows
                    ]))
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    put(("moe", "experts", proj, "kernel"), np.stack([
                        np.stack([
                            one(f"model.layers.{li}.mlp.experts.{e}.{proj}.weight", True)
                            for e in range(E)
                        ])
                        for (li, *_r) in moe_rows
                    ]))
                if cfg.moe.n_shared_experts:
                    for proj in ("gate_proj", "up_proj", "down_proj"):
                        put(("moe", "shared", proj, "kernel"), np.stack([
                            one(f"model.layers.{li}.mlp.shared_experts.{proj}.weight", True)
                            for (li, *_r) in moe_rows
                        ]))
        return params


def _register_adapter():
    from automodel_tpu.checkpoint.hf_adapter import ADAPTERS

    ADAPTERS["het_moe"] = HetMoEAdapter


_register_adapter()
