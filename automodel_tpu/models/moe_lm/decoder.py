"""MoE decoder LM — the engine behind Qwen3-MoE / Mixtral / DeepSeek-style
sparse models.

The analog of the reference's MoE model zoo (reference: nemo_automodel/
components/models/deepseek_v3/model.py:45-263 `DeepseekV3Model`,
qwen3_moe, glm4_moe …). Structure: the first `first_k_dense` layers are
dense decoder layers, the rest replace the gated MLP with the MoE block —
two stacked-layer scans, each rematerialized. Aux (load-balance) loss rides
the scan carry and is returned next to the logits; the recipe adds it to
the CE loss (the `MoEAuxLossAutoScaler` role, reference: moe/megatron/
moe_utils.py:569, without autograd-function tricks).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import (
    dense_init,
    embed_init,
    scan_layers_windowed,
)
from automodel_tpu.models.llm.decoder import (
    TransformerConfig,
    _stack,
    attention_block,
    attention_layer_specs,
    init_attention_layers,
    layer_windows,
    make_freq_for,
    mlp_block,
    unembed,
    _make_constrain,
)
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.layer import init_moe, moe_forward, moe_param_specs
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import rope_frequencies

def deepstack_inject(h, gidx, deepstack_embeds):
    """Add the gidx-th deepstack visual residual when gidx < K (reference:
    qwen3_vl_moe/model.py:419 _deepstack_process — the embeds arrive
    pre-scattered over the sequence, zeros off-image). Shared by the
    training forward and the KV-cache generate prefill, which must inject
    identically for decode to match teacher forcing."""
    if deepstack_embeds is None:
        return h
    K = deepstack_embeds.shape[0]
    inj = jax.lax.dynamic_index_in_dim(
        deepstack_embeds, jnp.clip(gidx, 0, K - 1), 0, keepdims=False
    )
    return h + jnp.where(gidx < K, inj.astype(h.dtype), 0.0)


#: Attention (incl. MLA/DSA) masks by position/segment and MoE routing is
#: per-token, so the CP load-balanced permuted layout is transparent —
#: EXCEPT the MTP head, which shifts in layout order; the recipe gates the
#: permutation on mtp_num_layers == 0.
CP_PERMUTATION_SAFE = True


@dataclasses.dataclass(frozen=True)
class MoETransformerConfig(TransformerConfig):
    moe: MoEConfig = dataclasses.field(default_factory=MoEConfig)
    first_k_dense: int = 0  # deepseek first_k_dense_replace
    mtp_num_layers: int = 0      # depth-1 MTP head when > 0
    mtp_loss_coeff: float = 0.1  # weight of the MTP CE term

    @property
    def num_moe_layers(self) -> int:
        return self.num_layers - self.first_k_dense

    def flops_per_token(self, seq_len: int) -> float:
        """Activated-params FLOPs/token for MFU (routed experts count k/E)."""
        D = self.resolved_head_dim
        H = self.hidden_size
        attn_params = self.attn_params_per_layer()
        dense_mlp = 3 * H * self.intermediate_size
        moe_mlp = (
            3 * H * self.moe.moe_intermediate_size * self.moe.experts_per_token
            + 3 * H * self.moe.shared_intermediate * (1 if self.moe.n_shared_experts else 0)
            + H * self.moe.n_routed_experts  # router
        )
        n_active = (
            self.vocab_size * H * (1 if self.tie_word_embeddings else 2)
            + self.num_layers * attn_params
            + self.first_k_dense * dense_mlp
            + self.num_moe_layers * moe_mlp
        )
        attn_flops = 6 * self.num_layers * self.num_heads * D * seq_len
        return 6.0 * n_active + attn_flops


def init(cfg: MoETransformerConfig, rng: jax.Array) -> dict:
    H, I = cfg.hidden_size, cfg.intermediate_size
    ks = jax.random.split(rng, 6)
    params: dict = {
        "embed": {"embedding": embed_init(ks[0], (cfg.vocab_size, H))},
        "final_norm": {"scale": jnp.ones((H,))},
    }
    if cfg.first_k_dense > 0:
        L = cfg.first_k_dense
        kg, ku, kd = jax.random.split(ks[2], 3)
        dense_layers = init_attention_layers(cfg, ks[1], L)
        dense_layers.update(
            {
                "gate_proj": {"kernel": _stack(dense_init, kg, (H, I), L)},
                "up_proj": {"kernel": _stack(dense_init, ku, (H, I), L)},
                "down_proj": {"kernel": _stack(dense_init, kd, (I, H), L)},
            }
        )
        params["dense_layers"] = dense_layers
    Lm = cfg.num_moe_layers
    moe_layers = init_attention_layers(cfg, ks[3], Lm)
    moe_keys = jax.random.split(ks[4], Lm)
    moe_stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs), *[init_moe(cfg.moe, H, k) for k in moe_keys]
    )
    moe_layers["moe"] = moe_stacked
    params["moe_layers"] = moe_layers
    if cfg.mtp_num_layers > 0:
        from automodel_tpu.models.moe_lm.mtp import init_mtp

        params["mtp"] = init_mtp(cfg, jax.random.fold_in(rng, 777))
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense_init(ks[5], (H, cfg.vocab_size))}
    return params


def param_specs(cfg: MoETransformerConfig) -> dict:
    specs: dict = {
        "embed": {"embedding": ("vocab", "embed")},
        "final_norm": {"scale": ("norm",)},
    }
    mlp_specs = {
        "gate_proj": {"kernel": ("layers", "embed", "mlp")},
        "up_proj": {"kernel": ("layers", "embed", "mlp")},
        "down_proj": {"kernel": ("layers", "mlp", "embed")},
    }
    if cfg.first_k_dense > 0:
        d = attention_layer_specs(cfg)
        d.update(mlp_specs)
        specs["dense_layers"] = d
    m = attention_layer_specs(cfg)
    # prepend the stacked-layers axis to every moe param spec
    m["moe"] = jax.tree.map(
        lambda s: ("layers",) + s,
        moe_param_specs(cfg.moe),
        is_leaf=lambda x: isinstance(x, tuple),
    )
    specs["moe_layers"] = m
    if cfg.mtp_num_layers > 0:
        from automodel_tpu.models.moe_lm.mtp import mtp_param_specs

        specs["mtp"] = mtp_param_specs(cfg)
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"kernel": ("embed", "vocab")}
    return specs


def _pp_moe_layer_setup(moe_layers_params, cfg: MoETransformerConfig, mesh_ctx, freq_for):
    """Per-stage MoE layer fn for the pipeline executors (parallel/pp.py).

    The MoE analog of llm.decoder._pp_layer_setup: inside the pipeline
    shard_map every collective is manual — attention psums its o_proj over
    `tp`, and the dropless expert dispatch issues its all-to-all over `ep`
    confined to THIS stage's step, so it overlaps with other stages'
    compute instead of fencing the whole program (the PP×EP composition,
    TorchTitan-style).

    Layer contract (pp.py `layer_aux=True` / `aux_scale` mode):
      pl_layer(h, lp, pos, seg[, token_mask]) ->
        (h, aux_scalar, {"tokens_per_expert": (E,)})
    aux is this layer's load-balance loss over the shard's LOCAL tokens; the
    executors psum over (data axes, pp). The GPipe forward threads the
    optional per-microbatch token_mask (pad tokens excluded from routing /
    aux, matching the GSPMD scan); the explicit 1F1B/ZB schedules do not —
    pad tokens route normally there (their CE contribution is still masked
    by labels == -100 in the head loss).

    Returns (layers_in, lspecs, pl_layer, extras_specs).
    """
    from jax.sharding import PartitionSpec as P

    from automodel_tpu.moe.experts import (
        dropless_ep_shardmap_body,
        experts_forward_dropless,
        shared_expert_forward,
    )
    from automodel_tpu.moe.gate import gate_forward

    windows = layer_windows(cfg)
    if len(set(windows)) != 1:
        raise NotImplementedError(
            "MoE pipeline with mixed per-layer sliding windows; use the "
            "GSPMD (non-pipelined) path for this model"
        )
    tp = mesh_ctx.sizes["tp"]
    ep = mesh_ctx.sizes["ep"]
    moe_cfg = cfg.moe
    if cfg.attention_type == "mla" and (tp > 1 or mesh_ctx.sizes["cp"] > 1):
        raise NotImplementedError(
            "pp×tp / pp×cp with MLA attention: the manual-collective layer "
            "mode is implemented for standard GQA attention only"
        )
    if moe_cfg.dispatcher != "dropless":
        raise NotImplementedError(
            "MoE inside the pipeline shard_map requires the dropless "
            "dispatcher (the capacity einsum path relies on GSPMD to place "
            "its all-to-all); set model.moe_dispatcher: dropless"
        )
    if moe_cfg.n_routed_experts % max(ep, 1) != 0:
        raise ValueError(
            f"n_routed_experts={moe_cfg.n_routed_experts} not divisible by "
            f"ep={ep}"
        )
    if tp > 1:
        if cfg.num_heads % tp or cfg.num_kv_heads % tp:
            raise ValueError(
                f"pp×tp needs num_heads={cfg.num_heads}, "
                f"num_kv_heads={cfg.num_kv_heads} divisible by tp={tp}"
            )
        if moe_cfg.n_shared_experts > 0 and moe_cfg.shared_intermediate % tp:
            raise ValueError(
                f"pp×tp needs shared_intermediate={moe_cfg.shared_intermediate} "
                f"divisible by tp={tp}"
            )
        cfg_pl = dataclasses.replace(
            cfg,
            num_heads=cfg.num_heads // tp,
            num_kv_heads=cfg.num_kv_heads // tp,
            head_dim=cfg.resolved_head_dim,  # pin before num_heads changes
        )
    else:
        cfg_pl = cfg
    window = windows[0]
    identity = lambda x, axes: x  # noqa: E731  (GSPMD constraints inert here)

    def pl_layer(hh, lp, pos, sg, tok_mask=None):
        h = attention_block(
            hh, lp, cfg_pl, pos, sg, freq_for(window), identity, window,
            mesh_ctx, manual=True,
        )
        x = rms_norm(
            h, lp["post_attn_norm"]["scale"], cfg.rms_norm_eps,
            cfg.zero_centered_norm,
        )
        B, S, H = x.shape
        flat = x.reshape(B * S, H)
        mp = lp["moe"]
        weights, indices, aux, stats = gate_forward(
            mp["gate"], moe_cfg, flat,
            token_mask=None if tok_mask is None else tok_mask.reshape(B * S),
        )
        if ep > 1:
            routed = dropless_ep_shardmap_body(
                mp["experts"], moe_cfg, flat, weights, indices, axis_name="ep"
            )
        else:
            routed = experts_forward_dropless(
                mp["experts"], moe_cfg, flat, weights, indices
            )
        out = routed
        if moe_cfg.n_shared_experts > 0:
            out = out + shared_expert_forward(
                mp["shared"], moe_cfg, flat,
                tp_axis="tp" if tp > 1 else None,  # mlp-dim slices → psum
            )
        h = h + out.reshape(B, S, H).astype(h.dtype)
        return h, aux, {"tokens_per_expert": stats["tokens_per_expert"]}

    lspecs = param_specs(cfg)["moe_layers"]
    extras_specs = {"tokens_per_expert": P("pp", None)}  # stacked layer dim
    return moe_layers_params, lspecs, pl_layer, extras_specs


def _pp_pipeline_compatible(cfg: MoETransformerConfig, mesh_ctx) -> bool:
    """Whether the pipelined (shard_map) MoE path covers this config; the
    out-of-scope remainder falls back to the GSPMD layer scan."""
    use_dsa = cfg.attention_type == "mla" and cfg.dsa_index_topk is not None
    return (
        cfg.first_k_dense == 0
        and cfg.moe.dispatcher == "dropless"
        and not use_dsa
        and len(set(layer_windows(cfg))) == 1
        and not (
            cfg.attention_type == "mla"
            and (mesh_ctx.sizes["tp"] > 1 or mesh_ctx.sizes["cp"] > 1)
        )
        and cfg.moe.n_routed_experts % mesh_ctx.sizes["ep"] == 0
    )


def forward(
    params: dict,
    cfg: MoETransformerConfig,
    input_ids: jnp.ndarray,
    *,
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    token_mask: jnp.ndarray | None = None,  # (B,S) bool; False = pad tokens
    return_stats: bool = False,
    return_routing: bool = False,           # stats["routing"] (Lm, B*S, K)
    routing_override: jnp.ndarray | None = None,  # replay a captured routing
    return_aux_hidden: tuple | None = None,  # EAGLE-3 target-side capture
    inputs_embeds: jnp.ndarray | None = None,  # (B,S,H) — VLM merged embeds
    rope_angles: jnp.ndarray | None = None,    # (B,S,rope_dim/2) MRoPE angles
    deepstack_embeds: jnp.ndarray | None = None,  # (K,B,S,H) injected after layer k<K
) -> tuple:
    """Returns (logits-or-hidden, aux_loss[, stats]).

    `return_aux_hidden=(lo, mid, hi)` additionally captures those layers'
    outputs (global layer indices over dense+moe layers, pre-final-norm),
    stacked (k, B, S, H) — the EAGLE-3 aux-hidden hook (same contract as the
    dense decoder). The first return becomes (out, aux_hidden).

    stats["tokens_per_expert"] is (num_moe_layers, E) — feed it to
    `apply_gate_bias_update` after the optimizer step for DeepSeek aux-free
    balancing (reference: train_ft.py:1164 `update_moe_gate_bias`) and to
    moe load-balance metrics.

    Routing replay (R3, reference: components/moe/router_replay.py): run
    once with `return_routing=True`, pass stats["routing"] back as
    `routing_override` on the training forward — the discrete expert
    selection is pinned while scores/weights recompute from live router
    weights (RL rollout/training mismatch removal).
    """
    from automodel_tpu.models.common.layers import cast_params

    params = cast_params(params, cfg.dtype)  # fp32 master → compute dtype
    B, S = input_ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    constrain = _make_constrain(mesh_ctx, rules)

    if inputs_embeds is not None:
        h = inputs_embeds.astype(cfg.dtype)
    else:
        # FSDP-unshard the table's embed dim before the gather (see llm/decoder)
        tbl = constrain(params["embed"]["embedding"], ("vocab", None))
        h = jnp.take(tbl, input_ids, axis=0).astype(cfg.dtype)
        if cfg.embed_scale != 1.0:
            h = h * jnp.asarray(cfg.embed_scale, cfg.dtype)
    h = constrain(h, ("act_batch", "act_seq", "act_embed"))

    inv_freq = rope_frequencies(cfg.rope_dim, cfg.rope_theta, cfg.rope_scaling)
    freq_for = make_freq_for(cfg, inv_freq)
    if rope_angles is not None:
        # qwen-vl MRoPE: per-token angles precomputed by the VL wrapper
        # (apply_rope detects the ndim>=2 form); window-local thetas don't
        # apply to mrope models
        freq_for = lambda w: rope_angles  # noqa: E731
    windows = layer_windows(cfg)
    Lm, E = cfg.num_moe_layers, cfg.moe.n_routed_experts

    pp_ok = (
        mesh_ctx is not None
        and mesh_ctx.sizes["pp"] > 1
        and _pp_pipeline_compatible(cfg, mesh_ctx)
        and routing_override is None
        and not return_routing
        and return_aux_hidden is None
        and deepstack_embeds is None
        and rope_angles is None
    )
    if pp_ok:
        # Pipelined GPipe forward: one shard_map over the whole mesh, expert
        # A2A confined to each stage's step (see _pp_moe_layer_setup). The
        # GSPMD scan below stays as the fallback for out-of-scope configs
        # (first_k_dense > 0, DSA, capacity dispatcher, deepstack, replay).
        from automodel_tpu.parallel.pp import pipeline_layers

        seg = segment_ids if segment_ids is not None else jnp.zeros_like(positions)
        layers_in, lspecs, pl_layer, extras_specs = _pp_moe_layer_setup(
            params["moe_layers"], cfg, mesh_ctx, freq_for
        )
        h, aux_loss, extras = pipeline_layers(
            h, positions, seg, layers_in, pl_layer, mesh_ctx,
            cfg.pipeline_microbatches, remat_policy=cfg.remat_policy,
            param_logical_specs=lspecs, layer_aux=True,
            extras_specs=extras_specs, token_mask=token_mask,
        )
        h = constrain(h, ("act_batch", "act_seq", "act_embed"))
        h = rms_norm(
            h, params["final_norm"]["scale"], cfg.rms_norm_eps,
            cfg.zero_centered_norm,
        )
        out = h if return_hidden else unembed(params, cfg, h)
        if return_stats:
            return out, aux_loss, {
                "tokens_per_expert": extras["tokens_per_expert"]
            }
        return out, aux_loss

    def _deepstack(h, gidx):
        return deepstack_inject(h, gidx, deepstack_embeds)

    # DSA: lightning-indexer sparse MLA returns an indexer-KL aux that rides
    # the same loss carry as the MoE balance loss (reference: deepseek_v4).
    # GLM IndexShare (reference: glm_moe_dsa/model.py:50): per-layer
    # indexer_types; "shared" layers reuse the running top-k selection, which
    # rides the layer-scan carry, with a traced 0/1 flag riding the xs.
    use_dsa = cfg.attention_type == "mla" and cfg.dsa_index_topk is not None
    idx_types = getattr(cfg, "dsa_indexer_types", None)
    index_share = use_dsa and idx_types is not None
    if index_share:
        assert len(idx_types) == cfg.num_layers, (len(idx_types), cfg.num_layers)
        assert idx_types[0] == "full", "IndexShare: layer 0 must run its indexer"
        idx_flags = jnp.asarray(
            [1 if t == "full" else 0 for t in idx_types], jnp.int32
        )
    else:
        idx_flags = jnp.ones((cfg.num_layers,), jnp.int32)
    # the running selection ((B,S,S) bool for the oracle, (B,S,K) indices
    # for the chunked path) rides the carry ONLY under IndexShare; plain DSA
    # would drag a dead S²-scale buffer through every layer boundary
    if index_share:
        from automodel_tpu.models.llm.mla import dsa_sel_init

        sel0 = dsa_sel_init(cfg, B, S)
    else:
        sel0 = jnp.zeros((1, 1, 1), bool)

    def _attn(h, lp, window, sel, iflag):
        if use_dsa:
            from automodel_tpu.models.llm.mla import mla_sparse_attention_block

            h, aux, sel_new = mla_sparse_attention_block(
                h, lp, cfg, positions, segment_ids, inv_freq, constrain,
                token_mask=token_mask,
                prev_sel=sel if index_share else None,
                indexer_flag=iflag if index_share else None,
            )
            return h, aux, (sel_new if index_share else sel)
        h = attention_block(
            h, lp, cfg, positions, segment_ids, freq_for(window), constrain,
            window, mesh_ctx,
        )
        return h, jnp.float32(0.0), sel

    cap_ids = tuple(return_aux_hidden) if return_aux_hidden is not None else None

    def _capture(auxbuf, gidx, y):
        for j, lid in enumerate(cap_ids):
            auxbuf = auxbuf.at[j].set(jnp.where(gidx == lid, y, auxbuf[j]))
        return auxbuf

    def dense_layer(carry, xs, window):
        h, aux, stats, routing, auxbuf, sel = carry
        lp, gidx, iflag = xs
        h, idx_aux, sel = _attn(h, lp, window, sel, iflag)
        h = mlp_block(h, lp, cfg, constrain)
        h = _deepstack(h, gidx)
        if cap_ids is not None:
            auxbuf = _capture(auxbuf, gidx, h)
        return (h, aux + idx_aux, stats, routing, auxbuf, sel)

    K = cfg.moe.experts_per_token
    replay = routing_override is not None

    def moe_layer(carry, xs, window):
        h, aux, stats, routing, auxbuf, sel = carry
        lp, idx, iflag = xs
        h, idx_aux, sel = _attn(h, lp, window, sel, iflag)
        aux = aux + idx_aux
        x = rms_norm(h, lp["post_attn_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
        forced = routing_override[idx] if replay else None
        moe_out, layer_aux, layer_stats = moe_forward(
            lp["moe"], cfg.moe, x, constrain, token_mask=token_mask,
            mesh_ctx=mesh_ctx, forced_indices=forced,
        )
        h = constrain(h + moe_out, ("act_batch", "act_seq", "act_embed"))
        h = _deepstack(h, idx + cfg.first_k_dense)
        stats = jax.lax.dynamic_update_index_in_dim(
            stats, layer_stats["tokens_per_expert"], idx, 0
        )
        routing = jax.lax.dynamic_update_index_in_dim(
            routing, layer_stats["indices"], idx, 0
        )
        if cap_ids is not None:
            auxbuf = _capture(auxbuf, idx + cfg.first_k_dense, h)
        return (h, aux + layer_aux, stats, routing, auxbuf, sel)

    stats0 = jnp.zeros((Lm, E), jnp.float32)
    routing0 = jnp.zeros((Lm, B * S, K), jnp.int32)
    auxbuf0 = (
        jnp.zeros((len(cap_ids),) + h.shape, h.dtype)
        if cap_ids is not None
        else jnp.zeros((0,) + h.shape, h.dtype)
    )
    carry = (h, jnp.float32(0.0), stats0, routing0, auxbuf0, sel0)
    if cfg.first_k_dense > 0:
        carry = scan_layers_windowed(
            dense_layer, carry,
            (
                params["dense_layers"],
                jnp.arange(cfg.first_k_dense),
                idx_flags[: cfg.first_k_dense],
            ),
            windows[: cfg.first_k_dense],
            remat_policy=cfg.remat_policy, unroll=cfg.scan_unroll,
        )
    carry = scan_layers_windowed(
        moe_layer, carry,
        (params["moe_layers"], jnp.arange(Lm), idx_flags[cfg.first_k_dense :]),
        windows[cfg.first_k_dense :],
        remat_policy=cfg.remat_policy, unroll=cfg.scan_unroll,
    )
    h, aux_loss, tokens_per_expert, routing, aux_hidden, _sel = carry

    h = rms_norm(h, params["final_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
    out = h if return_hidden else unembed(params, cfg, h)
    if cap_ids is not None:
        out = (out, aux_hidden)
    if return_stats:
        stats_out = {"tokens_per_expert": tokens_per_expert}
        if return_routing:
            stats_out["routing"] = routing
        return out, aux_loss, stats_out
    return out, aux_loss


def apply_gate_bias_update(params: dict, cfg: MoETransformerConfig, tokens_per_expert) -> dict:
    """DeepSeek aux-free balancing across all MoE layers at once
    (reference: layers.py:463 update_bias + train_ft.py:1164).
    tokens_per_expert: (num_moe_layers, E) from forward(..., return_stats=True).
    """
    gate = params["moe_layers"]["moe"]["gate"]
    if "e_score_bias" not in gate:
        return params
    err = tokens_per_expert.mean(-1, keepdims=True) - tokens_per_expert
    new_bias = gate["e_score_bias"] + cfg.moe.gate_bias_update_speed * jnp.sign(err)
    new_gate = {**gate, "e_score_bias": new_bias}
    new_moe = {**params["moe_layers"]["moe"], "gate": new_gate}
    new_layers = {**params["moe_layers"], "moe": new_moe}
    return {**params, "moe_layers": new_layers}
