"""Heterogeneous-attention MoE decoder — the Step-3.5 / MiMo-V2-Flash engine.

The analog of the reference's step3p5 (reference: nemo_automodel/components/
models/step3p5/, 2581 LoC) and mimo_v2_flash (mimo_v2_flash/, 1107 LoC)
families. Both interleave TWO attention geometries by `layer_types` — global
layers and sliding-window layers with their OWN head counts (and, for MiMo,
their own qk/v head dims and attention-sink biases) — over a decoder whose
MLPs are per-layer dense or routed-MoE (+ a per-layer shared expert):

- step3p5 (layers.py:183 `Step3p5Attention`): per-head qk-RMSNorm, optional
  head-wise sigmoid gate (g_proj), per-layer rope theta / partial rotary /
  NoPE layers (`use_rope_layers`), clamped swiglu MLPs with per-layer
  limits, arbitrary `moe_layers_enum` MoE placement, separate shared expert.
- mimo_v2_flash (model.py): sliding layers carry swa_* head settings and a
  learnable attention-sink bias; MoE with DeepSeek-style sigmoid routing.

TPU design: stacked parameter groups per attention geometry and per MLP
kind, a python loop over `layer_types` with running per-group indices (the
models/hybrid/qwen3_next idiom — the heterogeneity is static config), all
attention through ops/attention.dot_product_attention (flash on TPU,
sinks/windows/MLA-ish asymmetric v dims native).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init, embed_init
from automodel_tpu.models.llm.decoder import _make_constrain, _stack
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.layer import init_moe, moe_forward, moe_param_specs
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class AttnGeom:
    """One attention geometry (the global or the sliding group)."""

    num_heads: int = 8
    num_kv_heads: int = 4
    head_dim: int = 64
    v_head_dim: Optional[int] = None   # None → head_dim (MiMo swa differs)
    sliding_window: Optional[int] = None
    sinks: bool = False                # learnable per-head sink bias (MiMo)

    @property
    def vd(self) -> int:
        return self.v_head_dim or self.head_dim


@dataclasses.dataclass(frozen=True)
class HetMoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632      # dense-layer MLP width
    num_layers: int = 4
    layer_types: tuple = ()            # "global" | "sliding" per layer
    global_attn: AttnGeom = dataclasses.field(default_factory=AttnGeom)
    sliding_attn: AttnGeom = dataclasses.field(default_factory=AttnGeom)
    qk_norm: bool = True               # per-head-dim RMSNorm on q/k
    head_gate: bool = False            # step3p5 g_proj sigmoid head gate
    attention_bias: bool = False
    # per-layer rope: theta / rotary fraction / enabled (NoPE layers)
    rope_thetas: tuple = ()
    partial_rotary: tuple = ()
    use_rope: tuple = ()
    mlp_kinds: tuple = ()              # "dense" | "moe" per layer
    moe: MoEConfig = dataclasses.field(default_factory=MoEConfig)
    share_expert_dim: int = 0          # per-moe-layer shared expert width
    swiglu_limit: Optional[float] = None  # clamp for dense/shared MLPs
    # "swiglu_clamped": silu(clip(g))·clip(u) (step3p5);
    # "swigluoai": g·sigmoid(1.702g)·(u+1) with gate max-clamp (minimax m3)
    dense_activation: str = "swiglu_clamped"
    zero_centered_norm: bool = False   # gemma (1+w) norms (minimax m3)
    # MiniMax-M3 block-sparse attention: a selection-only lightning indexer
    # picks, per query, the top-k key BLOCKS (reference: minimax_m3_vl/
    # layers.py:318 MiniMaxM3Indexer + select_sparse_blocks)
    sparse_attn: tuple = ()            # per-layer bool; () → none
    sparse_index_heads: int = 1
    sparse_index_dim: int = 64
    sparse_block_size: int = 32
    sparse_topk_blocks: int = 8
    sparse_init_blocks: int = 1
    sparse_local_blocks: int = 1
    sparse_score_type: str = "max"     # "max" | "lse" block reduction
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    logits_soft_cap: Optional[float] = None
    causal: bool = True
    linear_precision: Optional[str] = None  # None | "fp8" | "int8"
    dtype: Any = jnp.bfloat16
    remat_policy: str = "full"
    attn_impl: str = "auto"
    scan_unroll: int = 1
    mtp_num_layers: int = 0  # chassis compatibility

    def __post_init__(self):
        assert len(self.layer_types) == self.num_layers
        assert len(self.mlp_kinds) == self.num_layers
        assert not self.sparse_attn or len(self.sparse_attn) == self.num_layers

    @property
    def num_sparse_layers(self) -> int:
        return sum(1 for s in self.sparse_attn if s)

    def geom(self, lt: str) -> AttnGeom:
        return self.sliding_attn if lt == "sliding" else self.global_attn

    @property
    def num_moe_layers(self) -> int:
        return sum(1 for k in self.mlp_kinds if k == "moe")

    def flops_per_token(self, seq_len: int) -> float:
        H = self.hidden_size
        total = self.vocab_size * H * (1 if self.tie_word_embeddings else 2)
        for i, lt in enumerate(self.layer_types):
            g = self.geom(lt)
            total += H * g.head_dim * (g.num_heads + 2 * g.num_kv_heads)
            total += g.num_heads * g.vd * H
            if self.mlp_kinds[i] == "moe":
                total += 3 * H * self.moe.moe_intermediate_size * self.moe.experts_per_token
                total += 3 * H * self.share_expert_dim
                if self.moe.n_shared_experts:
                    total += 3 * H * self.moe.shared_intermediate
                total += H * self.moe.n_routed_experts  # router
            else:
                total += 3 * H * self.intermediate_size
        attn_flops = sum(
            6.0 * self.geom(lt).num_heads * self.geom(lt).head_dim * seq_len
            for lt in self.layer_types
        )
        return 6.0 * total + attn_flops


def _init_attn_group(cfg: HetMoEConfig, g: AttnGeom, rng, n: int) -> dict:
    H = cfg.hidden_size
    ks = jax.random.split(rng, 6)
    p = {
        "q_proj": {"kernel": _stack(dense_init, ks[0], (H, g.num_heads * g.head_dim), n)},
        "k_proj": {"kernel": _stack(dense_init, ks[1], (H, g.num_kv_heads * g.head_dim), n)},
        "v_proj": {"kernel": _stack(dense_init, ks[2], (H, g.num_kv_heads * g.vd), n)},
        "o_proj": {"kernel": _stack(dense_init, ks[3], (g.num_heads * g.vd, H), n)},
    }
    if cfg.attention_bias:
        for name, width in (
            ("q_proj", g.num_heads * g.head_dim),
            ("k_proj", g.num_kv_heads * g.head_dim),
            ("v_proj", g.num_kv_heads * g.vd),
            ("o_proj", H),
        ):
            p[name]["bias"] = jnp.zeros((n, width))
    if cfg.qk_norm:
        norm1 = jnp.zeros if cfg.zero_centered_norm else jnp.ones
        p["q_norm"] = {"scale": norm1((n, g.head_dim))}
        p["k_norm"] = {"scale": norm1((n, g.head_dim))}
    if cfg.head_gate:
        p["g_proj"] = {"kernel": _stack(dense_init, ks[4], (H, g.num_heads), n)}
    if g.sinks:
        p["sinks"] = jnp.zeros((n, g.num_heads))
    return p


def _attn_group_specs(cfg: HetMoEConfig, g: AttnGeom) -> dict:
    p = {
        "q_proj": {"kernel": ("layers", "embed", "heads")},
        "k_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "v_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "o_proj": {"kernel": ("layers", "heads", "embed")},
    }
    if cfg.attention_bias:
        for name in ("q_proj", "k_proj", "v_proj"):
            p[name]["bias"] = ("layers", "heads")
        p["o_proj"]["bias"] = ("layers", "norm")
    if cfg.qk_norm:
        p["q_norm"] = {"scale": ("layers", "norm")}
        p["k_norm"] = {"scale": ("layers", "norm")}
    if cfg.head_gate:
        p["g_proj"] = {"kernel": ("layers", "embed", None)}
    if g.sinks:
        p["sinks"] = ("layers", "heads")
    return p


def _mlp_stack(cfg: HetMoEConfig, rng, n: int, width: int) -> dict:
    H = cfg.hidden_size
    ks = jax.random.split(rng, 3)
    return {
        "gate_proj": {"kernel": _stack(dense_init, ks[0], (H, width), n)},
        "up_proj": {"kernel": _stack(dense_init, ks[1], (H, width), n)},
        "down_proj": {"kernel": _stack(dense_init, ks[2], (width, H), n)},
    }


_MLP_SPECS = {
    "gate_proj": {"kernel": ("layers", "embed", "mlp")},
    "up_proj": {"kernel": ("layers", "embed", "mlp")},
    "down_proj": {"kernel": ("layers", "mlp", "embed")},
}


def init(cfg: HetMoEConfig, rng: jax.Array) -> dict:
    H = cfg.hidden_size
    L = cfg.num_layers
    n_g = sum(1 for t in cfg.layer_types if t == "global")
    n_s = L - n_g
    n_d = sum(1 for k in cfg.mlp_kinds if k == "dense")
    n_m = L - n_d
    ks = jax.random.split(rng, 9)
    norm1 = jnp.zeros if cfg.zero_centered_norm else jnp.ones
    params: dict = {
        "embed": {"embedding": embed_init(ks[0], (cfg.vocab_size, H))},
        "final_norm": {"scale": norm1((H,))},
        "input_norms": {"scale": norm1((L, H))},
        "post_norms": {"scale": norm1((L, H))},
        "g_attn": _init_attn_group(cfg, cfg.global_attn, ks[1], max(n_g, 1)),
        "s_attn": _init_attn_group(cfg, cfg.sliding_attn, ks[2], max(n_s, 1)),
    }
    n_sp = cfg.num_sparse_layers
    if n_sp:
        Di, Hi = cfg.sparse_index_dim, cfg.sparse_index_heads
        kq, kk = jax.random.split(ks[7])
        params["indexer"] = {
            "index_q_proj": {"kernel": _stack(dense_init, kq, (H, Hi * Di), n_sp)},
            "index_k_proj": {"kernel": _stack(dense_init, kk, (H, Di), n_sp)},
            "index_q_norm": {"scale": norm1((n_sp, Di))},
            "index_k_norm": {"scale": norm1((n_sp, Di))},
        }
    if n_d:
        params["dense_mlp"] = _mlp_stack(cfg, ks[3], n_d, cfg.intermediate_size)
    if n_m:
        params["moe"] = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[init_moe(cfg.moe, H, k) for k in jax.random.split(ks[4], n_m)],
        )
        if cfg.share_expert_dim:
            params["shared_mlp"] = _mlp_stack(cfg, ks[5], n_m, cfg.share_expert_dim)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense_init(ks[6], (H, cfg.vocab_size))}
    return params


def param_specs(cfg: HetMoEConfig) -> dict:
    specs: dict = {
        "embed": {"embedding": ("vocab", "embed")},
        "final_norm": {"scale": ("norm",)},
        "input_norms": {"scale": ("layers", "norm")},
        "post_norms": {"scale": ("layers", "norm")},
        "g_attn": _attn_group_specs(cfg, cfg.global_attn),
        "s_attn": _attn_group_specs(cfg, cfg.sliding_attn),
    }
    if cfg.num_sparse_layers:
        specs["indexer"] = {
            "index_q_proj": {"kernel": ("layers", "embed", "heads")},
            "index_k_proj": {"kernel": ("layers", "embed", None)},
            "index_q_norm": {"scale": ("layers", "norm")},
            "index_k_norm": {"scale": ("layers", "norm")},
        }
    if any(k == "dense" for k in cfg.mlp_kinds):
        specs["dense_mlp"] = _MLP_SPECS
    if cfg.num_moe_layers:
        specs["moe"] = jax.tree.map(
            lambda s: ("layers",) + s,
            moe_param_specs(cfg.moe),
            is_leaf=lambda x: isinstance(x, tuple),
        )
        if cfg.share_expert_dim:
            specs["shared_mlp"] = _MLP_SPECS
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"kernel": ("embed", "vocab")}
    return specs


def _clamped_swiglu(x, lp, i, limit, kind: str = "swiglu_clamped",
                    precision: str | None = None):
    from automodel_tpu.ops.quant import matmul as _mm

    g = _mm(x, lp["gate_proj"]["kernel"][i], precision)
    u = _mm(x, lp["up_proj"]["kernel"][i], precision)
    if kind == "swigluoai":
        from automodel_tpu.moe.experts import gated_combine

        inner = gated_combine(g, u, "swigluoai", limit if limit is not None else 7.0)
    else:
        if limit is not None:
            g = jnp.clip(g, -limit, limit)
            u = jnp.clip(u, -limit, limit)
        inner = jax.nn.silu(g) * u
    return _mm(inner, lp["down_proj"]["kernel"][i], precision)


def layer_rows(cfg: HetMoEConfig):
    """Static per-layer bookkeeping shared by forward, the HF adapter, and
    the KV-cache decode path: (li, layer_type, attn_group_key, attn_index,
    is_moe, mlp_index, is_sparse, sparse_index) per layer."""
    gi = si = di = mi = spi = 0
    rows = []
    for li, lt in enumerate(cfg.layer_types):
        a_key = "s_attn" if lt == "sliding" else "g_attn"
        ai = si if lt == "sliding" else gi
        is_moe = cfg.mlp_kinds[li] == "moe"
        is_sparse = bool(cfg.sparse_attn and cfg.sparse_attn[li])
        rows.append((li, lt, a_key, ai, is_moe, mi if is_moe else di, is_sparse, spi))
        si, gi = si + (lt == "sliding"), gi + (lt != "sliding")
        mi, di = mi + is_moe, di + (not is_moe)
        spi += is_sparse
    return rows


def index_projections(ip, cfg: HetMoEConfig, x, positions, inv_freq, spi):
    """The spi-th lightning indexer's (idx_q (B,S,Hi,Di), idx_k (B,S,Di)) —
    per-head gemma-normed projections + the layer's partial rope, shared by
    the training forward and the decode cache path. The indexer stays in
    full precision (the reference checkpoint keeps index_* unquantized:
    minimax_m3_vl/state_dict_adapter.py:52)."""
    B, S, _ = x.shape
    Hi, Di = cfg.sparse_index_heads, cfg.sparse_index_dim
    eps, zc = cfg.rms_norm_eps, cfg.zero_centered_norm
    idx_q = (x @ ip["index_q_proj"]["kernel"][spi]).reshape(B, S, Hi, Di)
    idx_k = (x @ ip["index_k_proj"]["kernel"][spi]).reshape(B, S, 1, Di)
    idx_q = rms_norm(idx_q, ip["index_q_norm"]["scale"][spi], eps, zc)
    idx_k = rms_norm(idx_k, ip["index_k_norm"]["scale"][spi], eps, zc)
    if inv_freq is not None:
        idx_q = apply_rope(idx_q, positions, inv_freq)
        idx_k = apply_rope(idx_k, positions, inv_freq)
    return idx_q, idx_k[:, :, 0, :]


def select_sparse_blocks(
    idx_q: jnp.ndarray,       # (B, S, Hi, Di) post-norm+rope index queries
    idx_k: jnp.ndarray,       # (B, T, Di) shared index key (post-norm+rope)
    positions: jnp.ndarray,   # (B, S) KEY-ROW position of each query — the
                              # row index in the key buffer, NOT a packed
                              # document-local rope position (the reference's
                              # eager path is row-causal, layers.py:290 tril;
                              # doc gating is a separate AND in the caller)
    *,
    block_size: int,
    topk_blocks: int,
    init_blocks: int,
    local_blocks: int,
    score_type: str = "max",
) -> jnp.ndarray:
    """Per-query top-k key-BLOCK selection (MiniMax-M3 DSA; reference:
    minimax_m3_vl/layers.py:179 select_sparse_blocks). Key-level causal →
    block scores (max|lse) → force-include the first `init_blocks` and the
    query's current block → top-k of the rest. Returns a bool keep mask
    (B, Hi, S, T) expanded back to key granularity — non-differentiable
    hard selection (the indexer is selection-only, as in the reference's
    `disable_index_value=True` branch)."""
    B, S, Hi, Di = idx_q.shape
    T = idx_k.shape[1]
    s = jnp.einsum(
        "bqhd,btd->bhqt", idx_q.astype(jnp.float32), idx_k.astype(jnp.float32)
    ) * (Di ** -0.5)
    kpos = jnp.arange(T)
    causal_key = kpos[None, None, None, :] <= positions[:, None, :, None]
    from automodel_tpu.ops.attention import NEG_INF

    s = jnp.where(causal_key, s, NEG_INF)
    nb = -(-T // block_size)
    pad = nb * block_size - T
    if pad:
        s = jnp.pad(s, ((0, 0), (0, 0), (0, 0), (0, pad)), constant_values=NEG_INF)
    s = s.reshape(B, Hi, S, nb, block_size)
    if score_type == "lse":
        block_score = jax.nn.logsumexp(s, axis=-1)
    else:
        block_score = jnp.max(s, axis=-1)              # (B, Hi, S, nb)
    blk = jnp.arange(nb)
    cur_block = positions // block_size                 # (B, S)
    causal_block = blk[None, None, None, :] <= cur_block[:, None, :, None]
    # force the trailing `local_blocks` blocks (ending at the current one)
    # and the first `init_blocks`. NOTE the reference treats local_blocks as
    # a boolean current-block switch (layers.py:165 `(blk == cur_block) &
    # (local_blocks > 0)`); this generalizes it the way init_blocks already
    # is — identical for the shipped local_blocks ∈ {0, 1} configs.
    forced = (
        blk[None, None, None, :] > (cur_block[:, None, :, None] - local_blocks)
    ) | (blk[None, None, None, :] < init_blocks)
    forced = forced & causal_block
    sel = jnp.where(causal_block, block_score, NEG_INF)
    sel = jnp.where(forced, jnp.inf, sel)
    k_eff = min(topk_blocks, nb)
    top_idx = jax.lax.top_k(sel, k_eff)[1]              # (B, Hi, S, k_eff)
    keep_blocks = jnp.any(
        jax.nn.one_hot(top_idx, nb, dtype=jnp.bool_), axis=-2
    )
    keep_blocks = keep_blocks & causal_block
    keep = jnp.repeat(keep_blocks, block_size, axis=-1)[..., :T]
    return keep & causal_key                            # token-level causal


def sparse_keep_mask(ip, cfg: HetMoEConfig, x, positions, inv_freq, spi,
                     num_heads: int, segment_ids=None):
    """Run the spi-th lightning indexer over normed hidden states `x` and
    return the (B, Hq, S, S) bool keep mask for the main attention
    (reference: MiniMaxM3Indexer.forward — per-head gemma-normed index q +
    single shared index k, same partial rope as the main attention, block
    top-k selection; GQA-expanded across `num_heads`//Hi groups for THIS
    layer's geometry).

    Block selection runs over key-ROW indices (the reference's eager path
    is row-causal); packed documents are handled by the segment AND below.
    `positions` (possibly document-local rope positions) only drive the
    indexer's rope phase."""
    idx_q, idx_k = index_projections(ip, cfg, x, positions, inv_freq, spi)
    B, S = x.shape[:2]
    rows = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    keep = select_sparse_blocks(
        idx_q, idx_k, rows,
        block_size=cfg.sparse_block_size,
        topk_blocks=cfg.sparse_topk_blocks,
        init_blocks=cfg.sparse_init_blocks,
        local_blocks=cfg.sparse_local_blocks,
        score_type=cfg.sparse_score_type,
    )
    if segment_ids is not None:
        same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        keep = keep & same
    return jnp.repeat(keep, num_heads // cfg.sparse_index_heads, axis=1)


def _sparse_masked_attention(q, k, v, keep, scale):
    """GQA attention under an explicit (B, Hq, S, T) bool keep mask (already
    causal) — XLA path; the block-sparse pattern has no flash kernel yet.
    (The head-repeat of `keep` fuses into this `where` under XLA; folding a
    per-head-mask arg into ops/attention.xla_attention would deduplicate the
    two bodies if a third explicit-mask caller appears.)"""
    B, S, Hq, D = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    qg = q.reshape(B, S, Hkv, G, D)
    s = jnp.einsum("bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32)
    s = s * scale
    from automodel_tpu.ops.attention import NEG_INF

    s = jnp.where(keep.reshape(B, Hkv, G, S, T), s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    o = jnp.einsum("bkgst,btkd->bskgd", p, v)
    return o.reshape(B, S, Hq, D)


def forward(
    params: dict,
    cfg: HetMoEConfig,
    input_ids: jnp.ndarray,
    *,
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    token_mask: jnp.ndarray | None = None,
    return_stats: bool = False,
    inputs_embeds: jnp.ndarray | None = None,  # (B,S,H) — VLM merged embeds
    **_ignored,
) -> tuple:
    """Returns (logits-or-hidden, aux_loss[, stats]) — the moe_lm protocol."""
    from automodel_tpu.models.common.layers import cast_params

    params = cast_params(params, cfg.dtype)
    B, S = input_ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    constrain = _make_constrain(mesh_ctx, rules)

    if inputs_embeds is not None:
        h = inputs_embeds.astype(cfg.dtype)
    else:
        tbl = constrain(params["embed"]["embedding"], ("vocab", None))
        h = jnp.take(tbl, input_ids, axis=0).astype(cfg.dtype)
    h = constrain(h, ("act_batch", "act_seq", "act_embed"))

    eps = cfg.rms_norm_eps
    zc = cfg.zero_centered_norm
    prec = cfg.linear_precision
    remat = cfg.remat_policy not in (None, "none")
    aux_total = jnp.float32(0.0)
    stats_rows = []

    from automodel_tpu.ops.quant import matmul as _mm

    for li, lt, gk, ai, is_moe, mi, is_sparse, spi in layer_rows(cfg):
        g = cfg.geom(lt)
        theta = cfg.rope_thetas[li] if cfg.rope_thetas else 10000.0
        frac = cfg.partial_rotary[li] if cfg.partial_rotary else 1.0
        roped = cfg.use_rope[li] if cfg.use_rope else True
        rot = int(g.head_dim * frac) // 2 * 2
        inv_freq = rope_frequencies(rot, theta) if roped and rot else None

        def layer(h, li=li, gk=gk, ai=ai, g=g, inv_freq=inv_freq, is_moe=is_moe,
                  mi=mi, is_sparse=is_sparse, spi=spi):
            lp = params[gk]
            x = rms_norm(h, params["input_norms"]["scale"][li], eps, zc)
            q = _mm(x, lp["q_proj"]["kernel"][ai], prec).reshape(B, S, g.num_heads, g.head_dim)
            k = _mm(x, lp["k_proj"]["kernel"][ai], prec).reshape(B, S, g.num_kv_heads, g.head_dim)
            v = _mm(x, lp["v_proj"]["kernel"][ai], prec).reshape(B, S, g.num_kv_heads, g.vd)
            if cfg.attention_bias:
                q = q + lp["q_proj"]["bias"][ai].reshape(1, 1, g.num_heads, g.head_dim)
                k = k + lp["k_proj"]["bias"][ai].reshape(1, 1, g.num_kv_heads, g.head_dim)
                v = v + lp["v_proj"]["bias"][ai].reshape(1, 1, g.num_kv_heads, g.vd)
            if cfg.qk_norm:
                q = rms_norm(q, lp["q_norm"]["scale"][ai], eps, zc)
                k = rms_norm(k, lp["k_norm"]["scale"][ai], eps, zc)
            if inv_freq is not None:
                q = apply_rope(q, positions, inv_freq)
                k = apply_rope(k, positions, inv_freq)
            q = constrain(q, ("act_batch", "act_seq", "act_heads", None))
            if is_sparse:
                keep = sparse_keep_mask(
                    params["indexer"], cfg, x, positions, inv_freq, spi,
                    g.num_heads, segment_ids=segment_ids,
                )
                attn = _sparse_masked_attention(q, k, v, keep, g.head_dim ** -0.5)
            else:
                sinks = lp["sinks"][ai] if g.sinks else None
                attn = dot_product_attention(
                    q, k, v, causal=cfg.causal, segment_ids=segment_ids,
                    positions=positions, sliding_window=g.sliding_window,
                    sinks=sinks, impl=cfg.attn_impl,
                )
            if cfg.head_gate:
                gate = jax.nn.sigmoid(x @ lp["g_proj"]["kernel"][ai])
                attn = attn * gate[..., :, None].astype(attn.dtype)
            attn = attn.reshape(B, S, g.num_heads * g.vd)
            out = _mm(attn, lp["o_proj"]["kernel"][ai], prec)
            if cfg.attention_bias and "bias" in lp["o_proj"]:
                out = out + lp["o_proj"]["bias"][ai]
            h = constrain(h + out, ("act_batch", "act_seq", "act_embed"))

            x = rms_norm(h, params["post_norms"]["scale"][li], eps, zc)
            if is_moe:
                mp = jax.tree.map(lambda p: p[mi], params["moe"])
                moe_out, aux, st = moe_forward(
                    mp, cfg.moe, x, constrain, token_mask=token_mask,
                    mesh_ctx=mesh_ctx,
                )
                if cfg.share_expert_dim:
                    moe_out = moe_out + _clamped_swiglu(
                        x, params["shared_mlp"], mi, cfg.swiglu_limit,
                        cfg.dense_activation, prec,
                    )
                h = h + moe_out
                extra = (aux, st["tokens_per_expert"])
            else:
                h = h + _clamped_swiglu(
                    x, params["dense_mlp"], mi, cfg.swiglu_limit,
                    cfg.dense_activation, prec,
                )
                extra = (jnp.float32(0.0), None)
            return constrain(h, ("act_batch", "act_seq", "act_embed")), extra

        h, (aux, tpe) = (jax.checkpoint(layer) if remat else layer)(h)
        aux_total = aux_total + aux
        if is_moe:
            stats_rows.append(tpe)

    h = rms_norm(h, params["final_norm"]["scale"], eps, zc)
    if return_hidden:
        out = h
    else:
        kernel = (
            params["embed"]["embedding"].T
            if cfg.tie_word_embeddings
            else params["lm_head"]["kernel"]
        )
        out = jnp.einsum(
            "bsh,hv->bsv", h, kernel.astype(h.dtype),
            preferred_element_type=jnp.float32,
        )
        if cfg.logits_soft_cap is not None:
            out = cfg.logits_soft_cap * jnp.tanh(out / cfg.logits_soft_cap)
    if return_stats:
        stats = {
            "tokens_per_expert": (
                jnp.stack(stats_rows) if stats_rows
                else jnp.zeros((0, cfg.moe.n_routed_experts), jnp.float32)
            )
        }
        return out, aux_total, stats
    return out, aux_total


def apply_gate_bias_update(params: dict, cfg: HetMoEConfig, tokens_per_expert) -> dict:
    """DeepSeek aux-free balancing over the het layout's stacked MoE gates
    (same math as moe_lm/decoder.apply_gate_bias_update; tokens_per_expert
    is (num_moe_layers, E))."""
    gate = params["moe"]["gate"]
    if "e_score_bias" not in gate:
        return params
    err = tokens_per_expert.mean(-1, keepdims=True) - tokens_per_expert
    new_bias = gate["e_score_bias"] + cfg.moe.gate_bias_update_speed * jnp.sign(err)
    new_gate = {**gate, "e_score_bias": new_bias}
    return {**params, "moe": {**params["moe"], "gate": new_gate}}
