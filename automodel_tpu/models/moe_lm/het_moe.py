"""Heterogeneous-attention MoE decoder — the Step-3.5 / MiMo-V2-Flash engine.

The analog of the reference's step3p5 (reference: nemo_automodel/components/
models/step3p5/, 2581 LoC) and mimo_v2_flash (mimo_v2_flash/, 1107 LoC)
families. Both interleave TWO attention geometries by `layer_types` — global
layers and sliding-window layers with their OWN head counts (and, for MiMo,
their own qk/v head dims and attention-sink biases) — over a decoder whose
MLPs are per-layer dense or routed-MoE (+ a per-layer shared expert):

- step3p5 (layers.py:183 `Step3p5Attention`): per-head qk-RMSNorm, optional
  head-wise sigmoid gate (g_proj), per-layer rope theta / partial rotary /
  NoPE layers (`use_rope_layers`), clamped swiglu MLPs with per-layer
  limits, arbitrary `moe_layers_enum` MoE placement, separate shared expert.
- mimo_v2_flash (model.py): sliding layers carry swa_* head settings and a
  learnable attention-sink bias; MoE with DeepSeek-style sigmoid routing.

TPU design: stacked parameter groups per attention geometry and per MLP
kind, a python loop over `layer_types` with running per-group indices (the
models/hybrid/qwen3_next idiom — the heterogeneity is static config), all
attention through ops/attention.dot_product_attention (flash on TPU,
sinks/windows/MLA-ish asymmetric v dims native).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init, embed_init
from automodel_tpu.models.llm.decoder import _make_constrain, _stack
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.layer import init_moe, moe_forward, moe_param_specs
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class AttnGeom:
    """One attention geometry (the global or the sliding group)."""

    num_heads: int = 8
    num_kv_heads: int = 4
    head_dim: int = 64
    v_head_dim: Optional[int] = None   # None → head_dim (MiMo swa differs)
    sliding_window: Optional[int] = None
    sinks: bool = False                # learnable per-head sink bias (MiMo)

    @property
    def vd(self) -> int:
        return self.v_head_dim or self.head_dim


@dataclasses.dataclass(frozen=True)
class HetMoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632      # dense-layer MLP width
    num_layers: int = 4
    layer_types: tuple = ()            # "global" | "sliding" per layer
    global_attn: AttnGeom = dataclasses.field(default_factory=AttnGeom)
    sliding_attn: AttnGeom = dataclasses.field(default_factory=AttnGeom)
    qk_norm: bool = True               # per-head-dim RMSNorm on q/k
    head_gate: bool = False            # step3p5 g_proj sigmoid head gate
    attention_bias: bool = False
    # per-layer rope: theta / rotary fraction / enabled (NoPE layers)
    rope_thetas: tuple = ()
    partial_rotary: tuple = ()
    use_rope: tuple = ()
    mlp_kinds: tuple = ()              # "dense" | "moe" per layer
    moe: MoEConfig = dataclasses.field(default_factory=MoEConfig)
    share_expert_dim: int = 0          # per-moe-layer shared expert width
    swiglu_limit: Optional[float] = None  # clamp for dense/shared MLPs
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    logits_soft_cap: Optional[float] = None
    causal: bool = True
    dtype: Any = jnp.bfloat16
    remat_policy: str = "full"
    attn_impl: str = "auto"
    scan_unroll: int = 1
    mtp_num_layers: int = 0  # chassis compatibility

    def __post_init__(self):
        assert len(self.layer_types) == self.num_layers
        assert len(self.mlp_kinds) == self.num_layers

    def geom(self, lt: str) -> AttnGeom:
        return self.sliding_attn if lt == "sliding" else self.global_attn

    @property
    def num_moe_layers(self) -> int:
        return sum(1 for k in self.mlp_kinds if k == "moe")

    def flops_per_token(self, seq_len: int) -> float:
        H = self.hidden_size
        total = self.vocab_size * H * (1 if self.tie_word_embeddings else 2)
        for i, lt in enumerate(self.layer_types):
            g = self.geom(lt)
            total += H * g.head_dim * (g.num_heads + 2 * g.num_kv_heads)
            total += g.num_heads * g.vd * H
            if self.mlp_kinds[i] == "moe":
                total += 3 * H * self.moe.moe_intermediate_size * self.moe.experts_per_token
                total += 3 * H * self.share_expert_dim
                if self.moe.n_shared_experts:
                    total += 3 * H * self.moe.shared_intermediate
                total += H * self.moe.n_routed_experts  # router
            else:
                total += 3 * H * self.intermediate_size
        attn_flops = sum(
            6.0 * self.geom(lt).num_heads * self.geom(lt).head_dim * seq_len
            for lt in self.layer_types
        )
        return 6.0 * total + attn_flops


def _init_attn_group(cfg: HetMoEConfig, g: AttnGeom, rng, n: int) -> dict:
    H = cfg.hidden_size
    ks = jax.random.split(rng, 6)
    p = {
        "q_proj": {"kernel": _stack(dense_init, ks[0], (H, g.num_heads * g.head_dim), n)},
        "k_proj": {"kernel": _stack(dense_init, ks[1], (H, g.num_kv_heads * g.head_dim), n)},
        "v_proj": {"kernel": _stack(dense_init, ks[2], (H, g.num_kv_heads * g.vd), n)},
        "o_proj": {"kernel": _stack(dense_init, ks[3], (g.num_heads * g.vd, H), n)},
    }
    if cfg.attention_bias:
        for name, width in (
            ("q_proj", g.num_heads * g.head_dim),
            ("k_proj", g.num_kv_heads * g.head_dim),
            ("v_proj", g.num_kv_heads * g.vd),
            ("o_proj", H),
        ):
            p[name]["bias"] = jnp.zeros((n, width))
    if cfg.qk_norm:
        p["q_norm"] = {"scale": jnp.ones((n, g.head_dim))}
        p["k_norm"] = {"scale": jnp.ones((n, g.head_dim))}
    if cfg.head_gate:
        p["g_proj"] = {"kernel": _stack(dense_init, ks[4], (H, g.num_heads), n)}
    if g.sinks:
        p["sinks"] = jnp.zeros((n, g.num_heads))
    return p


def _attn_group_specs(cfg: HetMoEConfig, g: AttnGeom) -> dict:
    p = {
        "q_proj": {"kernel": ("layers", "embed", "heads")},
        "k_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "v_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "o_proj": {"kernel": ("layers", "heads", "embed")},
    }
    if cfg.attention_bias:
        for name in ("q_proj", "k_proj", "v_proj"):
            p[name]["bias"] = ("layers", "heads")
        p["o_proj"]["bias"] = ("layers", "norm")
    if cfg.qk_norm:
        p["q_norm"] = {"scale": ("layers", "norm")}
        p["k_norm"] = {"scale": ("layers", "norm")}
    if cfg.head_gate:
        p["g_proj"] = {"kernel": ("layers", "embed", None)}
    if g.sinks:
        p["sinks"] = ("layers", "heads")
    return p


def _mlp_stack(cfg: HetMoEConfig, rng, n: int, width: int) -> dict:
    H = cfg.hidden_size
    ks = jax.random.split(rng, 3)
    return {
        "gate_proj": {"kernel": _stack(dense_init, ks[0], (H, width), n)},
        "up_proj": {"kernel": _stack(dense_init, ks[1], (H, width), n)},
        "down_proj": {"kernel": _stack(dense_init, ks[2], (width, H), n)},
    }


_MLP_SPECS = {
    "gate_proj": {"kernel": ("layers", "embed", "mlp")},
    "up_proj": {"kernel": ("layers", "embed", "mlp")},
    "down_proj": {"kernel": ("layers", "mlp", "embed")},
}


def init(cfg: HetMoEConfig, rng: jax.Array) -> dict:
    H = cfg.hidden_size
    L = cfg.num_layers
    n_g = sum(1 for t in cfg.layer_types if t == "global")
    n_s = L - n_g
    n_d = sum(1 for k in cfg.mlp_kinds if k == "dense")
    n_m = L - n_d
    ks = jax.random.split(rng, 9)
    params: dict = {
        "embed": {"embedding": embed_init(ks[0], (cfg.vocab_size, H))},
        "final_norm": {"scale": jnp.ones((H,))},
        "input_norms": {"scale": jnp.ones((L, H))},
        "post_norms": {"scale": jnp.ones((L, H))},
        "g_attn": _init_attn_group(cfg, cfg.global_attn, ks[1], max(n_g, 1)),
        "s_attn": _init_attn_group(cfg, cfg.sliding_attn, ks[2], max(n_s, 1)),
    }
    if n_d:
        params["dense_mlp"] = _mlp_stack(cfg, ks[3], n_d, cfg.intermediate_size)
    if n_m:
        params["moe"] = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[init_moe(cfg.moe, H, k) for k in jax.random.split(ks[4], n_m)],
        )
        if cfg.share_expert_dim:
            params["shared_mlp"] = _mlp_stack(cfg, ks[5], n_m, cfg.share_expert_dim)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense_init(ks[6], (H, cfg.vocab_size))}
    return params


def param_specs(cfg: HetMoEConfig) -> dict:
    specs: dict = {
        "embed": {"embedding": ("vocab", "embed")},
        "final_norm": {"scale": ("norm",)},
        "input_norms": {"scale": ("layers", "norm")},
        "post_norms": {"scale": ("layers", "norm")},
        "g_attn": _attn_group_specs(cfg, cfg.global_attn),
        "s_attn": _attn_group_specs(cfg, cfg.sliding_attn),
    }
    if any(k == "dense" for k in cfg.mlp_kinds):
        specs["dense_mlp"] = _MLP_SPECS
    if cfg.num_moe_layers:
        specs["moe"] = jax.tree.map(
            lambda s: ("layers",) + s,
            moe_param_specs(cfg.moe),
            is_leaf=lambda x: isinstance(x, tuple),
        )
        if cfg.share_expert_dim:
            specs["shared_mlp"] = _MLP_SPECS
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"kernel": ("embed", "vocab")}
    return specs


def _clamped_swiglu(x, lp, i, limit):
    g = x @ lp["gate_proj"]["kernel"][i]
    u = x @ lp["up_proj"]["kernel"][i]
    if limit is not None:
        g = jnp.clip(g, -limit, limit)
        u = jnp.clip(u, -limit, limit)
    return (jax.nn.silu(g) * u) @ lp["down_proj"]["kernel"][i]


def forward(
    params: dict,
    cfg: HetMoEConfig,
    input_ids: jnp.ndarray,
    *,
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    token_mask: jnp.ndarray | None = None,
    return_stats: bool = False,
    **_ignored,
) -> tuple:
    """Returns (logits-or-hidden, aux_loss[, stats]) — the moe_lm protocol."""
    from automodel_tpu.models.common.layers import cast_params

    params = cast_params(params, cfg.dtype)
    B, S = input_ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    constrain = _make_constrain(mesh_ctx, rules)

    tbl = constrain(params["embed"]["embedding"], ("vocab", None))
    h = jnp.take(tbl, input_ids, axis=0).astype(cfg.dtype)
    h = constrain(h, ("act_batch", "act_seq", "act_embed"))

    eps = cfg.rms_norm_eps
    remat = cfg.remat_policy not in (None, "none")
    aux_total = jnp.float32(0.0)
    stats_rows = []
    idx = {"g": 0, "s": 0, "d": 0, "m": 0}

    for li, lt in enumerate(cfg.layer_types):
        g = cfg.geom(lt)
        gk = "s_attn" if lt == "sliding" else "g_attn"
        ai = idx["s" if lt == "sliding" else "g"]
        theta = cfg.rope_thetas[li] if cfg.rope_thetas else 10000.0
        frac = cfg.partial_rotary[li] if cfg.partial_rotary else 1.0
        roped = cfg.use_rope[li] if cfg.use_rope else True
        rot = int(g.head_dim * frac) // 2 * 2
        inv_freq = rope_frequencies(rot, theta) if roped and rot else None
        is_moe = cfg.mlp_kinds[li] == "moe"
        mi = idx["m"] if is_moe else idx["d"]

        def layer(h, li=li, gk=gk, ai=ai, g=g, inv_freq=inv_freq, is_moe=is_moe, mi=mi):
            lp = params[gk]
            x = rms_norm(h, params["input_norms"]["scale"][li], eps)
            q = (x @ lp["q_proj"]["kernel"][ai]).reshape(B, S, g.num_heads, g.head_dim)
            k = (x @ lp["k_proj"]["kernel"][ai]).reshape(B, S, g.num_kv_heads, g.head_dim)
            v = (x @ lp["v_proj"]["kernel"][ai]).reshape(B, S, g.num_kv_heads, g.vd)
            if cfg.attention_bias:
                q = q + lp["q_proj"]["bias"][ai].reshape(1, 1, g.num_heads, g.head_dim)
                k = k + lp["k_proj"]["bias"][ai].reshape(1, 1, g.num_kv_heads, g.head_dim)
                v = v + lp["v_proj"]["bias"][ai].reshape(1, 1, g.num_kv_heads, g.vd)
            if cfg.qk_norm:
                q = rms_norm(q, lp["q_norm"]["scale"][ai], eps)
                k = rms_norm(k, lp["k_norm"]["scale"][ai], eps)
            if inv_freq is not None:
                q = apply_rope(q, positions, inv_freq)
                k = apply_rope(k, positions, inv_freq)
            q = constrain(q, ("act_batch", "act_seq", "act_heads", None))
            sinks = lp["sinks"][ai] if g.sinks else None
            attn = dot_product_attention(
                q, k, v, causal=cfg.causal, segment_ids=segment_ids,
                positions=positions, sliding_window=g.sliding_window,
                sinks=sinks, impl=cfg.attn_impl,
            )
            if cfg.head_gate:
                gate = jax.nn.sigmoid(x @ lp["g_proj"]["kernel"][ai])
                attn = attn * gate[..., :, None].astype(attn.dtype)
            attn = attn.reshape(B, S, g.num_heads * g.vd)
            out = attn @ lp["o_proj"]["kernel"][ai]
            if cfg.attention_bias and "bias" in lp["o_proj"]:
                out = out + lp["o_proj"]["bias"][ai]
            h = constrain(h + out, ("act_batch", "act_seq", "act_embed"))

            x = rms_norm(h, params["post_norms"]["scale"][li], eps)
            if is_moe:
                mp = jax.tree.map(lambda p: p[mi], params["moe"])
                moe_out, aux, st = moe_forward(
                    mp, cfg.moe, x, constrain, token_mask=token_mask,
                    mesh_ctx=mesh_ctx,
                )
                if cfg.share_expert_dim:
                    moe_out = moe_out + _clamped_swiglu(
                        x, params["shared_mlp"], mi, cfg.swiglu_limit
                    )
                h = h + moe_out
                extra = (aux, st["tokens_per_expert"])
            else:
                h = h + _clamped_swiglu(x, params["dense_mlp"], mi, cfg.swiglu_limit)
                extra = (jnp.float32(0.0), None)
            return constrain(h, ("act_batch", "act_seq", "act_embed")), extra

        h, (aux, tpe) = (jax.checkpoint(layer) if remat else layer)(h)
        aux_total = aux_total + aux
        if is_moe:
            stats_rows.append(tpe)
            idx["m"] += 1
        else:
            idx["d"] += 1
        idx["s" if lt == "sliding" else "g"] += 1

    h = rms_norm(h, params["final_norm"]["scale"], eps)
    if return_hidden:
        out = h
    else:
        kernel = (
            params["embed"]["embedding"].T
            if cfg.tie_word_embeddings
            else params["lm_head"]["kernel"]
        )
        out = jnp.einsum(
            "bsh,hv->bsv", h, kernel.astype(h.dtype),
            preferred_element_type=jnp.float32,
        )
        if cfg.logits_soft_cap is not None:
            out = cfg.logits_soft_cap * jnp.tanh(out / cfg.logits_soft_cap)
    if return_stats:
        stats = {
            "tokens_per_expert": (
                jnp.stack(stats_rows) if stats_rows
                else jnp.zeros((0, cfg.moe.n_routed_experts), jnp.float32)
            )
        }
        return out, aux_total, stats
    return out, aux_total


def apply_gate_bias_update(params: dict, cfg: HetMoEConfig, tokens_per_expert) -> dict:
    """DeepSeek aux-free balancing over the het layout's stacked MoE gates
    (same math as moe_lm/decoder.apply_gate_bias_update; tokens_per_expert
    is (num_moe_layers, E))."""
    gate = params["moe"]["gate"]
    if "e_score_bias" not in gate:
        return params
    err = tokens_per_expert.mean(-1, keepdims=True) - tokens_per_expert
    new_bias = gate["e_score_bias"] + cfg.moe.gate_bias_update_speed * jnp.sign(err)
    new_gate = {**gate, "e_score_bias": new_bias}
    return {**params, "moe": {**params["moe"], "gate": new_gate}}
