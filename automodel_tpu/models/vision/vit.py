"""Vision transformer encoder (CLIP/SigLIP-style) for VLM towers.

The analog of the reference's vision towers inside its VLM families
(reference: nemo_automodel/components/models/llava_onevision,
qwen3_vl_moe, kimivl … — all wrap a ViT encoder + projector). Functional
pytree style matching the decoders: patchify → linear embed → learned
position embeddings → pre-LN bidirectional transformer (stacked-layer
scan) → final LN.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import dense_init, maybe_remat
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_channels: int = 3
    layer_norm_eps: float = 1e-6
    # CLIP-style towers: class token + pre-LN + quick_gelu; SigLIP: none
    use_cls_token: bool = False
    use_pre_layernorm: bool = False
    activation: str = "gelu_tanh"  # or "quick_gelu"
    # -1 = after final post-LN; -2 = output of the penultimate layer (HF
    # llava's vision_feature_layer), etc.
    feature_layer: int = -1
    dtype: Any = jnp.bfloat16
    remat_policy: str = "full"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def num_positions(self) -> int:
        return self.num_patches + (1 if self.use_cls_token else 0)

    def param_count(self) -> int:
        H, I, L = self.hidden_size, self.intermediate_size, self.num_layers
        return (
            self.patch_size ** 2 * self.num_channels * H
            + self.num_positions * H
            + L * (4 * H * H + 2 * H * I)
        )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def from_hf(cls, hf: dict, **overrides) -> "VisionConfig":
        kw = dict(
            image_size=int(hf.get("image_size", 224)),
            patch_size=int(hf.get("patch_size", 14)),
            hidden_size=int(hf.get("hidden_size", 768)),
            intermediate_size=int(hf.get("intermediate_size", 3072)),
            num_layers=int(hf.get("num_hidden_layers", 12)),
            num_heads=int(hf.get("num_attention_heads", 12)),
            num_channels=int(hf.get("num_channels", 3)),
            layer_norm_eps=float(hf.get("layer_norm_eps", 1e-6)),
        )
        if hf.get("hidden_act") == "quick_gelu":
            kw["activation"] = "quick_gelu"
        kw.update(overrides)
        return cls(**kw)


def init(cfg: VisionConfig, rng: jax.Array) -> dict:
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    D_patch = cfg.patch_size * cfg.patch_size * cfg.num_channels
    ks = jax.random.split(rng, 8)

    def stack(key, shape):
        keys = jax.random.split(key, L)
        return jnp.stack([dense_init(k, shape) for k in keys])

    params = {
        "patch_embed": {
            "kernel": dense_init(ks[0], (D_patch, H)),
            "bias": jnp.zeros((H,)),
        },
        "pos_embed": 0.02 * jax.random.normal(ks[1], (cfg.num_positions, H)),
        "layers": {
            "ln1": {"scale": jnp.ones((L, H)), "bias": jnp.zeros((L, H))},
            "q_proj": {"kernel": stack(ks[2], (H, H)), "bias": jnp.zeros((L, H))},
            "k_proj": {"kernel": stack(ks[3], (H, H)), "bias": jnp.zeros((L, H))},
            "v_proj": {"kernel": stack(ks[4], (H, H)), "bias": jnp.zeros((L, H))},
            "o_proj": {"kernel": stack(ks[5], (H, H)), "bias": jnp.zeros((L, H))},
            "ln2": {"scale": jnp.ones((L, H)), "bias": jnp.zeros((L, H))},
            "fc1": {"kernel": stack(ks[6], (H, I)), "bias": jnp.zeros((L, I))},
            "fc2": {"kernel": stack(ks[7], (I, H)), "bias": jnp.zeros((L, H))},
        },
        "final_ln": {"scale": jnp.ones((H,)), "bias": jnp.zeros((H,))},
    }
    if cfg.use_cls_token:
        params["cls_embed"] = 0.02 * jax.random.normal(
            jax.random.fold_in(rng, 123), (H,)
        )
    if cfg.use_pre_layernorm:
        params["pre_ln"] = {"scale": jnp.ones((H,)), "bias": jnp.zeros((H,))}
    return params


def param_specs(cfg: VisionConfig) -> dict:
    specs = {
        "patch_embed": {"kernel": (None, "embed"), "bias": ("norm",)},
        "pos_embed": (None, "embed"),
        "layers": {
            "ln1": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "q_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "k_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "v_proj": {"kernel": ("layers", "embed", "heads"), "bias": ("layers", "heads")},
            "o_proj": {"kernel": ("layers", "heads", "embed"), "bias": ("layers", "norm")},
            "ln2": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "fc1": {"kernel": ("layers", "embed", "mlp"), "bias": ("layers", "mlp")},
            "fc2": {"kernel": ("layers", "mlp", "embed"), "bias": ("layers", "norm")},
        },
        "final_ln": {"scale": ("norm",), "bias": ("norm",)},
    }
    if cfg.use_cls_token:
        specs["cls_embed"] = ("norm",)
    if cfg.use_pre_layernorm:
        specs["pre_ln"] = {"scale": ("norm",), "bias": ("norm",)}
    return specs


def patchify(images: jnp.ndarray, patch: int) -> jnp.ndarray:
    """(B, H, W, C) → (B, N, patch*patch*C), row-major patches."""
    B, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(B, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, gh * gw, patch * patch * C)


def forward(params: dict, cfg: VisionConfig, images: jnp.ndarray) -> jnp.ndarray:
    """images (B, H, W, C) float → patch features (B, N, hidden)."""
    from automodel_tpu.models.common.layers import cast_params

    params = cast_params(params, cfg.dtype)
    x = patchify(images.astype(cfg.dtype), cfg.patch_size)
    x = x @ params["patch_embed"]["kernel"] + params["patch_embed"]["bias"]
    if cfg.use_cls_token:
        cls = jnp.broadcast_to(params["cls_embed"], (x.shape[0], 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(cfg.dtype)
    if cfg.use_pre_layernorm:
        x = layer_norm(x, params["pre_ln"]["scale"], params["pre_ln"]["bias"], cfg.layer_norm_eps)
    B, N, H = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    eps = cfg.layer_norm_eps

    def layer(x, lp):
        y = layer_norm(x, lp["ln1"]["scale"], lp["ln1"]["bias"], eps)
        q = (y @ lp["q_proj"]["kernel"] + lp["q_proj"]["bias"]).reshape(B, N, nh, hd)
        k = (y @ lp["k_proj"]["kernel"] + lp["k_proj"]["bias"]).reshape(B, N, nh, hd)
        v = (y @ lp["v_proj"]["kernel"] + lp["v_proj"]["bias"]).reshape(B, N, nh, hd)
        a = dot_product_attention(q, k, v, causal=False, impl="xla")
        x = x + a.reshape(B, N, H) @ lp["o_proj"]["kernel"] + lp["o_proj"]["bias"]
        y = layer_norm(x, lp["ln2"]["scale"], lp["ln2"]["bias"], eps)
        y = y @ lp["fc1"]["kernel"] + lp["fc1"]["bias"]
        if cfg.activation == "quick_gelu":
            y = y * jax.nn.sigmoid(1.702 * y)
        else:
            y = jax.nn.gelu(y, approximate=True)
        return x + y @ lp["fc2"]["kernel"] + lp["fc2"]["bias"]

    # feature_layer semantics follow HF hidden_states indexing: -1 = final
    # (post-LN applied), -k = output of layer L+1-k, k>=0 = output of layer k
    # — intermediate selections skip the final post-LN.
    if cfg.feature_layer == -1:
        n_run = cfg.num_layers
    elif cfg.feature_layer < 0:
        n_run = cfg.num_layers + 1 + cfg.feature_layer
    else:
        n_run = cfg.feature_layer
    if not 0 < n_run <= cfg.num_layers:
        raise ValueError(
            f"vision feature_layer={cfg.feature_layer} out of range for "
            f"{cfg.num_layers} layers"
        )
    run_params = jax.tree.map(lambda a: a[:n_run], params["layers"])
    fn = maybe_remat(lambda c, lp: (layer(c, lp), None), cfg.remat_policy)
    x, _ = jax.lax.scan(fn, x, run_params)
    if cfg.feature_layer == -1:
        x = layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"], eps)
    return x
