"""Generic dense decoder LM — the shared engine behind the Llama/Qwen/Mistral/
Gemma model families.

The reference hand-writes one model.py per family
(reference: nemo_automodel/components/models/llama/model.py:71-265,
qwen2, qwen3, mistral3, gemma …); on TPU those families differ only by
config knobs (GQA ratio, qkv bias, qk-norm, sliding windows, soft caps,
tied embeddings), so one functional decoder with a `TransformerConfig`
covers them, and each family module is a thin HF-config adapter
(see models/llm/families.py + models/registry.py, the analog of
_transformers/registry.py:30 MODEL_ARCH_MAPPING).

Architecture is params-as-pytree + stacked-layer `lax.scan` (see
models/common/layers.py). All parallelism is logical-axis annotations
resolved by parallel/sharding.py.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.layers import (
    dense_init,
    embed_init,
    scan_layers,
    scan_layers_windowed,
)
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import RopeScalingConfig, apply_rope, rope_frequencies

#: Attention here is position-causal everywhere (ring attention under cp,
#: position/segment masks otherwise), so a permuted sequence layout — the
#: CP load-balanced head/tail ordering — is numerically transparent. Order-
#: sensitive modules (SSM/linear-attention hybrids) must NOT set this.
CP_PERMUTATION_SAFE = True


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_layers: int = 22
    num_heads: int = 32
    num_kv_heads: int = 4
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rope_scaling: RopeScalingConfig = dataclasses.field(default_factory=RopeScalingConfig)
    rms_norm_eps: float = 1e-5
    attention_bias: bool = False
    qk_norm: bool = False  # qwen3-style per-head-dim RMSNorm on q/k
    # hunyuan applies the per-head qk-norm AFTER rotary instead of before
    qk_norm_after_rope: bool = False
    # MiniMax-M2: RMSNorm over the FLATTENED q/k projections (num_heads*D)
    # before the head reshape, instead of per-head-dim
    # (reference: models/minimax_m2/layers.py:78 "HF MiniMax applies RMSNorm
    # over flattened q/k projection dims before head reshape")
    qk_norm_flat: bool = False
    # GLM/Nemotron partial rotary: rotate only this fraction of head_dim
    partial_rotary_factor: float = 1.0
    # GLM-4 dense rotates interleaved even/odd pairs instead of split halves
    rope_interleaved: bool = False
    # gemma3: sliding-window layers use this rope theta (no scaling) while
    # global layers use rope_theta + rope_scaling
    rope_local_theta: Optional[float] = None
    attn_scale: Optional[float] = None  # None → head_dim**-0.5 (gemma2 overrides)
    sliding_window: Optional[int] = None
    # per-layer "sliding"/"global" types; None → sliding_window on all layers
    layer_types: Optional[tuple] = None
    use_post_norms: bool = False  # gemma2-style norms on the attn/mlp branches
    logits_soft_cap: Optional[float] = None
    attn_soft_cap: Optional[float] = None
    embed_scale: float = 1.0  # gemma multiplies embeddings by sqrt(hidden)
    tie_word_embeddings: bool = False
    activation: str = "silu"
    zero_centered_norm: bool = False  # gemma stores scale-1
    # False → bidirectional attention (retrieval/embedding encoders,
    # reference: models/llama_bidirectional)
    causal: bool = True
    # baichuan NormHead: L2-normalize lm_head rows on every forward
    normalized_lm_head: bool = False
    # gpt-oss: learnable per-head sink logits in the softmax denominator
    attention_sinks: bool = False
    o_proj_bias: bool = False  # gpt-oss biases ALL four attention projections
    # attention flavor: "gqa" (default) or "mla" (DeepSeek latent attention)
    attention_type: str = "gqa"
    mla_q_lora_rank: Optional[int] = None
    mla_kv_lora_rank: int = 512
    mla_qk_nope_head_dim: int = 128
    mla_qk_rope_head_dim: int = 64
    mla_v_head_dim: int = 128
    # Mistral-4 llama4-style position-dependent q-rope scaling
    # (reference: mistral4/model.py:52 _get_llama_4_attn_scale):
    # q_pe *= 1 + beta * log(1 + floor(pos / orig_max)); None = off
    mla_qpe_scaling_beta: Optional[float] = None
    mla_qpe_scaling_orig_max: int = 8192
    # DSA (DeepSeek sparse attention, V3.2/V4): lightning-indexer top-k
    # sparse MLA. None → dense MLA. (reference: deepseek_v4/layers.py)
    dsa_index_topk: Optional[int] = None
    dsa_index_n_heads: int = 4
    dsa_index_head_dim: int = 64
    dsa_indexer_loss_coeff: float = 0.01
    # "deepseek": lightning indexer on hidden states, full-head rope.
    # "glm": GLM-5.x variant — queries from the MLA q-lora residual,
    # LayerNorm'd keys, rope-first half-split slice, n_heads**-0.5 gate
    # scaling (reference: glm_moe_dsa/layers.py GlmMoeDsaIndexer).
    dsa_indexer_style: str = "deepseek"
    # GLM IndexShare: per-layer "full" (runs its own indexer) | "shared"
    # (reuses the previous full layer's top-k selection). None → all full.
    dsa_indexer_types: Optional[tuple] = None
    # "oracle": dense (S,S) mask formulation (exact, test reference).
    # "chunked": blockwise two-phase sparse path — per-query-block indexer
    # scores + top-k, then gather-based absorbed MLA over the selected kv
    # latents; peak memory O(S·block) instead of O(S²) (the 32k-context
    # path; reference: deepseek_v4/kernels/tilelang_sparse_mla_fwd.py).
    # "auto": chunked once S > dsa_query_block·4.
    dsa_impl: str = "auto"
    dsa_query_block: int = 256
    # execution knobs
    dtype: Any = jnp.bfloat16
    remat_policy: str = "full"
    scan_unroll: int = 1
    attn_impl: str = "auto"
    pipeline_microbatches: int = 2  # used when the mesh has pp > 1
    # "gpipe": forward pipeline_layers + autodiff (stashes all M microbatch
    # boundary activations). "1f1b": explicit fwd/bwd interleave with the
    # 1F1B memory bound (≤ pp stashed microbatches per stage). "interleaved":
    # virtual-stage 1F1B over pp·pipeline_virtual_stages stages mapped
    # cyclically onto the ring — ~V× smaller bubble (reference: distributed/
    # pipelining/functional.py:182 virtual stages, :777 schedule builder).
    # "zb": zero-bubble ZB-H1 — backward split into input-grad (B, critical
    # path) and weight-grad (W, fills drain bubbles) at 1F1B memory.
    pipeline_schedule: str = "gpipe"
    # blockdiag CP (distributed.cp_layout: blockdiag): documents are
    # rank-local (parallel/cp.py BlockDiagContextParallelSharder), so
    # attention runs LOCAL per cp shard instead of the ring — the reference
    # blockdiag_cp/ package's per-document exchange, collapsed to zero
    cp_blockdiag: bool = False
    pipeline_virtual_stages: int = 2  # used when pipeline_schedule=interleaved
    linear_precision: Optional[str] = None  # None | "fp8" | "int8"

    @property
    def resolved_head_dim(self) -> int:
        if self.attention_type == "mla":
            return self.mla_qk_nope_head_dim + self.mla_qk_rope_head_dim
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def rope_dim(self) -> int:
        if self.attention_type == "mla":
            return self.mla_qk_rope_head_dim
        d = round(self.resolved_head_dim * self.partial_rotary_factor)
        return d - (d % 2)

    def attn_params_per_layer(self) -> int:
        """Projection parameter count of one attention block."""
        H = self.hidden_size
        if self.attention_type == "mla":
            dn, dr, dv = (
                self.mla_qk_nope_head_dim,
                self.mla_qk_rope_head_dim,
                self.mla_v_head_dim,
            )
            n = self.num_heads
            q = (
                H * self.mla_q_lora_rank + self.mla_q_lora_rank * n * (dn + dr)
                if self.mla_q_lora_rank
                else H * n * (dn + dr)
            )
            kv = H * (self.mla_kv_lora_rank + dr) + self.mla_kv_lora_rank * n * (dn + dv)
            return q + kv + n * dv * H
        D = self.resolved_head_dim
        return H * (self.num_heads + 2 * self.num_kv_heads) * D + self.num_heads * D * H

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token (fwd+bwd ≈ 6*N + attention term) for MFU."""
        D = self.resolved_head_dim
        n_params = (
            self.vocab_size * self.hidden_size * (1 if self.tie_word_embeddings else 2)
            + self.num_layers
            * (
                self.attn_params_per_layer()
                + 3 * self.hidden_size * self.intermediate_size
            )
        )
        attn_flops = 6 * self.num_layers * self.num_heads * D * seq_len  # 2*2*1.5 causal
        return 6.0 * n_params + attn_flops


def layer_windows(cfg: "TransformerConfig", num_layers: int | None = None) -> tuple:
    """Per-layer static sliding windows (None = global attention)."""
    L = num_layers if num_layers is not None else cfg.num_layers
    if cfg.sliding_window is None:
        return (None,) * L
    if cfg.layer_types is None:
        return (cfg.sliding_window,) * L
    assert len(cfg.layer_types) == L, (len(cfg.layer_types), L)
    return tuple(
        cfg.sliding_window if t == "sliding" else None for t in cfg.layer_types
    )


def mixed_window_xs(windows: tuple, freq_for) -> tuple:
    """Encode static per-layer windows as scan-able arrays: window ints with
    a huge sentinel for None (global attention — the window mask becomes a
    tautology), plus the per-layer rope freq table selected statically."""
    win_arr = jnp.asarray(
        [w if w is not None else (1 << 30) for w in windows], jnp.int32
    )
    freq_arr = jnp.stack([freq_for(w) for w in windows])
    return win_arr, freq_arr


def make_freq_for(cfg: "TransformerConfig", inv_freq):
    """Per-layer-window rope frequency selector.

    gemma3 (`rope_local_base_freq`, reference: transformers
    Gemma3TextConfig): sliding-window layers rotate with a LOCAL unscaled
    theta while global layers use rope_theta + rope_scaling. Window
    grouping is static (scan_layers_windowed groups layers by window), so
    this is a python-level selection with no traced branching."""
    if cfg.rope_local_theta is None:
        return lambda window: inv_freq
    local = rope_frequencies(cfg.rope_dim, cfg.rope_local_theta, None)
    return lambda window: local if window is not None else inv_freq


ACTIVATIONS = {
    "silu": jax.nn.silu,
    "gelu": jax.nn.gelu,
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "relu2": lambda x: jnp.square(jax.nn.relu(x)),
}


# ---------------------------------------------------------------------------
# init / specs
# ---------------------------------------------------------------------------
def _stack(init_fn, key, shape, L):
    keys = jax.random.split(key, L)
    return jnp.stack([init_fn(k, shape) for k in keys])


def init_attention_layers(cfg: TransformerConfig, rng: jax.Array, L: int) -> dict:
    """Attention + norms portion of a layer stack (shared with MoE models)."""
    if cfg.attention_type == "mla":
        from automodel_tpu.models.llm.mla import init_mla_layers

        return init_mla_layers(cfg, rng, L)
    D = cfg.resolved_head_dim
    H = cfg.hidden_size
    ks = jax.random.split(rng, 4)
    layers = {
        "input_norm": {"scale": jnp.ones((L, H))},
        "q_proj": {"kernel": _stack(dense_init, ks[0], (H, cfg.num_heads * D), L)},
        "k_proj": {"kernel": _stack(dense_init, ks[1], (H, cfg.num_kv_heads * D), L)},
        "v_proj": {"kernel": _stack(dense_init, ks[2], (H, cfg.num_kv_heads * D), L)},
        "o_proj": {"kernel": _stack(dense_init, ks[3], (cfg.num_heads * D, H), L)},
        "post_attn_norm": {"scale": jnp.ones((L, H))},
    }
    if cfg.attention_bias:
        layers["q_proj"]["bias"] = jnp.zeros((L, cfg.num_heads * D))
        layers["k_proj"]["bias"] = jnp.zeros((L, cfg.num_kv_heads * D))
        layers["v_proj"]["bias"] = jnp.zeros((L, cfg.num_kv_heads * D))
    if cfg.o_proj_bias:
        layers["o_proj"]["bias"] = jnp.zeros((L, H))
    if cfg.qk_norm:
        layers["q_norm"] = {"scale": jnp.ones((L, D))}
        layers["k_norm"] = {"scale": jnp.ones((L, D))}
    if cfg.qk_norm_flat:
        layers["q_norm"] = {"scale": jnp.ones((L, cfg.num_heads * D))}
        layers["k_norm"] = {"scale": jnp.ones((L, cfg.num_kv_heads * D))}
    if cfg.use_post_norms:
        layers["post_attn_out_norm"] = {"scale": jnp.ones((L, H))}
        layers["post_mlp_norm"] = {"scale": jnp.ones((L, H))}
    if cfg.attention_sinks:
        layers["sinks"] = jnp.zeros((L, cfg.num_heads))
    return layers


def attention_layer_specs(cfg: TransformerConfig) -> dict:
    if cfg.attention_type == "mla":
        from automodel_tpu.models.llm.mla import mla_layer_specs

        return mla_layer_specs(cfg)
    layers = {
        "input_norm": {"scale": ("layers", "norm")},
        "q_proj": {"kernel": ("layers", "embed", "heads")},
        "k_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "v_proj": {"kernel": ("layers", "embed", "kv_heads")},
        "o_proj": {"kernel": ("layers", "heads", "embed")},
        "post_attn_norm": {"scale": ("layers", "norm")},
    }
    if cfg.attention_bias:
        layers["q_proj"]["bias"] = ("layers", "heads")
        layers["k_proj"]["bias"] = ("layers", "kv_heads")
        layers["v_proj"]["bias"] = ("layers", "kv_heads")
    if cfg.o_proj_bias:
        layers["o_proj"]["bias"] = ("layers", "norm")
    if cfg.qk_norm or cfg.qk_norm_flat:
        layers["q_norm"] = {"scale": ("layers", "norm")}
        layers["k_norm"] = {"scale": ("layers", "norm")}
    if cfg.use_post_norms:
        layers["post_attn_out_norm"] = {"scale": ("layers", "norm")}
        layers["post_mlp_norm"] = {"scale": ("layers", "norm")}
    if cfg.attention_sinks:
        layers["sinks"] = ("layers", "heads")
    return layers


def init(cfg: TransformerConfig, rng: jax.Array) -> dict:
    """Build fp32 master params with per-layer weights stacked on dim 0."""
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    ks = jax.random.split(rng, 8)

    layers = init_attention_layers(cfg, ks[0], L)
    layers.update(
        {
            "gate_proj": {"kernel": _stack(dense_init, ks[4], (H, I), L)},
            "up_proj": {"kernel": _stack(dense_init, ks[5], (H, I), L)},
            "down_proj": {"kernel": _stack(dense_init, ks[6], (I, H), L)},
        }
    )
    params = {
        "embed": {"embedding": embed_init(ks[7], (cfg.vocab_size, H))},
        "layers": layers,
        "final_norm": {"scale": jnp.ones((H,))},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense_init(jax.random.fold_in(rng, 99), (H, cfg.vocab_size))}
    return params


def param_specs(cfg: TransformerConfig) -> dict:
    """Logical axis names per param (consumed by parallel/sharding.py)."""
    layers = attention_layer_specs(cfg)
    layers.update(
        {
            "gate_proj": {"kernel": ("layers", "embed", "mlp")},
            "up_proj": {"kernel": ("layers", "embed", "mlp")},
            "down_proj": {"kernel": ("layers", "mlp", "embed")},
        }
    )
    specs = {
        "embed": {"embedding": ("vocab", "embed")},
        "layers": layers,
        "final_norm": {"scale": ("norm",)},
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"kernel": ("embed", "vocab")}
    return specs


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _pp_layer_setup(layers_params, cfg: TransformerConfig, mesh_ctx, freq_for):
    """Shared setup for both pipeline schedules: the per-stage layer fn plus
    the (possibly window-augmented) scanned layer pytree and its logical
    specs. Returns (layers_in, lspecs, pl_layer, uniform_windows).

    Inside the pipeline shard_map, tp is explicit: each tp rank holds a
    head/mlp slice, so the layer cfg carries the LOCAL counts and the layer
    fn psums partial o/down projections over tp (manual=True mode).
    """
    windows = layer_windows(cfg)
    if cfg.attention_type == "mla" and (
        mesh_ctx.sizes["tp"] > 1 or mesh_ctx.sizes["cp"] > 1
    ):
        raise NotImplementedError(
            "pp×tp / pp×cp with MLA attention: the manual-collective "
            "layer mode is implemented for standard GQA attention only"
        )
    tp = mesh_ctx.sizes["tp"]
    if tp > 1:
        if (cfg.num_heads % tp or cfg.num_kv_heads % tp
                or cfg.intermediate_size % tp):
            raise ValueError(
                f"pp×tp needs num_heads={cfg.num_heads}, "
                f"num_kv_heads={cfg.num_kv_heads}, "
                f"intermediate_size={cfg.intermediate_size} divisible by tp={tp}"
            )
        cfg_pl = dataclasses.replace(
            cfg,
            num_heads=cfg.num_heads // tp,
            num_kv_heads=cfg.num_kv_heads // tp,
            intermediate_size=cfg.intermediate_size // tp,
            head_dim=cfg.resolved_head_dim,  # pin before num_heads changes
        )
    else:
        cfg_pl = cfg

    layers_in = layers_params
    lspecs = param_specs(cfg)["layers"]
    if len(set(windows)) == 1:

        def pl_layer(hh, lp, pos, sg):
            return _decoder_layer(
                hh, lp, cfg_pl, pos, sg, freq_for(windows[0]),
                lambda x, axes: x, windows[0], mesh_ctx, manual=True,
            )

        return layers_in, lspecs, pl_layer, True

    # mixed per-layer windows inside the pipeline: the window value and its
    # rope freq table ride the scanned layer pytree (windows are static per
    # layer; only the stage scan makes them traced — the flash kernel folds
    # a traced window into its qwin aux array)
    win_arr, freq_arr = mixed_window_xs(windows, freq_for)
    layers_in = dict(layers_in, _window=win_arr, _freq=freq_arr)
    lspecs = dict(lspecs, _window=("layers",), _freq=("layers", None))

    def pl_layer(hh, lp, pos, sg):
        lp = dict(lp)
        w = lp.pop("_window")
        fr = lp.pop("_freq")
        return _decoder_layer(
            hh, lp, cfg_pl, pos, sg, fr, lambda x, axes: x, w,
            mesh_ctx, manual=True,
        )

    return layers_in, lspecs, pl_layer, False


def make_pp_1f1b_loss_and_grad(cfg: TransformerConfig, mesh_ctx, chunk_size: int = 1024):
    """Explicit 1F1B value-and-grad for the dense AND MoE decoders — the
    training-path analog of `forward` + autodiff under pp, with the 1F1B
    memory bound (at most pp stashed microbatch inputs per stage instead of
    all M boundary activations; reference schedule zoo: distributed/
    pipelining/functional.py:777 — here the schedule is precomputed action
    tables inside one lax.scan, parallel/pp.py:219).

    Returns grad_fn(params, batch, rng) -> (grads, ce_sum_plus_aux, aux)
    pluggable into training.make_train_step(grad_fn=...). The head (final
    norm + lm-head/tied-embed + fused linear CE) runs fused into the last
    stage's backward so logits are never materialized.

    MoE configs (cfg.moe set) run the dropless expert dispatch INSIDE each
    stage's step — the ep all-to-all overlaps with other stages' compute
    (moe_lm.decoder._pp_moe_layer_setup). Their load-balance aux is folded
    into the differentiated scalar pre-scaled by the global label-token
    count (the `combine_losses` contract), and the returned aux dict
    carries `tokens_per_expert` (Lm, E) for gate-bias updates / metrics.
    """
    from automodel_tpu.loss import fused_linear_cross_entropy
    from automodel_tpu.parallel.pp import (
        pipeline_train_1f1b,
        pipeline_train_interleaved,
    )

    tie = cfg.tie_word_embeddings
    is_moe = getattr(cfg, "moe", None) is not None
    layers_key = "moe_layers" if is_moe else "layers"
    if is_moe:
        if getattr(cfg, "first_k_dense", 0) > 0:
            raise NotImplementedError(
                f"pipeline_schedule={cfg.pipeline_schedule} with "
                "first_k_dense > 0 (heterogeneous layer stacks don't fit one "
                "scanned stage pytree); use the default gpipe schedule"
            )
        if getattr(cfg, "mtp_num_layers", 0) > 0:
            raise NotImplementedError(
                f"pipeline_schedule={cfg.pipeline_schedule} with the MTP "
                "head (it shifts outside the pipelined stack); use the "
                "default gpipe schedule"
            )

    def grad_fn(params, batch, rng):
        del rng  # no dropout in the decoder
        ids = batch["input_ids"]
        labels = batch["labels"]
        B, S = ids.shape
        positions = batch.get("positions")
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[None, :], (B, S)
            )
        seg = batch.get("segment_ids")
        if seg is None:
            seg = jnp.zeros_like(positions)
        n = jnp.sum((labels != -100).astype(jnp.float32))

        inv_freq = rope_frequencies(cfg.rope_dim, cfg.rope_theta, cfg.rope_scaling)
        freq_for = make_freq_for(cfg, inv_freq)
        from automodel_tpu.models.common.layers import cast_params

        def cast_layer(fn):
            def wrapped(hh, lp, pos, sg):
                return fn(hh, cast_params(lp, cfg.dtype), pos, sg)

            return wrapped

        if is_moe:
            from automodel_tpu.models.moe_lm.decoder import _pp_moe_layer_setup

            layers_in, lspecs, pl_layer, extras_specs = _pp_moe_layer_setup(
                params[layers_key], cfg, mesh_ctx, freq_for
            )
            # aux contract: each (stage, microbatch) chunk contributes
            # aux·scale to the differentiated sum; scale = n / n_chunks makes
            # the total n·mean(chunk aux) — combine_losses' n·aux with aux
            # the per-microbatch chunk-mean estimator (see pipeline_layers)
            n_chunks = cfg.pipeline_microbatches * math.prod(
                mesh_ctx.sizes[a]
                for a in ("dp_replicate", "dp_shard", "ep", "cp")
            )
            aux_kw = {"aux_scale": n / n_chunks, "extras_specs": extras_specs}
        else:
            layers_in, lspecs, pl_layer, uniform = _pp_layer_setup(
                params[layers_key], cfg, mesh_ctx, freq_for
            )
            if not uniform:
                raise NotImplementedError(
                    f"pipeline_schedule={cfg.pipeline_schedule} with mixed "
                    "per-layer sliding windows (the window aux arrays are "
                    "non-differentiable scan inputs); use gpipe for this model"
                )
            aux_kw = {}
        pl_layer = cast_layer(pl_layer)

        def embed_fwd(embed_p):
            tbl = embed_p["embedding"].astype(cfg.dtype)
            h = jnp.take(tbl, ids, axis=0)
            if cfg.embed_scale != 1.0:
                h = h * jnp.asarray(cfg.embed_scale, cfg.dtype)
            return h

        h, embed_vjp = jax.vjp(embed_fwd, params["embed"])

        head = {"final_norm": params["final_norm"]}
        if tie:
            head["embed"] = params["embed"]
        else:
            head["lm_head"] = params["lm_head"]

        def head_loss(h_mb, head_p, labels_mb):
            hh = rms_norm(
                h_mb, head_p["final_norm"]["scale"], cfg.rms_norm_eps,
                cfg.zero_centered_norm,
            )
            kernel = head_kernel(head_p, cfg)
            ce, _ = fused_linear_cross_entropy(
                hh, kernel.astype(hh.dtype), labels_mb, chunk_size=chunk_size,
                logits_soft_cap=cfg.logits_soft_cap,
            )
            return ce

        if cfg.pipeline_schedule == "interleaved":
            out = pipeline_train_interleaved(
                h, positions, seg, labels, layers_in, pl_layer, head,
                head_loss, mesh_ctx, cfg.pipeline_microbatches,
                cfg.pipeline_virtual_stages, param_logical_specs=lspecs,
                **aux_kw,
            )
        elif cfg.pipeline_schedule == "zb":
            from automodel_tpu.parallel.pp import pipeline_train_zb

            out = pipeline_train_zb(
                h, positions, seg, labels, layers_in, pl_layer, head,
                head_loss, mesh_ctx, cfg.pipeline_microbatches,
                param_logical_specs=lspecs, **aux_kw,
            )
        else:
            out = pipeline_train_1f1b(
                h, positions, seg, labels, layers_in, pl_layer, head,
                head_loss, mesh_ctx, cfg.pipeline_microbatches,
                param_logical_specs=lspecs, **aux_kw,
            )
        if is_moe:
            loss, dh, gl, gh, extras = out
        else:
            loss, dh, gl, gh = out
        (d_embed,) = embed_vjp(dh.astype(h.dtype))
        grads = {layers_key: gl, "final_norm": gh["final_norm"]}
        if tie:
            grads["embed"] = jax.tree.map(jnp.add, d_embed, gh["embed"])
        else:
            grads["embed"] = d_embed
            grads["lm_head"] = gh["lm_head"]
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        aux = {"num_label_tokens": n}
        if is_moe:
            aux["tokens_per_expert"] = extras["tokens_per_expert"]
        return grads, loss, aux

    return grad_fn


def _dense(x, p, precision=None):
    from automodel_tpu.ops.quant import matmul

    y = matmul(x, p["kernel"], precision)
    if "bias" in p:
        y = y + p["bias"]
    return y


def forward(
    params: dict,
    cfg: TransformerConfig,
    input_ids: jnp.ndarray,  # (B, S) int32
    *,
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    mesh_ctx=None,
    rules=None,
    return_hidden: bool = False,
    inputs_embeds: jnp.ndarray | None = None,  # (B,S,H) — VLM merged embeds
    return_aux_hidden: tuple | None = None,    # layer indices → EAGLE-3 aux
) -> jnp.ndarray:
    """Run the decoder. Returns logits (B,S,V) fp32, or hidden (B,S,H) when
    `return_hidden` (pair with loss/linear_ce.py to avoid materializing
    logits — the FusedLinearCrossEntropy analog).

    `return_aux_hidden=(lo, mid, hi)` additionally returns the outputs of
    those layers (pre-final-norm) stacked (k, B, S, H) — the target-side
    hidden capture for EAGLE-3 speculative training (reference:
    components/speculative/eagle/target.py hidden-state hooks; here it is a
    scan-ys selection, no hooks needed). Result becomes (out, aux)."""
    from automodel_tpu.models.common.layers import cast_params

    params = cast_params(params, cfg.dtype)  # fp32 master → compute dtype
    cfg_dtype = cfg.dtype
    B, S = input_ids.shape
    if positions is None:
        positions = jnp.arange(S, dtype=jnp.int32)[None, :].astype(jnp.int32) * jnp.ones((B, 1), jnp.int32)

    constrain = _make_constrain(mesh_ctx, rules)

    if inputs_embeds is not None:
        h = inputs_embeds.astype(cfg_dtype)
    else:
        # FSDP-unshard the table's embed dim before the gather: a gather out
        # of a (vocab×tp, embed×dp_shard) 2-D-sharded table otherwise yields
        # an H-on-dp_shard output the partitioner can only move to the
        # batch-sharded activation layout via involuntary full remat
        tbl = constrain(params["embed"]["embedding"], ("vocab", None))
        h = jnp.take(tbl, input_ids, axis=0).astype(cfg_dtype)
    if cfg.embed_scale != 1.0:
        h = h * jnp.asarray(cfg.embed_scale, cfg_dtype)
    h = constrain(h, ("act_batch", "act_seq", "act_embed"))

    inv_freq = rope_frequencies(cfg.rope_dim, cfg.rope_theta, cfg.rope_scaling)
    freq_for = make_freq_for(cfg, inv_freq)

    if mesh_ctx is not None and mesh_ctx.sizes["pp"] > 1:
        from automodel_tpu.parallel.pp import pipeline_layers

        if return_aux_hidden is not None:
            raise NotImplementedError("aux-hidden capture inside the pp pipeline")
        seg = segment_ids if segment_ids is not None else jnp.zeros_like(positions)
        layers_in, lspecs, pl_layer, _ = _pp_layer_setup(
            params["layers"], cfg, mesh_ctx, freq_for
        )

        h = pipeline_layers(
            h, positions, seg, layers_in, pl_layer, mesh_ctx,
            cfg.pipeline_microbatches, remat_policy=cfg.remat_policy,
            param_logical_specs=lspecs,
        )
        # pin the exit layout: without this the partitioner may propagate the
        # (pp-replicated) head's weight shardings backward into the pipeline
        # boundary and fall into involuntary full remat on the transition
        h = constrain(h, ("act_batch", "act_seq", "act_embed"))
    else:

        def layer(h, lp, window):
            return _decoder_layer(
                h, lp, cfg, positions, segment_ids, freq_for(window), constrain,
                window, mesh_ctx,
            )

        if return_aux_hidden is not None:
            windows = layer_windows(cfg)
            from automodel_tpu.models.common.layers import maybe_remat

            aux_ids = tuple(return_aux_hidden)
            mixed = len(set(windows)) != 1
            if mixed:
                # per-layer windows ride the scan as traced values (the flash
                # kernel folds them into its qwin aux array); rope freqs are
                # selected statically per layer and stacked
                win_xs, freq_xs = mixed_window_xs(windows, freq_for)

            # carry an (A, B, S, H) buffer updated only at the selected
            # layers — never materializes all L per-layer outputs
            def body(carry, xs):
                c, aux = carry
                if mixed:
                    lp, i, w, fr = xs
                    y = _decoder_layer(
                        c, lp, cfg, positions, segment_ids, fr, constrain, w,
                        mesh_ctx,
                    )
                else:
                    lp, i = xs
                    y = layer(c, lp, windows[0])
                for j, lid in enumerate(aux_ids):
                    aux = aux.at[j].set(jnp.where(i == lid, y, aux[j]))
                return (y, aux), None

            xs = (
                (params["layers"], jnp.arange(cfg.num_layers), win_xs, freq_xs)
                if mixed
                else (params["layers"], jnp.arange(cfg.num_layers))
            )
            aux0 = jnp.zeros((len(aux_ids),) + h.shape, h.dtype)
            (h, aux), _ = jax.lax.scan(
                maybe_remat(body, cfg.remat_policy),
                (h, aux0),
                xs,
                unroll=cfg.scan_unroll,
            )
        else:
            h = scan_layers_windowed(
                layer, h, params["layers"], layer_windows(cfg),
                remat_policy=cfg.remat_policy, unroll=cfg.scan_unroll,
            )

    h = rms_norm(h, params["final_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
    out = h if return_hidden else unembed(params, cfg, h)
    if return_aux_hidden is not None:
        return out, aux
    return out


def head_kernel(params: dict, cfg: TransformerConfig) -> jnp.ndarray:
    """(H, V) output-projection kernel: tied/untied, with baichuan NormHead
    L2-normalization per vocab row when cfg.normalized_lm_head."""
    if cfg.tie_word_embeddings:
        kernel = params["embed"]["embedding"].T
    else:
        kernel = params["lm_head"]["kernel"]
    if getattr(cfg, "normalized_lm_head", False):
        # baichuan NormHead (reference: models/baichuan/model.py NormHead):
        # F.normalize over the hidden dim, applied on every training forward
        norm = jnp.sqrt(jnp.sum(kernel.astype(jnp.float32) ** 2, axis=0, keepdims=True))
        kernel = (kernel.astype(jnp.float32) / jnp.maximum(norm, 1e-12)).astype(kernel.dtype)
    return kernel


def unembed(params: dict, cfg: TransformerConfig, h: jnp.ndarray) -> jnp.ndarray:
    """hidden → fp32 logits (with optional tied embeddings / soft cap)."""
    kernel = head_kernel(params, cfg)
    logits = jnp.einsum("bsh,hv->bsv", h, kernel.astype(h.dtype), preferred_element_type=jnp.float32)
    if cfg.logits_soft_cap is not None:
        logits = cfg.logits_soft_cap * jnp.tanh(logits / cfg.logits_soft_cap)
    return logits


def project_qkv(x, lp, cfg: TransformerConfig, positions, inv_freq):
    """q/k/v projections incl. bias, qk-norm, rope, linear precision —
    shared by training attention and the KV-cache generate path."""
    B, S, _ = x.shape
    D = cfg.resolved_head_dim
    q = _dense(x, lp["q_proj"], cfg.linear_precision)
    k = _dense(x, lp["k_proj"], cfg.linear_precision)
    v = _dense(x, lp["v_proj"], cfg.linear_precision)
    if cfg.qk_norm_flat:
        q = rms_norm(q, lp["q_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
        k = rms_norm(k, lp["k_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
    q = q.reshape(B, S, cfg.num_heads, D)
    k = k.reshape(B, S, cfg.num_kv_heads, D)
    v = v.reshape(B, S, cfg.num_kv_heads, D)
    if cfg.qk_norm and not cfg.qk_norm_after_rope:
        q = rms_norm(q, lp["q_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
        k = rms_norm(k, lp["k_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
    q = apply_rope(q, positions, inv_freq, cfg.rope_interleaved)
    k = apply_rope(k, positions, inv_freq, cfg.rope_interleaved)
    if cfg.qk_norm and cfg.qk_norm_after_rope:
        q = rms_norm(q, lp["q_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
        k = rms_norm(k, lp["k_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
    return q, k, v


def mlp_inner(x, lp, cfg: TransformerConfig):
    """Gated MLP core (no norm/residual) — shared with generate."""
    from automodel_tpu.ops.quant import matmul as _mm

    act = ACTIVATIONS[cfg.activation]
    gate = act(_mm(x, lp["gate_proj"]["kernel"], cfg.linear_precision))
    up = _mm(x, lp["up_proj"]["kernel"], cfg.linear_precision)
    return gate * up


def attention_block(h, lp, cfg: TransformerConfig, positions, segment_ids, inv_freq, constrain, sliding_window, mesh_ctx=None, manual=False):
    """Pre-norm attention with residual; shared by dense and MoE decoders.

    When the mesh has cp > 1 the sequence dim is sharded and attention runs
    as ring attention over the cp axis (parallel/cp.py); otherwise the
    backend dispatcher in ops/attention.py picks flash (TPU) or XLA.

    `manual=True` = running INSIDE a full-mesh shard_map (the pp pipeline):
    GSPMD constraints are inert there, so tensor parallelism is explicit —
    lp holds the per-tp-rank head/mlp slice (cfg carries the LOCAL counts)
    and the o_proj partial sum is psum'd over `tp`; cp attention calls the
    in-shard ring directly.
    """
    if cfg.attention_type == "mla":
        from automodel_tpu.models.llm.mla import mla_attention_block

        return mla_attention_block(
            h, lp, cfg, positions, segment_ids, inv_freq, constrain, sliding_window, mesh_ctx
        )
    D = cfg.resolved_head_dim
    B, S, _ = h.shape

    # -- attention ----------------------------------------------------------
    x = rms_norm(h, lp["input_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
    q, k, v = project_qkv(x, lp, cfg, positions, inv_freq)
    q = constrain(q, ("act_batch", "act_seq", "act_heads", None))
    k = constrain(k, ("act_batch", "act_seq", "act_kv_heads", None))
    v = constrain(v, ("act_batch", "act_seq", "act_kv_heads", None))

    sinks = lp.get("sinks") if cfg.attention_sinks else None
    if mesh_ctx is not None and mesh_ctx.sizes["cp"] > 1:
        if cfg.cp_blockdiag and not manual:
            # per-document layout: all keys a query needs are rank-local
            from automodel_tpu.parallel.cp import local_cp_attention

            attn = local_cp_attention(
                q, k, v, positions, segment_ids, mesh_ctx,
                causal=cfg.causal,
                sliding_window=sliding_window,
                logits_soft_cap=cfg.attn_soft_cap,
                scale=cfg.attn_scale,
                sinks=sinks,
                attn_impl=cfg.attn_impl,
            )
        elif manual:
            from automodel_tpu.parallel.cp import ring_attention

            attn = ring_attention(
                q, k, v, positions, segment_ids, axis_name="cp",
                causal=cfg.causal,
                sliding_window=sliding_window,
                logits_soft_cap=cfg.attn_soft_cap,
                scale=cfg.attn_scale,
                sinks=sinks,
                attn_impl=cfg.attn_impl,
            )
        else:
            from automodel_tpu.parallel.cp import ring_dot_product_attention

            attn = ring_dot_product_attention(
                q, k, v, positions, segment_ids, mesh_ctx,
                causal=cfg.causal,
                sliding_window=sliding_window,
                logits_soft_cap=cfg.attn_soft_cap,
                scale=cfg.attn_scale,
                sinks=sinks,
                attn_impl=cfg.attn_impl,
            )
    else:
        attn = dot_product_attention(
            q, k, v,
            causal=cfg.causal,
            segment_ids=segment_ids,
            positions=positions,
            sliding_window=sliding_window,
            logits_soft_cap=cfg.attn_soft_cap,
            scale=cfg.attn_scale,
            sinks=sinks,
            impl=cfg.attn_impl,
        )
    attn = attn.reshape(B, S, cfg.num_heads * D)
    from automodel_tpu.ops.quant import matmul as _mm

    attn_out = _mm(attn, lp["o_proj"]["kernel"], cfg.linear_precision)
    if manual and mesh_ctx is not None and mesh_ctx.sizes["tp"] > 1:
        attn_out = jax.lax.psum(attn_out, "tp")  # partial head-slice sums
    if "bias" in lp["o_proj"]:
        attn_out = attn_out + lp["o_proj"]["bias"]
    if cfg.use_post_norms:
        attn_out = rms_norm(
            attn_out, lp["post_attn_out_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm
        )
    h = h + attn_out
    return constrain(h, ("act_batch", "act_seq", "act_embed"))


def mlp_block(h, lp, cfg: TransformerConfig, constrain, mesh_ctx=None, manual=False):
    """Pre-norm gated MLP with residual. `manual` as in attention_block:
    explicit tp — lp holds the I/tp slice; the down_proj partial is psum'd."""
    from automodel_tpu.ops.quant import matmul as _mm

    x = rms_norm(h, lp["post_attn_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
    mlp = constrain(mlp_inner(x, lp, cfg), ("act_batch", "act_seq", "act_mlp"))
    mlp_out = _mm(mlp, lp["down_proj"]["kernel"], cfg.linear_precision)
    if manual and mesh_ctx is not None and mesh_ctx.sizes["tp"] > 1:
        mlp_out = jax.lax.psum(mlp_out, "tp")
    if cfg.use_post_norms:
        mlp_out = rms_norm(
            mlp_out, lp["post_mlp_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm
        )
    h = h + mlp_out
    return constrain(h, ("act_batch", "act_seq", "act_embed"))


def _decoder_layer(h, lp, cfg: TransformerConfig, positions, segment_ids, inv_freq, constrain, sliding_window, mesh_ctx=None, manual=False):
    h = attention_block(h, lp, cfg, positions, segment_ids, inv_freq, constrain, sliding_window, mesh_ctx, manual)
    return mlp_block(h, lp, cfg, constrain, mesh_ctx, manual)


def _make_constrain(mesh_ctx, rules):
    if mesh_ctx is None:
        return lambda x, axes: x
    from automodel_tpu.parallel.sharding import AxisRules, with_logical_constraint

    rules = rules or AxisRules()

    def constrain(x, axes):
        return with_logical_constraint(x, axes, mesh_ctx, rules)

    return constrain
