"""Model-family adapters: HF config dict → TransformerConfig.

The analog of the reference's per-family model modules + registry
(reference: nemo_automodel/components/models/{llama,qwen2,qwen3,mistral3,
gemma…}/model.py and _transformers/registry.py:30 MODEL_ARCH_MAPPING).
Dense families differ only by config; MoE families live in models/moe_lm/.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp

from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.ops.rope import RopeScalingConfig


def _base_kwargs(hf: Mapping[str, Any]) -> dict:
    hidden = int(hf["hidden_size"])
    heads = int(hf["num_attention_heads"])
    return dict(
        vocab_size=int(hf["vocab_size"]),
        hidden_size=hidden,
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=int(hf["num_hidden_layers"]),
        num_heads=heads,
        num_kv_heads=int(hf.get("num_key_value_heads", heads)),
        head_dim=int(hf["head_dim"]) if hf.get("head_dim") else None,
        max_position_embeddings=int(hf.get("max_position_embeddings", 4096)),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rope_scaling=RopeScalingConfig.from_hf(hf.get("rope_scaling")),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )


def llama_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """LlamaForCausalLM (Llama 2/3/3.x; reference: models/llama/model.py)."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    kw.update(overrides)
    return TransformerConfig(**kw)


def llama_bidirectional_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """LlamaBidirectionalModel / ...ForSequenceClassification — the llama
    retrieval encoder with causal masking removed (reference:
    models/llama_bidirectional/model.py:79). Pooling ('avg'/'cls'/'last',
    hf['pooling']) is applied by the retrieval/seq-cls recipes, not here."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    kw["causal"] = False
    kw.update(overrides)
    return TransformerConfig(**kw)


def mistral_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """MistralForCausalLM (reference: models/mistral3)."""
    kw = _base_kwargs(hf)
    if hf.get("sliding_window"):
        kw["sliding_window"] = int(hf["sliding_window"])
    kw.update(overrides)
    return TransformerConfig(**kw)


def qwen2_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Qwen2ForCausalLM — qkv bias (reference: models/qwen2/model.py)."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = True
    if hf.get("use_sliding_window") and hf.get("sliding_window"):
        kw["sliding_window"] = int(hf["sliding_window"])
        # HF Qwen2 windows only layers >= max_window_layers
        mwl = int(hf.get("max_window_layers", 0))
        kw["layer_types"] = tuple(
            "sliding" if i >= mwl else "global" for i in range(kw["num_layers"])
        )
    kw.update(overrides)
    return TransformerConfig(**kw)


def qwen3_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Qwen3ForCausalLM — qk-norm, no bias (reference: models/qwen3_5)."""
    kw = _base_kwargs(hf)
    kw["qk_norm"] = True
    kw.update(overrides)
    return TransformerConfig(**kw)


def glm4_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Glm4ForCausalLM — partial interleaved rotary, sandwich norms
    (post_self_attn/post_mlp_layernorm) and a fused gate_up MLP handled by
    the glm4 adapter style (reference: transformers modeling_glm4; the
    reference framework ships GLM via glm4_moe — components/models/glm4_moe)."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = bool(hf.get("attention_bias", True))
    kw["partial_rotary_factor"] = float(hf.get("partial_rotary_factor", 0.5))
    kw["rope_interleaved"] = True
    kw["use_post_norms"] = True
    kw.update(overrides)
    return TransformerConfig(**kw)


def ernie4_5_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Ernie4_5ForCausalLM — llama-shaped with GLM-style INTERLEAVED rotary
    (full head_dim), `use_bias` qkv flag, tied embeddings by default
    (reference: models/ernie4_5)."""
    kw = _base_kwargs(hf)
    kw["rope_interleaved"] = True
    kw["attention_bias"] = bool(hf.get("use_bias", False))
    kw["tie_word_embeddings"] = bool(hf.get("tie_word_embeddings", True))
    kw.update(overrides)
    return TransformerConfig(**kw)


def gemma3_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Gemma3ForCausalLM (text tower) — gemma2's zero-centered sandwich
    norms + qk-norm, 5:1 sliding/global layer pattern, and a separate
    unscaled rope theta for sliding layers (`rope_local_base_freq`).
    Reference: the gemma family dirs (gemma4_moe is its successor)."""
    kw = _base_kwargs(hf)
    kw["activation"] = "gelu_tanh"
    kw["zero_centered_norm"] = True
    kw["use_post_norms"] = True
    kw["qk_norm"] = True
    kw["embed_scale"] = float(kw["hidden_size"]) ** 0.5
    kw["rms_norm_eps"] = float(hf.get("rms_norm_eps", 1e-6))
    kw["tie_word_embeddings"] = bool(hf.get("tie_word_embeddings", True))
    if hf.get("query_pre_attn_scalar"):
        kw["attn_scale"] = float(hf["query_pre_attn_scalar"]) ** -0.5
    if hf.get("final_logit_softcapping"):
        kw["logits_soft_cap"] = float(hf["final_logit_softcapping"])
    if hf.get("sliding_window"):
        kw["sliding_window"] = int(hf["sliding_window"])
        n_layers = kw["num_layers"]
        if hf.get("layer_types"):
            kw["layer_types"] = tuple(
                "sliding" if t == "sliding_attention" else "global"
                for t in hf["layer_types"]
            )
        else:
            # gemma3 default: every 6th layer global, the rest sliding
            pattern = int(hf.get("sliding_window_pattern", 6))
            kw["layer_types"] = tuple(
                "global" if (i + 1) % pattern == 0 else "sliding"
                for i in range(n_layers)
            )
        if hf.get("rope_local_base_freq"):
            kw["rope_local_theta"] = float(hf["rope_local_base_freq"])
    kw.update(overrides)
    return TransformerConfig(**kw)


def hunyuan_dense_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """HunYuanDenseV1ForCausalLM (reference: models/hy_mt2/hy_v3 family):
    llama-shaped with an unconditional per-head qk-norm applied AFTER
    rotary (query/key_layernorm)."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    kw["qk_norm"] = True
    kw["qk_norm_after_rope"] = True
    kw.update(overrides)
    return TransformerConfig(**kw)


def gemma2_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Gemma2: zero-centered 4-norm layers, embed scaling, soft caps,
    query_pre_attn_scalar attention scale, alternating sliding/global."""
    kw = _base_kwargs(hf)
    kw["activation"] = "gelu_tanh"
    kw["zero_centered_norm"] = True
    kw["use_post_norms"] = True
    kw["embed_scale"] = float(kw["hidden_size"]) ** 0.5
    if hf.get("final_logit_softcapping"):
        kw["logits_soft_cap"] = float(hf["final_logit_softcapping"])
    if hf.get("attn_logit_softcapping"):
        kw["attn_soft_cap"] = float(hf["attn_logit_softcapping"])
    if hf.get("query_pre_attn_scalar"):
        kw["attn_scale"] = float(hf["query_pre_attn_scalar"]) ** -0.5
    if hf.get("sliding_window"):
        kw["sliding_window"] = int(hf["sliding_window"])
        n_layers = kw["num_layers"]
        if hf.get("layer_types"):
            kw["layer_types"] = tuple(
                "sliding" if t == "sliding_attention" else "global"
                for t in hf["layer_types"]
            )
        else:
            # gemma2 alternates: even layers sliding, odd layers global
            kw["layer_types"] = tuple(
                "sliding" if i % 2 == 0 else "global" for i in range(n_layers)
            )
    # gemma HF configs rely on the class default of tie_word_embeddings=True
    kw["tie_word_embeddings"] = bool(hf.get("tie_word_embeddings", True))
    kw.update(overrides)
    return TransformerConfig(**kw)


def baichuan_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """BaichuanForCausalLM — Baichuan2 7B shape (reference: models/baichuan/
    model.py): llama-like MHA with a fused W_pack qkv projection (handled by
    the adapter's "baichuan" style) and an L2-normalized lm_head (NormHead).
    The 13B ALiBi variant is not covered (rope only, like the reference)."""
    kw = _base_kwargs(hf)
    kw["num_kv_heads"] = kw["num_heads"]  # MHA
    kw["normalized_lm_head"] = True
    kw.update(overrides)
    return TransformerConfig(**kw)


def ministral3_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Ministral3ForCausalLM (reference: models/mistral3/model.py:50
    Ministral3Config): mistral body with an explicit head_dim, optional
    sliding window, and rope_theta nested under rope_parameters."""
    kw = _base_kwargs(hf)
    rp = hf.get("rope_parameters") or {}
    if rp.get("rope_theta"):
        kw["rope_theta"] = float(rp["rope_theta"])
    if hf.get("sliding_window"):
        kw["sliding_window"] = int(hf["sliding_window"])
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    kw.update(overrides)
    return TransformerConfig(**kw)


def ministral_bidirectional_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Ministral3BidirectionalModel (reference: models/
    ministral_bidirectional/model.py:36): the ministral retrieval encoder
    with causal masking removed; pooling is applied by the recipes."""
    kw_over = dict(overrides)
    kw_over["causal"] = False
    return ministral3_config(hf, **kw_over)
