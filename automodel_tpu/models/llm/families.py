"""Model-family adapters: HF config dict → TransformerConfig.

The analog of the reference's per-family model modules + registry
(reference: nemo_automodel/components/models/{llama,qwen2,qwen3,mistral3,
gemma…}/model.py and _transformers/registry.py:30 MODEL_ARCH_MAPPING).
Dense families differ only by config; MoE families live in models/moe_lm/.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp

from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.ops.rope import RopeScalingConfig


def _base_kwargs(hf: Mapping[str, Any]) -> dict:
    hidden = int(hf["hidden_size"])
    heads = int(hf["num_attention_heads"])
    return dict(
        vocab_size=int(hf["vocab_size"]),
        hidden_size=hidden,
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=int(hf["num_hidden_layers"]),
        num_heads=heads,
        num_kv_heads=int(hf.get("num_key_value_heads", heads)),
        head_dim=int(hf["head_dim"]) if hf.get("head_dim") else None,
        max_position_embeddings=int(hf.get("max_position_embeddings", 4096)),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rope_scaling=RopeScalingConfig.from_hf(hf.get("rope_scaling")),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )


def llama_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """LlamaForCausalLM (Llama 2/3/3.x; reference: models/llama/model.py)."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    kw.update(overrides)
    return TransformerConfig(**kw)


def llama_bidirectional_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """LlamaBidirectionalModel / ...ForSequenceClassification — the llama
    retrieval encoder with causal masking removed (reference:
    models/llama_bidirectional/model.py:79). Pooling ('avg'/'cls'/'last',
    hf['pooling']) is applied by the retrieval/seq-cls recipes, not here."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    kw["causal"] = False
    kw.update(overrides)
    return TransformerConfig(**kw)


def mistral_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """MistralForCausalLM (reference: models/mistral3)."""
    kw = _base_kwargs(hf)
    if hf.get("sliding_window"):
        kw["sliding_window"] = int(hf["sliding_window"])
    kw.update(overrides)
    return TransformerConfig(**kw)


def qwen2_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Qwen2ForCausalLM — qkv bias (reference: models/qwen2/model.py)."""
    kw = _base_kwargs(hf)
    kw["attention_bias"] = True
    if hf.get("use_sliding_window") and hf.get("sliding_window"):
        kw["sliding_window"] = int(hf["sliding_window"])
        # HF Qwen2 windows only layers >= max_window_layers
        mwl = int(hf.get("max_window_layers", 0))
        kw["layer_types"] = tuple(
            "sliding" if i >= mwl else "global" for i in range(kw["num_layers"])
        )
    kw.update(overrides)
    return TransformerConfig(**kw)


def qwen3_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Qwen3ForCausalLM — qk-norm, no bias (reference: models/qwen3_5)."""
    kw = _base_kwargs(hf)
    kw["qk_norm"] = True
    kw.update(overrides)
    return TransformerConfig(**kw)


def gemma2_config(hf: Mapping[str, Any], **overrides) -> TransformerConfig:
    """Gemma2: zero-centered 4-norm layers, embed scaling, soft caps,
    query_pre_attn_scalar attention scale, alternating sliding/global."""
    kw = _base_kwargs(hf)
    kw["activation"] = "gelu_tanh"
    kw["zero_centered_norm"] = True
    kw["use_post_norms"] = True
    kw["embed_scale"] = float(kw["hidden_size"]) ** 0.5
    if hf.get("final_logit_softcapping"):
        kw["logits_soft_cap"] = float(hf["final_logit_softcapping"])
    if hf.get("attn_logit_softcapping"):
        kw["attn_soft_cap"] = float(hf["attn_logit_softcapping"])
    if hf.get("query_pre_attn_scalar"):
        kw["attn_scale"] = float(hf["query_pre_attn_scalar"]) ** -0.5
    if hf.get("sliding_window"):
        kw["sliding_window"] = int(hf["sliding_window"])
        n_layers = kw["num_layers"]
        if hf.get("layer_types"):
            kw["layer_types"] = tuple(
                "sliding" if t == "sliding_attention" else "global"
                for t in hf["layer_types"]
            )
        else:
            # gemma2 alternates: even layers sliding, odd layers global
            kw["layer_types"] = tuple(
                "sliding" if i % 2 == 0 else "global" for i in range(n_layers)
            )
    # gemma HF configs rely on the class default of tie_word_embeddings=True
    kw["tie_word_embeddings"] = bool(hf.get("tie_word_embeddings", True))
    kw.update(overrides)
    return TransformerConfig(**kw)
