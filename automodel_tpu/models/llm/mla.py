"""Multi-head Latent Attention (MLA) — the DeepSeek V2/V3 attention.

The analog of the reference's MLA implementation inside
nemo_automodel/components/models/deepseek_v3/model.py:45-263 (Block / MLA
layers) — queries and keys/values are projected through low-rank latents;
RoPE applies to a small per-head rope slice plus ONE shared key-rope head:

    q = W_uq · rmsnorm(W_dq · x)            (or a direct W_q when no q rank)
    [c_kv ; k_rope] = W_dkv · x             (kv_lora_rank + qk_rope_head_dim)
    [k_nope ; v] = W_ukv · rmsnorm(c_kv)
    per head:  q = [q_nope ; rope(q_rope)],  k = [k_nope ; rope(k_rope)]

Attention logits use head_dim = qk_nope + qk_rope while values use
v_head_dim — the XLA attention path handles the asymmetric dims natively
(a dedicated Pallas MLA kernel is a later-round optimization; the
reference's TileLang sparse-MLA kernels map to that slot).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.models.llm.decoder import _dense
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope


def init_mla_layers(cfg, rng: jax.Array, L: int) -> dict:
    """MLA attention params for a stacked layer block (cfg: TransformerConfig
    with mla_* fields set)."""
    from automodel_tpu.models.llm.decoder import _stack
    from automodel_tpu.models.common.layers import dense_init

    H = cfg.hidden_size
    n = cfg.num_heads
    qk = cfg.mla_qk_nope_head_dim + cfg.mla_qk_rope_head_dim
    ks = jax.random.split(rng, 6)
    layers: dict = {
        "input_norm": {"scale": jnp.ones((L, H))},
        "post_attn_norm": {"scale": jnp.ones((L, H))},
        "kv_down_proj": {
            "kernel": _stack(
                dense_init, ks[0], (H, cfg.mla_kv_lora_rank + cfg.mla_qk_rope_head_dim), L
            )
        },
        "kv_norm": {"scale": jnp.ones((L, cfg.mla_kv_lora_rank))},
        "kv_up_proj": {
            "kernel": _stack(
                dense_init, ks[1],
                (cfg.mla_kv_lora_rank, n * (cfg.mla_qk_nope_head_dim + cfg.mla_v_head_dim)),
                L,
            )
        },
        "o_proj": {"kernel": _stack(dense_init, ks[2], (n * cfg.mla_v_head_dim, H), L)},
    }
    if cfg.mla_q_lora_rank:
        layers["q_down_proj"] = {"kernel": _stack(dense_init, ks[3], (H, cfg.mla_q_lora_rank), L)}
        layers["q_norm"] = {"scale": jnp.ones((L, cfg.mla_q_lora_rank))}
        layers["q_up_proj"] = {
            "kernel": _stack(dense_init, ks[4], (cfg.mla_q_lora_rank, n * qk), L)
        }
    else:
        layers["q_proj"] = {"kernel": _stack(dense_init, ks[5], (H, n * qk), L)}
    if cfg.dsa_index_topk is not None:
        layers["indexer"] = init_indexer(cfg, jax.random.fold_in(rng, 1234), L)
    return layers


def init_indexer(cfg, rng: jax.Array, L: int) -> dict:
    """Fresh lightning-indexer stack — also used to backfill checkpoints
    that predate DSA (reference: deepseek_v4 checkpoints carry indexer.*
    keys; V3-style ones do not). GLM style (dsa_indexer_style="glm")
    projects queries from the q-lora residual and LayerNorms keys."""
    from automodel_tpu.models.llm.decoder import _stack
    from automodel_tpu.models.common.layers import dense_init

    H = cfg.hidden_size
    Hi, Di = cfg.dsa_index_n_heads, cfg.dsa_index_head_dim
    ki = jax.random.split(rng, 3)
    if getattr(cfg, "dsa_indexer_style", "deepseek") == "glm":
        rq = cfg.mla_q_lora_rank or H
        return {
            "wq": {"kernel": _stack(dense_init, ki[0], (rq, Hi * Di), L)},
            "wk": {"kernel": _stack(dense_init, ki[1], (H, Di), L)},
            "k_norm": {"scale": jnp.ones((L, Di)), "bias": jnp.zeros((L, Di))},
            "wgate": {"kernel": _stack(dense_init, ki[2], (H, Hi), L)},
        }
    return {
        "wq": {"kernel": _stack(dense_init, ki[0], (H, Hi * Di), L)},
        "wk": {"kernel": _stack(dense_init, ki[1], (H, Di), L)},
        "wgate": {"kernel": _stack(dense_init, ki[2], (H, Hi), L)},
    }


def mla_layer_specs(cfg) -> dict:
    layers = {
        "input_norm": {"scale": ("layers", "norm")},
        "post_attn_norm": {"scale": ("layers", "norm")},
        "kv_down_proj": {"kernel": ("layers", "embed", None)},  # latent: replicated
        "kv_norm": {"scale": ("layers", "norm")},
        "kv_up_proj": {"kernel": ("layers", None, "heads")},
        "o_proj": {"kernel": ("layers", "heads", "embed")},
    }
    if cfg.mla_q_lora_rank:
        layers["q_down_proj"] = {"kernel": ("layers", "embed", None)}
        layers["q_norm"] = {"scale": ("layers", "norm")}
        layers["q_up_proj"] = {"kernel": ("layers", None, "heads")}
    else:
        layers["q_proj"] = {"kernel": ("layers", "embed", "heads")}
    if cfg.dsa_index_topk is not None:
        layers["indexer"] = {
            "wq": {"kernel": ("layers", "embed", "heads")},
            "wk": {"kernel": ("layers", "embed", None)},
            "wgate": {"kernel": ("layers", "embed", None)},
        }
        if getattr(cfg, "dsa_indexer_style", "deepseek") == "glm":
            layers["indexer"]["wq"] = {"kernel": ("layers", None, "heads")}
            layers["indexer"]["k_norm"] = {
                "scale": ("layers", "norm"), "bias": ("layers", "norm"),
            }
    return layers


def _mla_qkv(x, lp, cfg, positions, constrain, inv_freq):
    """Project normed input to MLA q/k/v (B,S,n,·), the logit scale, and the
    q-lora residual (post q_norm; None without q compression) — the GLM
    indexer's query source."""
    from automodel_tpu.ops.quant import matmul as _mm

    B, S, H = x.shape
    n = cfg.num_heads
    dn, dr, dv = cfg.mla_qk_nope_head_dim, cfg.mla_qk_rope_head_dim, cfg.mla_v_head_dim
    prec = cfg.linear_precision

    q_lat = None
    if cfg.mla_q_lora_rank:
        q_lat = rms_norm(_mm(x, lp["q_down_proj"]["kernel"], prec), lp["q_norm"]["scale"], cfg.rms_norm_eps)
        q = _mm(q_lat, lp["q_up_proj"]["kernel"], prec)
    else:
        q = _mm(x, lp["q_proj"]["kernel"], prec)
    q = q.reshape(B, S, n, dn + dr)
    q_nope, q_rope = q[..., :dn], q[..., dn:]
    q_rope = apply_rope(q_rope, positions, inv_freq)
    if cfg.mla_qpe_scaling_beta is not None:
        # mistral4 llama4-style scaling (reference: mistral4/model.py:52)
        sc = 1.0 + cfg.mla_qpe_scaling_beta * jnp.log1p(
            jnp.floor(positions.astype(jnp.float32) / cfg.mla_qpe_scaling_orig_max)
        )
        q_rope = q_rope * sc[:, :, None, None].astype(q_rope.dtype)
    q = jnp.concatenate([q_nope, q_rope], axis=-1)
    q = constrain(q, ("act_batch", "act_seq", "act_heads", None))

    kv = _mm(x, lp["kv_down_proj"]["kernel"], prec)  # (B,S, kv_rank + dr)
    c_kv, k_rope = kv[..., : cfg.mla_kv_lora_rank], kv[..., cfg.mla_kv_lora_rank :]
    # shared single-head key rope, broadcast across heads after rotation
    k_rope = apply_rope(k_rope[:, :, None, :], positions, inv_freq)
    c_kv = rms_norm(c_kv, lp["kv_norm"]["scale"], cfg.rms_norm_eps)
    kv_up = _mm(c_kv, lp["kv_up_proj"]["kernel"], prec).reshape(B, S, n, dn + dv)
    k_nope, v = kv_up[..., :dn], kv_up[..., dn:]
    k = jnp.concatenate([k_nope, jnp.broadcast_to(k_rope, (B, S, n, dr))], axis=-1)
    k = constrain(k, ("act_batch", "act_seq", "act_heads", None))
    v = constrain(v, ("act_batch", "act_seq", "act_heads", None))
    scale = cfg.attn_scale if cfg.attn_scale is not None else (dn + dr) ** -0.5
    return q, k, v, scale, q_lat


def resolve_dsa_impl(cfg, seq_len: int) -> str:
    impl = getattr(cfg, "dsa_impl", "auto")
    if impl == "auto":
        return "chunked" if seq_len > 4 * getattr(cfg, "dsa_query_block", 256) else "oracle"
    return impl


def dsa_sel_init(cfg, B: int, S: int):
    """Zero-initialized IndexShare carry for the configured implementation:
    a dense (B,S,S) bool selection for the oracle, (B,S,K) top-k indices
    for the chunked path."""
    if resolve_dsa_impl(cfg, S) == "chunked":
        return jnp.zeros((B, S, min(cfg.dsa_index_topk, S)), jnp.int32)
    return jnp.zeros((B, S, S), bool)


def _indexer_qkw(x, q_lat, lp, cfg, positions):
    """Roped indexer queries (B,S,Hi,Di), keys (B,S,Di) and fp32 gate
    weights (B,S,Hi), canonicalized so that for BOTH styles
    score[t,s] = Σ_h w[t,h] · relu(q[t,h]·k[s]) · Di**-0.5."""
    from automodel_tpu.ops.rope import rope_frequencies

    B, S, H = x.shape
    Hi, Di = cfg.dsa_index_n_heads, cfg.dsa_index_head_dim
    ip = lp["indexer"]
    if getattr(cfg, "dsa_indexer_style", "deepseek") == "glm":
        inv_freq_idx = rope_frequencies(
            cfg.mla_qk_rope_head_dim, cfg.rope_theta, cfg.rope_scaling
        )
        qsrc = q_lat if q_lat is not None else x
        q = (qsrc @ ip["wq"]["kernel"].astype(x.dtype)).reshape(B, S, Hi, Di)
        k = x @ ip["wk"]["kernel"].astype(x.dtype)
        mu = jnp.mean(k.astype(jnp.float32), axis=-1, keepdims=True)
        var = jnp.var(k.astype(jnp.float32), axis=-1, keepdims=True)
        k = (k.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + 1e-6)
        k = (k * ip["k_norm"]["scale"].astype(jnp.float32)
             + ip["k_norm"]["bias"].astype(jnp.float32)).astype(x.dtype)
        w = (x @ ip["wgate"]["kernel"].astype(x.dtype)).astype(jnp.float32)
        w = w * (Hi ** -0.5)
    else:
        inv_freq_idx = rope_frequencies(Di, cfg.rope_theta, cfg.rope_scaling)
        q = (x @ ip["wq"]["kernel"].astype(x.dtype)).reshape(B, S, Hi, Di)
        k = x @ ip["wk"]["kernel"].astype(x.dtype)
        w = (x @ ip["wgate"]["kernel"].astype(x.dtype)).astype(jnp.float32)
    q = apply_rope(q, positions, inv_freq_idx)
    k = apply_rope(k[:, :, None, :], positions, inv_freq_idx)[:, :, 0, :]
    return q, k, w


def mla_sparse_attention_block_chunked(
    h, lp, cfg, positions, segment_ids, inv_freq, constrain, token_mask=None,
    prev_idx=None, indexer_flag=None,
):
    """Two-phase sparse MLA without (S,S) materialization (the 32k-context
    DSA path; reference: deepseek_v4/kernels/tilelang_sparse_mla_fwd.py +
    tilelang_indexer_topk — here a blockwise XLA program: `lax.map` over
    query blocks keeps peak memory at O(S·block) while the MXU sees dense
    (block, K) dots).

    Per query block: indexer scores vs all keys → masked top-k indices →
    gather the kv LATENTS (c_kv (K, r) + shared rope key (K, dr)) → absorbed
    attention (scores and values in latent space via the kv up-projection
    halves — the exact-algebra form also used by the decode cache,
    inference/generate._mla_attn_with_cache). Returns (h_out, aux, idx) with
    idx (B, S, K) — the IndexShare carry in index form.
    """
    from automodel_tpu.ops.attention import NEG_INF

    B, S, H = h.shape
    n = cfg.num_heads
    dn, dr, dv = cfg.mla_qk_nope_head_dim, cfg.mla_qk_rope_head_dim, cfg.mla_v_head_dim
    r = cfg.mla_kv_lora_rank
    prec = cfg.linear_precision
    from automodel_tpu.ops.quant import matmul as _mm

    K = min(cfg.dsa_index_topk, S)
    bq = getattr(cfg, "dsa_query_block", 256)
    while S % bq != 0:
        bq //= 2
    nb = S // bq

    x = rms_norm(h, lp["input_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)

    # full-sequence latents (O(S·(r+dr)) — the whole point of MLA)
    q_lat = None
    if cfg.mla_q_lora_rank:
        q_lat = rms_norm(_mm(x, lp["q_down_proj"]["kernel"], prec), lp["q_norm"]["scale"], cfg.rms_norm_eps)
        q = _mm(q_lat, lp["q_up_proj"]["kernel"], prec)
    else:
        q = _mm(x, lp["q_proj"]["kernel"], prec)
    q = q.reshape(B, S, n, dn + dr)
    q_nope, q_rope = q[..., :dn], apply_rope(q[..., dn:], positions, inv_freq)

    kv = _mm(x, lp["kv_down_proj"]["kernel"], prec)
    c_kv = rms_norm(kv[..., :r], lp["kv_norm"]["scale"], cfg.rms_norm_eps)
    k_rope = apply_rope(kv[..., r:][:, :, None, :], positions, inv_freq)[:, :, 0, :]

    qi, ki, wi = _indexer_qkw(x, q_lat, lp, cfg, positions)

    W = lp["kv_up_proj"]["kernel"].astype(x.dtype).reshape(r, n, dn + dv)
    w_uk, w_uv = W[..., :dn], W[..., dn:]
    q_abs = jnp.einsum("bsnd,rnd->bsnr", q_nope, w_uk)
    scale = cfg.attn_scale if cfg.attn_scale is not None else (dn + dr) ** -0.5
    Di = cfg.dsa_index_head_dim

    seg = segment_ids if segment_ids is not None else jnp.zeros_like(positions)
    tmask = token_mask if token_mask is not None else jnp.ones((B, S), bool)

    def blk(xs):
        (qa_b, qr_b, qi_b, wi_b, qpos_b, qseg_b, tm_b, pidx_b, flag_or_none) = xs
        # ---- phase 1: indexer scores vs all keys, masked top-k ----
        # head loop (Hi is 2-8): peak stays at one (B, bq, S) buffer instead
        # of the (B, Hi, bq, S) einsum intermediate — at 32k keys that is
        # the difference between ~33MB and ~0.5GB per block
        scores = jnp.zeros(qi_b.shape[:2] + (ki.shape[1],), jnp.float32)
        for hh in range(qi_b.shape[2]):
            d = jnp.einsum(
                "bqd,bsd->bqs", qi_b[:, :, hh], ki,
                preferred_element_type=jnp.float32,
            )
            scores = scores + wi_b[:, :, hh][..., None] * jax.nn.relu(d)
        scores = scores * (Di ** -0.5)  # (B, bq, S) fp32
        adm = jnp.logical_and(
            qpos_b[:, :, None] >= positions[:, None, :],
            qseg_b[:, :, None] == seg[:, None, :],
        ) if cfg.causal else (qseg_b[:, :, None] == seg[:, None, :])
        masked = jnp.where(adm, scores, -jnp.inf)
        top_vals, idx = jax.lax.top_k(masked, K)  # (B, bq, K)
        if flag_or_none is not None:
            run = flag_or_none.astype(bool)
            idx = jnp.where(run, idx, pidx_b)
            # recompute validity/scores at the (possibly replayed) indices
            top_vals = jnp.take_along_axis(masked, idx, axis=-1)
        valid = jnp.isfinite(top_vals)

        # ---- phase 2: gather latents, absorbed attention over K ----
        flat = idx.reshape(B, -1)
        c_sel = jnp.take_along_axis(c_kv, flat[..., None], axis=1).reshape(B, bq, K, r)
        kr_sel = jnp.take_along_axis(k_rope, flat[..., None], axis=1).reshape(B, bq, K, dr)
        s = jnp.einsum("bqnr,bqkr->bqnk", qa_b, c_sel, preferred_element_type=jnp.float32)
        s = s + jnp.einsum("bqnd,bqkd->bqnk", qr_b, kr_sel, preferred_element_type=jnp.float32)
        s = jnp.where(valid[:, :, None, :], s * scale, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        out_lat = jnp.einsum("bqnk,bqkr->bqnr", p.astype(c_sel.dtype), c_sel)
        out = jnp.einsum("bqnr,rnd->bqnd", out_lat, w_uv)

        # ---- indexer KL over the selected set ----
        neg = jnp.float32(NEG_INF)
        logq = jax.nn.log_softmax(jnp.where(valid, top_vals, neg), axis=-1)
        pm = jax.lax.stop_gradient(jnp.mean(p, axis=2))  # (B, bq, K) head-avg
        pm = jnp.where(valid, pm, 0.0)
        pm = pm / jnp.maximum(jnp.sum(pm, -1, keepdims=True), 1e-9)
        kl = jnp.sum(pm * (jnp.log(jnp.maximum(pm, 1e-9)) - logq), axis=-1)
        m = tm_b.astype(jnp.float32)
        return out, idx, jnp.sum(kl * m), jnp.sum(m)

    def rs(a):  # (B, S, ...) → (nb, B, bq, ...)
        return jnp.swapaxes(a.reshape(B, nb, bq, *a.shape[2:]), 0, 1)

    xs = (
        rs(q_abs), rs(q_rope), rs(qi), rs(wi), rs(positions), rs(seg), rs(tmask),
        rs(prev_idx) if prev_idx is not None else rs(jnp.zeros((B, S, K), jnp.int32)),
        (jnp.broadcast_to(indexer_flag, (nb,)) if indexer_flag is not None else None),
    )
    if xs[-1] is None:
        xs = xs[:-1]

        def blk_noflag(args):
            return blk(args + (None,))

        out_b, idx_b, kl_b, cnt_b = jax.lax.map(blk_noflag, xs)
    else:
        out_b, idx_b, kl_b, cnt_b = jax.lax.map(blk, xs)

    attn = jnp.swapaxes(out_b, 0, 1).reshape(B, S, n * dv)
    idx = jnp.swapaxes(idx_b, 0, 1).reshape(B, S, K)
    aux = cfg.dsa_indexer_loss_coeff * jnp.sum(kl_b) / jnp.maximum(jnp.sum(cnt_b), 1.0)
    if indexer_flag is not None:
        aux = jnp.where(indexer_flag.astype(bool), aux, 0.0)

    h = h + _dense(attn, {"kernel": lp["o_proj"]["kernel"]}, prec)
    return constrain(h, ("act_batch", "act_seq", "act_embed")), aux, idx


def mla_sparse_attention_block(
    h, lp, cfg, positions, segment_ids, inv_freq, constrain, token_mask=None,
    prev_sel=None, indexer_flag=None,
):
    """DSA: lightning-indexer top-k sparse MLA (reference:
    deepseek_v4/layers.py; mask-based like its SDPA fallback path;
    glm_moe_dsa/layers.py for the GLM indexer + IndexShare variant).

    Returns (h_out, indexer_kl_aux, sel) — the aux rides the MoE decoder's
    loss carry; it is the ONLY gradient path into the indexer (hard top-k).
    `token_mask` (B,S) excludes pad queries from the indexer KL.

    IndexShare (GLM-5.x): `indexer_flag` is a traced 0/1 scalar riding the
    layer scan — 1 runs this layer's indexer, 0 reuses `prev_sel` (the most
    recent full layer's selection) and contributes no indexer KL. The
    returned `sel` is the running selection for the next layer.

    Implementation dispatch (cfg.dsa_impl): this dense-mask oracle, or the
    blockwise two-phase `mla_sparse_attention_block_chunked` for long
    sequences (prev_sel is then (B,S,K) indices)."""
    if resolve_dsa_impl(cfg, h.shape[1]) == "chunked":
        return mla_sparse_attention_block_chunked(
            h, lp, cfg, positions, segment_ids, inv_freq, constrain,
            token_mask=token_mask, prev_idx=prev_sel, indexer_flag=indexer_flag,
        )
    from automodel_tpu.ops.attention import NEG_INF, make_attention_mask
    from automodel_tpu.ops.dsa import (
        indexer_kl_loss,
        indexer_scores,
        indexer_scores_glm,
        topk_select_mask,
    )
    from automodel_tpu.ops.rope import rope_frequencies

    B, S, H = h.shape
    x = rms_norm(h, lp["input_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
    q, k, v, scale, q_lat = _mla_qkv(x, lp, cfg, positions, constrain, inv_freq)

    base_mask = make_attention_mask(
        S, S, causal=cfg.causal,
        q_segment_ids=segment_ids, kv_segment_ids=segment_ids,
        q_positions=positions, kv_positions=positions,
    )
    if base_mask is None:
        base_mask = jnp.ones((1, S, S), bool)

    if getattr(cfg, "dsa_indexer_style", "deepseek") == "glm":
        # rope applies to the FIRST qk_rope_head_dim channels only (GLM)
        inv_freq_idx = rope_frequencies(
            cfg.mla_qk_rope_head_dim, cfg.rope_theta, cfg.rope_scaling
        )
        scores = indexer_scores_glm(
            x, q_lat if q_lat is not None else x, lp["indexer"],
            cfg.dsa_index_n_heads, cfg.dsa_index_head_dim,
            positions, inv_freq_idx,
        )
    else:
        # same rope scaling as the main path — a yarn-scaled model's indexer
        # must agree with its attention about long-context positions
        inv_freq_idx = rope_frequencies(
            cfg.dsa_index_head_dim, cfg.rope_theta, cfg.rope_scaling
        )
        scores = indexer_scores(
            x, lp["indexer"], cfg.dsa_index_n_heads, cfg.dsa_index_head_dim,
            positions, inv_freq_idx,
        )
    sel = topk_select_mask(scores, base_mask, cfg.dsa_index_topk)
    if indexer_flag is not None and prev_sel is not None:
        run = indexer_flag.astype(bool)
        sel = jnp.where(run, sel, prev_sel)

    logits = jnp.einsum("bsnd,btnd->bnst", q, k, preferred_element_type=jnp.float32) * scale
    logits = jnp.where(sel[:, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnst,btnv->bsnv", probs.astype(v.dtype), v)

    aux = cfg.dsa_indexer_loss_coeff * indexer_kl_loss(
        scores, jnp.mean(probs, axis=1), sel, token_mask=token_mask
    )
    if indexer_flag is not None:
        aux = jnp.where(indexer_flag.astype(bool), aux, 0.0)

    attn = out.reshape(B, S, cfg.num_heads * cfg.mla_v_head_dim)
    h = h + _dense(attn, {"kernel": lp["o_proj"]["kernel"]}, cfg.linear_precision)
    return constrain(h, ("act_batch", "act_seq", "act_embed")), aux, sel


def mla_attention_block(h, lp, cfg, positions, segment_ids, inv_freq, constrain, sliding_window, mesh_ctx=None):
    """Pre-norm MLA attention with residual (drop-in for attention_block)."""
    B, S, H = h.shape
    n = cfg.num_heads
    dv = cfg.mla_v_head_dim

    x = rms_norm(h, lp["input_norm"]["scale"], cfg.rms_norm_eps, cfg.zero_centered_norm)
    q, k, v, scale, _ = _mla_qkv(x, lp, cfg, positions, constrain, inv_freq)

    if mesh_ctx is not None and mesh_ctx.sizes["cp"] > 1:
        from automodel_tpu.parallel.cp import ring_dot_product_attention

        attn = ring_dot_product_attention(
            q, k, v, positions, segment_ids, mesh_ctx,
            causal=cfg.causal,
            sliding_window=sliding_window,
            logits_soft_cap=cfg.attn_soft_cap,
            scale=scale,
            attn_impl=cfg.attn_impl,
        )
    else:
        # the flash kernel handles MLA's asymmetric qk (192) / v (128) head
        # dims natively (qk padded to 256 lanes, v block carries its own dim)
        attn = dot_product_attention(
            q, k, v,
            causal=cfg.causal,
            segment_ids=segment_ids,
            positions=positions,
            sliding_window=sliding_window,
            logits_soft_cap=cfg.attn_soft_cap,
            scale=scale,
            impl=cfg.attn_impl,
        )
    attn = attn.reshape(B, S, n * dv)
    h = h + _dense(attn, {"kernel": lp["o_proj"]["kernel"]}, cfg.linear_precision)
    return constrain(h, ("act_batch", "act_seq", "act_embed"))
