"""Per-request phase timelines reconstructed from trace events.

Answers the question the raw stats cannot: *where did this request's TTFT
go?* The tracer records lifecycle markers (submit → admit → handoff
extract → handoff admit → first_token) and engine `step.run` spans; this
module partitions each request's [submit, first_token] wall interval at
those marker boundaries, so the phase components SUM TO TTFT EXACTLY by
construction:

    queue      submit → first admission (waiting for a slot)
    prefill    admission → handoff extract (disagg) or the committing
               step's start (monolithic): prompt chunking time
    transfer   handoff extract → decode-side admission (disagg KV move)
    step       remainder up to first_token: the device step(s) that
               committed the first token, plus absorb
    backpressure  stream-pause overlap, subtracted from its enclosing
               phase and reported separately

ITL attribution splits each inter-commit gap into step time (overlap with
`step.run` spans), backpressure (stream-pause overlap), and scheduling
remainder. `attribution_summary` picks the median-TTFT request so the
reported components sum to the p50 the bench headline already prints.
"""

from __future__ import annotations

import dataclasses

#: lifecycle instants consumed here; emitters live in serving/*.
SUBMIT_EVENTS = ("frontend.submit", "request.submit")


@dataclasses.dataclass
class RequestTimeline:
    rid: int
    t_submit: float | None = None
    t_admit: float | None = None          # first admission anywhere
    t_extract: float | None = None        # disagg: prefill-side extraction
    t_handoff_admit: float | None = None  # disagg: decode-side admission
    t_first: float | None = None          # first committed token
    t_done: float | None = None
    finish_reason: str | None = None
    commits: list = dataclasses.field(default_factory=list)  # (ts, n_tokens)
    pauses: list = dataclasses.field(default_factory=list)   # (t0, t1)

    @property
    def ttft_s(self) -> float | None:
        if self.t_submit is None or self.t_first is None:
            return None
        return self.t_first - self.t_submit


def build_timelines(events) -> dict[int, RequestTimeline]:
    """Fold the event list into per-rid timelines. Only the FIRST
    occurrence of each marker counts (preempted requests re-admit; the
    original admission is what TTFT attribution wants)."""
    tls: dict[int, RequestTimeline] = {}
    open_pause: dict[int, float] = {}
    for e in events:
        if e.rid < 0:
            continue
        tl = tls.get(e.rid)
        if tl is None:
            tl = tls[e.rid] = RequestTimeline(rid=e.rid)
        n = e.name
        if n in SUBMIT_EVENTS:
            if tl.t_submit is None:
                tl.t_submit = e.ts
        elif n == "request.admit":
            if tl.t_admit is None:
                tl.t_admit = e.ts
        elif n == "request.handoff_extract":
            if tl.t_extract is None:
                tl.t_extract = e.ts
        elif n == "request.handoff_admit":
            if tl.t_handoff_admit is None:
                tl.t_handoff_admit = e.ts
        elif n == "request.first_token":
            if tl.t_first is None:
                tl.t_first = e.ts
        elif n == "request.commit":
            tl.commits.append((e.ts, int(e.args.get("n", 1))))
        elif n in ("request.done", "request.shed", "request.cancel",
                   "request.expire"):
            if tl.t_done is None:
                tl.t_done = e.ts
                tl.finish_reason = e.args.get("reason", n.split(".")[1])
        elif n == "stream.pause":
            open_pause.setdefault(e.rid, e.ts)
        elif n == "stream.resume":
            t0 = open_pause.pop(e.rid, None)
            if t0 is not None:
                tl.pauses.append((t0, e.ts))
    return tls


def _step_spans(events) -> list:
    return sorted(
        (e.ts, e.ts + e.dur)
        for e in events
        if e.ph == "X" and e.name == "step.run"
    )


def _overlap(t0: float, t1: float, intervals) -> float:
    s = 0.0
    for a, b in intervals:
        s += max(0.0, min(t1, b) - max(t0, a))
    return s


def attribute_ttft(tl: RequestTimeline, step_spans) -> dict | None:
    """Partition [submit, first_token] at the marker boundaries. Returns
    ms components summing exactly to ttft_ms, or None if the request
    never produced a token."""
    if tl.t_submit is None or tl.t_first is None:
        return None
    t0 = tl.t_submit
    t_admit = min(max(tl.t_admit if tl.t_admit is not None else t0, t0),
                  tl.t_first)
    disagg = tl.t_extract is not None and tl.t_handoff_admit is not None
    if disagg:
        tx0 = min(max(tl.t_extract, t_admit), tl.t_first)
        tx1 = min(max(tl.t_handoff_admit, tx0), tl.t_first)
        step_start = tx1
    else:
        tx0 = tx1 = None
        # the committing step: last step.run span ending at/before t_first
        # that started after admission; its start splits prefill from step
        step_start = t_admit
        for a, b in step_spans:
            if a >= t_admit and b <= tl.t_first + 1e-9:
                step_start = max(step_start, a)
    phases = {
        "queue": (t0, t_admit),
        "prefill": (t_admit, tx0 if disagg else step_start),
        "transfer": (tx0, tx1) if disagg else None,
        "step": (tx1 if disagg else step_start, tl.t_first),
    }
    out = {}
    backpressure = 0.0
    for name, iv in phases.items():
        if iv is None:
            out[f"{name}_ms"] = 0.0
            continue
        a, b = iv
        pause = _overlap(a, b, tl.pauses)
        backpressure += pause
        out[f"{name}_ms"] = (b - a - pause) * 1e3
    out["backpressure_ms"] = backpressure * 1e3
    out["ttft_ms"] = (tl.t_first - t0) * 1e3
    return out


def attribute_itl(tl: RequestTimeline, step_spans) -> dict | None:
    """Split the inter-commit gaps into step / backpressure / scheduling
    components (means over the request's gaps, in ms)."""
    ts = sorted(t for t, _ in tl.commits)
    if len(ts) < 2:
        return None
    step = bp = total = 0.0
    for a, b in zip(ts, ts[1:]):
        p = _overlap(a, b, tl.pauses)
        s = min(_overlap(a, b, step_spans), b - a - p)
        bp += p
        step += s
        total += b - a
    n = len(ts) - 1
    return {
        "gaps": n,
        "itl_mean_ms": total / n * 1e3,
        "step_ms": step / n * 1e3,
        "backpressure_ms": bp / n * 1e3,
        "sched_ms": (total - step - bp) / n * 1e3,
    }


def attribution_summary(events) -> dict:
    """The bench-headline block: TTFT attribution for the MEDIAN-TTFT
    request (components sum to the reported p50 exactly) plus mean ITL
    attribution over every inter-commit gap."""
    tls = build_timelines(events)
    spans = _step_spans(events)
    ttfts = sorted(
        (tl.ttft_s, rid) for rid, tl in tls.items() if tl.ttft_s is not None
    )
    out = {"requests": len(tls), "with_first_token": len(ttfts)}
    if ttfts:
        _, med_rid = ttfts[len(ttfts) // 2]
        att = attribute_ttft(tls[med_rid], spans)
        out["ttft_p50"] = {"rid": med_rid, **att}
    gaps = step = bp = sched = 0
    for tl in tls.values():
        itl = attribute_itl(tl, spans)
        if itl is None:
            continue
        gaps += itl["gaps"]
        step += itl["step_ms"] * itl["gaps"]
        bp += itl["backpressure_ms"] * itl["gaps"]
        sched += itl["sched_ms"] * itl["gaps"]
    if gaps:
        out["itl_mean"] = {
            "gaps": gaps,
            "step_ms": step / gaps,
            "backpressure_ms": bp / gaps,
            "sched_ms": sched / gaps,
            "itl_mean_ms": (step + bp + sched) / gaps,
        }
    return out
