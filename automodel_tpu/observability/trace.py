"""Span/event tracer for the serving stack — host-side, dual-clock.

Every event carries BOTH clocks: wall time (`time.perf_counter`, exported
in microseconds for Perfetto) and the engine step clock (`step`), because
serving questions come in both flavors — "how many milliseconds did the
KV handoff take" and "how many steps did this request wait in the
admission queue". Spans (`ph == "X"`) time engine phases (host plan build
vs device step vs absorb, KV transfers); instants (`ph == "i"`) mark
request lifecycle transitions (submit → admit → first_token → commit →
done/shed/cancel/expire).

Three export faces:

- `export_chrome(path)` — Chrome trace-event JSON, loadable in Perfetto
  (`ui.perfetto.dev`) or `chrome://tracing`; tracks become named threads.
- `export_jsonl(path)`  — one event object per line, greppable.
- `digest()`            — sha1 over the DETERMINISTIC projection of each
  request's lifecycle (event names + integer payloads, never wall times
  or step indices), so two identical runs produce identical digests even
  though the online loop's idle turns make absolute timing nondeterministic.

The flight recorder is a bounded ring of the most recent events,
maintained alongside the full buffer; `Observability.flight_dump` writes
it on crash / stall / SIGTERM so the last moments before a failure are
always on disk, next to the resilience layer's emergency checkpoint.

Everything here is plain host Python. Calling any of it from
jit-reachable code is a host-sync hazard — lint rule AM106 flags it.
"""

from __future__ import annotations

import hashlib
import json
import time


class TraceEvent:
    __slots__ = ("name", "ph", "ts", "dur", "step", "track", "rid", "args")

    def __init__(self, name, ph, ts, dur, step, track, rid, args):
        self.name = name
        self.ph = ph          # "X" complete span | "i" instant
        self.ts = ts          # wall seconds (perf_counter epoch)
        self.dur = dur        # span duration, seconds (0.0 for instants)
        self.step = step      # engine step clock (-1 = not step-aligned)
        self.track = track    # logical thread: engine / replica0 / prefill1 ...
        self.rid = rid        # request id (-1 = not request-scoped)
        self.args = args      # small dict of ints/strs; deterministic values only

    def to_dict(self) -> dict:
        d = {
            "name": self.name, "ph": self.ph,
            "ts_us": round(self.ts * 1e6, 1), "step": self.step,
            "track": self.track, "rid": self.rid,
        }
        if self.ph == "X":
            d["dur_us"] = round(self.dur * 1e6, 1)
        if self.args:
            d["args"] = self.args
        return d


class _SpanCtx:
    """Reusable-shape context manager: records one X event on exit."""

    __slots__ = ("_tr", "_name", "_track", "_step", "_rid", "_args", "_t0")

    def __init__(self, tr, name, track, step, rid, args):
        self._tr = tr
        self._name = name
        self._track = track
        self._step = step
        self._rid = rid
        self._args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tr._record(TraceEvent(
            self._name, "X", self._t0, t1 - self._t0,
            self._step, self._track, self._rid, self._args,
        ))
        return False


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class NullTracer:
    """The disabled tracer: every call is a constant-time no-op, so the
    instrumented hot loops cost two attribute lookups when tracing is off
    and the serve-step HLO stays byte-identical (nothing device-side ever
    depends on tracing either way)."""

    enabled = False
    events = ()

    def instant(self, name, *, track="engine", step=-1, rid=-1, **args):
        pass

    def span(self, name, *, track="engine", step=-1, rid=-1, **args):
        return _NULL_CTX


NULL_TRACER = NullTracer()


class Tracer:
    """Recording tracer: appends to one unbounded buffer. The
    flight-recorder "ring" is virtual — the last `ring_len` events of the
    buffer, materialized only at dump time — so the hot record path is a
    single list.append (atomic under the online frontend's threading
    model: event loop + one executor thread)."""

    enabled = True

    def __init__(self, *, ring_len: int = 256):
        self.events: list[TraceEvent] = []
        self.ring_len = max(1, int(ring_len))

    @property
    def ring(self) -> list:
        return self.events[-self.ring_len:]

    # -- recording --------------------------------------------------------

    def _record(self, ev: TraceEvent) -> None:
        self.events.append(ev)

    def instant(self, name, *, track="engine", step=-1, rid=-1, **args):
        self._record(TraceEvent(
            name, "i", time.perf_counter(), 0.0, step, track, rid, args
        ))

    def span(self, name, *, track="engine", step=-1, rid=-1, **args):
        return _SpanCtx(self, name, track, step, rid, args)

    # -- export -----------------------------------------------------------

    def _chrome_events(self) -> list[dict]:
        tids = {}
        out = []
        for t in sorted({e.track for e in self.events}):
            tids[t] = len(tids)
            out.append({
                "name": "thread_name", "ph": "M", "pid": 0, "tid": tids[t],
                "args": {"name": t},
            })
        for e in self.events:
            d = {
                "name": e.name, "ph": e.ph, "pid": 0, "tid": tids[e.track],
                "ts": round(e.ts * 1e6, 1),
                "args": {"step": e.step, "rid": e.rid, **e.args},
            }
            if e.ph == "X":
                d["dur"] = round(e.dur * 1e6, 1)
            else:
                d["s"] = "t"
            out.append(d)
        return out

    def export_chrome(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"traceEvents": self._chrome_events(),
                       "displayTimeUnit": "ms"}, f)

    def export_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for e in self.events:
                f.write(json.dumps(e.to_dict()) + "\n")

    def dump_ring(self, path: str, *, reason: str = "") -> int:
        """Write the flight-recorder ring as JSONL; returns event count."""
        evs = list(self.ring)
        with open(path, "w") as f:
            f.write(json.dumps({"flight_recorder": True, "reason": reason,
                                "events": len(evs)}) + "\n")
            for e in evs:
                f.write(json.dumps(e.to_dict()) + "\n")
        return len(evs)

    # -- determinism ------------------------------------------------------

    def digest(self) -> str:
        """sha1 over each request's lifecycle projected onto deterministic
        fields only: per-rid ordered (name, sorted int/str args), rids
        sorted. Wall clocks, durations, and step indices are excluded —
        idle turns in the online loop shift those between otherwise
        identical runs — and so are `stream.*` backpressure edges, which
        depend on consumer read timing rather than the request's
        lifecycle."""
        by_rid: dict[int, list] = {}
        for e in self.events:
            if e.rid < 0 or e.name.startswith("stream."):
                continue
            by_rid.setdefault(e.rid, []).append(
                (e.name, tuple(sorted(e.args.items())))
            )
        h = hashlib.sha1()
        for rid in sorted(by_rid):
            h.update(repr((rid, by_rid[rid])).encode())
        return h.hexdigest()


def validate_chrome_trace(path: str) -> dict:
    """CI helper: parse a Chrome trace export and check per-track span
    sanity — spans sorted by start must properly nest (every span that
    starts inside an open span must also end inside it) and instants must
    carry timestamps. Returns summary stats; raises on violation."""
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    spans: dict[int, list] = {}
    n_spans = n_instants = 0
    for e in evs:
        if e.get("ph") == "X":
            n_spans += 1
            spans.setdefault(e["tid"], []).append(
                (float(e["ts"]), float(e["ts"]) + float(e["dur"]))
            )
        elif e.get("ph") == "i":
            n_instants += 1
            if "ts" not in e:
                raise ValueError(f"instant without ts: {e}")
    for tid, ss in spans.items():
        ss.sort()
        stack: list[float] = []
        for t0, t1 in ss:
            while stack and stack[-1] <= t0:
                stack.pop()
            if stack and t1 > stack[-1] + 1e-6:
                raise ValueError(
                    f"tid {tid}: span [{t0}, {t1}] overlaps enclosing span "
                    f"ending at {stack[-1]} without nesting"
                )
            stack.append(t1)
    return {"events": len(evs), "spans": n_spans, "instants": n_instants,
            "tracks": len(spans)}
