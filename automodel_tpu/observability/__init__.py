"""Unified observability for the serving stack — host-side, off by default.

- metrics.py:  typed central registry (counters / gauges / fixed-bucket
               histograms) + Prometheus text snapshot; every serve stat
               lands here.
- trace.py:    span/event tracer, dual step-clock + wall-clock stamps,
               Chrome-trace (Perfetto) + JSONL export, deterministic
               lifecycle digest, flight-recorder ring.
- timeline.py: per-request phase timelines → TTFT/ITL attribution
               (queue vs prefill vs transfer vs step vs backpressure).
- profiler.py: `jax.profiler` windowed capture for train + serve paths,
               compiled cost analysis → MFU / bandwidth estimates.

The `Observability` bundle is what the engines thread through: metrics
are ALWAYS live (plain float adds, negligible), tracing/profiling/flight
recording only when `ObservabilityConfig.enabled`. Nothing in this
package may be referenced from jit-reachable code — the tracer records
host wall clocks and the registry mutates Python floats, either of which
inside a jitted function is a tracing-time no-op at best and a host-sync
hazard at worst. Lint rule AM106 (analysis/lint.py) enforces the fence.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

from automodel_tpu.observability.metrics import (
    LATENCY_MS_BUCKETS,
    METRIC_CATALOG,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from automodel_tpu.observability.timeline import (
    RequestTimeline,
    attribute_itl,
    attribute_ttft,
    attribution_summary,
    build_timelines,
)
from automodel_tpu.observability.trace import (
    NULL_TRACER,
    NullTracer,
    TraceEvent,
    Tracer,
    validate_chrome_trace,
)
from automodel_tpu.observability.profiler import (
    Profiler,
    ProfilingConfig,
    ServeProfiler,
    annotate,
    serve_step_cost,
    step_efficiency,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """`serving.observability` YAML section. Everything defaults off;
    with `enabled: false` the serve path is byte-identical to a build
    without this package."""

    enabled: bool = False
    #: trace export prefix: writes <trace_path>.trace.json (Perfetto) and
    #: <trace_path>.trace.jsonl at the end of the run
    trace_path: Optional[str] = None
    #: bounded ring of recent events dumped on crash/stall/SIGTERM
    flight_recorder_len: int = 256
    flight_recorder_path: Optional[str] = None
    #: [start_step, num_steps] window for a serve-path jax.profiler capture
    profile_window: Optional[tuple] = None
    #: alternatively: capture when a step exceeds this many ms
    itl_spike_ms: Optional[float] = None
    profile_dir: Optional[str] = None
    #: serve a tiny HTTP /metrics + /healthz endpoint from OnlineFrontend
    #: (0 picks an ephemeral port; None disables)
    http_port: Optional[int] = None


class Observability:
    """The per-engine (or per-router, shared) observability bundle.

    `registry` is always a real `MetricsRegistry` — counters cost one
    float add, so they stay on unconditionally and offline/online stats
    mirror onto them. `tracer` is the null tracer unless enabled, so the
    hot serve loop pays two attribute lookups when tracing is off.
    """

    def __init__(self, cfg: ObservabilityConfig | None = None, *,
                 registry: MetricsRegistry | None = None):
        self.cfg = cfg or ObservabilityConfig()
        self.enabled = bool(self.cfg.enabled)
        self.registry = registry if registry is not None else MetricsRegistry()
        self.tracer = (
            Tracer(ring_len=self.cfg.flight_recorder_len)
            if self.enabled else NULL_TRACER
        )
        self.profiler: ServeProfiler | None = None
        if self.enabled and self.cfg.profile_dir and (
            self.cfg.profile_window or self.cfg.itl_spike_ms is not None
        ):
            self.profiler = ServeProfiler(
                self.cfg.profile_dir,
                window=self.cfg.profile_window,
                itl_spike_ms=self.cfg.itl_spike_ms,
            )

    @classmethod
    def build(cls, cfg: ObservabilityConfig | None) -> "Observability":
        return cls(cfg)

    # -- step hook --------------------------------------------------------

    def observe_step(self, step_idx: int, step_ms: float) -> None:
        self.registry.histogram(
            "serve_step_ms", "device step wall time (ms)"
        ).observe(step_ms)
        if self.profiler is not None:
            self.profiler.observe(step_idx, step_ms)

    # -- exports ----------------------------------------------------------

    def export(self, prefix: Optional[str] = None) -> dict:
        """Write the Chrome + JSONL trace exports; returns written paths."""
        prefix = prefix or self.cfg.trace_path
        if not self.enabled or not prefix or not self.tracer.events:
            return {}
        d = os.path.dirname(prefix)
        if d:
            os.makedirs(d, exist_ok=True)
        chrome, jsonl = prefix + ".trace.json", prefix + ".trace.jsonl"
        self.tracer.export_chrome(chrome)
        self.tracer.export_jsonl(jsonl)
        return {"chrome": chrome, "jsonl": jsonl}

    def flight_dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Dump the flight-recorder ring (crash / stall / SIGTERM). Safe
        to call from except/finally blocks — never raises."""
        if not self.enabled:
            return None
        try:
            path = path or self.cfg.flight_recorder_path
            if path is None:
                base = self.cfg.trace_path or "flight"
                path = f"{base}.flight.{reason}.jsonl"
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            n = self.tracer.dump_ring(path, reason=reason)
            self.registry.counter(
                "flight_recorder_dumps_total",
                "flight-recorder dumps written (labeled by reason)",
                reason=reason,
            ).inc()
            logger.warning("flight recorder: %d events → %s (%s)",
                           n, path, reason)
            return path
        except Exception:  # pragma: no cover - last-resort path
            logger.exception("flight recorder dump failed")
            return None

    def close(self) -> None:
        if self.profiler is not None:
            self.profiler.close()
        self.export()


#: Shared do-nothing bundle for code paths that never configured one.
#: Its registry is real (process-global default), its tracer is null.
NULL_OBSERVABILITY = Observability(None, registry=default_registry())

__all__ = [
    "LATENCY_MS_BUCKETS",
    "METRIC_CATALOG",
    "NULL_OBSERVABILITY",
    "NULL_TRACER",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NullTracer",
    "Observability",
    "ObservabilityConfig",
    "Profiler",
    "ProfilingConfig",
    "RequestTimeline",
    "ServeProfiler",
    "TraceEvent",
    "Tracer",
    "annotate",
    "attribute_itl",
    "attribute_ttft",
    "attribution_summary",
    "build_timelines",
    "default_registry",
    "serve_step_cost",
    "step_efficiency",
    "validate_chrome_trace",
]
