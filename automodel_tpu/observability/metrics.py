"""Typed central metrics registry — counters, gauges, fixed-bucket histograms.

Every number the serving stack emits (engine `serve_batch` stats, router
per-replica balance, frontend TTFT/ITL/shed/goodput, resilience
retry/rollback totals, bench probe failures) lands on ONE registry so a
single snapshot answers "what has this process done so far". Two export
faces:

- `snapshot()`   — a flat dict (deterministic key order) for JSONL sinks,
                   test assertions, and the lead-vs-follower lockstep
                   parity check in the multi-host CI dryrun.
- `snapshot_prometheus()` — Prometheus text exposition format, served by
                   the `OnlineFrontend` `/metrics` endpoint.

Histograms use FIXED bucket boundaries declared at registration time
(default `LATENCY_MS_BUCKETS`) — never adaptive — so two identical runs
produce byte-identical digests and the lockstep parity check can compare
histograms, not just counters.

Everything here is host-side Python over plain floats. None of it may be
referenced from jit-reachable code (lint rule AM106 enforces this).
"""

from __future__ import annotations

import bisect
import threading

#: Fixed histogram boundaries for latencies in milliseconds. Deterministic
#: by construction: the same observations always land in the same buckets.
LATENCY_MS_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
    50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
)

_KINDS = ("counter", "gauge", "histogram")


class Counter:
    """Monotonic total. `inc` only; decrementing is a bug, not a feature."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        self.value += n


class Gauge:
    """Point-in-time value; set/inc/dec freely."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n


class Histogram:
    """Fixed-boundary cumulative histogram (Prometheus bucket semantics:
    bucket i counts observations <= bounds[i], with a +Inf overflow)."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds=LATENCY_MS_BUCKETS) -> None:
        b = tuple(float(x) for x in bounds)
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"histogram bounds must strictly increase: {b}")
        self.bounds = b
        self.counts = [0] * (len(b) + 1)  # last slot is +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def percentile(self, q: float) -> float:
        """Deterministic bucket-upper-bound estimate of the q-quantile
        (q in [0, 1]). Overflow observations report the top boundary."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                return self.bounds[min(i, len(self.bounds) - 1)]
        return self.bounds[-1]

    def snapshot(self) -> dict:
        cum, out = 0, []
        for c in self.counts[:-1]:
            cum += c
            out.append(cum)
        return {
            "count": self.count,
            "sum": self.sum,
            "bounds": list(self.bounds),
            "cumulative": out,  # per-bound cumulative counts (le semantics)
        }


def _series_key(name: str, labels: dict) -> str:
    if not labels:
        return name
    body = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{body}}}"


class _Family:
    __slots__ = ("kind", "help", "series", "bounds")

    def __init__(self, kind: str, help_: str, bounds=None):
        self.kind = kind
        self.help = help_
        self.series: dict[tuple, object] = {}  # sorted label items -> instrument
        self.bounds = bounds


class MetricsRegistry:
    """Process-local named-metric registry. Thread-safe registration (the
    online frontend's executor thread and the event loop both touch it);
    individual increments are plain float ops under the GIL."""

    def __init__(self) -> None:
        self._families: dict[str, _Family] = {}
        self._lock = threading.Lock()

    # -- registration -----------------------------------------------------

    def _get(self, name: str, kind: str, help_: str, labels: dict,
             bounds=None):
        key = tuple(sorted(labels.items()))
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.setdefault(
                    name, _Family(kind, help_, bounds)
                )
        if fam.kind != kind:
            raise TypeError(
                f"metric {name!r} is a {fam.kind}, requested as {kind}"
            )
        inst = fam.series.get(key)
        if inst is None:
            with self._lock:
                if key not in fam.series:
                    if kind == "counter":
                        inst = Counter()
                    elif kind == "gauge":
                        inst = Gauge()
                    else:
                        inst = Histogram(fam.bounds or LATENCY_MS_BUCKETS)
                    fam.series[key] = inst
                inst = fam.series[key]
        return inst

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "", *,
                  buckets=LATENCY_MS_BUCKETS, **labels) -> Histogram:
        return self._get(name, "histogram", help, labels, bounds=buckets)

    def register_catalog(self, catalog=None) -> None:
        """Pre-register every cataloged metric (zero-valued) so snapshots
        expose the full schema even before traffic arrives."""
        for name, kind, help_ in (catalog or METRIC_CATALOG):
            self._get(name, kind, help_, {})

    # -- export -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Flat deterministic dict: scalar metrics map to their value,
        histograms to their bucket snapshot dict."""
        out = {}
        for name in sorted(self._families):
            fam = self._families[name]
            for key in sorted(fam.series):
                inst = fam.series[key]
                skey = _series_key(name, dict(key))
                if fam.kind == "histogram":
                    out[skey] = inst.snapshot()
                else:
                    out[skey] = inst.value
        return out

    def snapshot_prometheus(self) -> str:
        """Prometheus text exposition format, one family per block."""
        lines = []
        for name in sorted(self._families):
            fam = self._families[name]
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for key in sorted(fam.series):
                inst = fam.series[key]
                labels = dict(key)
                if fam.kind != "histogram":
                    lines.append(
                        f"{_series_key(name, labels)} {_fmt(inst.value)}"
                    )
                    continue
                cum = 0
                for bound, c in zip(inst.bounds, inst.counts):
                    cum += c
                    lines.append(
                        f"{_series_key(name + '_bucket', {**labels, 'le': _fmt(bound)})}"
                        f" {cum}"
                    )
                cum += inst.counts[-1]
                lines.append(
                    f"{_series_key(name + '_bucket', {**labels, 'le': '+Inf'})}"
                    f" {cum}"
                )
                lines.append(f"{_series_key(name + '_sum', labels)} {_fmt(inst.sum)}")
                lines.append(f"{_series_key(name + '_count', labels)} {cum}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return format(v, ".10g")


#: Every metric the stack emits, pinned here so docs/OBSERVABILITY.md and
#: `snapshot_prometheus()` round-trip exactly (tested). Additions MUST be
#: documented in the catalog table of docs/OBSERVABILITY.md.
METRIC_CATALOG = (
    # engine step loop (incremented inside run_step / absorb — lockstep
    # across lead and follower processes, which is what the multi-host
    # parity dryrun compares)
    ("serve_steps_total", "counter", "jitted serve steps executed"),
    ("serve_plan_tokens_total", "counter", "tokens fed through step plans"),
    ("serve_plan_samples_total", "counter", "sample rows active in step plans"),
    ("serve_step_ms", "histogram", "device step wall time (ms)"),
    # engine serve_batch outcomes
    ("serve_new_tokens_total", "counter", "tokens committed to requests"),
    ("serve_requests_total", "counter", "requests finished by the engine"),
    ("serve_preemptions_total", "counter", "requests preempted and requeued"),
    ("serve_timed_out_total", "counter", "requests expired at their deadline"),
    ("serve_cancelled_total", "counter", "requests cancelled mid-flight"),
    ("serve_free_pages", "gauge", "KV pages currently free"),
    ("serve_compiled_signatures", "gauge", "jit cache entries for the serve step"),
    # prefix cache
    ("serve_prefix_hits_total", "counter", "admissions that matched a cached prefix"),
    ("serve_prefill_skipped_tokens_total", "counter", "prompt tokens skipped via prefix reuse"),
    ("serve_cow_copies_total", "counter", "copy-on-write page copies"),
    # speculative decoding
    ("serve_spec_drafted_total", "counter", "draft tokens proposed"),
    ("serve_spec_accepted_total", "counter", "draft tokens accepted"),
    ("serve_spec_rolled_back_total", "counter", "draft tokens rolled back"),
    ("serve_spec_steps_total", "counter", "verify steps run"),
    # disaggregation + KV movement
    ("serve_handoffs_total", "counter", "prefill→decode handoffs admitted"),
    ("serve_handoff_pages_moved_total", "counter", "handoff pages moved between pools"),
    ("serve_handoff_pages_spliced_total", "counter", "handoff pages spliced via decode-side prefix match"),
    ("serve_handoff_expired_total", "counter", "handoffs expired before decode admission"),
    ("serve_kv_transfer_pages_total", "counter", "KV pages shipped by transfers"),
    ("serve_kv_transfer_chunks_total", "counter", "fixed-size transfer chunks issued"),
    ("serve_kv_transfer_bytes_total", "counter", "KV transfer wire bytes (quantized pools ship int8+scales)"),
    # online frontend
    ("frontend_submitted_total", "counter", "requests submitted to the frontend"),
    ("frontend_finished_total", "counter", "streams finished (any reason)"),
    ("frontend_shed_total", "counter", "requests shed (labeled by reason)"),
    ("frontend_rejected_total", "counter", "submissions rejected at admission"),
    ("frontend_cancelled_total", "counter", "streams cancelled by the caller"),
    ("frontend_running", "gauge", "requests resident in slots"),
    ("frontend_waiting", "gauge", "requests queued for admission"),
    ("frontend_paused", "gauge", "slots paused for stream backpressure"),
    ("frontend_itl_ewma_ms", "gauge", "decayed inter-token latency estimate (ms)"),
    ("request_ttft_ms", "histogram", "time to first token (ms)"),
    ("request_itl_ms", "histogram", "inter-token latency (ms)"),
    # serving resilience (serving/resilience.py: health board, recovery,
    # degraded routing — all host-side)
    ("serve_replica_failures_total", "counter", "replica deaths observed (labeled by class)"),
    ("serve_requests_recovered_total", "counter", "requests requeued onto survivors after a replica death"),
    ("serve_recovery_reprefill_tokens_total", "counter", "known tokens requeued for re-prefill by failure recovery"),
    ("serve_transfer_retries_total", "counter", "KV transfer / plan-wire send retry attempts"),
    ("serve_degraded_mode", "gauge", "1 while disagg routing is collapsed to monolithic"),
    # resilience
    ("resilience_retries_total", "counter", "I/O retries attempted"),
    ("resilience_rollbacks_total", "counter", "rollback restores performed"),
    ("resilience_wasted_steps_total", "counter", "train steps redone after rollback"),
    # observability itself
    ("flight_recorder_dumps_total", "counter", "flight-recorder dumps written (labeled by reason)"),
    # bench environment probes
    ("bench_probe_failures_total", "counter", "failed accelerator probes (labeled by reason)"),
)

#: Process-global registry for components without an engine in hand
#: (resilience counters, bench probes). Engine/router/frontend metrics use
#: the per-`Observability` registry instead so tests stay hermetic.
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT
