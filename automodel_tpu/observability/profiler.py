"""Profiling — step-windowed `jax.profiler` capture + compiled cost analysis.

Home of the former `utils/profiling.py` (train-path `Profiler`, kept
API-compatible; `utils.profiling` remains as a deprecation shim) plus the
serving-path additions:

- `ServeProfiler` — windowed `jax.profiler` capture for the serve loop,
  triggered either by a fixed step range (`profile_window: [start, n]` in
  the `serving.observability` config) or by a latency-spike predicate
  (`itl_spike_ms`): the first step whose measured device time crosses the
  threshold starts the capture, so the trace you get is the trace of the
  anomaly, not of a lucky warm step.
- `serve_step_cost` / `step_efficiency` — `compiled.cost_analysis()`
  FLOPs/bytes for the engine's jitted step via AOT lowering (does NOT
  touch the jit call cache, so compile-once assertions still hold),
  joined with measured step wall time into achieved-FLOP/s and
  bandwidth figures, and MFU / bandwidth-utilization when hardware peaks
  are known.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import jax

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ProfilingConfig:
    trace_dir: Optional[str] = None
    start_step: int = 5     # skip compile + warmup steps
    num_steps: int = 3

    def build(self) -> "Profiler":
        return Profiler(self)


class Profiler:
    """Step-windowed trace capture; call `step(n)` once per train step."""

    def __init__(self, config: ProfilingConfig):
        self.config = config
        self._active = False
        self.done = False

    def step(self, step_num: int) -> None:
        c = self.config
        if c.trace_dir is None or self.done:
            return
        if not self._active and step_num >= c.start_step:
            jax.profiler.start_trace(c.trace_dir)
            self._active = True
            logger.info("profiler trace started (step %d) → %s", step_num, c.trace_dir)
        elif self._active and step_num >= c.start_step + c.num_steps:
            jax.profiler.stop_trace()
            self._active = False
            self.done = True
            logger.info("profiler trace written to %s", c.trace_dir)

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self.done = True


annotate = jax.named_scope  # the NVTX-range analog for model code


class ServeProfiler:
    """Serving-path windowed capture. One capture per run: either the
    fixed `window = (start_step, num_steps)` or the first step whose
    measured time exceeds `itl_spike_ms` (then `spike_steps` more)."""

    def __init__(self, trace_dir: str, *, window=None,
                 itl_spike_ms: float | None = None, spike_steps: int = 3):
        self.trace_dir = trace_dir
        self.window = tuple(window) if window else None
        self.itl_spike_ms = itl_spike_ms
        self.spike_steps = spike_steps
        self._active = False
        self._stop_at: int | None = None
        self.done = False
        self.triggered_by: str | None = None

    def observe(self, step_idx: int, step_ms: float | None = None) -> None:
        """Call once per serve step with the step's measured wall ms."""
        if self.done or self.trace_dir is None:
            return
        if not self._active:
            if self.window and self.window[0] <= step_idx:
                self._start(step_idx, step_idx + self.window[1], "window")
            elif (self.itl_spike_ms is not None and step_ms is not None
                  and step_ms > self.itl_spike_ms):
                self._start(step_idx, step_idx + self.spike_steps, "spike")
        elif self._stop_at is not None and step_idx >= self._stop_at:
            self._stop()

    def _start(self, step_idx: int, stop_at: int, why: str) -> None:
        jax.profiler.start_trace(self.trace_dir)
        self._active = True
        self._stop_at = stop_at
        self.triggered_by = why
        logger.info("serve profiler started (%s, step %d) → %s",
                    why, step_idx, self.trace_dir)

    def _stop(self) -> None:
        jax.profiler.stop_trace()
        self._active = False
        self.done = True
        logger.info("serve profiler trace written to %s", self.trace_dir)

    def close(self) -> None:
        if self._active:
            self._stop()


def serve_step_cost(engine) -> dict | None:
    """FLOPs/bytes of the engine's compiled serve step via AOT
    `lower().compile().cost_analysis()`. AOT compilation is cached
    separately from the jit call cache, so `step_cache_size()` (the
    compile-once counter) is unaffected. Returns None when the backend
    does not expose a cost model."""
    try:
        plan = engine.empty_plan()
        lowered = engine.lower_step(plan)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else None
        if not cost:
            return None
        return {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        }
    except Exception as e:  # pragma: no cover - backend-dependent
        logger.debug("serve step cost analysis unavailable: %s", e)
        return None


def step_efficiency(cost: dict | None, step_s: float, *,
                    peak_flops: float | None = None,
                    peak_bytes_per_s: float | None = None) -> dict:
    """Join static cost with one measured step time. Achieved rates are
    always reported; MFU / bandwidth-utilization only when the hardware
    peaks are known (None on CPU fallback runs)."""
    out = {"step_ms": step_s * 1e3}
    if not cost or step_s <= 0:
        return out
    gflops_s = cost["flops"] / step_s / 1e9
    gbytes_s = cost["bytes_accessed"] / step_s / 1e9
    out.update({
        "flops_per_step": cost["flops"],
        "bytes_per_step": cost["bytes_accessed"],
        "achieved_gflops_per_s": gflops_s,
        "achieved_gbytes_per_s": gbytes_s,
    })
    if peak_flops:
        out["mfu"] = cost["flops"] / step_s / peak_flops
    if peak_bytes_per_s:
        out["bw_util"] = cost["bytes_accessed"] / step_s / peak_bytes_per_s
    return out
