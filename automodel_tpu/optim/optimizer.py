"""Optimizer configs → optax transforms.

The analog of the reference's typed optimizer configs
(reference: nemo_automodel/components/optim/optimizer.py:179-338 —
Adam/AdamW/FusedAdam/FlashAdamW). On TPU, "fused" is what XLA does by
default; the knobs that matter are kept: betas/eps/weight-decay, a
no-decay mask for 1-D params (norm scales, biases), and param-group
style overrides via a predicate.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import optax


def default_weight_decay_mask(params) -> Any:
    """Decay matrices only — norm scales / biases (ndim < 2) are excluded."""
    return jax.tree.map(lambda p: p.ndim >= 2, params)


@dataclasses.dataclass
class OptimizerConfig:
    name: str = "adamw"
    lr: float = 3e-4
    betas: tuple = (0.9, 0.95)
    eps: float = 1e-8
    weight_decay: float = 0.1
    momentum: float = 0.9  # sgd only
    adamw_lr: float = 3e-4  # muon/dion: lr for the non-matrix (adamw) params
    dion_rank: int = 16     # dion only: power-iteration rank
    decay_mask: Optional[Callable] = dataclasses.field(default=None, repr=False)
    # per-group hyperparameter overrides, first match wins (the analog of the
    # reference's param-group machinery, optim/optimizer.py:80):
    #   param_groups: [{pattern: "embed", lr_mult: 0.1, weight_decay: 0.0}]
    # `pattern` is a substring/regex over the slash-joined param path.
    param_groups: tuple = ()

    def build(self, lr_schedule: "float | Callable" = None) -> optax.GradientTransformation:
        lr = lr_schedule if lr_schedule is not None else self.lr
        mask = self.decay_mask or default_weight_decay_mask
        if self.param_groups:
            return self._build_grouped(lr)
        if self.name in ("adamw", "fused_adamw", "flash_adamw"):
            return optax.adamw(
                lr, b1=self.betas[0], b2=self.betas[1], eps=self.eps,
                weight_decay=self.weight_decay, mask=mask,
            )
        if self.name in ("adam", "fused_adam"):
            return optax.adam(lr, b1=self.betas[0], b2=self.betas[1], eps=self.eps)
        if self.name == "sgd":
            return optax.sgd(lr, momentum=self.momentum)
        if self.name == "adafactor":
            return optax.adafactor(lr)
        if self.name == "lion":
            return optax.lion(lr, b1=self.betas[0], b2=self.betas[1], weight_decay=self.weight_decay)
        if self.name in ("muon", "dion"):
            # the adamw half (embeddings/norms/biases) follows the SAME
            # schedule shape, rescaled from the matrix peak lr to adamw_lr
            if callable(lr):
                ratio = self.adamw_lr / self.lr
                adamw_sched = lambda step: lr(step) * ratio
            else:
                adamw_sched = self.adamw_lr
            if self.name == "dion":
                from automodel_tpu.optim.dion import DionConfig

                return DionConfig(
                    lr=self.lr, rank=self.dion_rank, adamw_lr=self.adamw_lr,
                    weight_decay=self.weight_decay, betas=self.betas,
                ).build(lr_schedule=lr, adamw_schedule=adamw_sched)
            from automodel_tpu.optim.muon import MuonConfig

            return MuonConfig(
                lr=self.lr,
                adamw_lr=self.adamw_lr,
                weight_decay=self.weight_decay,
                betas=self.betas,
            ).build(lr_schedule=lr, adamw_schedule=adamw_sched)
        raise ValueError(f"Unknown optimizer '{self.name}'")

    def _build_grouped(self, lr) -> optax.GradientTransformation:
        """Per-group lr/weight-decay overrides via multi_transform."""
        import re

        groups = [
            g.to_dict() if hasattr(g, "to_dict") else dict(g)
            for g in self.param_groups
        ]
        for g in groups:
            if not g.get("pattern"):
                raise ValueError(
                    "optimizer.param_groups entries require a non-empty "
                    f"'pattern' (got {g})"
                )
        txs = {"__default__": dataclasses.replace(self, param_groups=()).build(lr)}
        for i, g in enumerate(groups):
            lr_mult = float(g.get("lr_mult", 1.0))
            glr = (lambda s, m=lr_mult: lr(s) * m) if callable(lr) else lr * lr_mult
            base = dataclasses.replace(
                self, param_groups=(),
                weight_decay=float(g.get("weight_decay", self.weight_decay)),
            )
            txs[f"g{i}"] = base.build(glr)

        def labeler(params):
            flat = jax.tree_util.tree_flatten_with_path(params)[0]
            labels = []
            for path, _leaf in flat:
                name = "/".join(str(getattr(k, "key", k)) for k in path)
                label = "__default__"
                for i, g in enumerate(groups):
                    if re.search(str(g.get("pattern", "")), name):
                        label = f"g{i}"
                        break
                labels.append(label)
            return jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(params), labels
            )

        return optax.multi_transform(txs, labeler)


@dataclasses.dataclass
class LRSchedulerConfig:
    """Warmup + decay schedule (reference: optim/scheduler.py:18
    `OptimizerParamScheduler` — cosine / linear / wsd)."""

    warmup_steps: int = 0
    decay_steps: int = 1000
    style: str = "cosine"  # cosine | linear | constant | wsd
    min_lr_ratio: float = 0.0
    stable_steps: int = 0  # wsd only

    def build(self, peak_lr: float) -> Callable:
        floor = peak_lr * self.min_lr_ratio
        if self.style == "constant":
            sched = optax.constant_schedule(peak_lr)
        elif self.style == "cosine":
            sched = optax.cosine_decay_schedule(
                peak_lr, max(self.decay_steps, 1), alpha=self.min_lr_ratio
            )
        elif self.style == "linear":
            sched = optax.linear_schedule(peak_lr, floor, max(self.decay_steps, 1))
        elif self.style == "wsd":
            # warmup handled below; stable then linear decay to floor
            sched = optax.join_schedules(
                [
                    optax.constant_schedule(peak_lr),
                    optax.linear_schedule(peak_lr, floor, max(self.decay_steps, 1)),
                ],
                [self.stable_steps],
            )
        else:
            raise ValueError(f"Unknown LR style '{self.style}'")
        if self.warmup_steps > 0:
            warmup = optax.linear_schedule(0.0, peak_lr, self.warmup_steps)
            return optax.join_schedules([warmup, sched], [self.warmup_steps])
        return sched
