"""Dion — distributed orthonormalized updates with low-rank power iteration.

The reference wires the external `dion` package's Dion optimizer into its
param-group machinery (reference: nemo_automodel/components/optim/dion.py:160
`build_dion_optimizer`); here the algorithm itself (arXiv:2504.05295
Algorithm 1) is implemented as an optax transformation:

    B   = M + G                      # momentum buffer + fresh grad
    P   = qr(B Q).Q                  # one power-iteration step, (m, r)
    R   = Bᵀ P                       # (n, r)
    M'  = B − (1−μ) P Rᵀ             # error feedback keeps the residual
    Q'  = R / ‖R‖_col                # next iteration's sketch
    ΔW  = P Q'ᵀ · √(max(1, out/in))  # orthonormal low-rank update

Rank r ≪ min(m, n) makes the heavy math O(mnr) instead of Muon's O(mn²)
Newton–Schulz — and under GSPMD the three matmuls + thin QR shard like any
other op, which is the part the reference implements by hand over DTensor
meshes. Stacked-layer params vmap over the leading dim. Non-matrix params
(and embeddings/unembeddings) fall back to AdamW, same split as Muon.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class DionState(NamedTuple):
    momentum: Any
    q: Any  # per matrix leaf: (..., n, r) power-iteration sketch


def _q_init(leaf: jnp.ndarray, rank: int) -> jnp.ndarray:
    n = leaf.shape[-1]
    r = min(rank, n, leaf.shape[-2])
    eye = jnp.eye(n, r, dtype=jnp.float32)
    return jnp.broadcast_to(eye, leaf.shape[:-2] + (n, r)).copy()


def _dion_update(b: jnp.ndarray, q: jnp.ndarray, mu: float):
    """One Dion step for a single (m, n) matrix. Returns (delta, m', q')."""
    p = b @ q                                          # (m, r)
    p, _ = jnp.linalg.qr(p)                            # orthonormal columns
    r_mat = b.T @ p                                    # (n, r)
    m_new = b - (1.0 - mu) * (p @ r_mat.T)
    col = jnp.linalg.norm(r_mat, axis=0, keepdims=True)
    q_new = r_mat / jnp.maximum(col, 1e-8)
    delta = p @ q_new.T                                # ~orthonormal
    fan_in, fan_out = b.shape
    delta = delta * (max(1.0, fan_out / fan_in) ** 0.5)
    return delta, m_new, q_new


def scale_by_dion(rank: int = 16, mu: float = 0.95):
    def init(params):
        return DionState(
            momentum=jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            q=jax.tree.map(lambda p: _q_init(p, rank), params),
        )

    def update(updates, state, params=None):
        def one(g, m, q):
            b = m.astype(jnp.float32) + g.astype(jnp.float32)
            if b.ndim == 2:
                return _dion_update(b, q, mu)
            flat_b = b.reshape((-1,) + b.shape[-2:])
            flat_q = q.reshape((-1,) + q.shape[-2:])
            d, mn, qn = jax.vmap(lambda bb, qq: _dion_update(bb, qq, mu))(
                flat_b, flat_q
            )
            return d.reshape(b.shape), mn.reshape(b.shape), qn.reshape(q.shape)

        out = jax.tree.map(one, updates, state.momentum, state.q)

        def pick(i):
            # optax.masked leaves (MaskedNode, an empty tuple) pass through
            return jax.tree.map(
                lambda t: t[i] if len(t) == 3 else t, out,
                is_leaf=lambda x: isinstance(x, tuple),
            )

        return pick(0), DionState(momentum=pick(1), q=pick(2))

    return optax.GradientTransformation(init, update)


@dataclasses.dataclass
class DionConfig:
    """`optimizer: {name: dion, ...}` — matrices get Dion, the rest AdamW."""

    lr: float = 2e-2
    rank: int = 16
    mu: float = 0.95
    adamw_lr: float = 3e-4
    betas: tuple = (0.9, 0.95)
    weight_decay: float = 0.01

    def build(self, lr_schedule=None, adamw_schedule=None) -> optax.GradientTransformation:
        from automodel_tpu.optim.muon import matrix_param_labeler

        dion_tx = optax.chain(
            scale_by_dion(self.rank, self.mu),
            optax.add_decayed_weights(self.weight_decay),
            optax.scale_by_learning_rate(
                lr_schedule if lr_schedule is not None else self.lr
            ),
        )
        adamw_tx = optax.adamw(
            adamw_schedule if adamw_schedule is not None else self.adamw_lr,
            b1=self.betas[0], b2=self.betas[1], weight_decay=self.weight_decay,
            mask=lambda p: jax.tree.map(lambda x: x.ndim >= 2, p),
        )
        return optax.multi_transform(
            {"dion": dion_tx, "adamw": adamw_tx},
            lambda p: matrix_param_labeler(p, "dion"),
        )
