"""Muon optimizer — Newton–Schulz-orthogonalized momentum for matrices.

The analog of the reference's distributed Muon/Dion optimizers
(reference: nemo_automodel/components/optim/dion.py:160
`build_dion_optimizer`, optimizer.py:339 `_DionConfigBase`). TPU-native
form: an optax transformation. Matrix params (ndim ≥ 2, excluding
embeddings/unembeddings, which Muon's authors exclude) get
momentum → Newton–Schulz orthogonalization → shape-scaled update; all
other params fall back to AdamW via optax.multi_transform. Stacked-layer
leading dims are vmapped, so one (L, in, out) array orthogonalizes per
layer. Under GSPMD the NS iteration's matmuls are sharded like any other —
no bespoke distributed-optimizer communication code is needed (the part
dion.py hand-implements over DTensor meshes).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

# quintic Newton–Schulz coefficients (Muon defaults)
_NS_COEFFS = (3.4445, -4.7750, 2.0315)


def _newton_schulz(g: jnp.ndarray, steps: int) -> jnp.ndarray:
    """Approximate UV^T of the SVD of g (2-D), via quintic NS iteration."""
    a, b, c = _NS_COEFFS
    x = g.astype(jnp.bfloat16)
    transposed = x.shape[0] > x.shape[1]
    if transposed:
        x = x.T
    x = x / (jnp.linalg.norm(x) + 1e-7)

    def body(x, _):
        xxt = x @ x.T
        out = a * x + (b * xxt + c * (xxt @ xxt)) @ x
        return out, None

    x, _ = jax.lax.scan(body, x, None, length=steps)
    if transposed:
        x = x.T
    return x.astype(jnp.float32)


def _orthogonalize(m: jnp.ndarray, steps: int) -> jnp.ndarray:
    """NS-orthogonalize the trailing two dims; vmap stacked leading dims."""
    if m.ndim == 2:
        return _newton_schulz(m, steps)
    flat = m.reshape((-1,) + m.shape[-2:])
    out = jax.vmap(lambda x: _newton_schulz(x, steps))(flat)
    return out.reshape(m.shape)


class MuonState(NamedTuple):
    momentum: Any


def scale_by_muon(momentum: float = 0.95, ns_steps: int = 5, nesterov: bool = True):
    def init(params):
        return MuonState(momentum=jax.tree.map(jnp.zeros_like, params))

    def update(updates, state, params=None):
        buf = jax.tree.map(lambda m, g: momentum * m + g, state.momentum, updates)
        eff = (
            jax.tree.map(lambda m, g: momentum * m + g, buf, updates)
            if nesterov
            else buf
        )

        def one(g):
            o = _orthogonalize(g, ns_steps)
            # scale so update RMS matches adamw-style magnitudes (Muon paper:
            # sqrt(max(1, out/in)); kernels here are (in, out))
            fan_in, fan_out = g.shape[-2], g.shape[-1]
            return o * (max(1.0, fan_out / fan_in) ** 0.5)

        return jax.tree.map(one, eff), MuonState(momentum=buf)

    return optax.GradientTransformation(init, update)


@dataclasses.dataclass
class MuonConfig:
    """`optimizer: {name: muon, ...}` — matrices get Muon, the rest AdamW."""

    lr: float = 2e-2
    momentum: float = 0.95
    ns_steps: int = 5
    nesterov: bool = True
    adamw_lr: float = 3e-4
    betas: tuple = (0.9, 0.95)
    weight_decay: float = 0.01

    def build(self, lr_schedule=None, adamw_schedule=None) -> optax.GradientTransformation:
        muon_tx = optax.chain(
            scale_by_muon(self.momentum, self.ns_steps, self.nesterov),
            optax.add_decayed_weights(self.weight_decay),
            optax.scale_by_learning_rate(lr_schedule if lr_schedule is not None else self.lr),
        )
        adamw_tx = optax.adamw(
            adamw_schedule if adamw_schedule is not None else self.adamw_lr,
            b1=self.betas[0], b2=self.betas[1], weight_decay=self.weight_decay,
            mask=lambda p: jax.tree.map(lambda x: x.ndim >= 2, p),
        )

        return optax.multi_transform(
            {"muon": muon_tx, "adamw": adamw_tx},
            lambda p: matrix_param_labeler(p, "muon"),
        )


def matrix_param_labeler(params, matrix_label: str = "muon"):
    """`matrix_label` for ndim≥2 non-embedding params, 'adamw' otherwise —
    the Muon/Dion split (embedding-like tables and lm_head excluded per
    the Muon authors; shared with optim/dion.py). The label doubles as an
    optimizer-state pytree key, so each optimizer keeps its own name for
    checkpoint compatibility."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    labels = []
    for path, leaf in flat:
        keys = [str(getattr(k, "key", k)) for k in path]
        is_matrix = leaf.ndim >= 2
        is_embed = any(("embed" in k) or k == "lm_head" for k in keys)
        labels.append(matrix_label if (is_matrix and not is_embed) else "adamw")
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), labels
    )
