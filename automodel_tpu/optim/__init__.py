from automodel_tpu.optim.optimizer import LRSchedulerConfig, OptimizerConfig, default_weight_decay_mask

__all__ = ["LRSchedulerConfig", "OptimizerConfig", "default_weight_decay_mask"]
