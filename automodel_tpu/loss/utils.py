"""Loss composition helpers.

The analog of the reference's `calculate_loss` dispatch + aux-loss scaling
(reference: nemo_automodel/components/loss/utils.py:74 and moe/megatron/
moe_utils.py:569 `MoEAuxLossAutoScaler`).
"""

from __future__ import annotations

import jax.numpy as jnp


def combine_losses(
    ce_sum: jnp.ndarray,
    num_label_tokens: jnp.ndarray,
    aux_loss: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fold an O(1) auxiliary loss into a SUM loss that will later be divided
    by the global label-token count.

    The train step normalizes gradients by num_label_tokens (the reference's
    dp all-reduce of n_tokens, train_ft.py:1093); multiplying the aux term by
    the same count first keeps its effective coefficient scale-invariant —
    exactly what MoEAuxLossAutoScaler's backward-scale does in the reference.
    """
    total = ce_sum
    if aux_loss is not None:
        total = total + aux_loss * num_label_tokens
    return total, num_label_tokens
