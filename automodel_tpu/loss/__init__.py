from automodel_tpu.loss.masked_ce import IGNORE_INDEX, cross_entropy_sum, masked_cross_entropy
from automodel_tpu.loss.linear_ce import fused_linear_cross_entropy

__all__ = [
    "IGNORE_INDEX",
    "cross_entropy_sum",
    "masked_cross_entropy",
    "fused_linear_cross_entropy",
]
