"""Knowledge-distillation losses.

The analog of the reference KD stack (reference: nemo_automodel/components/
loss/kd_loss.py + soft_ce.py Triton soft-label CE; recipes/llm/kd.py).
Temperature-scaled soft cross-entropy between teacher and student logits,
masked like the hard loss, returned as (sum, token_count) to ride the same
global-token normalization as everything else. Chunked over the sequence so
teacher+student logits never co-materialize at full (B*S, V).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from automodel_tpu.loss.masked_ce import IGNORE_INDEX


def soft_cross_entropy_sum(
    student_logits: jnp.ndarray,  # (..., V)
    teacher_logits: jnp.ndarray,  # (..., V)
    labels: jnp.ndarray,          # (...,) mask via IGNORE_INDEX
    *,
    temperature: float = 1.0,
    ignore_index: int = IGNORE_INDEX,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """sum_t T² · CE(softmax(teacher/T), softmax(student/T)) over valid tokens."""
    mask = labels != ignore_index
    t = jnp.float32(temperature)
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, axis=-1)
    p = jax.nn.softmax(teacher_logits.astype(jnp.float32) / t, axis=-1)
    ce = -jnp.sum(p * s, axis=-1) * (t * t)
    ce = jnp.where(mask, ce, 0.0)
    return jnp.sum(ce), jnp.sum(mask).astype(jnp.float32)


def fused_kd_cross_entropy(
    student_hidden: jnp.ndarray,   # (B, S, H)
    student_kernel: jnp.ndarray,   # (H, V)
    teacher_hidden: jnp.ndarray,   # (B, S, Ht)
    teacher_kernel: jnp.ndarray,   # (Ht, V)
    labels: jnp.ndarray,           # (B, S)
    *,
    kd_ratio: float = 0.5,
    temperature: float = 1.0,
    chunk_size: int = 1024,
    ignore_index: int = IGNORE_INDEX,
    student_soft_cap: float | None = None,
    teacher_soft_cap: float | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Combined hard CE + soft KD without materializing full logits:
    loss = (1-kd_ratio)·CE(student, labels) + kd_ratio·softCE(teacher→student).

    Returns (sum, num_label_tokens). Same chunked-lm-head trade as
    loss/linear_ce.py, with the teacher's head projected per chunk too.
    """
    B, S, H = student_hidden.shape
    N = B * S
    sh = student_hidden.reshape(N, H)
    th = teacher_hidden.reshape(N, teacher_hidden.shape[-1])
    fl = labels.reshape(N)
    chunk_size = min(chunk_size, N)
    pad = (-N) % chunk_size
    if pad:
        sh = jnp.pad(sh, ((0, pad), (0, 0)))
        th = jnp.pad(th, ((0, pad), (0, 0)))
        fl = jnp.pad(fl, (0, pad), constant_values=ignore_index)
    n_chunks = sh.shape[0] // chunk_size
    sh = sh.reshape(n_chunks, chunk_size, -1)
    th = th.reshape(n_chunks, chunk_size, -1)
    fl = fl.reshape(n_chunks, chunk_size)

    @partial(jax.checkpoint, policy=jax.checkpoint_policies.nothing_saveable)
    def chunk(carry, xs):
        s_h, t_h, l = xs
        s_logits = jnp.einsum(
            "ch,hv->cv", s_h, student_kernel.astype(s_h.dtype),
            preferred_element_type=jnp.float32,
        )
        if student_soft_cap is not None:
            s_logits = student_soft_cap * jnp.tanh(s_logits / student_soft_cap)
        t_logits = jax.lax.stop_gradient(
            jnp.einsum(
                "ch,hv->cv", t_h, teacher_kernel.astype(t_h.dtype),
                preferred_element_type=jnp.float32,
            )
        )
        if teacher_soft_cap is not None:
            t_logits = teacher_soft_cap * jnp.tanh(t_logits / teacher_soft_cap)
        mask = l != ignore_index
        safe = jnp.where(mask, l, 0)
        lse = jax.scipy.special.logsumexp(s_logits, axis=-1)
        picked = jnp.take_along_axis(s_logits, safe[:, None], axis=-1)[:, 0]
        hard = jnp.where(mask, lse - picked, 0.0)
        soft_sum, _ = soft_cross_entropy_sum(
            s_logits, t_logits, l, temperature=temperature, ignore_index=ignore_index
        )
        total, n = carry
        combined = (1.0 - kd_ratio) * jnp.sum(hard) + kd_ratio * soft_sum
        return (total + combined, n + jnp.sum(mask).astype(jnp.float32)), None

    (total, n), _ = jax.lax.scan(
        chunk, (jnp.float32(0.0), jnp.float32(0.0)), (sh, th, fl)
    )
    return total, n
