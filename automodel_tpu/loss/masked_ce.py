"""Masked cross-entropy losses.

Analogs of the reference loss zoo (reference: nemo_automodel/components/
loss/masked_ce.py:22 `MaskedCrossEntropy`, chunked_ce.py:128
`ChunkedCrossEntropy`). Losses return an UN-normalized sum plus the valid
token count so the recipe can normalize by the GLOBAL number of label
tokens across dp/cp ranks (reference: recipes/llm/train_ft.py:1093-1125) —
under GSPMD the sums are already global, so the division is a no-op shard-wise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100


def cross_entropy_sum(
    logits: jnp.ndarray,  # (..., V) any float dtype; upcast to fp32 inside
    labels: jnp.ndarray,  # (...,) int, IGNORE_INDEX masked out
    ignore_index: int = IGNORE_INDEX,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (sum_ce_fp32, num_valid_tokens_fp32)."""
    mask = labels != ignore_index
    safe_labels = jnp.where(mask, labels, 0)
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    ce = jnp.where(mask, lse - picked, 0.0)
    return jnp.sum(ce), jnp.sum(mask).astype(jnp.float32)


def masked_cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    ignore_index: int = IGNORE_INDEX,
    reduction: str = "sum",
) -> jnp.ndarray:
    ce_sum, n = cross_entropy_sum(logits, labels, ignore_index)
    if reduction == "sum":
        return ce_sum
    if reduction == "mean":
        return ce_sum / jnp.maximum(n, 1.0)
    raise ValueError(f"Unknown reduction '{reduction}'")
