"""InfoNCE contrastive loss for retrieval training.

The analog of the reference retrieval loss (reference: nemo_automodel/
components/loss/infonce.py; recipes train_bi_encoder). In-batch negatives:
each query's positive is its own document; every other document in the
(global) batch is a negative.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def info_nce_loss(
    query_emb: jnp.ndarray,  # (B, D)
    doc_emb: jnp.ndarray,    # (B, D)
    *,
    temperature: float = 0.05,
    symmetric: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (sum_loss, count) matching the framework loss contract."""
    q = query_emb / (jnp.linalg.norm(query_emb, axis=-1, keepdims=True) + 1e-8)
    d = doc_emb / (jnp.linalg.norm(doc_emb, axis=-1, keepdims=True) + 1e-8)
    logits = (q @ d.T).astype(jnp.float32) / temperature  # (B, B)
    labels = jnp.arange(q.shape[0])
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    loss_q = jnp.sum(lse - jnp.take_along_axis(logits, labels[:, None], 1)[:, 0])
    if symmetric:
        lse_d = jax.scipy.special.logsumexp(logits.T, axis=-1)
        loss_d = jnp.sum(lse_d - jnp.take_along_axis(logits.T, labels[:, None], 1)[:, 0])
        total = 0.5 * (loss_q + loss_d)
    else:
        total = loss_q
    return total, jnp.float32(q.shape[0])


def mean_pool(hidden: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Masked mean pooling (B,S,H) → (B,H)."""
    m = mask.astype(hidden.dtype)[..., None]
    return jnp.sum(hidden * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)


def normalized_mean_pool(hidden: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """mean_pool + L2 normalization — the shared embedding head of the
    bi-encoder recipes (train/distill/mining use ONE definition)."""
    e = mean_pool(hidden, mask)
    return e / jnp.maximum(jnp.linalg.norm(e, axis=-1, keepdims=True), 1e-8)
