"""Fused linear + cross-entropy: the lm_head loss without materialized logits.

The analog of `FusedLinearCrossEntropy` (reference: nemo_automodel/
components/loss/linear_ce.py:130, Triton cut-cross-entropy): the model
returns hidden states (`logits_to_keep=1` trick, train_ft.py:1031) and the
loss projects CHUNKS of the sequence through the lm_head inside a
rematerialized `lax.scan`, so peak memory holds one (chunk, vocab) logits
block instead of (batch*seq, vocab). XLA keeps the chunk matmul on the MXU;
backward recomputes each chunk's logits (flops-for-memory, the same trade
the Triton kernel makes).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from automodel_tpu.loss.masked_ce import IGNORE_INDEX


def fused_linear_cross_entropy(
    hidden: jnp.ndarray,          # (B, S, H)
    lm_head_kernel: jnp.ndarray,  # (H, V)
    labels: jnp.ndarray,          # (B, S)
    *,
    chunk_size: int = 1024,
    ignore_index: int = IGNORE_INDEX,
    logits_soft_cap: float | None = None,
    token_weights: jnp.ndarray | None = None,  # (B, S) per-token CE weight
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (sum_ce_fp32, num_valid_tokens_fp32).

    `token_weights` scales each valid token's CE before the sum (the dLLM
    1/p_mask ELBO weight rides this); the returned count stays unweighted.
    """
    B, S, H = hidden.shape
    flat_h = hidden.reshape(B * S, H)
    flat_l = labels.reshape(B * S)
    N = B * S
    chunk_size = min(chunk_size, N)
    pad = (-N) % chunk_size
    flat_w = None
    if token_weights is not None:
        flat_w = token_weights.reshape(B * S).astype(jnp.float32)
    if pad:
        flat_h = jnp.pad(flat_h, ((0, pad), (0, 0)))
        flat_l = jnp.pad(flat_l, (0, pad), constant_values=ignore_index)
        if flat_w is not None:
            flat_w = jnp.pad(flat_w, (0, pad))
    n_chunks = flat_h.shape[0] // chunk_size
    flat_h = flat_h.reshape(n_chunks, chunk_size, H)
    flat_l = flat_l.reshape(n_chunks, chunk_size)
    if flat_w is not None:
        flat_w = flat_w.reshape(n_chunks, chunk_size)

    @partial(jax.checkpoint, policy=jax.checkpoint_policies.nothing_saveable)
    def chunk_loss(carry, xs):
        h, l, w = xs
        logits = jnp.einsum(
            "ch,hv->cv", h, lm_head_kernel.astype(h.dtype),
            preferred_element_type=jnp.float32,
        )
        if logits_soft_cap is not None:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
        mask = l != ignore_index
        safe = jnp.where(mask, l, 0)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        ce = jnp.where(mask, lse - picked, 0.0)
        if w is not None:
            ce = ce * w
        ce_sum, n = carry
        return (ce_sum + jnp.sum(ce), n + jnp.sum(mask).astype(jnp.float32)), None

    xs = (flat_h, flat_l, flat_w)
    (ce_sum, n), _ = jax.lax.scan(
        chunk_loss, (jnp.float32(0.0), jnp.float32(0.0)), xs
    )
    return ce_sum, n
