"""Test harness: force an 8-device virtual CPU mesh before JAX backend init.

The analog of the reference's FakeStore/fake-process-group trick
(reference: tests/unit_tests/distributed/test_cp_sharder.py) — distributed
semantics are exercised on a host-only mesh with no accelerators.

NOTE: do NOT enable jax's persistent compilation cache here — deserializing
a cached CPU executable that contains collectives (any shard_map/pp test)
aborts the process in this jaxlib (reproduced: first run populates and
passes, second run SIGABRTs loading the cache). Suite wall time is managed
by test tiering (pytest markers) instead.
"""

from automodel_tpu.utils.hostplatform import force_cpu_devices

force_cpu_devices(8)
