"""Test harness: force an 8-device virtual CPU mesh before JAX imports.

The analog of the reference's FakeStore/fake-process-group trick
(reference: tests/unit_tests/distributed/test_cp_sharder.py) — distributed
semantics are exercised on a host-only mesh with no accelerators.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The container's sitecustomize registers the axon TPU platform with higher
# priority than the env var; force the config knob before backend init.
jax.config.update("jax_platforms", "cpu")
