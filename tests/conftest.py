"""Test harness: force an 8-device virtual CPU mesh before JAX backend init.

The analog of the reference's FakeStore/fake-process-group trick
(reference: tests/unit_tests/distributed/test_cp_sharder.py) — distributed
semantics are exercised on a host-only mesh with no accelerators.
"""

from automodel_tpu.utils.hostplatform import force_cpu_devices

force_cpu_devices(8)
