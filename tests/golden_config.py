"""The pinned recipes behind the golden-value tier (see
scripts/generate_golden.py).

Five recipe families are under per-step golden regression (the reference
commits such JSONLs per recipe family, reference: tests/ci_tests/
golden_values/**): dense SFT, MoE (ep mesh), LoRA, VLM (llava) and dLLM
(MDLM). Regenerate ONLY on intentional numeric changes.
"""

import os

from automodel_tpu.config import ConfigNode

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden_values")

_DENSE_HF = {
    "architectures": ["LlamaForCausalLM"],
    "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "num_key_value_heads": 2,
}

_MOE_HF = {
    "architectures": ["Qwen3MoeForCausalLM"],
    "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "num_key_value_heads": 2, "num_experts": 4, "num_experts_per_tok": 2,
    "moe_intermediate_size": 32, "router_aux_loss_coef": 0.01,
}


def _base(run_dir: str, **over) -> ConfigNode:
    cfg = ConfigNode({
        "seed": 1234,
        "auto_resume": False,
        "run_dir": run_dir,
        "model": {
            "hf_config": dict(_DENSE_HF),
            "dtype": "float32",
            "remat_policy": "none",
        },
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
            "num_samples": 128, "seq_len": 64, "vocab_size": 256, "seed": 7,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 2, "seed": 7},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.01},
        "lr_scheduler": {"warmup_steps": 2, "decay_steps": 20, "style": "cosine"},
        "step_scheduler": {"max_steps": 8, "ckpt_every_steps": 1000},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 64},
    })
    for k, v in over.items():
        cfg.set(k, v)
    return cfg


def golden_cfg(run_dir: str) -> ConfigNode:
    """The original dense pinned recipe (kept for compatibility)."""
    return _base(run_dir)


def _moe_cfg(run_dir: str) -> ConfigNode:
    cfg = _base(run_dir)
    cfg.set("model.hf_config", dict(_MOE_HF))
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    return cfg


def _lora_cfg(run_dir: str) -> ConfigNode:
    cfg = _base(run_dir)
    cfg.set("peft", {"r": 4, "alpha": 8.0, "target_modules": ["q_proj", "v_proj"]})
    return cfg


def _vlm_cfg(run_dir: str) -> ConfigNode:
    cfg = _base(run_dir, recipe="vlm_finetune")
    cfg.set("model.hf_config", {
        "architectures": ["LlavaForConditionalGeneration"],
        "model_type": "llava",
        "image_token_index": 250,
        "vision_config": {
            "model_type": "clip_vision_model",
            "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 2,
            "image_size": 56, "patch_size": 14,
        },
        "text_config": dict(_DENSE_HF),
    })
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.vlm.MockVLMDatasetConfig",
        "num_samples": 64, "seq_len": 64, "vocab_size": 256,
        "image_size": 56, "patch_size": 14, "image_token_id": 250, "seed": 7,
    })
    cfg.set("step_scheduler.max_steps", 6)
    return cfg


def _dllm_cfg(run_dir: str) -> ConfigNode:
    cfg = _base(run_dir, recipe="dllm_train_ft")
    cfg.set("dllm", {"mode": "mdlm", "mask_token_id": 255})
    cfg.set("step_scheduler.max_steps", 6)
    return cfg


def _cp_cfg(run_dir: str) -> ConfigNode:
    """Ring-CP convergence pin: the cp=2 load-balanced layout must track the
    committed loss curve step-for-step (the long-context parallelism path)."""
    cfg = _base(run_dir)
    cfg.set("distributed", {"dp_shard": -1, "cp": 2})
    return cfg


def _pp_cfg(run_dir: str) -> ConfigNode:
    """1F1B pipeline convergence pin (explicit fwd/bwd interleave path)."""
    cfg = _base(run_dir)
    cfg.set("distributed", {
        "dp_shard": -1, "pp": 2, "pipeline_schedule": "1f1b",
        "pipeline_microbatches": 2,
    })
    return cfg


#: name → config factory; each family has a committed training.jsonl
GOLDEN_RECIPES = {
    "dense": golden_cfg,
    "moe": _moe_cfg,
    "lora": _lora_cfg,
    "vlm": _vlm_cfg,
    "dllm": _dllm_cfg,
    "cp": _cp_cfg,
    "pp_1f1b": _pp_cfg,
}


def golden_path(name: str) -> str:
    if name == "dense":  # original flat location, kept stable
        return os.path.join(GOLDEN_DIR, "training.jsonl")
    return os.path.join(GOLDEN_DIR, name, "training.jsonl")
