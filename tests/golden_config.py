"""The pinned recipe behind the golden-value tier (see scripts/generate_golden.py)."""

import os

from automodel_tpu.config import ConfigNode

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden_values")


def golden_cfg(run_dir: str) -> ConfigNode:
    return ConfigNode({
        "seed": 1234,
        "auto_resume": False,
        "run_dir": run_dir,
        "model": {
            "hf_config": {
                "architectures": ["LlamaForCausalLM"],
                "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
                "num_hidden_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2,
            },
            "dtype": "float32",
            "remat_policy": "none",
        },
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
            "num_samples": 128, "seq_len": 64, "vocab_size": 256, "seed": 7,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 2, "seed": 7},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.01},
        "lr_scheduler": {"warmup_steps": 2, "decay_steps": 20, "style": "cosine"},
        "step_scheduler": {"max_steps": 8, "ckpt_every_steps": 1000},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 64},
    })
