import os

import pytest

from automodel_tpu.config import (
    ConfigError,
    ConfigNode,
    apply_overrides,
    load_yaml,
    parse_override,
)


def test_attr_and_dotted_access():
    cfg = ConfigNode({"model": {"hidden_size": 128, "rope": {"theta": 10000.0}}})
    assert cfg.model.hidden_size == 128
    assert cfg.get("model.rope.theta") == 10000.0
    assert cfg.get("model.missing", "d") == "d"
    cfg.set("model.rope.theta", 500000.0)
    assert cfg.model.rope.theta == 500000.0
    assert "model.rope.theta" in cfg
    assert cfg.to_dict()["model"]["rope"]["theta"] == 500000.0


def test_env_interpolation(monkeypatch):
    monkeypatch.setenv("AM_TEST_VAR", "hello")
    cfg = ConfigNode({"a": "${AM_TEST_VAR}", "b": "${MISSING_VAR:fallback}"})
    assert cfg.a == "hello"
    assert cfg.b == "fallback"
    with pytest.raises(ConfigError):
        ConfigNode({"c": "${DEFINITELY_MISSING_VAR}"})


def test_instantiate_target():
    cfg = ConfigNode(
        {"_target_": "automodel_tpu.distributed.mesh.MeshConfig", "tp": 2, "dp_shard": 4}
    )
    mc = cfg.instantiate()
    assert mc.tp == 2 and mc.dp_shard == 4
    mc2 = cfg.instantiate(tp=1)
    assert mc2.tp == 1


def test_instantiate_allowlist():
    cfg = ConfigNode({"_target_": "os.system", "command": "true"})
    with pytest.raises(ConfigError):
        cfg.instantiate()


def test_nested_instantiate():
    cfg = ConfigNode(
        {
            "_target_": "builtins.dict",
            "inner": {"_target_": "automodel_tpu.distributed.mesh.MeshConfig", "tp": 2},
        }
    )
    out = cfg.instantiate()
    assert out["inner"].tp == 2


def test_secret_redaction():
    cfg = ConfigNode({"wandb_api_key": "abc123", "lr": 0.1})
    d = cfg.to_dict(redact=True)
    assert d["wandb_api_key"] == "***"
    assert "abc123" not in repr(cfg)


def test_overrides():
    cfg = ConfigNode({"optim": {"lr": 1e-4}})
    key, val = parse_override("--optim.lr=3e-4")
    assert key == "optim.lr" and val == pytest.approx(3e-4)
    apply_overrides(cfg, ["--optim.lr=5e-4", "--new.flag=[1,2]"])
    assert cfg.optim.lr == pytest.approx(5e-4)
    assert cfg.get("new.flag") == [1, 2]


def test_load_yaml(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text("model:\n  n_layers: 4\noptim:\n  lr: 1.0e-3\n")
    cfg = load_yaml(str(p))
    assert cfg.model.n_layers == 4
    assert cfg.optim.lr == pytest.approx(1e-3)


def test_typed_recipe_config_facade_and_strictness():
    """RecipeConfig coerces sections lazily and rejects typo'd keys
    (previously silently dropped)."""
    import pytest as _pytest

    from automodel_tpu.config import ConfigNode
    from automodel_tpu.recipes.typed_config import RecipeConfig

    cfg = ConfigNode({
        "distributed": {"dp_shard": -1, "tp": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "dataloader": {"microbatch_size": 4, "grad_acc_steps": 2},
        "checkpoint": {"enabled": False, "restore_from": "/x"},  # allowed extra
        "step_scheduler": {"max_steps": 5},
        "peft": {"r": 4, "alpha": 8.0, "target_modules": ["q_proj"]},
    })
    t = RecipeConfig(cfg)
    assert t.optimizer.lr == 1e-3
    assert t.optimizer is t.optimizer  # cached
    assert t.dataloader.grad_acc_steps == 2
    assert t.checkpoint.enabled is False  # restore_from tolerated
    assert t.step_scheduler.max_steps == 5
    assert t.peft.target_modules == ("q_proj",)
    assert t.qat.enabled is False  # absent section → defaults

    bad = ConfigNode({"optimizer": {"name": "adamw", "lr2": 1e-3}})
    with _pytest.raises(ValueError, match="lr2"):
        RecipeConfig(bad).optimizer
