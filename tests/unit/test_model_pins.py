"""Numeric golden pins for families without a torch-oracle parity test.

transformers 4.57 (the in-env version) predates these architectures, so
their other tests are structural/self-consistency only (see
test_model_tail.py) — a transposed weight or a wrong norm epsilon could
pass every one of them. Each family here pins a fixed-seed tiny model's
logits against a COMMITTED reference (tests/golden_values/model_pins/);
any numeric drift in the forward path fails the pin (reference discipline:
tests/ci_tests/golden_values/ committed JSONL).

The configs below are DELIBERATE copies of the tiny configs in the other
test files: a pin must not silently move when another test edits its
config. Regenerate after an intentional numeric change with:

    AM_WRITE_PINS=1 python -m pytest tests/unit/test_model_pins.py -q

and commit the diff (review it — a pin change IS a semantics change).
"""

import json
import os
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.registry import get_model_spec

#: compile-heavy (13 families × full forward) — slow tier; the pins still
#: gate CI (the full suite runs slow) without costing the smoke budget
pytestmark = pytest.mark.slow

PIN_DIR = pathlib.Path(__file__).parent.parent / "golden_values" / "model_pins"
WRITE = bool(os.environ.get("AM_WRITE_PINS"))

_TEXT = "text"
_VLM = "vlm"
_BAGEL = "bagel"

FAMILIES = {
    "baichuan": (_TEXT, {
        "architectures": ["BaichuanForCausalLM"], "model_type": "baichuan",
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4, "rms_norm_eps": 1e-6,
    }),
    "ling_v2": (_TEXT, {
        "architectures": ["BailingMoeV2ForCausalLM"], "model_type": "bailing_moe",
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 3, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "use_qk_norm": True, "partial_rotary_factor": 0.5,
        "num_experts": 4, "num_shared_experts": 1, "num_experts_per_tok": 2,
        "n_group": 2, "topk_group": 2, "moe_intermediate_size": 16,
        "first_k_dense_replace": 1, "score_function": "sigmoid",
        "routed_scaling_factor": 1.0, "norm_topk_prob": True,
        "moe_router_enable_expert_bias": True,
    }),
    "glm_moe_dsa": (_TEXT, {
        "architectures": ["GlmMoeDsaForCausalLM"], "model_type": "glm_moe_dsa",
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 4,
        "n_routed_experts": 4, "n_shared_experts": 1,
        "num_experts_per_tok": 2, "moe_intermediate_size": 16,
        "first_k_dense_replace": 0, "norm_topk_prob": True,
        "routed_scaling_factor": 1.0,
        "kv_lora_rank": 16, "q_lora_rank": 12,
        "qk_nope_head_dim": 8, "qk_rope_head_dim": 8, "v_head_dim": 8,
        "index_topk": 6, "index_n_heads": 2, "index_head_dim": 16,
        "indexer_types": ["full", "shared"],
    }),
    "gemma4_moe": (_TEXT, {
        "architectures": ["Gemma4ForConditionalGeneration"], "model_type": "gemma4",
        "text_config": {
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 4, "num_attention_heads": 4,
            "num_key_value_heads": 2, "head_dim": 8,
            "layer_types": [
                "sliding_attention", "full_attention",
                "sliding_attention", "full_attention",
            ],
            "sliding_window": 8, "rope_theta": 1000000.0,
            "rope_local_base_freq": 10000.0, "query_pre_attn_scalar": 8,
            "num_kv_shared_layers": 2,
            "num_experts": 4, "top_k_experts": 2, "moe_intermediate_size": 16,
            "rms_norm_eps": 1e-6,
        },
        "tie_word_embeddings": True,
    }),
    "step3p5": (_TEXT, {
        "architectures": ["Step3p5ForCausalLM"], "model_type": "step3p5",
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 4, "num_attention_heads": 4,
        "num_attention_groups": 2, "head_dim": 8,
        "attention_other_setting": {"num_attention_heads": 2, "num_attention_groups": 1},
        "layer_types": [
            "full_attention", "sliding_attention",
            "sliding_attention", "full_attention",
        ],
        "sliding_window": 8,
        "rope_theta": [10000.0, 5000.0, 5000.0, 10000.0],
        "partial_rotary_factors": [1.0, 0.5, 0.5, 1.0],
        "use_rope_layers": [True, True, False, True],
        "use_head_wise_attn_gate": True,
        "moe_layers_enum": [1, 3],
        "moe_num_experts": 4, "moe_top_k": 2, "moe_intermediate_size": 16,
        "moe_router_activation": "sigmoid", "use_moe_router_bias": True,
        "share_expert_dims": [16, 16, 16, 16],
        "rms_norm_eps": 1e-5,
    }),
    "mimo_v2_flash": (_TEXT, {
        "architectures": ["MiMoV2FlashForCausalLM"], "model_type": "mimo_v2_flash",
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 4, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8, "v_head_dim": 8,
        "swa_num_attention_heads": 2, "swa_num_key_value_heads": 1,
        "swa_head_dim": 16, "swa_v_head_dim": 8,
        "hybrid_layer_pattern": [0, 1, 1, 0],
        "sliding_window": 8,
        "rope_theta": 5000000.0, "swa_rope_theta": 10000.0,
        "partial_rotary_factor": 0.5,
        "add_full_attention_sink_bias": False,
        "add_swa_attention_sink_bias": True,
        "n_routed_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 16, "scoring_func": "sigmoid",
        "n_group": 2, "topk_group": 2, "norm_topk_prob": True,
        "moe_layer_freq": [0, 1, 1, 1], "n_shared_experts": 1,
    }),
    "minimax_m3": (_TEXT, {
        "architectures": ["MiniMaxM3SparseForCausalLM"], "model_type": "minimax_m3",
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 16,
        "dense_intermediate_size": 64, "shared_intermediate_size": 16,
        "num_hidden_layers": 3, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8, "rotary_dim": 4,
        "rope_theta": 5000000.0, "use_gemma_norm": True, "use_qk_norm": True,
        "num_local_experts": 4, "num_experts_per_tok": 2,
        "n_shared_experts": 1, "scoring_func": "sigmoid",
        "use_routing_bias": True, "routed_scaling_factor": 2.0,
        "moe_layer_freq": [0, 1, 1],
        "sparse_attention_config": {
            "use_sparse_attention": True, "sparse_attention_freq": [0, 1, 1],
            "sparse_num_index_heads": 2, "sparse_index_dim": 8,
            "sparse_block_size": 4, "sparse_topk_blocks": 3,
            "sparse_init_block": 1, "sparse_local_block": 1,
        },
        "rms_norm_eps": 1e-6,
    }),
    "qwen3_5": (_TEXT, {
        "architectures": ["Qwen3_5ForCausalLM"], "model_type": "qwen3_5",
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "layer_types": ["linear_attention", "full_attention"],
        "linear_num_value_heads": 4, "linear_num_key_heads": 2,
        "linear_key_head_dim": 8, "linear_value_head_dim": 8,
    }),
    "qwen3_5_moe": (_TEXT, {
        "architectures": ["Qwen3_5MoeForConditionalGeneration"],
        "model_type": "qwen3_5_moe",
        "text_config": {
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 4, "num_attention_heads": 4,
            "num_key_value_heads": 2, "head_dim": 8,
            "layer_types": [
                "linear_attention", "full_attention",
                "linear_attention", "full_attention",
            ],
            "linear_num_value_heads": 4, "linear_num_key_heads": 2,
            "linear_key_head_dim": 8, "linear_value_head_dim": 8,
            "num_experts": 4, "num_experts_per_tok": 2,
            "moe_intermediate_size": 16, "shared_expert_intermediate_size": 16,
            "norm_topk_prob": True, "rope_theta": 10000.0,
        },
    }),
    "kimi_vl": (_VLM, {
        "architectures": ["KimiVLForConditionalGeneration"], "model_type": "kimi_vl",
        "media_placeholder_token_id": 120,
        "vision_config": {
            "patch_size": 14, "init_pos_emb_height": 8, "init_pos_emb_width": 8,
            "num_attention_heads": 2, "num_hidden_layers": 2,
            "hidden_size": 32, "intermediate_size": 48,
            "merge_kernel_size": [2, 2],
        },
        "text_config": {
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 4,
            "n_routed_experts": 4, "n_shared_experts": 1,
            "num_experts_per_tok": 2, "moe_intermediate_size": 16,
            "first_k_dense_replace": 1, "norm_topk_prob": True,
            "kv_lora_rank": 16, "q_lora_rank": 12,
            "qk_nope_head_dim": 8, "qk_rope_head_dim": 8, "v_head_dim": 8,
        },
    }),
    "qwen3_vl_moe": (_VLM, {
        "architectures": ["Qwen3VLMoeForConditionalGeneration"],
        "model_type": "qwen3_vl_moe",
        "image_token_id": 120,
        "vision_config": {
            "patch_size": 14, "temporal_patch_size": 2, "spatial_merge_size": 2,
            "num_heads": 2, "depth": 3, "hidden_size": 32, "intermediate_size": 48,
            "out_hidden_size": 32, "num_position_embeddings": 64,
            "deepstack_visual_indexes": [0, 1],
        },
        "text_config": {
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "head_dim": 8,
            "num_experts": 4, "num_experts_per_tok": 2,
            "moe_intermediate_size": 16, "norm_topk_prob": True,
            "rope_scaling": {"mrope_section": [2, 1, 1], "mrope_interleaved": True},
        },
    }),
    "minimax_m3_vl": (_VLM, {
        "architectures": ["MiniMaxM3SparseForConditionalGeneration"],
        "model_type": "minimax_m3_vl",
        "image_token_index": 120, "projector_hidden_size": 48,
        "multimodal_projector_bias": True, "patch_merge_bias": True,
        "vision_config": {
            "hidden_size": 32, "num_attention_heads": 2, "num_hidden_layers": 2,
            "intermediate_size": 48, "patch_size": 14,
            "img_token_compression_config": {
                "spatial_merge_size": 2, "temporal_patch_size": 2,
            },
        },
        "text_config": {
            "architectures": ["MiniMaxM3SparseForCausalLM"],
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 16,
            "dense_intermediate_size": 64, "shared_intermediate_size": 16,
            "num_hidden_layers": 3, "num_attention_heads": 4,
            "num_key_value_heads": 2, "head_dim": 8, "rotary_dim": 4,
            "use_gemma_norm": True, "use_qk_norm": True,
            "num_local_experts": 4, "num_experts_per_tok": 2,
            "n_shared_experts": 1, "scoring_func": "sigmoid",
            "use_routing_bias": True, "routed_scaling_factor": 2.0,
            "moe_layer_freq": [0, 1, 1],
            "sparse_attention_config": {
                "use_sparse_attention": True, "sparse_attention_freq": [0, 1, 1],
                "sparse_num_index_heads": 2, "sparse_index_dim": 8,
                "sparse_block_size": 4, "sparse_topk_blocks": 3,
                "sparse_init_block": 1, "sparse_local_block": 1,
            },
        },
    }),
    "llama_nemotron_vl": (_VLM, {
        "architectures": ["LlamaNemotronVLModel"], "model_type": "llama_nemotron_vl",
        "img_context_token_id": 120, "downsample_ratio": 0.5,
        "select_layer": -1, "pooling": "avg",
        "vision_config": {
            "model_type": "siglip_vision_model",
            "hidden_size": 32, "intermediate_size": 48, "num_hidden_layers": 2,
            "num_attention_heads": 2, "image_size": 56, "patch_size": 14,
        },
        "llm_config": {
            "architectures": ["LlamaBidirectionalModel"],
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "pooling": "avg",
        },
    }),
    "bagel": (_BAGEL, {
        "architectures": ["BagelForUnifiedMultimodal"], "model_type": "bagel",
        "visual_gen": True,
        "llm_config": {
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "qk_norm": True,
        },
        "vision_config": {
            "hidden_size": 32, "intermediate_size": 48, "num_hidden_layers": 2,
            "num_attention_heads": 2, "image_size": 56, "patch_size": 14,
        },
        "vit_max_num_patch_per_side": 8, "latent_patch_size": 2,
        "max_latent_size": 8, "vae_config": {"z_channels": 4, "downsample": 8},
    }),
}


def _vlm_inputs(image_token: int, n_img: int = 4, B: int = 2, S: int = 24):
    rng = np.random.default_rng(0)
    text = rng.integers(1, 100, (B, S - n_img), dtype=np.int32)
    ids = np.concatenate(
        [text[:, :4], np.full((B, n_img), image_token, np.int32), text[:, 4:]],
        axis=1,
    )
    pixels = rng.normal(size=(B, 56, 56, 3)).astype(np.float32)
    return jnp.asarray(ids), jnp.asarray(pixels)


def _run(name):
    kind, hf = FAMILIES[name]
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    params = spec.module.init(cfg, jax.random.key(0))
    if kind == _BAGEL:
        rng = np.random.default_rng(0)
        B, S = 2, 40
        ids = jnp.asarray(rng.integers(1, 100, (B, S), dtype=np.int32))
        tt = np.zeros((B, S), np.int32)
        tt[:, 2:18] = 1
        tt[:, 20:36] = 2
        pix = jnp.asarray(rng.normal(size=(B, 56, 56, 3)).astype(np.float32))
        lat = jnp.asarray(rng.normal(size=(B, 4, 8, 8)).astype(np.float32))
        t = jnp.asarray(rng.normal(size=(B,)).astype(np.float32))
        out, _gen = spec.module.forward(
            params, cfg, ids, jnp.asarray(tt), pixel_values=pix,
            latents=lat, timesteps=t, rng=jax.random.key(1),
        )
    elif kind == _VLM:
        tok = int(
            hf.get("image_token_id")
            or hf.get("image_token_index")
            or hf.get("media_placeholder_token_id")
            or hf.get("img_context_token_id")
        )
        ids, pixels = _vlm_inputs(tok)
        out = spec.module.forward(params, cfg, ids, pixels)
    else:
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(1, 100, (2, 16), dtype=np.int32))
        out = spec.module.forward(params, cfg, ids)
    if isinstance(out, tuple):
        out = out[0]
    out = np.asarray(out, dtype=np.float64)
    return {
        "arch": hf["architectures"][0],
        "shape": list(out.shape),
        "slice": out[0, -1, :16].tolist(),
        "mean": float(out.mean()),
        "std": float(out.std()),
    }


@pytest.mark.parametrize("name", sorted(FAMILIES))
def test_model_pin(name):
    pin_file = PIN_DIR / f"{name}.json"
    got = _run(name)
    if WRITE:
        PIN_DIR.mkdir(parents=True, exist_ok=True)
        pin_file.write_text(json.dumps(got, indent=1) + "\n")
        pytest.skip(f"wrote {pin_file}")
    assert pin_file.exists(), (
        f"missing committed pin {pin_file} — generate with AM_WRITE_PINS=1"
    )
    want = json.loads(pin_file.read_text())
    assert got["shape"] == want["shape"]
    np.testing.assert_allclose(got["slice"], want["slice"], atol=1e-5, rtol=0)
    np.testing.assert_allclose(got["mean"], want["mean"], atol=1e-6, rtol=0)
    np.testing.assert_allclose(got["std"], want["std"], atol=1e-6, rtol=0)
