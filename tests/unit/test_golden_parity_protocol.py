"""Loss-curve parity protocol: torch/HF training stack vs this framework.

The offline half of the golden-values protocol (docs/PARITY.md; reference:
tests/ci_tests/golden_values/). The reference's goldens are tied to
pretrained checkpoints this environment cannot download, so the oracle
here is the reference STACK itself: the same tiny llama checkpoint, the
same data order, AdamW with the same hyperparameters, fp32 everywhere —
torch trains it, this framework trains it, and the per-step loss curves
must stay within tight relative tolerance over many steps (this checks
model math + loss normalization + optimizer semantics + grad clipping in
one shot, exactly what a golden curve checks)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.parity

torch = pytest.importorskip("torch")

STEPS = 20
LR = 1e-3
WD = 0.1
CLIP = 1.0
B, S, V = 4, 32, 128


def _data():
    rng = np.random.default_rng(0)
    return rng.integers(1, V, (STEPS, B, S + 1), dtype=np.int64)


def test_sft_loss_curve_matches_torch(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    from automodel_tpu.checkpoint import HFCheckpointReader, get_adapter
    from automodel_tpu.loss import fused_linear_cross_entropy
    from automodel_tpu.models.registry import get_model_spec
    from automodel_tpu.optim import OptimizerConfig
    from automodel_tpu.training import init_train_state, make_train_step
    from automodel_tpu.training.train_step import TrainStepConfig

    config = LlamaConfig(
        vocab_size=V, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config)
    model.save_pretrained(tmp_path, safe_serialization=True)
    with open(tmp_path / "config.json", "w") as f:
        json.dump(json.loads(config.to_json_string()), f)
    data = _data()

    # ---- torch reference run (the reference stack's semantics) ----
    model = model.float().train()
    opt = torch.optim.AdamW(model.parameters(), lr=LR, weight_decay=WD)
    torch_losses = []
    for t in range(STEPS):
        ids = torch.tensor(data[t, :, :-1])
        labels = torch.tensor(data[t, :, 1:])
        logits = model(ids).logits.float()
        loss = torch.nn.functional.cross_entropy(
            logits.reshape(-1, V), labels.reshape(-1)
        )
        opt.zero_grad()
        loss.backward()
        torch.nn.utils.clip_grad_norm_(model.parameters(), CLIP)
        opt.step()
        torch_losses.append(float(loss))

    # ---- this framework, same checkpoint / data / hyperparameters ----
    reader = HFCheckpointReader(str(tmp_path))
    spec = get_model_spec(reader.hf_config())
    cfg = spec.config_from_hf(reader.hf_config(), dtype=jnp.float32, remat_policy="none")
    params = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs).from_hf(reader)
    params = jax.tree.map(lambda p: jnp.asarray(p, jnp.float32), params)

    def loss_fn(p, batch, rng):
        hidden = spec.module.forward(p, cfg, batch["input_ids"], return_hidden=True)
        return fused_linear_cross_entropy(
            hidden, p["lm_head"]["kernel"], batch["labels"], chunk_size=64
        )

    tx = OptimizerConfig(name="adamw", lr=LR, weight_decay=WD).build()
    state = init_train_state(params, tx)
    step = jax.jit(make_train_step(loss_fn, tx, None, TrainStepConfig(max_grad_norm=CLIP)))

    jax_losses = []
    for t in range(STEPS):
        batch = {
            "input_ids": jnp.asarray(data[t, None, :, :-1], jnp.int32),
            "labels": jnp.asarray(data[t, None, :, 1:], jnp.int32),
        }
        state, m = step(state, batch, jax.random.key(t))
        jax_losses.append(float(m["loss"]))

    # per-step parity: tight at the start, small drift allowed later
    for t in range(STEPS):
        rtol = 1e-4 if t < 5 else 5e-3
        assert abs(jax_losses[t] - torch_losses[t]) / torch_losses[t] < rtol, (
            t, jax_losses[t], torch_losses[t],
        )

    # artifact for the documented protocol: run scripts/compare_golden.py
    ours = tmp_path / "ours.jsonl"
    ref = tmp_path / "ref.jsonl"
    ours.write_text("\n".join(
        json.dumps({"step": t + 1, "loss": jax_losses[t]}) for t in range(STEPS)
    ))
    ref.write_text("\n".join(
        json.dumps({"step": t, "loss": torch_losses[t]}) for t in range(STEPS)
    ))
    import os
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    out = subprocess.run(
        [sys.executable, "scripts/compare_golden.py", str(ours), str(ref),
         "--loss-rtol", "0.01"],
        capture_output=True, text=True, cwd=repo_root, timeout=60,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "PARITY OK" in out.stdout
