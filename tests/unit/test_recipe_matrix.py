"""Feature-matrix tests: PEFT × MoE across every recipe family.

Round-1 verdict called out the recipe fences (KD×MoE, KD×PEFT, seq-cls×MoE,
bi-encoder×MoE, dLLM×MoE, …) as collectively making the advertised feature
matrix sparse. These tests pin the lifted combinations end-to-end on the
8-device CPU mesh (the reference exercises the same matrix through its
recipe CI tier, reference: tests/ci_tests/).
"""

import json

import numpy as np
import pytest

pytestmark = pytest.mark.recipe

from automodel_tpu.cli.app import resolve_recipe_class
from automodel_tpu.config import ConfigNode

MOE_HF = {
    "architectures": ["Qwen3MoeForCausalLM"],
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "num_key_value_heads": 2, "num_experts": 4, "num_experts_per_tok": 2,
    "moe_intermediate_size": 16, "router_aux_loss_coef": 0.01,
}


def _records(tmp_path, name="training.jsonl"):
    return [json.loads(l) for l in open(tmp_path / name) if l.strip()]


def _finite(recs):
    assert recs and all(np.isfinite(r["loss"]) for r in recs)


def _run(cfg):
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    return r


def test_seq_cls_moe_backbone(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "llm_seq_cls")
    cfg.set("model.hf_config", dict(MOE_HF, vocab_size=512))
    cfg.set("seq_cls", {"num_labels": 4})
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockSeqClsDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512, "num_labels": 4,
    })
    cfg.set("step_scheduler.max_steps", 3)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    _run(cfg)
    recs = _records(tmp_path)
    _finite(recs)
    assert "moe_load_imbalance" in recs[-1]


def test_seq_cls_lora(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "llm_seq_cls")
    cfg.set("seq_cls", {"num_labels": 4})
    cfg.set("peft", {"r": 4, "alpha": 8.0})
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockSeqClsDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512, "num_labels": 4,
    })
    cfg.set("step_scheduler.max_steps", 3)
    r = _run(cfg)
    _finite(_records(tmp_path))
    # trainable tree = adapters + score head only
    keys = set(r.train_state.params)
    assert "score_head" in keys and any("q_proj" in k for k in keys)
    assert "embed" not in keys


def test_kd_moe_student_and_teacher(tmp_path):
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path, recipe="llm_kd")
    cfg.set("model.hf_config", MOE_HF)
    cfg.set("teacher_model", {
        "hf_config": dict(MOE_HF, hidden_size=48),
        "dtype": "float32",
    })
    cfg.set("kd", {"ratio": 0.5, "temperature": 2.0})
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    _run(cfg)
    recs = _records(tmp_path)
    _finite(recs)
    assert "moe_load_imbalance" in recs[-1]


def test_kd_lora_student(tmp_path):
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path, recipe="llm_kd")
    cfg.set("teacher_model", {
        "hf_config": {
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": 128, "hidden_size": 48, "intermediate_size": 96,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2,
        },
        "dtype": "float32",
    })
    cfg.set("kd", {"ratio": 0.5, "temperature": 2.0})
    cfg.set("peft", {"r": 4, "alpha": 8.0})
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    r = _run(cfg)
    _finite(_records(tmp_path))
    n_train = sum(p.size for p in __import__("jax").tree.leaves(r.train_state.params))
    n_base = sum(p.size for p in __import__("jax").tree.leaves(r.base_params))
    assert n_train < n_base  # only adapters train


def test_bi_encoder_moe(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "retrieval_bi_encoder")
    cfg.set("model.hf_config", dict(MOE_HF, vocab_size=512))
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockRetrievalDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512,
    })
    cfg.set("retrieval", {"temperature": 0.05})
    cfg.set("step_scheduler.max_steps", 3)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    r = _run(cfg)
    assert not r.model_cfg.causal
    recs = _records(tmp_path)
    _finite(recs)
    assert "moe_load_imbalance" in recs[-1]


def test_cross_encoder_lora(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "retrieval_cross_encoder")
    cfg.set("peft", {"r": 4, "alpha": 8.0})
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockRerankDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512, "group_size": 4,
    })
    cfg.set("step_scheduler.max_steps", 3)
    _run(cfg)
    _finite(_records(tmp_path))


def test_dllm_moe(tmp_path):
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path, recipe="dllm_train_ft")
    cfg.set("model.hf_config", MOE_HF)
    cfg.set("dllm", {"mode": "mdlm", "mask_token_id": 127})
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    r = _run(cfg)
    assert not r.model_cfg.causal
    recs = _records(tmp_path)
    _finite(recs)
    assert "moe_load_imbalance" in recs[-1]


def test_distill_bi_encoder_lora(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "retrieval_distill_bi_encoder")
    cfg.set("peft", {"r": 4, "alpha": 8.0})
    cfg.set("teacher_model", {
        "hf_config": {
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": 512, "hidden_size": 48, "intermediate_size": 96,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2,
        },
        "dtype": "float32",
    })
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockRetrievalDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512,
    })
    cfg.set("distill", {"weight": 1.0, "infonce_weight": 0.1})
    cfg.set("step_scheduler.max_steps", 3)
    _run(cfg)
    _finite(_records(tmp_path))
