"""Feature-matrix tests: PEFT × MoE across every recipe family.

Round-1 verdict called out the recipe fences (KD×MoE, KD×PEFT, seq-cls×MoE,
bi-encoder×MoE, dLLM×MoE, …) as collectively making the advertised feature
matrix sparse. These tests pin the lifted combinations end-to-end on the
8-device CPU mesh (the reference exercises the same matrix through its
recipe CI tier, reference: tests/ci_tests/).
"""

import json

import numpy as np
import pytest

pytestmark = pytest.mark.recipe

from automodel_tpu.cli.app import resolve_recipe_class
from automodel_tpu.config import ConfigNode

MOE_HF = {
    "architectures": ["Qwen3MoeForCausalLM"],
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "num_key_value_heads": 2, "num_experts": 4, "num_experts_per_tok": 2,
    "moe_intermediate_size": 16, "router_aux_loss_coef": 0.01,
}


def _records(tmp_path, name="training.jsonl"):
    return [json.loads(l) for l in open(tmp_path / name) if l.strip()]


def _finite(recs):
    assert recs and all(np.isfinite(r["loss"]) for r in recs)


def _run(cfg):
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    return r


def test_seq_cls_moe_backbone(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "llm_seq_cls")
    cfg.set("model.hf_config", dict(MOE_HF, vocab_size=512))
    cfg.set("seq_cls", {"num_labels": 4})
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockSeqClsDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512, "num_labels": 4,
    })
    cfg.set("step_scheduler.max_steps", 3)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    _run(cfg)
    recs = _records(tmp_path)
    _finite(recs)
    assert "moe_load_imbalance" in recs[-1]


def test_seq_cls_lora(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "llm_seq_cls")
    cfg.set("seq_cls", {"num_labels": 4})
    cfg.set("peft", {"r": 4, "alpha": 8.0})
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockSeqClsDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512, "num_labels": 4,
    })
    cfg.set("step_scheduler.max_steps", 3)
    r = _run(cfg)
    _finite(_records(tmp_path))
    # trainable tree = adapters + score head only
    keys = set(r.train_state.params)
    assert "score_head" in keys and any("q_proj" in k for k in keys)
    assert "embed" not in keys


def test_kd_moe_student_and_teacher(tmp_path):
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path, recipe="llm_kd")
    cfg.set("model.hf_config", MOE_HF)
    cfg.set("teacher_model", {
        "hf_config": dict(MOE_HF, hidden_size=48),
        "dtype": "float32",
    })
    cfg.set("kd", {"ratio": 0.5, "temperature": 2.0})
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    _run(cfg)
    recs = _records(tmp_path)
    _finite(recs)
    assert "moe_load_imbalance" in recs[-1]


def test_kd_lora_student(tmp_path):
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path, recipe="llm_kd")
    cfg.set("teacher_model", {
        "hf_config": {
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": 128, "hidden_size": 48, "intermediate_size": 96,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2,
        },
        "dtype": "float32",
    })
    cfg.set("kd", {"ratio": 0.5, "temperature": 2.0})
    cfg.set("peft", {"r": 4, "alpha": 8.0})
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    r = _run(cfg)
    _finite(_records(tmp_path))
    n_train = sum(p.size for p in __import__("jax").tree.leaves(r.train_state.params))
    n_base = sum(p.size for p in __import__("jax").tree.leaves(r.base_params))
    assert n_train < n_base  # only adapters train


def test_bi_encoder_moe(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "retrieval_bi_encoder")
    cfg.set("model.hf_config", dict(MOE_HF, vocab_size=512))
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockRetrievalDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512,
    })
    cfg.set("retrieval", {"temperature": 0.05})
    cfg.set("step_scheduler.max_steps", 3)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    r = _run(cfg)
    assert not r.model_cfg.causal
    recs = _records(tmp_path)
    _finite(recs)
    assert "moe_load_imbalance" in recs[-1]


def test_cross_encoder_lora(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "retrieval_cross_encoder")
    cfg.set("peft", {"r": 4, "alpha": 8.0})
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockRerankDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512, "group_size": 4,
    })
    cfg.set("step_scheduler.max_steps", 3)
    _run(cfg)
    _finite(_records(tmp_path))


def test_dllm_moe(tmp_path):
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path, recipe="dllm_train_ft")
    cfg.set("model.hf_config", MOE_HF)
    cfg.set("dllm", {"mode": "mdlm", "mask_token_id": 127})
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    r = _run(cfg)
    assert not r.model_cfg.causal
    recs = _records(tmp_path)
    _finite(recs)
    assert "moe_load_imbalance" in recs[-1]


def test_distill_bi_encoder_lora(tmp_path):
    from tests.unit.test_seqcls_retrieval import _base

    cfg = _base(tmp_path, "retrieval_distill_bi_encoder")
    cfg.set("peft", {"r": 4, "alpha": 8.0})
    cfg.set("teacher_model", {
        "hf_config": {
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": 512, "hidden_size": 48, "intermediate_size": 96,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2,
        },
        "dtype": "float32",
    })
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockRetrievalDatasetConfig",
        "num_samples": 32, "seq_len": 16, "vocab_size": 512,
    })
    cfg.set("distill", {"weight": 1.0, "infonce_weight": 0.1})
    cfg.set("step_scheduler.max_steps", 3)
    _run(cfg)
    _finite(_records(tmp_path))


def _eagle_cfg(tmp_path, recipe, target_hf, spec=None):
    cfg = ConfigNode({
        "recipe": recipe,
        "seed": 3,
        "run_dir": str(tmp_path),
        "target_model": {"hf_config": target_hf, "dtype": "float32"},
        "speculative": spec or {},
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
            "num_samples": 16, "seq_len": 16,
            "vocab_size": target_hf["vocab_size"],
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "lr_scheduler": {"warmup_steps": 1, "decay_steps": 10},
        "step_scheduler": {"max_steps": 3},
        "checkpoint": {
            "enabled": False, "checkpoint_dir": str(tmp_path / "ckpt"),
        },
    })
    return cfg


def test_eagle3_moe_target_and_export(tmp_path):
    """EAGLE-3 with a MoE (qwen3-moe) target: aux-hidden capture rides the
    MoE layer scan; the trained drafter exports in the SGLang layout."""
    cfg = _eagle_cfg(
        tmp_path, "llm_train_eagle3", dict(MOE_HF),
        spec={"draft_vocab_size": 64, "ttt_steps": 2, "aux_layer_ids": [0, 1]},
    )
    r = _run(cfg)
    recs = _records(tmp_path)
    _finite(recs)
    assert "accept_length" in recs[-1]
    out = r.save_consolidated_hf()
    import os

    files = os.listdir(out)
    assert "config.json" in files
    assert any(f.endswith(".safetensors") for f in files)


def test_eagle1_dense_target_and_export(tmp_path):
    """EAGLE-1 feature-regression drafter trains and exports."""
    dense_hf = {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2,
    }
    cfg = _eagle_cfg(
        tmp_path, "llm_train_eagle1", dense_hf,
        spec={"num_layers": 1, "feature_noise": 0.1},
    )
    r = _run(cfg)
    recs = _records(tmp_path)
    _finite(recs)
    assert "hidden_loss" in recs[-1] and "token_loss" in recs[-1]
    out = r.save_consolidated_hf()
    import os

    assert any(f.endswith(".safetensors") for f in os.listdir(out))


def test_spec_acceptance_bench_end_to_end(tmp_path):
    """Train EAGLE-1 briefly, export the drafter, run the acceptance bench
    on the export (VERDICT r4: accept-length JSONL harness)."""
    import json
    import os

    dense_hf = {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2,
    }
    cfg = _eagle_cfg(
        tmp_path / "train", "llm_train_eagle1", dense_hf,
        spec={"num_layers": 1, "feature_noise": 0.0},
    )
    r = _run(cfg)
    drafter_dir = r.save_consolidated_hf()

    bench_cfg = _eagle_cfg(
        tmp_path / "bench", "llm_spec_bench", dense_hf,
        spec={"num_layers": 1},
    )
    bench_cfg.set("drafter_path", str(drafter_dir))
    bench_cfg.set("bench", {"gamma": 3, "path_source": "dataset", "max_batches": 2})
    from automodel_tpu.cli.app import resolve_recipe_class

    b = resolve_recipe_class(bench_cfg)(bench_cfg)
    b.setup()
    b.run_train_validation_loop()
    recs = [
        json.loads(l)
        for l in open(os.path.join(tmp_path / "bench", "acceptance.jsonl"))
        if l.strip()
    ]
    assert recs[-1]["summary"] is True
    assert 1.0 <= recs[-1]["mean_accept_length"] <= 4.0  # 1..gamma+1
    per_batch = [r for r in recs if "batch" in r]
    assert len(per_batch) == 2
    for rec in per_batch:
        assert len(rec["step_hit_rates"]) == 3
        assert all(0.0 <= h <= 1.0 for h in rec["step_hit_rates"])


def test_spec_acceptance_generate_path(tmp_path):
    """path_source=generate: the target's greedy continuation feeds the
    estimator (and a perfect drafter would score gamma+1 on it)."""
    import json
    import os

    dense_hf = {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2,
    }
    cfg = _eagle_cfg(
        tmp_path, "llm_spec_bench", dense_hf, spec={"num_layers": 1},
    )
    cfg.set("bench", {
        "gamma": 2, "path_source": "generate",
        "max_new_tokens": 8, "max_batches": 1,
    })
    from automodel_tpu.cli.app import resolve_recipe_class

    b = resolve_recipe_class(cfg)(cfg)
    b.setup()
    b.run_train_validation_loop()
    recs = [
        json.loads(l)
        for l in open(os.path.join(tmp_path, "acceptance.jsonl"))
        if l.strip()
    ]
    assert recs[-1]["summary"] is True
