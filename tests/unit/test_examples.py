"""Example-YAML surface tests (reference discipline: tests/ci_tests/ —
generated per-recipe configs, every one exercised).

Fast tier: every example parses, its recipe class resolves, and (when it
carries a tiny hf_config) the model spec + config builder accept it.
Recipe tier: every HERMETIC smoke (mock dataset + /tmp run_dir) actually
trains end-to-end in-process.
"""

import pathlib

import numpy as np
import pytest

from automodel_tpu.cli.app import resolve_recipe_class
from automodel_tpu.config import ConfigNode
from automodel_tpu.config.loader import load_yaml

EXAMPLES = sorted(
    pathlib.Path(__file__).parent.parent.parent.glob("examples/**/*.yaml")
)
assert len(EXAMPLES) >= 70, f"example surface shrank: {len(EXAMPLES)}"


def _load(path) -> ConfigNode:
    return load_yaml(str(path))


def _is_hermetic(cfg: ConfigNode) -> bool:
    ds = cfg.get("dataset")
    tgt = ds.get("_target_", "") if ds is not None else ""
    mock = "mock" in str(tgt).lower() or "bagel_mock" in str(tgt)
    run_dir = str(cfg.get("run_dir", ""))
    return mock and run_dir.startswith("/tmp")


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: str(p.relative_to(p.parents[2])))
def test_example_parses_and_resolves(path):
    cfg = _load(path)
    cls = resolve_recipe_class(cfg)
    assert cls is not None
    mcfg = cfg.get("model")
    hf = mcfg.get("hf_config") if mcfg is not None else None
    if hf is not None and "architectures" in hf:
        from automodel_tpu.models.registry import get_model_spec

        hf_d = hf.to_dict() if hasattr(hf, "to_dict") else dict(hf)
        spec = get_model_spec(hf_d)
        # the config builder must accept the YAML's tiny config
        spec.config_from_hf(hf_d, remat_policy="none")


#: hermetic by shape but not runnable on the CPU smoke host — excluded with
#: a reason, never silently (test_example_parses_and_resolves still covers
#: them)
_SMOKE_EXCLUDE = {
    # 1.1B × 2048-seq benchmark: a single CPU step takes longer than the
    # whole smoke tier; meaningful only on an accelerator
    "examples/llm_benchmark/llama_1b_bench.yaml",
}

#: compile-heaviest smokes (≥15s on the 1-core host, --durations audit) whose
#: recipes already have a dedicated tier-1 recipe test — slow tier keeps the
#: end-to-end YAML coverage without blowing the 870s smoke budget
_SLOW_SMOKES = {
    "examples/multimodal/omni_mock_smoke.yaml",      # test_omni recipe test
    "examples/multimodal/bagel_smoke.yaml",          # test_bagel recipe test
    "examples/vlm_finetune/minimax_m3_vl_smoke.yaml",  # test_minimax_m3
    "examples/multimodal/pretrain_smoke.yaml",       # test_vlm recipe tests
    "examples/llm_finetune/deepseek_v4_dsa_smoke.yaml",  # test_dsa recipe smoke
    "examples/llm_finetune/qwen3_next_smoke.yaml",   # test_hf_parity logits
    "examples/vlm_kd/llava_kd_smoke.yaml",           # test_recipe_matrix KD
    "examples/llm_finetune/mimo_v2_flash_smoke.yaml",  # test_model_tail + pin
    "examples/llm_finetune/gemma4_moe_smoke.yaml",   # test_model_tail + pin
    "examples/vlm_finetune/qwen3_vl_moe_mock_smoke.yaml",  # test_qwen3_vl
    "examples/vlm_finetune/kimi_vl_mock_smoke.yaml",  # test_kimi_vl
    "examples/diffusion/dit_flow_smoke.yaml",        # test_diffusion_pipeline
    "examples/llm_finetune/deepseek_v32_smoke.yaml",  # test_dsa recipe tests
    # same tiny-llama train as tiny_llama_mock_smoke + the resilience knobs,
    # which tier-1 already exercises end-to-end in test_resilience.py
    "examples/llm_finetune/tiny_llama_resilient_smoke.yaml",
}

_SMOKES = [
    pytest.param(
        p,
        marks=[pytest.mark.slow]
        if str(p.relative_to(p.parents[2])) in _SLOW_SMOKES
        else [],
    )
    for p in EXAMPLES
    if _is_hermetic(_load(p))
    and str(p.relative_to(p.parents[2])) not in _SMOKE_EXCLUDE
]


@pytest.mark.recipe
@pytest.mark.parametrize(
    "path", _SMOKES, ids=lambda p: str(p.relative_to(p.parents[2]))
)
def test_example_smoke_trains(path, tmp_path, monkeypatch):
    """Run every hermetic example end-to-end (redirected run_dir)."""
    import json

    cfg = _load(path)
    cfg.set("run_dir", str(tmp_path))
    # keep every smoke cheap regardless of the YAML's own step budget
    if cfg.get("step_scheduler") is not None:
        cfg.set("step_scheduler.max_steps", min(
            int(cfg.get("step_scheduler.max_steps", 2)), 2
        ))
    # redirect the checkpoint dir too: a YAML's absolute /tmp path outlives
    # the test, and a stale checkpoint from an earlier (longer) run makes
    # auto_resume skip straight past the clamped step budget — the smoke
    # then "passes" zero steps or fails with no train records
    if cfg.get("checkpoint") is not None and cfg.get("checkpoint.checkpoint_dir"):
        cfg.set("checkpoint.checkpoint_dir", str(tmp_path / "ckpt"))
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    out = tmp_path / "training.jsonl"
    recs = (
        [json.loads(l) for l in open(out) if l.strip()] if out.exists() else []
    )
    if recs:
        assert all(np.isfinite(x["loss"]) for x in recs)
    else:
        # eval/generate-style recipes log no train steps (the metrics logger
        # still touches training.jsonl) — they must leave their own artifact
        arts = [
            p for p in (
                "generations.jsonl", "decode_eval.jsonl", "acceptance.jsonl",
            )
            if (tmp_path / p).exists() and (tmp_path / p).stat().st_size > 0
        ]
        assert arts, "recipe produced neither train records nor an eval artifact"
